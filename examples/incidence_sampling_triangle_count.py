#!/usr/bin/env python
"""Incidence-sampling triangle-count estimate.

Usage: incidence_sampling_triangle_count.py [<input path> <output path>
       <vertex count> <sample size> [parallelism]]

Mirrors the reference CLI
(example/IncidenceSamplingTriangleCount.java:246-266).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import Edge, NULL, StreamEnvironment
from gelly_streaming_tpu.models.sampling_triangles import \
    incidence_sampling_triangle_count

DEFAULT_EDGES = [(1, 2), (2, 3), (1, 3), (3, 4), (3, 5), (4, 5)]


def main(argv):
    env = StreamEnvironment.get_execution_environment()
    if len(argv) >= 4:
        edges = env.read_text_file(argv[0]).map(
            lambda l: Edge(int(l.split()[0]), int(l.split()[1]), NULL)
        )
        out_path = argv[1]
        vertices = int(argv[2])
        samples = int(argv[3])
        parallelism = int(argv[4]) if len(argv) > 4 else 1
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection([Edge(s, t, NULL) for s, t in DEFAULT_EDGES])
        out_path, vertices, samples, parallelism = None, 5, 1000, 1

    estimates = incidence_sampling_triangle_count(
        edges, samples, vertices, parallelism
    )
    if out_path:
        estimates.write_as_csv(out_path)
    else:
        estimates.print_()
    env.execute("Incidence sampling triangle count")


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env python
"""Sliding-window neighborhood sums — BEYOND the reference's examples
(all tumbling; SimpleEdgeStream.java:139-171): per-vertex sums of
neighborhood edge weights over overlapping event-time windows via
`slice(size, direction, slide=...)`. Named-monoid reduces run as ONE
pane-partial device dispatch for every window (docs/DESIGN.md §1.1).

Usage: sliding_degree_sums.py [<input path> <output path>
                               [<size_ms> [<slide_ms>]]]
Input lines: "src dst ts" — the third column is both the edge weight
and the event-time timestamp, as in the reference's timestamped
fixtures (ExamplesTestData.java:20-33).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import (AscendingTimestampExtractor, Edge,
                                 EdgeDirection, JaxEdgesReduce,
                                 SimpleEdgeStream, StreamEnvironment, Time)

DEFAULT_EDGES = [(1, 2, 100), (1, 3, 150), (1, 2, 250), (2, 3, 350)]


def main(argv):
    env = StreamEnvironment.get_execution_environment()
    if argv:
        edges = env.read_text_file(argv[0]).map(
            lambda l: Edge(*[int(x) for x in l.split()[:3]]))
        out_path = argv[1] if len(argv) > 1 else None
        size_ms = int(argv[2]) if len(argv) > 2 else 200
        slide_ms = int(argv[3]) if len(argv) > 3 else max(1, size_ms // 2)
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection(
            [Edge(s, t, v) for s, t, v in DEFAULT_EDGES])
        out_path, size_ms, slide_ms = None, 200, 100

    graph = SimpleEdgeStream(
        edges, env,
        timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value))
    sums = graph.slice(Time.milliseconds_of(size_ms), EdgeDirection.OUT,
                       slide=Time.milliseconds_of(slide_ms)) \
                .reduce_on_edges(JaxEdgesReduce(name="sum"))
    if out_path:
        sums.write_as_csv(out_path)
    else:
        sums.print_()
    env.execute("Sliding-window neighborhood sums")


if __name__ == "__main__":
    main(sys.argv[1:])

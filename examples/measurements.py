#!/usr/bin/env python
"""Throughput measurement programs — recreating the reference's three
declared-but-missing measurement jars (pom.xml:95-135 builds
DegreeMeasurement / TriangleMeasurements / BipartiteMeasurement whose
sources are absent from the snapshot; SURVEY.md §6) on the columnar
streaming path.

Usage: measurements.py [<workload> [<edges file> [window]]] [--sharded]
       [--fused] [--cpu]

  workload: degrees | cc | bipartite | triangles | reduce | all
            (default all; `reduce` = BASELINE config #2's
            reduceOnEdges sum-of-weights on the columnar engine)
  window:   edges per count-based window (default 65536)
  --fused:  run ALL analytics in one carried-state scan program per
            64-window chunk (ops/scan_analytics.py) — the minimal-
            transfer path; prints a single combined line

Without a file, measures a synthetic power-law stream (zero-egress
environment). Prints one JSON line per workload:
  {"workload": ..., "edges_per_sec": N, "windows": W, "edges": E}
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)


def synthetic_stream(num_edges: int, num_vertices: int, seed: int = 7):
    import numpy as np

    rng = np.random.default_rng(seed)
    weights = 1.0 / np.arange(1, num_vertices + 1) ** 1.1
    weights /= weights.sum()
    src = rng.choice(num_vertices, size=num_edges, p=weights)
    dst = rng.choice(num_vertices, size=num_edges, p=weights)
    return src, dst


def measure(workload: str, src, dst, window_edges: int, mesh):
    import numpy as np

    from gelly_streaming_tpu import StreamingAnalyticsDriver

    drv = StreamingAnalyticsDriver(
        window_ms=0, analytics=(workload,), mesh=mesh,
        edge_bucket=window_edges,
        # size to the vertex domain up front: bucket doublings mid-
        # measurement would put recompiles inside the timed region
        vertex_bucket=int(max(src.max(), dst.max())) + 1,
    )
    # warmup: compile at the exact window shape, then reset so the
    # timed run starts from clean carried state (no double-counted
    # first window, cursors at zero)
    drv.run_arrays(src[: drv.eb], dst[: drv.eb])
    drv.reset()
    t0 = time.perf_counter()
    results = drv.run_arrays(src, dst)
    elapsed = time.perf_counter() - t0
    return {
        "workload": workload,
        "edges_per_sec": round(len(src) / elapsed),
        "windows": len(results),
        # actual window length: buckets round up to powers of two
        "window_edges": drv.eb,
        "edges": len(src),
    }


def measure_fused(src, dst, window_edges: int):
    import numpy as np

    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine

    eng = StreamSummaryEngine(
        edge_bucket=window_edges,
        vertex_bucket=int(max(src.max(), dst.max())) + 1)
    # warmup at the EXACT chunk shapes the timed run will dispatch
    # (full chunk + ragged final chunk), so no compile lands in timing
    num_w = -(-len(src) // eng.eb)
    for w in {min(num_w, eng.MAX_WINDOWS), num_w % eng.MAX_WINDOWS}:
        if w:
            zeros = np.zeros(w * eng.eb, np.int64)
            eng.process(zeros, zeros)
            eng.reset()
    # the overflow-recount fallback compiles lazily; warm it so a
    # skewed stream's first hub window doesn't compile mid-timing
    eng.warm_fallback()
    t0 = time.perf_counter()
    results = eng.process(src, dst)
    elapsed = time.perf_counter() - t0
    return {
        "workload": "fused(degrees+cc+bipartite+triangles)",
        "edges_per_sec": round(len(src) / elapsed),
        "windows": len(results),
        "window_edges": eng.eb,
        "edges": len(src),
    }


def measure_reduce(src, dst, window_edges: int, mesh=None,
                   direction: str = "out"):
    """BASELINE.json config #2: `reduceOnEdges` sum-of-weights over
    tumbling count windows, on the columnar engine
    (ops/windowed_reduce.py; reference hot loop
    GraphWindowStream.java:101-121) — single-chip, or the sharded pane
    form (panes_per_window=1) over a mesh."""
    import numpy as np

    # deterministic synthetic weights (the SNAP streams carry none)
    val = (1 + (src + 3 * dst) % 97).astype(np.int32)
    vb = int(max(src.max(), dst.max())) + 1
    if mesh is not None:
        from gelly_streaming_tpu.parallel.sharded import \
            ShardedWindowEngine

        eng = ShardedWindowEngine(mesh, num_vertices_bucket=vb)
        num_w = -(-len(src) // window_edges)
        pane = (np.arange(len(src)) // window_edges).astype(np.int64)
        # warm the exact program (same pane bucket + value shape)
        eng.sliding_reduce(src, np.zeros_like(pane), val,
                           num_panes=num_w, panes_per_window=1)
        t0 = time.perf_counter()
        wv, wc = eng.sliding_reduce(src, pane, val, num_panes=num_w,
                                    panes_per_window=1)
        elapsed = time.perf_counter() - t0
        windows = num_w
    else:
        from gelly_streaming_tpu.ops.windowed_reduce import \
            WindowedEdgeReduce

        eng = WindowedEdgeReduce(vertex_bucket=vb,
                                 edge_bucket=window_edges,
                                 name="sum", direction=direction)
        eb = eng.eb
        # warm every chunk shape the timed run dispatches (full chunks
        # + the bucketed ragged tail), zeros streams — same discipline
        # as measure_fused
        num_w = -(-len(src) // eb)
        for w in {min(num_w, eng.MAX_STREAM_WINDOWS),
                  num_w % eng.MAX_STREAM_WINDOWS}:
            if w:
                z = np.zeros(w * eb, np.int64)
                eng.process_stream(z, z, np.zeros(w * eb, np.int32))
        t0 = time.perf_counter()
        results = eng.process_stream(src, dst, val)
        elapsed = time.perf_counter() - t0
        windows = len(results)
    return {
        "workload": "reduce_on_edges(sum-of-weights, %s)" % direction,
        "edges_per_sec": round(len(src) / elapsed),
        "windows": windows,
        "window_edges": window_edges,
        "edges": len(src),
    }


def main(argv):
    sharded = "--sharded" in argv
    fused = "--fused" in argv
    argv = [a for a in argv if not a.startswith("--")]
    workload = argv[0] if argv else "all"
    path = argv[1] if len(argv) > 1 else None
    window_edges = int(argv[2]) if len(argv) > 2 else 65536

    mesh = None
    if sharded:
        from gelly_streaming_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

    if path:
        from gelly_streaming_tpu.io.sources import load_edge_arrays

        src, dst, _ts = load_edge_arrays(path)
    else:
        src, dst = synthetic_stream(1 << 20, 1 << 17)

    if fused:
        if sharded:
            sys.exit("--fused runs single-chip; drop --sharded or "
                     "measure workloads separately")
        if workload != "all":
            sys.exit("--fused measures all analytics in one program; "
                     "drop the workload argument or the flag")
        print(json.dumps(measure_fused(src, dst, window_edges)))
        return
    names = (["degrees", "cc", "bipartite", "triangles", "reduce"]
             if workload == "all" else [workload])
    for name in names:
        if name == "reduce":
            print(json.dumps(measure_reduce(src, dst, window_edges,
                                            mesh)))
        else:
            print(json.dumps(measure(name, src, dst, window_edges,
                                     mesh)))


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env python
"""Streaming connected components via summary aggregation.

Usage: connected_components.py [<input edges path> <output path>
       [merge window ms] [--tpu]]

Mirrors the reference CLI (example/ConnectedComponentsExample.java:74-98,
defaults merge=1000 ms); `--tpu` selects the device union-find window
fold.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import Edge, NULL, SimpleEdgeStream, StreamEnvironment
from gelly_streaming_tpu.models import (ConnectedComponents,
                                        TpuConnectedComponents)


def main(argv):
    tpu = "--tpu" in argv
    argv = [a for a in argv if a != "--tpu"]
    env = StreamEnvironment.get_execution_environment()
    if argv:
        edges = env.read_text_file(argv[0]).map(
            lambda l: Edge(int(l.split()[0]), int(l.split()[1]), NULL)
        )
        out_path = argv[1] if len(argv) > 1 else None
        merge_ms = int(argv[2]) if len(argv) > 2 else 1000
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection([
            Edge(1, 2, NULL), Edge(1, 3, NULL), Edge(2, 3, NULL),
            Edge(1, 5, NULL), Edge(6, 7, NULL), Edge(8, 9, NULL),
        ])
        out_path, merge_ms = None, 1000

    graph = SimpleEdgeStream(edges, env)
    algo = TpuConnectedComponents(merge_ms) if tpu else ConnectedComponents(merge_ms)
    cc = graph.aggregate(algo)
    if out_path:
        cc.write_as_text(out_path)
    else:
        cc.print_()
    env.execute("Streaming connected components")


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env python
"""Centralized streaming weighted matching (greedy 1/2-approximation).

Usage: centralized_weighted_matching.py [<input path>]

Mirrors the reference CLI (example/CentralizedWeightedMatching.java:38-65):
input lines are 'user item rating' (MovieLens format); items are
shifted by 1,000,000 and ratings scaled ×10, and the job's net runtime
is printed.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import Edge, StreamEnvironment
from gelly_streaming_tpu.models.matching import centralized_weighted_matching

DEFAULT_EDGES = [(1, 2, 30), (2, 3, 40), (1, 3, 10), (3, 4, 200), (4, 5, 5)]


def main(argv):
    env = StreamEnvironment.get_execution_environment()
    if argv:
        def parse(line):
            user, item, rating = line.split("\t")[:3]
            return Edge(int(user), int(item) + 1_000_000, int(rating) * 10)

        edges = env.read_text_file(argv[0]).map(parse)
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection([Edge(s, t, w) for s, t, w in DEFAULT_EDGES])

    centralized_weighted_matching(edges).print_()
    result = env.execute("Centralized weighted matching")
    print(f"Runtime: {result.get_net_runtime():.1f}")


if __name__ == "__main__":
    main(sys.argv[1:])

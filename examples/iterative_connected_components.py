#!/usr/bin/env python
"""Iterative (feedback-loop) connected components.

Usage: iterative_connected_components.py [<input path> <output path>] [--tpu]

Mirrors the reference CLI (example/IterativeConnectedComponents.java:45-63);
`--tpu` runs the in-step while_loop label propagation instead of the
feedback queue.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

import numpy as np

from gelly_streaming_tpu import StreamEnvironment
from gelly_streaming_tpu.models.iterative_cc import (
    TpuIterativeConnectedComponents, iterative_connected_components)

DEFAULT_EDGES = [(1, 2), (1, 3), (2, 3), (1, 5), (6, 7), (8, 9)]


def main(argv):
    tpu = "--tpu" in argv
    argv = [a for a in argv if a != "--tpu"]
    if argv:
        with open(argv[0]) as f:
            pairs = [tuple(int(x) for x in l.split()[:2]) for l in f if l.strip()]
        out_path = argv[1] if len(argv) > 1 else None
    else:
        print("Executing with built-in default data.")
        pairs, out_path = DEFAULT_EDGES, None

    if tpu:
        model = TpuIterativeConnectedComponents()
        src = np.array([p[0] for p in pairs])
        dst = np.array([p[1] for p in pairs])
        updates = model.process_batch(src, dst)
        lines = [f"({v},{c})" for v, c in updates]
    else:
        env = StreamEnvironment.get_execution_environment()
        edges = env.from_collection([(s, t) for s, t in pairs])
        result = iterative_connected_components(edges)
        sink = result.collect()
        env.execute("Iterative connected components")
        lines = [f"({v},{c})" for v, c in env.results_of(sink)]

    if out_path:
        with open(out_path, "w") as f:
            f.write("\n".join(lines) + "\n")
    else:
        print("\n".join(lines))


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env python
"""Continuous degree aggregate — the measurement workload whose class is
missing from the reference snapshot (pom.xml:120-135 DegreeMeasurement;
README "Graph Streaming Algorithms"): a continuously improving degree
stream via SimpleEdgeStream.getDegrees (SimpleEdgeStream.java:417-420).

Usage: degree_aggregate.py [<input path> <output path> [in|out|all]]
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import Edge, NULL, SimpleEdgeStream, StreamEnvironment

DEFAULT_EDGES = [(1, 2), (1, 3), (2, 3), (3, 4), (3, 5), (4, 5), (5, 1)]


def main(argv):
    env = StreamEnvironment.get_execution_environment()
    if argv:
        edges = env.read_text_file(argv[0]).map(
            lambda l: Edge(int(l.split()[0]), int(l.split()[1]), NULL)
        )
        out_path = argv[1] if len(argv) > 1 else None
        direction = argv[2] if len(argv) > 2 else "all"
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection([Edge(s, t, NULL) for s, t in DEFAULT_EDGES])
        out_path, direction = None, "all"

    graph = SimpleEdgeStream(edges, env)
    degrees = {
        "in": graph.get_in_degrees,
        "out": graph.get_out_degrees,
        "all": graph.get_degrees,
    }[direction]()
    if out_path:
        degrees.write_as_csv(out_path)
    else:
        degrees.print_()
    env.execute("Continuous degree aggregate")


if __name__ == "__main__":
    main(sys.argv[1:])

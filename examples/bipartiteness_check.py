#!/usr/bin/env python
"""Streaming bipartiteness check.

Usage: bipartiteness_check.py [<input edges path> <output path>
       [merge window ms] [--tpu]]

Mirrors the reference CLI (example/BipartitenessCheckExample.java:44-80,
default merge window 500 ms); `--tpu` selects the double-cover device
kernel.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import Edge, NULL, SimpleEdgeStream, StreamEnvironment
from gelly_streaming_tpu.models import (BipartitenessCheck,
                                        TpuBipartitenessCheck)


def main(argv):
    tpu = "--tpu" in argv
    argv = [a for a in argv if a != "--tpu"]
    env = StreamEnvironment.get_execution_environment()
    if argv:
        edges = env.read_text_file(argv[0]).map(
            lambda l: Edge(int(l.split()[0]), int(l.split()[1]), NULL)
        )
        out_path = argv[1] if len(argv) > 1 else None
        merge_ms = int(argv[2]) if len(argv) > 2 else 500
    else:
        print("Executing with built-in default data.")
        edges = env.from_collection([
            Edge(1, 2, NULL), Edge(1, 3, NULL), Edge(1, 4, NULL),
            Edge(4, 5, NULL), Edge(4, 7, NULL), Edge(4, 9, NULL),
        ])
        out_path, merge_ms = None, 500

    graph = SimpleEdgeStream(edges, env)
    algo = TpuBipartitenessCheck(merge_ms) if tpu else BipartitenessCheck(merge_ms)
    result = graph.aggregate(algo)
    if out_path:
        result.write_as_text(out_path)
    else:
        result.print_()
    env.execute("Bipartiteness check")


if __name__ == "__main__":
    main(sys.argv[1:])

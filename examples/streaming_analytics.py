#!/usr/bin/env python
"""Columnar streaming analytics over an edge file — the production
ingest→device path (core/driver.py): native parse, tumbling event-time
windows, and per-window carried-state device analytics, without
per-record Python.

Usage: streaming_analytics.py [<input path> <window_ms>
       [degrees,cc,bipartite,triangles]] [--sharded] [--trace] [--cpu]

With no input, runs the built-in timestamped triangle sample.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

DEFAULT = "\n".join(
    f"{s} {d} {t}"
    for s, d, t in [(1, 2, 100), (1, 3, 150), (3, 2, 200), (2, 4, 250),
                    (3, 4, 300), (3, 5, 350), (4, 5, 400), (4, 6, 450),
                    (6, 5, 500), (5, 7, 550), (6, 7, 600), (8, 6, 650)]
)


def main(argv):
    import numpy as np

    from gelly_streaming_tpu import StreamingAnalyticsDriver

    sharded = "--sharded" in argv
    trace = "--trace" in argv
    argv = [a for a in argv if not a.startswith("--")]

    mesh = None
    if sharded:
        from gelly_streaming_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

    cleanup = None
    if argv:
        path = argv[0]
        window_ms = int(argv[1]) if len(argv) > 1 else 1000
        analytics = (tuple(argv[2].split(",")) if len(argv) > 2
                     else StreamingAnalyticsDriver.ANALYTICS)
    else:
        print("Executing with built-in default data.")
        import os
        import tempfile

        f = tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False)
        f.write(DEFAULT + "\n")
        f.close()
        path, window_ms = f.name, 200
        analytics = StreamingAnalyticsDriver.ANALYTICS
        cleanup = lambda: os.unlink(f.name)  # noqa: E731

    driver = StreamingAnalyticsDriver(window_ms, analytics=analytics,
                                      mesh=mesh, tracing=trace)
    try:
        results = driver.run_file(path)
    finally:
        if cleanup:
            cleanup()
    for res in results:
        parts = [f"window={res.window_start}", f"edges={res.num_edges}"]
        if res.triangles is not None:
            parts.append(f"triangles={res.triangles}")
        if res.cc_labels is not None:
            parts.append(
                f"components={len(np.unique(res.cc_labels[:len(res.vertex_ids)]))}")
        if res.bipartite_odd is not None:
            parts.append(f"odd_cycle={bool(res.bipartite_odd.any())}")
        if res.degrees is not None:
            parts.append(f"max_degree={int(res.degrees.max())}")
        print(" ".join(parts))
    if trace:
        print(driver.timer)


if __name__ == "__main__":
    main(sys.argv[1:])

"""Shared CLI bootstrap: puts the repo on sys.path and handles the
--cpu flag (hermetic CPU backend instead of the real TPU chip)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu" in sys.argv:
    sys.argv.remove("--cpu")
    from gelly_streaming_tpu.core.platform import use_cpu
    use_cpu()

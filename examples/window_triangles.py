#!/usr/bin/env python
"""Exact window triangle count.

Usage: window_triangles.py <input edges path> <output path> <window ms>
       [--fused]

Mirrors the reference CLI (example/WindowTriangles.java:147-168) with
the same default window (300 ms) and built-in generated graph when no
args are given; `--fused` runs the single-program device kernel instead
of the API-parity candidate pipeline.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import _bootstrap  # noqa: F401  (repo path + --cpu flag handling)

from gelly_streaming_tpu import (Edge, SimpleEdgeStream, StreamEnvironment,
                                 Time, AscendingTimestampExtractor, NULL)
from gelly_streaming_tpu.models.triangles import WindowTriangleCount
from gelly_streaming_tpu.models.workloads import (timestamped_graph,
                                                  window_triangles_pipeline)


def generated_graph(env):
    """Built-in default graph (reference: WindowTriangles.java:188-197)."""
    def gen(key, collect):
        for i in range(1, 3):
            collect(Edge(key, key + i, key * 100 + (i - 1) * 50))

    edges = env.generate_sequence(1, 10).flat_map(gen)
    return SimpleEdgeStream(
        edges, env,
        timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
    ).map_edges(lambda e: NULL)


def main(argv):
    fused = "--fused" in argv
    argv = [a for a in argv if a != "--fused"]
    env = StreamEnvironment.get_execution_environment()
    if len(argv) >= 3:
        graph = timestamped_graph(env, argv[0])
        window = Time.milliseconds_of(int(argv[2]))
        out_path = argv[1]
    else:
        print("Executing WindowTriangles example with default parameters "
              "and built-in default data.")
        graph = generated_graph(env)
        window = Time.milliseconds_of(300)
        out_path = None

    if fused:
        counts = WindowTriangleCount(window).run(graph)
    else:
        counts = window_triangles_pipeline(graph, window)

    if out_path:
        counts.write_as_text(out_path)
    else:
        counts.print_()
    env.execute("Window triangle count")


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env python
"""North-star benchmark: edges/sec on exact Window Triangle Count.

Streams a synthetic power-law edge stream (a stand-in for the Twitter
slice named in BASELINE.json — zero-egress environment, no external
datasets) through tumbling count-windows and measures end-to-end
throughput of the streaming device pipeline
(ops/triangles.TriangleWindowKernel: ONE compiled program for all
windows; the host ships only raw COO arrays).

Baseline (BASELINE.md: "run the Flink reference or a faithful CPU
port"): faithful CPU ports of the reference's candidate-pair pipeline
(GenerateCandidateEdges + CountTriangles, WindowTriangles.java:83-140)
on the same stream. The PRIMARY baseline is a numpy-vectorized port
(same O(d²) candidate algorithm, compiled inner loops — a fair proxy
for the JVM comparator) timed at the device's own window size; the
pure-Python dict/set port is kept as a secondary row (it measures
CPython interpreter overhead as much as the algorithm).

Exact-count parity between all paths is asserted on the shared sample
windows (and the leading device-size windows) before anything is
reported.

Prints one JSON line per completed scale (smallest first), so an
external timeout still leaves the best completed number; the LAST line
is the headline result:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N}
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# Exceptions that mean "the device ran out of room at this scale" — the
# only ones worth stopping the scale ladder for. Matched narrowly (the
# XLA status code / canonical OOM phrasing) so arbitrary compiler bugs
# whose text happens to mention allocation are NOT masked as capacity.
def _is_resource_error(e: Exception) -> bool:
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "out of memory" in s.lower()


def _is_backend_drop(e: Exception) -> bool:
    """A mid-run tunnel death (the exact failure recorded in
    BENCH_r01.json) — once at least one scale has completed, this must
    keep the completed result rather than exit nonzero."""
    s = str(e)
    return "UNAVAILABLE" in s or "Unable to initialize backend" in s


def run_with_hard_timeout(argv, timeout_s: int, env=None):
    """Run argv in its own process GROUP with a hard timeout; returns
    (rc, stdout, stderr) with rc=None on timeout. Output goes to temp
    FILES, not pipes, and the child gets its own session: if the PJRT
    plugin forks a helper that inherits the descriptors, a pipe would
    keep a post-kill communicate() stuck forever; a file EOFs
    regardless, and killpg reaps the helper. Shared by probe_backend
    and tools/profile_kernels.py's section runner (the per-scale bench
    runs keep their own Popen because they stream stdout live)."""
    import signal
    import tempfile

    with tempfile.TemporaryFile("w+") as out, \
            tempfile.TemporaryFile("w+") as err:
        p = subprocess.Popen(argv, stdout=out, stderr=err, text=True,
                             env=env, start_new_session=True)
        try:
            rc = p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            rc = None
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except OSError:
                pass
            p.wait()
        out.seek(0)
        err.seek(0)
        return rc, out.read(), err.read()


def clean_cpu_env(**extra):
    """Env for a hermetic CPU child: JAX pinned to cpu AND the baked
    sitecustomize's PJRT plugin registration stripped (PYTHONPATH="") —
    with a wedged tunnel the plugin otherwise hangs every process at
    backend init, even under JAX_PLATFORMS=cpu. Shared by the bench
    fallback and both evidence tools."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def run_json_child(argv, timeout_s: int, env=None, require_key=None):
    """run_with_hard_timeout + parse the LAST JSON object line of the
    child's stdout (optionally requiring a key, to skip progress
    lines). Returns {'error': ...} on timeout/nonzero-rc/no-JSON — the
    shared child contract of tools/profile_kernels.py sections and
    tools/scale_run.py legs."""
    rc, stdout, stderr = run_with_hard_timeout(argv, timeout_s, env=env)
    if rc is None:
        return {"error": "timeout after %ds (wedged compile?)" % timeout_s}
    if rc != 0:
        return {"error": "rc=%d: %s" % (rc, stderr.strip()[-800:])}
    for line in reversed(stdout.strip().splitlines()):
        try:
            got = json.loads(line)
        except ValueError:
            continue
        if isinstance(got, dict) and (require_key is None
                                      or got.get(require_key)):
            return got
    return {"error": "no JSON line in child output"}


def probe_backend(attempts: int = None, timeout_s: int = None,
                  backoff_s: int = 20):
    """Check in a SUBPROCESS (with a hard timeout) that jax can bring up
    a backend. The TPU tunnel has two failure modes, both of which must
    not eat the bench window (round 1 lost the whole window to this):
      - plugin registration hangs forever  -> subprocess timeout
      - backend init fails after ~25 min internally -> our timeout fires
        first
    Bounded retries with backoff, then give up fast. Returns the
    platform name ('axon'/'tpu'/'cpu'/...) or None if nothing came up —
    the caller must label a cpu result, not report it as a chip."""
    if attempts is None:
        attempts = int(os.environ.get("GS_BENCH_PROBE_ATTEMPTS", "3"))
    if timeout_s is None:
        timeout_s = int(os.environ.get("GS_BENCH_PROBE_TIMEOUT", "120"))

    code = "import jax; d=jax.devices(); print(d[0].platform)"
    for i in range(attempts):
        # Escalate the timeout per attempt so a slow-but-healthy init is
        # distinguished from a hang (120s, 240s, 360s by default).
        t = timeout_s * (i + 1)
        rc, stdout, stderr = run_with_hard_timeout(
            [sys.executable, "-c", code], t)
        if rc == 0 and stdout.strip():
            platform = stdout.strip().splitlines()[-1]
            print("backend probe ok: %s" % platform, file=sys.stderr)
            return platform
        if rc is None:
            print("backend probe timed out after %ds" % t,
                  file=sys.stderr)
        else:
            print("backend probe failed (rc=%d): %s"
                  % (rc, stderr.strip()[-200:]), file=sys.stderr)
        if i + 1 < attempts:
            time.sleep(backoff_s)
    return None


def make_stream(num_edges: int, num_vertices: int, seed: int = 7):
    """Power-law-ish edge stream: endpoints drawn from a Zipf-like
    distribution over the vertex space (heavy hitters like a social
    stream), timestamps strictly increasing."""
    rng = np.random.default_rng(seed)
    # exponent ~1.1 keeps candidate counts representative but bounded
    weights = 1.0 / np.arange(1, num_vertices + 1) ** 1.1
    weights /= weights.sum()
    src = rng.choice(num_vertices, size=num_edges, p=weights)
    dst = rng.choice(num_vertices, size=num_edges, p=weights)
    # no self-loops (match real graph datasets): redraw collisions
    loops = src == dst
    while loops.any():
        dst[loops] = rng.choice(num_vertices, size=int(loops.sum()), p=weights)
        loops = src == dst
    # remap so hot vertices are scattered over the id space
    perm = rng.permutation(num_vertices)
    return perm[src], perm[dst]


def device_window_counts(kernel, src, dst, window_edges):
    """Streaming device path: the whole stream's windows batched into
    lax.map dispatches (kernel.count_stream) — one h2d per chunk, one
    d2h of the counts, zero per-window round-trips."""
    assert window_edges == kernel.eb, "stream windows must match the bucket"
    return kernel.count_stream(src, dst)


def warmup_stream_shapes(kernel, num_edges):
    """Compile the (at most two) chunk shapes the timed run will use:
    a full MAX_STREAM_WINDOWS chunk and the ragged final chunk."""
    num_w = -(-num_edges // kernel.eb)
    first = min(num_w, kernel.MAX_STREAM_WINDOWS)
    zeros = np.zeros(first * kernel.eb, np.int32)
    kernel.count_stream(zeros, zeros)
    tail = num_w % kernel.MAX_STREAM_WINDOWS
    if tail and tail != first:
        zeros = zeros[: tail * kernel.eb]
        kernel.count_stream(zeros, zeros)


def cpu_reference_window_counts(src, dst, window_edges):
    """Faithful CPU port of the reference pipeline: per-vertex ALL-window
    neighborhoods → candidate pairs (ids > vertex) → per-pair groups →
    count candidates where a real edge exists. On self-looped input its
    self-pair candidates mirror the reference's HashSet-order-dependent
    emission (see _numpy_window_count), so parity across ports is
    asserted only on loop-free streams — which every bench stream is."""
    counts = []
    for start in range(0, len(src), window_edges):
        s = src[start:start + window_edges]
        d = dst[start:start + window_edges]
        neighborhoods = {}
        for u, v in zip(s.tolist(), d.tolist()):
            neighborhoods.setdefault(u, []).append(v)
            neighborhoods.setdefault(v, []).append(u)
        real = set()
        candidates = {}
        for vertex, nbrs in neighborhoods.items():
            distinct = list(dict.fromkeys(nbrs))
            for n in nbrs:
                real.add((vertex, n))
            for i in range(len(distinct) - 1):
                if distinct[i] <= vertex:
                    continue
                for j in range(i, len(distinct)):
                    if distinct[j] > vertex:
                        pair = (distinct[i], distinct[j])
                        candidates[pair] = candidates.get(pair, 0) + 1
        total = sum(c for pair, c in candidates.items() if pair in real)
        counts.append(total)
    return counts


def _numpy_window_count(s: np.ndarray, d: np.ndarray) -> int:
    """One window of the faithful candidate-pair algorithm
    (WindowTriangles.java:83-140), numpy-vectorized: same O(d²)
    candidate generation per vertex, but with compiled inner loops so
    the baseline is the ALGORITHM's cost, not CPython interpreter
    overhead. Semantics match cpu_reference_window_counts on
    SELF-LOOP-FREE streams (asserted at bench time; every bench stream
    is loop-free by construction): for each center vertex, every
    unordered pair of distinct neighbors both > center is a candidate,
    counted once per center; candidates that are real edges sum to the
    window's triangle count. Self-loops are stripped here — the
    reference's own i==j self-pair emission depends on Java HashSet
    iteration order (GenerateCandidateEdges skips the LAST-iterated
    neighbor's self-pair), so its looped-input count is
    nondeterministic and parity there is undefined; the device kernels
    strip self-loops for the same reason."""
    keep_e = s != d
    s, d = s[keep_e], d[keep_e]
    if len(s) == 0:
        return 0
    V = int(max(s.max(), d.max())) + 1
    center = np.concatenate([s, d]).astype(np.int64)
    nbr = np.concatenate([d, s]).astype(np.int64)
    # distinct (center, neighbor) incidences, both directions = the
    # port's `real` set and its deduped neighborhoods in one array
    enc_u = np.unique(center * V + nbr)
    c = enc_u // V
    n = enc_u % V
    keep = n > c
    ck, nk = c[keep], n[keep]
    if len(ck) == 0:
        return 0
    # per-center segments (ck is sorted because enc_u is)
    change = np.flatnonzero(np.diff(ck)) + 1
    offs = np.concatenate(([0], change, [len(ck)]))
    k = np.diff(offs)
    pairs_per_seg = k * (k - 1) // 2
    cum = np.cumsum(pairs_per_seg)
    total = 0
    # batch segments so the pair arrays stay bounded in memory; hub
    # vertices at 32K-edge windows generate tens of millions of pairs
    MAX_PAIRS = 8_000_000
    start_seg = 0
    while start_seg < len(k):
        base = int(cum[start_seg - 1]) if start_seg else 0
        end_seg = int(np.searchsorted(cum, base + MAX_PAIRS,
                                      side="right"))
        end_seg = min(max(end_seg, start_seg + 1), len(k))
        kb = k[start_seg:end_seg]
        nb = nk[offs[start_seg]:offs[end_seg]]
        kb_offs = np.concatenate(([0], np.cumsum(kb)))
        # position of each element within its segment; element at
        # position p is the SECOND member of p pairs (one per earlier
        # element), which unrolls every i<j pair without a Python loop
        pos = np.arange(len(nb)) - np.repeat(kb_offs[:-1], kb)
        P = int(pos.sum())
        if P:
            j_idx = np.repeat(np.arange(len(nb)), pos)
            blk = np.concatenate(([0], np.cumsum(pos)[:-1]))
            i_off = np.arange(P) - np.repeat(blk, pos)
            i_idx = np.repeat(kb_offs[:-1], kb)[j_idx] + i_off
            pe = nb[i_idx] * V + nb[j_idx]
            loc = np.searchsorted(enc_u, pe)
            loc[loc >= len(enc_u)] = len(enc_u) - 1
            total += int((enc_u[loc] == pe).sum())
        start_seg = end_seg
    return total


def cpu_reference_window_counts_numpy(src, dst, window_edges):
    """Numpy-vectorized faithful port (primary CPU baseline; the
    pure-Python dict/set port above is kept as the secondary row —
    VERDICT r2 weak-2: an interpreted baseline softens the ≥10× bar
    because the real comparator is Flink's JVM, not CPython)."""
    return [
        _numpy_window_count(np.asarray(src[s:s + window_edges]),
                            np.asarray(dst[s:s + window_edges]))
        for s in range(0, len(src), window_edges)
    ]


def run_at_scale(scale: float, metric_suffix: str = "") -> None:
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    num_edges = int(2_097_152 * scale)
    # The window is CAPPED at 32768 edges: scaling up grows the STREAM
    # (more windows through the same compiled program — the north-star
    # metric is edges/sec over a 10M-edge stream slice), not the window.
    # An uncapped 131072-edge window program sent the tunnel's remote
    # compile into a >30min stall in round 2; the per-edge triangle work
    # also grows superlinearly with window length, so bigger windows
    # would only make the reported rate conservative, not comparable.
    window_edges = min(int(131_072 * scale), 32_768)
    num_vertices = min(int(262_144 * scale), 65_536)
    src, dst = make_stream(num_edges, num_vertices)

    kernel = TriangleWindowKernel(
        edge_bucket=window_edges, vertex_bucket=num_vertices)
    # count_stream slices windows of exactly the kernel's edge bucket,
    # so align the stream's window length to it (scales whose raw
    # window_edges is not a power of two round up)
    window_edges = kernel.eb

    # correctness cross-check + CPU baselines on shared sample windows
    # (small enough for the O(d²) interpreted pipeline to finish; four
    # windows — the ports' per-window time swings with host load and
    # sits in the denominator of the ratio, so averaging steadies it)
    sample_window = min(window_edges, 8_192)
    sample = 4 * sample_window
    reps = int(os.environ.get("GS_BENCH_REPS", "3"))
    t0 = time.perf_counter()
    ref_counts = cpu_reference_window_counts(
        src[:sample], dst[:sample], sample_window)
    cpu_py_rate = sample / (time.perf_counter() - t0)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np_counts = cpu_reference_window_counts_numpy(
            src[:sample], dst[:sample], sample_window)
        ts.append(time.perf_counter() - t0)
    cpu_np_sample_rate = sample / float(np.median(ts))
    assert np_counts == ref_counts, (np_counts, ref_counts)
    # parity of BOTH device paths: the per-window escalating kernel and
    # the batched lax.map streaming path the timed run uses
    dev_counts = [
        kernel.count(src[s:s + sample_window], dst[s:s + sample_window])
        for s in range(0, sample, sample_window)
    ]
    assert dev_counts == ref_counts, (dev_counts, ref_counts)
    sample_kernel = TriangleWindowKernel(
        edge_bucket=sample_window, vertex_bucket=num_vertices)
    stream_counts = sample_kernel.count_stream(src[:sample], dst[:sample])
    assert stream_counts == ref_counts, (stream_counts, ref_counts)

    # PRIMARY baseline: the numpy-vectorized faithful port timed at the
    # DEVICE's window size, so the headline ratio compares like against
    # like (the old sample-window/device-window asymmetry was argued
    # conservative but never measured). Median of 3 on BOTH sides of
    # the ratio: single samples on this shared host swing 30-45% with
    # load, and the headline must not ride one lucky/unlucky draw.
    if window_edges == sample_window:
        # the sample windows ARE device-size windows: reuse that
        # measurement instead of timing the identical work twice
        nfull, full_counts, cpu_rate = 4, np_counts, cpu_np_sample_rate
    else:
        nfull = max(1, min(4, num_edges // window_edges))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            full_counts = cpu_reference_window_counts_numpy(
                src[:nfull * window_edges], dst[:nfull * window_edges],
                window_edges)
            ts.append(time.perf_counter() - t0)
        cpu_rate = nfull * window_edges / float(np.median(ts))

    # the tier the framework actually routes this bucket to (committed
    # per-bucket evidence on chip, process-wide on CPU backends;
    # ops/triangles._resolve_stream_impl) — reported so every row says
    # what ran, and so a routed row still carries the raw chip path as
    # its decomposition (VERDICT r4 item 5)
    from gelly_streaming_tpu.ops.triangles import _resolve_stream_impl

    tier = _resolve_stream_impl(kernel.eb)

    # warmup at the exact chunk shapes of the timed run (compile here)
    warmup_stream_shapes(kernel, num_edges)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        timed_counts = device_window_counts(kernel, src, dst,
                                            window_edges)
        ts.append(time.perf_counter() - t0)
    rate = num_edges / float(np.median(ts))
    # full-window-size parity: the timed device counts vs the primary
    # baseline's counts on the shared leading windows
    assert list(timed_counts[:nfull]) == full_counts, (
        list(timed_counts[:nfull]), full_counts)

    # the same routed path with the ingress pipeline FORCED
    # SYNCHRONOUS (single-threaded prep, no worker pool): the A/B the
    # pipelined-host-ingress work is accountable to, with exact
    # window-by-window parity asserted — identical counts are part of
    # the pipeline's contract, not a sampling check
    from gelly_streaming_tpu.ops import ingress_pipeline

    ts = []
    for _ in range(reps):
        with ingress_pipeline.forced_sync():
            t0 = time.perf_counter()
            sync_counts = device_window_counts(kernel, src, dst,
                                               window_edges)
            ts.append(time.perf_counter() - t0)
    sync_rate = num_edges / float(np.median(ts))
    assert list(sync_counts) == list(timed_counts), \
        "pipelined path diverged from sync host-prep path"

    device_path_rate = None
    if tier != "device":
        # decomposition row: the raw device/chip path at this scale,
        # parity-checked against the routed tier's counts (one rep —
        # it exists to show WHERE the crossover sits, not as the
        # headline)
        from gelly_streaming_tpu.ops import segment as seg_ops

        seg_ops.warm_stream_buckets(kernel)
        dev_stream = kernel._count_stream_device(src, dst)  # warm run
        assert list(dev_stream) == list(timed_counts), \
            "device path diverged from routed tier"
        t0 = time.perf_counter()
        kernel._count_stream_device(src, dst)
        device_path_rate = num_edges / (time.perf_counter() - t0)

    row = {
        "metric": "edges/sec/chip, exact window triangle count "
                  "(power-law stream, %d-edge windows)%s%s"
                  % (window_edges,
                     "" if tier == "device" else " [%s tier]" % tier,
                     metric_suffix),
        "value": round(rate),
        "unit": "edges/s",
        "tier": tier,
        "vs_baseline": round(rate / cpu_rate, 2),
        # the measured baselines, persisted (BASELINE.md milestone:
        # faithful CPU ports of WindowTriangles.java:83-140 on the same
        # stream; the reference publishes no numbers of its own).
        # PRIMARY: numpy-vectorized port at the device's window size.
        "baseline_cpu_edges_per_s": round(cpu_rate),
        # secondary rows: the same vectorized port on the sample
        # windows, and the pure-Python dict/set port (interpreter-bound;
        # kept for continuity with rounds 1-2)
        "baseline_cpu_numpy_sample_edges_per_s":
            round(cpu_np_sample_rate),
        "baseline_cpu_python_edges_per_s": round(cpu_py_rate),
        "vs_python_baseline": round(rate / cpu_py_rate, 2),
        # the ingress-pipeline A/B: the routed path with parallel
        # window prep + overlapped h2d/dispatch (the headline `value`)
        # vs the same path forced single-threaded-synchronous,
        # identical counts asserted window-by-window above
        "sync_prep_edges_per_s": round(sync_rate),
        "pipeline_speedup": round(rate / sync_rate, 2),
        "pipeline_workers": ingress_pipeline.worker_count(),
        "num_edges": num_edges,
    }
    if device_path_rate is not None:
        row["device_path_edges_per_s"] = round(device_path_rate)
        row["device_path_vs_baseline"] = round(
            device_path_rate / cpu_rate, 2)
    # chosen-knob provenance: every row says what dispatch
    # configuration it actually ran — the static gates, and (when the
    # online tuner was live on the device path) the tuner's chosen arm
    # plus its decision timeline tail (ops/autotune.py)
    from gelly_streaming_tpu.ops import autotune as _autotune

    row["knobs"] = {"k_bucket": kernel.kb,
                    "windows_per_dispatch": kernel.MAX_STREAM_WINDOWS,
                    "ingress": kernel.ingress}
    tuner = getattr(kernel, "tuner", None)
    if tuner is not None:
        ts = tuner.summary()
        row["autotune"] = {
            "enabled": True,
            "chosen": ts["chosen"],
            "rounds": ts["rounds"],
            "promotions": ts["promotions"],
            "edges_per_s_ema": ts["edges_per_s_ema"],
            "timeline": ts["timeline"][-8:],
        }
    else:
        row["autotune"] = {"enabled": _autotune.enabled()}
    # flight-recorder provenance (utils/telemetry): the A/B
    # measurement sections above run DISARMED by default
    # (GS_TELEMETRY=0 — the zero-overhead contract keeps the headline
    # honest); an operator who arms it gets the armed row labeled,
    # with its trace ID and the top span aggregates riding along
    from gelly_streaming_tpu.utils import telemetry as _telemetry

    # the run trace ID rides EVERY row (armed or not — the recorder
    # mints one per process regardless), so a bench_compare regression
    # against this row correlates straight to its ledger
    # (tools/explain_perf.py --regression)
    row["trace"] = _telemetry.trace_id()
    if _telemetry.enabled():
        row["telemetry"] = {"armed": True,
                            "trace": _telemetry.trace_id(),
                            "spans": _telemetry.summary(top=8)}
    else:
        row["telemetry"] = {"armed": False}
    print(json.dumps(row), flush=True)


def run_reduce_leg(metric_suffix: str = "") -> None:
    """BASELINE.json config #2: `reduceOnEdges` sum-of-weights over
    tumbling count windows (reference hot loop
    GraphWindowStream.java:101-121), on the columnar engine
    (ops/windowed_reduce.py). Baseline: a vectorized faithful numpy
    port of the per-window fold (np.bincount(weights) groupby-sum —
    the stiffest single-core form of the reference's per-record
    accumulate), parity-asserted before timing."""
    from gelly_streaming_tpu.ops.windowed_reduce import WindowedEdgeReduce

    num_edges, window_edges = 2_097_152, 8_192
    num_vertices = 1 << 14
    src, dst = make_stream(num_edges, num_vertices)
    val = (1 + (src + 3 * dst) % 97).astype(np.int32)
    reps = int(os.environ.get("GS_BENCH_REPS", "3"))

    def np_port():
        out = []
        for lo in range(0, num_edges, window_edges):
            out.append(np.bincount(
                src[lo:lo + window_edges], val[lo:lo + window_edges],
                minlength=num_vertices).astype(np.int64))
        return out

    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        base = np_port()
        ts.append(time.perf_counter() - t0)
    cpu_rate = num_edges / float(np.median(ts))

    def np_port_with_counts():
        """The same port ALSO producing per-vertex counts — the part
        of the engine's contract (absence detection for non-sum
        monoids, delta consumers) the values-only port omits. Reported
        as a secondary baseline so the primary stays the strictest
        one."""
        out = []
        for lo in range(0, num_edges, window_edges):
            s = src[lo:lo + window_edges]
            out.append((np.bincount(s, val[lo:lo + window_edges],
                                    minlength=num_vertices),
                        np.bincount(s, minlength=num_vertices)))
        return out

    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np_port_with_counts()
        ts.append(time.perf_counter() - t0)
    cpu_rate_counts = num_edges / float(np.median(ts))

    eng = WindowedEdgeReduce(vertex_bucket=num_vertices,
                             edge_bucket=window_edges, name="sum",
                             direction="out")
    got = eng.process_stream(src, dst, val)   # warm + parity material
    assert len(got) == len(base)
    for (cells, _cnt), want in zip(got, base):
        np.testing.assert_array_equal(
            cells[:num_vertices].astype(np.int64), want)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        eng.process_stream(src, dst, val)
        ts.append(time.perf_counter() - t0)
    rate = num_edges / float(np.median(ts))
    from gelly_streaming_tpu.ops.windowed_reduce import (
        _resolve_reduce_impl)

    tier = _resolve_reduce_impl("sum")
    from gelly_streaming_tpu.utils import telemetry as _telemetry

    device_path_rate = None
    if tier != "device":
        # decomposition row: the raw device segment-kernel path (one
        # warm + one timed rep), parity-checked against the routed
        # tier's already-verified windows like the triangles leg
        dev = eng._device_process_stream(src.astype(np.int64),
                                         dst.astype(np.int64), val)
        for (cells, _cnt), want in zip(dev, base):
            np.testing.assert_array_equal(
                cells[:num_vertices].astype(np.int64), want)
        t0 = time.perf_counter()
        eng._device_process_stream(src.astype(np.int64),
                                   dst.astype(np.int64), val)
        device_path_rate = num_edges / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "edges/sec/chip, windowed reduceOnEdges "
                  "sum-of-weights (power-law stream, %d-edge "
                  "windows)%s" % (window_edges, metric_suffix),
        "value": round(rate),
        "unit": "edges/s",
        "tier": tier,
        "vs_baseline": round(rate / cpu_rate, 2),
        "baseline_cpu_edges_per_s": round(cpu_rate),
        # secondary: the port made contract-equal (values AND counts)
        "baseline_cpu_with_counts_edges_per_s": round(cpu_rate_counts),
        "vs_baseline_with_counts": round(rate / cpu_rate_counts, 2),
        "num_edges": num_edges,
        # trace-ID correlation (see the triangles leg's row)
        "trace": _telemetry.trace_id(),
        **({"device_path_edges_per_s": round(device_path_rate),
            "device_path_vs_baseline": round(
                device_path_rate / cpu_rate, 2)}
           if device_path_rate is not None else {}),
    }), flush=True)


def run_cohort_leg(metric_suffix: str = "") -> None:
    """Multi-tenant cohort serving scenario (core/tenancy.py): N
    small tenant streams fed window by window, the cohort's ONE
    vmapped dispatch per round vs N sequential single-tenant engines
    — the 'thousands of small streams' serving shape the ROADMAP
    north star names. Per-tenant sha256 parity asserted before any
    speedup is claimed (tools/tenancy_ab.py owns the deeper
    median-of-3 committed evidence; this leg keeps the regression
    sentry's eye on it every bench run)."""
    from tools.tenancy_ab import (cohort_run, digest_summaries,
                                  make_tenant_streams,
                                  sequential_oracle)

    tenants, windows, eb, vb = 8, 8, 512, 1024
    streams = make_tenant_streams(tenants, windows, eb, vb)
    total_edges = sum(len(s) for s, _d in streams.values())
    want = sequential_oracle(streams, eb, vb, True)
    got = cohort_run(streams, eb, vb, True)
    for tid in streams:
        assert digest_summaries(got[tid]) == digest_summaries(
            want[tid]), "cohort diverged from the sequential " \
            "oracle for tenant %s" % tid
    reps = int(os.environ.get("GS_BENCH_REPS", "3"))
    seq_ts, coh_ts = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        sequential_oracle(streams, eb, vb, True)
        seq_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        cohort_run(streams, eb, vb, True)
        coh_ts.append(time.perf_counter() - t0)
    seq_s = float(np.median(seq_ts))
    coh_s = float(np.median(coh_ts))

    from gelly_streaming_tpu.ops import autotune as _autotune
    from gelly_streaming_tpu.utils import knobs as _knobs
    from gelly_streaming_tpu.utils import latency as _latency
    from gelly_streaming_tpu.utils import resilience as _resilience
    from gelly_streaming_tpu.utils import sanitize as _sanitize
    from gelly_streaming_tpu.utils import telemetry as _telemetry

    # robustness counters for the regression sentry: rejected-record
    # depth of the (possibly disarmed → 0) dead-letter journal, and
    # bulkhead quarantines recorded this process
    _dlq = _sanitize.dlq_status()
    _dlq_records = 0 if _dlq is None else int(_dlq["records"])
    _quarantines = sum(1 for e in _resilience.demotion_events()
                       if e.get("to") == "quarantined")

    # latency identities of the serving shape: one extra ARMED rep
    # (outside the timed medians — the ≤1.05x overhead must not skew
    # the speedup measurement) emits serve_e2e_p{50,95,99}_s, the
    # fields bench_compare checks lower-is-better; armed summaries
    # are asserted digest-identical first (the observe-only contract)
    lat_prev = os.environ.get("GS_LATENCY")
    os.environ["GS_LATENCY"] = "1"
    _latency.reset()
    try:
        armed = cohort_run(streams, eb, vb, True)
        for tid in streams:
            assert digest_summaries(armed[tid]) == digest_summaries(
                want[tid]), "ARMED latency plane changed tenant %s's " \
                "summaries — the zero-overhead contract is broken" % tid
        lat_fields = _latency.percentile_fields("serve_e2e")
    finally:
        if lat_prev is None:
            os.environ.pop("GS_LATENCY", None)
        else:
            os.environ["GS_LATENCY"] = lat_prev
        _latency.reset()

    print(json.dumps({
        "metric": "edges/sec/chip, multi-tenant cohort serving "
                  "(%d tenants, %d-edge windows, one vmapped "
                  "dispatch per round)%s"
                  % (tenants, eb, metric_suffix),
        "value": round(total_edges / coh_s),
        "unit": "edges/s",
        "tenants": tenants,
        "num_edges": total_edges,
        "tenant_edges_per_s": round(total_edges / coh_s),
        "sequential_edges_per_s": round(total_edges / seq_s),
        "cohort_speedup": round(seq_s / coh_s, 2),
        # ingest→deliver latency identities (utils/latency, armed
        # parity rep above): lower-is-better in bench_compare
        **lat_fields,
        # robustness counters (utils/sanitize + the tenancy
        # bulkhead): a clean serving run rejects nothing and
        # quarantines no one — bench_compare flags ANY non-zero turn
        # of either (lower-is-better, zero-baseline strict)
        "dlq_records": _dlq_records,
        "quarantines": _quarantines,
        # chosen-knob provenance, like every bench row: what dispatch
        # configuration the cohort actually ran
        "knobs": {"eb": eb, "vb": vb,
                  "tenants_per_dispatch": _knobs.get_int(
                      "GS_TENANT_TPD") or "auto",
                  "queue_windows": _knobs.get_int(
                      "GS_TENANT_QUEUE_WINDOWS"),
                  "admission": _knobs.get_str("GS_TENANT_ADMISSION")},
        "autotune": {"enabled": _autotune.enabled()},
        # trace-ID correlation (see the triangles leg's row)
        "trace": _telemetry.trace_id(),
    }), flush=True)


def run_gnn_leg(metric_suffix: str = "") -> None:
    """Windowed-GNN message-passing scenario (ops/gnn_window): the
    fused per-window GNN round (segment-sum aggregation + the dense
    MXU update) over a power-law stream. Parity vs the numpy lattice
    twin is asserted — summary stream AND final feature slab — before
    any rate is reported; the metric unit is edge-features/s (edges ×
    feature_dim per second), the axis the dense update actually
    scales on. tools/gnn_ab.py owns the deeper committed evidence;
    this leg keeps the regression sentry's eye on the workload every
    bench run."""
    from gelly_streaming_tpu.ops import gnn_window as gw
    from gelly_streaming_tpu.utils import knobs as _knobs
    from gelly_streaming_tpu.utils import telemetry as _telemetry
    from tools.gnn_ab import (digest_slab, digest_summaries,
                              run_engine)

    eb, vb, F, windows = 512, 1024, 16, 16
    n = windows * eb - eb // 3  # ragged tail: the partial-window path
    src, dst = make_stream(n, vb, seed=7)
    src, dst = src.astype(np.int32), dst.astype(np.int32)

    got, slab = run_engine(gw.GnnSummaryEngine, eb, vb, F, src, dst)
    want, wslab = run_engine(gw.GnnHostEngine, eb, vb, F, src, dst)
    assert digest_summaries(got) == digest_summaries(want) \
        and digest_slab(slab) == digest_slab(wslab), \
        "GNN round diverged from the numpy lattice twin"

    reps = int(os.environ.get("GS_BENCH_REPS", "3"))
    dev_ts, host_ts = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_engine(gw.GnnSummaryEngine, eb, vb, F, src, dst)
        dev_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_engine(gw.GnnHostEngine, eb, vb, F, src, dst)
        host_ts.append(time.perf_counter() - t0)
    dev_s = float(np.median(dev_ts))
    host_s = float(np.median(host_ts))

    print(json.dumps({
        "metric": "edge-features/sec/chip, windowed GNN round "
                  "(%d-edge windows, F=%d, fused scan vs numpy "
                  "twin)%s" % (eb, F, metric_suffix),
        "value": round(n * F / dev_s),
        "unit": "edge-features/s",
        "num_edges": n,
        "feature_dim": F,
        "gnn_edge_features_per_s": round(n * F / dev_s),
        "edges_per_s": round(n / dev_s),
        "host_edges_per_s": round(n / host_s),
        "parity": True,
        "knobs": {"eb": eb, "vb": vb, "feature_dim": F,
                  "act": _knobs.get_str("GS_GNN_ACT") or "relu",
                  "pallas": _knobs.get_str("GS_GNN_PALLAS")
                  or "auto"},
        "trace": _telemetry.trace_id(),
    }), flush=True)


def main():
    metric_suffix = ""
    if os.environ.get("GS_BENCH_GNN"):
        # GNN-leg child (same re-exec/watchdog/capacity contract as
        # the scale children)
        if "--cpu" in sys.argv or os.environ.get(
                "GS_BENCH_CPU_FALLBACK") == "1":
            from gelly_streaming_tpu.core.platform import use_cpu
            use_cpu()
        try:
            run_gnn_leg(os.environ.get("GS_BENCH_SUFFIX", ""))
        except AssertionError:
            raise  # parity failure: NEVER mask a correctness regression
        except Exception as e:
            if _is_resource_error(e) or _is_backend_drop(e):
                print("gnn leg: %s: %s" % (type(e).__name__, e),
                      file=sys.stderr)
                sys.exit(EXIT_CAPACITY)
            raise
        return
    if os.environ.get("GS_BENCH_COHORT"):
        # cohort-leg child (same re-exec/watchdog/capacity contract
        # as the scale children)
        if "--cpu" in sys.argv or os.environ.get(
                "GS_BENCH_CPU_FALLBACK") == "1":
            from gelly_streaming_tpu.core.platform import use_cpu
            use_cpu()
        try:
            run_cohort_leg(os.environ.get("GS_BENCH_SUFFIX", ""))
        except AssertionError:
            raise  # parity failure: NEVER mask a correctness regression
        except Exception as e:
            if _is_resource_error(e) or _is_backend_drop(e):
                print("cohort leg: %s: %s" % (type(e).__name__, e),
                      file=sys.stderr)
                sys.exit(EXIT_CAPACITY)
            raise
        return
    if os.environ.get("GS_BENCH_REDUCE"):
        # reduce-leg child (same re-exec/watchdog/capacity contract as
        # the scale children)
        if "--cpu" in sys.argv or os.environ.get(
                "GS_BENCH_CPU_FALLBACK") == "1":
            from gelly_streaming_tpu.core.platform import use_cpu
            use_cpu()
        try:
            run_reduce_leg(os.environ.get("GS_BENCH_SUFFIX", ""))
        except AssertionError:
            raise  # parity failure: NEVER mask a correctness regression
        except Exception as e:
            if _is_resource_error(e) or _is_backend_drop(e):
                print("reduce leg: %s: %s" % (type(e).__name__, e),
                      file=sys.stderr)
                sys.exit(EXIT_CAPACITY)
            raise
        return
    if os.environ.get("GS_BENCH_CHILD"):
        # child mode (checked FIRST — a child must never re-enter the
        # scale ladder): the parent already probed the backend and
        # chose the suffix; pin CPU when the parent did, then run the
        # one scale
        if "--cpu" in sys.argv or os.environ.get(
                "GS_BENCH_CPU_FALLBACK") == "1":
            from gelly_streaming_tpu.core.platform import use_cpu
            use_cpu()
        run_one_scale_child(float(os.environ["GS_BENCH_CHILD"]),
                            os.environ.get("GS_BENCH_SUFFIX", ""))
        return
    if "--cpu" in sys.argv:
        from gelly_streaming_tpu.core.platform import use_cpu
        use_cpu()
        metric_suffix = " [CPU - requested via --cpu]"
    elif os.environ.get("GS_BENCH_CPU_FALLBACK") == "1":
        # Re-exec'd below with a clean CPU env. Belt and braces: also
        # pop any non-cpu backend factory that registered via
        # site-packages entry points (PYTHONPATH= only kills the
        # sitecustomize route) so the dead tunnel can't re-enter.
        from gelly_streaming_tpu.core.platform import use_cpu
        use_cpu()
        metric_suffix = " [CPU FALLBACK - TPU tunnel down]"
    else:
        platform = probe_backend()
        if platform is None:
            # Dead backend: fail FAST into a hermetic CPU run instead of
            # burning the window against a tunnel that can't come up.
            # PYTHONPATH= skips the sitecustomize that injects the
            # (hanging) TPU plugin; JAX_PLATFORMS=cpu pins the backend.
            print("backend unavailable -> re-exec with hermetic CPU "
                  "backend", file=sys.stderr)
            env = clean_cpu_env(GS_BENCH_CPU_FALLBACK="1")
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        elif platform == "cpu":
            # a healthy probe of a CPU-only jax is NOT a chip result
            metric_suffix = " [CPU backend - no TPU in this env]"

    # Smallest scale first, one JSON line per completed scale: an
    # external timeout at a larger scale still leaves the best completed
    # number on stdout (the driver keeps the last line). Every requested
    # scale is attempted on every backend.
    # top scale = a 10.5M-edge stream (≥ the north star's 10M-edge
    # slice) through the capped 32768-edge window program
    scale = float(os.environ.get("BENCH_SCALE", "5.0"))
    done = 0
    for attempt in (scale / 80, scale / 20, scale):
        rc = run_scale_watchdogged(attempt, metric_suffix)
        if rc == 0:
            done += 1
            continue
        if rc == EXIT_CAPACITY and done:
            # device limit / backend death at this scale: keep the
            # completed smaller-scale results on stdout
            print("bench stopped at scale %g (capacity/backend); "
                  "keeping completed scales" % attempt, file=sys.stderr)
            break
        if rc == EXIT_TIMEOUT and done:
            # a wedged remote compile (round 2: a single big-window
            # compile stalled the tunnel >30 min) must not eat the
            # window; completed scales are already on stdout
            print("bench scale %g timed out (wedged backend?); "
                  "keeping completed scales" % attempt, file=sys.stderr)
            break
        # nothing completed (timeout/capacity at the smallest scale) or
        # a genuine bug (incl. parity): a green exit with no metric
        # lines must be impossible
        sys.exit(rc or 1)

    # BASELINE config #2's measured leg (columnar reduceOnEdges) — a
    # watchdogged child like the scales; capacity/timeout keeps the
    # triangle lines, a parity failure still fails the bench
    rc = run_scale_watchdogged(0.0, metric_suffix,
                               extra_env={"GS_BENCH_REDUCE": "1"})
    if rc not in (0, EXIT_CAPACITY, EXIT_TIMEOUT):
        sys.exit(rc)
    if rc:
        print("reduce leg rc=%d (capacity/timeout); triangle scales "
              "kept" % rc, file=sys.stderr)

    # multi-tenant cohort serving leg (core/tenancy.py) — watchdogged
    # like the others; capacity/timeout keeps the completed lines, a
    # parity failure still fails the bench
    rc = run_scale_watchdogged(0.0, metric_suffix,
                               extra_env={"GS_BENCH_COHORT": "1"})
    if rc not in (0, EXIT_CAPACITY, EXIT_TIMEOUT):
        sys.exit(rc)
    if rc:
        print("cohort leg rc=%d (capacity/timeout); other lines kept"
              % rc, file=sys.stderr)

    # windowed-GNN leg (ops/gnn_window) — watchdogged like the
    # others; capacity/timeout keeps the completed lines, a parity
    # failure still fails the bench
    rc = run_scale_watchdogged(0.0, metric_suffix,
                               extra_env={"GS_BENCH_GNN": "1"})
    if rc not in (0, EXIT_CAPACITY, EXIT_TIMEOUT):
        sys.exit(rc)
    if rc:
        print("gnn leg rc=%d (capacity/timeout); other lines kept"
              % rc, file=sys.stderr)


EXIT_CAPACITY = 3
EXIT_TIMEOUT = 4


def run_one_scale_child(attempt: float, metric_suffix: str) -> None:
    try:
        run_at_scale(attempt, metric_suffix)
    except AssertionError:
        raise  # parity failure: NEVER mask a correctness regression
    except Exception as e:
        if _is_resource_error(e) or _is_backend_drop(e):
            print("scale %g: %s: %s" % (attempt, type(e).__name__, e),
                  file=sys.stderr)
            sys.exit(EXIT_CAPACITY)
        raise


def run_scale_watchdogged(attempt: float, metric_suffix: str,
                          extra_env: dict = None) -> int:
    """Run one scale (or, with extra_env, another bench leg) in a
    subprocess with a hard timeout, streaming its stdout through. A
    hung remote compile gets SIGKILLed (process group) instead of
    stalling the whole bench."""
    import signal

    timeout_s = int(os.environ.get("GS_BENCH_SCALE_TIMEOUT", "1500"))
    env = dict(os.environ, GS_BENCH_SUFFIX=metric_suffix)
    if extra_env:
        env.update(extra_env)
    else:
        env["GS_BENCH_CHILD"] = repr(attempt)
    p = subprocess.Popen([sys.executable] + sys.argv, env=env,
                         stdout=subprocess.PIPE, text=True,
                         start_new_session=True)
    import threading

    def pump():
        for line in p.stdout:
            sys.stdout.write(line)
            sys.stdout.flush()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        rc = p.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.wait()
        rc = EXIT_TIMEOUT
    t.join(timeout=5)
    return rc


if __name__ == "__main__":
    main()

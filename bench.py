#!/usr/bin/env python
"""North-star benchmark: edges/sec on exact Window Triangle Count.

Streams a synthetic power-law edge stream (a stand-in for the Twitter
slice named in BASELINE.json — zero-egress environment, no external
datasets) through tumbling count-windows and measures end-to-end
throughput of the streaming device pipeline
(ops/triangles.TriangleWindowKernel: ONE compiled program for all
windows; the host ships only raw COO arrays).

Baseline (BASELINE.md: "run the Flink reference or a faithful CPU
port"): a faithful CPU port of the reference's candidate-pair pipeline
(GenerateCandidateEdges + CountTriangles, WindowTriangles.java:83-140)
measured on a sample of the same stream. The CPU port runs on smaller
windows than the device (its O(d²) candidate generation is intractable
at the device's window size — hub degree grows with window length), so
the reported ratio is CONSERVATIVE: per-edge work grows superlinearly
with window size for both paths.

Exact-count parity between both paths is asserted on the shared sample
windows before anything is timed.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N}
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def make_stream(num_edges: int, num_vertices: int, seed: int = 7):
    """Power-law-ish edge stream: endpoints drawn from a Zipf-like
    distribution over the vertex space (heavy hitters like a social
    stream), timestamps strictly increasing."""
    rng = np.random.default_rng(seed)
    # exponent ~1.1 keeps candidate counts representative but bounded
    weights = 1.0 / np.arange(1, num_vertices + 1) ** 1.1
    weights /= weights.sum()
    src = rng.choice(num_vertices, size=num_edges, p=weights)
    dst = rng.choice(num_vertices, size=num_edges, p=weights)
    # no self-loops (match real graph datasets): redraw collisions
    loops = src == dst
    while loops.any():
        dst[loops] = rng.choice(num_vertices, size=int(loops.sum()), p=weights)
        loops = src == dst
    # remap so hot vertices are scattered over the id space
    perm = rng.permutation(num_vertices)
    return perm[src], perm[dst]


def device_window_counts(kernel, src, dst, window_edges):
    """Streaming device path: the whole stream's windows batched into
    lax.map dispatches (kernel.count_stream) — one h2d per chunk, one
    d2h of the counts, zero per-window round-trips."""
    assert window_edges == kernel.eb, "stream windows must match the bucket"
    return kernel.count_stream(src, dst)


def warmup_stream_shapes(kernel, num_edges):
    """Compile the (at most two) chunk shapes the timed run will use:
    a full MAX_STREAM_WINDOWS chunk and the ragged final chunk."""
    num_w = -(-num_edges // kernel.eb)
    first = min(num_w, kernel.MAX_STREAM_WINDOWS)
    zeros = np.zeros(first * kernel.eb, np.int32)
    kernel.count_stream(zeros, zeros)
    tail = num_w % kernel.MAX_STREAM_WINDOWS
    if tail and tail != first:
        zeros = zeros[: tail * kernel.eb]
        kernel.count_stream(zeros, zeros)


def cpu_reference_window_counts(src, dst, window_edges):
    """Faithful CPU port of the reference pipeline: per-vertex ALL-window
    neighborhoods → candidate pairs (ids > vertex) → per-pair groups →
    count candidates where a real edge exists."""
    counts = []
    for start in range(0, len(src), window_edges):
        s = src[start:start + window_edges]
        d = dst[start:start + window_edges]
        neighborhoods = {}
        for u, v in zip(s.tolist(), d.tolist()):
            neighborhoods.setdefault(u, []).append(v)
            neighborhoods.setdefault(v, []).append(u)
        real = set()
        candidates = {}
        for vertex, nbrs in neighborhoods.items():
            distinct = list(dict.fromkeys(nbrs))
            for n in nbrs:
                real.add((vertex, n))
            for i in range(len(distinct) - 1):
                if distinct[i] <= vertex:
                    continue
                for j in range(i, len(distinct)):
                    if distinct[j] > vertex:
                        pair = (distinct[i], distinct[j])
                        candidates[pair] = candidates.get(pair, 0) + 1
        total = sum(c for pair, c in candidates.items() if pair in real)
        counts.append(total)
    return counts


def run_at_scale(scale: float) -> None:
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    num_edges = int(2_097_152 * scale)
    window_edges = int(131_072 * scale)
    num_vertices = int(262_144 * scale)
    src, dst = make_stream(num_edges, num_vertices)

    kernel = TriangleWindowKernel(
        edge_bucket=window_edges, vertex_bucket=num_vertices)
    # count_stream slices windows of exactly the kernel's edge bucket,
    # so align the stream's window length to it (scales whose raw
    # window_edges is not a power of two round up)
    window_edges = kernel.eb

    # correctness cross-check + CPU baseline on shared sample windows
    # (small enough for the O(d²) candidate pipeline to finish)
    sample_window = min(window_edges, 8_192)
    sample = 2 * sample_window
    t0 = time.perf_counter()
    ref_counts = cpu_reference_window_counts(
        src[:sample], dst[:sample], sample_window)
    cpu_rate = sample / (time.perf_counter() - t0)
    # parity of BOTH device paths: the per-window escalating kernel and
    # the batched lax.map streaming path the timed run uses
    dev_counts = [
        kernel.count(src[s:s + sample_window], dst[s:s + sample_window])
        for s in range(0, sample, sample_window)
    ]
    assert dev_counts == ref_counts, (dev_counts, ref_counts)
    sample_kernel = TriangleWindowKernel(
        edge_bucket=sample_window, vertex_bucket=num_vertices)
    stream_counts = sample_kernel.count_stream(src[:sample], dst[:sample])
    assert stream_counts == ref_counts, (stream_counts, ref_counts)

    # warmup at the exact chunk shapes of the timed run (compile here)
    warmup_stream_shapes(kernel, num_edges)
    t0 = time.perf_counter()
    device_window_counts(kernel, src, dst, window_edges)
    elapsed = time.perf_counter() - t0
    rate = num_edges / elapsed

    print(json.dumps({
        "metric": "edges/sec/chip, exact window triangle count "
                  "(power-law stream, %d-edge windows)" % window_edges,
        "value": round(rate),
        "unit": "edges/s",
        "vs_baseline": round(rate / cpu_rate, 2),
    }))


def main():
    if "--cpu" in sys.argv:
        from gelly_streaming_tpu.core.platform import use_cpu
        use_cpu()

    # fall back to smaller streams rather than reporting nothing if the
    # full-scale run hits a device limit (the metric line names the
    # actual window size, so a fallback result stays honest)
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    for attempt in (scale, scale / 4, scale / 16):
        try:
            run_at_scale(attempt)
            return
        except AssertionError:
            raise  # parity failure: NEVER mask a correctness regression
        except Exception as e:
            if attempt == scale / 16:
                raise
            print("bench failed at scale %g (%s: %s); retrying smaller"
                  % (attempt, type(e).__name__, e), file=sys.stderr)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""North-star benchmark: edges/sec on exact Window Triangle Count.

Streams a synthetic power-law edge stream (a stand-in for the Twitter
slice named in BASELINE.json — zero-egress environment, no external
datasets) through tumbling count-windows and measures end-to-end
throughput of the fused device pipeline (host interning + device
triangle kernel, models/triangles.py).

Baseline (BASELINE.md: "run the Flink reference or a faithful CPU port"):
a faithful CPU port of the reference's candidate-pair pipeline
(GenerateCandidateEdges + CountTriangles, WindowTriangles.java:83-140)
measured on a sample of the same stream, with identical per-window
counts asserted between both paths.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N}
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def make_stream(num_edges: int, num_vertices: int, seed: int = 7):
    """Power-law-ish edge stream: endpoints drawn from a Zipf-like
    distribution over the vertex space (heavy hitters like a social
    stream), timestamps strictly increasing."""
    rng = np.random.default_rng(seed)
    # exponent ~1.1 keeps candidate counts representative but bounded
    weights = 1.0 / np.arange(1, num_vertices + 1) ** 1.1
    weights /= weights.sum()
    src = rng.choice(num_vertices, size=num_edges, p=weights)
    dst = rng.choice(num_vertices, size=num_edges, p=weights)
    # no self-loops (match real graph datasets): redraw collisions
    loops = src == dst
    while loops.any():
        dst[loops] = rng.choice(num_vertices, size=int(loops.sum()), p=weights)
        loops = src == dst
    # remap so hot vertices are scattered over the id space
    perm = rng.permutation(num_vertices)
    return perm[src], perm[dst]


def device_window_counts(src, dst, window_edges):
    """Fused device path: per-window intern + triangle kernel."""
    from gelly_streaming_tpu.ops import segment as seg_ops
    from gelly_streaming_tpu.ops import triangles as tri_ops

    counts = []
    for start in range(0, len(src), window_edges):
        s = src[start:start + window_edges]
        d = dst[start:start + window_edges]
        uniq, (si, di) = seg_ops.intern(s, d)
        counts.append(tri_ops.triangle_count(si, di, len(uniq)))
    return counts


def cpu_reference_window_counts(src, dst, window_edges):
    """Faithful CPU port of the reference pipeline: per-vertex ALL-window
    neighborhoods → candidate pairs (ids > vertex) → per-pair groups →
    count candidates where a real edge exists."""
    counts = []
    for start in range(0, len(src), window_edges):
        s = src[start:start + window_edges]
        d = dst[start:start + window_edges]
        neighborhoods = {}
        for u, v in zip(s.tolist(), d.tolist()):
            neighborhoods.setdefault(u, []).append(v)
            neighborhoods.setdefault(v, []).append(u)
        real = set()
        candidates = {}
        for vertex, nbrs in neighborhoods.items():
            distinct = list(dict.fromkeys(nbrs))
            for n in nbrs:
                real.add((vertex, n))
            for i in range(len(distinct) - 1):
                if distinct[i] <= vertex:
                    continue
                for j in range(i, len(distinct)):
                    if distinct[j] > vertex:
                        pair = (distinct[i], distinct[j])
                        candidates[pair] = candidates.get(pair, 0) + 1
        total = sum(c for pair, c in candidates.items() if pair in real)
        counts.append(total)
    return counts


def main():
    if "--cpu" in sys.argv:
        from gelly_streaming_tpu.core.platform import use_cpu
        use_cpu()

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    num_edges = int(2_097_152 * scale)
    window_edges = int(131_072 * scale)
    num_vertices = int(262_144 * scale)
    src, dst = make_stream(num_edges, num_vertices)

    # correctness cross-check + baseline measurement on a sample
    sample_windows = 2
    sample = sample_windows * min(window_edges, 16_384)
    t0 = time.perf_counter()
    ref_counts = cpu_reference_window_counts(
        src[:sample], dst[:sample], sample // sample_windows)
    cpu_rate = sample / (time.perf_counter() - t0)
    dev_counts = device_window_counts(
        src[:sample], dst[:sample], sample // sample_windows)
    assert dev_counts == ref_counts, (dev_counts, ref_counts)

    # warmup (compile), then timed full stream
    device_window_counts(src[:window_edges], dst[:window_edges], window_edges)
    t0 = time.perf_counter()
    device_window_counts(src, dst, window_edges)
    elapsed = time.perf_counter() - t0
    rate = num_edges / elapsed

    print(json.dumps({
        "metric": "edges/sec/chip, exact window triangle count "
                  "(power-law stream, %d-edge windows)" % window_edges,
        "value": round(rate),
        "unit": "edges/s",
        "vs_baseline": round(rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()

"""Program cost observatory suite (utils/costmodel):

- signature rendering: metrics.abstract_sig tuples → the compact
  deterministic string the ledger tags and registry keys share;
- roofline classification: bytes- vs FLOPs-bound against the
  GS_COSTMODEL_PEAK_* machine balance, `unknown` without both inputs;
- capture paths: wrap_exec (free, off the existing AOT executable)
  and the wrap_jit on_call hook (one extra AOT compile per new
  signature), idempotent per (program, sig), error-tolerant on
  un-lowerable functions;
- the telemetry-sink join: program/sig-tagged dispatch spans
  accumulate measured seconds, report() serves the joined rows
  (including cost-less rows for programs armed after their compile);
- end-to-end: an armed fused-scan engine run leaves ledger dispatch
  spans carrying program="fused_scan" + sig — the attribution
  substrate tools/explain_perf.py drills into;
- the zero-overhead contract: GS_COSTMODEL=0 (the default) vs 1 on
  the 524K/32768 CPU row is digest-identical (the observatory
  observes, never participates) — the acceptance pin.
"""

import hashlib

import numpy as np
import pytest

from gelly_streaming_tpu.utils import costmodel, metrics, telemetry


@pytest.fixture
def armed(monkeypatch):
    """Observatory armed, registry fresh before AND after."""
    monkeypatch.setenv("GS_COSTMODEL", "1")
    monkeypatch.delenv("GS_TELEMETRY", raising=False)
    costmodel.reset()
    telemetry.reset()
    yield
    costmodel.reset()
    telemetry.reset()


def _stream(num_edges, num_vertices, seed=7):
    from bench import make_stream

    return make_stream(num_edges, num_vertices, seed)


def _toy_exec():
    """A tiny AOT-compiled executable + its abstract signature."""
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return (x * y).sum() + jnp.dot(x, y)

    sds = (jax.ShapeDtypeStruct((64,), jnp.float32),
           jax.ShapeDtypeStruct((64,), jnp.float32))
    return jax.jit(f).lower(*sds).compile(), metrics.abstract_sig(sds)


# ----------------------------------------------------------------------
# signature rendering
# ----------------------------------------------------------------------
def test_sig_key_renders_abstract_sigs():
    import jax.numpy as jnp

    sig = metrics.abstract_sig(
        (jnp.zeros((16, 32768), jnp.int32),
         jnp.zeros((16, 32768), jnp.uint16),
         jnp.zeros((16,), jnp.bool_)))
    assert costmodel.sig_key(sig) \
        == "i32[16,32768],u16[16,32768],b1[16]"
    # nested pytree args (the fused-scan carry tuple) render nested
    nested = metrics.abstract_sig(
        ((jnp.zeros(4, jnp.int32), jnp.zeros(8, jnp.float32)),))
    assert costmodel.sig_key(nested) == "(i32[4],f32[8])"
    # deterministic: the same sig twice is the same key
    assert costmodel.sig_key(sig) == costmodel.sig_key(sig)


# ----------------------------------------------------------------------
# roofline classification
# ----------------------------------------------------------------------
def test_classify_bytes_vs_flops_bound(monkeypatch):
    monkeypatch.setenv("GS_COSTMODEL_PEAK_GFLOPS", "100")
    monkeypatch.setenv("GS_COSTMODEL_PEAK_GBPS", "10")
    # machine balance = 10 FLOPs/byte
    low = costmodel.classify({"flops": 10, "bytes_accessed": 100})
    assert low["bound"] == "bytes"
    assert low["arith_intensity_flops_per_byte"] == 0.1
    # bytes-bound: roofline time is the bandwidth term
    assert low["roofline_s"] == pytest.approx(100 / 10e9)
    high = costmodel.classify({"flops": 10000, "bytes_accessed": 100})
    assert high["bound"] == "flops"
    assert high["roofline_s"] == pytest.approx(10000 / 100e9)
    assert high["machine_balance_flops_per_byte"] == 10.0


def test_classify_unknown_without_both_inputs():
    for entry in ({}, {"flops": 10}, {"bytes_accessed": 10},
                  {"flops": None, "bytes_accessed": 10}):
        out = costmodel.classify(dict(entry))
        assert out["bound"] == "unknown"
        assert out["roofline_s"] is None


def test_join_measure_math():
    entry = costmodel.classify(
        {"flops": 2_000_000_000, "bytes_accessed": 4_000_000_000})
    costmodel.join_measure(entry, count=4, total_s=8.0)
    assert entry["dispatches"] == 4
    assert entry["measured_mean_s"] == 2.0
    assert entry["achieved_gflops"] == 1.0     # 2 GF / 2 s
    assert entry["achieved_gbps"] == 2.0       # 4 GB / 2 s
    assert entry["roofline_frac"] == pytest.approx(
        entry["roofline_s"] / 2.0, abs=1e-6)
    # zero measurements: economics fields stay absent
    bare = costmodel.join_measure(costmodel.classify({}), 0, 0.0)
    assert "measured_mean_s" not in bare


# ----------------------------------------------------------------------
# disarmed: guarded no-ops
# ----------------------------------------------------------------------
def test_disarmed_captures_nothing(monkeypatch):
    monkeypatch.setenv("GS_COSTMODEL", "0")
    costmodel.reset()
    try:
        ex, sig = _toy_exec()
        costmodel.record_compiled("toy", ex, sig)
        costmodel.on_call("toy", ex, sig, (), {})
        wrapped = costmodel.wrap_exec("toy", ex, sig)
        wrapped(np.ones(64, np.float32), np.ones(64, np.float32))
        assert costmodel.programs() == {}
        assert costmodel.report() == []
        assert telemetry.pop_dispatch_tags() == {}
    finally:
        costmodel.reset()


# ----------------------------------------------------------------------
# armed capture: wrap_exec (free) and on_call (one extra compile)
# ----------------------------------------------------------------------
def test_wrap_exec_captures_and_tags(armed):
    ex, sig = _toy_exec()
    wrapped = costmodel.wrap_exec("toy_exec", ex, sig)
    assert wrapped.__wrapped__ is ex
    out = wrapped(np.ones(64, np.float32), np.ones(64, np.float32))
    assert float(np.asarray(out)) == pytest.approx(128.0)
    entry = costmodel.programs()[("toy_exec", "f32[64],f32[64]")]
    # the CPU backend reports both analyses on an AOT executable
    assert entry["flops"] > 0
    assert entry["bytes_accessed"] > 0
    assert entry["argument_bytes"] == 512      # 2 × 64 × f32
    assert entry["bound"] in ("bytes", "flops")
    # the dispatch bound its program/sig tags for the span record site
    assert telemetry.pop_dispatch_tags() \
        == {"program": "toy_exec", "sig": "f32[64],f32[64]"}
    # idempotent per key: a second call re-tags, never re-captures
    before = costmodel.programs()
    wrapped(np.ones(64, np.float32), np.ones(64, np.float32))
    assert costmodel.programs() == before


def test_wrap_exec_armed_mid_stream_still_captures(monkeypatch):
    """Disarmed at wrap time, armed later: the compiled handle rides
    the closure, so the first ARMED call captures."""
    monkeypatch.setenv("GS_COSTMODEL", "0")
    costmodel.reset()
    try:
        ex, sig = _toy_exec()
        wrapped = costmodel.wrap_exec("toy_late", ex, sig)
        wrapped(np.ones(64, np.float32), np.ones(64, np.float32))
        assert costmodel.programs() == {}
        monkeypatch.setenv("GS_COSTMODEL", "1")
        wrapped(np.ones(64, np.float32), np.ones(64, np.float32))
        assert ("toy_late", "f32[64],f32[64]") in costmodel.programs()
        telemetry.pop_dispatch_tags()
    finally:
        costmodel.reset()


def test_on_call_via_wrap_jit_captures_per_signature(armed):
    import jax
    import jax.numpy as jnp

    fn = metrics.wrap_jit("toy_jit", jax.jit(lambda x: x + 1))
    fn(jnp.arange(8))
    fn(jnp.arange(8))                      # same sig: one entry
    fn(jnp.arange(16, dtype=jnp.float32))  # new sig: second entry
    progs = costmodel.programs()
    assert set(progs) == {("toy_jit", "i32[8]"),
                          ("toy_jit", "f32[16]")}
    assert progs[("toy_jit", "i32[8]")]["flops"] is not None
    telemetry.pop_dispatch_tags()


def test_on_call_unlowerable_records_error_entry(armed):
    costmodel.on_call("plain_fn", lambda x: x, ("sig",), (1,), {})
    entry = costmodel.programs()[("plain_fn", "sig")]
    assert "not AOT-lowerable" in entry["error"]
    assert entry["bound"] == "unknown"
    # error rows still carry the schema-required cost keys (null), so
    # a partially-captured run commits a valid cost_model section
    assert entry["flops"] is None
    assert entry["bytes_accessed"] is None
    # the error entry still reports (cost-less) instead of vanishing
    rows = costmodel.report()
    assert any(r.get("program") == "plain_fn" for r in rows)
    telemetry.pop_dispatch_tags()


# ----------------------------------------------------------------------
# the sink join + report
# ----------------------------------------------------------------------
def test_sink_joins_tagged_spans_into_report(armed, monkeypatch):
    ex, sig = _toy_exec()
    costmodel.record_compiled("joined", ex, sig)
    for _ in range(3):
        with telemetry.span("ingress.dispatch", program="joined",
                            sig="f32[64],f32[64]"):
            pass
    # untagged spans never reach the registry
    with telemetry.span("ingress.prep"):
        pass
    rows = {r["program"]: r for r in costmodel.report()}
    assert rows["joined"]["dispatches"] == 3
    assert rows["joined"]["measured_total_s"] >= 0
    assert "roofline_frac" in rows["joined"] \
        or rows["joined"]["measured_total_s"] == 0.0
    # a tagged program that was never captured (armed after compile)
    # still reports, cost-less
    with telemetry.span("ingress.dispatch", program="ghost",
                        sig="i32[4]"):
        pass
    rows = {r["program"]: r for r in costmodel.report()}
    assert rows["ghost"]["dispatches"] == 1
    assert rows["ghost"]["bound"] == "unknown"
    # cost-less rows still carry the schema-required keys as null
    assert rows["ghost"]["flops"] is None
    assert rows["ghost"]["bytes_accessed"] is None


def test_report_sorted_by_measured_time(armed):
    for name, n in (("cold", 1), ("hot", 4)):
        for _ in range(n):
            with telemetry.span("ingress.dispatch", program=name,
                                sig="s"):
                import time

                time.sleep(0.001)
    order = [r["program"] for r in costmodel.report()]
    assert order.index("hot") < order.index("cold")


# ----------------------------------------------------------------------
# end-to-end: the fused-scan engine leaves an attributable ledger
# ----------------------------------------------------------------------
def test_engine_dispatch_spans_carry_program_tags(
        armed, monkeypatch, tmp_path):
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    src, dst = _stream(4096, 512)
    eng = StreamSummaryEngine(edge_bucket=1024, vertex_bucket=512)
    eng.process(src, dst)
    spans = [r for r in telemetry.records() if r["t"] == "span"]
    tagged = [r for r in spans
              if (r.get("a") or {}).get("program") == "fused_scan"]
    assert tagged, "no dispatch span carried the fused_scan tag"
    sig = tagged[0]["a"]["sig"]
    assert "i32[" in sig                  # the COO slab is in the key
    assert ("fused_scan", sig) in costmodel.programs()
    # the live join serves the same rows explain_perf computes offline
    row = next(r for r in costmodel.report()
               if r["program"] == "fused_scan")
    assert row["dispatches"] == len(tagged)
    assert row["flops"] is not None


def test_dispatch_tags_survive_armed_stage_watchdog(
        armed, monkeypatch, tmp_path):
    """With GS_STAGE_TIMEOUT_S armed, resilience runs the dispatch on
    the gs-stage-watchdog helper thread — the program/sig tags bind
    in THAT thread's TLS and must still reach the span record (the
    production-debugging configuration: watchdog + observatory)."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("GS_STAGE_TIMEOUT_S", "120")
    telemetry.reset()
    src, dst = _stream(4096, 512)
    eng = StreamSummaryEngine(edge_bucket=1024, vertex_bucket=512)
    eng.process(src, dst)
    tagged = [r for r in telemetry.records()
              if r["t"] == "span" and r.get("name") == "ingress.dispatch"
              and (r.get("a") or {}).get("program") == "fused_scan"]
    assert tagged, ("guarded dispatch lost its program tags — the "
                    "watchdog thread's TLS didn't reach the record")


# ----------------------------------------------------------------------
# the zero-overhead contract (acceptance pin)
# ----------------------------------------------------------------------
def test_disarmed_digest_parity_524k_row(monkeypatch):
    """GS_COSTMODEL=0 (default knobs) vs 1 on the 524K/32768 CPU row:
    counts are bit-identical — the observatory observes, never
    participates."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    src, dst = _stream(524288, 65536)
    monkeypatch.delenv("GS_COSTMODEL", raising=False)
    monkeypatch.delenv("GS_TELEMETRY", raising=False)
    costmodel.reset()
    telemetry.reset()
    kern = TriangleWindowKernel(edge_bucket=32768,
                                vertex_bucket=65536)
    base = kern.count_stream(src, dst)
    assert costmodel.programs() == {}     # disarmed: nothing captured
    monkeypatch.setenv("GS_COSTMODEL", "1")
    costmodel.reset()
    try:
        armed_counts = kern.count_stream(src, dst)
        captured = costmodel.programs()
    finally:
        costmodel.reset()
        telemetry.reset()
    digest = lambda c: hashlib.sha256(  # noqa: E731
        np.asarray(c, np.int64).tobytes()).hexdigest()
    assert digest(base) == digest(armed_counts)
    # armed, the device tier's stream program was captured — unless
    # this host's committed evidence routes the row to the numpy tier
    # (no dispatches to observe); either way the counts are identical
    if any(k[0] == "triangle_stream" for k in captured):
        entry = next(v for k, v in captured.items()
                     if k[0] == "triangle_stream")
        assert entry["bound"] in ("bytes", "flops", "unknown")

"""Documentation drift guards: the evidence and design docs cite repo
files and symbols; a rename or deletion must fail HERE, not silently
rot the docs (stale citations were the most common review-finding
class while these docs grew)."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOCS = [
    "README.md", "PERF.md", "BASELINE.md",
    "docs/DESIGN.md", "docs/PARITY.md", "docs/PORTING.md",
    "docs/OPERATIONS.md", "docs/ROUND2.md",
]

# symbols the docs name as load-bearing API
DOC_SYMBOLS = [
    ("bench.py", "def probe_backend"),
    ("bench.py", "def run_with_hard_timeout"),
    ("bench.py", "def run_json_child"),
    ("bench.py", "def clean_cpu_env"),
    ("gelly_streaming_tpu/ops/neighborhood.py", "def _make_pane_reduce"),
    ("gelly_streaming_tpu/ops/neighborhood.py", "def window_stack_combine"),
    ("gelly_streaming_tpu/ops/segment.py",
     "def segmented_reduce_associative"),
    ("gelly_streaming_tpu/ops/triangles.py", "def resolve_intersect_impl"),
    ("gelly_streaming_tpu/ops/triangles.py", "def resolve_xla_intersect"),
    ("gelly_streaming_tpu/ops/triangles.py", "def _tuned_kb"),
    ("gelly_streaming_tpu/parallel/sharded.py",
     "def make_sharded_pane_reduce"),
    ("gelly_streaming_tpu/core/platform.py", "def use_cpu"),
]


def _exists_somewhere(path: str) -> bool:
    cands = [path, os.path.join("gelly_streaming_tpu", path),
             os.path.join("tests", path), os.path.join("docs", path),
             os.path.join("tools", path), os.path.join("examples", path)]
    if os.path.basename(path) == path and path.startswith("test_"):
        return any(path in files
                   for _r, _d, files in os.walk(os.path.join(REPO, "tests")))
    return any(os.path.exists(os.path.join(REPO, c)) for c in cands)


def test_doc_file_citations_resolve():
    bad = []
    for doc in DOCS:
        text = open(os.path.join(REPO, doc), encoding="utf-8").read()
        cited = set(re.findall(
            r"`([A-Za-z_][A-Za-z0-9_/.]*\.(?:py|sh|md|json|cpp))`", text))
        cited |= set(re.findall(r"\b(tests/[a-z0-9_/]+\.py)\b", text))
        cited |= set(re.findall(r"\b(test_[a-z0-9_]+\.py)\b", text))
        for c in sorted(cited):
            # driver/queue-produced per-round artifacts may not exist
            # yet (BENCH_r02.json lands at end of round;
            # BENCH_chip_rNN.json is the queue's in-window snapshot)
            if re.match(r"(BENCH|MULTICHIP)(_chip)?_r(\{?N\}?|NN|\d+)",
                        os.path.basename(c)):
                continue
            if not _exists_somewhere(c):
                bad.append((doc, c))
    assert not bad, bad


def test_doc_symbol_citations_resolve():
    bad = [(f, sym) for f, sym in DOC_SYMBOLS
           if sym not in open(os.path.join(REPO, f),
                              encoding="utf-8").read()]
    assert not bad, bad

"""Sliding-window semantics and the pane-based device path.

The substrate surface (Flink `timeWindow(size, slide)`,
`SlidingEventTimeWindows`) supports sliding windows even though the
reference's examples only ever use the tumbling form — `slice(size,
direction, slide=...)` exposes them here. Golden values are
hand-computed on a 4-edge event-time fixture; the device monoid path
(one pane-partial dispatch for ALL windows) must agree with the
reference-semantics host path exactly.
"""

import numpy as np
import pytest

from gelly_streaming_tpu import (AscendingTimestampExtractor, Edge,
                                 EdgeDirection, EdgesReduce, JaxEdgesReduce,
                                 SimpleEdgeStream, Time)

from ..conftest import run_and_sort

# value doubles as the event-time timestamp (ms)
EDGES = [
    Edge(1, 2, 100),
    Edge(1, 3, 150),
    Edge(1, 2, 250),
    Edge(2, 3, 350),
]

# size=200ms, slide=100ms over OUT-direction neighborhoods:
#   [0,200):   v1 = 100+150          = 250
#   [100,300): v1 = 100+150+250      = 500
#   [200,400): v1 = 250, v2 = 350
#   [300,500): v2 = 350
SLIDING_SUM = sorted(["1,250", "1,500", "1,250", "2,350", "2,350"])
SLIDING_MAX = sorted(["1,150", "1,250", "1,250", "2,350", "2,350"])


def _graph(env, edges=EDGES):
    return SimpleEdgeStream(
        env.from_collection(edges), env,
        timestamp_extractor=AscendingTimestampExtractor(
            lambda e: e.value))


def test_sliding_reduce_host(env):
    out = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(EdgesReduce(lambda a, b: a + b))
    assert run_and_sort(env, out) == SLIDING_SUM


@pytest.mark.parametrize("name,expected",
                         [("sum", SLIDING_SUM), ("max", SLIDING_MAX)])
def test_sliding_reduce_device_pane_path(env, name, expected):
    """Named monoids take the pane path: ONE device dispatch builds
    per-(pane, vertex) partials and combines size/slide shifted
    slices into every window."""
    out = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(JaxEdgesReduce(name=name))
    assert run_and_sort(env, out) == expected


def test_slide_equal_size_is_tumbling(env):
    tumbling = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
    ).reduce_on_edges(JaxEdgesReduce(name="sum"))
    got_t = run_and_sort(env, tumbling)

    env2 = type(env)(clock=env.clock)
    sliding = _graph(env2).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(200),
    ).reduce_on_edges(JaxEdgesReduce(name="sum"))
    assert run_and_sort(env2, sliding) == got_t


def test_sliding_non_divisible_slide_matches_host(env):
    """size % slide != 0: the pane path declines (panes don't tile
    windows); the per-window assignment path must still be exact."""
    size, slide = Time.milliseconds_of(250), Time.milliseconds_of(100)
    host = _graph(env).slice(size, EdgeDirection.OUT, slide=slide) \
        .reduce_on_edges(EdgesReduce(lambda a, b: a + b))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2).slice(size, EdgeDirection.OUT, slide=slide) \
        .reduce_on_edges(JaxEdgesReduce(name="sum"))
    assert run_and_sort(env2, dev) == want
    assert len(want) > 0


def test_sliding_pane_fallback_matches(env, monkeypatch):
    """Over the pane-cell limit the pane kernel falls back to
    per-window device calls — same results."""
    from gelly_streaming_tpu.ops import neighborhood

    monkeypatch.setattr(neighborhood, "_PANE_CELL_LIMIT", 1)
    out = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(JaxEdgesReduce(name="sum"))
    assert run_and_sort(env, out) == SLIDING_SUM


@pytest.mark.parametrize("direction", [EdgeDirection.OUT,
                                       EdgeDirection.IN,
                                       EdgeDirection.ALL])
def test_sliding_random_parity_host_vs_pane(env, direction):
    """Random stream: pane path == host reference semantics across a
    ragged pane axis with gaps, in every edge direction (IN reverses
    the stream, ALL doubles it — both upstream of the pane grouping)."""
    rng = np.random.default_rng(7)
    edges = []
    t = 0
    for _ in range(200):
        t += int(rng.integers(1, 120))
        edges.append(Edge(int(rng.integers(0, 12)),
                          int(rng.integers(0, 12)), t))
    size, slide = Time.milliseconds_of(400), Time.milliseconds_of(100)

    host = _graph(env, edges).slice(size, direction, slide=slide) \
        .reduce_on_edges(EdgesReduce(lambda a, b: min(a, b)))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2, edges).slice(size, direction, slide=slide) \
        .reduce_on_edges(JaxEdgesReduce(name="min"))
    assert run_and_sort(env2, dev) == want


def test_sliding_empty_input_emits_nothing(env):
    """Zero records through the pane path: no windows fire."""
    g = _graph(env, [Edge(1, 2, 100)])
    out = g.filter_edges(lambda e: False).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(JaxEdgesReduce(name="sum"))
    sink = out.collect()
    env.execute()
    assert env.results_of(sink) == []


def test_sliding_sparse_huge_span_fallback(env):
    """A sparse stream spanning a huge time range exceeds the pane-cell
    limit; the fallback must iterate only occupied windows (a dense
    range sweep would effectively hang) and stay exact."""
    rng = np.random.default_rng(3)
    edges, t = [], 0
    for _ in range(120):
        t += int(rng.integers(1, 10_000_000))
        edges.append(Edge(int(rng.integers(0, 6)),
                          int(rng.integers(0, 6)), t))
    size, slide = Time.milliseconds_of(400), Time.milliseconds_of(100)
    host = _graph(env, edges).slice(size, EdgeDirection.OUT, slide=slide) \
        .reduce_on_edges(EdgesReduce(lambda a, b: a + b))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2, edges).slice(size, EdgeDirection.OUT, slide=slide) \
        .reduce_on_edges(JaxEdgesReduce(name="sum"))
    assert run_and_sort(env2, dev) == want
    assert len(want) > 0


def test_sliding_fold_and_apply_host_device_parity(env):
    """fold (arrival-order, no pane shortcut) and apply (whole
    neighborhoods) run sliding via per-window assignment on both
    paths; host and device forms must agree."""
    import jax.numpy as jnp

    from gelly_streaming_tpu import (EdgesApply, EdgesFold, JaxEdgesApply,
                                     JaxEdgesFold)

    size, slide = Time.milliseconds_of(200), Time.milliseconds_of(100)

    host_fold = _graph(env).slice(size, EdgeDirection.OUT, slide=slide) \
        .fold_neighbors((0, 0),
                        EdgesFold(lambda acc, vid, nid, val:
                                  (vid, acc[1] + val)))
    want = run_and_sort(env, host_fold)
    assert want == SLIDING_SUM

    env2 = type(env)(clock=env.clock)
    dev_fold = _graph(env2).slice(size, EdgeDirection.OUT, slide=slide) \
        .fold_neighbors(JaxEdgesFold(
            init=(jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)),
            fn=lambda acc, vid, nid, val: (vid, acc[1] + val)))
    assert run_and_sort(env2, dev_fold) == want

    def big_small(vid, nbrs, collect):
        total = sum(v for _n, v in nbrs)
        collect((vid, "big" if total > 300 else "small"))

    env3 = type(env)(clock=env.clock)
    host_apply = _graph(env3).slice(size, EdgeDirection.OUT, slide=slide) \
        .apply_on_neighbors(EdgesApply(big_small))
    want_a = run_and_sort(env3, host_apply)
    assert len(want_a) == len(SLIDING_SUM)

    env4 = type(env)(clock=env.clock)
    dev_apply = _graph(env4).slice(size, EdgeDirection.OUT, slide=slide) \
        .apply_on_neighbors(JaxEdgesApply(
            fn=lambda vid, nbrs, vals, mask: jnp.sum(
                jnp.where(mask, vals, 0)),
            emit=lambda vid, row: (vid,
                                   "big" if row[0] > 300 else "small")))
    assert run_and_sort(env4, dev_apply) == want_a


def test_sliding_keyed_window_fold(env):
    """Keyed DataStream.time_window(size, slide) — the generic keyed
    sliding fold (reference substrate: KeyedStream.timeWindow)."""
    edges = _graph(env).get_edges()
    out = edges.key_by(selector=lambda e: e.source) \
        .time_window(Time.milliseconds_of(200), Time.milliseconds_of(100)) \
        .fold((0, 0), lambda acc, e: (e.source, acc[1] + e.value))
    assert run_and_sort(env, out) == SLIDING_SUM


def test_sliding_window_all_sum(env):
    """Non-keyed sliding global sum (time_window_all(size, slide))."""
    vals = _graph(env).get_edges().map(lambda e: (e.value,))
    out = vals.time_window_all(Time.milliseconds_of(200),
                               Time.milliseconds_of(100)).sum(0)
    # windows: [0,200)=250, [100,300)=500, [200,400)=600, [300,500)=350
    assert run_and_sort(env, out) == sorted(["250", "500", "600", "350"])


def test_sliding_associative_reduce_takes_pane_path(env):
    """fn + associative=True gets the pane path too (not just named
    monoids): golden values on the 4-edge fixture."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops.neighborhood import make_reduce_kernel

    udf = JaxEdgesReduce(fn=lambda a, b: jnp.maximum(a, b),
                         associative=True)
    assert hasattr(make_reduce_kernel(udf), "pane_kernel")

    out = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(udf)
    assert run_and_sort(env, out) == SLIDING_MAX


@pytest.mark.parametrize("direction", [EdgeDirection.OUT,
                                       EdgeDirection.IN,
                                       EdgeDirection.ALL])
def test_sliding_random_parity_host_vs_assoc_pane(env, direction):
    """Random ragged stream with gaps: the associative-fn pane path ==
    host reference semantics, all directions (the analog of
    test_sliding_random_parity_host_vs_pane for the fn tier; gcd is
    associative+commutative but NOT a named monoid)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(13)
    edges = []
    t = 0
    for _ in range(200):
        t += int(rng.integers(1, 120))
        edges.append(Edge(int(rng.integers(0, 12)),
                          int(rng.integers(0, 12)), t))
    size, slide = Time.milliseconds_of(400), Time.milliseconds_of(100)

    import math

    host = _graph(env, edges).slice(size, direction, slide=slide) \
        .reduce_on_edges(EdgesReduce(lambda a, b: math.gcd(a, b)))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2, edges).slice(size, direction, slide=slide) \
        .reduce_on_edges(JaxEdgesReduce(fn=jnp.gcd, associative=True))
    assert run_and_sort(env2, dev) == want
    assert len(want) > 0


def test_sliding_assoc_pane_fallback_matches(env, monkeypatch):
    """Over the pane-cell limit the associative pane kernel falls back
    to per-window device calls — same results."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import neighborhood

    monkeypatch.setattr(neighborhood, "_PANE_CELL_LIMIT", 1)
    out = _graph(env).slice(
        Time.milliseconds_of(200), EdgeDirection.OUT,
        slide=Time.milliseconds_of(100),
    ).reduce_on_edges(JaxEdgesReduce(fn=lambda a, b: jnp.maximum(a, b),
                                     associative=True))
    assert run_and_sort(env, out) == SLIDING_MAX

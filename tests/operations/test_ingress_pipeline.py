"""The three-stage host-ingress pipeline (ops/ingress_pipeline):
pipeline-vs-sync parity for EVERY kernel routed through it, worker-pool
determinism (same results at pool sizes 1/2/4), per-stage timers, prep
error propagation with the worker traceback preserved, and the
parallel interning scheme's exact slot parity — the parametrized
extension of test_iter_edge_chunks_prefetch_matches_sync to the whole
ingress layer."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.ops import ingress_pipeline as ip


@pytest.fixture
def pool_env(monkeypatch):
    """Set the pool width for a test and always restore + rebuild."""

    def set_workers(n):
        monkeypatch.setenv("GS_PIPELINE_WORKERS", str(n))
        ip.reset_pool()

    yield set_workers
    monkeypatch.delenv("GS_PIPELINE_WORKERS", raising=False)
    ip.reset_pool()


def _stream(n, v, seed=11):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, n).astype(np.int32)
    dst = rng.integers(0, v, n).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep]


# ----------------------------------------------------------------------
# run_pipeline unit contract
# ----------------------------------------------------------------------

def test_run_pipeline_orders_and_lags_finalize():
    """Finalize sees chunks in order and lags dispatch by exactly one;
    per-stage timers count every chunk once."""
    events = []
    timers = ip.StageTimers()

    ip.run_pipeline(
        range(5),
        prep=lambda i: ("prep", i),
        h2d=lambda p: ("dev", p[1]),
        dispatch=lambda d: (events.append(("dispatch", d[1]))
                            or ("raw", d[1])),
        finalize=lambda r: events.append(("finalize", r[1])),
        timers=timers)

    assert [e for e in events if e[0] == "finalize"] == [
        ("finalize", i) for i in range(5)]
    d_at = [i for i, e in enumerate(events) if e[0] == "dispatch"]
    f_at = [i for i, e in enumerate(events) if e[0] == "finalize"]
    # chunk i finalizes AFTER chunk i+1 dispatches (depth-2), except
    # the last, which flushes at the end
    for i in range(4):
        assert f_at[i] > d_at[i + 1]
    assert timers.chunks == 5
    snap = timers.snapshot()
    assert set(snap) == {"chunks", "prep_ms_per_chunk",
                         "h2d_ms_per_chunk", "compute_ms_per_chunk"}


def test_run_pipeline_prep_error_carries_worker_traceback():
    """A prep failure surfaces as PrepError (a RuntimeError) whose
    message contains the WORKER'S formatted traceback — the frames
    where prep actually died, not just the consumer-side re-raise —
    with the original exception chained as __cause__."""

    def bad_prep(i):
        if i == 2:
            raise ValueError("prep exploded here")
        return i

    with pytest.raises(RuntimeError) as ei:
        ip.run_pipeline(range(4), bad_prep, lambda p: p, lambda d: d,
                        lambda r: None)
    assert isinstance(ei.value, ip.PrepError)
    msg = str(ei.value)
    assert "prep exploded here" in msg
    assert "bad_prep" in msg          # the worker-side frame
    assert "Traceback" in msg
    assert isinstance(ei.value.__cause__, ValueError)


def test_run_pipeline_sync_and_parallel_identical(pool_env):
    """Same finalize stream at every pool size and in forced_sync."""

    def run():
        out = []
        ip.run_pipeline(range(7),
                        prep=lambda i: i * 10,
                        h2d=lambda p: p + 1,
                        dispatch=lambda d: d * 2,
                        finalize=out.append)
        return out

    with ip.forced_sync():
        want = run()
    for w in (1, 2, 4):
        pool_env(w)
        assert run() == want


def test_run_pipeline_inflight_cap_and_interrupts(pool_env,
                                                  monkeypatch):
    """GS_PIPELINE_INFLIGHT bounds look-ahead without changing
    results, and a KeyboardInterrupt in prep aborts UNWRAPPED (never
    converted into a PrepError a broad fallback would eat)."""
    pool_env(4)
    monkeypatch.setenv("GS_PIPELINE_INFLIGHT", "1")
    out = []
    ip.run_pipeline(range(6), lambda i: i, lambda p: p,
                    lambda d: d, out.append)
    assert out == list(range(6))
    monkeypatch.delenv("GS_PIPELINE_INFLIGHT")

    def interrupt(i):
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        with ip.forced_sync():
            ip.run_pipeline(range(2), interrupt, lambda p: p,
                            lambda d: d, lambda r: None)


def test_map_ordered_preserves_order_and_errors(pool_env):
    pool_env(4)
    assert ip.map_ordered(lambda x: x * x, range(20)) == [
        x * x for x in range(20)]
    with pytest.raises(ip.PrepError, match="boom"):
        ip.map_ordered(
            lambda x: (_ for _ in ()).throw(RuntimeError("boom")),
            range(3))


# ----------------------------------------------------------------------
# pipeline-vs-sync parity for every routed kernel (the parametrized
# extension of test_iter_edge_chunks_prefetch_matches_sync)
# ----------------------------------------------------------------------

def _triangle_counts(ingress, src, dst):
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    kern = TriangleWindowKernel(edge_bucket=256, vertex_bucket=256,
                                ingress=ingress)
    kern.MAX_STREAM_WINDOWS = 3   # several chunks + a ragged tail
    return kern._count_stream_device(src, dst)


def _reduce_cells(ingress, src, dst):
    from gelly_streaming_tpu.ops.windowed_reduce import WindowedEdgeReduce

    val = (1 + (src.astype(np.int64) + 3 * dst) % 97).astype(np.int32)
    eng = WindowedEdgeReduce(vertex_bucket=256, edge_bucket=256,
                             name="sum", direction="all",
                             ingress=ingress)
    eng.MAX_STREAM_WINDOWS = 3
    out = eng._device_process_stream(src.astype(np.int64),
                                     dst.astype(np.int64), val)
    return [(c.tolist(), k.tolist()) for c, k in out]


def _fused_summaries(ingress, src, dst):
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine

    eng = StreamSummaryEngine(edge_bucket=256, vertex_bucket=256,
                              ingress=ingress)
    eng.MAX_WINDOWS = 3
    return eng.process(src, dst)


def _driver_results(_ingress, src, dst):
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=256,
                                   vertex_bucket=256)
    drv._SCAN_CHUNK = 3
    out = []
    for res in drv.run_arrays(src.astype(np.int64),
                              dst.astype(np.int64)):
        out.append((res.window_start, res.num_edges,
                    res.vertex_ids.tolist(), res.degrees.tolist(),
                    res.cc_labels.tolist(),
                    np.asarray(res.bipartite_odd).tolist(),
                    res.triangles))
    return out


ENGINES = [
    ("triangles-standard", _triangle_counts, "standard"),
    ("triangles-compact", _triangle_counts, "compact"),
    ("reduce-standard", _reduce_cells, "standard"),
    ("reduce-compact", _reduce_cells, "compact"),
    ("fused-standard", _fused_summaries, "standard"),
    ("fused-compact", _fused_summaries, "compact"),
    ("driver", _driver_results, None),
]


@pytest.mark.parametrize("name,fn,ingress",
                         ENGINES, ids=[e[0] for e in ENGINES])
def test_pipeline_matches_sync_every_engine(name, fn, ingress,
                                            pool_env):
    """Every kernel routed through the ingress pipeline produces
    byte-identical results with the pipeline on (several pool sizes)
    and forced synchronous — the worker-pool determinism contract."""
    src, dst = _stream(10 * 256 + 96, 256, seed=23)
    with ip.forced_sync():
        want = fn(ingress, src, dst)
    assert want  # the stream produces real windows
    for workers in (1, 2, 4):
        pool_env(workers)
        assert fn(ingress, src, dst) == want, \
            "%s diverged at %d workers" % (name, workers)


def test_host_and_native_tiers_parallel_parity(pool_env):
    """The CPU-fallback tiers (numpy + native C++) count identical
    windows through the pool and sequentially."""
    from gelly_streaming_tpu.ops import host_triangles
    from gelly_streaming_tpu.ops.triangles import (
        _native_count_stream_parallel)

    from gelly_streaming_tpu import native

    src, dst = _stream(9 * 128 + 50, 200, seed=5)
    with ip.forced_sync():
        want = host_triangles.count_stream(src, dst, 128)
    for workers in (1, 2, 4):
        pool_env(workers)
        assert host_triangles.count_stream(src, dst, 128) == want
        if native.triangles_available():
            assert _native_count_stream_parallel(src, dst, 128) == want


def test_stage_timers_populated_by_stream_run():
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    kern = TriangleWindowKernel(edge_bucket=128, vertex_bucket=128)
    kern.MAX_STREAM_WINDOWS = 2
    src, dst = _stream(8 * 128, 128, seed=9)
    kern._count_stream_device(src, dst)
    snap = kern.stage_timers.snapshot()
    assert snap["chunks"] >= 4
    assert snap["compute_ms_per_chunk"] > 0


def test_parallel_intern_accepts_unorderable_hashables(pool_env):
    """Arbitrary-hashable (unorderable) id streams — the Python
    interner's contract — must still intern with the pool enabled:
    the parallel uniques scheme needs orderable elements, so object
    arrays take the sequential loop instead of crashing in
    np.unique's sort."""
    from gelly_streaming_tpu.utils.interning import (
        IncrementalInterner, parallel_intern_arrays)

    pool_env(4)
    mixed = [np.array([(1, 2), 7, "x", 7, (1, 2)], dtype=object),
             np.array(["x", (3,), 7], dtype=object)]
    seq = IncrementalInterner()
    want = [seq.intern_array(a).tolist() for a in mixed]
    par = IncrementalInterner()
    dense, sizes = parallel_intern_arrays(par, mixed)
    assert [d.tolist() for d in dense] == want
    assert sizes[-1] == len(seq)


def test_compact_fused_engine_rejects_wrapping_ids():
    """Ids the uint16 cast would wrap must raise loudly through the
    fused engine's compact path (same contract as the windowed-reduce
    compact prep), never silently corrupt another vertex's carried
    state."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eng = StreamSummaryEngine(edge_bucket=64, vertex_bucket=65536,
                              ingress="compact")
    with pytest.raises(ValueError, match="outside \\[0"):
        eng.process(np.array([70000], np.int64),
                    np.array([1], np.int64))


def test_parallel_intern_matches_sequential(pool_env):
    """parallel_intern_arrays assigns EXACTLY the slots the sequential
    loop would, at every pool size (first-occurrence order preserved
    through the uniques scheme)."""
    from gelly_streaming_tpu.utils.interning import (
        IncrementalInterner, parallel_intern_arrays)

    rng = np.random.default_rng(3)
    arrays = [rng.integers(0, 500, rng.integers(0, 400))
              for _ in range(9)]
    seq = IncrementalInterner()
    want = []
    sizes_want = []
    for a in arrays:
        want.append(seq.intern_array(a).tolist())
        sizes_want.append(len(seq))
    for workers in (1, 2, 4):
        pool_env(workers)
        par = IncrementalInterner()
        dense, sizes = parallel_intern_arrays(par, arrays)
        assert [d.tolist() for d in dense] == want
        assert sizes == sizes_want
        assert par.ids_of(np.arange(len(par))) == seq.ids_of(
            np.arange(len(seq)))

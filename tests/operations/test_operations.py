"""Stream transformation parity tests.

Golden outputs from the reference's operation ITs
(test/operations/*.java — SURVEY.md §4): creation, getVertices,
getDegrees ×3, numberOfVertices/Edges, mapEdges (incl. type-changing and
chained), filterEdges/filterVertices (simple/keep-all/discard-all),
distinct, reverse, undirected, union.
"""

from gelly_streaming_tpu import SimpleEdgeStream

from ..conftest import long_long_edges, run_and_sort


def _graph(env):
    return SimpleEdgeStream(env.from_collection(long_long_edges()), env)


def test_graph_stream_creation(env):
    # reference: TestGraphStreamCreation.java:60-66
    assert run_and_sort(env, _graph(env).get_edges()) == sorted(
        ["1,2,12", "1,3,13", "2,3,23", "3,4,34", "3,5,35", "4,5,45", "5,1,51"]
    )


def test_get_vertices(env):
    # reference: TestGetVertices.java:61-65
    assert run_and_sort(env, _graph(env).get_vertices()) == sorted(
        ["1,(null)", "2,(null)", "3,(null)", "4,(null)", "5,(null)"]
    )


def test_get_degrees(env):
    # reference: TestGetDegrees.java:68-81
    assert run_and_sort(env, _graph(env).get_degrees()) == sorted(
        ["1,1", "1,2", "1,3", "2,1", "2,2", "3,1", "3,2", "3,3", "3,4",
         "4,1", "4,2", "5,1", "5,2", "5,3"]
    )


def test_get_in_degrees(env):
    # reference: TestGetDegrees.java:94-100
    assert run_and_sort(env, _graph(env).get_in_degrees()) == sorted(
        ["1,1", "2,1", "3,1", "3,2", "4,1", "5,1", "5,2"]
    )


def test_get_out_degrees(env):
    # reference: TestGetDegrees.java:113-119
    assert run_and_sort(env, _graph(env).get_out_degrees()) == sorted(
        ["1,1", "1,2", "2,1", "3,1", "3,2", "4,1", "5,1"]
    )


def test_number_of_vertices(env):
    # reference: TestNumberOfEntities.java:73-77
    assert run_and_sort(env, _graph(env).number_of_vertices()) == sorted(
        ["1", "2", "3", "4", "5"]
    )


def test_number_of_edges(env):
    # reference: TestNumberOfEntities.java:96-102
    assert run_and_sort(env, _graph(env).number_of_edges()) == sorted(
        ["1", "2", "3", "4", "5", "6", "7"]
    )


def test_map_edges(env):
    # reference: TestMapEdges.java:71-77 (add one to each value)
    mapped = _graph(env).map_edges(lambda e: e.value + 1)
    assert run_and_sort(env, mapped.get_edges()) == sorted(
        ["1,2,13", "1,3,14", "2,3,24", "3,4,35", "3,5,36", "4,5,46", "5,1,52"]
    )


def test_map_edges_to_tuple_type(env):
    # reference: TestMapEdges.java:99-105 (value type Long → Tuple2)
    mapped = _graph(env).map_edges(lambda e: (e.value, e.value + 1))
    assert run_and_sort(env, mapped.get_edges()) == sorted(
        ["1,2,(12,13)", "1,3,(13,14)", "2,3,(23,24)", "3,4,(34,35)",
         "3,5,(35,36)", "4,5,(45,46)", "5,1,(51,52)"]
    )


def test_chained_maps(env):
    # reference: TestMapEdges.java:129-135
    mapped = _graph(env).map_edges(lambda e: e.value + 1).map_edges(
        lambda e: (e.value, e.value + 1)
    )
    assert run_and_sort(env, mapped.get_edges()) == sorted(
        ["1,2,(13,14)", "1,3,(14,15)", "2,3,(24,25)", "3,4,(35,36)",
         "3,5,(36,37)", "4,5,(46,47)", "5,1,(52,53)"]
    )


def test_filter_edges(env):
    # reference: TestFilterEdges.java:70-74 (value > 20)
    filtered = _graph(env).filter_edges(lambda e: e.value > 20)
    assert run_and_sort(env, filtered.get_edges()) == sorted(
        ["2,3,23", "3,4,34", "3,5,35", "4,5,45", "5,1,51"]
    )


def test_filter_edges_keep_all(env):
    # reference: TestFilterEdges.java:99-105
    filtered = _graph(env).filter_edges(lambda e: True)
    assert len(run_and_sort(env, filtered.get_edges())) == 7


def test_filter_edges_discard_all(env):
    # reference: TestFilterEdges.java:128
    filtered = _graph(env).filter_edges(lambda e: False)
    assert run_and_sort(env, filtered.get_edges()) == []


def test_filter_vertices(env):
    # reference: TestFilterVertices.java:70-73 (id > 1 on both endpoints)
    filtered = _graph(env).filter_vertices(lambda v: v.id > 1)
    assert run_and_sort(env, filtered.get_edges()) == sorted(
        ["2,3,23", "3,4,34", "3,5,35", "4,5,45"]
    )


def test_filter_vertices_keep_all(env):
    filtered = _graph(env).filter_vertices(lambda v: True)
    assert len(run_and_sort(env, filtered.get_edges())) == 7


def test_filter_vertices_discard_all(env):
    filtered = _graph(env).filter_vertices(lambda v: False)
    assert run_and_sort(env, filtered.get_edges()) == []


def test_distinct(env):
    # reference: TestDistinct.java:69-75 (doubled edge list deduped)
    doubled = long_long_edges() + long_long_edges()
    stream = SimpleEdgeStream(env.from_collection(doubled), env).distinct()
    assert run_and_sort(env, stream.get_edges()) == sorted(
        ["1,2,12", "1,3,13", "2,3,23", "3,4,34", "3,5,35", "4,5,45", "5,1,51"]
    )


def test_reverse(env):
    # reference: TestReverse.java:62-68
    assert run_and_sort(env, _graph(env).reverse().get_edges()) == sorted(
        ["2,1,12", "3,1,13", "3,2,23", "4,3,34", "5,3,35", "5,4,45", "1,5,51"]
    )


def test_undirected(env):
    # reference: TestUndirected.java:62-75
    assert run_and_sort(env, _graph(env).undirected().get_edges()) == sorted(
        ["1,2,12", "2,1,12", "1,3,13", "3,1,13", "2,3,23", "3,2,23",
         "3,4,34", "4,3,34", "3,5,35", "5,3,35", "4,5,45", "5,4,45",
         "5,1,51", "1,5,51"]
    )


def test_union(env):
    # reference: TestUnion.java:80-86 (split then union restores the graph)
    edges = long_long_edges()
    first = SimpleEdgeStream(env.from_collection(edges[:4]), env)
    second = SimpleEdgeStream(env.from_collection(edges[4:]), env)
    assert run_and_sort(env, first.union(second).get_edges()) == sorted(
        ["1,2,12", "1,3,13", "2,3,23", "3,4,34", "3,5,35", "4,5,45", "5,1,51"]
    )


def test_public_aggregate(env):
    # reference: SimpleEdgeStream.java:493-498 — the generic
    # flatMap -> keyBy(0) -> stateful map composition, here computing a
    # running sum of edge values per source vertex
    from gelly_streaming_tpu import Vertex

    def edge_value_per_source(edge, collect):
        collect(Vertex(edge.source, edge.value))

    sums = {}

    def running_sum(vertex):
        sums[vertex.id] = sums.get(vertex.id, 0) + vertex.value
        return Vertex(vertex.id, sums[vertex.id])

    out = _graph(env).aggregate(edge_value_per_source, running_sum)
    assert run_and_sort(env, out) == sorted(
        ["1,12", "1,25", "2,23", "3,34", "3,69", "4,45", "5,51"]
    )

"""Randomized host/device equivalence across the windowed neighborhood
surface: every op x direction on random multi-window event-time streams
must produce identical sorted output on the host (reference-semantics)
and device (segment-kernel) paths. Complements the golden tests
(test_slice.py pins the reference's exact tables; this pins the two
implementations to EACH OTHER over a much larger input space).
"""

import numpy as np
import pytest

from gelly_streaming_tpu import (AscendingTimestampExtractor, Edge,
                                 EdgeDirection, EdgesApply, EdgesFold,
                                 EdgesReduce, JaxEdgesApply, JaxEdgesFold,
                                 JaxEdgesReduce, SimpleEdgeStream, Time)

from ..conftest import run_and_sort

DIRECTIONS = [EdgeDirection.OUT, EdgeDirection.IN, EdgeDirection.ALL]


def _random_edges(seed: int, n: int = 400, v: int = 24):
    rng = np.random.default_rng(seed)
    ts = np.sort(rng.integers(0, 5_000, n))
    src = rng.integers(0, v, n)
    dst = (src + 1 + rng.integers(0, v - 1, n)) % v  # no self-loops
    val = rng.integers(1, 100, n)
    return [Edge(int(s), int(d), (int(x) << 13) + int(t))
            for s, d, x, t in zip(src, dst, val, ts)]


def _graph(env, edges):
    # value packs (weight << 13) + ts so the extractor sees ascending
    # event times while weights stay deterministic per edge
    return SimpleEdgeStream(
        env.from_collection(edges), env,
        timestamp_extractor=AscendingTimestampExtractor(
            lambda e: e.value & 0x1FFF))


@pytest.mark.parametrize("direction", DIRECTIONS)
@pytest.mark.parametrize("seed", [0, 1])
def test_reduce_tiers_agree(env, direction, seed):
    """Named monoid, associative-scan, and arrival-order device tiers
    all equal the host reference on random streams."""
    edges = _random_edges(seed)
    size = Time.milliseconds_of(700)

    host = _graph(env, edges).slice(size, direction).reduce_on_edges(
        EdgesReduce(lambda a, b: a + b))
    want = run_and_sort(env, host)
    assert len(want) > 10

    for udf in (JaxEdgesReduce(name="sum"),
                JaxEdgesReduce(fn=lambda a, b: a + b, associative=True),
                JaxEdgesReduce(fn=lambda a, b: a + b)):
        env2 = type(env)(clock=env.clock)
        dev = _graph(env2, edges).slice(size, direction).reduce_on_edges(udf)
        assert run_and_sort(env2, dev) == want


@pytest.mark.parametrize("direction", DIRECTIONS)
@pytest.mark.parametrize("seed", [0, 1])
def test_fold_agrees(env, direction, seed):
    """Arrival-order device fold == host fold (non-commutative
    accumulator: order matters and must match exactly)."""
    import jax.numpy as jnp

    edges = _random_edges(seed)
    size = Time.milliseconds_of(700)

    host = _graph(env, edges).slice(size, direction).fold_neighbors(
        (0, 0), EdgesFold(lambda acc, vid, nid, val:
                          (vid, 31 * acc[1] % 1013 + val)))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2, edges).slice(size, direction).fold_neighbors(
        JaxEdgesFold(
            init=(jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)),
            fn=lambda acc, vid, nid, val:
                (vid, 31 * acc[1] % 1013 + val)))
    assert run_and_sort(env2, dev) == want


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_apply_agrees(env, direction):
    """Whole-neighborhood apply: device padded-CSR path == host
    buffered path (order-insensitive aggregate)."""
    import jax.numpy as jnp

    edges = _random_edges(7)
    size = Time.milliseconds_of(700)

    def host_fn(vid, nbrs, collect):
        total = sum(v for _n, v in nbrs)
        mx = max(v for _n, v in nbrs)
        collect((vid, total, mx))

    host = _graph(env, edges).slice(size, direction).apply_on_neighbors(
        EdgesApply(host_fn))
    want = run_and_sort(env, host)

    env2 = type(env)(clock=env.clock)
    dev = _graph(env2, edges).slice(size, direction).apply_on_neighbors(
        JaxEdgesApply(
            fn=lambda vid, nbrs, vals, mask: (
                jnp.sum(jnp.where(mask, vals, 0)),
                jnp.max(jnp.where(mask, vals, jnp.iinfo(jnp.int32).min))),
            emit=lambda vid, row: (vid, int(row[0]), int(row[1]))))
    assert run_and_sort(env2, dev) == want

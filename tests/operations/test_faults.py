"""Deterministic fault-injection suite (utils/faults + the resilient
runtime): stage watchdogs, bounded retry, error-path drain, tier
demotion, and checkpoint damage — every failure path exercised with a
fixed plan, no randomness, CPU-only.

Real sleeps are bounded by sub-second watchdog deadlines; the one
deliberately long (10 s) injected stall is never WAITED on — the
watchdog cuts it at its 1 s deadline (the acceptance shape: a hung h2d
surfaces as a typed StageTimeout naming the chunk within ~2× the
deadline) and the sleeping helper thread is abandoned as a daemon.
"""

import os
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import ingress_pipeline as ip
from gelly_streaming_tpu.utils import checkpoint as ck
from gelly_streaming_tpu.utils import faults, resilience

pytestmark = pytest.mark.faults

_KNOBS = ("GS_STAGE_TIMEOUT_S", "GS_STAGE_RETRIES", "GS_STAGE_BACKOFF_S",
          "GS_TIER_RETRY_WINDOWS", "GS_TIER_DEMOTE")


@pytest.fixture(autouse=True)
def _clean_knobs():
    """Every test starts from inert knobs and leaves none behind; the
    pool is dropped afterwards so a worker a test deliberately hung
    never serves a later test."""
    saved = {k: os.environ.pop(k, None) for k in _KNOBS}
    os.environ["GS_STAGE_BACKOFF_S"] = "0.01"
    try:
        yield
    finally:
        for k in _KNOBS:
            os.environ.pop(k, None)
            if saved[k] is not None:
                os.environ[k] = saved[k]
        ip.reset_pool()


def _run(n_chunks=4, **kw):
    """Tiny run_pipeline harness: chunk i -> prep doubles, h2d +1,
    finalize collects. Returns the collected list."""
    out = []
    ip.run_pipeline(range(n_chunks), lambda i: i * 2, lambda p: p + 1,
                    lambda d: d, out.append, **kw)
    return out


# ----------------------------------------------------------------------
# watchdog + retry on the shared ingress pipeline
# ----------------------------------------------------------------------
def test_transient_prep_failure_retried():
    os.environ["GS_STAGE_RETRIES"] = "2"
    with faults.inject(faults.FaultSpec(site="prep", on_call=2)) as plan:
        assert _run() == [1, 3, 5, 7]
    assert ("prep", 2, "raise") in plan.fired


def test_transient_h2d_failure_retried_forced_sync():
    os.environ["GS_STAGE_RETRIES"] = "1"
    with ip.forced_sync():
        with faults.inject(faults.FaultSpec(site="h2d", on_call=3)):
            assert _run() == [1, 3, 5, 7]


def test_prep_failure_exhausts_retries_typed():
    os.environ["GS_STAGE_RETRIES"] = "1"
    with faults.inject(faults.FaultSpec(site="prep", on_call=2,
                                        times=99)):
        with pytest.raises(resilience.StageFailed) as ei:
            _run()
    err = ei.value
    assert err.stage == "prep" and err.chunk == 1
    assert len(err.attempts) == 2
    assert all(a["outcome"] == "PrepError" for a in err.attempts)


def test_hung_h2d_surfaces_typed_within_deadline():
    """The acceptance shape: a 10 s injected h2d stall under
    GS_STAGE_TIMEOUT_S=1 surfaces as StageTimeout NAMING the chunk
    within ~2× the deadline instead of blocking the stream for 10 s."""
    os.environ["GS_STAGE_TIMEOUT_S"] = "1"
    t0 = time.perf_counter()
    with faults.inject(faults.FaultSpec(site="h2d", on_call=2,
                                        action="hang", seconds=10.0)):
        with pytest.raises(resilience.StageTimeout) as ei:
            _run()
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, elapsed
    assert ei.value.stage == "h2d" and ei.value.chunk == 1
    assert ei.value.attempts[0]["outcome"] == "timeout"
    assert "chunk 1" in str(ei.value)


def test_hung_h2d_forced_sync_also_enforced():
    os.environ["GS_STAGE_TIMEOUT_S"] = "0.15"
    t0 = time.perf_counter()
    with ip.forced_sync():
        with faults.inject(faults.FaultSpec(site="h2d", on_call=1,
                                            action="hang", seconds=2.0)):
            with pytest.raises(resilience.StageTimeout) as ei:
                _run()
    assert time.perf_counter() - t0 < 1.0
    assert ei.value.stage == "h2d" and ei.value.chunk == 0


def test_hang_then_timeout_then_retry_succeeds():
    """A once-hung h2d is cut by the deadline and the retry (a fresh
    dedicated thread, the pool worker stays abandoned) completes the
    stream with identical results."""
    os.environ["GS_STAGE_TIMEOUT_S"] = "0.15"
    os.environ["GS_STAGE_RETRIES"] = "1"
    with faults.inject(faults.FaultSpec(site="h2d", on_call=2,
                                        action="hang", seconds=0.6)):
        assert _run() == [1, 3, 5, 7]


def test_queued_chunk_behind_wedged_pool_still_times_out():
    """A task no worker ever picks up must count its QUEUE wait
    against the deadline: with the pool's only worker wedged on an
    abandoned hang, the next chunk would otherwise spin forever in
    the consumer's poll loop (review finding on _await_attempt)."""
    saved = os.environ.get("GS_PIPELINE_WORKERS")
    os.environ["GS_PIPELINE_WORKERS"] = "1"
    ip.reset_pool()
    os.environ["GS_STAGE_TIMEOUT_S"] = "0.2"
    os.environ["GS_STAGE_RETRIES"] = "1"
    try:
        t0 = time.perf_counter()
        with faults.inject(faults.FaultSpec(site="h2d", on_call=1,
                                            action="hang",
                                            seconds=1.2)):
            # chunk 0's pooled h2d wedges the lone worker; its retry
            # runs on a dedicated thread, and every later chunk's
            # pooled attempt times out of the QUEUE and retries the
            # same way — the stream completes, bounded by deadlines
            assert _run() == [1, 3, 5, 7]
        assert time.perf_counter() - t0 < 3.0
    finally:
        if saved is None:
            os.environ.pop("GS_PIPELINE_WORKERS", None)
        else:
            os.environ["GS_PIPELINE_WORKERS"] = saved
        ip.reset_pool()


def test_dispatch_failure_typed_and_not_retried():
    os.environ["GS_STAGE_RETRIES"] = "3"
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=2,
                                        times=99)) as plan:
        with pytest.raises(resilience.StageFailed) as ei:
            _run()
    assert ei.value.stage == "dispatch"
    # dispatch folds into carried state in the real engines: exactly
    # one firing means exactly one attempt (never re-run)
    assert [f for f in plan.fired if f[0] == "dispatch"] \
        == [("dispatch", 2, "raise")]


def test_pipeline_drains_pending_on_failure():
    """Satellite: a mid-run failure no longer abandons the
    already-dispatched chunk — its finalize runs (best-effort) before
    the error surfaces, in both the pooled and sync forms."""
    for sync in (False, True):
        out = []
        ctx = ip.forced_sync() if sync else _null()
        with ctx:
            with faults.inject(faults.FaultSpec(site="prep", on_call=3)):
                with pytest.raises(ip.PrepError):
                    ip.run_pipeline(range(4), lambda i: i * 2,
                                    lambda p: p + 1, lambda d: d,
                                    out.append)
        # chunks 0 AND 1 finalized: 1 was in flight (dispatched, not
        # yet finalized) when chunk 2's prep died
        assert out == [1, 3], (sync, out)


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_fatal_fault_never_retried():
    """fatal=True is the chaos harness's simulated hard kill: it must
    pierce the retry budget and surface raw."""
    os.environ["GS_STAGE_RETRIES"] = "5"
    with faults.inject(faults.FaultSpec(site="finalize", on_call=1,
                                        fatal=True)) as plan:
        with pytest.raises(faults.InjectedFault):
            _run()
    assert [f for f in plan.fired if f[0] == "finalize"] \
        == [("finalize", 1, "raise")]


def test_stage_timers_reset_locked():
    """Satellite: reset() takes the accumulator lock (a concurrent
    add() can no longer interleave a partial erase)."""
    t = ip.StageTimers()
    t.add("prep", 0.5)
    t.reset()
    assert t.prep_ms == 0.0 and t.chunks == 0
    # the lock object is shared by add/reset — a reset inside an add's
    # critical section is impossible by construction
    assert t._lock is not None


# ----------------------------------------------------------------------
# parse-corruption robustness
# ----------------------------------------------------------------------
def test_corrupt_edge_line_dropped_without_misalignment(tmp_path):
    from gelly_streaming_tpu.io.sources import iter_edge_chunks

    p = tmp_path / "edges.txt"
    lines = [f"{i} {i + 1}" for i in range(100)]
    p.write_text("\n".join(lines) + "\n")
    with faults.inject(faults.FaultSpec(site="parse",
                                        action="corrupt_bytes")):
        chunks = list(iter_edge_chunks(str(p), chunk_bytes=1 << 20,
                                       prefetch=0))
    src = np.concatenate([c[0] for c in chunks])
    dst = np.concatenate([c[1] for c in chunks])
    # the torn first line is DROPPED, never misread: remaining pairs
    # stay aligned
    assert len(src) == 99
    assert src[0] == 1 and dst[0] == 2
    assert np.array_equal(dst, src + 1)


# ----------------------------------------------------------------------
# driver: demotion ladder + checkpoint damage
# ----------------------------------------------------------------------
def _stream(n=4096, v=512, seed=5):
    rng = np.random.default_rng(seed)
    return rng.integers(0, v, size=n), rng.integers(0, v, size=n)


def _snap_key(results):
    return [(r.window_start, r.num_edges,
             None if r.degrees is None else r.degrees.tolist(),
             None if r.cc_labels is None else r.cc_labels.tolist(),
             None if r.bipartite_odd is None
             else r.bipartite_odd.tolist(),
             None if r.delta_cc is None
             else [a.tolist() for a in r.delta_cc],
             None if r.delta_degrees is None
             else [a.tolist() for a in r.delta_degrees],
             None if r.delta_bipartite is None
             else [a.tolist() for a in r.delta_bipartite])
            for r in results]


def _driver(**kw):
    kw.setdefault("analytics", ("degrees", "cc", "bipartite"))
    return StreamingAnalyticsDriver(window_ms=0, edge_bucket=512,
                                    vertex_bucket=1024,
                                    emit_deltas=True, **kw)


def test_mid_stream_demotion_preserves_state_bit_exactly():
    """Acceptance: a persistent device failure mid-stream demotes
    scan→native, carrying degrees/cc/bipartite (and the delta streams)
    bit-exactly, and the demotion lands in the tracing layer and the
    process registry."""
    src, dst = _stream()
    ref = _driver(snapshot_tier="scan")
    want = _snap_key(ref.run_arrays(src, dst))

    resilience.reset_demotions()
    drv = _driver(snapshot_tier="scan", tracing=True)
    half = len(src) // 2
    got = drv.run_arrays(src[:half], dst[:half])
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        got += drv.run_arrays(src[half:], dst[half:])
    assert _snap_key(got) == want
    (event,) = drv.demotion_log()
    assert event["from"] == "scan" and event["to"] == "native"
    assert any(e["event"] == "tier_demotion"
               for e in drv.timer.event_log())
    assert any(e["to"] == "native"
               for e in resilience.demotion_events())


def test_demotion_ladder_falls_through_to_host():
    """Two persistent failures walk the whole ladder: the host-numpy
    tier finishes the stream with identical counts."""
    src, dst = _stream()
    want = _snap_key(_driver(snapshot_tier="scan").run_arrays(src, dst))
    drv = _driver(snapshot_tier="scan")
    # the fold sites of scan AND native both fail once: scan→native,
    # native→host, host completes
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1,
                                        times=2)):
        got = drv.run_arrays(src, dst)
    assert _snap_key(got) == want
    tiers = [(e["from"], e["to"]) for e in drv.demotion_log()]
    assert tiers == [("scan", "native"), ("native", "host")]


def test_demotion_disabled_raises_typed():
    os.environ["GS_TIER_DEMOTE"] = "0"
    src, dst = _stream()
    drv = _driver(snapshot_tier="scan")
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        with pytest.raises(resilience.StageFailed):
            drv.run_arrays(src, dst)


def test_semantic_errors_never_demote():
    """A programming bug (non-runtime error) must surface, not be
    'cured' by silently falling off the fast tier."""
    src, dst = _stream()
    drv = _driver(snapshot_tier="scan")
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1,
                                        exc=TypeError)):
        with pytest.raises(resilience.StageFailed) as ei:
            drv.run_arrays(src, dst)
    assert isinstance(ei.value.__cause__, TypeError)
    assert drv.demotion_log() == []


def test_probation_repromotion():
    os.environ["GS_TIER_RETRY_WINDOWS"] = "4"
    src, dst = _stream()
    want = _snap_key(_driver(snapshot_tier="scan").run_arrays(src, dst))
    drv = _driver(snapshot_tier="scan")
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        got = drv.run_arrays(src, dst)  # demotes at window 0
    assert _snap_key(got) == want
    assert drv._demoted_tier == "native"
    # probation served during those 8 windows: the next call probes
    # the scan tier again and stays there
    got2 = drv.run_arrays(src, dst)
    assert drv._demoted_tier is None
    events = [e for e in drv.demotion_log()]
    assert events[-1]["to"] == "scan"  # the re-promotion probe


def test_retry_cures_transient_device_failure_without_demotion():
    os.environ["GS_STAGE_RETRIES"] = "1"
    src, dst = _stream()
    want = _snap_key(_driver(snapshot_tier="scan").run_arrays(src, dst))
    drv = _driver(snapshot_tier="scan")
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        got = drv.run_arrays(src, dst)
    assert _snap_key(got) == want
    assert drv.demotion_log() == []  # the retry absorbed it


def test_driver_prefetch_prep_failure_retried():
    """A transient prep failure in the snapshot-scan PREFETCH worker
    gets the guard's retry budget like every other prep consumer
    (review finding: only _FutureTimeout was caught)."""
    os.environ["GS_STAGE_RETRIES"] = "1"
    rng = np.random.default_rng(7)
    w, eb = 66, 128  # two scan chunks (64 + 2): chunk 2 is prefetched
    src = rng.integers(0, 300, size=w * eb)
    dst = rng.integers(0, 300, size=w * eb)

    def run(plan_specs):
        drv = StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=512,
            analytics=("degrees", "cc", "bipartite"))
        with faults.inject(*plan_specs) as plan:
            out = drv.run_arrays(src, dst)
        return out, plan

    want, _ = run([])
    # prep-site call accounting: parallel interning fires once per
    # window array (2·w), then the first prefetch (chunk 2's stack
    # build) is the next firing
    got, plan = run([faults.FaultSpec(site="prep", on_call=2 * w + 1)])
    assert ("prep", 2 * w + 1, "raise") in plan.fired
    assert [(r.window_start, r.degrees.tolist(), r.cc_labels.tolist())
            for r in got] \
        == [(r.window_start, r.degrees.tolist(), r.cc_labels.tolist())
            for r in want]


def test_engine_reset_reanchors_checkpoint_cadence(tmp_path):
    """reset() must re-anchor the surviving CheckpointPolicy: a stale
    high-water mark silently disabled checkpointing for the next
    stream (review finding)."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb, vb = 64, 128
    rng = np.random.default_rng(3)
    s = rng.integers(0, vb, size=4 * eb).astype(np.int32)
    d = rng.integers(0, vb, size=4 * eb).astype(np.int32)
    path = str(tmp_path / "e.npz")
    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    eng.enable_auto_checkpoint(path, every_n_windows=2)
    eng.process(s, d)  # marks the policy at window 4
    os.unlink(path)
    eng.reset()
    eng.process(s[:2 * eb], d[:2 * eb])
    assert os.path.exists(path)  # due at window 2 of the NEW stream


def test_checkpoint_truncation_falls_back_to_rotation(tmp_path):
    """Satellite: external damage to the newest checkpoint generation
    resumes from the rotated previous one with a warning — and only
    when EVERY generation is damaged does resume start fresh."""
    src, dst = _stream()
    ckpt = str(tmp_path / "drv.npz")
    a = _driver()
    a.enable_auto_checkpoint(ckpt, every_n_windows=2)
    half = len(src) // 2
    a.run_arrays(src[:half], dst[:half])  # two calls: two checkpoint
    a.run_arrays(src[half:], dst[half:])  # generations (rotation)
    assert os.path.exists(ckpt) and os.path.exists(ck.prev_path(ckpt))

    with faults.inject(faults.FaultSpec(site="ckpt_save",
                                        action="truncate_file")):
        ck.save(ckpt, a.state_dict())  # newest generation now damaged
    b = _driver()
    with pytest.warns(UserWarning, match="rotated previous"):
        assert b.try_resume(ckpt)
    assert 0 < b.windows_done <= a.windows_done

    # damage the rotation too: resume refuses politely
    with open(ck.prev_path(ckpt), "r+b") as f:
        f.truncate(8)
    c = _driver()
    with pytest.warns(UserWarning, match="starting fresh"):
        assert not c.try_resume(ckpt)


def test_checkpoint_policy_every_seconds_fake_clock(tmp_path):
    clock = [0.0]
    pol = ck.CheckpointPolicy(every_seconds=30.0, clock=lambda: clock[0])
    src, dst = _stream()
    drv = _driver()
    drv.enable_auto_checkpoint(str(tmp_path / "t.npz"), policy=pol)
    drv.run_arrays(src[:2048], dst[:2048])
    assert not os.path.exists(str(tmp_path / "t.npz"))  # clock frozen
    clock[0] = 31.0
    drv.run_arrays(src[2048:], dst[2048:])
    assert os.path.exists(str(tmp_path / "t.npz"))
    e = _driver()
    assert e.try_resume(str(tmp_path / "t.npz"))
    assert e.windows_done > 0

"""Online dispatch autotuner (ops/autotune.py): deterministic
decisions, hysteresis, cache/checkpoint round-trips, and the hard
contract that GS_AUTOTUNE=0 — and the tuner being ON — never changes
results, only dispatch economics."""

import hashlib
import json
import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import autotune
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    """Every test gets a private tuning cache (and leaves the
    process-wide env untouched)."""
    monkeypatch.setenv("GS_TUNE_CACHE", str(tmp_path / "tune"))
    yield


def _stream(n, vmax, seed=3):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, vmax, n).astype(np.int64)
    dst = (src + 1 + rng.integers(0, vmax - 1, n)) % vmax
    return src, dst.astype(np.int64)


# ----------------------------------------------------------------------
# the tuner object
# ----------------------------------------------------------------------
def _tuner(**kw):
    kw.setdefault("key", "t:eb=8:vb=8")
    kw.setdefault("space", {"wb": [2, 4, 8]})
    kw.setdefault("initial", {"wb": 8})
    return autotune.DispatchTuner(**kw)


def test_exploit_by_default_explore_on_cadence(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "3")
    t = _tuner()
    seen = []
    for _ in range(6):
        arm = t.next_round()
        seen.append(arm["wb"])
        t.record(arm, 1000, 1.0)
    # rounds 3 and 6 explore (cadence 3), the rest exploit the
    # incumbent — and with flat rates nothing is ever promoted
    assert seen[0] == seen[1] == 8
    assert seen[2] != 8
    assert t.best() == {"wb": 8}


def test_promotion_needs_margin_and_two_observations(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "2")
    t = _tuner(margin=1.05)
    # incumbent measured at 1000 edges/s
    t.record({"wb": 8}, 1000, 1.0)
    # first sight of a 3x-better challenger: NOT promoted (hysteresis —
    # one lucky draw must not flip the configuration)
    t.record({"wb": 4}, 3000, 1.0)
    assert t.best() == {"wb": 8}
    # second consistent observation clears the margin: promoted
    t.record({"wb": 4}, 3000, 1.0)
    assert t.best() == {"wb": 4}
    # a challenger that does NOT clear 1.05x never wins
    t.record({"wb": 2}, 3100, 1.0)
    t.record({"wb": 2}, 3100, 1.0)
    assert t.best() == {"wb": 4}
    assert any(e["action"] == "promote" for e in t.timeline)


def test_decisions_are_deterministic(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "2")

    def drive():
        t = _tuner(space={"wb": [2, 4, 8], "ingress": ["a", "b"]},
                   initial={"wb": 8, "ingress": "a"})
        picks = []
        for i in range(8):
            arm = t.next_round()
            picks.append(json.dumps(arm, sort_keys=True))
            t.record(arm, 1000 + 7 * i, 1.0)
        return picks, t.best()

    assert drive() == drive()


def test_cache_round_trip_and_seed():
    t = _tuner()
    t.record({"wb": 8}, 1000, 1.0)
    t.record({"wb": 4}, 4000, 1.0)
    t.record({"wb": 4}, 4000, 1.0)
    assert t.best() == {"wb": 4}
    t.save()
    # a new process (fresh tuner, same key): seeds from the cache
    t2 = _tuner()
    assert t2.best() == {"wb": 4}
    assert t2.timeline[0]["action"] == "cache_seed"
    # a cached arm OUTSIDE the current space is ignored
    t3 = _tuner(space={"wb": [8, 16]}, initial={"wb": 16})
    assert t3.best() == {"wb": 16}


def test_cache_disabled_and_corrupt_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    assert autotune.cache_path("cpu") == ""
    t = _tuner()
    t.record({"wb": 8}, 1000, 1.0)
    t.save()  # no-op, no crash
    cache_dir = tmp_path / "corrupt"
    monkeypatch.setenv("GS_TUNE_CACHE", str(cache_dir))
    os.makedirs(cache_dir)
    with open(autotune.cache_path("x"), "w") as f:
        f.write("{not json")
    assert autotune.load_cached_best("any", "x") is None


def test_state_dict_round_trip():
    t = _tuner(space={"wb": [2, 4, 8]})
    for i in range(5):
        arm = t.next_round()
        t.record(arm, 1000 + 100 * i, 1.0)
    state = t.state_dict()
    t2 = _tuner(space={"wb": [2, 4, 8]})
    t2.load_state_dict(state)
    assert t2.state_dict() == state
    assert t2.best() == t.best()
    # stale incumbent (space changed across a code change): dropped
    t3 = _tuner(space={"wb": [16, 32]}, initial={"wb": 32})
    t3.load_state_dict(state)
    assert t3.best() == {"wb": 32}


def test_initial_outside_space_rejected():
    with pytest.raises(ValueError):
        _tuner(initial={"wb": 3})


# ----------------------------------------------------------------------
# engine wiring: results invariant, knobs live
# ----------------------------------------------------------------------
def test_triangle_counts_identical_on_and_off(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    k0 = TriangleWindowKernel(edge_bucket=256, vertex_bucket=1024)
    # the tuner engages only past one maximal chunk: size the stream
    # off the kernel's own (possibly evidence-tuned) chunk depth
    n_w = 2 * k0.MAX_STREAM_WINDOWS + 3
    src, dst = _stream(n_w * 256, 1024)
    legacy = k0._count_stream_device(src, dst)
    monkeypatch.setenv("GS_AUTOTUNE", "1")
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "2")
    k = TriangleWindowKernel(edge_bucket=256, vertex_bucket=1024)
    tuned = k._count_stream_device(src, dst)
    assert tuned == legacy
    assert k.tuner is not None and k.tuner._round > 0


def test_autotune_off_keeps_legacy_path(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    src, dst = _stream(24 * 256, 1024)
    k = TriangleWindowKernel(edge_bucket=256, vertex_bucket=1024)
    k._count_stream_device(src, dst)
    # the tuner was never built: the static path ran untouched
    assert getattr(k, "tuner", None) is None


def test_pinned_knobs_freeze_tuner_dimensions():
    k = TriangleWindowKernel(edge_bucket=256, vertex_bucket=1024,
                             k_bucket=64, ingress="standard")
    space = k._tuner_space()
    assert space["kb"] == [k.kb]
    assert space["ingress"] == ["standard"]
    # unpinned: the ladder and both wire formats are in play
    k2 = TriangleWindowKernel(edge_bucket=256, vertex_bucket=1024)
    space2 = k2._tuner_space()
    assert len(space2["kb"]) >= 1 and "compact" in space2["ingress"]


def test_engine_summaries_identical_and_ckpt_round_trip(
        monkeypatch, tmp_path):
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    probe = StreamSummaryEngine(edge_bucket=512, vertex_bucket=2048)
    n_w = 2 * probe.MAX_WINDOWS + 3
    src, dst = _stream(n_w * 512, 2048, seed=7)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)
    legacy = probe.process(src32, dst32)
    monkeypatch.setenv("GS_AUTOTUNE", "1")
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "2")
    eng = StreamSummaryEngine(edge_bucket=512, vertex_bucket=2048)
    assert eng.process(src32, dst32) == legacy
    assert eng._tuner is not None
    # the learned state rides the engine checkpoint
    state = eng.state_dict()
    assert "autotune" in state
    eng2 = StreamSummaryEngine(edge_bucket=512, vertex_bucket=2048)
    eng2.load_state_dict(state)
    assert eng2._tuner.state_dict() == eng._tuner.state_dict()


def test_driver_digests_identical_and_ckpt_round_trip(
        monkeypatch, tmp_path):
    src, dst = _stream(20 * 256, 2048, seed=11)

    def digest(results):
        h = hashlib.sha256()
        for r in results:
            for a in (r.vertex_ids, r.degrees, r.cc_labels,
                      r.bipartite_odd):
                if a is not None:
                    h.update(np.ascontiguousarray(a).tobytes())
            h.update(str(r.triangles).encode())
        return h.hexdigest()

    def run():
        drv = StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=256, vertex_bucket=2048,
            snapshot_tier="scan")
        return digest(drv.run_arrays(src, dst)), drv

    monkeypatch.setenv("GS_AUTOTUNE", "0")
    d0, drv0 = run()
    assert drv0._scan_tuner is None
    monkeypatch.setenv("GS_AUTOTUNE", "1")
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "2")
    d1, drv1 = run()
    assert d0 == d1
    assert drv1._scan_tuner is not None
    state = drv1.state_dict()
    assert "autotune" in state
    drv2 = StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=256, vertex_bucket=2048,
        snapshot_tier="scan")
    drv2.load_state_dict(state)
    assert (drv2._scan_tuner.state_dict()
            == drv1._scan_tuner.state_dict())

"""Mesh-scoped fault injection + the sharded demotion ladder
(utils/faults shard-aware plans, parallel/sharded guards,
core/driver sharded → scan → native → host): dead shards, ICI stalls,
corrupt shard wires — every mesh failure path exercised with a fixed
plan on the virtual CPU mesh, no randomness. Part of the tier-1
`faults` suite (the marker below), like the single-chip fault drills
in test_faults.py."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import ingress_pipeline as ip
from gelly_streaming_tpu.parallel.host_twin import HostSummaryEngine
from gelly_streaming_tpu.parallel.mesh import make_mesh
from gelly_streaming_tpu.parallel.sharded import (
    ShardedSummaryEngine, ShardedTriangleWindowKernel, guard_wire)
from gelly_streaming_tpu.utils import faults, resilience

pytestmark = pytest.mark.faults

_KNOBS = ("GS_STAGE_TIMEOUT_S", "GS_STAGE_RETRIES",
          "GS_STAGE_BACKOFF_S", "GS_TIER_RETRY_WINDOWS",
          "GS_TIER_DEMOTE", "GS_MESH_DEMOTE", "GS_MESH_WIRE_CHECK")


@pytest.fixture(autouse=True)
def _clean_knobs():
    """Every test starts from inert knobs, leaves none behind, and
    clears the process demotion registry; the prep pool is dropped so
    a deliberately hung worker never serves a later test."""
    saved = {k: os.environ.pop(k, None) for k in _KNOBS}
    os.environ["GS_STAGE_BACKOFF_S"] = "0.01"
    resilience.reset_demotions()
    try:
        yield
    finally:
        for k in _KNOBS:
            os.environ.pop(k, None)
            if saved[k] is not None:
                os.environ[k] = saved[k]
        resilience.reset_demotions()
        ip.reset_pool()


EB, VB, V = 256, 512, 300


def _stream(num_w=8, seed=5):
    rng = np.random.default_rng(seed)
    n = num_w * EB
    return (rng.integers(0, V, n).astype(np.int64),
            rng.integers(0, V, n).astype(np.int64))


def _driver(mesh=None, **kw):
    return StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=EB, vertex_bucket=VB,
        analytics=("degrees", "cc", "triangles"), mesh=mesh, **kw)


def _key(results):
    return [(r.window_start, r.num_edges, r.degrees.tolist(),
             r.cc_labels.tolist(), r.triangles) for r in results]


def _arm(timeout="5", retries="2"):
    os.environ["GS_STAGE_TIMEOUT_S"] = timeout
    os.environ["GS_STAGE_RETRIES"] = retries


# ----------------------------------------------------------------------
# driver ladder: sharded → scan
# ----------------------------------------------------------------------
def test_dead_shard_demotes_mid_stream_with_parity():
    """A persistently dead shard demotes the mesh session to the
    single-chip scan tier MID-STREAM; results stay window-by-window
    identical to the fault-free run and the demotion record carries
    the mesh shape and the implicated shard."""
    _arm()
    src, dst = _stream()
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        on_call=2, times=1 << 20,
                                        shard=3)):
        got = _key(drv.run_arrays(src[:4 * EB], dst[:4 * EB]))
        got += _key(drv.run_arrays(src[4 * EB:], dst[4 * EB:]))
    assert got == want
    assert not drv._mesh_live()
    evs = resilience.demotion_events()
    assert evs and evs[0]["from"] == "sharded" and evs[0]["to"] == "scan"
    assert evs[0]["mesh_shape"] == [4] and evs[0]["shard_id"] == 3


def test_mesh_demote_pin_raises_instead():
    """GS_MESH_DEMOTE=0 pins the mesh rung: the dead shard surfaces as
    the typed stage error instead of silently degrading."""
    _arm()
    os.environ["GS_MESH_DEMOTE"] = "0"
    src, dst = _stream(num_w=4)
    drv = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        on_call=1, times=1 << 20)):
        with pytest.raises(resilience.StageError):
            drv.run_arrays(src, dst)
    assert drv._mesh_live()  # never demoted
    assert not resilience.demotion_events()


def test_ici_stall_cut_by_watchdog_and_retried():
    """A transient mesh stall (hang at the sharded scan dispatch) is
    cut by the GS_STAGE_TIMEOUT_S watchdog and the retried dispatch
    completes — no demotion, identical results."""
    src, dst = _stream(num_w=4)
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    drv.run_arrays(src[:2 * EB], dst[:2 * EB])  # compile OUTSIDE the
    drv2 = _driver(mesh=make_mesh(4))           # deadline (fresh twin
    drv2.run_arrays(src[:2 * EB], dst[:2 * EB])  # burns its counters)
    _arm(timeout="1")
    drv3 = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        on_call=1, action="hang",
                                        seconds=5.0)):
        got = _key(drv3.run_arrays(src, dst))
    assert got == want
    assert drv3._mesh_live()
    assert not resilience.demotion_events()


def test_corrupt_shard_wire_caught_retried_then_demotes():
    """GS_MESH_WIRE_CHECK=1: a corrupt shard slice is caught BEFORE
    dispatch (typed failure naming the shard). Transient corruption is
    retried clean; persistent corruption exhausts the budget and rides
    the demotion ladder — results identical either way."""
    _arm()
    os.environ["GS_MESH_WIRE_CHECK"] = "1"
    src, dst = _stream()
    want = _key(_driver().run_arrays(src, dst))

    drv = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_wire", on_call=2,
                                        times=1, action="corrupt_shard",
                                        shard=1)):
        got = _key(drv.run_arrays(src, dst))
    assert got == want
    assert drv._mesh_live() and not resilience.demotion_events()

    drv2 = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_wire", on_call=2,
                                        times=1 << 20,
                                        action="corrupt_shard",
                                        shard=1)):
        got2 = _key(drv2.run_arrays(src, dst))
    assert got2 == want
    evs = resilience.demotion_events()
    assert evs and evs[0]["from"] == "sharded"
    # the reason carries the wire-check failure (directly, or inside
    # the worker traceback when the h2d stage caught it — the [:500]
    # reason cut can land mid-traceback)
    assert ("corrupt shard wire" in evs[0]["reason"]
            or "_check_wire" in evs[0]["reason"])


def test_guard_wire_names_the_offending_shard():
    os.environ["GS_MESH_WIRE_CHECK"] = "1"
    good = np.full((2, 16), 7, np.int32)
    assert guard_wire((good, good), 4, 10) == (good, good)
    bad = good.copy()
    bad[:, 8:12] = 1 << 20  # shard 2's slice of 4
    with pytest.raises(RuntimeError, match="shard 2 of 4"):
        guard_wire((good, bad), 4, 10)


def test_wire_check_disarmed_is_pass_through():
    """Default GS_MESH_WIRE_CHECK=0: guard_wire is a pure pass-through
    (no validation cost, no behavior change) when no plan is active."""
    bad = np.full((2, 16), 1 << 20, np.int32)
    out = guard_wire((bad, bad), 4, 10)
    assert out[0] is bad and out[1] is bad


def test_repromotion_after_probation_returns_to_mesh():
    """GS_TIER_RETRY_WINDOWS: after probation windows on the demoted
    single-chip tier, the session re-promotes to the sharded tier
    (mirrors → engine slabs) and keeps producing identical results."""
    _arm()
    os.environ["GS_TIER_RETRY_WINDOWS"] = "2"
    src, dst = _stream()
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        on_call=2, times=2, shard=0)):
        got = _key(drv.run_arrays(src[:4 * EB], dst[:4 * EB]))
    got += _key(drv.run_arrays(src[4 * EB:6 * EB], dst[4 * EB:6 * EB]))
    got += _key(drv.run_arrays(src[6 * EB:], dst[6 * EB:]))
    assert got == want
    kinds = [(e["from"], e["to"]) for e in resilience.demotion_events()]
    assert ("sharded", "scan") in kinds and ("scan", "sharded") in kinds
    assert drv._mesh_live()


# ----------------------------------------------------------------------
# engine-level drain + twin hand-off
# ----------------------------------------------------------------------
def test_sharded_summary_drain_and_host_twin_handoff():
    """The satellite contract: an error escaping the sharded summary
    engine first drains the in-flight finalize — the finalized
    summaries land on `drained_partial`, the cursor sits exactly past
    them, and a host twin continues from there to the uninterrupted
    run's results."""
    _arm(retries="0")
    rng = np.random.default_rng(9)
    eb, v = 128, 100
    src = rng.integers(0, v, 8 * eb).astype(np.int32)
    dst = rng.integers(0, v, 8 * eb).astype(np.int32)
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine

    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=v).process(src, dst)
    eng = ShardedSummaryEngine(make_mesh(4), edge_bucket=eb,
                               vertex_bucket=v)
    # 8 windows dispatch as multiple chunks: kill the second dispatch
    eng.MAX_WINDOWS = 2
    with pytest.raises(resilience.StageError):
        with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                            on_call=2,
                                            times=1 << 20)):
            eng.process(src, dst)
    drained = eng.drained_partial
    assert drained is not None
    assert len(drained) == eng.windows_done
    assert drained == want[:len(drained)]
    twin = HostSummaryEngine.from_sharded(eng)
    off = twin.resume_offset()
    tail = twin.process(src[off:], dst[off:])
    assert drained + tail == want


def test_sharded_triangle_kernel_drains_counts():
    _arm(retries="0")
    rng = np.random.default_rng(3)
    kern = ShardedTriangleWindowKernel(make_mesh(4), edge_bucket=128,
                                       vertex_bucket=64)
    kern.MAX_STREAM_WINDOWS = 2
    src = rng.integers(0, 60, 8 * 128).astype(np.int32)
    dst = rng.integers(0, 60, 8 * 128).astype(np.int32)
    want = kern.count_stream(src, dst)
    assert kern.drained_counts is None  # clean run leaves no stash
    with pytest.raises(resilience.StageError):
        with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                            on_call=2,
                                            times=1 << 20)):
            kern.count_stream(src, dst)
    drained = kern.drained_counts
    assert drained is not None and 0 < len(drained) < 8
    assert drained == want[:len(drained)]


def test_dead_gather_still_demotes_off_the_mirrors():
    """The demotion hand-off must not depend on the failing mesh: with
    the d2h gather dead too (the realistic dead-chip model), the host
    mirrors — refreshed at every finalized boundary — carry the
    hand-off, results stay identical, and a checkpoint taken while
    demoted never touches the mesh."""
    from gelly_streaming_tpu.utils import checkpoint as ck

    _arm()
    src, dst = _stream()
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    with faults.inject(
            faults.FaultSpec(site="shard_dispatch", on_call=2,
                             times=1 << 20, shard=1),
            faults.FaultSpec(site="shard_gather", on_call=3,
                             times=1 << 20, shard=1)):
        head = _key(drv.run_arrays(src[:4 * EB], dst[:4 * EB]))
        assert not drv._mesh_live()
        state = drv.state_dict()  # still inside the dead-mesh plan
    assert _key(drv.run_arrays(src[4 * EB:], dst[4 * EB:])) \
        == want[4:]
    assert head == want[:4]
    # ... and the mesh-free checkpoint resumes bit-exactly off-mesh
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as wd:
        path = _os.path.join(wd, "demoted.npz")
        ck.save(path, state)
        res = _driver()
        assert res.try_resume(path)
        tail = _key(res.run_arrays(src[res.edges_done:],
                                   dst[res.edges_done:]))
        assert head + tail == want


def test_failed_repromotion_probe_restarts_probation():
    """A mesh still dead at probe time must RE-DEMOTE (restart
    probation, record the failed probe), never crash the stream."""
    _arm()
    os.environ["GS_TIER_RETRY_WINDOWS"] = "2"
    src, dst = _stream()
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    orig = StreamingAnalyticsDriver._sync_engine_from_mirrors
    calls = {"n": 0}

    def dying_sync(self):
        calls["n"] += 1
        if calls["n"] <= 2:  # the first probes find the mesh dead
            raise RuntimeError("mesh still dead")
        return orig(self)

    StreamingAnalyticsDriver._sync_engine_from_mirrors = dying_sync
    try:
        with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                            on_call=2, times=2)):
            got = _key(drv.run_arrays(src[:4 * EB], dst[:4 * EB]))
        for lo in range(4, 8, 2):
            got += _key(drv.run_arrays(src[lo * EB:(lo + 2) * EB],
                                       dst[lo * EB:(lo + 2) * EB]))
    finally:
        StreamingAnalyticsDriver._sync_engine_from_mirrors = orig
    assert got == want, "probe-failure run diverged"
    kinds = [(e["from"], e["to"])
             for e in resilience.demotion_events()]
    assert ("scan", "scan") in kinds           # failed probe recorded
    assert drv._demoted_tier == "scan"          # still safely demoted


def test_host_demoted_triangles_use_numpy_twin():
    """Past the device rungs (native/host), the triangle flush must
    run the pure-numpy twin — never compile against the dead backend
    it demoted away from."""
    from gelly_streaming_tpu.parallel.host_twin import (
        HostTriangleWindowKernel)

    src, dst = _stream(num_w=4)
    want = _key(_driver().run_arrays(src, dst))
    drv = _driver(mesh=make_mesh(4))
    got = _key(drv.run_arrays(src[:2 * EB], dst[:2 * EB]))
    err = resilience.StageFailed("x", "dispatch", 0)
    err.__cause__ = RuntimeError("dead device")
    assert drv._maybe_demote("sharded", err)
    assert drv._maybe_demote("scan", err)
    assert drv._demoted_tier in ("native", "host")
    assert isinstance(drv._tri_kern(), HostTriangleWindowKernel)
    got += _key(drv.run_arrays(src[2 * EB:], dst[2 * EB:]))
    assert got == want


def test_per_window_event_time_path_demotes_too():
    """The PER-WINDOW dispatch path (event-time streaming, single
    window per call) rides the same ladder: a dead shard demotes
    mid-stream and the per-window analytics continue off the mirrors
    with identical results."""
    _arm()
    rng = np.random.default_rng(8)
    n = 6 * EB
    src = rng.integers(0, V, n).astype(np.int64)
    dst = rng.integers(0, V, n).astype(np.int64)
    ts = (np.arange(n, dtype=np.int64) // EB) * 1000  # 6 windows

    def mk(mesh=None):
        return StreamingAnalyticsDriver(
            window_ms=1000, edge_bucket=EB, vertex_bucket=VB,
            analytics=("degrees", "cc", "triangles"), mesh=mesh)

    def one_by_one(drv):
        out = []
        for w in range(6):  # one window per call → the _window path
            lo = w * EB
            out += drv.run_arrays(src[lo:lo + EB], dst[lo:lo + EB],
                                  ts[lo:lo + EB])
        return _key(out)

    want = one_by_one(mk())
    drv = mk(mesh=make_mesh(4))
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        on_call=4, times=1 << 20,
                                        shard=2)):
        got = one_by_one(drv)
    assert got == want
    assert not drv._mesh_live()
    evs = resilience.demotion_events()
    assert evs and evs[0]["from"] == "sharded"


def test_fault_event_carries_shard_metadata():
    """The injected-fault telemetry/exception surface names the
    shard, so a post-mortem can attribute the failure."""
    with faults.inject(faults.FaultSpec(site="shard_dispatch",
                                        shard=5)) as plan:
        with pytest.raises(faults.InjectedFault) as ei:
            faults.fire("shard_dispatch", 8)
    assert ei.value.shard == 5
    assert "shard 5" in str(ei.value)
    assert plan.fired == [("shard_dispatch", 1, "raise")]

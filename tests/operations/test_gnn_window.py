"""Windowed GNN message passing (ops/gnn_window.py): device ≡ numpy
twin BIT-exactness across (eb, vb, F, act) grids with ragged tails,
the empty-window-holds rule that makes dispatch padding inert, the
lattice snapping helpers, kill→resume through checkpoint + WAL
(gnn→gnn and the gnn→host demotion hand-off), the vmapped tenant
cohort at N ∈ {1, 3, 8} vs sequential engines, the fused Pallas GNN
kernel (interpret parity, VMEM-refusal fallback event, the
GS_GNN_PALLAS evidence gate), the analytic cost-model registration
(the repo's first MXU-class intensity rows), and the disarmed-default
digest pin."""

import hashlib
import json

import numpy as np
import pytest

from gelly_streaming_tpu.core.tenancy import GnnTenantCohort
from gelly_streaming_tpu.ops import gnn_window as gw
from gelly_streaming_tpu.ops import pallas_window as pw
from gelly_streaming_tpu.ops import triangles as tri_ops
from gelly_streaming_tpu.utils import faults, resilience, telemetry


def _stream(n, v, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, v, n).astype(np.int32),
            rng.integers(0, v, n).astype(np.int32))


def _digest(summaries, slab=None) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    if slab is not None:
        h.update(np.ascontiguousarray(slab, np.float32).tobytes())
    return h.hexdigest()[:16]


def _mk(cls, eb, vb, F, act="relu", **kw):
    eng = cls(eb, vb, feature_dim=F, activation=act, **kw)
    rng = np.random.RandomState(3)
    eng.set_weights(rng.randn(F, F) * 0.3, rng.randn(F) * 0.1)
    eng.load_feature_units(gw.default_features(vb, F, seed=5))
    return eng


# ----------------------------------------------------------------------
# lattice helpers
# ----------------------------------------------------------------------
def test_shift_and_cap_laws():
    assert gw.agg_shift(2 ** 15) == 0
    assert gw.agg_shift(2 ** 16) == 1
    assert gw.agg_shift(8) == 0
    assert gw.weight_shift(64) == 0
    assert gw.weight_shift(65) == 1
    assert gw.weight_cap(64) == 512
    assert gw.weight_cap(128) == 256


def test_snap_weights_grid_and_shapes():
    W, b = gw.snap_weights(np.full((4, 4), 0.33), np.zeros(4), 4)
    # 0.33 * 32 = 10.56 → 11 units, exactly representable
    assert np.all(W == np.float32(11.0))
    assert W.dtype == np.float32 and b.shape == (4,)
    with pytest.raises(ValueError):
        gw.snap_weights(np.zeros((3, 4)), np.zeros(4), 4)


def test_snap_features_clips_and_pads():
    slab = gw.snap_features(np.full((3, 2), 99.0), vb=8, F=2)
    assert slab.shape == (9, 2)
    assert np.all(slab[:3] == gw.UNIT_CAP)
    assert np.all(slab[3:] == 0)
    with pytest.raises(ValueError):
        gw.snap_features(np.zeros((9, 2)), vb=8, F=2)


# ----------------------------------------------------------------------
# device ≡ numpy twin parity (the lattice bit-exactness contract)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("eb,vb,F,act", [
    (64, 128, 4, "relu"),
    (256, 512, 16, "abs"),
    (128, 64, 8, "identity"),
])
def test_engine_host_parity_ragged(eb, vb, F, act):
    n = 5 * eb - eb // 3  # ragged tail closes a partial window
    src, dst = _stream(n, vb, seed=eb + F)
    dev = _mk(gw.GnnSummaryEngine, eb, vb, F, act)
    host = _mk(gw.GnnHostEngine, eb, vb, F, act)
    got, want = dev.process(src, dst), host.process(src, dst)
    assert got == want
    assert np.array_equal(dev.state(), host.state())
    assert got[-1]["msg_edges"] == n - 4 * eb  # the partial tail


def test_resident_tier_parity():
    eb, vb, F = 64, 128, 8
    src, dst = _stream(6 * eb, vb, seed=2)
    res = _mk(gw.GnnResidentEngine, eb, vb, F, superbatch=4)
    host = _mk(gw.GnnHostEngine, eb, vb, F)
    assert res.process(src, dst) == host.process(src, dst)
    assert np.array_equal(res.state(), host.state())


def test_empty_window_holds_slab():
    """The padding-inertness foundation: a window with zero valid
    edges leaves the carry bit-identical (the dense layer must NOT
    tick), on both the XLA round and the numpy twin."""
    import jax.numpy as jnp

    eb, vb, F = 32, 64, 4
    round_ = gw._build_gnn_round(eb, vb, F, "relu")
    h0 = jnp.asarray(gw.default_features(vb, F, seed=1))
    W = jnp.asarray(gw.snap_weights(*gw.default_weights(F), F)[0])
    b = jnp.zeros(F)
    s = jnp.zeros(eb, jnp.int32)
    d = jnp.zeros(eb, jnp.int32)
    h1, (maxf, active, csum, nmsg) = round_(
        h0, W, b, s, d, jnp.zeros(eb, bool))
    assert np.array_equal(np.asarray(h1), np.asarray(h0))
    assert int(nmsg) == 0
    # a live window with the same slab DOES tick
    h2, _ = round_(h0, W, b, s, d, jnp.ones(eb, bool))
    assert not np.array_equal(np.asarray(h2), np.asarray(h0))


def test_engine_padding_inert_across_chunk_splits():
    """The chunk loop pads dispatches to bucketed window counts with
    all-invalid windows; feeding the same stream in different call
    granularities must be bit-identical."""
    eb, vb, F = 64, 128, 8
    n = 6 * eb
    src, dst = _stream(n, vb, seed=4)
    one = _mk(gw.GnnSummaryEngine, eb, vb, F)
    whole = one.process(src, dst)
    two = _mk(gw.GnnSummaryEngine, eb, vb, F)
    split = []
    for lo in range(0, n, 2 * eb):
        split += two.process(src[lo:lo + 2 * eb],
                             dst[lo:lo + 2 * eb])
    assert split == whole
    assert np.array_equal(one.state(), two.state())


# ----------------------------------------------------------------------
# weights / checkpoint layout
# ----------------------------------------------------------------------
def test_set_weights_never_recompiles_and_snaps():
    eb, vb, F = 64, 128, 4
    eng = _mk(gw.GnnSummaryEngine, eb, vb, F)
    W, b = eng.weights()
    assert np.all(W == np.rint(W))  # lattice units are integers
    src, dst = _stream(2 * eb, vb, seed=6)
    a = eng.process(src, dst)
    eng.set_weights(np.eye(F) * 2.0)
    bb = eng.process(src, dst)
    assert a != bb  # the new layer actually applied


def test_state_dict_roundtrip_and_f_mismatch():
    eb, vb, F = 64, 128, 8
    eng = _mk(gw.GnnSummaryEngine, eb, vb, F)
    src, dst = _stream(2 * eb, vb, seed=7)
    eng.process(src, dst)
    snap = eng.state_dict()
    assert snap["gnn"]["feat_dim"] == F
    eng2 = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
    eng2.load_state_dict(snap)
    assert np.array_equal(eng2.state(), eng.state())
    assert np.array_equal(eng2.weights()[0], eng.weights()[0])
    wrong = gw.GnnSummaryEngine(eb, vb, feature_dim=4)
    with pytest.raises(ValueError):
        wrong.load_state_dict(snap)


def test_kill_resume_gnn_to_gnn(tmp_path):
    """Fatal kill mid-stream → auto-checkpoint resume, positional
    at-least-once combine ≡ the fault-free run, slab included."""
    eb, vb, F = 64, 128, 8
    num_w = 8
    src, dst = _stream(num_w * eb, vb, seed=9)
    oracle = _mk(gw.GnnSummaryEngine, eb, vb, F)
    baseline = oracle.process(src, dst)

    ckpt = str(tmp_path / "gnn.npz")
    eng = _mk(gw.GnnSummaryEngine, eb, vb, F)
    eng.enable_auto_checkpoint(ckpt, every_n_windows=2)
    out = eng.process(src[:4 * eb], dst[:4 * eb])
    with pytest.raises(faults.InjectedFault):
        with faults.inject(faults.FaultSpec(site="dispatch",
                                            on_call=1, fatal=True)):
            eng.process(src[4 * eb:], dst[4 * eb:])
    eng2 = _mk(gw.GnnSummaryEngine, eb, vb, F)
    assert eng2.try_resume(ckpt)
    off = eng2.resume_offset()
    assert off >= 4 * eb  # the checkpoint covered the delivered calls
    rest = eng2.process(src[off:], dst[off:])
    assert out[:off // eb] + rest == baseline
    assert np.array_equal(eng2.state(), oracle.state())


def test_demotion_gnn_to_host_twin():
    """The gnn→host hand-off: a host twin built from a device
    checkpoint continues the stream bit-exactly."""
    eb, vb, F = 64, 128, 8
    src, dst = _stream(6 * eb, vb, seed=10)
    oracle = _mk(gw.GnnSummaryEngine, eb, vb, F)
    baseline = oracle.process(src, dst)
    eng = _mk(gw.GnnSummaryEngine, eb, vb, F)
    head = eng.process(src[:2 * eb], dst[:2 * eb])
    twin = gw.GnnHostEngine.from_state(eng.state_dict())
    assert twin.act == eng.act and twin.F == F
    tail = twin.process(src[2 * eb:], dst[2 * eb:])
    assert head + tail == baseline
    assert np.array_equal(twin.state(), oracle.state())


# ----------------------------------------------------------------------
# tenant cohort
# ----------------------------------------------------------------------
def _cohort_streams(n_tenants, windows, eb, vb):
    streams = {}
    for i in range(n_tenants):
        n = windows * eb - (eb // 3 if i % 3 == 2 else 0)
        streams["t%02d" % i] = _stream(n, vb, seed=50 + i)
    return streams


def _sequential(streams, eb, vb, F):
    out, slabs = {}, {}
    for i, tid in enumerate(sorted(streams)):
        eng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
        eng.load_feature_units(gw.default_features(vb, F, seed=i))
        s, d = streams[tid]
        out[tid] = eng.process(s, d)
        slabs[tid] = eng.state()
    return out, slabs


@pytest.mark.parametrize("n_tenants", [1, 3, 8])
def test_cohort_parity_vs_sequential(n_tenants):
    eb, vb, F = 64, 128, 8
    streams = _cohort_streams(n_tenants, 4, eb, vb)
    want, _slabs = _sequential(streams, eb, vb, F)
    co = GnnTenantCohort(eb, vb, feature_dim=F)
    for i, tid in enumerate(sorted(streams)):
        co.admit(tid, feature_units=gw.default_features(vb, F,
                                                        seed=i))
    for tid, (s, d) in streams.items():
        co.feed(tid, s, d)
    got = co.pump()
    for tid in streams:
        got[tid] += co.close(tid)
        assert got[tid] == want[tid], tid


def test_cohort_demote_to_engine():
    """demote() pops a tenant into a single-stream GnnSummaryEngine:
    full queued windows fold through the engine (their summaries are
    returned, never dropped), the sub-window tail comes back UNFOLDED
    for the caller to prepend, a durable demotion record lands, and
    the continued stream stays bit-exact."""
    eb, vb, F = 64, 128, 8
    streams = _cohort_streams(2, 4, eb, vb)
    want, wslabs = _sequential(streams, eb, vb, F)
    resilience.reset_demotions()
    co = GnnTenantCohort(eb, vb, feature_dim=F)
    for i, tid in enumerate(sorted(streams)):
        co.admit(tid, feature_units=gw.default_features(vb, F,
                                                        seed=i))
    got = {tid: [] for tid in streams}
    for tid, (s, d) in streams.items():
        co.feed(tid, s[:2 * eb], d[:2 * eb])
    for tid, res in co.pump().items():
        got[tid] += res
    # leave t00 with one FULL window + a sub-window tail queued
    s, d = streams["t00"]
    cut = 2 * eb + eb + eb // 2
    co.feed("t00", s[2 * eb:cut], d[2 * eb:cut])
    eng, folded, (ts, td) = co.demote("t00")
    assert isinstance(eng, gw.GnnSummaryEngine)
    assert len(folded) == 1 and len(ts) == eb // 2
    got["t00"] += folded
    got["t00"] += eng.process(np.concatenate([ts, s[cut:]]),
                              np.concatenate([td, d[cut:]]))
    assert got["t00"] == want["t00"]
    assert np.array_equal(eng.state(), wslabs["t00"])
    assert any(e.get("tenant") == "t00"
               for e in resilience.demotion_events())
    assert "t00" not in co.tenants()
    # the remaining tenant is undisturbed
    s, d = streams["t01"]
    co.feed("t01", s[2 * eb:], d[2 * eb:])
    for tid, res in co.pump().items():
        got[tid] += res
    got["t01"] += co.close("t01")
    assert got["t01"] == want["t01"]


def test_cohort_state_dict_engine_interchange():
    eb, vb, F = 64, 128, 8
    co = GnnTenantCohort(eb, vb, feature_dim=F)
    co.admit("t", feature_units=gw.default_features(vb, F, seed=0))
    s, d = _stream(2 * eb, vb, seed=60)
    co.feed("t", s, d)
    co.pump()
    snap = co.tenant_state_dict("t")
    eng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
    eng.load_state_dict(snap)
    assert np.array_equal(eng.state(), co.state("t"))


# ----------------------------------------------------------------------
# fused Pallas GNN kernel
# ----------------------------------------------------------------------
@pytest.fixture
def gnn_pallas_on(monkeypatch):
    monkeypatch.setenv("GS_GNN_PALLAS", "on")
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    pw._reset_pallas_window()
    yield
    pw._reset_pallas_window()


def test_pallas_interpret_parity(gnn_pallas_on):
    eb, vb, F = 64, 128, 8
    src, dst = _stream(5 * eb - eb // 3, vb, seed=11)
    eng = _mk(gw.GnnSummaryEngine, eb, vb, F)
    assert eng._pallas  # actually selected, not silently declined
    host = _mk(gw.GnnHostEngine, eb, vb, F)
    assert eng.process(src, dst) == host.process(src, dst)
    assert np.array_equal(eng.state(), host.state())


def test_pallas_vmem_refusal_falls_back_with_event(monkeypatch):
    """A pretend-chip refusing the VMEM budget must decline the
    kernel with a durable selection.fallback — the engine silently
    keeps the XLA round."""
    monkeypatch.setenv("GS_GNN_PALLAS", "on")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.delenv("GS_TRACE_DIR", raising=False)
    monkeypatch.setattr(pw, "_on_tpu", lambda: True)
    pw._reset_pallas_window()
    telemetry.reset()
    try:
        assert not pw.supports_gnn(32768, 65536, 128)
        assert pw.maybe_gnn_body(32768, 65536, 128, "relu") is None
        evs = [r for r in telemetry.records()
               if r["name"] == "selection.fallback"
               and r["a"].get("component") == "gnn_pallas"
               and "vmem budget" in r["a"].get("error", "")]
        assert evs
    finally:
        pw._reset_pallas_window()
        telemetry.reset()


def test_resolve_gnn_pallas_pins_and_evidence(monkeypatch):
    monkeypatch.setenv("GS_GNN_PALLAS", "on")
    assert pw.resolve_gnn_pallas() is True
    monkeypatch.setenv("GS_GNN_PALLAS", "off")
    assert pw.resolve_gnn_pallas() is False
    monkeypatch.delenv("GS_GNN_PALLAS")

    def fake_perf(rows):
        return lambda *a, **k: {"gnn_ab": rows}

    winning = [{"probe": "gnn_pallas", "parity": True,
                "speedup": 1.3}]
    losing = [{"probe": "gnn_pallas", "parity": True,
               "speedup": 1.01}]
    interp = [{"probe": "gnn_pallas", "parity": True,
               "speedup": 2.0, "interpret": True}]
    for rows, want in ((winning, True), (losing, False),
                       (interp, False), ([], False)):
        monkeypatch.setattr(tri_ops, "_load_matching_perf",
                            fake_perf(rows))
        pw._reset_pallas_window()
        assert pw.resolve_gnn_pallas() is want, rows
    pw._reset_pallas_window()


# ----------------------------------------------------------------------
# analytic cost model: the first MXU-class intensity rows
# ----------------------------------------------------------------------
def test_gnn_cost_model_intensity(monkeypatch):
    from gelly_streaming_tpu.utils import costmodel

    monkeypatch.setenv("GS_COSTMODEL", "1")
    costmodel.reset()
    try:
        pw.register_gnn_cost_model(32768, 65536, 16)
        rows = {r["program"]: r for r in costmodel.report()
                if r.get("program", "").startswith("gnn")}
        assert set(rows) >= {"gnn_scan", "gnn_resident",
                             "gnn_pallas"}
        for r in rows.values():
            assert r["arith_intensity_flops_per_byte"] > 0.28
        # the fused kernel reads strictly fewer bytes than the scan
        assert (rows["gnn_pallas"]["bytes_accessed"]
                < rows["gnn_scan"]["bytes_accessed"])
        assert (rows["gnn_pallas"]["arith_intensity_flops_per_byte"]
                > rows["gnn_scan"]
                ["arith_intensity_flops_per_byte"])
    finally:
        costmodel.reset()


def test_gnn_flops_model_has_matmul_term():
    # doubling F must ~quadruple the dense term at fixed eb, vb
    f1 = pw.gnn_window_flops(1024, 4096, 32)
    f2 = pw.gnn_window_flops(1024, 4096, 64)
    dense1 = 2 * 4097 * 32 * 32
    dense2 = 2 * 4097 * 64 * 64
    assert f2 - f1 > (dense2 - dense1) * 0.9


# ----------------------------------------------------------------------
# disarmed-default digest pin
# ----------------------------------------------------------------------
def test_default_gate_digest_pin(monkeypatch):
    """No GS_GNN_* set: the XLA round is selected (no committed
    non-interpret gnn_ab chip rows on CPU) and the digest over
    summaries + slab is the committed pin — which the pinned Pallas
    kernel reproduces bit-for-bit (same stream and seeds as CI gate
    12, tools/gnn_smoke.py)."""
    for k in ("GS_GNN_PALLAS", "GS_GNN_F", "GS_GNN_ACT"):
        monkeypatch.delenv(k, raising=False)
    pw._reset_pallas_window()
    eb = vb = 256
    rng = np.random.default_rng(42)
    src = rng.integers(0, vb - 8, eb).astype(np.int32)
    dst = rng.integers(0, vb - 8, eb).astype(np.int32)
    eng = _mk(gw.GnnSummaryEngine, eb, vb, 16)
    assert not eng._pallas
    assert eng.F == 16 and eng.act == "relu"  # the knob defaults
    got = _digest(eng.process(src, dst), eng.state())
    assert got == "d1ee18e13dd6a744"
    monkeypatch.setenv("GS_GNN_PALLAS", "on")
    pw._reset_pallas_window()
    try:
        eng2 = _mk(gw.GnnSummaryEngine, eb, vb, 16)
        assert eng2._pallas
        assert _digest(eng2.process(src, dst), eng2.state()) == got
    finally:
        pw._reset_pallas_window()

"""Fused Pallas window megakernel (ops/pallas_window.py): interpret-
mode parity against the host twins across all four analytics (the
524K/32768 acceptance row included), ragged window tails, vb/eb
bucket boundaries, the K-overflow exact-redo handoff, the
GS_PALLAS_WINDOW evidence gate (default off = committed digests
unchanged), the trace-failure fallback chaos leg (durable
`selection.fallback`, stream survives), the VMEM-budget `supports`
gate, the tile tuner family, and the analytic cost-model
registration (one slab read strictly below the scan-of-gathers
bytes)."""

import hashlib
import json

import numpy as np
import pytest

from gelly_streaming_tpu.ops import pallas_window as pw
from gelly_streaming_tpu.ops import triangles as tri_ops
from gelly_streaming_tpu.ops.resident_engine import (
    ResidentSummaryEngine)
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.parallel.host_twin import HostSummaryEngine
from gelly_streaming_tpu.utils import telemetry


@pytest.fixture
def pallas_on(monkeypatch):
    monkeypatch.setenv("GS_PALLAS_WINDOW", "on")
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    pw._reset_pallas_window()
    yield
    pw._reset_pallas_window()


@pytest.fixture
def pallas_unset(monkeypatch):
    monkeypatch.delenv("GS_PALLAS_WINDOW", raising=False)
    pw._reset_pallas_window()
    yield
    pw._reset_pallas_window()


def _stream(n, v, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, v, n).astype(np.int32),
            rng.integers(0, v, n).astype(np.int32))


def _digest(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()


# ----------------------------------------------------------------------
# parity: megakernel ≡ XLA scan ≡ host twin
# ----------------------------------------------------------------------
def test_engine_parity_all_analytics_ragged_tail(pallas_on):
    """All four analytics (degrees, CC, bipartiteness, triangles)
    through the engine, with a ragged trailing window."""
    src, dst = _stream(5 * 256 - 37, 200)
    eng = StreamSummaryEngine(edge_bucket=256, vertex_bucket=256)
    assert eng._pallas, "megakernel body not selected under pin"
    out = eng.process(src, dst)
    host = HostSummaryEngine(edge_bucket=256,
                             vertex_bucket=256).process(src, dst)
    assert out == host
    # every analytic actually exercised
    assert any(s["triangles"] for s in out)
    assert any(s["odd_cycle"] for s in out)
    assert out[-1]["max_degree"] >= out[0]["max_degree"]


def test_resident_engine_compact_fused_parity(pallas_on):
    """The resident tier's compact twin decodes uint16 IN-kernel —
    summaries must still match the host twin exactly."""
    src, dst = _stream(2048, 180, seed=2)
    eng = ResidentSummaryEngine(edge_bucket=256, vertex_bucket=256)
    assert eng._pallas and eng.ingress == "compact"
    host = HostSummaryEngine(edge_bucket=256,
                             vertex_bucket=256).process(src, dst)
    assert eng.process(src, dst) == host


def test_stream_counter_parity(pallas_on):
    src, dst = _stream(4 * 256, 150, seed=3)
    on = tri_ops.TriangleWindowKernel(edge_bucket=256,
                                      vertex_bucket=256)
    assert on._pallas_counter
    got = on._count_stream_device(src, dst)
    from gelly_streaming_tpu.ops import host_triangles

    assert got == host_triangles.count_stream(src, dst, 256)


def test_acceptance_524k_row(pallas_on):
    """The acceptance pin: interpret-mode megakernel output is
    sha256-bit-identical to the host twins on the canonical
    524K/32768 row (eb=32768, vb=65536) — all four analytics."""
    src, dst = _stream(524_288, 60_000, seed=7)
    eng = StreamSummaryEngine(edge_bucket=32768, vertex_bucket=65536)
    assert eng._pallas
    got = _digest(eng.process(src, dst))
    host = HostSummaryEngine(edge_bucket=32768, vertex_bucket=65536)
    assert got == _digest(host.process(src, dst))


def test_bucket_boundaries(pallas_on):
    """vb at the uint16 ceiling (compact fused) and past it (standard
    fallback wire), and the minimum edge bucket."""
    src, dst = _stream(512, 60, seed=4)
    for eb, vb in ((8, 65536), (8, 131072), (256, 131072)):
        eng = ResidentSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        assert eng._pallas
        want = "compact" if vb <= 65536 else "standard"
        assert eng.ingress == want
        host = HostSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        assert eng.process(src, dst) == host.process(src, dst)


def test_k_overflow_exact_redo_handoff(pallas_on):
    """A hub whose oriented out-degree outruns K must (a) raise the
    kernel's overflow signal and (b) come back EXACT through the call
    site's escalating redo."""
    import jax
    import jax.numpy as jnp

    v, eb, kb = 128, 128, 8
    # complete graph K14: every vertex has equal degree, so the
    # (degree, id) orientation gives vertex 0 an out-degree of 13 >
    # kb=8 (a low-degree hub would orient INWARD and never overflow)
    m = 14
    ks, kd = np.triu_indices(m, k=1)
    extra_s, extra_d = _stream(200, v, seed=5)
    src = np.concatenate([ks.astype(np.int32), extra_s])
    dst = np.concatenate([kd.astype(np.int32), extra_d])
    # the kernel itself must report the overflow (else this test is
    # vacuous and the redo path untested)
    body = pw.maybe_window_body(eb, vb := 128, kb)
    assert body is not None
    carry = (jnp.zeros(vb + 1, jnp.int32),
             jnp.arange(vb + 1, dtype=jnp.int32),
             jnp.arange(2 * (vb + 1), dtype=jnp.int32))
    from gelly_streaming_tpu.ops import segment as seg_ops

    _w, s, d, valid = seg_ops.window_stack(src, dst, eb, sentinel=vb)
    _c, ys = jax.jit(lambda c, a, b, m: jax.lax.scan(
        body, c, (a, b, m)))(carry, jnp.asarray(s), jnp.asarray(d),
                             jnp.asarray(valid))
    assert int(np.asarray(ys[4]).sum()) > 0, "hub did not overflow K"
    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=128,
                              k_bucket=kb)
    assert eng._pallas
    host = HostSummaryEngine(edge_bucket=eb, vertex_bucket=128,
                             k_bucket=kb)
    assert eng.process(src, dst) == host.process(src, dst)


def test_cohort_scan_stays_xla_with_parity(pallas_on, monkeypatch):
    """build_cohort_scan opts out (vmap-of-pallas is its own future
    evidence) ALL the way down — pallas_ok=False must also reach the
    embedded triangle counter, or a pallas_call smuggles into the
    vmapped body anyway — and per-tenant results still match the
    megakernel engine exactly."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops import scan_analytics as sa

    # spy: nothing in a pallas_ok=False build may consult the
    # megakernel selectors
    calls = []
    real_body, real_ctr = pw.maybe_window_body, pw.maybe_counter
    monkeypatch.setattr(
        pw, "maybe_window_body",
        lambda *a, **k: calls.append("body") or real_body(*a, **k))
    monkeypatch.setattr(
        pw, "maybe_counter",
        lambda *a, **k: calls.append("ctr") or real_ctr(*a, **k))
    sa._build_scan(256, 256, 16, pallas_ok=False)
    assert calls == [], "pallas selector consulted despite opt-out"

    src, dst = _stream(1024, 100, seed=6)
    cohort = TenantCohort(edge_bucket=256, vertex_bucket=256)
    cohort.admit("t0")
    cohort.feed("t0", src, dst)
    outs = cohort.pump()
    single = StreamSummaryEngine(edge_bucket=256,
                                 vertex_bucket=256)
    assert single._pallas
    assert outs["t0"] == single.process(src, dst)


# ----------------------------------------------------------------------
# the evidence gate
# ----------------------------------------------------------------------
def test_gate_default_off_digests_unchanged(pallas_unset):
    """GS_PALLAS_WINDOW unset: the XLA body is selected (no committed
    pallas_ab rows clear the bar on this backend) and the digests are
    the committed ones — which the pinned megakernel reproduces
    bit-for-bit."""
    src, dst = _stream(1024, 120, seed=8)
    eng = StreamSummaryEngine(edge_bucket=256, vertex_bucket=256)
    assert not eng._pallas
    base = _digest(eng.process(src, dst))
    kern = tri_ops.TriangleWindowKernel(edge_bucket=256,
                                        vertex_bucket=256)
    assert not kern._pallas_counter
    counts = kern._count_stream_device(src, dst)

    import os

    os.environ["GS_PALLAS_WINDOW"] = "on"
    pw._reset_pallas_window()
    try:
        eng2 = StreamSummaryEngine(edge_bucket=256,
                                   vertex_bucket=256)
        assert eng2._pallas
        assert _digest(eng2.process(src, dst)) == base
        kern2 = tri_ops.TriangleWindowKernel(edge_bucket=256,
                                             vertex_bucket=256)
        assert kern2._count_stream_device(src, dst) == counts
    finally:
        os.environ.pop("GS_PALLAS_WINDOW", None)
        pw._reset_pallas_window()


def test_resolve_pins(monkeypatch):
    pw._reset_pallas_window()
    monkeypatch.setenv("GS_PALLAS_WINDOW", "on")
    assert pw.resolve_pallas_window() is True
    monkeypatch.setenv("GS_PALLAS_WINDOW", "off")
    assert pw.resolve_pallas_window() is False
    monkeypatch.delenv("GS_PALLAS_WINDOW")
    pw._reset_pallas_window()


def test_resolve_evidence_gate(monkeypatch):
    """auto adopts only when every committed pallas_ab row shows
    parity AND ≥1.05× — the repo-wide measured-adoption bar."""
    def fake_perf(rows):
        return lambda *a, **k: {"pallas_ab": rows}

    winning = [{"probe": "engine_pallas", "parity": True,
                "speedup": 1.3},
               {"probe": "stream_pallas", "parity": True,
                "speedup": 1.1}]
    losing = [dict(winning[0]), dict(winning[1], speedup=1.01)]
    no_parity = [dict(winning[0], parity=False), dict(winning[1])]
    monkeypatch.delenv("GS_PALLAS_WINDOW", raising=False)
    for rows, want in ((winning, True), (losing, False),
                       (no_parity, False), ([], False)):
        monkeypatch.setattr(tri_ops, "_load_matching_perf",
                            fake_perf(rows))
        pw._reset_pallas_window()
        assert pw.resolve_pallas_window() is want, rows
    pw._reset_pallas_window()


# ----------------------------------------------------------------------
# fallback legs (the chaos contract)
# ----------------------------------------------------------------------
def test_trace_failure_falls_back_with_durable_event(monkeypatch):
    """pallas_call raising at build/trace time must degrade to the
    XLA scan with a durable selection.fallback event — the stream
    keeps running, results stay exact."""
    monkeypatch.setenv("GS_PALLAS_WINDOW", "on")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.delenv("GS_TRACE_DIR", raising=False)
    pw._reset_pallas_window()
    telemetry.reset()

    def boom(*a, **k):
        raise RuntimeError("mosaic said no")

    monkeypatch.setattr(pw.pl, "pallas_call", boom)
    pw._CALLS.clear()
    try:
        src, dst = _stream(512, 90, seed=9)
        eng = StreamSummaryEngine(edge_bucket=256, vertex_bucket=256)
        assert not eng._pallas  # fell back to the XLA body
        out = eng.process(src, dst)
        host = HostSummaryEngine(edge_bucket=256, vertex_bucket=256)
        assert out == host.process(src, dst)
        evs = [r for r in telemetry.records()
               if r["name"] == "selection.fallback"
               and r["a"].get("component") == "pallas_window"]
        assert evs, "no durable selection.fallback event"
        assert "mosaic said no" in evs[0]["a"]["error"]
    finally:
        pw._CALLS.clear()
        pw._reset_pallas_window()
        telemetry.reset()


def test_vmem_budget_gate(monkeypatch):
    """supports() enforces the chip VMEM budget on TPU backends only:
    interpret (no VMEM) always passes, a pretend-chip refuses shapes
    whose K-bucket table can't fit — with a durable fallback event
    when the engine build hits the refusal."""
    assert pw.supports(32768, 65536, 128)  # interpret: no budget
    monkeypatch.setattr(pw, "_on_tpu", lambda: True)
    assert pw.supports(8192, 8192, 16)
    assert not pw.supports(32768, 65536, 128)  # 33MB table alone
    monkeypatch.setenv("GS_PALLAS_WINDOW", "on")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.delenv("GS_TRACE_DIR", raising=False)
    pw._reset_pallas_window()
    telemetry.reset()
    try:
        assert pw.maybe_window_body(32768, 65536, 128) is None
        evs = [r for r in telemetry.records()
               if r["name"] == "selection.fallback"
               and "vmem budget" in r["a"].get("error", "")]
        assert evs
    finally:
        pw._reset_pallas_window()
        telemetry.reset()


# ----------------------------------------------------------------------
# tiling layer + tuner family
# ----------------------------------------------------------------------
def test_resolve_tiles_pins_and_divisibility(monkeypatch):
    monkeypatch.setenv("GS_PALLAS_TILE", "64")
    monkeypatch.setenv("GS_PALLAS_CK", "16")
    tile, ck = pw.resolve_tiles(256, 32)
    assert (tile, ck) == (64, 16)
    monkeypatch.setenv("GS_PALLAS_TILE", "96")  # not a divisor
    tile, _ = pw.resolve_tiles(256, 32)
    assert 256 % tile == 0
    monkeypatch.delenv("GS_PALLAS_TILE")
    monkeypatch.delenv("GS_PALLAS_CK")
    tile, ck = pw.resolve_tiles(256, 32)
    assert 256 % tile == 0 and 8 <= ck <= 32


def test_tile_tuner_family(monkeypatch):
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    tuner = pw.tile_tuner(32768, 65536, 32)
    assert tuner.key == "pallas_window:eb=32768:vb=65536:kb=32"
    assert set(tuner.space) == {"tile_e", "ck"}
    for t in tuner.space["tile_e"]:
        assert 32768 % t == 0
    arm = tuner.next_round()
    tuner.record(arm, 32768, 0.5)
    assert tuner.best() in [dict(zip(tuner.space, v)) for v in
                            __import__("itertools").product(
                                *tuner.space.values())]


def test_explicit_tile_arm_parity(pallas_on):
    """A multi-tile grid (the chip shape) folds tile-by-tile and
    must match the whole-slab default bit-for-bit."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import scan_analytics as sa
    from gelly_streaming_tpu.ops import segment as seg_ops

    eb, vb, kb = 64, 64, 8
    src, dst = _stream(3 * eb, 50, seed=10)
    _w, s, d, valid = seg_ops.window_stack(src, dst, eb, sentinel=vb)

    def run(body):
        carry = (jnp.zeros(vb + 1, jnp.int32),
                 jnp.arange(vb + 1, dtype=jnp.int32),
                 jnp.arange(2 * (vb + 1), dtype=jnp.int32))
        c, ys = jax.jit(lambda c0, a, b, m: jax.lax.scan(
            body, c0, (a, b, m)))(carry, jnp.asarray(s),
                                  jnp.asarray(d), jnp.asarray(valid))
        return ([np.asarray(x) for x in c],
                [np.asarray(y) for y in ys])

    cx, yx = run(sa._build_scan(eb, vb, kb, pallas_ok=False))
    for tile in (16, 32, 64):
        ct, yt = run(pw.build_window_body(eb, vb, kb, tile_e=tile,
                                          chunk_k=8))
        assert all(np.array_equal(a, b) for a, b in zip(cx, ct))
        assert all(np.array_equal(a, b) for a, b in zip(yx, yt))


# ----------------------------------------------------------------------
# cost-model registration (the observatory acceptance)
# ----------------------------------------------------------------------
def test_cost_model_registers_single_slab_read(monkeypatch,
                                               pallas_on):
    from gelly_streaming_tpu.utils import costmodel

    monkeypatch.setenv("GS_COSTMODEL", "1")
    costmodel.reset()
    try:
        eng = StreamSummaryEngine(edge_bucket=256, vertex_bucket=256)
        assert eng._pallas
        rows = [r for r in costmodel.report()
                if r["program"] == "pallas_window"
                and r.get("model") == "analytic"]
        assert rows, "analytic megakernel entry not registered"
        # a dispatch must join the STATED model at its own span sig —
        # never a capture of the interpret lowering (review fix)
        eng.process(*_stream(256, 200, seed=1))
        sig_rows = [r for r in costmodel.report()
                    if r["program"] == "pallas_window"
                    and not r["sig"].startswith("eb=")]
        assert sig_rows, \
            "dispatch sig not instantiated from the analytic template"
        assert all(r.get("model") == "analytic" for r in sig_rows)
        row = rows[0]
        # the adoption story in one inequality: ONE slab read,
        # strictly below the scan-of-gathers' summed reads
        assert row["slab_bytes"] == pw.slab_bytes(256)
        assert row["bytes_accessed"] < row["scan_of_gathers_bytes"]
        assert row["scan_of_gathers_bytes"] \
            == pw.scan_of_gathers_bytes(256, 256)
        assert row["flops"] and row["bound"] in ("bytes", "flops")
        # and the summed gathers dominate BY the extra slab reads
        assert (row["scan_of_gathers_bytes"] - row["bytes_accessed"]
                >= 3 * pw.slab_bytes(256))
    finally:
        costmodel.reset()


def test_window_bytes_model_shapes():
    assert pw.slab_bytes(1024, compact=True) < pw.slab_bytes(1024)
    assert pw.window_bytes(1024, 512) \
        < pw.scan_of_gathers_bytes(1024, 512)
    # budget arithmetic is monotone in each dimension
    assert pw.vmem_window_bytes(1024, 512, 16) \
        < pw.vmem_window_bytes(2048, 512, 16) \
        < pw.vmem_window_bytes(2048, 1024, 32)


# ----------------------------------------------------------------------
# tenant-axis cohort megakernel (GS_COHORT_PALLAS)
# ----------------------------------------------------------------------
@pytest.fixture
def cohort_pallas_on(monkeypatch):
    monkeypatch.setenv("GS_COHORT_PALLAS", "on")
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    pw._reset_pallas_window()
    yield
    pw._reset_pallas_window()


def test_cohort_kernel_interpret_parity(cohort_pallas_on):
    """The tier-1 interpret-parity pin: the tenant-axis megakernel
    (tenant axis as a second grid dimension, whole cohort's carries
    VMEM-resident) reproduces N sequential single-stream engines
    exactly — ragged tails and pad rows included."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops import scan_analytics as sa

    eb, vb = 256, 256
    # the cohort program the dispatch will build must BE the kernel
    run = sa.build_cohort_scan(eb, vb, 16, nb=4)
    assert getattr(run, "pallas_window", False), \
        "cohort scan did not select the tenant-axis megakernel"

    streams = {}
    for i in range(3):
        n = 3 * eb - (17 if i == 2 else 0)
        streams["t%d" % i] = _stream(n, 200, seed=20 + i)
    want = {tid: StreamSummaryEngine(
                edge_bucket=eb, vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}
    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    got = {tid: [] for tid in streams}
    for tid in streams:
        co.admit(tid)
    for tid, (s, d) in streams.items():
        co.feed(tid, s, d)
    for tid, res in co.pump().items():
        got[tid].extend(res)
    for tid in streams:
        got[tid].extend(co.close(tid))
    assert got == want


def test_cohort_resolve_pins(monkeypatch):
    pw._reset_pallas_window()
    monkeypatch.setenv("GS_COHORT_PALLAS", "on")
    assert pw.resolve_cohort_pallas() is True
    monkeypatch.setenv("GS_COHORT_PALLAS", "off")
    assert pw.resolve_cohort_pallas() is False
    monkeypatch.delenv("GS_COHORT_PALLAS")
    pw._reset_pallas_window()


def test_cohort_resolve_evidence_gate_ignores_interpret(monkeypatch):
    """auto adopts only on committed NON-interpret cohort_pallas rows
    with parity AND ≥1.05x — interpret rows are parity evidence, not
    speed evidence, and must never flip the gate."""
    def fake_perf(rows):
        return lambda *a, **k: {"tenancy_ab": rows}

    winning = [{"probe": "cohort_pallas", "parity": True,
                "speedup": 1.4, "tenants": 8}]
    interp = [dict(winning[0], interpret=True)]
    losing = [dict(winning[0], speedup=1.01)]
    other = [{"probe": "cohort_serving", "parity": True,
              "speedup": 2.0, "tenants": 8}]
    monkeypatch.delenv("GS_COHORT_PALLAS", raising=False)
    for rows, want in ((winning, True), (interp, False),
                       (losing, False), (other, False), ([], False)):
        monkeypatch.setattr(tri_ops, "_load_matching_perf",
                            fake_perf(rows))
        pw._reset_pallas_window()
        assert pw.resolve_cohort_pallas() is want, rows
    pw._reset_pallas_window()


def test_cohort_vmem_budget_scales_with_rows(monkeypatch):
    """supports_cohort recomputes the DESIGN.md budget with N carry
    rows in flight: a shape a single tenant affords can refuse at
    cohort width, and refusal surfaces as a durable fallback (the
    dispatch degrades to the vmapped XLA scan, never dies)."""
    # interpret (off-chip): no budget, any width passes
    assert pw.supports_cohort(8192, 8192, 16, 64)
    monkeypatch.setattr(pw, "_on_tpu", lambda: True)
    assert pw.supports(8192, 8192, 16)
    assert pw.supports_cohort(8192, 8192, 16, 1)
    # 2 * 64 * carry_bytes(8192) alone is ~16.8MB > the 12MB budget
    assert not pw.supports_cohort(8192, 8192, 16, 64)
    # the cohort term is exactly N stacked carries over the single row
    assert (pw.cohort_vmem_window_bytes(8192, 8192, 16, 64)
            - pw.cohort_vmem_window_bytes(8192, 8192, 16, 1)
            == 2 * 63 * pw.carry_bytes(8192))
    monkeypatch.setenv("GS_COHORT_PALLAS", "on")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.delenv("GS_TRACE_DIR", raising=False)
    pw._reset_pallas_window()
    telemetry.reset()
    try:
        assert pw.maybe_cohort_body(8192, 8192, 16, 64) is None
        evs = [r for r in telemetry.records()
               if r["name"] == "selection.fallback"
               and r["a"].get("component") == "cohort_pallas"]
        assert evs and "vmem budget" in evs[0]["a"].get("error", "")
    finally:
        pw._reset_pallas_window()
        telemetry.reset()


def test_cohort_gate_default_off_is_vmapped_scan(pallas_unset,
                                                 monkeypatch):
    """GS_COHORT_PALLAS unset on a backend with no committed
    non-interpret rows: build_cohort_scan returns the vmapped XLA
    scan, bit-identical to today's default."""
    monkeypatch.delenv("GS_COHORT_PALLAS", raising=False)
    from gelly_streaming_tpu.ops import scan_analytics as sa

    run = sa.build_cohort_scan(256, 256, 16, nb=4)
    assert not getattr(run, "pallas_window", False)
    assert pw.maybe_cohort_body(256, 256, 16, 4) is None

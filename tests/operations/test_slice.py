"""Slice + neighborhood aggregation parity tests.

Golden outputs from the reference's TestSlice.java:81-229 — all 9 cases
({fold, reduce, apply} × {OUT, IN, ALL}) — each run through BOTH the
host UDF path and the device (JAX segment-kernel) path.
"""

import pytest

from gelly_streaming_tpu import (Edge, EdgeDirection, EdgesApply, EdgesFold,
                                 EdgesReduce, JaxEdgesApply, JaxEdgesFold,
                                 JaxEdgesReduce, SimpleEdgeStream, Time)

from ..conftest import long_long_edges, run_and_sort

FOLD_EXPECTED = {
    # reference TestSlice.java:81-121
    EdgeDirection.OUT: ["1,25", "2,23", "3,69", "4,45", "5,51"],
    EdgeDirection.IN: ["1,51", "2,12", "3,36", "4,34", "5,80"],
    EdgeDirection.ALL: ["1,76", "2,35", "3,105", "4,79", "5,131"],
}

APPLY_EXPECTED = {
    # reference TestSlice.java:189-229. Note: the reference file lists
    # "2,big" for ALL (TestSlice.java:226), which contradicts its own
    # fold-ALL golden "2,35" (TestSlice.java:118) — the apply iterator
    # (GraphWindowStream.java:157-159) exposes exactly the fold's
    # (neighbor, value) pairs, and 35 ≤ 50 ⇒ "small". The reference
    # harness never actually asserts the earlier tables (only the last
    # expectedResult assignment survives to postSubmit), so we pin the
    # self-consistent value here.
    EdgeDirection.OUT: ["1,small", "2,small", "3,big", "4,small", "5,big"],
    EdgeDirection.IN: ["1,big", "2,small", "3,small", "4,small", "5,big"],
    EdgeDirection.ALL: ["1,big", "2,small", "3,big", "4,big", "5,big"],
}

DIRECTIONS = [EdgeDirection.OUT, EdgeDirection.IN, EdgeDirection.ALL]


def _graph(env):
    return SimpleEdgeStream(env.from_collection(long_long_edges()), env)


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_fold_neighbors_host(env, direction):
    fold = EdgesFold(lambda acc, vid, nid, val: (vid, acc[1] + val))
    sums = _graph(env).slice(Time.seconds(1), direction).fold_neighbors(
        (0, 0), fold
    )
    assert run_and_sort(env, sums) == sorted(FOLD_EXPECTED[direction])


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_fold_neighbors_device(env, direction):
    import jax.numpy as jnp

    fold = JaxEdgesFold(
        init=(jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)),
        fn=lambda acc, vid, nid, val: (vid, acc[1] + val),
    )
    sums = _graph(env).slice(Time.seconds(1), direction).fold_neighbors(fold)
    assert run_and_sort(env, sums) == sorted(FOLD_EXPECTED[direction])


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_reduce_on_edges_host(env, direction):
    sums = _graph(env).slice(Time.seconds(1), direction).reduce_on_edges(
        EdgesReduce(lambda a, b: a + b)
    )
    assert run_and_sort(env, sums) == sorted(FOLD_EXPECTED[direction])


@pytest.mark.parametrize("direction", DIRECTIONS)
@pytest.mark.parametrize("spec", ["named", "generic", "associative"])
def test_reduce_on_edges_device(env, direction, spec):
    reduce_udf = (
        JaxEdgesReduce(name="sum") if spec == "named"
        else JaxEdgesReduce(fn=lambda a, b: a + b,
                            associative=(spec == "associative")))
    sums = _graph(env).slice(Time.seconds(1), direction).reduce_on_edges(reduce_udf)
    assert run_and_sort(env, sums) == sorted(FOLD_EXPECTED[direction])


def test_segmented_reduce_associative_matches_sequential():
    """The O(log E) flagged associative-scan tier agrees with the
    sequential arrival-order tier for associative fns — including a
    non-commutative one (take-right), which pins the arrival ORDER
    inside each segment, not just the multiset of values."""
    import jax.numpy as jnp
    import numpy as np

    from gelly_streaming_tpu.ops import segment as seg_ops

    rng = np.random.default_rng(5)
    n, n_seg = 999, 37
    seg = np.sort(rng.integers(0, n_seg, n)).astype(np.int32)
    vals = rng.integers(1, 100, n).astype(np.int32)
    for fn in (jnp.add, jnp.maximum, lambda a, b: b):  # b: take-right
        fast, fh = seg_ops.segmented_reduce_associative(
            fn, seg, vals, n_seg)
        slow, sh = seg_ops.segmented_reduce(fn, seg, vals, n_seg)
        np.testing.assert_array_equal(fh, sh)
        np.testing.assert_array_equal(fast[fh], np.asarray(slow)[sh])


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_apply_on_neighbors_host(env, direction):
    def classify(vid, neighbors, collect):
        total = sum(v for _n, v in neighbors)
        collect((vid, "big" if total > 50 else "small"))

    out = _graph(env).slice(Time.seconds(1), direction).apply_on_neighbors(
        EdgesApply(classify)
    )
    assert run_and_sort(env, out) == sorted(APPLY_EXPECTED[direction])


@pytest.mark.parametrize("direction", DIRECTIONS)
def test_apply_on_neighbors_device(env, direction):
    import jax.numpy as jnp

    apply_udf = JaxEdgesApply(
        fn=lambda vid, nbrs, vals, mask: jnp.sum(jnp.where(mask, vals, 0)),
        emit=lambda vid, row: (vid, "big" if row[0] > 50 else "small"),
    )
    out = _graph(env).slice(Time.seconds(1), direction).apply_on_neighbors(apply_udf)
    assert run_and_sort(env, out) == sorted(APPLY_EXPECTED[direction])


def test_multiple_windows_event_time(env):
    """Windowing splits neighborhoods by event time (Flink TimeWindow
    semantics: start = ts - ts % size; result ts = window end - 1)."""
    from gelly_streaming_tpu import AscendingTimestampExtractor

    edges = [Edge(1, 2, 10), Edge(1, 3, 20), Edge(1, 4, 120)]
    stream = SimpleEdgeStream(
        env.from_collection(edges), env,
        timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
    )
    sums = stream.slice(Time.milliseconds_of(100)).reduce_on_edges(
        EdgesReduce(lambda a, b: a + b)
    )
    assert run_and_sort(env, sums) == ["1,120", "1,30"]

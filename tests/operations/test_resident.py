"""Resident-state window megakernel (ops/resident_engine.py): exact
parity with the scan tier at engine and driver level, the ingest ring,
the GS_RESIDENT selection gate, the demotion ladder rung, the
re-key-instead-of-discard tuner contract on vertex-bucket growth (the
ISSUE-9 arm-freezing fix), and the observability ownership rules
(resident.superbatch spans at the drain, mark_window counted once,
gs_inflight_chunks covering the ring)."""

import numpy as np
import pytest

from gelly_streaming_tpu.core import driver as driver_mod
from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import resident_engine
from gelly_streaming_tpu.ops.resident_engine import (IngestRing,
                                                     ResidentState,
                                                     ResidentSummaryEngine)
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.utils import faults, metrics, resilience

pytestmark = pytest.mark.faults


def _stream(n=4096, v=384, seed=9):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, v, size=n).astype(np.int64),
            rng.integers(0, v, size=n).astype(np.int64))


def _key(results):
    return [(r.window_start, r.num_edges, r.vertex_ids.tolist(),
             None if r.degrees is None else r.degrees.tolist(),
             None if r.cc_labels is None else r.cc_labels.tolist(),
             None if r.bipartite_odd is None
             else r.bipartite_odd.tolist(),
             r.triangles)
            for r in results]


def _driver(tier, **kw):
    return StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=512, vertex_bucket=1024,
        snapshot_tier=tier, **kw)


# ----------------------------------------------------------------------
# parity
# ----------------------------------------------------------------------
def test_engine_parity_with_scan_tier():
    src, dst = _stream(n=2048, v=200)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)
    scan = StreamSummaryEngine(edge_bucket=256,
                               vertex_bucket=256).process(s32, d32)
    res = ResidentSummaryEngine(edge_bucket=256,
                                vertex_bucket=256).process(s32, d32)
    assert res == scan


def test_engine_parity_standard_wire():
    """The standard-wire fallback (vb too wide for uint16 would force
    it; here we pin it) matches the compact-fused default exactly."""
    src, dst = _stream(n=2048, v=200)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)
    compact = ResidentSummaryEngine(edge_bucket=256, vertex_bucket=256)
    standard = ResidentSummaryEngine(edge_bucket=256,
                                     vertex_bucket=256,
                                     ingress="standard")
    assert compact.ingress == "compact"
    assert standard.ingress == "standard"
    assert compact.process(s32, d32) == standard.process(s32, d32)


def test_driver_parity_and_chunked_calls():
    src, dst = _stream()
    full = _key(_driver("scan").run_arrays(src, dst))
    assert _key(_driver("resident").run_arrays(src, dst)) == full
    drv = _driver("resident")
    head = _key(drv.run_arrays(src[:2048], dst[:2048]))
    tail = _key(drv.run_arrays(src[2048:], dst[2048:]))
    assert head + tail == full


def test_driver_parity_delta_egress_and_deltas():
    src, dst = _stream(seed=11)
    kw = dict(emit_deltas=True)
    a = _driver("scan", egress="full", **kw)
    b = _driver("resident", egress="delta", **kw)
    ra, rb = a.run_arrays(src, dst), b.run_arrays(src, dst)
    assert _key(ra) == _key(rb)
    for x, y in zip(ra, rb):
        for f in ("delta_degrees", "delta_cc", "delta_bipartite"):
            dx, dy = getattr(x, f), getattr(y, f)
            assert np.array_equal(dx[0], dy[0])
            assert np.array_equal(dx[1], dy[1])


# ----------------------------------------------------------------------
# selection gate + ladder
# ----------------------------------------------------------------------
def test_resolve_resident_pins(monkeypatch):
    resident_engine._reset_resident()
    monkeypatch.setenv("GS_RESIDENT", "on")
    assert resident_engine.resolve_resident() is True
    monkeypatch.setenv("GS_RESIDENT", "off")
    assert resident_engine.resolve_resident() is False
    monkeypatch.delenv("GS_RESIDENT")
    resident_engine._reset_resident()


def test_resolve_resident_evidence_gate(monkeypatch):
    """auto adopts resident only when every committed driver row shows
    parity AND >=1.05x over the best alternative (scan and native)."""
    from gelly_streaming_tpu.ops import triangles as tri_ops

    def fake_perf(rows):
        return lambda *a, **k: {"resident_ab": rows}

    winning = [{"probe": "driver_resident", "parity": True,
                "resident_edges_per_s": 2_000_000,
                "scan_edges_per_s": 1_000_000,
                "native_edges_per_s": 1_500_000}]
    losing_to_native = [dict(winning[0],
                             native_edges_per_s=3_000_000)]
    monkeypatch.delenv("GS_RESIDENT", raising=False)
    monkeypatch.setattr(tri_ops, "_load_matching_perf",
                        fake_perf(winning))
    resident_engine._reset_resident()
    assert resident_engine.resolve_resident() is True
    monkeypatch.setattr(tri_ops, "_load_matching_perf",
                        fake_perf(losing_to_native))
    resident_engine._reset_resident()
    assert resident_engine.resolve_resident() is False
    resident_engine._reset_resident()


def test_resident_tier_resolution_flows_to_driver(monkeypatch):
    monkeypatch.setenv("GS_RESIDENT", "on")
    resident_engine._reset_resident()
    driver_mod._reset_snapshot_tier()
    try:
        assert driver_mod.resolve_snapshot_tier() == "resident"
    finally:
        monkeypatch.delenv("GS_RESIDENT")
        resident_engine._reset_resident()
        driver_mod._reset_snapshot_tier()


def test_resident_demotes_to_scan_with_parity():
    """A runtime failure on the resident rung demotes resident → scan
    mid-call (never INTO resident from above), and results stay exact.
    """
    src, dst = _stream()
    full = _key(_driver("scan").run_arrays(src, dst))
    drv = _driver("resident")
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        out = _key(drv.run_arrays(src, dst))
    assert out == full
    transitions = [(e["from"], e["to"]) for e in drv.demotion_log()]
    assert ("resident", "scan") in transitions
    assert not any(to == "resident" for _f, to in transitions)


def test_resident_checkpoint_carries_its_tuner(tmp_path,
                                               monkeypatch):
    """The resident tuner's state rides the driver checkpoint under
    its own key, beside (not inside) the scan tuner's."""
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    drv = _driver("resident")
    tuner = drv._ensure_resident_tuner()
    if tuner is None:
        pytest.skip("autotune disabled in this environment")
    tuner.record(tuner.best(), 1000, 0.01)
    state = drv.state_dict()
    assert state["autotune_resident"] == tuner.state_dict()
    drv2 = _driver("resident")
    drv2.load_state_dict(state)
    assert drv2._resident_tuner.state_dict() == tuner.state_dict()


# ----------------------------------------------------------------------
# the arm-freezing fix: vb growth re-keys instead of discarding
# ----------------------------------------------------------------------
def test_engine_growth_rekeys_tuner_and_keeps_parity(monkeypatch):
    """ResidentSummaryEngine.grow_vertex_bucket migrates the carried
    ResidentState to the wider bucket (parity pinned) AND re-keys the
    live tuner — round counter and learned state survive into the new
    key instead of freezing at the dead one."""
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    src, dst = _stream(n=2048, v=200)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)
    full = ResidentSummaryEngine(edge_bucket=256,
                                 vertex_bucket=512).process(s32, d32)

    eng = ResidentSummaryEngine(edge_bucket=256, vertex_bucket=256)
    head = eng.process(s32[:1024], d32[:1024])
    tuner = eng._ensure_tuner()
    tuner.record(tuner.best(), 1000, 0.01)
    rounds_before = tuner.state_dict()["round"]
    old_key = tuner.key
    assert rounds_before >= 1

    eng.grow_vertex_bucket(512)
    assert eng.vb == 512
    # same tuner OBJECT, new identity, learned state carried
    assert eng._tuner is tuner
    assert tuner.key != old_key
    assert "vb=512" in tuner.key
    assert tuner.state_dict()["round"] == rounds_before
    assert eng.process(s32[1024:], d32[1024:]) == full[4:]
    assert head == full[:4]


def test_driver_growth_rekeys_resident_tuner(monkeypatch):
    """The driver's bucket growth re-keys the resident tuner with the
    same re-key-instead-of-discard contract as the scan tuner."""
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    drv = _driver("resident")
    tuner = drv._ensure_resident_tuner()
    if tuner is None:
        pytest.skip("autotune disabled in this environment")
    tuner.record(tuner.best(), 1000, 0.01)
    rounds = tuner.state_dict()["round"]
    old_key = tuner.key
    src, dst = _stream(n=4096, v=2000, seed=3)  # forces vb growth
    drv.run_arrays(src, dst)
    assert drv.vb > 1024
    assert drv._resident_tuner is tuner
    assert tuner.key != old_key
    assert str(drv.vb) in tuner.key
    assert tuner.state_dict()["round"] >= rounds


def test_engine_growth_past_uint16_repins_ingress(monkeypatch):
    """Growing past the uint16 ceiling switches the fused decode to
    the standard wire — the re-keyed tuner must re-pin its ingress
    arm with it (a surviving compact arm would be lossy)."""
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    eng = ResidentSummaryEngine(edge_bucket=256, vertex_bucket=65536)
    assert eng.ingress == "compact"
    tuner = eng._ensure_tuner()
    tuner.record(tuner.best(), 1000, 0.01)
    eng.grow_vertex_bucket(2 * 65536)
    assert eng.ingress == "standard"
    assert tuner.space["ingress"] == ["standard"]
    assert tuner.incumbent["ingress"] == "standard"


def test_engine_growth_preserves_ingress_pin(monkeypatch):
    """An explicit construction-time ingress pin survives bucket
    growth — the rebuild must keep measuring the wire the caller
    pinned (and keep the tuner frozen to it), not re-resolve. A pinned
    compact wire that turns lossy at the new bucket degrades to
    standard instead of raising."""
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    eng = ResidentSummaryEngine(edge_bucket=64, vertex_bucket=256,
                                ingress="standard")
    assert eng._pinned_ingress
    eng.grow_vertex_bucket(512)
    # an unpinned rebuild would re-resolve to compact (512 fits uint16)
    assert eng.ingress == "standard"
    assert eng._pinned_ingress
    # pinned compact grown past uint16: degrade, don't raise
    eng2 = ResidentSummaryEngine(edge_bucket=64, vertex_bucket=1024,
                                 ingress="compact")
    eng2.grow_vertex_bucket(2 * 65536)
    assert eng2.ingress == "standard"
    assert eng2._pinned_ingress


def test_pipeline_inflight_narrows_not_replaces(monkeypatch):
    """The ring's `inflight` narrows the look-ahead BELOW the global
    GS_PIPELINE_INFLIGHT bound; it can never raise it above the
    operator's ceiling."""
    from gelly_streaming_tpu.ops import ingress_pipeline as ip

    import threading

    monkeypatch.setenv("GS_PIPELINE_INFLIGHT", "2")
    lock = threading.Lock()
    state = {"started": 0, "dispatched": 0, "peak": 0}

    def prep(it):
        with lock:
            state["started"] += 1
            state["peak"] = max(
                state["peak"],
                state["started"] - state["dispatched"])
        return it

    def dispatch(d):
        with lock:
            state["dispatched"] += 1
        return d

    seen = []
    items = list(range(8))
    ip.run_pipeline(items, prep=prep, h2d=lambda p: p,
                    dispatch=dispatch,
                    finalize=lambda r: seen.append(r),
                    inflight=6)
    assert seen == items  # order preserved under the narrowed bound
    # lookahead must be min(6, GS_PIPELINE_INFLIGHT=2), not 6: one
    # extra slot covers the pop→dispatch→refill race window
    assert state["peak"] <= 3


def test_resident_state_grow_layout():
    st = ResidentState.fresh(4)
    st.degrees[:4] = [3, 1, 0, 2]
    st.labels[:4] = [0, 0, 2, 2]
    # cover: (+) side joined across to (−) side for vertex 1: label
    # points into the (−) half (>= vb) and must shift with it
    st.cover[1] = 4 + 1 + 0  # old (−)0 slot
    grown = ResidentState.grow(st, 4, 8)
    assert grown.degrees[:4].tolist() == [3, 1, 0, 2]
    assert grown.degrees[4:].tolist() == [0] * 5
    assert grown.labels[:4].tolist() == [0, 0, 2, 2]
    assert grown.labels[4:].tolist() == [4, 5, 6, 7, 8]
    assert grown.cover[1] == 8 + 1 + 0  # shifted with the (−) half
    assert grown.cover[8] == 8  # sentinel identity


# ----------------------------------------------------------------------
# ingest ring
# ----------------------------------------------------------------------
def test_ingest_ring_bounds_and_order():
    ring = IngestRing(slots=2)
    done = []
    for i in range(3):
        ok = ring.submit(lambda item: done.append(item) or item, i, i)
        if not ok and len(ring) == 0:
            pytest.skip("ingress pipelining disabled here")
        if i < 2:
            assert ok
        else:
            assert not ok  # full at 2 slots
    assert len(ring) == 2 and ring.full
    assert ring.pop(1) is None  # FIFO: head is 0
    fut, item = ring.pop(0)
    assert fut.result() == 0 and item == 0
    ring.drain()
    assert len(ring) == 0


def test_ring_slots_knob(monkeypatch):
    monkeypatch.setenv("GS_RESIDENT_SLOTS", "5")
    assert resident_engine.ring_slots() == 5
    assert IngestRing().slots == 5
    monkeypatch.setenv("GS_RESIDENT_SLOTS", "0")  # clamped at lo=1
    assert resident_engine.ring_slots() == 1


def test_superbatch_knob(monkeypatch):
    monkeypatch.setenv("GS_RESIDENT_SPB", "100")
    # bucketed to a power of two
    assert resident_engine.resident_spb(4096) == 128
    eng = ResidentSummaryEngine(edge_bucket=256, vertex_bucket=256)
    assert eng.MAX_WINDOWS == 128


# ----------------------------------------------------------------------
# observability ownership
# ----------------------------------------------------------------------
def test_superbatch_spans_and_single_marks(monkeypatch):
    """One resident.superbatch span per super-batch drain, windows
    marked exactly once (the owner rule), and the ring feeding the
    gs_inflight_chunks gauge."""
    from gelly_streaming_tpu.utils import telemetry

    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_METRICS", "1")
    # several super-batches, so the ingest ring actually cycles (a
    # single-superbatch call never submits to the ring at all; spb
    # buckets have a floor of 8 — seg_ops.bucket_size)
    monkeypatch.setenv("GS_RESIDENT_SPB", "8")
    telemetry.reset()
    metrics.reset()
    try:
        src, dst = _stream(n=8192)
        out = _driver("resident").run_arrays(src, dst)
        spans = [r for r in telemetry.records()
                 if r.get("t") == "span"
                 and r.get("name") == "resident.superbatch"]
        assert spans, "no resident.superbatch span recorded"
        assert sum((s.get("a") or {}).get("windows", 0)
                   for s in spans) == len(out)
        snap = metrics.health_snapshot()
        assert snap["windows_finalized"] == len(out)
        gauges = {name: v for (name, _l), v in metrics.gauges().items()}
        assert "gs_inflight_chunks" in gauges
    finally:
        telemetry.reset()
        metrics.reset()


def test_resident_metrics_tier_label(monkeypatch):
    monkeypatch.setenv("GS_METRICS", "1")
    metrics.reset()
    try:
        src, dst = _stream()
        _driver("resident").run_arrays(src, dst)
        tiers = {dict(labels).get("tier")
                 for (name, labels), _v in metrics.counters().items()
                 if name == "gs_windows_finalized_total"}
        assert "resident" in tiers
    finally:
        metrics.reset()


def test_mesh_refuses_resident_pin():
    with pytest.raises(ValueError, match="single-chip"):
        StreamingAnalyticsDriver(window_ms=0, mesh=object(),
                                 snapshot_tier="resident")


def test_donation_config_matches_backend():
    import jax

    kw = resident_engine.donate_kw()
    if jax.default_backend() in ("tpu", "gpu", "cuda", "rocm"):
        assert kw == {"donate_argnums": (0,)}
    else:
        assert kw == {}

"""Delta-compacted d2h egress (ops/delta_egress.py): bit-identical to
full-vector egress window-by-window across tiers, through the
cap-overflow host refold, a mid-stream tier demotion, and a
checkpoint kill→resume; plus the resolve_egress adoption gate."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import delta_egress
from gelly_streaming_tpu.ops.windowed_reduce import WindowedEdgeReduce
from gelly_streaming_tpu.utils import faults, resilience


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.setenv("GS_AUTOTUNE", "0")  # egress in isolation
    monkeypatch.delenv("GS_EGRESS", raising=False)
    monkeypatch.delenv("GS_EGRESS_CAP", raising=False)
    delta_egress._reset_egress()
    yield
    delta_egress._reset_egress()


def _stream(n=6144, v=700, seed=5):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, v, size=n).astype(np.int64),
            rng.integers(0, v, size=n).astype(np.int64))


def _snap_key(results):
    return [(r.window_start, r.num_edges,
             None if r.triangles is None else int(r.triangles),
             None if r.degrees is None else r.degrees.tolist(),
             None if r.cc_labels is None else r.cc_labels.tolist(),
             None if r.bipartite_odd is None
             else r.bipartite_odd.tolist(),
             None if r.delta_degrees is None
             else [a.tolist() for a in r.delta_degrees],
             None if r.delta_cc is None
             else [a.tolist() for a in r.delta_cc],
             None if r.delta_bipartite is None
             else [a.tolist() for a in r.delta_bipartite])
            for r in results]


def _driver(**kw):
    kw.setdefault("analytics", ("degrees", "cc", "bipartite"))
    kw.setdefault("emit_deltas", True)
    return StreamingAnalyticsDriver(window_ms=0, edge_bucket=512,
                                    vertex_bucket=1024, **kw)


# ----------------------------------------------------------------------
# driver snapshot egress
# ----------------------------------------------------------------------
@pytest.mark.parametrize("emit_deltas", [False, True])
def test_delta_equals_full_window_by_window(emit_deltas):
    src, dst = _stream()
    want = _snap_key(_driver(
        snapshot_tier="scan", egress="full",
        emit_deltas=emit_deltas).run_arrays(src, dst))
    got = _snap_key(_driver(
        snapshot_tier="scan", egress="delta",
        emit_deltas=emit_deltas).run_arrays(src, dst))
    assert got == want


def test_cap_overflow_refolds_on_host_bit_exactly(monkeypatch):
    """A changed-set wider than the wire cap routes the chunk to the
    bit-exact host fold — results identical at ANY cap."""
    src, dst = _stream(seed=6)
    want = _snap_key(_driver(snapshot_tier="scan",
                             egress="full").run_arrays(src, dst))
    monkeypatch.setenv("GS_EGRESS_CAP", "8")  # absurdly tight: every
    got = _driver(snapshot_tier="scan",      # chunk overflows
                  egress="delta").run_arrays(src, dst)
    assert _snap_key(got) == want


def test_delta_matches_across_tiers():
    """The host tier (and native, when the library exports the
    symbol) produces the same windows as the delta-egress scan."""
    src, dst = _stream(seed=7)
    want = _snap_key(_driver(snapshot_tier="scan",
                             egress="delta").run_arrays(src, dst))
    host = _snap_key(_driver(snapshot_tier="host").run_arrays(src, dst))
    assert host == want
    from gelly_streaming_tpu import native

    if native.snapshot_available():
        nat = _snap_key(_driver(
            snapshot_tier="native").run_arrays(src, dst))
        assert nat == want


def test_delta_survives_mid_stream_demotion():
    """A persistent device failure demotes scan→native/host MID-STREAM
    while delta egress is live: the mirrors the delta decode maintains
    must hand the next tier exact carried state."""
    resilience.reset_demotions()
    src, dst = _stream(seed=8)
    want = _snap_key(_driver(snapshot_tier="scan",
                             egress="full").run_arrays(src, dst))
    drv = _driver(snapshot_tier="scan", egress="delta")
    # three calls: the first decodes deltas cleanly; the second's
    # dispatch fails persistently (demotes scan→native/host off the
    # delta-maintained mirrors); the third runs on the demoted tier
    cut1, cut2 = 4 * 512, 8 * 512
    got = drv.run_arrays(src[:cut1], dst[:cut1])
    with faults.inject(faults.FaultSpec(site="dispatch", on_call=1)):
        got += drv.run_arrays(src[cut1:cut2], dst[cut1:cut2])
    got += drv.run_arrays(src[cut2:], dst[cut2:])
    assert _snap_key(got) == want
    assert drv.demotion_log(), "the fault never demoted — the test " \
        "exercised nothing"


def test_delta_checkpoint_kill_resume(tmp_path):
    src, dst = _stream(seed=9)
    want = _snap_key(_driver(snapshot_tier="scan",
                             egress="full").run_arrays(src, dst))
    path = str(tmp_path / "edges.txt")
    with open(path, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            f.write("%d %d\n" % (s, d))
    ckpt = str(tmp_path / "ck.npz")
    drv = _driver(snapshot_tier="scan", egress="delta")
    drv.enable_auto_checkpoint(ckpt, every_n_windows=3)
    got = {}
    try:
        with faults.inject(faults.FaultSpec(site="dispatch",
                                            on_call=3, fatal=True)):
            for r in drv.stream_file(path, chunk_bytes=1 << 14):
                got[r.window_start] = r
    except faults.InjectedFault:
        pass
    drv2 = _driver(snapshot_tier="scan", egress="delta")
    assert drv2.try_resume(ckpt)
    for r in drv2.stream_file(path, chunk_bytes=1 << 14,
                              resume=True):
        got[r.window_start] = r  # at-least-once: keep last
    final = [got[k] for k in sorted(got)]
    assert _snap_key(final) == want


def test_degree_overflow_still_detected_under_delta():
    """The int32 width guard must fire from the delta wire's changed
    values exactly like the full snapshot's min() check."""
    drv = StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=8, vertex_bucket=16,
        analytics=("degrees",), snapshot_tier="scan", egress="delta")
    # seed the mirror just under the cliff, then two more windows
    drv._degrees = np.array([2**31 - 2], np.int64)
    drv.interner.intern_array(np.array([7]))
    src = np.zeros(16, np.int64) + 7
    with pytest.raises(OverflowError):
        drv.run_arrays(src, src)


# ----------------------------------------------------------------------
# windowed reduce egress
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name", ["sum", "min", "max"])
@pytest.mark.parametrize("direction", ["out", "all"])
def test_reduce_delta_equals_full(name, direction):
    src, dst = _stream(4096, 2000, seed=11)
    val = (1 + (src + 3 * dst) % 97).astype(np.int32)

    def rows(egress, ingress=None):
        eng = WindowedEdgeReduce(
            vertex_bucket=2048, edge_bucket=256, name=name,
            direction=direction, egress=egress, ingress=ingress)
        return eng._device_process_stream(src, dst, val)

    full = rows("full")
    delta = rows("delta")
    assert len(full) == len(delta)
    for (c0, n0), (c1, n1) in zip(full, delta):
        np.testing.assert_array_equal(np.asarray(c0), c1)
        np.testing.assert_array_equal(np.asarray(n0), n1)
    # the delta egress composes with compact ingress (both wires live)
    compact = rows("delta", ingress="compact")
    for (c0, n0), (c1, n1) in zip(full, compact):
        np.testing.assert_array_equal(np.asarray(c0), c1)
        np.testing.assert_array_equal(np.asarray(n0), n1)


# ----------------------------------------------------------------------
# the adoption gate
# ----------------------------------------------------------------------
def test_resolve_egress_defaults_full_and_honors_pin(monkeypatch):
    delta_egress._reset_egress()
    assert delta_egress.resolve_egress() in ("full", "delta")
    monkeypatch.setenv("GS_EGRESS", "delta")
    assert delta_egress.resolve_egress() == "delta"
    monkeypatch.setenv("GS_EGRESS", "full")
    assert delta_egress.resolve_egress() == "full"


def test_resolve_egress_requires_clearing_rows(monkeypatch):
    from gelly_streaming_tpu.ops import triangles as tri_ops

    def fake_perf(rows):
        return lambda *a, **k: {"egress_ab": rows}

    delta_egress._reset_egress()
    monkeypatch.setattr(tri_ops, "_load_matching_perf", fake_perf([
        {"probe": "driver_ab", "parity": True, "speedup": 1.2},
        {"probe": "reduce_ab", "parity": True, "speedup": 1.07}]))
    assert delta_egress.resolve_egress() == "delta"
    delta_egress._reset_egress()
    monkeypatch.setattr(tri_ops, "_load_matching_perf", fake_perf([
        {"probe": "driver_ab", "parity": True, "speedup": 1.2},
        {"probe": "reduce_ab", "parity": True, "speedup": 1.02}]))
    assert delta_egress.resolve_egress() == "full"
    delta_egress._reset_egress()
    monkeypatch.setattr(tri_ops, "_load_matching_perf", fake_perf([
        {"probe": "driver_ab", "parity": False, "speedup": 9.9}]))
    assert delta_egress.resolve_egress() == "full"


def test_egress_cap_bounds(monkeypatch):
    assert delta_egress.egress_cap(256, 4096) == 512
    assert delta_egress.egress_cap(4096, 1024) == 1024
    monkeypatch.setenv("GS_EGRESS_CAP", "64")
    assert delta_egress.egress_cap(256, 4096) == 64
    monkeypatch.setenv("GS_EGRESS_CAP", "999999")
    assert delta_egress.egress_cap(256, 4096) == 4096  # clamped to vb

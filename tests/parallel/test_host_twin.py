"""Host twins of the sharded engines (parallel/host_twin) — bit-exact
parity with their mesh originals on the virtual CPU mesh, plus the
gathered-state hand-off that makes them the mesh's demotion floor.
Outputs (and the `[:vb]` meaningful state) are the parity surface;
carry SENTINEL slots are deliberately excluded where documented
(they absorb call-pattern-dependent padding in every engine)."""

import numpy as np
import pytest

from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.parallel.host_twin import (
    HostSummaryEngine, HostTriangleWindowKernel, HostWindowEngine)
from gelly_streaming_tpu.parallel.mesh import make_mesh
from gelly_streaming_tpu.parallel.sharded import (
    ShardedSummaryEngine, ShardedTriangleWindowKernel,
    ShardedWindowEngine)


def _edges(rng, v, n):
    return (rng.integers(0, v, n).astype(np.int32),
            rng.integers(0, v, n).astype(np.int32))


def test_window_engine_twin_matches_sharded():
    rng = np.random.default_rng(3)
    vb = 64
    sh = ShardedWindowEngine(make_mesh(4), num_vertices_bucket=vb)
    tw = HostWindowEngine(num_vertices_bucket=vb)
    for _ in range(3):  # carried state across windows
        s, d = _edges(rng, 50, 200)
        np.testing.assert_array_equal(sh.degrees(s, d),
                                      tw.degrees(s, d))
        np.testing.assert_array_equal(sh.cc_labels(s, d),
                                      tw.cc_labels(s, d))
        for a, b in zip(sh.bipartite(s, d), tw.bipartite(s, d)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_window_engine_state_hands_off_both_ways():
    """Mid-stream demotion shape: gathered sharded state → twin, twin
    state → a fresh sharded engine; both continue identically."""
    rng = np.random.default_rng(4)
    vb = 64
    sh = ShardedWindowEngine(make_mesh(4), num_vertices_bucket=vb)
    s, d = _edges(rng, 50, 300)
    sh.degrees(s, d)
    sh.cc_labels(s, d)
    sh.bipartite(s, d)

    tw = HostWindowEngine.from_sharded(sh)
    s2, d2 = _edges(rng, 50, 300)
    np.testing.assert_array_equal(sh.cc_labels(s2, d2),
                                  tw.cc_labels(s2, d2))
    np.testing.assert_array_equal(sh.degrees(s2, d2),
                                  tw.degrees(s2, d2))

    # twin → mesh: the re-promotion direction (twin state carries the
    # extra windows the sharded engine above also folded)
    back = ShardedWindowEngine(make_mesh(2), num_vertices_bucket=vb)
    back.load_state_dict(tw.state_dict())
    s3, d3 = _edges(rng, 50, 200)
    np.testing.assert_array_equal(back.cc_labels(s3, d3),
                                  tw.cc_labels(s3, d3))


def test_triangle_kernel_twin_matches_sharded():
    rng = np.random.default_rng(5)
    k = ShardedTriangleWindowKernel(make_mesh(4), edge_bucket=256,
                                    vertex_bucket=64)
    tw = HostTriangleWindowKernel.from_sharded(k)
    assert (tw.eb, tw.vb) == (k.eb, k.vb)  # identical window cuts
    s, d = _edges(rng, 60, 1000)
    assert k.count_stream(s, d) == tw.count_stream(s, d)
    wins = [(_edges(rng, 60, n)) for n in (5, 100, 256)]
    assert k.count_windows(wins) == tw.count_windows(wins)
    with pytest.raises(ValueError, match="exceeds edge bucket"):
        tw.count(np.zeros(tw.eb + 1, np.int32),
                 np.ones(tw.eb + 1, np.int32))


def test_summary_twin_matches_both_engines():
    """HostSummaryEngine == StreamSummaryEngine == ShardedSummaryEngine
    summary-for-summary, including a hub-overflow window (the sharded
    path recounts it; the host fold is exact outright)."""
    rng = np.random.default_rng(23)
    n, v, eb = 1536, 200, 256
    src, dst = _edges(rng, v, n)
    # splice a 30-clique into window 2 to force a sharded K overflow
    cl_s, cl_d = [], []
    for u in range(1, 31):
        for w in range(u + 1, 31):
            cl_s.append(u)
            cl_d.append(w)
    src[2 * eb:2 * eb + len(cl_s[:eb])] = cl_s[:eb]
    dst[2 * eb:2 * eb + len(cl_d[:eb])] = cl_d[:eb]

    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=v).process(src, dst)
    host = HostSummaryEngine(edge_bucket=eb, vertex_bucket=v)
    assert host.process(src, dst) == want
    sh = ShardedSummaryEngine(make_mesh(4), edge_bucket=eb,
                              vertex_bucket=v, k_bucket=8)
    assert sh.process(src, dst) == want
    # the twins' visible state agrees too
    hd, hl, ho = host.state()
    sd, sl, so = sh.state()
    np.testing.assert_array_equal(hd[:v], sd[:v])
    np.testing.assert_array_equal(hl[:v], sl[:v])
    np.testing.assert_array_equal(ho[:v], so[:v])


def test_summary_twin_resumes_sharded_mid_stream():
    """The demotion hand-off: fold half the stream on the mesh, hand
    the gathered carry to the twin, continue — combined summaries
    equal the uninterrupted single-chip run."""
    rng = np.random.default_rng(11)
    eb, v = 256, 200
    src, dst = _edges(rng, v, 2048)
    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=v).process(src, dst)
    sh = ShardedSummaryEngine(make_mesh(4), edge_bucket=eb,
                              vertex_bucket=v)
    head = sh.process(src[:1024], dst[:1024])
    tw = HostSummaryEngine.from_sharded(sh)
    off = tw.resume_offset()
    assert off == 1024
    tail = tw.process(src[off:], dst[off:])
    assert head + tail == want


def test_summary_twin_needs_no_device_dispatch(monkeypatch):
    """The twin must stay a pure-host path (it exists for sessions
    whose device/mesh is DEAD): compute the oracle first, then poison
    the jax dispatch entry points and run the twin through a full
    stream, checkpoint save included."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    eb, v = 128, 100
    src, dst = _edges(rng, v, 512)
    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=v).process(src, dst)

    def boom(*a, **k):
        raise AssertionError("host twin dispatched to the device")

    monkeypatch.setattr(jax, "device_put", boom)
    monkeypatch.setattr(jax, "jit", boom)
    monkeypatch.setattr(jnp, "asarray", boom)
    tw = HostSummaryEngine(edge_bucket=eb, vertex_bucket=v)
    assert tw.process(src, dst) == want
    state = tw.state_dict()  # the gather is host-side too
    tw2 = HostSummaryEngine.from_state(state)
    assert tw2.windows_done == tw.windows_done

"""Worker for the 2-process CPU jax.distributed smoke test.

Launched (twice) by test_sharded.py::test_multihost_two_process_smoke.
Executes the multi-process branches of parallel/multihost.py that a
single-process test can never reach: initialize_runtime,
make_hybrid_mesh(process_is_granule=True) with the granule-contiguity
check, and one sharded degree window over the flattened hybrid mesh
(the DCN-crossing psum of SURVEY.md §5.8).

Usage: _multihost_worker.py <process_id> <num_processes> <port>
Prints "MULTIHOST_OK <process_id>" on success.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> None:
    proc_id, nprocs, port = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3])
    from gelly_streaming_tpu.parallel.multihost import (
        flatten_for_edges, initialize_runtime, make_hybrid_mesh)

    initialize_runtime(coordinator_address=f"localhost:{port}",
                       num_processes=nprocs, process_id=proc_id)
    import jax
    import numpy as np

    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.device_count() == 4 * nprocs, jax.device_count()

    mesh = make_hybrid_mesh()  # defaults: one DCN granule per process
    assert mesh.shape == {"dcn": nprocs, "shard": 4}, mesh.shape
    flat = flatten_for_edges(mesh)

    from gelly_streaming_tpu.parallel.sharded import (
        make_sharded_degree_fn)
    from gelly_streaming_tpu.parallel.mesh import pad_edges_for_mesh

    vb = 16
    degree_fn = make_sharded_degree_fn(flat, vb)
    # one window: a ring over vertices 0..9 — every vertex degree 2
    src = np.arange(10, dtype=np.int32)
    dst = ((np.arange(10) + 1) % 10).astype(np.int32)
    s, d = pad_edges_for_mesh(src, dst, flat, sentinel=vb + 1)

    # every process holds the whole window; lift host copies into
    # GLOBAL arrays spanning both processes' devices (the multi-host
    # ingestion contract: addressable shards are filled from local data)
    from jax.sharding import NamedSharding, PartitionSpec as P

    def global_array(host, spec):
        sharding = NamedSharding(flat, spec)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    zeros = np.zeros(vb + 2, np.int32)
    counts = degree_fn(global_array(s, P("shard")),
                       global_array(d, P("shard")),
                       global_array(zeros, P()))
    # out_spec P() → fully replicated: every process reads the result
    got = np.asarray(counts)[:10]
    np.testing.assert_array_equal(got, np.full(10, 2))

    # the FULL window triangle pipeline across the process boundary —
    # every collective class the framework uses crosses the simulated
    # DCN here: psum (degrees/count), all_to_all (pair exchange), and
    # pmax table merge / all_gather+all_to_all row exchange (both
    # neighbor-row distribution modes)
    from gelly_streaming_tpu.parallel.sharded import (
        make_sharded_window_triangle_fn)

    ta = np.resize(np.array([0, 0, 1, 1, 2, 0, 3], np.int32), 16)
    tb = np.resize(np.array([1, 2, 2, 3, 3, 1, 3], np.int32), 16)
    tvalid = np.ones(16, bool)
    for table in ("replicated", "owner"):
        tri_fn = make_sharded_window_triangle_fn(
            flat, eb=16, vb=16, kb=8, cap=8, table=table)
        count, b_ovf, k_ovf = tri_fn(
            global_array(ta, P("shard")), global_array(tb, P("shard")),
            global_array(tvalid, P("shard")))
        count, b_ovf, k_ovf = (int(np.asarray(x))
                               for x in (count, b_ovf, k_ovf))
        assert (count, b_ovf, k_ovf) == (2, 0, 0), (table, count,
                                                    b_ovf, k_ovf)

    print(f"MULTIHOST_OK {proc_id}", flush=True)


if __name__ == "__main__":
    main()

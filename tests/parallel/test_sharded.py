"""Multi-chip kernels on the virtual 8-device CPU mesh — the sharding
analog of the reference's in-process mini-cluster tests (SURVEY.md §4:
same dataflow, multiple subtasks, one process).
"""

import numpy as np
import pytest

import jax

from gelly_streaming_tpu.parallel.mesh import make_mesh, shard_count
from gelly_streaming_tpu.parallel.sharded import (
    ShardedTriangleWindowKernel, ShardedWindowEngine)
from gelly_streaming_tpu.ops import segment as seg_ops
from gelly_streaming_tpu.ops import triangles as tri_ops


@pytest.fixture(scope="module")
def engine():
    mesh = make_mesh()
    assert shard_count(mesh) == 8, "conftest should provide 8 CPU devices"
    return ShardedWindowEngine(mesh, num_vertices_bucket=64)


def test_sharded_degrees_match_host(engine):
    rng = np.random.default_rng(0)
    src = rng.integers(0, 50, 333)
    dst = rng.integers(0, 50, 333)
    out = engine.degrees(src, dst)
    expected = (np.bincount(src, minlength=64)
                + np.bincount(dst, minlength=64))
    np.testing.assert_array_equal(out, expected)
    # second window accumulates (continuous-degree semantics)
    out2 = engine.degrees(src, dst)
    np.testing.assert_array_equal(out2, 2 * expected)


def test_sharded_cc_labels(engine):
    # two components: 0-1-2-3 chain, 10-11
    src = np.array([0, 1, 2, 10])
    dst = np.array([1, 2, 3, 11])
    labels = engine.cc_labels(src, dst, carry=False)
    assert labels[0] == labels[1] == labels[2] == labels[3] == 0
    assert labels[10] == labels[11] == 10
    # carried state: bridging edge merges components (P5 iteration)
    labels = engine.cc_labels(np.array([3]), np.array([10]), carry=True)
    assert labels[11] == 0


def test_sharded_triangles_match_single_chip(engine):
    rng = np.random.default_rng(3)
    n, e = 40, 300
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    expected = tri_ops.triangle_count_sparse(src, dst, n)

    # build the oriented CSR exactly as the single-chip path does
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    keep = lo != hi
    lo, hi = lo[keep], hi[keep]
    und = np.unique(lo * n + hi)
    lo, hi = und // n, und % n
    deg = np.bincount(np.concatenate([lo, hi]), minlength=n)
    rank = np.argsort(np.argsort(deg.astype(np.int64) * n + np.arange(n)))
    a = np.where(rank[lo] < rank[hi], lo, hi).astype(np.int32)
    b = np.where(rank[lo] < rank[hi], hi, lo).astype(np.int32)
    order = np.argsort(a.astype(np.int64) * n + b, kind="stable")
    a, b = a[order], b[order]
    counts = np.bincount(a, minlength=n)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    vb = seg_ops.bucket_size(n)
    max_out = seg_ops.bucket_size(int(counts.max()))
    nbr = np.full((vb + 1, max_out), vb, np.int32)
    nbr[a, np.arange(len(a)) - starts[a]] = b

    got = engine.triangles(nbr, a, b, np.ones(len(a), bool))
    assert got == expected


def test_sharded_window_pipeline_from_raw_coo():
    """The full sharded pipeline (orient → all_to_all exchange → dedupe
    → distributed CSR → intersect) = single-chip kernel = host path,
    from raw COO with duplicates, self-loops, and ragged padding."""
    mesh = make_mesh()
    k = ShardedTriangleWindowKernel(mesh, edge_bucket=1024,
                                    vertex_bucket=128)
    single = tri_ops.TriangleWindowKernel(edge_bucket=1024,
                                          vertex_bucket=128)
    for seed in range(4):
        rng = np.random.default_rng(seed)
        e = int(rng.integers(10, 1000))
        src = rng.integers(0, 100, e)
        dst = rng.integers(0, 100, e)
        expected = tri_ops.triangle_count_sparse(src, dst, 128)
        assert k.count(src, dst) == expected
        assert single.count(src, dst) == expected
    assert k.count(np.array([], np.int64), np.array([], np.int64)) == 0


def test_sharded_window_pipeline_escalates_on_hub_overflow():
    """A clique hub overflows the kb/n column slice; the kernel must
    escalate (wider K / capacity, then host path) and stay exact."""
    mesh = make_mesh()
    k = ShardedTriangleWindowKernel(mesh, edge_bucket=1024,
                                    vertex_bucket=128, k_bucket=8)
    src, dst = [], []
    for u in range(1, 41):
        for v in range(u + 1, 41):
            src.append(u)
            dst.append(v)
    src, dst = np.array(src), np.array(dst)
    assert k.count(src, dst) == tri_ops.triangle_count_sparse(src, dst, 128)


def test_sharded_count_stream_matches_per_window():
    """Sharded batched lax.map streaming = per-window sharded counts =
    host path, with a ragged tail and an overflowing clique window."""
    mesh = make_mesh()
    k = ShardedTriangleWindowKernel(mesh, edge_bucket=512,
                                    vertex_bucket=128, k_bucket=8)
    rng = np.random.default_rng(21)
    s0 = rng.integers(0, 100, 512)
    d0 = rng.integers(0, 100, 512)
    s1, d1 = [], []
    for u in range(1, 41):  # clique: overflows k_bucket=8
        for v in range(u + 1, 41):
            s1.append(u)
            d1.append(v)
    s1 = np.array(s1[:512])
    d1 = np.array(d1[:512])
    s2 = rng.integers(0, 100, 137)  # ragged tail
    d2 = rng.integers(0, 100, 137)
    src = np.concatenate([s0, s1, s2])
    dst = np.concatenate([d0, d1, d2])
    expected = [tri_ops.triangle_count_sparse(a, b, 128)
                for a, b in ((s0, d0), (s1, d1), (s2, d2))]
    assert k.count_stream(src, dst) == expected
    assert k.count_stream(np.array([], np.int64),
                          np.array([], np.int64)) == []


def test_sharded_window_pipeline_non_power_of_two_mesh():
    """Shard counts that don't divide powers of two (e.g. 3) must work:
    buckets round up to multiples of the mesh size."""
    mesh = make_mesh(3)
    k = ShardedTriangleWindowKernel(mesh, edge_bucket=512,
                                    vertex_bucket=64)
    rng = np.random.default_rng(9)
    src = rng.integers(0, 60, 400)
    dst = rng.integers(0, 60, 400)
    assert k.count(src, dst) == tri_ops.triangle_count_sparse(src, dst, 64)


def test_sharded_bipartite_matches_host():
    from gelly_streaming_tpu.ops import unionfind

    engine = ShardedWindowEngine(make_mesh(), num_vertices_bucket=32)
    # even cycle 0-1-2-3-0 (bipartite) + odd cycle 10-11-12-10
    src = np.array([0, 1, 2, 3, 10, 11, 12])
    dst = np.array([1, 2, 3, 0, 11, 12, 10])
    labels, signs, odd = engine.bipartite(src, dst, carry=False)
    hl, hs, ho = unionfind.bipartite_labels(src, dst, 32)
    np.testing.assert_array_equal(labels, hl)
    np.testing.assert_array_equal(odd, ho)
    assert not odd[0] and odd[10]
    # signs 2-color the even cycle
    assert signs[0] == signs[2] != signs[1] == signs[3]
    # carried window: an edge joining both sides of the even cycle at
    # odd distance makes it odd (streaming merge-tree semantics)
    _, _, odd2 = engine.bipartite(np.array([0]), np.array([2]), carry=True)
    assert odd2[0] and odd2[1]


def test_mesh_uses_all_devices():
    assert len(jax.devices()) == 8


def test_hybrid_mesh_single_process_shapes():
    """Hybrid ('dcn','shard') mesh construction and its flat edge view;
    the sharded kernels must run unchanged on the flattened mesh."""
    from gelly_streaming_tpu.parallel import multihost

    mesh = multihost.make_hybrid_mesh(ici_shards=4, dcn_shards=2)
    assert mesh.shape == {"dcn": 2, "shard": 4}
    flat = multihost.flatten_for_edges(mesh)
    assert flat.shape == {"shard": 8}

    k = ShardedTriangleWindowKernel(flat, edge_bucket=512,
                                    vertex_bucket=64)
    rng = np.random.default_rng(1)
    src = rng.integers(0, 60, 500)
    dst = rng.integers(0, 60, 500)
    assert k.count(src, dst) == tri_ops.triangle_count_sparse(src, dst, 64)
    with pytest.raises(ValueError, match="devices"):
        multihost.make_hybrid_mesh(ici_shards=3, dcn_shards=2)


def test_sharded_summary_engine_matches_single_chip():
    """Sharded fused scan = single-chip fused scan, carried state
    across chunks, including a hub-overflow window."""
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
    from gelly_streaming_tpu.parallel.sharded import ShardedSummaryEngine

    rng = np.random.default_rng(23)
    n, v, eb = 2048, 200, 256
    src = rng.integers(0, v, n)
    dst = rng.integers(0, v, n)
    # splice a 30-clique into window 3 to force a K overflow
    cl_s, cl_d = [], []
    for u in range(1, 31):
        for w in range(u + 1, 31):
            cl_s.append(u)
            cl_d.append(w)
    src[3 * eb:3 * eb + len(cl_s[:eb])] = cl_s[:eb]
    dst[3 * eb:3 * eb + len(cl_d[:eb])] = cl_d[:eb]

    sh = ShardedSummaryEngine(make_mesh(), edge_bucket=eb,
                              vertex_bucket=v, k_bucket=8)
    single = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=v,
                                 k_bucket=8)
    got = sh.process(src[:1024], dst[:1024]) + sh.process(src[1024:],
                                                          dst[1024:])
    want = single.process(src[:1024], dst[:1024]) + single.process(
        src[1024:], dst[1024:])
    assert got == want
    sd, sl, so = sh.state()
    wd, wl, wo = single.state()
    np.testing.assert_array_equal(sd[:v], wd[:v])
    np.testing.assert_array_equal(sl[:v], wl[:v])
    np.testing.assert_array_equal(so[:v], wo[:v])


def _hermetic_cpu_env():
    """Env for a child process that must never touch the (possibly
    wedged) TPU tunnel: JAX pinned to cpu, the plugin-registering
    sitecustomize dropped, and XLA_FLAGS cleared so the child sets its
    own device count."""
    import os

    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.xfail(
    strict=False,
    reason="this jaxlib's CPU backend rejects multiprocess computations "
           "(XlaRuntimeError: 'Multiprocess computations aren't "
           "implemented on the CPU backend'); the branch needs a real "
           "multi-host slice — tracked in ROADMAP 'sharded_table on "
           "real ICI'")
def test_multihost_two_process_smoke():
    """VERDICT r1 item 8: actually execute the multi-process branches of
    parallel/multihost.py — jax.distributed initialize_runtime, the
    process_is_granule hybrid mesh (with its granule-contiguity check),
    and one sharded degree window whose psum crosses the process
    boundary — via two real CPU processes on this machine."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_worker.py")
    env = _hermetic_cpu_env()
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:  # a hung coordinator must not leak workers
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MULTIHOST_OK {i}" in out, out


@pytest.mark.parametrize("n_devices", [4, 16])
def test_sharded_engine_parity_other_mesh_sizes(n_devices):
    """The sharded engines must not bake in the CI mesh's 8 devices:
    run ShardedSummaryEngine parity against the single-chip engine on
    4- and 16-device virtual meshes (subprocess — the device count must
    be set before jax initializes)."""
    import os
    import subprocess
    import sys

    REPO = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    code = r"""
import sys
sys.path.insert(0, %(repo)r)
from gelly_streaming_tpu.core.platform import cpu_mesh
cpu_mesh(%(n)d)
from bench import make_stream
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.parallel.mesh import make_mesh
from gelly_streaming_tpu.parallel.sharded import ShardedSummaryEngine

eb, vb, num_w = 1024, 2048, 6
src, dst = make_stream(num_w * eb, vb)
single = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
want = single.process(src, dst)
mesh = make_mesh()
assert mesh.devices.size == %(n)d, mesh.devices.size
eng = ShardedSummaryEngine(mesh, edge_bucket=eb, vertex_bucket=vb)
got = eng.process(src, dst)
assert got == want, (got[-1], want[-1])
print("PARITY-OK", %(n)d)
""" % {"repo": REPO, "n": n_devices}
    env = _hermetic_cpu_env()
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, r.stderr[-800:]
    assert f"PARITY-OK {n_devices}" in r.stdout


@pytest.mark.parametrize("dtype", [np.int32, np.float32])
@pytest.mark.parametrize("name", ["sum", "min", "max"])
def test_sharded_pane_reduce_matches_numpy(name, dtype):
    """Sliding-window monoid reduce over the mesh (edges sharded, one
    collective merge, static window combine) == direct numpy sliding
    reduction per (window, vertex)."""
    from gelly_streaming_tpu.parallel.sharded import make_sharded_pane_reduce

    mesh = make_mesh()
    n = shard_count(mesh)
    rng = np.random.default_rng(13)
    vb, pb, wp, e = 40, 12, 4, 512
    src = rng.integers(0, vb, e).astype(np.int32)
    pane = rng.integers(0, pb, e).astype(np.int32)
    val = rng.integers(-50, 100, e).astype(dtype)
    valid = rng.random(e) < 0.85
    # pad to a shard multiple
    pad = (-e) % n
    if pad:
        src = np.concatenate([src, np.zeros(pad, np.int32)])
        pane = np.concatenate([pane, np.zeros(pad, np.int32)])
        val = np.concatenate([val, np.zeros(pad, dtype)])
        valid = np.concatenate([valid, np.zeros(pad, bool)])

    fn = make_sharded_pane_reduce(mesh, vb, pb, wp, name)
    got_v, got_c = (np.asarray(x) for x in fn(src, pane, val, valid))

    red = {"sum": np.sum, "min": np.min, "max": np.max}[name]
    n_w = pb + wp - 1
    for w in range(n_w):
        lo, hi = w - wp + 1, w          # dense pane span of window w
        for v in range(vb + 1):
            m = valid & (src == v) & (pane >= lo) & (pane <= hi)
            assert got_c[w, v] == m.sum(), (w, v)
            if m.any():
                assert got_v[w, v] == red(val[m]), (name, w, v)
            elif name != "sum":
                from gelly_streaming_tpu.ops.neighborhood import \
                    _pane_identity
                assert got_v[w, v] == _pane_identity(
                    name, got_v.dtype), (name, w, v)


def test_engine_sliding_reduce_matches_loose_fn():
    """ShardedWindowEngine.sliding_reduce == the loose
    make_sharded_pane_reduce it wraps (padding to shard multiples,
    pane bucketing, program caching)."""
    from gelly_streaming_tpu.parallel.sharded import (
        ShardedWindowEngine, make_sharded_pane_reduce)

    mesh = make_mesh()
    n = shard_count(mesh)
    eng = ShardedWindowEngine(mesh, num_vertices_bucket=32)
    rng = np.random.default_rng(21)
    e = 7 * n + 3   # deliberately NOT a shard multiple
    src = rng.integers(0, 32, e).astype(np.int32)
    pane = rng.integers(0, 5, e).astype(np.int32)
    val = rng.integers(1, 50, e).astype(np.int32)
    wv, wc = eng.sliding_reduce(src, pane, val, num_panes=5,
                                panes_per_window=3, name="sum")
    # second call reuses the cached program
    wv2, wc2 = eng.sliding_reduce(src, pane, val, num_panes=5,
                                  panes_per_window=3, name="sum")
    np.testing.assert_array_equal(wv, wv2)
    assert len(eng._pane_fns) == 1

    pb = seg_ops.bucket_size(5)
    fn = make_sharded_pane_reduce(mesh, 32, pb, 3, "sum")
    pad = (-e) % n
    s2 = np.concatenate([src, np.zeros(pad, np.int32)])
    p2 = np.concatenate([pane, np.zeros(pad, np.int32)])
    v2 = np.concatenate([val, np.zeros(pad, np.int32)])
    m2 = np.concatenate([np.ones(e, bool), np.zeros(pad, bool)])
    ev, ec = (np.asarray(x) for x in fn(s2, p2, v2, m2))
    np.testing.assert_array_equal(wv, ev)
    np.testing.assert_array_equal(wc, ec)


# ----------------------------------------------------------------------
# owner-local vs replicated neighbor-row distribution (VERDICT r2
# weak-4: the pmax table's O(V*K) all-reduce needed a measured
# alternative + accounted communication)
# ----------------------------------------------------------------------

def test_owner_table_mode_matches_replicated_and_host():
    from gelly_streaming_tpu.ops.triangles import triangle_count_sparse

    mesh = make_mesh()
    rng = np.random.default_rng(21)
    for _ in range(4):
        e = int(rng.integers(50, 1500))
        v = int(rng.integers(8, 300))
        src = rng.integers(0, v, e).astype(np.int32)
        dst = rng.integers(0, v, e).astype(np.int32)
        want = triangle_count_sparse(src, dst, v)
        for table in ("replicated", "owner"):
            k = ShardedTriangleWindowKernel(
                mesh, edge_bucket=max(e, 64), vertex_bucket=v,
                table=table)
            assert k.count(src, dst) == want, (table, e, v)


def test_owner_table_mode_escalation_ladder():
    """A hub star graph overflows a tiny K in BOTH modes; the owner
    gather must escalate identically (same exact result)."""
    mesh = make_mesh()
    hub = np.zeros(64, np.int32)
    leaves = np.arange(1, 65, dtype=np.int32)
    # triangles: hub-leaf_i-leaf_{i+1} rim edges
    src = np.concatenate([hub, leaves[:-1]])
    dst = np.concatenate([leaves, leaves[1:]])
    from gelly_streaming_tpu.ops.triangles import triangle_count_sparse

    want = triangle_count_sparse(src, dst, 70)
    for table in ("replicated", "owner"):
        k = ShardedTriangleWindowKernel(mesh, edge_bucket=128,
                                        vertex_bucket=70, k_bucket=8,
                                        table=table)
        assert k.count(src, dst) == want, table


def test_window_collective_bytes_accounting():
    from gelly_streaming_tpu.parallel.sharded import (
        ici_time_model, window_collective_bytes)

    r = window_collective_bytes(8, 262144, 64, 2048, "replicated")
    o = window_collective_bytes(8, 262144, 64, 2048, "owner")
    # totals are the sum of their parts
    for d in (r, o):
        assert d["total"] == sum(v for k, v in d.items() if k != "total")
    # the replicated pmax moves O(V*K); the owner gather O(owned*K) —
    # the sparse-window regime the 10M buckets live in is >10x lighter
    assert r["total"] > 10 * o["total"]
    # single shard: no ICI traffic at all
    assert window_collective_bytes(1, 262144, 64, 2048)["total"] == 0
    # time model is linear in bytes at the modeled bandwidth
    t = ici_time_model(r, gbps=45.0)
    assert abs(t["total"] - r["total"] / 45e9) < 1e-12


def test_resolve_table_mode_flips_on_committed_measurement(
        tmp_path, monkeypatch):
    """The mode selection follows the same committed-measurement policy
    as the kernel choices: owner wins only with a >=5% backend-matched
    row; absent/losing/mismatched rows keep the replicated default.
    The selection is memoized per process, so each re-resolve goes
    through the test reset hook."""
    import json

    from gelly_streaming_tpu.parallel import sharded

    perf_path = tmp_path / "PERF.json"
    monkeypatch.setattr(tri_ops, "_PERF_PATH", str(perf_path))
    backend = jax.default_backend()

    def write(file_backend, owner, repl, counts_match=True,
              row_backend=None):
        perf_path.write_text(json.dumps({
            "backend": file_backend,
            "sharded_table": {"backend": row_backend or file_backend,
                              "owner_edges_per_s": owner,
                              "replicated_edges_per_s": repl,
                              "counts_match": counts_match}}))

    write(backend, owner=2000, repl=1000)
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "owner"
    write(backend, owner=1020, repl=1000)   # under the 5% bar
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    write(backend, owner=0, repl=1000)      # missing measurement
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    write("not-" + backend, owner=2000, repl=1000)  # backend mismatch
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    # a fast mode whose own evidence says it miscounted never wins
    write(backend, owner=2000, repl=1000, counts_match=False)
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    # the section's OWN backend label must match the LIVE backend:
    # virtual-mesh rows riding inside a chip-labeled PERF.json can
    # never drive a TPU process's selection (ADVICE r5); the virtual
    # mesh IS the cpu backend, so "<live>-virtual-mesh" still matches
    write(backend, owner=2000, repl=1000,
          row_backend="some-other-backend")
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    write(backend, owner=2000, repl=1000,
          row_backend="%s-virtual-mesh" % backend)
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "owner"
    # a row with NO backend label is treated as unmatched evidence
    perf_path.write_text(json.dumps({
        "backend": backend,
        "sharded_table": {"owner_edges_per_s": 2000,
                          "replicated_edges_per_s": 1000,
                          "counts_match": True}}))
    sharded._reset_table_mode()
    assert sharded.resolve_table_mode() == "replicated"
    # don't leak a resolution made against the fake PERF.json
    sharded._reset_table_mode()


def test_sharded_assoc_pane_reduce_matches_numpy_fold():
    """The associative-fn tier of the sharded pane reduce (per-shard
    flagged scan + all_gather shard fold + masked window combine) ==
    a direct left-fold per (window, vertex) in edge-position order.
    gcd is associative but not a named monoid."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.parallel.sharded import (
        make_sharded_pane_reduce)

    mesh = make_mesh()
    n = shard_count(mesh)
    rng = np.random.default_rng(29)
    vb, pb, wp, e = 24, 8, 3, 33 * n
    src = rng.integers(0, vb, e).astype(np.int32)
    pane = rng.integers(0, pb, e).astype(np.int32)
    val = rng.integers(1, 1000, e).astype(np.int32)
    valid = rng.random(e) < 0.8

    fn = make_sharded_pane_reduce(mesh, vb, pb, wp, fn=jnp.gcd)
    got_v, got_c = (np.asarray(x) for x in fn(src, pane, val, valid))

    import math

    n_w = pb + wp - 1
    for w in range(n_w):
        lo, hi = w - wp + 1, w
        for v in range(vb + 1):
            m = valid & (src == v) & (pane >= lo) & (pane <= hi)
            assert got_c[w, v] == m.sum(), (w, v)  # real edge counts
            if m.any():
                acc = None
                # combine order: pane ascending, then edge position —
                # exactly what the pane path's regrouping produces
                for p in range(max(lo, 0), hi + 1):
                    for x in val[m & (pane == p)].tolist():
                        acc = x if acc is None else math.gcd(acc, x)
                assert got_v[w, v] == acc, (w, v, got_v[w, v], acc)


def test_engine_sliding_reduce_assoc_fn_tier():
    """ShardedWindowEngine.sliding_reduce(fn=...) reaches the
    associative tier, caches per-fn programs, and agrees with the
    monoid tier where the fn IS a monoid (min)."""
    import jax.numpy as jnp

    eng = ShardedWindowEngine(make_mesh(), num_vertices_bucket=32)
    rng = np.random.default_rng(31)
    e = 100
    src = rng.integers(0, 32, e).astype(np.int32)
    pane = rng.integers(0, 5, e).astype(np.int32)
    val = rng.integers(1, 50, e).astype(np.int32)
    mv, mc = eng.sliding_reduce(src, pane, val, num_panes=5,
                                panes_per_window=3, name="min")
    fv, fc = eng.sliding_reduce(src, pane, val, num_panes=5,
                                panes_per_window=3,
                                fn=jnp.minimum)
    occupied = fc > 0
    # both tiers return REAL edge counts (ADVICE r3): exact equality,
    # not just matching occupancy
    np.testing.assert_array_equal(fc, mc)
    np.testing.assert_array_equal(mv[occupied], fv[occupied])
    assert len(eng._pane_fns) == 2

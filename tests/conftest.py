"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): in-process
"mini-cluster" — here a virtual 8-device CPU mesh via
`--xla_force_host_platform_device_count`, the JAX analog of Flink's
multi-subtask single-JVM StreamingProgramTestBase — with golden-output
comparison of sorted result lines.
"""

import os

# Must run before jax initializes a backend. Force (not setdefault): the
# surrounding environment pins JAX_PLATFORMS to the real TPU tunnel, and
# tests must be hermetic CPU runs on the virtual 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The TPU-tunnel PJRT plugin registers itself in every interpreter via
# sitecustomize and is initialized even under JAX_PLATFORMS=cpu; drop
# its factory so tests never dial the (single, shareable-with-bench)
# real chip.
try:
    import jax as _jax

    # sitecustomize imports jax before this file runs, so the config has
    # already captured JAX_PLATFORMS from the environment — update it too.
    _jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    for _name in [n for n in _xb._backend_factories if n != "cpu"]:
        _xb._backend_factories.pop(_name, None)
except Exception:
    pass

import pytest  # noqa: E402

from gelly_streaming_tpu import Edge, ManualClock, StreamEnvironment  # noqa: E402


@pytest.fixture
def env():
    """Deterministic environment: manual ingestion clock pinned at 0 so a
    whole finite source lands in one window (the reference gets this from
    fast fromCollection ingestion; ConnectedComponentsTest.java:62 pins
    parallelism=1 for the same determinism)."""
    return StreamEnvironment(clock=ManualClock(0))


def long_long_edges():
    """The canonical 5-vertex/7-edge weighted test graph
    (reference: GraphStreamTestUtils.java:56-67)."""
    return [
        Edge(1, 2, 12),
        Edge(1, 3, 13),
        Edge(2, 3, 23),
        Edge(3, 4, 34),
        Edge(3, 5, 35),
        Edge(4, 5, 45),
        Edge(5, 1, 51),
    ]


def run_and_sort(env, stream):
    """Execute and return sorted formatted lines — the reference's
    `compareResultsByLinesInMemory` idiom (TestSlice.java:53-55)."""
    from gelly_streaming_tpu.core.types import csv_line

    sink = stream.collect()
    env.execute()
    return sorted(csv_line(v) for v in env.results_of(sink))


@pytest.fixture
def sample_edges():
    return long_long_edges()

"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): in-process
"mini-cluster" — here a virtual 8-device CPU mesh via
`--xla_force_host_platform_device_count`, the JAX analog of Flink's
multi-subtask single-JVM StreamingProgramTestBase — with golden-output
comparison of sorted result lines.
"""

# Must run before jax initializes a backend: tests are hermetic CPU runs
# on a virtual 8-device mesh, never the real (single, shared) TPU chip.
from gelly_streaming_tpu.core.platform import cpu_mesh

cpu_mesh(8)

import pytest  # noqa: E402

from gelly_streaming_tpu import Edge, ManualClock, StreamEnvironment  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running leg (kept in-suite; the mark "
        "documents the cost and allows -m 'not slow' deselection)")
    config.addinivalue_line(
        "markers", "faults: deterministic fault-injection suite "
        "(utils/faults) — CPU-only, no randomness, real sleeps bounded "
        "by ~100ms-scale watchdog deadlines; runs in tier-1 (it is "
        "deliberately NOT 'slow')")
    config.addinivalue_line(
        "markers", "lint: static project-invariant suite "
        "(tools/gslint + utils/knobs) — pure AST/source checks, no "
        "device, no randomness; runs in tier-1 so an invariant "
        "violation is a test failure")


@pytest.fixture
def env():
    """Deterministic environment: manual ingestion clock pinned at 0 so a
    whole finite source lands in one window (the reference gets this from
    fast fromCollection ingestion; ConnectedComponentsTest.java:62 pins
    parallelism=1 for the same determinism)."""
    return StreamEnvironment(clock=ManualClock(0))


def long_long_edges():
    """The canonical 5-vertex/7-edge weighted test graph
    (reference: GraphStreamTestUtils.java:56-67)."""
    return [
        Edge(1, 2, 12),
        Edge(1, 3, 13),
        Edge(2, 3, 23),
        Edge(3, 4, 34),
        Edge(3, 5, 35),
        Edge(4, 5, 45),
        Edge(5, 1, 51),
    ]


def run_and_sort(env, stream):
    """Execute and return sorted formatted lines — the reference's
    `compareResultsByLinesInMemory` idiom (TestSlice.java:53-55)."""
    from gelly_streaming_tpu.core.types import csv_line

    sink = stream.collect()
    env.execute()
    return sorted(csv_line(v) for v in env.results_of(sink))


@pytest.fixture
def sample_edges():
    return long_long_edges()

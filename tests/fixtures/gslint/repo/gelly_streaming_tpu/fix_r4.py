"""R4 fixture: silent broad excepts (true positives) vs re-raising /
recording / pragma'd handlers (true negatives)."""

from .utils import telemetry  # noqa: F401 (parsed, never imported)


def swallows():
    try:
        risky()
    except Exception:        # TP: silent swallow
        pass
    try:
        risky()
    except:                  # TP: bare except  # noqa: E722
        return None


def compliant():
    try:
        risky()
    except Exception as e:   # TN: raises typed
        raise RuntimeError("wrapped") from e
    try:
        risky()
    except Exception as e:   # TN: records a flight-recorder event
        telemetry.event("probe_failed", error=str(e))
    try:
        risky()
    except Exception:  # gslint: disable=except-hygiene (benign probe)
        pass


def risky():
    raise ValueError

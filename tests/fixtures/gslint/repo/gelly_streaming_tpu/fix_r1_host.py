"""R1 true-negative fixture: no jax import — np.asarray here is
numpy-on-numpy, never a device sync."""

import numpy as np


def pure_host(rows):
    return np.asarray(rows, np.int64).sum()

"""R1 fixture: a jax-importing module with unsanctioned sync calls
(true positives) plus pragma'd and non-sync forms (true negatives)."""

import jax
import numpy as np


def bad_syncs(dev, w):
    a = np.asarray(dev)                       # TP: d2h materialize
    b = jax.device_get(dev)                   # TP
    c = dev.item()                            # TP: forced scalar
    dev.block_until_ready()                   # TP
    d = float(dev[w])                         # TP: forced device scalar
    return a, b, c, d


def fine(dev, n):
    ok = np.asarray(dev)  # gslint: disable=host-sync (sanctioned by review: test fixture)
    e = float(n)          # TN: plain name, everyday host arithmetic
    return ok, e

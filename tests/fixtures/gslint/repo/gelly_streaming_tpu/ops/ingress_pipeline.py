"""R5 fixture (at a threaded-module path): an unguarded mutated
module mutable (true positive) vs lock-guarded and read-only ones
(true negatives)."""

import threading

_UNGUARDED = {}              # TP: mutated below, never under a lock
_GUARDED = {}                # TN: accessed under _LOCK
_TABLE = {"a": 1}            # TN: read-only after import
_LOCK = threading.Lock()


def touch(key, value):
    _UNGUARDED[key] = value
    with _LOCK:
        _GUARDED[key] = value
    return _TABLE["a"]

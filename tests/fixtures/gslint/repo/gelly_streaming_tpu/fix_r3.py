"""R3 fixture: raw env reads and an unregistered knob literal (true
positives) vs a registered knob name (true negative)."""

import os

RAW = os.environ.get("GS_TELEMETRY")     # TP: read outside knobs.py
TYPO = "GS_TELEMETRYY"                   # TP: unregistered GS_* name
OK = "GS_TELEMETRY"                      # TN: registered knob name

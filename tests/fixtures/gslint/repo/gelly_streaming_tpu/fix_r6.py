"""R6 fixture: asymmetric checkpoint keys (true positives) vs a
symmetric pair and a pragma'd provenance key (true negatives)."""


class Asymmetric:
    def state_dict(self):
        return {"kept": self.kept, "orphan_saved": 1}  # orphan: TP

    def load_state_dict(self, state):
        self.kept = state["kept"]
        self.ghost = state.get("orphan_loaded")        # ghost: TP


class Symmetric:
    def state_dict(self):
        return {"a": self.a, "b": self.b}

    def load_state_dict(self, state):
        self.a = state["a"]
        self.b = state.get("b", 0)


class Provenance:
    def state_dict(self):
        return {
            "a": self.a,
            "mesh_shape": None,  # gslint: disable=ckpt-symmetry (provenance only)
        }

    def load_state_dict(self, state):
        self.a = state["a"]

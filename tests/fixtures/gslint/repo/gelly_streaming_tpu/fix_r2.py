"""R2 fixture: impure reads reachable from traced roots (true
positives) vs the same reads in untraced host code (true negatives)."""

import os
import time

import jax
from jax import lax

from .utils import costmodel
from .utils import knobs
from .utils import metrics

_MEMO = {}


def _step(carry, x):
    flag = os.environ.get("GS_TELEMETRY")      # TP: frozen at trace
    t = time.perf_counter()                    # TP: trace-time clock
    k = knobs.get_bool("GS_AUTOTUNE")          # TP: frozen knob read
    metrics.counter_inc("gs_edges_total", 1)   # TP: trace-time record
    costmodel.tag = costmodel.on_call("f", None, (), (), {})  # TP
    return carry + x + len(_MEMO) + k, (flag, t)  # TP: module mutable


@jax.jit
def traced(xs):
    return lax.scan(_step, 0, xs)


def _kernel(in_ref, out_ref):
    # TP: a knob read inside a Pallas kernel body freezes into the
    # compiled Mosaic program exactly like any jit-traced read
    out_ref[0] = in_ref[0] + knobs.get_bool("GS_AUTOTUNE")


def pallas_entry(x):
    from jax.experimental import pallas as pl

    return pl.pallas_call(_kernel, out_shape=x)(x)


def host_only():
    # TN: same reads, never traced
    _MEMO["x"] = os.environ.get("GS_TELEMETRY")
    _MEMO["k"] = knobs.get_bool("GS_AUTOTUNE")
    metrics.counter_inc("gs_edges_total", 1)
    costmodel.on_call("f", None, (), (), {})
    return time.perf_counter()

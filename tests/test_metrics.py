"""Live health plane suite (utils/metrics + utils/healthz):

- registry semantics: counters/gauges/bounded histograms with label
  sets, thread-safe, live-knob-gated;
- label cardinality bounds (GS_METRICS_SERIES): overflow collapses
  into one series instead of growing the registry;
- Prometheus text-format golden file (the /metrics body);
- /healthz endpoint: JSON schema, ok=200 / degraded=503, /metrics
  content type, 404s;
- staleness watchdog with an injectable clock: degraded after
  GS_HEALTH_STALE_S without a finalize (durable `health_degraded`),
  recovery on the next finalize (durable `health_recovered`);
- recompile envelope: doubling bucket growth stays inside the
  O(log V) envelope (true negative), a shape-churning toy loop trips
  a durable `recompile_storm` (true positive);
- the telemetry-sink feed: stage spans → latency histograms and
  events → counters with GS_TELEMETRY=0 (arming metrics never arms
  the ledger);
- `GS_METRICS=0` digest parity on the 524K/32768 CPU row (the
  zero-overhead contract; the committed armed-overhead evidence is
  PERF_cpu.json's `metrics` section).
"""

import hashlib
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from gelly_streaming_tpu.utils import healthz, metrics, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "fixtures",
                      "metrics_prometheus.txt")


@pytest.fixture
def armed(monkeypatch):
    """Registry armed (no server, no ledger); reset before AND after
    so no series leak across tests."""
    monkeypatch.setenv("GS_METRICS", "1")
    monkeypatch.delenv("GS_TELEMETRY", raising=False)
    monkeypatch.delenv("GS_METRICS_PORT", raising=False)
    metrics.reset()
    yield
    metrics.reset()


def _stream(num_edges, num_vertices, seed=7):
    from bench import make_stream

    return make_stream(num_edges, num_vertices, seed)


# ----------------------------------------------------------------------
# registry semantics
# ----------------------------------------------------------------------
def test_counter_gauge_histogram_semantics(armed):
    metrics.counter_inc("gs_edges_total", 100, engine="driver")
    metrics.counter_inc("gs_edges_total", 28, engine="driver")
    metrics.counter_inc("gs_edges_total", 5, engine="other")
    metrics.gauge_set("gs_inflight_chunks", 3)
    metrics.gauge_set("gs_inflight_chunks", 1)
    for ms in (1, 2, 3, 4):
        metrics.observe("gs_stage_seconds", ms / 1e3, stage="prep")
    c = metrics.counters()
    assert c[("gs_edges_total", (("engine", "driver"),))] == 128
    assert c[("gs_edges_total", (("engine", "other"),))] == 5
    assert metrics.gauges()[("gs_inflight_chunks", ())] == 1.0
    h = metrics.histogram("gs_stage_seconds", stage="prep")
    assert h["count"] == 4
    assert h["sum"] == pytest.approx(0.010)
    # nearest-rank over [1,2,3,4] ms
    assert (h["p50"], h["p95"], h["p99"]) == (0.002, 0.004, 0.004)
    assert metrics.histogram("gs_stage_seconds", stage="h2d") is None


def test_disarmed_is_inert(monkeypatch):
    monkeypatch.setenv("GS_METRICS", "0")
    metrics.reset()
    try:
        metrics.counter_inc("gs_edges_total", 1)
        metrics.gauge_set("g", 1)
        metrics.observe("h", 1)
        metrics.mark_window(1, 10)
        metrics.note_compile("f", ())
        assert metrics.counters() == {}
        assert metrics.gauges() == {}
        assert metrics.health_snapshot()["windows_finalized"] == 0
    finally:
        metrics.reset()


def test_label_cardinality_bound(armed, monkeypatch):
    monkeypatch.setenv("GS_METRICS_SERIES", "4")
    for i in range(10):
        metrics.counter_inc("gs_edges_total", 1, tenant="t%d" % i)
    series = [labels for (name, labels) in metrics.counters()
              if name == "gs_edges_total"]
    # 4 admitted + the one overflow series
    assert len(series) == 5
    overflow = metrics.counters()[
        ("gs_edges_total", (("overflow", "true"),))]
    assert overflow == 6
    # known series keep accumulating normally past the bound
    metrics.counter_inc("gs_edges_total", 1, tenant="t0")
    assert metrics.counters()[
        ("gs_edges_total", (("tenant", "t0"),))] == 2
    # a RECURRING over-bound label set counts once, not per
    # observation (dropped_series sizes the bound, not the traffic)
    metrics.counter_inc("gs_edges_total", 1, tenant="t9")
    metrics.counter_inc("gs_edges_total", 1, tenant="t9")
    assert "gs_metrics_dropped_series_total 6" \
        in metrics.render_prometheus()


# ----------------------------------------------------------------------
# Prometheus text format (golden file)
# ----------------------------------------------------------------------
def _fixed_registry():
    metrics.counter_inc("gs_edges_total", 524288, engine="driver",
                        tier="scan")
    metrics.counter_inc("gs_windows_finalized_total", 16,
                        engine="driver", tier="scan")
    metrics.counter_inc("gs_stage_retries_total", 2, stage="h2d")
    metrics.gauge_set("gs_inflight_chunks", 3)
    metrics.gauge_set("gs_live_buffers", 42)
    for ms in (10, 20, 30, 40):
        metrics.observe("gs_stage_seconds", ms / 1e3, stage="prep")


def test_prometheus_golden_file(armed):
    _fixed_registry()
    got = metrics.render_prometheus()
    with open(GOLDEN) as f:
        assert got == f.read()


def test_prometheus_parses_as_exposition(armed):
    _fixed_registry()
    for line in metrics.render_prometheus().splitlines():
        assert line.startswith("# TYPE ") or " " in line
        if not line.startswith("#"):
            name_part, value = line.rsplit(" ", 1)
            float(value)  # every sample value is a number
            assert name_part.startswith("gs_")


# ----------------------------------------------------------------------
# /healthz + /metrics endpoint
# ----------------------------------------------------------------------
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read(), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers


def test_healthz_endpoint_schema_and_codes(armed, monkeypatch):
    metrics.mark_window(4, 4096, engine="driver", tier="scan")
    srv = healthz.start(port=0)
    try:
        base = "http://127.0.0.1:%d" % srv.port
        code, body, headers = _get(base + "/healthz")
        assert code == 200
        snap = json.loads(body)
        for key, kind in (
                ("status", str), ("windows_finalized", int),
                ("edges_total", int), ("stale_after_s", float),
                ("engines", dict), ("transitions", list),
                ("demotions", list), ("compiles", dict),
                ("backlog_chunks", float), ("trace", str)):
            assert isinstance(snap[key], kind), (key, snap[key])
        assert "last_finalize_age_s" in snap
        assert "ledger" in snap
        assert snap["status"] == "ok"
        assert snap["engines"]["driver"]["tier"] == "scan"
        code, body, headers = _get(base + "/metrics")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "gs_windows_finalized_total" in body.decode()
        assert _get(base + "/nope")[0] == 404
        # degraded flips the HTTP code to 503 (probe needs no JSON)
        monkeypatch.setenv("GS_HEALTH_STALE_S", "0.000001")
        code, body, _ = _get(base + "/healthz")
        assert code == 503
        assert json.loads(body)["status"] == "degraded"
    finally:
        healthz.stop()


def test_healthz_not_started_without_port(armed):
    assert healthz.maybe_start() is None


# ----------------------------------------------------------------------
# staleness watchdog (injectable clock)
# ----------------------------------------------------------------------
def test_staleness_watchdog_flip_and_recover(armed, monkeypatch,
                                             tmp_path):
    monkeypatch.setenv("GS_HEALTH_STALE_S", "5")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    try:
        metrics.mark_window(1, 100, now=100.0)
        assert metrics.check_staleness(now=104.0) == "ok"
        assert metrics.check_staleness(now=106.0) == "degraded"
        # sticky per episode: no second durable event
        assert metrics.check_staleness(now=200.0) == "degraded"
        metrics.mark_window(1, 100, now=201.0)  # recovery signal
        assert metrics.health_snapshot()["status"] == "ok"
        trans = metrics.health_snapshot()["transitions"]
        assert [t[0] for t in trans] == ["degraded", "ok"]
        # both durable events on disk, exactly once
        names = []
        with open(telemetry.ledger_path()) as f:
            for line in f:
                names.append(json.loads(line).get("name"))
        assert names.count("health_degraded") == 1
        assert names.count("health_recovered") == 1
    finally:
        telemetry.reset()


def test_staleness_disabled_at_zero(armed, monkeypatch):
    monkeypatch.setenv("GS_HEALTH_STALE_S", "0")
    metrics.mark_window(1, 100, now=0.0)
    assert metrics.check_staleness(now=1e9) == "ok"


def test_stream_start_reanchors_stale_clock(armed, monkeypatch):
    """A stream starting long after the previous one finalized must
    not inherit the stale clock and get flagged before its first
    window is even due."""
    monkeypatch.setenv("GS_HEALTH_STALE_S", "5")
    metrics.mark_window(1, 100, now=100.0)   # stream A's last window
    metrics.clock = lambda: 200.0            # stream B starts at 200
    try:
        metrics.on_stream_start("driver")
    finally:
        metrics.clock = __import__("time").monotonic
    assert metrics.check_staleness(now=203.0) == "ok"
    assert metrics.check_staleness(now=206.0) == "degraded"


def test_health_transitions_bounded(armed, monkeypatch):
    """Episodic degrade/recover flips forever must not grow the
    transition log without bound (only the tail is served)."""
    monkeypatch.setenv("GS_HEALTH_STALE_S", "1")
    for i in range(200):
        t = 10.0 * i
        metrics.mark_window(1, 100, now=t)
        metrics.check_staleness(now=t + 2.0)  # flip degraded
    reg = metrics._reg()
    assert len(reg.transitions) <= 64
    assert len(metrics.health_snapshot()["transitions"]) == 8


def test_wrap_jit_signature_memory_bounded(armed, monkeypatch):
    """The compile watcher itself must not leak in the churn failure
    mode it detects: past _SIG_CAP distinct signatures the set stops
    growing while the compile count keeps moving."""
    monkeypatch.setattr(metrics, "_SIG_CAP", 16)
    fn = metrics.wrap_jit("churn_bound", lambda x: x)
    for n in range(1, 41):
        fn(np.zeros(n, np.int32))
    rep = metrics.compile_report()["churn_bound"]
    assert rep["count"] == 40        # counting never stops
    assert rep["storm"]
    seen = next(c.cell_contents for c in fn.__closure__
                if isinstance(c.cell_contents, set))
    assert len(seen) == 16           # capped at _SIG_CAP
    # passthrough intact past the cap
    np.testing.assert_array_equal(fn(np.zeros(1, np.int32)),
                                  np.zeros(1, np.int32))


# ----------------------------------------------------------------------
# recompile envelope
# ----------------------------------------------------------------------
def test_recompile_envelope_doubling_growth_is_clean(armed):
    """True negative: O(log V) bucket doubling — ten doublings from
    1K — stays inside the envelope."""
    fn = metrics.wrap_jit("grower", lambda x: x)
    for k in range(10, 20):
        fn(np.zeros(1 << k, np.int32))
    rep = metrics.compile_report()["grower"]
    assert rep["count"] == 10
    assert not rep["storm"]
    assert rep["count"] <= rep["allowed"]


def test_recompile_envelope_churn_trips_storm(armed, monkeypatch,
                                              tmp_path):
    """True positive: a shape-churning toy loop (same order of
    magnitude, ever-new shapes) blows past base+log2(growth)+1 and
    stamps ONE durable recompile_storm."""
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    try:
        fn = metrics.wrap_jit("churner", lambda x: x)
        for n in range(1000, 1040):
            fn(np.zeros(n, np.int32))
        rep = metrics.compile_report()["churner"]
        assert rep["storm"]
        assert rep["count"] == 40
        assert rep["count"] > rep["allowed"]
        names = []
        with open(telemetry.ledger_path()) as f:
            for line in f:
                names.append(json.loads(line).get("name"))
        assert names.count("recompile_storm") == 1  # sticky
    finally:
        telemetry.reset()


def test_wrap_jit_passthrough_and_dedupe(armed):
    calls = []

    def fn(x, flag=False):
        calls.append(1)
        return x * 2

    w = metrics.wrap_jit("f", fn)
    a = np.arange(4)
    np.testing.assert_array_equal(w(a), a * 2)
    np.testing.assert_array_equal(w(a + 1), (a + 1) * 2)
    assert len(calls) == 2                       # every call runs
    rep = metrics.compile_report()["f"]
    assert rep["count"] == 1                     # one signature
    w(np.arange(8))                              # new shape
    assert metrics.compile_report()["f"]["count"] == 2
    w(a, flag=True)                              # kwargs in the sig
    assert metrics.compile_report()["f"]["count"] == 3


# ----------------------------------------------------------------------
# the telemetry-sink feed (GS_TELEMETRY stays 0)
# ----------------------------------------------------------------------
def test_sink_maps_spans_and_events_without_ledger(armed):
    assert not telemetry.enabled()
    t0 = telemetry.clock()
    telemetry.record_span("ingress.prep", t0, 0.002)
    telemetry.record_span("ingress.finalize", t0, 0.001)
    with telemetry.span("fused_scan.round", edges=4096):
        pass
    telemetry.event("stage_retry", stage="h2d", attempt=1)
    telemetry.event("tier_demotion", durable=True)
    telemetry.event("checkpoint_saved")
    assert telemetry.records() == []  # the ledger/ring stayed off
    assert metrics.histogram("gs_stage_seconds",
                             stage="prep")["count"] == 1
    assert metrics.histogram("gs_stage_seconds",
                             stage="finalize")["count"] == 1
    assert metrics.histogram("gs_round_seconds",
                             span="fused_scan.round")["count"] == 1
    c = metrics.counters()
    assert c[("gs_stage_retries_total", (("stage", "h2d"),))] == 1
    assert c[("gs_tier_demotions_total", ())] == 1
    assert c[("gs_checkpoints_total", ())] == 1
    assert c[("gs_round_edges_total",
              (("span", "fused_scan.round"),))] == 4096


def test_broken_sink_dropped_with_visible_scar(armed):
    """A sink that raises is removed from the record path (the stream
    survives) but must leave a scar: `gs_metrics_sink_dropped_total`
    on /metrics even with the ledger off."""
    assert not telemetry.enabled()
    calls = []

    def bad_sink(rec):
        calls.append(rec)
        raise KeyError("malformed record")

    telemetry.register_sink(bad_sink, lambda: True)
    try:
        telemetry.event("stage_retry", stage="h2d")   # kills bad_sink
        telemetry.event("stage_retry", stage="h2d")   # survives
        assert len(calls) == 1                        # dropped, not retried
        assert metrics.counters()[
            ("gs_metrics_sink_dropped_total", ())] == 1
        assert "gs_metrics_sink_dropped_total 1" \
            in metrics.render_prometheus()
        # the registry's own sink kept recording after the drop
        assert metrics.counters()[
            ("gs_stage_retries_total", (("stage", "h2d"),))] == 2
    finally:
        with telemetry._REC_LOCK:
            telemetry._SINKS[:] = [
                s for s in telemetry._SINKS if s[0] is not bad_sink]


def test_mark_window_drives_throughput_and_age(armed):
    metrics.on_stream_start()
    metrics.mark_window(4, 4000, engine="driver", tier="scan",
                        now=10.0)
    metrics.mark_window(4, 8000, engine="driver", tier="scan",
                        now=12.0)
    snap = metrics.health_snapshot(now=13.0)
    assert snap["windows_finalized"] == 8
    assert snap["edges_total"] == 12000
    assert snap["last_finalize_age_s"] == 1.0
    assert snap["edges_per_s_ema"] == 4000  # 8000 edges / 2 s
    assert snap["engines"]["driver"]["windows"] == 8


def test_mark_window_tenant_rows_and_staleness(armed, monkeypatch):
    """Per-tenant finalize marks drive the /healthz `tenants` section:
    window/edge counters, per-tenant last-finalize age, and a per-row
    stale flag once GS_HEALTH_STALE_S passes without THAT tenant
    finalizing (the cohort stays ok while one stream wedges)."""
    monkeypatch.setenv("GS_HEALTH_STALE_S", "5")
    metrics.on_stream_start("cohort", tenant="t1")
    metrics.mark_window(2, 1024, engine="cohort", tier="cohort",
                        tenant="t1", now=10.0)
    metrics.mark_window(1, 512, engine="cohort", tier="cohort",
                        tenant="t2", now=11.0)
    metrics.mark_window(1, 512, engine="cohort", tier="cohort",
                        tenant="t2", now=18.0)
    snap = metrics.health_snapshot(now=19.0)
    t1, t2 = snap["tenants"]["t1"], snap["tenants"]["t2"]
    assert (t1["windows"], t1["edges"]) == (2, 1024)
    assert (t2["windows"], t2["edges"]) == (2, 1024)
    assert t1["last_finalize_age_s"] == 9.0 and t1["stale"] is True
    assert t2["last_finalize_age_s"] == 1.0 and t2["stale"] is False
    # tenant-labeled counters ride the normal registry
    c = metrics.counters()
    assert c[("gs_tenant_windows_total",
              (("tenant", "t1"), ("tier", "cohort")))] == 2
    assert c[("gs_windows_finalized_total",
              (("engine", "cohort"), ("tenant", "t2"),
               ("tier", "cohort")))] == 2


def test_tenant_table_cardinality_bound(armed, monkeypatch):
    """The per-tenant /healthz table obeys the SAME cardinality bound
    as label sets: past GS_METRICS_SERIES new tenants collapse into
    one `overflow` row (counted once each in dropped_series), so a
    tenant-shaped label can never grow the registry unboundedly."""
    monkeypatch.setenv("GS_METRICS_SERIES", "4")
    for i in range(10):
        metrics.mark_tenant("t%d" % i, 1, 100, tier="cohort")
    snap = metrics.health_snapshot(now=1.0)
    assert len(snap["tenants"]) == 5  # 4 admitted + overflow
    assert snap["tenants"]["overflow"]["windows"] == 6
    # recurring marks on a collapsed tenant accumulate in overflow
    # without inflating the dropped counter past one per DISTINCT id
    metrics.mark_tenant("t9", 1, 100)
    metrics.mark_tenant("t9", 1, 100)
    snap = metrics.health_snapshot(now=1.0)
    assert snap["tenants"]["overflow"]["windows"] == 8
    assert "gs_metrics_dropped_series_total" in \
        metrics.render_prometheus()
    # admitted tenants keep their own rows past the bound
    metrics.mark_tenant("t0", 3, 100)
    assert metrics.health_snapshot(
        now=1.0)["tenants"]["t0"]["windows"] == 4


def test_sample_memory_reports_live_buffers(armed):
    import jax.numpy as jnp

    keep = jnp.arange(1024)  # noqa: F841 — a live buffer to count
    sample = metrics.sample_memory()
    assert sample["live_buffers"] >= 1
    assert sample["live_buffer_bytes"] > 0
    assert metrics.gauges()[("gs_live_buffers", ())] >= 1


# ----------------------------------------------------------------------
# engine integration + the zero-overhead contract
# ----------------------------------------------------------------------
def test_engine_feeds_registry_end_to_end(armed):
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eng = StreamSummaryEngine(edge_bucket=1024, vertex_bucket=2048)
    eng.MAX_WINDOWS = 2
    src, dst = _stream(8 * 1024, 1024, seed=3)
    eng.process(src, dst)
    c = metrics.counters()
    key = ("gs_windows_finalized_total",
           (("engine", "StreamSummaryEngine"),
            ("tier", "fused_scan")))
    assert c[key] == 8
    assert metrics.histogram("gs_stage_seconds",
                             stage="prep")["count"] >= 4
    assert "fused_scan" in metrics.compile_report()
    assert metrics.health_snapshot()["status"] == "ok"


def test_disarmed_digest_parity_524k_row(monkeypatch):
    """GS_METRICS=0 vs 1 on the 524K/32768 CPU bench row: counts are
    bit-identical (the registry observes, never participates). The
    armed-overhead bound is committed evidence (PERF_cpu.json
    `metrics`, tools/profile_kernels.py section_metrics)."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    src, dst = _stream(524288, 65536)
    monkeypatch.setenv("GS_METRICS", "0")
    monkeypatch.setenv("GS_TELEMETRY", "0")
    metrics.reset()
    kern = TriangleWindowKernel(edge_bucket=32768,
                                vertex_bucket=65536)
    base = kern.count_stream(src, dst)
    assert metrics.counters() == {}       # disarmed: nothing recorded
    monkeypatch.setenv("GS_METRICS", "1")
    metrics.reset()
    try:
        armed_counts = kern.count_stream(src, dst)
        observed = metrics.health_snapshot()["windows_finalized"]
    finally:
        metrics.reset()
    digest = lambda c: hashlib.sha256(  # noqa: E731
        np.asarray(c, np.int64).tobytes()).hexdigest()
    assert digest(base) == digest(armed_counts)
    assert observed == len(base)          # armed: every window seen


def test_committed_metrics_section_meets_the_bar():
    """The committed PERF_cpu.json `metrics` section holds the
    acceptance bar: parity true, armed overhead ≤ 1.05×."""
    with open(os.path.join(REPO, "PERF_cpu.json")) as f:
        meta = json.load(f).get("metrics")
    assert meta, "PERF_cpu.json is missing the metrics section"
    assert meta["parity"] is True
    assert meta["overhead_ratio"] <= 1.05
    assert meta["num_edges"] == 524288
    assert meta["edge_bucket"] == 32768

"""Async serving pump suite (ISSUE 18 tentpole a) + satellites:

- digest parity: the async pump (GS_PUMP=async, dedicated dispatch
  thread) emits exactly the sync oracle's summaries, per tenant;
- overlap: feeds accepted while a dispatch is in flight are counted
  (the pump_smoke gate's in-suite twin, forced deterministic here by
  hanging one dispatch);
- races: concurrent feeders x pump thread x close/drain, with an
  injected mid-pump fault — nothing lost, nothing doubled;
- default pin: GS_PUMP unset keeps the single-lock legacy path (no
  pump thread, both serve locks alias the legacy lock);
- subscribe: pushed `event: window` rows in emission order, bounded
  per-connection queue, slow-subscriber shed via serve_client_shed;
- GS_OOO_BOUND reorder buffer: within-bound release, beyond-bound
  atomic refusal, close() flushes the hold, true watermark lag in the
  latency plane.
"""

import threading
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.serve import ServeClient, StreamServer
from gelly_streaming_tpu.core.tenancy import TenantCohort
from gelly_streaming_tpu.utils import faults
from gelly_streaming_tpu.utils import latency

EB, VB = 256, 512


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("GS_PUMP", "GS_SUB_QUEUE", "GS_OOO_BOUND",
              "GS_TENANT_QUEUE_WINDOWS", "GS_AUTOTUNE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("GS_AUTOTUNE", "0")


def _stream(num_w, seed=0):
    rng = np.random.default_rng(seed)
    n = num_w * EB
    return (rng.integers(0, VB, n).astype(np.int32),
            rng.integers(0, VB, n).astype(np.int32))


def _oracle(streams):
    """Sync single-thread reference: one cohort, windows in order."""
    c = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    out = {}
    for tid in streams:
        c.admit(tid)
        out[tid] = []
    for tid, (s, d) in streams.items():
        for i in range(0, len(s), EB):
            c.feed(tid, s[i:i + EB], d[i:i + EB])
            out[tid] += c.pump().get(tid, [])
    for tid in streams:
        out[tid] += c.close(tid)
    return out


def _feed_all(cli, tid, src, dst, chunk=EB):
    """Feed riding the protocol's typed backpressure retry hint —
    the async pump compiles on its first dispatch, so early feeds can
    legitimately fill the bounded queue."""
    for i in range(0, len(src), chunk):
        deadline = time.monotonic() + 60
        while True:
            r = cli.feed(tid, src[i:i + chunk], dst[i:i + chunk])
            if r.get("ok"):
                break
            assert r["error"] == "TenantBackpressure", r
            assert time.monotonic() < deadline, "backpressure wedged"
            time.sleep(r.get("retry_after_s", 0.05))


def _async_server(tmp_path, monkeypatch, **kw):
    monkeypatch.setenv("GS_PUMP", "async")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0, **kw).start()
    assert srv.pump_mode == "async"
    assert srv._pump_thread is not None and srv._pump_thread.is_alive()
    return srv


def test_async_pump_digest_equals_sync_oracle(tmp_path, monkeypatch):
    streams = {"a": _stream(3, seed=1), "b": _stream(2, seed=2)}
    want = _oracle(streams)
    srv = _async_server(tmp_path, monkeypatch)
    try:
        cli = ServeClient(srv.port, timeout=60)
        for tid, (s, d) in streams.items():
            assert cli.admit(tid)["ok"]
            _feed_all(cli, tid, s, d)
        cli.close()
        srv.drain(deadline_s=60)
        got = {tid: [row["summary"] for row in rows]
               for tid, rows in srv.results.items()}
        assert got == want
    finally:
        srv.close()


def test_async_pump_overlaps_ingest_with_dispatch(tmp_path,
                                                  monkeypatch):
    """Hang ONE dispatch on the pump thread and feed through it: the
    accept loop keeps admitting (overlap_feeds counts them) and the
    digest is still the oracle's — ingest never waits on dispatch."""
    src, dst = _stream(3, seed=3)
    want = _oracle({"t": (src, dst)})
    srv = _async_server(tmp_path, monkeypatch)
    try:
        cli = ServeClient(srv.port, timeout=60)
        cli.admit("t")
        _feed_all(cli, "t", src[:EB], dst[:EB])
        with faults.inject(faults.FaultSpec(
                site="tenant_prep", on_call=1, action="hang",
                seconds=0.6)):
            t0 = time.monotonic()
            # lands while the hung dispatch holds the pump thread
            _feed_all(cli, "t", src[EB:2 * EB], dst[EB:2 * EB])
            ingest_s = time.monotonic() - t0
        assert ingest_s < 0.5, \
            f"feed waited on the hung dispatch ({ingest_s:.2f}s)"
        _feed_all(cli, "t", src[2 * EB:], dst[2 * EB:])
        cli.close()
        srv.drain(deadline_s=60)
        assert srv._stats["overlap_feeds"] >= 1
        got = [row["summary"] for row in srv.results["t"]]
        assert got == want["t"]
    finally:
        srv.close()


def test_async_pump_races_feed_close_drain(tmp_path, monkeypatch):
    """Concurrent feeder threads against the live pump thread, closes
    racing the last feeds, then drain: per-tenant digests equal the
    sequential oracle — nothing lost, nothing doubled."""
    streams = {f"t{i}": _stream(2, seed=10 + i) for i in range(3)}
    want = _oracle(streams)
    srv = _async_server(tmp_path, monkeypatch)
    try:
        errs = []

        def feeder(tid, s, d):
            try:
                cli = ServeClient(srv.port, timeout=60)
                cli.admit(tid)
                _feed_all(cli, tid, s, d)
                cli.close()
            except Exception as e:  # surfaced after join
                errs.append((tid, e))

        threads = [threading.Thread(target=feeder, args=(tid, s, d))
                   for tid, (s, d) in streams.items()]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60)
        assert not errs, errs
        srv.drain(deadline_s=60)
        got = {tid: [row["summary"] for row in rows]
               for tid, rows in srv.results.items()}
        assert got == want
    finally:
        srv.close()


def test_async_pump_survives_mid_pump_fault(tmp_path, monkeypatch):
    """A non-fatal injected fault on the pump thread's dispatch kills
    that ROUND, not the pump: the loop reports it and the next round
    finalizes every window — digest still the oracle's."""
    src, dst = _stream(2, seed=4)
    want = _oracle({"t": (src, dst)})
    srv = _async_server(tmp_path, monkeypatch)
    try:
        cli = ServeClient(srv.port, timeout=60)
        cli.admit("t")
        with faults.inject(faults.FaultSpec(site="tenant_prep",
                                            on_call=1)):
            _feed_all(cli, "t", src, dst)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(srv.results.get("t", ())) >= 2:
                    break
                time.sleep(0.05)
        cli.close()
        srv.drain(deadline_s=60)
        got = [row["summary"] for row in srv.results["t"]]
        assert got == want["t"]
    finally:
        srv.close()


def test_pump_default_sync_is_single_lock_legacy(tmp_path):
    """GS_PUMP unset: no pump thread, both serve locks ARE the legacy
    lock (bit-identical acquisition pattern), digest == oracle."""
    src, dst = _stream(2, seed=5)
    want = _oracle({"t": (src, dst)})
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0).start()
    try:
        assert srv.pump_mode == "sync"
        assert srv._pump_thread is None
        assert srv._ingest_lock is srv._lock
        assert srv._pump_mutex is srv._lock
        cli = ServeClient(srv.port, timeout=60)
        cli.admit("t")
        got = []
        for i in range(0, len(src), EB):
            assert cli.feed("t", src[i:i + EB], dst[i:i + EB])["ok"]
            got += [row["summary"] for row in
                    cli.pump()["results"].get("t", [])]
        got += [row["summary"] for row in cli.close_tenant("t")["results"]]
        cli.close()
        assert got == want["t"]
    finally:
        srv.close()


# ----------------------------------------------------------------------
# subscribe
# ----------------------------------------------------------------------
def test_subscribe_pushes_rows_in_order(tmp_path):
    src, dst = _stream(3, seed=6)
    want = _oracle({"t": (src, dst)})
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0).start()
    try:
        sub = ServeClient(srv.port, timeout=60)
        assert sub.subscribe("t")["ok"]
        cli = ServeClient(srv.port, timeout=60)
        cli.admit("t")
        for i in range(0, len(src), EB):
            assert cli.feed("t", src[i:i + EB], dst[i:i + EB])["ok"]
            cli.pump()
        cli.close_tenant("t")
        pushed = [sub.next_window(timeout=30) for _ in range(3)]
        assert [p["tenant"] for p in pushed] == ["t"] * 3
        assert [p["summary"] for p in pushed] == want["t"]
        assert [p["window"] for p in pushed] == [0, 1, 2]
        assert srv._stats["pushed"] == 3
        cli.close()
        sub.close()
    finally:
        srv.close()


def test_subscribe_slow_consumer_is_shed(tmp_path, monkeypatch):
    """GS_SUB_QUEUE=1 + a sender wedged by a hung socket write: the
    fan-out's non-blocking put overflows, the subscriber is shed with
    a durable serve_client_shed, and the pump finishes undisturbed."""
    monkeypatch.setenv("GS_SUB_QUEUE", "1")
    src, dst = _stream(3, seed=7)
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0).start()
    try:
        sub = ServeClient(srv.port, timeout=60)
        assert sub.subscribe("*")["ok"]
        assert srv._stats["subscribers"] == 1
        cli = ServeClient(srv.port, timeout=60)
        cli.admit("t")
        for i in range(0, len(src), EB):
            assert cli.feed("t", src[i:i + EB], dst[i:i + EB])["ok"]
        with faults.inject(faults.FaultSpec(
                site="serve_send", on_call=1, action="hang",
                seconds=1.5)):
            # one pump emits 3 rows: the hung sender holds row 1, the
            # 1-deep mailbox holds row 2, row 3 overflows -> shed
            r = cli.pump()
            assert len(r["results"]["t"]) == 3
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and srv._subs:
            time.sleep(0.05)
        assert not srv._subs, "slow subscriber not shed"
        assert srv._stats["shed"] >= 1
        cli.close()
        sub.close()
    finally:
        srv.close()


# ----------------------------------------------------------------------
# GS_OOO_BOUND reorder buffer
# ----------------------------------------------------------------------
def _ts_cohort():
    c = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    c.admit("t")
    return c


def test_ooo_within_bound_reorders_to_the_sorted_stream(monkeypatch):
    """A bounded-out-of-order feed equals feeding the ts-sorted stream
    through an unbuffered cohort: the hold releases exactly the
    watermark-passed prefix, in stamp order."""
    rng = np.random.default_rng(8)
    n = 2 * EB
    src = rng.integers(0, VB, n).astype(np.int32)
    dst = rng.integers(0, VB, n).astype(np.int32)
    base = np.arange(n, dtype=np.int64) * 1_000
    jitter = rng.integers(-40, 40, n) * 1_000
    ts = base + jitter
    order = np.argsort(ts, kind="stable")
    want_c = _ts_cohort()
    want_c.feed("t", src[order], dst[order], ts=ts[order])
    want = want_c.pump().get("t", []) + want_c.close("t")
    monkeypatch.setenv("GS_OOO_BOUND", str(100 * 1_000))
    c = _ts_cohort()
    for i in range(0, n, 64):
        c.feed("t", src[i:i + 64], dst[i:i + 64], ts=ts[i:i + 64])
    got = c.pump().get("t", []) + c.close("t")
    assert got == want


def test_ooo_beyond_bound_refused_atomically(monkeypatch):
    monkeypatch.setenv("GS_OOO_BOUND", "100")
    c = _ts_cohort()
    c.feed("t", [1, 2], [2, 3], ts=[1000, 2000])
    held = c.tenants["t"].ooo_ts.copy()
    # min ts 1500 is within the hold, but 500 reaches back past the
    # released frontier (watermark 2000-100=1900 released ts<=1900)
    with pytest.raises(ValueError, match="regression past"):
        c.feed("t", [3, 4], [4, 5], ts=[1500, 500])
    # atomic: the refused batch left the hold untouched
    assert np.array_equal(c.tenants["t"].ooo_ts, held)


def test_ooo_close_flushes_the_hold(monkeypatch):
    monkeypatch.setenv("GS_OOO_BOUND", str(10**12))
    c = _ts_cohort()
    src, dst = _stream(1, seed=9)
    ts = np.arange(EB, dtype=np.int64)
    c.feed("t", src, dst, ts=ts)
    # an astronomically wide bound holds EVERYTHING until close
    assert c.tenants["t"].ooo_ts.size == EB
    assert c.tenants["t"].queued == 0
    out = c.close("t")
    assert len(out) == 1  # the full window emerged at the boundary


def test_ooo_watermark_lag_reaches_the_latency_plane(monkeypatch):
    monkeypatch.setenv("GS_OOO_BOUND", str(10**12))
    monkeypatch.setenv("GS_LATENCY", "1")
    latency.reset()
    try:
        c = _ts_cohort()
        # stamps 2s apart in ns: held lag = 2s, exactly
        c.feed("t", [1, 2], [2, 3], ts=[0, 2_000_000_000])
        rows = latency.health_section()["tenants"]
        row = rows["t"]
        assert row["watermark_held"] == 2
        assert row["watermark_lag_s"] == pytest.approx(2.0)
        assert latency.oldest_age() == pytest.approx(2.0)
        c.close("t")
    finally:
        latency.reset()

"""Tenant observatory (utils/provenance.py + per-tenant attribution).

Contracts pinned here:
- the ledger's WAL-style framing: CRC-framed canonical-JSON records,
  segment rotation at GS_WAL_SEGMENT_BYTES, GS_PROVENANCE_RETAIN
  pruning of closed segments, torn-TAIL tolerance (reopen truncates,
  scan reports) vs typed ProvenanceCorrupt anywhere else;
- every finalize owner emits: the fused-scan engine, the host twin,
  the GNN engine, the driver, and the tenant cohort (resident tier
  included) each write one record per finalized window at the
  checkpoint's own wal-offset cursor arithmetic;
- kill -> checkpoint-resume -> WAL-replay re-emits byte-identical
  payloads for the replayed windows (records carry no wall clock and
  no process identity), and the deduped ledger equals a fault-free
  oracle's;
- tools/replay_window re-derives every record on the host twin AND
  the fused scan tier, and the two tiers agree;
- cost attribution reconciles EXACTLY (DESIGN.md section 24): the
  attributed per-tenant seconds of one dispatch sum bit-for-bit to
  the span's measured seconds, pad rows attribute zero;
- /healthz serves ranked `hot_tenants` off the attribution table
  under the GS_METRICS_SERIES cardinality collapse;
- GS_PROVENANCE=0 (the default) is inert: no directory, no records,
  and summaries bit-identical to an armed run's.
"""

import glob
import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.core.tenancy import TenantCohort
from gelly_streaming_tpu.ops import gnn_window as gw
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.parallel.host_twin import HostSummaryEngine
from gelly_streaming_tpu.utils import metrics, provenance
from tools import replay_window

EB, VB = 128, 256


@pytest.fixture
def armed(monkeypatch, tmp_path):
    d = str(tmp_path / "prov")
    monkeypatch.setenv("GS_PROVENANCE", "1")
    monkeypatch.setenv("GS_PROVENANCE_DIR", d)
    provenance.reset()
    yield d
    provenance.reset()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("GS_METRICS", "1")
    metrics.reset()
    yield
    metrics.reset()


def make_edges(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, VB, n, dtype=np.int32),
            rng.integers(0, VB, n, dtype=np.int32))


def _payloads(dirpath):
    return [provenance._encode_payload(r)
            for r in provenance.scan(dirpath)["records"]]


# ----------------------------------------------------------------------
# ledger mechanics
# ----------------------------------------------------------------------
def test_disarmed_default_is_inert_with_digest_parity(
        monkeypatch, tmp_path, armed):
    src, dst = make_edges(2 * EB)
    ref = StreamSummaryEngine(edge_bucket=EB,
                              vertex_bucket=VB).process(src, dst)
    assert len(provenance.scan(armed)["records"]) == 2

    monkeypatch.setenv("GS_PROVENANCE", "0")
    provenance.reset()
    assert not provenance.armed()
    before = len(_payloads(armed))
    out = StreamSummaryEngine(edge_bucket=EB,
                              vertex_bucket=VB).process(src, dst)
    # no new records, and the summaries are bit-identical to the
    # armed run's (the ledger observes, never participates)
    assert len(_payloads(armed)) == before
    assert out == ref
    provenance.emit(tenant="t", window=0, wal_lo=0, wal_hi=1,
                    tier="x", program="x", summary={})  # guarded no-op
    assert len(_payloads(armed)) == before


def test_emit_canonical_framing_roundtrip(armed):
    rec = dict(tenant="t-1", window=3, wal_lo=384, wal_hi=512,
               tier="cohort", program="cohort_scan", sig="sig0",
               summary={"triangles": 7, "max_degree": 2})
    provenance.emit(**rec)
    provenance.emit(**rec)
    got = provenance.scan(armed)
    assert got["torn"] is None and got["segments"] == 1
    a, b = got["records"]
    assert a == b
    assert a["tenant"] == "t-1" and a["window"] == 3
    assert (a["wal_lo"], a["wal_hi"]) == (384, 512)
    assert (a["tier"], a["program"], a["sig"]) == ("cohort",
                                                   "cohort_scan",
                                                   "sig0")
    assert a["digest"] == provenance.summary_digest(rec["summary"])
    assert a["knobs"] == provenance.knob_fingerprint()
    assert sorted(a) == list(provenance.FIELDS)
    # identical records frame to identical bytes (the replay-identity
    # substrate): the segment is magic + twice the same frame
    seg = glob.glob(os.path.join(armed, "prov_*.seg"))[0]
    with open(seg, "rb") as f:
        data = f.read()
    body = data[len(provenance._MAGIC):]
    assert len(body) % 2 == 0
    assert body[:len(body) // 2] == body[len(body) // 2:]


def test_segment_rotation_keeps_every_record(monkeypatch, armed):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    for w in range(64):
        provenance.emit(tenant="t", window=w, wal_lo=w * EB,
                        wal_hi=(w + 1) * EB, tier="cohort",
                        program="cohort_scan", summary={"w": w})
    got = provenance.scan(armed)
    assert got["torn"] is None
    assert got["segments"] >= 2
    assert [r["window"] for r in got["records"]] == list(range(64))


def test_retention_prunes_closed_segments_never_reuses_names(
        monkeypatch, armed):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("GS_PROVENANCE_RETAIN", "1")
    for w in range(64):
        provenance.emit(tenant="t", window=w, wal_lo=0, wal_hi=EB,
                        tier="cohort", program="cohort_scan",
                        summary={"w": w})
    segs = sorted(os.path.basename(p) for p in
                  glob.glob(os.path.join(armed, "prov_*.seg")))
    # at most the retained closed segment + the open one survive
    assert 1 <= len(segs) <= 2
    assert segs[0] != "prov_00000000.seg"  # the prefix was pruned
    got = provenance.scan(armed)
    assert got["torn"] is None
    assert 0 < len(got["records"]) < 64
    # reopening continues PAST the highest existing name (a
    # count-derived index would re-open a live segment mid-file)
    provenance.reset()
    provenance.emit(tenant="t", window=99, wal_lo=0, wal_hi=EB,
                    tier="cohort", program="cohort_scan",
                    summary={"w": 99})
    newest = sorted(os.path.basename(p) for p in
                    glob.glob(os.path.join(armed, "prov_*.seg")))[-1]
    assert newest > segs[-1]


def test_torn_tail_tolerated_and_quarantined_on_reopen(armed):
    for w in range(3):
        provenance.emit(tenant="t", window=w, wal_lo=w * EB,
                        wal_hi=(w + 1) * EB, tier="cohort",
                        program="cohort_scan", summary={"w": w})
    provenance.reset()
    seg = sorted(glob.glob(os.path.join(armed, "prov_*.seg")))[-1]
    clean = os.path.getsize(seg)
    with open(seg, "ab") as f:
        f.write(b"\x13\x37")  # a crash's torn partial header
    got = provenance.scan(armed)
    assert [r["window"] for r in got["records"]] == [0, 1, 2]
    assert got["torn"] is not None
    assert got["torn"]["dropped_bytes"] == 2
    # reopening (the next armed emit) truncates the torn bytes —
    # the record was never acknowledged durable — and continues
    provenance.emit(tenant="t", window=3, wal_lo=3 * EB,
                    wal_hi=4 * EB, tier="cohort",
                    program="cohort_scan", summary={"w": 3})
    assert os.path.getsize(seg) == clean
    got = provenance.scan(armed)
    assert got["torn"] is None
    assert [r["window"] for r in got["records"]] == [0, 1, 2, 3]


def test_mid_ledger_corruption_raises_typed(monkeypatch, armed):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    for w in range(64):
        provenance.emit(tenant="t", window=w, wal_lo=0, wal_hi=EB,
                        tier="cohort", program="cohort_scan",
                        summary={"w": w})
    segs = sorted(glob.glob(os.path.join(armed, "prov_*.seg")))
    assert len(segs) >= 2
    # flip one payload byte in a CLOSED (non-last) segment: that is
    # an audit hole, never a tolerable torn tail
    with open(segs[0], "r+b") as f:
        f.seek(-2, os.SEEK_END)
        b = f.read(1)
        f.seek(-2, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(provenance.ProvenanceCorrupt) as ei:
        provenance.scan(armed)
    assert ei.value.path == segs[0]


def test_knob_fingerprint_excludes_path_knobs(monkeypatch, tmp_path):
    provenance.reset()
    fp0 = provenance.knob_fingerprint()
    # path-kind knobs are deployment-local: the fingerprint must
    # survive a migration to a host with different directories
    monkeypatch.setenv("GS_PROVENANCE_DIR", str(tmp_path / "elsewhere"))
    assert provenance.knob_fingerprint() == fp0
    # a value-shaping knob IS configuration identity
    monkeypatch.setenv("GS_METRICS_SERIES", "63")
    fp1 = provenance.knob_fingerprint()
    assert fp1 != fp0
    monkeypatch.delenv("GS_METRICS_SERIES")
    assert provenance.knob_fingerprint() == fp0


# ----------------------------------------------------------------------
# finalize-owner coverage
# ----------------------------------------------------------------------
def _check_engine_records(dirpath, out, tier, program, n_edges):
    got = provenance.scan(dirpath)
    assert got["torn"] is None
    recs = [r for r in got["records"] if r["tier"] == tier]
    assert len(recs) == len(out)
    for w, r in enumerate(recs):
        assert r["window"] == w
        assert r["wal_lo"] == w * EB
        assert r["wal_hi"] == min((w + 1) * EB, n_edges)
        assert r["program"] == program
        assert r["digest"] == provenance.summary_digest(out[w])
        assert r["knobs"] == provenance.knob_fingerprint()
    return recs


def test_fused_scan_engine_emits(armed):
    src, dst = make_edges(3 * EB, seed=1)
    out = StreamSummaryEngine(edge_bucket=EB,
                              vertex_bucket=VB).process(src, dst)
    recs = _check_engine_records(armed, out, "fused_scan",
                                 "fused_scan", 3 * EB)
    assert all(r["tenant"] == "engine" for r in recs)


def test_host_twin_emits_and_agrees_with_scan(armed):
    src, dst = make_edges(3 * EB, seed=1)
    host = HostSummaryEngine(edge_bucket=EB,
                             vertex_bucket=VB).process(src, dst)
    _check_engine_records(armed, host, "host", "fused_scan", 3 * EB)
    scan_recs = [r for r in provenance.scan(armed)["records"]
                 if r["tier"] == "fused_scan"]
    if not scan_recs:  # the scan tier run lives in the test above
        scan_out = StreamSummaryEngine(
            edge_bucket=EB, vertex_bucket=VB).process(src, dst)
        scan_recs = [r for r in provenance.scan(armed)["records"]
                     if r["tier"] == "fused_scan"]
        assert scan_out == host
    # cross-tier: same stream, same digests, different tier label
    assert ([r["digest"] for r in scan_recs]
            == [provenance.summary_digest(s) for s in host])


def test_gnn_engine_emits(armed):
    F = 4
    src, dst = make_edges(2 * EB, seed=5)
    rngw = np.random.RandomState(2)
    eng = gw.GnnSummaryEngine(EB, VB, feature_dim=F)
    eng.set_weights(rngw.randn(F, F) * 0.3, rngw.randn(F) * 0.1)
    eng.load_feature_units(gw.default_features(VB, F, seed=3))
    out = eng.process(src, dst)
    _check_engine_records(armed, out, "gnn_scan", "gnn_round", 2 * EB)


def test_driver_emits_and_rerun_ledger_is_identical(
        monkeypatch, tmp_path):
    src, dst = make_edges(2 * EB, seed=9)
    src, dst = src.astype(np.int64), dst.astype(np.int64)
    ledgers = []
    for run in ("a", "b"):
        d = str(tmp_path / ("prov_" + run))
        monkeypatch.setenv("GS_PROVENANCE", "1")
        monkeypatch.setenv("GS_PROVENANCE_DIR", d)
        provenance.reset()
        drv = StreamingAnalyticsDriver(
            window_ms=1000, analytics=("degrees", "cc"),
            vertex_bucket=VB, edge_bucket=EB)
        results = drv.run_arrays(src, dst)
        recs = provenance.scan(d)["records"]
        assert len(recs) == len(results) == 2
        for w, r in enumerate(recs):
            assert r["program"] == "driver"
            assert r["window"] == w
            assert (r["wal_lo"], r["wal_hi"]) == (w * EB, (w + 1) * EB)
            assert r["digest"] == provenance.result_digest(results[w])
        ledgers.append(_payloads(d))
        provenance.reset()
    # no wall clock, no process identity: a re-run writes the very
    # same bytes (the chaos leg's replay-identity contract in small)
    assert ledgers[0] == ledgers[1]


def test_cohort_emits_per_tenant_and_resident_tier(
        monkeypatch, tmp_path):
    src, dst = make_edges(2 * EB, seed=3)
    for mode, tier in (("off", "cohort"), ("on", "cohort_resident")):
        d = str(tmp_path / ("prov_" + mode))
        monkeypatch.setenv("GS_PROVENANCE", "1")
        monkeypatch.setenv("GS_PROVENANCE_DIR", d)
        monkeypatch.setenv("GS_COHORT_RESIDENT", mode)
        provenance.reset()
        co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
        delivered = {}
        for tid in ("p0", "p1"):
            co.admit(tid)
            co.feed(tid, src, dst)
        for tid, rows in co.pump().items():
            delivered.setdefault(tid, []).extend(rows)
        recs = provenance.scan(d)["records"]
        assert all(r["tier"] == tier for r in recs), mode
        assert all(r["program"] == "cohort_scan" for r in recs)
        for tid, rows in delivered.items():
            mine = [r for r in recs if r["tenant"] == tid]
            assert [r["window"] for r in mine] == list(range(len(rows)))
            assert ([r["digest"] for r in mine]
                    == [provenance.summary_digest(s) for s in rows])
        provenance.reset()


# ----------------------------------------------------------------------
# kill -> replay identity, and the replay oracle tool
# ----------------------------------------------------------------------
def _cohort(wal_dir, ckpt_dir, tids=()):
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    assert co.enable_wal(wal_dir)
    co.enable_auto_checkpoint(ckpt_dir, every_n_windows=2)
    for tid in tids:
        co.admit(tid)
    return co


def _feed_rounds(co, streams, splits):
    out = {tid: [] for tid in streams}
    for lo, hi in splits:
        for tid, (s, d) in streams.items():
            co.feed(tid, s[lo * EB:hi * EB], d[lo * EB:hi * EB])
        for tid, rows in co.pump().items():
            out[tid].extend(rows)
    return out


def test_kill_replay_reemits_byte_identical_records(
        monkeypatch, tmp_path):
    monkeypatch.setenv("GS_WAL", "1")
    monkeypatch.setenv("GS_COHORT_RESIDENT", "off")
    monkeypatch.setenv("GS_PROVENANCE", "1")
    streams = {"p0": make_edges(3 * EB, seed=11),
               "p1": make_edges(3 * EB, seed=12)}

    # fault-free oracle in its own directories
    oracle_prov = str(tmp_path / "oracle_prov")
    monkeypatch.setenv("GS_PROVENANCE_DIR", oracle_prov)
    provenance.reset()
    oracle_co = _cohort(str(tmp_path / "oracle_wal"),
                        str(tmp_path / "oracle_ckpt"), streams)
    oracle_out = _feed_rounds(oracle_co, streams, [(0, 2), (2, 3)])
    oracle = _payloads(oracle_prov)

    # the victim: same rounds, then a kill after the second pump —
    # the checkpoint covers 2 windows, the WAL all 3
    prov = str(tmp_path / "prov")
    monkeypatch.setenv("GS_PROVENANCE_DIR", prov)
    provenance.reset()
    co = _cohort(str(tmp_path / "wal"), str(tmp_path / "ckpt"),
                 streams)
    out = _feed_rounds(co, streams, [(0, 2), (2, 3)])
    assert out == oracle_out
    before = _payloads(prov)
    assert sorted(before) == sorted(oracle)

    provenance.reset()  # the process dies; a fresh one reopens
    co2 = _cohort(str(tmp_path / "wal"), str(tmp_path / "ckpt"))
    rec = co2.recover()
    assert rec["replayed_edges"] == {"p0": EB, "p1": EB}
    redelivered = co2.pump()
    for tid, rows in redelivered.items():
        assert rows == out[tid][2:]
    after = provenance.scan(prov)["records"]
    # the replayed window re-emitted: duplicates, byte-identical to
    # the first run's records for the same (tenant, window)
    assert len(after) == len(before) + 2
    dup = {provenance._encode_payload(r) for r in after}
    assert dup == set(before)


@pytest.fixture
def replayable(monkeypatch, tmp_path):
    """One armed cohort run (WAL + checkpoints + ledger) shared by
    the replay-oracle tests."""
    monkeypatch.setenv("GS_WAL", "1")
    monkeypatch.setenv("GS_COHORT_RESIDENT", "off")
    monkeypatch.setenv("GS_PROVENANCE", "1")
    prov = str(tmp_path / "prov")
    wal = str(tmp_path / "wal")
    ckpt = str(tmp_path / "ckpt")
    monkeypatch.setenv("GS_PROVENANCE_DIR", prov)
    provenance.reset()
    co = _cohort(wal, ckpt)
    streams = {"p0": make_edges(3 * EB, seed=21),
               "p1": make_edges(3 * EB, seed=22)}
    for tid in streams:
        co.admit(tid)
    _feed_rounds(co, streams, [(0, 3)])
    yield {"prov": prov, "wal": wal, "ckpt": ckpt}
    provenance.reset()


def test_replay_window_verifies_on_two_tiers(replayable):
    digests = {}
    for tier in ("host", "scan"):
        rep = replay_window.replay_all(
            replayable["prov"], replayable["wal"],
            ckpt=replayable["ckpt"], tier=tier, eb=EB, vb=VB)
        assert rep["records"] == 6
        assert rep["verified"] == 6
        assert rep["mismatched"] == 0 and rep["skipped"] == 0
        assert rep["torn"] is None
        digests[tier] = {(r["tenant"], r["window"]): r["computed"]
                         for r in rep["rows"]}
    # the two replay tiers agree with each other, not just the ledger
    assert digests["host"] == digests["scan"]


def test_replay_window_reports_unreplayable_records(replayable,
                                                    tmp_path):
    empty = str(tmp_path / "no_wal")
    os.makedirs(empty)
    rep = replay_window.replay_all(replayable["prov"], empty,
                                   tier="host", eb=EB, vb=VB)
    # a record that cannot be replayed is REPORTED, never dropped
    assert rep["records"] == 6
    assert rep["verified"] == 0
    assert rep["skipped"] == 6
    assert all(r["skipped"] and not r["ok"] for r in rep["rows"])


# ----------------------------------------------------------------------
# per-tenant cost attribution + /healthz hot tenants
# ----------------------------------------------------------------------
def test_attribution_reconciles_exactly(metrics_on):
    span = 0.123456789
    rows = [("hot", 3 * EB), ("pad", 0), ("warm", EB), ("cold", 17)]
    out = metrics.attribute_dispatch(span, rows)
    assert [t for t, _s, _b in out] == ["hot", "pad", "warm", "cold"]
    by = {t: s for t, s, _b in out}
    assert by["pad"] == 0.0
    assert by["hot"] > by["warm"] > by["cold"] > 0.0
    # the reconciliation bugfix (DESIGN.md section 24): bit-for-bit,
    # not approximately — the last nonzero row absorbs the residue
    assert sum(s for _t, s, _b in out) == span
    # degenerate spans attribute nothing rather than divide by zero
    assert metrics.attribute_dispatch(span, [("a", 0)]) is None
    assert metrics.attribute_dispatch(-1.0, rows) is None


def test_attribution_disarmed_is_none(monkeypatch):
    monkeypatch.setenv("GS_METRICS", "0")
    metrics.reset()
    assert metrics.attribute_dispatch(1.0, [("a", 10)]) is None


def test_healthz_serves_ranked_hot_tenants(metrics_on):
    metrics.attribute_dispatch(3.0, [("big", 3 * EB), ("small", EB)])
    snap = metrics.health_snapshot()
    assert snap["tenants"]["big"]["device_s"] == pytest.approx(2.25)
    assert snap["tenants"]["small"]["device_s"] == pytest.approx(0.75)
    hot = snap["hot_tenants"]
    assert [r["tenant"] for r in hot] == ["big", "small"]
    assert hot[0]["device_share"] == pytest.approx(0.75)
    assert hot[0]["score"] >= hot[1]["score"]
    assert metrics.hot_tenants(snap, k=1) == hot[:1]


def test_attribution_respects_cardinality_bound(monkeypatch,
                                                metrics_on):
    monkeypatch.setenv("GS_METRICS_SERIES", "2")
    metrics.attribute_dispatch(
        1.0, [("t%d" % i, EB) for i in range(6)])
    snap = metrics.health_snapshot()
    tens = snap["tenants"]
    # past the bound new tenants collapse into ONE overflow row; the
    # table (and therefore /healthz) stays bounded
    assert len(tens) <= 3
    assert "overflow" in tens
    # device_s is served rounded to 6 decimals, so the roll-up
    # tolerance is the rounding grain, not the exact-sum contract
    # (that one is pinned un-rounded in reconciles_exactly above)
    assert sum(r["device_s"] for r in tens.values()) \
        == pytest.approx(1.0, abs=1e-5)
    assert len(snap["hot_tenants"]) == len(tens)

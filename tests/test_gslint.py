"""The project invariant checker, in tier-1 (marker `lint`).

Two jobs: (a) the REAL tree must be gslint-clean — zero non-baseline
findings — so a new unsanctioned host-sync, impure jit read, raw env
read, silent swallow, unguarded shared mutable, or asymmetric
checkpoint key is a test failure, not a review hope; (b) the linter
itself is pinned by fixture-backed true-positive AND true-negative
cases per rule (tests/fixtures/gslint/repo mirrors the package
layout), plus schema/baseline/determinism guards, so rule edits can't
silently go blind."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_REPO = os.path.join(REPO, "tests", "fixtures", "gslint", "repo")


def _gslint():
    if "tools.gslint" in sys.modules:
        return sys.modules["tools.gslint"]
    spec = importlib.util.spec_from_file_location(
        "tools.gslint", os.path.join(REPO, "tools", "gslint",
                                     "__init__.py"),
        submodule_search_locations=[os.path.join(REPO, "tools",
                                                 "gslint")])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tools.gslint"] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def gslint():
    return _gslint()


@pytest.fixture(scope="module")
def fixture_findings(gslint):
    """One lint pass over the fixture repo, baseline-free."""
    return gslint.run_lint(["gelly_streaming_tpu"], baseline_path=None,
                           repo=FIXTURE_REPO)


def _hits(findings, rule, path=None):
    return [f for f in findings
            if f.rule == rule and (path is None or f.path == path)]


# ----------------------------------------------------------------------
# the real tree
# ----------------------------------------------------------------------
def test_package_is_clean(gslint):
    """`python -m tools.gslint gelly_streaming_tpu` == exit 0: every
    finding is grandfathered in the committed baseline, pragma'd with
    a reason, or fixed. THE tier-1 invariant gate."""
    findings = gslint.run_lint(["gelly_streaming_tpu"])
    new = [f for f in findings if not f.baselined]
    assert not new, "\n".join(f.render() for f in new)


def test_cli_exit_zero_and_json_schema(gslint, tmp_path):
    """The committed entrypoint, end to end: exit 0 and a
    schema-clean JSON report (perf_schema conventions)."""
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.gslint", "gelly_streaming_tpu",
         "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert gslint.validate_report(report) == []
    assert report["counts"]["new"] == 0


def test_baseline_policy(gslint):
    """The baseline is R1-only grandfathering and only ever shrinks:
    122 entries at introduction, 111 after the ISSUE-8 burn-down, 104
    after ISSUE-9's (ops/autotune + ops/compact_ingress reasoned
    pragmas), 94 after ISSUE-10's (triangles/sharded finalize-boundary
    and host-input pragmas), 88 after ISSUE-11's (windowed_reduce
    finalize/host-input pragmas), 56 after ISSUE-19's (segment
    window_stack, unionfind double_cover_edges and the windowed_reduce
    numpy_reference oracle — all host-input/host-oracle pragmas), 52
    after ISSUE-20's (mesh/multihost device-handle layouts and the
    triangles committed-evidence read — no device value in sight). If
    this fails with MORE entries, someone
    regenerated it to absorb new findings — fix the findings
    instead."""
    baseline = gslint.load_baseline()
    assert baseline, "committed baseline missing"
    assert all(key[0] == "R1" for key in baseline), (
        "baseline may only grandfather R1 host-sync sites")
    assert len(baseline) <= 52
    # every entry still corresponds to a live finding: stale entries
    # (the flagged line was fixed or deleted) must be pruned so the
    # baseline can't silently absorb a future regression at that key
    findings = gslint.run_lint(["gelly_streaming_tpu"],
                               baseline_path=None)
    live = {f.key() for f in findings}
    stale = [k for k in baseline if k not in live]
    assert not stale, "prune fixed sites from baseline.json: %r" % stale


def test_deterministic_and_cwd_independent(gslint, tmp_path,
                                           monkeypatch):
    """Hermeticity: two runs agree exactly, and the verdict doesn't
    depend on the working directory or runtime state (the property
    tools/chaos_run.py's gslint leg pins after a soak)."""
    a = gslint.run_lint(["gelly_streaming_tpu"])
    monkeypatch.chdir(tmp_path)
    b = gslint.run_lint(["gelly_streaming_tpu"])
    assert [f.to_json() for f in a] == [f.to_json() for f in b]


# ----------------------------------------------------------------------
# R1 host-sync
# ----------------------------------------------------------------------
def test_r1_true_positives(fixture_findings):
    msgs = [f.message for f in _hits(fixture_findings, "R1",
                                     "gelly_streaming_tpu/fix_r1.py")]
    assert len(msgs) == 5
    for surface in ("np.asarray", "jax.device_get", ".item()",
                    ".block_until_ready()", "float(<device expr>)"):
        assert any(surface in m for m in msgs), surface


def test_r1_true_negatives(fixture_findings):
    # pragma'd call and float(name) inside the jax module: not flagged
    bad = [f for f in _hits(fixture_findings, "R1",
                            "gelly_streaming_tpu/fix_r1.py")
           if f.symbol == "fine"]
    assert bad == []
    # no jax import at all: np.asarray is numpy-on-numpy
    assert _hits(fixture_findings, "R1",
                 "gelly_streaming_tpu/fix_r1_host.py") == []


def test_r1_sanctioned_modules_exempt(gslint):
    """The sanctioned egress sites are exactly where sync lives — no
    R1 findings there by construction."""
    findings = gslint.run_lint(["gelly_streaming_tpu"],
                               baseline_path=None)
    for path in ("gelly_streaming_tpu/core/driver.py",
                 "gelly_streaming_tpu/ops/delta_egress.py",
                 "gelly_streaming_tpu/parallel/host_twin.py"):
        assert _hits(findings, "R1", path) == []


# ----------------------------------------------------------------------
# R2 jit purity
# ----------------------------------------------------------------------
def test_r2_true_positives(fixture_findings):
    hits = _hits(fixture_findings, "R2",
                 "gelly_streaming_tpu/fix_r2.py")
    # _kernel: a Pallas kernel body is a traced root too (the fused
    # window megakernel made pallas_call part of the traced surface)
    assert {f.symbol for f in hits} == {"_step", "_kernel"}
    msgs = " ".join(f.message for f in hits)
    assert "os.environ" in msgs
    assert "time.perf_counter" in msgs
    assert "_MEMO" in msgs
    assert "knobs.get_bool" in msgs
    assert "metrics-registry" in msgs
    assert "cost-observatory" in msgs


def test_r2_true_negatives(fixture_findings):
    # the identical reads in host_only() are fine: never traced
    assert not [f for f in _hits(fixture_findings, "R2")
                if f.symbol == "host_only"]


# ----------------------------------------------------------------------
# R3 knob registry
# ----------------------------------------------------------------------
def test_r3_true_positives(fixture_findings):
    hits = _hits(fixture_findings, "R3",
                 "gelly_streaming_tpu/fix_r3.py")
    msgs = " ".join(f.message for f in hits)
    assert "os.environ" in msgs
    assert "GS_TELEMETRYY" in msgs


def test_r3_true_negatives(fixture_findings):
    # the registered name literal is not flagged
    assert not any("'GS_TELEMETRY'" in f.message
                   for f in _hits(fixture_findings, "R3"))


def test_r3_readme_drift(fixture_findings):
    drift = _hits(fixture_findings, "R3", "README.md")
    assert len(drift) == 1
    assert "stale row `GS_TELEMETRY`" in drift[0].message
    assert "unregistered row `GS_NOT_A_KNOB`" in drift[0].message


def test_r3_real_readme_in_sync(gslint):
    """The committed README contains the registry-rendered table
    verbatim (regenerate: python -m tools.gslint --knob-table)."""
    from tools.gslint.rules import KnobRegistryRule

    table = KnobRegistryRule.registry().render_table()
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        assert table in f.read()


# ----------------------------------------------------------------------
# R4 exception hygiene
# ----------------------------------------------------------------------
def test_r4_true_positives(fixture_findings):
    hits = _hits(fixture_findings, "R4",
                 "gelly_streaming_tpu/fix_r4.py")
    assert len(hits) == 2
    assert all(f.symbol == "swallows" for f in hits)


def test_r4_true_negatives(fixture_findings):
    assert not [f for f in _hits(fixture_findings, "R4")
                if f.symbol == "compliant"]


# ----------------------------------------------------------------------
# R5 thread-shared state
# ----------------------------------------------------------------------
def test_r5_true_positive(fixture_findings):
    hits = _hits(fixture_findings, "R5",
                 "gelly_streaming_tpu/ops/ingress_pipeline.py")
    assert ["_UNGUARDED"] == [
        f.message.split("`")[1] for f in hits]


def test_r5_true_negatives(fixture_findings):
    msgs = " ".join(f.message
                    for f in _hits(fixture_findings, "R5"))
    assert "_GUARDED" not in msgs   # lock-guarded
    assert "_TABLE" not in msgs     # read-only after import


# ----------------------------------------------------------------------
# R6 checkpoint symmetry
# ----------------------------------------------------------------------
def test_r6_true_positives(fixture_findings):
    hits = _hits(fixture_findings, "R6",
                 "gelly_streaming_tpu/fix_r6.py")
    msgs = " ".join(f.message for f in hits)
    assert "orphan_saved" in msgs   # written, never read
    assert "orphan_loaded" in msgs  # read, never written
    assert len(hits) == 2


def test_r6_true_negatives(fixture_findings):
    msgs = " ".join(f.message for f in _hits(fixture_findings, "R6"))
    assert "Symmetric" not in msgs
    assert "Provenance" not in msgs  # pragma'd provenance key


# ----------------------------------------------------------------------
# framework mechanics
# ----------------------------------------------------------------------
def test_baseline_counts_consume(gslint):
    """N grandfathered copies of a key never absolve an N+1th."""
    f1 = gslint.Finding("R1", "host-sync", "p.py", 3, 0, "m", "s", "c")
    f2 = gslint.Finding("R1", "host-sync", "p.py", 9, 0, "m", "s", "c")
    gslint.apply_baseline([f1, f2], {f1.key(): 1})
    assert [f1.baselined, f2.baselined] == [True, False]


def test_validate_report_rejects_malformed(gslint):
    good = gslint.report_json([], ["x"])
    assert gslint.validate_report(good) == []
    assert gslint.validate_report([]) != []
    bad = gslint.report_json([], ["x"])
    bad["findings"] = [{"rule": "R9"}]
    problems = gslint.validate_report(bad)
    assert any("unknown rule" in p for p in problems)
    assert any("missing" in p for p in problems)
    drifted = gslint.report_json([], ["x"])
    drifted["counts"]["per_rule"] = {"R1": 5}
    assert any("does not sum" in p
               for p in gslint.validate_report(drifted))

"""Write-ahead edge journal unit suite (utils/wal.py): record
framing + CRC, segment rotation, torn-tail fallback vs mid-journal
corruption, reopen/quarantine, offset-trimmed replay, seal, bounded
retention — plus the edge-source EOF regression the journal's
durability story leans on (a final line with no trailing newline is
never stranded)."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.io import sources
from gelly_streaming_tpu.utils import wal

pytestmark = pytest.mark.faults


def _edges(n, seed=0, dtype=np.int32):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 100, n).astype(dtype),
            rng.integers(0, 100, n).astype(dtype))


def _mk(tmp_path, name="wal"):
    return wal.WriteAheadLog(str(tmp_path / name))


# ----------------------------------------------------------------------
# framing / offsets / replay
# ----------------------------------------------------------------------
def test_append_replay_roundtrip(tmp_path):
    w = _mk(tmp_path)
    s1, d1 = _edges(5, 1)
    s2, d2 = _edges(3, 2)
    assert w.append("t1", s1, d1) == (0, 5)
    assert w.append("t1", s2, d2) == (5, 8)
    assert w.offsets() == {"t1": 8}
    w.close()
    got = list(wal.replay(w.dir))
    assert [(t, st) for t, st, *_ in got] == [("t1", 0), ("t1", 5)]
    np.testing.assert_array_equal(got[0][2], s1)
    np.testing.assert_array_equal(got[1][3], d2)
    assert got[0][4] is None


def test_replay_trims_straddling_record(tmp_path):
    w = _mk(tmp_path)
    s, d = _edges(10, 3)
    w.append("t1", s, d)
    w.close()
    (tid, start, rs, rd, _ts), = wal.replay(w.dir, {"t1": 4})
    assert (tid, start) == ("t1", 4)
    np.testing.assert_array_equal(rs, s[4:])
    np.testing.assert_array_equal(rd, d[4:])
    # fully covered: nothing replays
    assert list(wal.replay(w.dir, {"t1": 10})) == []


def test_int64_and_timestamps_roundtrip(tmp_path):
    w = _mk(tmp_path)
    s, d = _edges(4, 4, dtype=np.int64)
    ts = np.array([10, 20, 30, 40], np.int64)
    w.append("drv", s, d, ts=ts)
    w.close()
    (_t, _st, rs, _rd, rts), = wal.replay(w.dir)
    assert rs.dtype == np.int64
    np.testing.assert_array_equal(rts, ts)


def test_per_tenant_interleaving(tmp_path):
    w = _mk(tmp_path)
    for i in range(3):
        w.append("a", *_edges(2, i))
        w.append("b", *_edges(4, 10 + i))
    assert w.offsets() == {"a": 6, "b": 12}
    w.close()
    info = wal.scan(w.dir)
    assert info["offsets"] == {"a": 6, "b": 12}
    assert info["seqs"] == {"a": 3, "b": 3}
    assert info["records"] == 6 and not info["sealed"]
    # replay with one tenant fully covered yields only the other
    got = list(wal.replay(w.dir, {"a": 6}))
    assert {t for t, *_ in got} == {"b"}


# ----------------------------------------------------------------------
# segment rotation & retention
# ----------------------------------------------------------------------
def test_segment_rotation_and_reopen(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    w = _mk(tmp_path)
    for i in range(6):
        w.append("t", np.zeros(900, np.int32), np.zeros(900, np.int32))
    w.close()
    segs = [f for f in os.listdir(w.dir) if f.endswith(".seg")]
    assert len(segs) > 1  # rotation happened
    assert wal.scan(w.dir)["offsets"] == {"t": 5400}
    # reopen recovers offsets and continues in a FRESH segment
    w2 = wal.WriteAheadLog(w.dir)
    assert w2.offsets() == {"t": 5400}
    assert w2.append("t", *_edges(1)) == (5400, 5401)
    w2.close()
    assert wal.scan(w.dir)["records"] == 7


def test_truncate_covered_never_deletes_uncheckpointed(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    w = _mk(tmp_path)
    for i in range(6):
        w.append("t", np.zeros(900, np.int32), np.zeros(900, np.int32))
    before = len([f for f in os.listdir(w.dir)
                  if f.endswith(".seg")])
    removed = w.truncate_covered({"t": 1800})  # first 2 records
    after = len([f for f in os.listdir(w.dir) if f.endswith(".seg")])
    assert removed >= 1 and after == before - removed
    # the un-covered suffix still replays intact
    got = list(wal.replay(w.dir, {"t": 1800}))
    assert sum(len(s) for _t, _st, s, _d, _ts in got) == 3600
    w.close()


# ----------------------------------------------------------------------
# damage: torn tail tolerated, anything else typed-raises
# ----------------------------------------------------------------------
def test_torn_tail_falls_back_one_record(tmp_path):
    w = _mk(tmp_path)
    w.append("t", *_edges(5, 1))
    w.append("t", *_edges(5, 2))
    w.close()
    seg = sorted(os.path.join(w.dir, f) for f in os.listdir(w.dir))[0]
    with open(seg, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 3)
    info = wal.scan(w.dir)
    assert info["records"] == 1 and info["offsets"] == {"t": 5}
    assert info["torn"] is not None
    assert len(list(wal.replay(w.dir))) == 1


def test_crc_flip_at_tail_is_torn(tmp_path):
    w = _mk(tmp_path)
    w.append("t", *_edges(5, 1))
    w.append("t", *_edges(5, 2))
    w.close()
    seg = sorted(os.path.join(w.dir, f) for f in os.listdir(w.dir))[0]
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.seek(size - 2)
        b = f.read(1)
        f.seek(size - 2)
        f.write(bytes([b[0] ^ 0xFF]))
    info = wal.scan(w.dir)
    assert info["records"] == 1
    assert "CRC" in info["torn"]["problem"]


def test_mid_journal_damage_raises_typed(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    w = _mk(tmp_path)
    for i in range(4):
        w.append("t", np.zeros(900, np.int32), np.zeros(900, np.int32))
    w.close()
    first = sorted(os.path.join(w.dir, f)
                   for f in os.listdir(w.dir))[0]
    with open(first, "r+b") as f:
        f.seek(30)
        f.write(b"\xde\xad")
    with pytest.raises(wal.WalCorrupt):
        list(wal.replay(w.dir))
    with pytest.raises(wal.WalCorrupt):
        wal.scan(w.dir)


def test_reopen_quarantines_torn_tail(tmp_path):
    """A reopened journal TRUNCATES the torn bytes before appending a
    fresh segment — otherwise the damaged tail would later read as
    mid-journal corruption once it is no longer the last segment."""
    w = _mk(tmp_path)
    w.append("t", *_edges(5, 1))
    w.append("t", *_edges(5, 2))
    w.close()
    seg = sorted(os.path.join(w.dir, f) for f in os.listdir(w.dir))[0]
    with open(seg, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 3)
    w2 = wal.WriteAheadLog(w.dir)   # quarantine happens here
    assert w2.offsets() == {"t": 5}
    assert w2.append("t", *_edges(2, 3)) == (5, 7)
    w2.close()
    # the whole journal (old segment no longer last) scans clean
    info = wal.scan(w.dir)
    assert info["torn"] is None and info["offsets"] == {"t": 7}


def test_seq_gap_raises_typed(tmp_path):
    w = _mk(tmp_path)
    w.append("t", *_edges(3, 1))
    w.append("t", *_edges(3, 2))
    w.append("t", *_edges(3, 3))
    w.close()
    # surgically remove the middle record from the segment
    seg = sorted(os.path.join(w.dir, f) for f in os.listdir(w.dir))[0]
    data = open(seg, "rb").read()
    head = 8  # magic
    import struct
    recs = []
    off = head
    while off < len(data):
        _crc, ln = struct.unpack_from("<II", data, off)
        recs.append(data[off:off + 8 + ln])
        off += 8 + ln
    with open(seg, "wb") as f:
        f.write(data[:head] + recs[0] + recs[2])
    with pytest.raises(wal.WalCorrupt, match="sequence gap"):
        list(wal.replay(w.dir))


# ----------------------------------------------------------------------
# seal & disarm
# ----------------------------------------------------------------------
def test_seal_marks_journal_and_refuses_appends(tmp_path):
    w = _mk(tmp_path)
    w.append("t", *_edges(3, 1))
    w.seal()
    assert wal.scan(w.dir)["sealed"] is True
    with pytest.raises(ValueError, match="sealed"):
        w.append("t", *_edges(1))
    # a reopened journal may accept a NEW stream (service restart)
    w2 = wal.WriteAheadLog(w.dir)
    w2.append("t", *_edges(2, 2))
    w2.close()
    assert wal.scan(w.dir)["sealed"] is False


def test_fsync_batching_interval(tmp_path, monkeypatch):
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd),
                                                 real(fd))[1])
    monkeypatch.setenv("GS_WAL_FSYNC_S", "3600")
    w = _mk(tmp_path)
    for i in range(5):
        w.append("t", *_edges(2, i))
    batched = len(calls)
    w.sync()
    assert len(calls) == batched + 1  # the forced flush
    monkeypatch.setenv("GS_WAL_FSYNC_S", "0")
    w.append("t", *_edges(2, 9))
    assert len(calls) == batched + 2  # per-append again
    w.close()


def test_gs_wal_zero_disarms_every_enable_site(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_WAL", "0")
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    cohort = TenantCohort(edge_bucket=64, vertex_bucket=128)
    assert cohort.enable_wal(str(tmp_path / "a")) is False
    eng = StreamSummaryEngine(edge_bucket=64, vertex_bucket=128)
    assert eng.enable_wal(str(tmp_path / "b")) is False
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=64,
                                   vertex_bucket=128)
    assert drv.enable_wal(str(tmp_path / "c")) is False
    # nothing was created: the disarmed path leaves no journal at all
    assert not os.path.exists(str(tmp_path / "a"))
    # and the disarmed digests are the journal-less ones by
    # construction (no WAL object exists to consult)
    cohort.admit("t")
    s = np.arange(64, dtype=np.int32) % 100
    cohort.feed("t", s, s[::-1].copy())
    plain = TenantCohort(edge_bucket=64, vertex_bucket=128)
    plain.admit("t")
    plain.feed("t", s, s[::-1].copy())
    assert cohort.pump() == plain.pump()


# ----------------------------------------------------------------------
# edge-source EOF regression (the satellite fix's pin): a file whose
# last line lacks a trailing newline must never strand its final
# record — sync path, prefetch path, and the serving file-tail
# ----------------------------------------------------------------------
@pytest.mark.parametrize("chunk_bytes", [4, 1 << 20])
def test_final_line_without_newline_is_flushed(tmp_path, chunk_bytes):
    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        f.write("1 2\n3 4\n5 6")  # no trailing newline
    got = list(sources._iter_edge_chunks_sync(p, chunk_bytes))
    src = np.concatenate([c[0] for c in got])
    dst = np.concatenate([c[1] for c in got])
    np.testing.assert_array_equal(src, [1, 3, 5])
    np.testing.assert_array_equal(dst, [2, 4, 6])


def test_final_line_without_newline_prefetch_path(tmp_path):
    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        f.write("7 8\n9 10")
    got = list(sources.iter_edge_chunks(p, chunk_bytes=4, prefetch=2))
    src = np.concatenate([c[0] for c in got])
    np.testing.assert_array_equal(src, [7, 9])


def test_tail_edge_file_flushes_final_partial_line(tmp_path):
    import threading

    p = str(tmp_path / "tail.txt")
    with open(p, "w") as f:
        f.write("1 2\n")
    stop = threading.Event()
    got = []

    def consume():
        for s, d, _ts in sources.tail_edge_file(p, stop,
                                                poll_s=0.01):
            got.append((s, d))

    t = threading.Thread(target=consume)
    t.start()
    import time

    time.sleep(0.1)
    with open(p, "a") as f:
        f.write("3 4\n5 6")  # appended; final line unterminated
    time.sleep(0.2)
    stop.set()
    t.join(5)
    assert not t.is_alive()
    src = np.concatenate([s for s, _d in got])
    np.testing.assert_array_equal(np.sort(src), [1, 3, 5])


def test_reopen_after_truncate_never_collides_segments(tmp_path,
                                                       monkeypatch):
    """Review fix: the next segment index derives from the highest
    EXISTING name, not the count — after truncate_covered() deletes
    prefix segments, a count-derived index re-opened a live segment
    and wrote a second magic header mid-file."""
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    w = _mk(tmp_path)
    for i in range(6):
        w.append("t", np.zeros(900, np.int32), np.zeros(900, np.int32))
    w.close()
    assert w.truncate_covered({"t": 1800}) >= 1
    w2 = wal.WriteAheadLog(w.dir)  # reopen AFTER the prefix deletion
    w2.append("t", *_edges(3, 9))
    w2.close()
    info = wal.scan(w.dir)  # a collision would raise / drop records
    assert info["torn"] is None
    assert info["offsets"] == {"t": 5403}


def test_append_canonicalizes_mismatched_dtypes(tmp_path):
    """Review fix: one itemsize frames BOTH id arrays — mismatched
    or exotic dtypes are canonicalized to int64 instead of replaying
    CRC-valid garbage."""
    w = _mk(tmp_path)
    w.append("t", np.array([1, 2], np.int32),
             np.array([3, 4], np.int64))
    w.append("t", np.array([5.0, 6.0]), np.array([7, 8], np.int16))
    w.close()
    recs = list(wal.replay(w.dir))
    np.testing.assert_array_equal(recs[0][2], [1, 2])
    np.testing.assert_array_equal(recs[0][3], [3, 4])
    np.testing.assert_array_equal(recs[1][2], [5, 6])
    np.testing.assert_array_equal(recs[1][3], [7, 8])
    assert recs[0][2].dtype == np.int64


def test_driver_rejected_batch_leaves_no_journal_record(tmp_path):
    """Review fix: run_arrays journals AFTER validation — a rejected
    batch (non-ascending timestamps) must leave the journal
    untouched, or replay re-raises the rejection and every later
    offset skews against edges_done."""
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

    drv = StreamingAnalyticsDriver(window_ms=100, edge_bucket=64,
                                   vertex_bucket=128)
    assert drv.enable_wal(str(tmp_path / "wal"))
    with pytest.raises(ValueError, match="ascending"):
        drv.run_arrays(np.array([1, 2]), np.array([3, 4]),
                       ts=np.array([500, 100]))
    assert wal.scan(str(tmp_path / "wal"))["records"] == 0
    # an accepted event-time batch DOES journal, with its timestamps
    drv.run_arrays(np.array([1, 2]), np.array([3, 4]),
                   ts=np.array([100, 500]))
    (_t, _s, _src, _dst, ts), = wal.replay(str(tmp_path / "wal"))
    np.testing.assert_array_equal(ts, [100, 500])


def test_stream_file_refused_on_journal_armed_driver(tmp_path):
    """Review fix: wal_offset is DEFINED as edges_done, and
    stream_file edges are never journaled — mixing the sources would
    make recovery skip journaled live edges, so it is refused."""
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        f.write("1 2\n")
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=64,
                                   vertex_bucket=128)
    assert drv.enable_wal(str(tmp_path / "wal"))
    with pytest.raises(ValueError, match="journal-armed"):
        list(drv.stream_file(p))


# ----------------------------------------------------------------------
# GS_WAL_RETAIN: truncation at checkpoint-flush boundaries
# ----------------------------------------------------------------------
def _retain_env(monkeypatch, tmp_path):
    monkeypatch.setenv("GS_WAL_RETAIN", "1")
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    return str(tmp_path / "wal")


def _segments(d):
    return sorted(p for p in os.listdir(d) if p.endswith(".seg"))


def test_engine_auto_checkpoint_truncates_and_replays_exactly(
        tmp_path, monkeypatch):
    """The engine's auto-checkpoint flush truncates covered journal
    segments (GS_WAL_RETAIN), and a recovery AFTER truncation —
    including one that falls back a checkpoint generation — still
    replays bit-exactly from the new floor."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    wal_dir = _retain_env(monkeypatch, tmp_path)
    ck = str(tmp_path / "ck.npz")
    rng = np.random.default_rng(11)
    src = rng.integers(0, 100, 4096).astype(np.int32)
    dst = rng.integers(0, 100, 4096).astype(np.int32)

    eng = StreamSummaryEngine(edge_bucket=128, vertex_bucket=128)
    assert eng.enable_wal(wal_dir)
    eng.enable_auto_checkpoint(ck, every_n_windows=4)
    oracle = []
    for i in range(0, 4096, 1024):
        oracle += eng.process(src[i:i + 1024], dst[i:i + 1024])
    segs = _segments(wal_dir)
    assert segs and int(segs[0][4:12]) > 0, \
        "no covered segment was truncated"
    # the floor lags ONE generation: the .prev checkpoint's replay
    # suffix must still be fully present
    from gelly_streaming_tpu.utils import checkpoint

    prev_state = checkpoint.restore(ck + ".prev")
    prev_cursor = int(prev_state["windows_done"]) * 128
    replayable = sorted(start for _t, start, *_ in
                        wal.replay(wal_dir, {"engine": 0}))
    assert replayable and replayable[0] <= prev_cursor

    # kill here → fresh engine recovers and continues exactly
    eng2 = StreamSummaryEngine(edge_bucket=128, vertex_bucket=128)
    eng2.enable_wal(wal_dir)
    replayed = eng2.resume_and_replay(ck)
    done = eng2.windows_done
    assert replayed == oracle[done - len(replayed):done]
    more_s = rng.integers(0, 100, 1024).astype(np.int32)
    more_d = rng.integers(0, 100, 1024).astype(np.int32)
    cont = eng2.process(more_s, more_d)
    oracle_full = StreamSummaryEngine(
        edge_bucket=128, vertex_bucket=128).process(
        np.concatenate([src, more_s]), np.concatenate([dst, more_d]))
    assert oracle == oracle_full[:len(oracle)]
    assert cont == oracle_full[done:]


def test_retain_disarmed_keeps_every_segment(tmp_path, monkeypatch):
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    monkeypatch.delenv("GS_WAL_RETAIN", raising=False)
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    wal_dir = str(tmp_path / "wal")
    ck = str(tmp_path / "ck.npz")
    rng = np.random.default_rng(12)
    src = rng.integers(0, 100, 4096).astype(np.int32)
    dst = rng.integers(0, 100, 4096).astype(np.int32)
    eng = StreamSummaryEngine(edge_bucket=128, vertex_bucket=128)
    assert eng.enable_wal(wal_dir)
    eng.enable_auto_checkpoint(ck, every_n_windows=4)
    eng.process(src, dst)
    assert int(_segments(wal_dir)[0][4:12]) == 0  # nothing deleted


def test_cohort_checkpoint_all_truncates_shared_journal(
        tmp_path, monkeypatch):
    """checkpoint_all() moves EVERY tenant's floor in one truncation
    (a shared segment is only deletable once all its tenants are
    covered), and a post-truncate recover() reproduces the fault-free
    continuation exactly."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    wal_dir = _retain_env(monkeypatch, tmp_path)
    rng = np.random.default_rng(13)

    def feed_all(co, n):
        for t in ("a", "b"):
            co.feed(t, rng.integers(0, 90, n).astype(np.int32),
                    rng.integers(0, 90, n).astype(np.int32))

    co = TenantCohort(edge_bucket=128, vertex_bucket=128)
    co.enable_auto_checkpoint(str(tmp_path / "ck"))
    assert co.enable_wal(wal_dir)
    for t in ("a", "b"):
        co.admit(t)
    outs = {"a": [], "b": []}
    rng_oracle = np.random.default_rng(13)
    fed = {"a": [], "b": []}
    for _ in range(4):
        for t in ("a", "b"):
            s = rng_oracle.integers(0, 90, 1024).astype(np.int32)
            d = rng_oracle.integers(0, 90, 1024).astype(np.int32)
            co.feed(t, s, d)
            fed[t].append((s, d))
        for t, res in co.pump().items():
            outs[t] += res
        # two flush boundaries move the two-generation floor forward
        assert co.checkpoint_all() == 2
    segs = _segments(wal_dir)
    assert segs and int(segs[0][4:12]) > 0, \
        "no covered shared segment was truncated"

    # kill → fresh cohort recovers off the truncated journal
    co2 = TenantCohort(edge_bucket=128, vertex_bucket=128)
    co2.enable_auto_checkpoint(str(tmp_path / "ck"))
    assert co2.enable_wal(wal_dir)
    co2.recover()
    outs2 = {"a": list(outs["a"]), "b": list(outs["b"])}
    for t, res in co2.pump().items():
        outs2[t] += res
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    for t in ("a", "b"):
        oracle = StreamSummaryEngine(
            edge_bucket=128, vertex_bucket=128).process(
            np.concatenate([s for s, _ in fed[t]]),
            np.concatenate([d for _, d in fed[t]]))
        assert outs2[t][:len(oracle)] == oracle


def test_retain_first_flush_truncates_nothing(tmp_path, monkeypatch):
    """Review fix: a tenant's FIRST checkpoint flush must not
    truncate — only one generation exists, so a damaged sole
    checkpoint still needs the whole journal to replay from 0."""
    monkeypatch.setenv("GS_WAL_RETAIN", "1")
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    w = _mk(tmp_path)
    cur = wal.RetentionCursor()
    for i in range(40):  # force several closed segments
        s, d = _edges(64, i)
        w.append("t1", s, d)
    assert len(_segments(w.dir)) > 1
    before = _segments(w.dir)
    assert cur.flushed(w, "t1", 64 * 40) == 0
    assert _segments(w.dir) == before
    # the SECOND flush floors at the first's offset and truncates
    assert cur.flushed(w, "t1", 64 * 40) > 0

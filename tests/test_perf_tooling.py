"""PERF.json contract guards: the committed evidence files validate
against the schema (tools/perf_schema.py), and the PERF.md renderer
(tools/update_perf_md.py) round-trips a full fixture — so a new
profiler section can't silently break the selection gates or the
unattended end-of-window renderer."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


perf_schema = _load_tool("perf_schema")
update_perf_md = _load_tool("update_perf_md")
trace_report = _load_tool("trace_report")
bench_compare = _load_tool("bench_compare")


# ----------------------------------------------------------------------
# schema: the committed files must stay valid
# ----------------------------------------------------------------------
@pytest.mark.parametrize("fname", [
    "PERF.json", "PERF_cpu.json", "PERF_tpu.json"])
def test_committed_perf_files_validate(fname):
    path = os.path.join(REPO, fname)
    if not os.path.exists(path):
        pytest.skip("%s not committed" % fname)
    with open(path) as f:
        perf = json.load(f)
    assert perf_schema.validate(perf) == []


def test_schema_rejects_malformed_sections():
    bad = {
        "backend": "cpu",
        "ingress_ab": {"not": "a list"},
        "egress_ab": [{"probe": "driver_ab", "parity": True}],  # no speedup
        "degradations": [{"from": "scan"},  # missing to/window/mesh
                         {"from": "sharded", "to": "scan", "window": 1,
                          "mesh_shape": "4x1",     # not a list of ints
                          "shard_id": "two"}],     # not an int
        "pipeline_stages": ["not-a-dict"],
        "host_reduce_error": "not-a-dict",
        "telemetry": [{"count": 3}],               # missing span
        "regressions": [{"row": "x"}],             # missing field/...
        "metrics": [{"engine": "t"}],              # dict, not a list
    }
    errors = perf_schema.validate(bad)
    joined = "\n".join(errors)
    assert "ingress_ab" in joined
    assert "egress_ab" in joined and "speedup" in joined
    assert "degradations" in joined
    assert "'mesh_shape'" in joined and "'shard_id'" in joined
    assert "pipeline_stages" in joined
    assert "host_reduce_error" in joined
    assert "telemetry" in joined and "'span'" in joined
    assert "regressions" in joined and "'ratio'" in joined
    assert "metrics: expected a dict section" in joined
    # a dict metrics section missing its required keys is also caught
    errors = perf_schema.validate(
        {"backend": "cpu", "metrics": {"engine": "t"}})
    assert any("metrics" in e and "overhead_ratio" in e
               for e in errors)
    assert perf_schema.validate([]) != []       # top level must be dict
    assert perf_schema.validate({"backend": 3})  # backend must be str


def test_schema_allows_unknown_sections():
    assert perf_schema.validate(
        {"backend": "cpu", "brand_new_section": [{"x": 1}]}) == []


# ----------------------------------------------------------------------
# renderer round-trip on a full fixture
# ----------------------------------------------------------------------
FIXTURE = {
    "backend": "cpu",
    "device": "TFRT_CPU_0",
    "roofline": {
        "peaks": {"hw": "v5e", "bf16_tflops": 197, "hbm_gbps": 819},
        "rows": [{"program": "tri_stream", "ms": 1.5,
                  "gflops_achieved": 10.0, "mfu_vs_bf16_peak": 0.01,
                  "gbps_achieved": 5.0, "hbm_frac_of_peak": 0.01,
                  "bound": "hbm",
                  "arith_intensity_flops_per_byte": 2.0}],
    },
    "trace": {"windows": 16, "edge_bucket": 32768,
              "dispatch_wall_ms": 100.0, "trace_dir": "logs/trace",
              "top_ops": [{"op": "sort", "total_ms": 5.0, "calls": 2}]},
    "host_stream": [{"edge_bucket": 8192, "parity": True,
                     "host_edges_per_s": 2, "device_edges_per_s": 1,
                     "host_vs_device": 2.0}],
    "pipeline_stages": [{"engine": "triangle", "edge_bucket": 32768,
                         "ingress": "standard", "workers": 4,
                         "prep_ms_per_chunk": 1.0,
                         "h2d_ms_per_chunk": 2.0,
                         "compute_ms_per_chunk": 3.0,
                         "pipelined_edges_per_s": 10,
                         "sync_edges_per_s": 5,
                         "pipeline_speedup": 2.0, "parity": True}],
    "ingress_probes": [{"probe": "dispatch_latency",
                        "round_trip_s": 0.2}],
    "ingress_ab": [{"probe": "stream_ab", "parity": True,
                    "num_edges": 100, "std_edges_per_s": 1,
                    "compact_edges_per_s": 2, "speedup": 2.0,
                    "speedup_worst": 1.8, "speedup_best": 2.2}],
    "egress_ab": [{"probe": "driver_ab", "parity": True,
                   "eb": 32768, "vb": 65536,
                   "full_edges_per_s": 1, "delta_edges_per_s": 2,
                   "speedup": 2.0, "speedup_worst": 1.9,
                   "speedup_best": 2.1}],
    "tenancy_ab": [{"probe": "cohort_serving", "parity": True,
                    "tenants": 8, "eb": 512, "vb": 1024,
                    "tenant_edges_per_s": 18476,
                    "sequential_edges_per_s": 12285,
                    "speedup": 1.504, "speedup_worst": 1.346,
                    "speedup_best": 1.584}],
    "autotune": [{"engine": "triangle_stream", "edge_bucket": 32768,
                  "parity": True, "static_edges_per_s": 1,
                  "tuned_cold_edges_per_s": 2,
                  "tuned_seeded_edges_per_s": 3,
                  "seeded_vs_static": 3.0,
                  "chosen": {"wb": 64, "kb": 32,
                             "ingress": "standard"}}],
    "degradations": [{"section": "driver", "from": "scan",
                      "to": "native", "window": 5, "reason": "t",
                      "mesh_shape": None, "shard_id": None},
                     {"section": "driver", "from": "sharded",
                      "to": "scan", "window": 9, "reason": "dead shard",
                      "mesh_shape": [4], "shard_id": 2}],
    "telemetry": [{"span": "ingress.prep", "count": 16,
                   "total_ms": 40.0, "p50_ms": 2.0, "p95_ms": 4.0,
                   "p99_ms": 5.0}],
    "telemetry_meta": {"engine": "triangle_stream+driver",
                       "parity": True, "overhead_ratio": 1.01,
                       "trace": "abc-123"},
    "metrics": {"engine": "triangle_stream", "edge_bucket": 32768,
                "num_edges": 524288, "parity": True,
                "disarmed_edges_per_s": 24000000,
                "armed_edges_per_s": 23500000,
                "overhead_ratio": 1.021, "windows_observed": 16},
    "cost_model": {"engine": "triangle_stream+fused_scan",
                   "edge_bucket": 32768, "num_edges": 524288,
                   "parity": True, "trace": "abc-123",
                   "ledger": "logs/costmodel_ledger_cpu.jsonl",
                   "peaks": {"gflops": 197000.0, "gbps": 819.0},
                   "programs": [
                       {"program": "fused_scan",
                        "sig": "i32[16,32768],b1[16,32768]",
                        "flops": 47352212,
                        "bytes_accessed": 186835344,
                        "arith_intensity_flops_per_byte": 0.2534,
                        "bound": "bytes", "dispatches": 1,
                        "measured_mean_s": 0.2376,
                        "roofline_s": 0.000228,
                        "roofline_frac": 0.00096}]},
    "regressions": [{"row": "bench[triangle]", "field": "value",
                     "baseline": 100, "current": 50, "ratio": 0.5,
                     "tolerance": 0.2}],
    "sharded": {"collectives": {
        "config": {"n": 8, "vb": 65536, "kb": 32, "cap": 4096},
        "backend": "cpu-virtual-mesh", "note": "modeled",
        "rows": [{"collective": "psum",
                  "modeled_ici_bytes_per_chip": 1024,
                  "modeled_ms_v5e_ici": 0.01,
                  "measured_ms_cpu_mesh": 0.5}]}},
}


def test_fixture_passes_schema():
    assert perf_schema.validate(FIXTURE) == []


def test_render_covers_every_new_section():
    block = update_perf_md.render(FIXTURE)
    assert update_perf_md.MARK_BEGIN in block
    assert update_perf_md.MARK_END in block
    for needle in ("d2h egress A/B", "Online dispatch autotuner",
                   "driver_ab", "triangle_stream",
                   "wb=64", "DEGRADED RUN", "Roofline",
                   "Ingress pipeline per-stage timing",
                   "Flight recorder", "ingress.prep", "1.010",
                   "Metrics plane", "1.021",
                   "Program cost observatory", "fused_scan",
                   "explain_perf",
                   "Multi-tenant cohort A/B", "cohort_serving"):
        assert needle in block, needle


def test_update_perf_md_round_trips_idempotently(tmp_path):
    perf_path = str(tmp_path / "PERF.json")
    md_path = str(tmp_path / "PERF.md")
    with open(perf_path, "w") as f:
        json.dump(FIXTURE, f)
    with open(md_path, "w") as f:
        f.write("# PERF\n\nhand-written preamble\n\n%s\nstale\n%s\n"
                "hand-written tail\n" % (update_perf_md.MARK_BEGIN,
                                         update_perf_md.MARK_END))
    update_perf_md.main(perf_path, md_path)
    with open(md_path) as f:
        once = f.read()
    assert "hand-written preamble" in once
    assert "hand-written tail" in once
    assert "stale" not in once
    assert "Online dispatch autotuner" in once
    update_perf_md.main(perf_path, md_path)  # idempotent
    with open(md_path) as f:
        assert f.read() == once


# ----------------------------------------------------------------------
# trace_report round-trips its committed fixture ledger (no network,
# no chip): the tier-1 guard that the flight-recorder toolchain keeps
# reading the ledgers real runs write
# ----------------------------------------------------------------------
LEDGER_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                              "telemetry_ledger.jsonl")


def test_trace_report_loads_fixture_and_skips_torn_tail():
    records = trace_report.load(LEDGER_FIXTURE)
    # the fixture ends with a deliberately torn line (a crash
    # mid-append): skipped, never fatal
    assert not any("torn" in str(r.get("name", "")) for r in records)
    assert trace_report.meta_of(records)["trace"] == "fixture-1"
    kinds = {r["t"] for r in records}
    assert {"meta", "span", "event", "counter"} <= kinds


def test_trace_report_histograms_exact_on_fixture():
    records = trace_report.load(LEDGER_FIXTURE)
    rows = {r["span"]: r for r in trace_report.span_rows(records)}
    prep = rows["ingress.prep"]
    # durations committed in the fixture: 10/20/30/40 ms -> nearest
    # rank p50=20, p95=40, p99=40; total 100
    assert prep["count"] == 4
    assert prep["total_ms"] == 100.0
    assert (prep["p50_ms"], prep["p95_ms"], prep["p99_ms"]) \
        == (20.0, 40.0, 40.0)
    thr = {r["span"]: r
           for r in trace_report.throughput_rows(records)}
    # two triangles.round spans: 131072 edges over 0.2 s
    assert thr["triangles.round"]["edges"] == 131072
    assert thr["triangles.round"]["edges_per_s"] == 655360


def test_trace_report_perfetto_and_render_round_trip(tmp_path):
    records = trace_report.load(LEDGER_FIXTURE)
    trace = json.loads(json.dumps(trace_report.to_perfetto(records)))
    evs = trace["traceEvents"]
    assert all({"name", "ph", "pid", "tid", "ts"} <= set(e)
               for e in evs)
    assert any(e["ph"] == "X" and e["name"] == "ingress.chunk"
               for e in evs)
    assert any(e["ph"] == "i" and e["name"] == "resume" for e in evs)
    assert any(e["ph"] == "C" for e in evs)
    text = trace_report.render(records)
    for needle in ("fixture-1", "ingress.prep", "tier_demotion",
                   "resume", "edges/s"):
        assert needle in text, needle
    # the CLI end-to-end: report + perfetto export, exit 0
    out = str(tmp_path / "trace.json")
    assert trace_report.main([LEDGER_FIXTURE, "--perfetto", out]) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]


# ----------------------------------------------------------------------
# bench_compare: the perf regression sentry (tools/bench_compare.py)
# ----------------------------------------------------------------------
BENCH_ROWS = [
    {"metric": "triangle 32768", "value": 9000000, "unit": "edges/s",
     "pipeline_speedup": 3.1, "sync_prep_edges_per_s": 2900000},
    {"metric": "reduce 8192", "value": 170000000, "unit": "edges/s",
     "vs_baseline": 1.19},
]


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_bench_compare_unchanged_run_exits_zero(tmp_path, capsys):
    base = str(tmp_path / "base.jsonl")
    _write_jsonl(base, BENCH_ROWS)
    assert bench_compare.main(["--baseline", base]) == 0
    report = json.loads(capsys.readouterr().out)
    assert perf_schema.validate(report) == []
    assert report["regressions"] == []
    assert report["rows_compared"] == 2


def test_bench_compare_committed_baseline_self_compare():
    """The acceptance pin: `--baseline BENCH_r05.json` (no --current)
    exits 0 on the unchanged run."""
    path = os.path.join(REPO, "BENCH_r05.json")
    if not os.path.exists(path):
        pytest.skip("BENCH_r05.json not committed")
    assert bench_compare.main(["--baseline", path]) == 0


def test_bench_compare_slowed_row_exits_nonzero(tmp_path, capsys):
    base = str(tmp_path / "base.jsonl")
    cur = str(tmp_path / "cur.jsonl")
    _write_jsonl(base, BENCH_ROWS)
    slowed = [dict(r) for r in BENCH_ROWS]
    slowed[0]["value"] = int(slowed[0]["value"] * 0.5)  # -50%
    _write_jsonl(cur, slowed)
    rc = bench_compare.main(["--baseline", base, "--current", cur,
                             "--out", str(tmp_path / "report.json")])
    assert rc == 1
    report = json.loads((tmp_path / "report.json").read_text())
    assert perf_schema.validate(report) == []
    regs = report["regressions"]
    assert len(regs) == 1
    assert regs[0]["row"] == "triangle 32768"
    assert regs[0]["field"] == "value"
    assert regs[0]["ratio"] == 0.5


def test_bench_compare_ratio_field_and_tolerance(tmp_path):
    base = str(tmp_path / "base.jsonl")
    cur = str(tmp_path / "cur.jsonl")
    _write_jsonl(base, BENCH_ROWS)
    slowed = [dict(r) for r in BENCH_ROWS]
    slowed[0]["pipeline_speedup"] = 2.6  # -16%: inside 0.2, not 0.1
    _write_jsonl(cur, slowed)
    assert bench_compare.main(
        ["--baseline", base, "--current", cur]) == 0
    assert bench_compare.main(
        ["--baseline", base, "--current", cur,
         "--tolerance", "0.1"]) == 1


def test_schema_and_sentry_cover_tenancy_rows(tmp_path):
    """The tenancy_ab section: required keys enforced (probe / parity
    / tenants; parity-true rows need a positive speedup), and
    bench_compare matches tenancy rows by (probe, tenants) identity
    comparing tenant_edges_per_s — the regression sentry covers the
    cohort path."""
    bad = {"backend": "cpu",
           "tenancy_ab": [{"probe": "cohort_serving", "parity": True}]}
    errors = "\n".join(perf_schema.validate(bad))
    assert "tenancy_ab" in errors
    assert "'tenants'" in errors and "speedup" in errors
    good = {"backend": "cpu",
            "tenancy_ab": [{"probe": "cohort_serving", "parity": True,
                            "tenants": 8, "speedup": 1.5,
                            "tenant_edges_per_s": 20000,
                            "sequential_edges_per_s": 13000}]}
    assert perf_schema.validate(good) == []

    base = str(tmp_path / "PERF_base.json")
    cur = str(tmp_path / "PERF_cur.json")
    with open(base, "w") as f:
        json.dump(good, f)
    slowed = json.loads(json.dumps(good))
    slowed["tenancy_ab"][0]["tenant_edges_per_s"] = 9000  # -55%
    with open(cur, "w") as f:
        json.dump(slowed, f)
    assert bench_compare.main(
        ["--baseline", base, "--current", base]) == 0
    rc = bench_compare.main(
        ["--baseline", base, "--current", cur,
         "--out", str(tmp_path / "report.json")])
    assert rc == 1
    report = json.loads((tmp_path / "report.json").read_text())
    regs = report["regressions"]
    assert regs[0]["row"] == "tenancy_ab[cohort_serving,8]"
    assert regs[0]["field"] == "tenant_edges_per_s"


def test_bench_compare_reads_perf_json(tmp_path):
    """PERF*.json baselines compare section rows (host_stream etc.)
    and the metrics/telemetry_meta dict sections."""
    base = str(tmp_path / "PERF_base.json")
    cur = str(tmp_path / "PERF_cur.json")
    with open(base, "w") as f:
        json.dump(FIXTURE, f)
    slowed = json.loads(json.dumps(FIXTURE))
    slowed["metrics"]["armed_edges_per_s"] = 10
    with open(cur, "w") as f:
        json.dump(slowed, f)
    assert bench_compare.main(
        ["--baseline", base, "--current", base]) == 0
    assert bench_compare.main(
        ["--baseline", base, "--current", cur]) == 1


def test_bench_compare_unreadable_inputs_exit_two(tmp_path):
    empty = str(tmp_path / "empty.json")
    with open(empty, "w") as f:
        f.write("{}")
    assert bench_compare.main(["--baseline", empty]) == 2
    assert bench_compare.main(
        ["--baseline", str(tmp_path / "missing.json")]) == 2


# ----------------------------------------------------------------------
# trace_report filters + empty-ledger exits
# ----------------------------------------------------------------------
def test_trace_report_filters(tmp_path):
    records = trace_report.load(LEDGER_FIXTURE)
    only = trace_report.filter_records(records, trace_id="fixture-1")
    assert only and all(r.get("trace") == "fixture-1" for r in only)
    none = trace_report.filter_records(records, trace_id="nope")
    assert none == []
    late = trace_report.filter_records(records, since=1e12)
    assert all(r["t"] == "meta" for r in late)  # meta anchor kept


def test_trace_report_exits_nonzero_on_empty_and_torn(tmp_path,
                                                      capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert trace_report.main([str(empty)]) == 1
    assert "no usable records" in capsys.readouterr().err
    torn = tmp_path / "torn.jsonl"
    torn.write_text('{"t": "span", "name": "torn')
    assert trace_report.main([str(torn)]) == 1
    assert "torn" in capsys.readouterr().err
    # filters that match nothing are an error, not an empty table
    assert trace_report.main([LEDGER_FIXTURE,
                              "--trace-id", "nope"]) == 1
    assert "nothing to report" in capsys.readouterr().err
    assert trace_report.main([LEDGER_FIXTURE,
                              "--trace-id", "fixture-1"]) == 0


# ----------------------------------------------------------------------
# cost_model schema + the BENCH capture shape (round 13)
# ----------------------------------------------------------------------
def test_schema_rejects_malformed_cost_model():
    bad = {"backend": "cpu",
           "cost_model": {"engine": "t"}}          # missing keys
    joined = "\n".join(perf_schema.validate(bad))
    assert "cost_model" in joined and "'programs'" in joined
    bad = {"backend": "cpu",
           "cost_model": {"programs": {"not": "a list"},
                          "parity": True, "edge_bucket": 1,
                          "trace": "t", "ledger": "l"}}
    assert any("must be a list" in e for e in perf_schema.validate(bad))
    bad = {"backend": "cpu",
           "cost_model": {"programs": [{"program": "p"}],  # bare row
                          "parity": True, "edge_bucket": 1,
                          "trace": "t", "ledger": "l"}}
    joined = "\n".join(perf_schema.validate(bad))
    # flops/bytes may be null but the keys must EXIST (reported-none
    # vs silently-dropped must stay distinguishable)
    for key in ("'sig'", "'flops'", "'bytes_accessed'", "'bound'",
                "'dispatches'"):
        assert key in joined, key
    ok = {"backend": "cpu",
          "cost_model": {"programs": [
              {"program": "p", "sig": "s", "flops": None,
               "bytes_accessed": None, "bound": "unknown",
               "dispatches": 0}],
              "parity": True, "edge_bucket": 1,
              "trace": "t", "ledger": "l"}}
    assert perf_schema.validate(ok) == []


def test_schema_validates_bench_capture_shape():
    cap = {"n": 1, "cmd": "python bench.py", "rc": 0,
           "tail": '{"metric": "x", "value": 1}\n', "parsed": None}
    assert perf_schema.is_capture(cap)
    assert perf_schema.validate_capture(cap) == []
    assert not perf_schema.is_capture({"backend": "cpu"})
    bad = {"cmd": "x", "rc": "zero", "tail": 3, "parsed": []}
    errors = perf_schema.validate_capture(bad)
    joined = "\n".join(errors)
    assert "'tail'" in joined and "'rc'" in joined \
        and "'parsed'" in joined


@pytest.mark.parametrize("fname", [
    "BENCH_r01.json", "BENCH_r02.json", "BENCH_r03.json",
    "BENCH_r04.json", "BENCH_r05.json"])
def test_committed_bench_captures_validate(fname):
    """tools/ci_check.sh runs perf_schema over every committed
    evidence file — the captures must stay valid too."""
    path = os.path.join(REPO, fname)
    if not os.path.exists(path):
        pytest.skip("%s not committed" % fname)
    with open(path) as f:
        doc = json.load(f)
    assert perf_schema.is_capture(doc)
    assert perf_schema.validate_capture(doc) == []


# ----------------------------------------------------------------------
# bench_compare: null identity fields match missing ones (the
# satellite fix), trace-ID correlation stamps
# ----------------------------------------------------------------------
def test_bench_compare_null_identity_treated_as_missing(tmp_path):
    """A row whose `metric` is present-but-null must behave exactly
    like a row without the key: no phantom `None` identity, so two
    UNRELATED null-identity rows can never be compared against each
    other as if they were the same row."""
    # extract_rows: every supported shape drops the null-identity row
    text = ('{"metric": null, "value": 100}\n'
            '{"metric": "real", "value": 7}\n'
            '{"value": 3}\n')
    rows = bench_compare.extract_rows(text, "stdout")
    assert set(rows) == {"real"}
    cap = {"tail": text, "parsed": {"metric": None, "value": 100}}
    assert set(bench_compare.extract_rows(cap, "cap")) == {"real"}
    assert bench_compare.extract_rows(
        {"metric": None, "value": 100, "tail_": 0}, "dict") == {}
    # end-to-end: baseline and current each carry a DIFFERENT
    # null-identity row (100 vs 10 — a 10× "drop" were they matched);
    # the shared real row is unchanged, so the sentry must exit 0
    base, cur = str(tmp_path / "b.jsonl"), str(tmp_path / "c.jsonl")
    _write_jsonl(base, [{"metric": None, "value": 100},
                        {"metric": "real", "value": 7}])
    _write_jsonl(cur, [{"metric": None, "value": 10},
                       {"metric": "real", "value": 7}])
    assert bench_compare.main(
        ["--baseline", base, "--current", cur]) == 0


def test_bench_compare_stamps_trace_correlation(tmp_path, capsys):
    """Bench rows carry the run trace ID; a regression report must
    stamp baseline/current traces (top level AND per regression row)
    so explain_perf --regression resolves the right ledger."""
    base, cur = str(tmp_path / "b.jsonl"), str(tmp_path / "c.jsonl")
    _write_jsonl(base, [{"metric": "t", "value": 100,
                         "trace": "aaaa-1111"}])
    _write_jsonl(cur, [{"metric": "t", "value": 10,
                        "trace": "bbbb-2222"}])
    out = str(tmp_path / "report.json")
    rc = bench_compare.main(["--baseline", base, "--current", cur,
                             "--out", out])
    assert rc == 1
    report = json.loads((tmp_path / "report.json").read_text())
    assert perf_schema.validate(report) == []
    assert report["baseline_trace"] == "aaaa-1111"
    assert report["current_trace"] == "bbbb-2222"
    reg = report["regressions"][0]
    assert reg["baseline_trace"] == "aaaa-1111"
    assert reg["current_trace"] == "bbbb-2222"
    # the operator is told the drill-down command
    assert "explain_perf.py --regression" in capsys.readouterr().err
    # multi-run files: each regression follows ITS row's trace, not
    # the document's first-seen one
    _write_jsonl(base, [{"metric": "a", "value": 100,
                         "trace": "runA-base"},
                        {"metric": "b", "value": 100,
                         "trace": "runB-base"}])
    _write_jsonl(cur, [{"metric": "a", "value": 100,
                        "trace": "runA-cur"},
                       {"metric": "b", "value": 10,
                        "trace": "runB-cur"}])
    assert bench_compare.main(["--baseline", base, "--current", cur,
                               "--out", out]) == 1
    report = json.loads((tmp_path / "report.json").read_text())
    reg = report["regressions"][0]
    assert reg["row"] == "b"
    assert reg["baseline_trace"] == "runB-base"
    assert reg["current_trace"] == "runB-cur"


# ----------------------------------------------------------------------
# trace_report: cost-registry columns in the span table + Perfetto
# ----------------------------------------------------------------------
def _tagged_ledger_rows():
    return [
        {"t": "meta", "trace": "cost-1", "pid": 1,
         "epoch": 1e9, "mono": 0.0, "ring": 4096},
        {"t": "span", "name": "ingress.dispatch", "trace": "cost-1",
         "tid": 1, "ts": 0.0, "dur": 0.25, "sid": 2,
         "a": {"chunk": 0, "program": "fused_scan",
               "sig": "i32[16,32768],b1[16,32768]"}},
        {"t": "span", "name": "ingress.prep", "trace": "cost-1",
         "tid": 1, "ts": 0.3, "dur": 0.01, "sid": 3,
         "a": {"chunk": 0}},
    ]


def test_trace_report_span_table_carries_cost_columns(tmp_path):
    cost = trace_report.cost_index(FIXTURE)
    assert cost[("fused_scan",
                 "i32[16,32768],b1[16,32768]")]["flops"] == 47352212
    rows = {r["span"]: r
            for r in trace_report.span_rows(_tagged_ledger_rows(),
                                            cost)}
    disp = rows["ingress.dispatch"]
    assert disp["program"] == "fused_scan"
    assert disp["flops"] == 47352212
    assert disp["bytes_accessed"] == 186835344
    assert disp["bound"] == "bytes"
    assert "program" not in rows["ingress.prep"]   # untagged: no cols
    # the rendered table shows the program + FLOPs/bytes annotation
    text = trace_report.render(_tagged_ledger_rows(), cost=cost)
    assert "fused_scan" in text
    assert "GF" in text and "bytes" in text


def test_trace_report_perfetto_args_carry_cost(tmp_path):
    cost = trace_report.cost_index(FIXTURE)
    trace = trace_report.to_perfetto(_tagged_ledger_rows(), cost)
    disp = next(e for e in trace["traceEvents"]
                if e["name"] == "ingress.dispatch")
    assert disp["args"]["flops"] == 47352212
    assert disp["args"]["bound"] == "bytes"
    # the CLI end-to-end: --perf annotates, exports, exits 0
    ledger = tmp_path / "l.jsonl"
    _write_jsonl(str(ledger), _tagged_ledger_rows())
    perf = tmp_path / "PERF.json"
    perf.write_text(json.dumps(FIXTURE))
    out = str(tmp_path / "trace.json")
    assert trace_report.main([str(ledger), "--perf", str(perf),
                              "--perfetto", out]) == 0
    with open(out) as f:
        evs = json.load(f)["traceEvents"]
    assert any(e.get("args", {}).get("flops") for e in evs)


# ----------------------------------------------------------------------
# explain_perf: the attribution drill-down (tools/explain_perf.py)
# ----------------------------------------------------------------------
explain_perf = _load_tool("explain_perf")


def test_explain_perf_committed_row_attributes(capsys):
    """The acceptance pin: run on the committed 524K/32768 CPU row
    (PERF_cpu.json cost_model + its committed ledger) — per-stage and
    per-program attribution, stage totals reconciling with the ledger
    within the default 5%, exit 0."""
    perf = os.path.join(REPO, "PERF_cpu.json")
    if not os.path.exists(perf):
        pytest.skip("PERF_cpu.json not committed")
    assert explain_perf.main(["--perf", perf]) == 0
    out = capsys.readouterr().out
    assert "stage attribution" in out
    assert "reconciled: 100.0% mapped, tolerance 5.0%" in out
    for program in ("fused_scan", "triangle_stream"):
        assert program + "@" in out, program
    assert "ranked suspects" in out


def test_explain_perf_stage_attribution_and_containers():
    """Leaf spans map to their stages; container spans are excluded
    so time is never double-booked — by name (the known envelopes)
    AND structurally (any span that parents another, even under an
    unknown name); the two independent accountings agree."""
    records = _tagged_ledger_rows() + [
        {"t": "span", "name": "ingress.chunk", "trace": "cost-1",
         "tid": 1, "ts": 0.0, "dur": 0.26, "sid": 1,
         "a": {"chunk": 0}},                # known container: excluded
        {"t": "span", "name": "step.triangles", "trace": "cost-1",
         "tid": 1, "ts": 0.4, "dur": 0.51, "sid": 10},  # parents a
        {"t": "span", "name": "ingress.finalize", "trace": "cost-1",
         "tid": 1, "ts": 0.4, "dur": 0.5, "sid": 4, "par": 10,
         "a": {"chunk": 0}},                # ...leaf: envelope excluded
    ]
    stages, attributed, ledger_total, unmapped = \
        explain_perf.stage_attribution(records)
    by_stage = {r["stage"]: r for r in stages}
    assert by_stage["dispatch"]["total_s"] == 0.25
    assert by_stage["prep"]["total_s"] == 0.01
    # step.triangles maps to a stage but PARENTS the finalize span —
    # only the child leaf counts, never both
    assert by_stage["d2h+finalize"]["total_s"] == 0.5
    assert by_stage["dispatch"]["count"] == 1
    assert attributed == pytest.approx(0.76, abs=1e-6)
    assert attributed == pytest.approx(ledger_total, rel=1e-3)
    assert unmapped == []
    # program attribution: the finalize span's d2h time lands on the
    # program whose chunk it drained
    progs = explain_perf.program_attribution(
        records, FIXTURE["cost_model"]["programs"])
    row = next(r for r in progs if r["program"] == "fused_scan")
    assert row["dispatches"] == 1
    assert row["materialize_s"] == 0.5
    assert row["flops"] == 47352212


def test_explain_perf_suspect_heuristics():
    """A recompile_storm event and a finalize-dominated ledger each
    fire their suspect, ranked by score."""
    records = _tagged_ledger_rows() + [
        {"t": "span", "name": "ingress.finalize", "trace": "cost-1",
         "tid": 1, "ts": 0.4, "dur": 5.0, "sid": 4,
         "a": {"chunk": 0}},
        {"t": "event", "name": "recompile_storm", "trace": "cost-1",
         "tid": 1, "ts": 0.5, "a": {"fn": "fused_scan"}},
    ]
    stages, _att, _led, _un = explain_perf.stage_attribution(records)
    progs = explain_perf.program_attribution(
        records, FIXTURE["cost_model"]["programs"])
    suspects = explain_perf.rank_suspects(stages, progs, records)
    names = [s["suspect"] for s in suspects]
    assert "recompile_storm" in names
    assert "host_sync" in names
    assert "launch_bound" in names        # 0.25 s vs a sub-ms roofline
    scores = [s["score"] for s in suspects]
    assert scores == sorted(scores, reverse=True)
    storm = next(s for s in suspects
                 if s["suspect"] == "recompile_storm")
    assert "fused_scan" in storm["evidence"]


def test_explain_perf_unmapped_spans_fail_conservation(tmp_path,
                                                       capsys):
    """The taxonomy polices itself: leaf time under a span name the
    stage map doesn't know (beyond --tolerance of the total) exits
    non-zero and names the unmapped spans."""
    ledger = tmp_path / "l.jsonl"
    _write_jsonl(str(ledger), _tagged_ledger_rows() + [
        {"t": "span", "name": "brand.new_stage", "trace": "cost-1",
         "tid": 1, "ts": 1.0, "dur": 4.0, "sid": 7}])
    assert explain_perf.main(["--ledger", str(ledger)]) == 1
    err = capsys.readouterr().err
    assert "could not name" in err
    assert "brand.new_stage" in err
    # inside tolerance the same ledger attributes fine
    assert explain_perf.main(["--ledger", str(ledger),
                              "--tolerance", "0.97"]) == 0


def test_explain_perf_error_exits(tmp_path, capsys):
    # no ledger resolvable → 2
    assert explain_perf.main([]) == 2
    assert "no ledger" in capsys.readouterr().err
    # a ledger with no span records → 1, with the arming hint
    empty = tmp_path / "empty.jsonl"
    _write_jsonl(str(empty), [{"t": "meta", "trace": "x", "pid": 1,
                               "epoch": 1e9, "mono": 0.0}])
    assert explain_perf.main(["--ledger", str(empty)]) == 1
    assert "GS_TELEMETRY=1" in capsys.readouterr().err


def test_explain_perf_regression_correlation(tmp_path, capsys):
    """The sentry→drill-down handoff: a bench_compare --out report's
    current_trace selects the ledger records, and the regression rows
    are echoed first."""
    ledger = tmp_path / "l.jsonl"
    rows = _tagged_ledger_rows()
    # a second run's records under a different trace id: must be
    # filtered OUT when the regression names trace cost-1
    rows += [{"t": "span", "name": "ingress.dispatch",
              "trace": "other-2", "tid": 1, "ts": 9.0, "dur": 9.0,
              "sid": 9, "a": {"chunk": 0}}]
    _write_jsonl(str(ledger), rows)
    report = tmp_path / "report.json"
    report.write_text(json.dumps({
        "regressions": [{"row": "t", "field": "value",
                         "baseline": 100, "current": 10, "ratio": 0.1,
                         "tolerance": 0.2,
                         "baseline_trace": "aaaa-1111",
                         "current_trace": "cost-1"}],
        "baseline_trace": "aaaa-1111", "current_trace": "cost-1"}))
    rc = explain_perf.main(["--ledger", str(ledger),
                            "--regression", str(report), "--json"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "regression: t.value 100 -> 10" in captured.err
    doc = json.loads(captured.out)
    # only the regression's trace was attributed (9 s span excluded)
    assert doc["attributed_total_s"] == pytest.approx(0.26, abs=1e-6)
    assert doc["regression"]["current_trace"] == "cost-1"


def test_update_perf_md_appends_block_when_markers_absent(tmp_path):
    perf_path = str(tmp_path / "PERF.json")
    md_path = str(tmp_path / "PERF.md")
    with open(perf_path, "w") as f:
        json.dump(FIXTURE, f)
    with open(md_path, "w") as f:
        f.write("# PERF\n")
    update_perf_md.main(perf_path, md_path)
    with open(md_path) as f:
        out = f.read()
    assert out.startswith("# PERF")
    assert update_perf_md.MARK_BEGIN in out


# ----------------------------------------------------------------------
# chaos soak-summary schema (logs/CHAOS_*.json; ISSUE 12 serve leg)
# ----------------------------------------------------------------------
def _chaos_doc(**over):
    doc = {
        "parity": True,
        "fault_classes_fired": ["kill_resume"],
        "serve_leg": {
            "parity": True,
            "kill": {"parity": True},
            "torn_tail": {"parity": True},
            "slow_client": {"parity": True, "shed": True},
            "drain": {"parity": True, "rc": 0, "sealed": True,
                      "digest_match": True},
        },
    }
    doc.update(over)
    return doc


def test_chaos_schema_accepts_well_formed_serve_leg():
    doc = _chaos_doc()
    assert perf_schema.is_chaos(doc)
    assert perf_schema.validate_chaos(doc) == []


def test_chaos_schema_rejects_divergence_and_bad_drain():
    assert any("parity" in e for e in
               perf_schema.validate_chaos(_chaos_doc(parity=False)))
    bad = _chaos_doc()
    bad["serve_leg"]["parity"] = False
    assert any("serve_leg" in e for e in
               perf_schema.validate_chaos(bad))
    bad = _chaos_doc()
    bad["serve_leg"]["drain"]["rc"] = 143
    assert any("exit 0" in e for e in
               perf_schema.validate_chaos(bad))
    bad = _chaos_doc()
    del bad["serve_leg"]["drain"]["sealed"]
    assert any("sealed" in e for e in
               perf_schema.validate_chaos(bad))


def test_chaos_schema_legs_are_additive():
    # older soaks predate newer legs: absent legs are fine, present
    # ones must carry their keys
    doc = _chaos_doc()
    del doc["serve_leg"]
    assert perf_schema.validate_chaos(doc) == []
    doc = _chaos_doc(tenancy_leg={"parity": True})
    errs = perf_schema.validate_chaos(doc)
    assert any("tenancy_leg" in e and "faults_fired" in e
               for e in errs)


@pytest.mark.parametrize("fname", ["CHAOS_resident.json",
                                   "CHAOS_tenancy.json",
                                   "CHAOS_serve.json"])
def test_committed_chaos_logs_validate(fname):
    path = os.path.join(REPO, "logs", fname)
    if not os.path.exists(path):
        pytest.skip("%s not committed" % fname)
    with open(path) as f:
        doc = json.load(f)
    assert perf_schema.is_chaos(doc)
    assert perf_schema.validate_chaos(doc) == []

"""utils/knobs: the typed GS_* registry every env read goes through.

Pins the contract the migration relied on: live per-call reads,
unset/empty = default, clamps instead of surprises at the declared
bounds, typed KnobError (naming knob + value + kind) on malformed
text, and the README table rendered from the registry so docs can't
drift (gslint R3 enforces the same diff tree-wide)."""

import os

import pytest

from gelly_streaming_tpu.utils import knobs

pytestmark = pytest.mark.lint

ALL = ("GS_PIPELINE_WORKERS GS_PIPELINE_INFLIGHT GS_STREAM_PREFETCH "
       "GS_STAGE_TIMEOUT_S GS_STAGE_RETRIES GS_STAGE_BACKOFF_S "
       "GS_TIER_RETRY_WINDOWS GS_TIER_DEMOTE GS_MESH_DEMOTE "
       "GS_MESH_WIRE_CHECK GS_AUTOTUNE GS_AUTOTUNE_ROUND "
       "GS_AUTOTUNE_EXPLORE GS_TUNE_CACHE "
       "GS_RESIDENT GS_RESIDENT_SPB GS_RESIDENT_SLOTS "
       "GS_PALLAS_WINDOW GS_PALLAS_TILE GS_PALLAS_CK "
       "GS_EGRESS GS_EGRESS_CAP "
       "GS_TELEMETRY GS_TRACE_DIR GS_TRACE_RING "
       "GS_TRACE_DURABLE GS_METRICS GS_METRICS_PORT "
       "GS_METRICS_SERIES GS_METRICS_COMPILE_BASE "
       "GS_HEALTH_STALE_S "
       "GS_TENANT_MAX GS_TENANT_QUEUE_WINDOWS GS_TENANT_ADMISSION "
       "GS_TENANT_TPD GS_COHORT_RESIDENT GS_COHORT_PALLAS "
       "GS_WAL GS_WAL_RETAIN GS_WAL_FSYNC_S GS_WAL_SEGMENT_BYTES "
       "GS_SERVE_PORT GS_SERVE_DRAIN_S GS_SERVE_IDLE_S "
       "GS_LATENCY GS_LAT_MARKS GS_LAT_PENDING "
       "GS_SLO_P99_S GS_SLO_BUDGET GS_SLO_WINDOW_S GS_SLO_BURN "
       "GS_SANITIZE GS_DLQ_DIR GS_DLQ_RETAIN "
       "GS_QUARANTINE_WINDOWS GS_MAX_BATCH_EDGES "
       "GS_PUMP GS_SLIDE GS_OOO_BOUND GS_SUB_QUEUE "
       "GS_GNN_F GS_GNN_ACT GS_GNN_PALLAS "
       "GS_PROVENANCE GS_PROVENANCE_DIR GS_PROVENANCE_RETAIN "
       "GS_COSTMODEL GS_COSTMODEL_PEAK_GFLOPS "
       "GS_COSTMODEL_PEAK_GBPS").split()

_GETTERS = {"int": knobs.get_int, "float": knobs.get_float,
            "bool": knobs.get_bool, "str": knobs.get_str,
            "path": knobs.get_path}


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    for name in ALL:
        monkeypatch.delenv(name, raising=False)


def test_registry_contents():
    """Exactly the package's knob set — a new knob must be registered
    here (and lands in the README table by rendering)."""
    assert sorted(knobs.REGISTRY) == sorted(ALL)


def test_registry_round_trip_defaults():
    """Every registered knob reads through its kind's getter with the
    env unset, returning the declared default."""
    for name, knob in knobs.REGISTRY.items():
        value = _GETTERS[knob.kind](name)
        if knob.default is None:
            assert value is None, name
        elif knob.kind == "bool":
            assert value is bool(knob.default), name
        else:
            assert value == knob.default, name


def test_unset_and_empty_mean_default(monkeypatch):
    assert knobs.get_int("GS_TRACE_RING") == 4096
    monkeypatch.setenv("GS_TRACE_RING", "")
    assert knobs.get_int("GS_TRACE_RING") == 4096
    monkeypatch.setenv("GS_TELEMETRY", "")
    assert knobs.get_bool("GS_TELEMETRY") is False


def test_int_parse_and_clamp(monkeypatch):
    monkeypatch.setenv("GS_STAGE_RETRIES", "7")
    assert knobs.get_int("GS_STAGE_RETRIES") == 7
    monkeypatch.setenv("GS_STAGE_RETRIES", "-3")   # lo=0
    assert knobs.get_int("GS_STAGE_RETRIES") == 0
    monkeypatch.setenv("GS_TRACE_RING", "4")       # lo=16
    assert knobs.get_int("GS_TRACE_RING") == 16
    monkeypatch.setenv("GS_AUTOTUNE_EXPLORE", "1")  # lo=2
    assert knobs.get_int("GS_AUTOTUNE_EXPLORE") == 2


def test_float_parse_and_clamp(monkeypatch):
    monkeypatch.setenv("GS_STAGE_TIMEOUT_S", "2.5")
    assert knobs.get_float("GS_STAGE_TIMEOUT_S") == 2.5
    monkeypatch.setenv("GS_STAGE_TIMEOUT_S", "-1")
    assert knobs.get_float("GS_STAGE_TIMEOUT_S") == 0.0
    assert knobs.get_float("GS_STAGE_BACKOFF_S") == 0.05


def test_bool_parse(monkeypatch):
    for text, want in (("1", True), ("true", True), ("YES", True),
                       ("on", True), ("0", False), ("false", False),
                       ("No", False), ("off", False)):
        monkeypatch.setenv("GS_TIER_DEMOTE", text)
        assert knobs.get_bool("GS_TIER_DEMOTE") is want, text


def test_str_choices(monkeypatch):
    assert knobs.get_str("GS_EGRESS") == ""
    monkeypatch.setenv("GS_EGRESS", "delta")
    assert knobs.get_str("GS_EGRESS") == "delta"
    monkeypatch.setenv("GS_EGRESS", "sideways")
    with pytest.raises(knobs.KnobError):
        knobs.get_str("GS_EGRESS")


def test_egress_accepts_documented_auto(monkeypatch):
    # the README table renders GS_EGRESS's default as `auto`; setting
    # the documented default explicitly must behave like unset
    monkeypatch.setenv("GS_EGRESS", "auto")
    assert knobs.get_str("GS_EGRESS") == "auto"
    from gelly_streaming_tpu.ops import delta_egress
    assert delta_egress.resolve_egress() in ("full", "delta")


def test_path_kind(monkeypatch):
    assert knobs.get_path("GS_TRACE_DIR") is None
    monkeypatch.setenv("GS_TRACE_DIR", "/tmp/ledger")
    assert knobs.get_path("GS_TRACE_DIR") == "/tmp/ledger"
    monkeypatch.setenv("GS_TUNE_CACHE", "0")  # conventional "disabled"
    assert knobs.get_path("GS_TUNE_CACHE") == "0"


@pytest.mark.parametrize("name,getter,bad", [
    ("GS_STAGE_RETRIES", knobs.get_int, "3O"),
    ("GS_STAGE_TIMEOUT_S", knobs.get_float, "fast"),
    ("GS_TELEMETRY", knobs.get_bool, "maybe"),
    ("GS_EGRESS_CAP", knobs.get_int, "1e3"),
])
def test_malformed_raises_typed(monkeypatch, name, getter, bad):
    """A mistyped knob fails FAST and NAMED instead of silently
    running at the default the operator didn't ask for."""
    monkeypatch.setenv(name, bad)
    with pytest.raises(knobs.KnobError) as exc:
        getter(name)
    assert name in str(exc.value)
    assert bad in str(exc.value)
    assert exc.value.knob is knobs.REGISTRY[name]
    assert isinstance(exc.value, ValueError)  # old callers still catch


def test_reads_are_live(monkeypatch):
    """No caching: tools/chaos_run.py and the fault tests flip knobs
    mid-process and the next read must see it."""
    monkeypatch.setenv("GS_STAGE_RETRIES", "1")
    assert knobs.get_int("GS_STAGE_RETRIES") == 1
    monkeypatch.setenv("GS_STAGE_RETRIES", "2")
    assert knobs.get_int("GS_STAGE_RETRIES") == 2


def test_kind_mismatch_is_programming_error():
    with pytest.raises(AssertionError):
        knobs.get_int("GS_TELEMETRY")       # declared bool
    with pytest.raises(AssertionError):
        knobs.get_bool("GS_NO_SUCH_KNOB")   # unregistered


def test_migrated_call_sites_resolve_through_registry(monkeypatch):
    """The five migrated modules' helpers read the registry (a spot
    check per module; gslint R3 proves the tree-wide absence of raw
    reads)."""
    from gelly_streaming_tpu.ops import autotune, delta_egress
    from gelly_streaming_tpu.ops import ingress_pipeline
    from gelly_streaming_tpu.utils import resilience, telemetry

    monkeypatch.setenv("GS_STAGE_TIMEOUT_S", "1.5")
    assert resilience.stage_timeout_s() == 1.5
    monkeypatch.setenv("GS_TELEMETRY", "1")
    assert telemetry.enabled() is True
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    assert autotune.enabled() is False
    monkeypatch.setenv("GS_EGRESS_CAP", "64")
    assert delta_egress.egress_cap(1024, 4096) == 64
    monkeypatch.setenv("GS_PIPELINE_INFLIGHT", "5")
    assert ingress_pipeline.inflight_limit() == 5
    monkeypatch.setenv("GS_TUNE_CACHE", "0")
    assert autotune.cache_path("cpu") == ""


def test_render_table_matches_readme():
    """The committed README contains the registry-rendered knob table
    verbatim — the doc-drift fixture gslint R3 also diffs."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    table = knobs.render_table()
    assert table in readme
    assert len(table.splitlines()) == len(ALL) + 2  # header + rule

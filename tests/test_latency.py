"""End-to-end latency plane (utils/latency.py).

Contracts pinned here:
- the stage waterfall is CONSERVATIVE: per-window stage latencies sum
  to the measured ingest→deliver end-to-end exactly (the identity
  tools/latency_report.py re-checks from ledgers within 5%);
- batch→window membership joins each finalized window back to the
  admission stamp of the edge that completed it, across the cohort,
  the engine, and the driver paths;
- `GS_LATENCY=0` digest parity: summaries, WindowResult fields, serve
  rows and WAL bytes are bit-identical to a plane-less build (the
  zero-overhead contract; the ≤1.05× armed bar is committed to
  PERF_cpu.json's `latency` section and re-checked here);
- kill→WAL-replay recovery preserves admission timestamps (honest,
  larger latency — never reset-to-zero);
- the SLO module burns the error budget, flips the `/healthz`
  `latency` section degraded on sustained burn (durable `slo_burn`),
  and recovers;
- tools/latency_report.py exits non-zero on unaccounted time.
"""

import json
import os
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.tenancy import TenantCohort
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.utils import knobs, latency, metrics, telemetry

EB, VB = 128, 256


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("GS_LATENCY", "1")
    latency.reset()
    yield
    latency.reset()


def make_edges(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, VB, n, dtype=np.int32),
            rng.integers(0, VB, n, dtype=np.int32))


# ----------------------------------------------------------------------
# plane semantics
# ----------------------------------------------------------------------
def test_disarmed_is_inert(monkeypatch):
    monkeypatch.setenv("GS_LATENCY", "0")
    latency.reset()
    assert latency.on_admit("t", 10) is None
    assert latency.on_window("t", edges=10) is None
    assert latency.stamps() is None
    latency.stamp(None, "prep")  # no-op by contract
    assert latency.queue_age("t") is None
    assert latency.oldest_age() is None
    assert latency.health_section() == {"enabled": False}
    assert latency.percentile_fields() == {}
    assert latency.recent() == []


def test_waterfall_sums_to_e2e_exactly(armed):
    t0 = latency.clock()
    latency.on_admit("t", 100, t0=t0)
    st = latency.stamps()
    for key in ("start", "prep", "h2d", "dispatch"):
        latency.stamp(st, key)
    rec = latency.on_window("t", edges=100, st=st, ordinal=0)
    assert set(rec["stages"]) == {"admission", "queue_wait", "prep",
                                  "h2d", "dispatch", "finalize"}
    assert sum(rec["stages"].values()) == pytest.approx(
        rec["e2e_s"], abs=1e-12)
    assert rec["e2e_s"] >= 0
    assert not rec["replayed"]


def test_window_joins_completing_batch(armed):
    # two batches; the first window (5 edges) completes inside batch
    # 1, the second (5 edges) needs batch 2 — each window's admission
    # anchor is its COMPLETING batch's stamp
    t1 = latency.clock() - 1.0
    t2 = latency.clock() - 0.2
    latency.on_admit("t", 6, t0=t1, t1=t1)
    latency.on_admit("t", 4, t0=t2, t1=t2)
    w1 = latency.on_window("t", edges=5)
    w2 = latency.on_window("t", edges=5)
    assert w1["e2e_s"] == pytest.approx(
        latency.clock() - t1, abs=0.05)
    assert w2["e2e_s"] == pytest.approx(
        latency.clock() - t2, abs=0.05)
    assert w1["e2e_s"] > w2["e2e_s"]


def test_queue_age_tracks_oldest_unfinalized(armed):
    t0 = latency.clock() - 2.0
    latency.on_admit("t", 10, t0=t0, t1=t0)
    age = latency.queue_age("t")
    assert age == pytest.approx(2.0, abs=0.2)
    assert latency.oldest_age() == pytest.approx(age, abs=0.2)
    latency.on_window("t", edges=10)
    assert latency.queue_age("t") is None  # fully finalized


def test_deferred_delivery_and_settle(armed):
    latency.on_admit("t", 10)
    rec = latency.on_window("t", edges=10, ordinal=7, defer=True)
    assert latency.recent() == []  # not emitted yet
    time.sleep(0.01)
    done = latency.delivered("t", 7)
    assert done is rec
    assert done["stages"]["deliver"] >= 0.01
    assert sum(done["stages"].values()) == pytest.approx(
        done["e2e_s"], abs=1e-12)
    assert latency.delivered("t", 7) is None  # already taken
    # settle() emits what was never delivered
    latency.on_admit("t", 5)
    latency.on_window("t", edges=5, ordinal=8, defer=True)
    assert latency.settle() == 1
    assert len(latency.recent()) == 2


def test_lane_cardinality_bound(armed, monkeypatch):
    monkeypatch.setenv("GS_METRICS_SERIES", "2")
    for i in range(5):
        latency.on_admit("lane-%d" % i, 1)
        latency.on_window("lane-%d" % i, edges=1)
    sec = latency.health_section()
    assert len(sec["tenants"]) <= 3  # 2 lanes + the overflow row
    assert "overflow" in sec["tenants"]


def test_mark_memory_bounded(armed, monkeypatch):
    monkeypatch.setenv("GS_LAT_MARKS", "16")
    latency.reset()
    for _ in range(100):
        latency.on_admit("t", 1)
    # the window whose mark was evicted still records, flagged approx
    rec = latency.on_window("t", edges=1)
    assert rec.get("approx") is True


def test_replay_marks_preserve_original_time(armed):
    old = latency.clock() - 3.0
    latency.on_replay("t", 10, np.array([int(old * 1e9)] * 10))
    rec = latency.on_window("t", edges=10)
    assert rec["replayed"] is True
    assert rec["e2e_s"] == pytest.approx(3.0, abs=0.2)


# ----------------------------------------------------------------------
# SLO burn
# ----------------------------------------------------------------------
def test_slo_burn_flip_and_recover(armed, monkeypatch, tmp_path):
    monkeypatch.setenv("GS_SLO_P99_S", "0.5")
    monkeypatch.setenv("GS_SLO_BUDGET", "0.1")
    monkeypatch.setenv("GS_SLO_BURN", "2.0")
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    try:
        old = latency.clock() - 2.0  # every window blows the target
        for i in range(10):
            latency.on_admit("t", 1, t0=old, t1=old)
            latency.on_window("t", edges=1)
        sec = latency.health_section()
        assert sec["status"] == "degraded"
        assert sec["slo"]["burn_rate"] >= 2.0
        telemetry.flush()
        ledger = telemetry.ledger_path()
        events = [json.loads(line)["name"]
                  for line in open(ledger) if line.strip()
                  if json.loads(line).get("t") == "event"]
        assert events.count("slo_burn") == 1  # once per episode
        # recovery: fast windows dilute the burn below threshold
        for i in range(200):
            latency.on_admit("t", 1)
            latency.on_window("t", edges=1)
        assert latency.health_section()["status"] == "ok"
        telemetry.flush()
        events = [json.loads(line)["name"]
                  for line in open(ledger) if line.strip()
                  if json.loads(line).get("t") == "event"]
        assert "slo_recovered" in events
    finally:
        telemetry.reset()


# ----------------------------------------------------------------------
# instrumented paths
# ----------------------------------------------------------------------
def test_engine_records_reconcile(armed):
    src, dst = make_edges(4 * EB)
    eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
    out = eng.process(src, dst)
    recs = latency.recent()
    assert len(recs) == len(out) == 4
    for rec in recs:
        assert set(rec["stages"]) >= {"admission", "queue_wait",
                                      "prep", "h2d", "dispatch",
                                      "finalize"}
        assert sum(rec["stages"].values()) == pytest.approx(
            rec["e2e_s"], abs=1e-9)
    assert [r["window"] for r in recs] == [0, 1, 2, 3]


def test_cohort_records_and_ordinals(armed):
    src, dst = make_edges(3 * EB, seed=1)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    co.feed("a", src, dst)
    out = co.pump()
    assert len(out["a"]) == 3
    recs = latency.recent()
    assert [r["window"] for r in recs] == [0, 1, 2]
    assert all(r["tenant"] == "a" for r in recs)
    for rec in recs:
        assert sum(rec["stages"].values()) == pytest.approx(
            rec["e2e_s"], abs=1e-9)


def test_cohort_replay_preserves_admission(armed, tmp_path):
    src, dst = make_edges(2 * EB, seed=2)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    assert co.enable_wal(str(tmp_path))
    co.admit("t")
    co.feed("t", src, dst)
    co._wal.close()  # crash before any pump
    time.sleep(0.2)
    latency.reset()  # fresh plane = the new-process shape
    co2 = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    assert co2.enable_wal(str(tmp_path))
    co2.recover()
    out = co2.pump()
    assert len(out["t"]) == 2
    recs = latency.recent()
    assert all(r["replayed"] for r in recs)
    assert all(r["e2e_s"] >= 0.2 for r in recs), \
        "replayed windows reset their admission time"


def test_demoted_tenant_keeps_its_lane(armed):
    src, dst = make_edges(2 * EB, seed=3)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    co.feed("t", src, dst)
    co.demote("t", reason="test")
    out = co.pump()
    assert len(out["t"]) == 2
    recs = latency.recent()
    assert len(recs) == 2
    assert all(r["tenant"] == "t" for r in recs)
    # the single-tenant engine must NOT have re-stamped admission:
    # the lane's fed cursor still equals what feed() admitted
    assert latency.queue_age("t") is None


def test_driver_attaches_window_records(armed):
    from gelly_streaming_tpu.core.driver import (
        StreamingAnalyticsDriver)

    src, dst = make_edges(4 * EB, seed=4)
    drv = StreamingAnalyticsDriver(window_ms=1000, vertex_bucket=VB,
                                   edge_bucket=EB)
    results = drv.run_arrays(src.astype(np.int64),
                             dst.astype(np.int64))
    assert len(results) == 4
    for res in results:
        assert res.latency is not None
        assert sum(res.latency["stages"].values()) == pytest.approx(
            res.latency["e2e_s"], abs=1e-9)


# ----------------------------------------------------------------------
# zero-overhead / digest parity
# ----------------------------------------------------------------------
def test_disarmed_digest_parity(monkeypatch):
    src, dst = make_edges(4 * EB, seed=5)
    monkeypatch.setenv("GS_LATENCY", "0")
    latency.reset()
    base_eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
    base = base_eng.process(src, dst)
    assert latency.recent() == []

    monkeypatch.setenv("GS_LATENCY", "1")
    latency.reset()
    armed_eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
    got = armed_eng.process(src, dst)
    assert got == base  # summaries bit-identical armed or not
    latency.reset()


def test_disarmed_driver_has_no_latency_field(monkeypatch):
    from gelly_streaming_tpu.core.driver import (
        StreamingAnalyticsDriver)

    monkeypatch.setenv("GS_LATENCY", "0")
    latency.reset()
    src, dst = make_edges(2 * EB, seed=6)
    drv = StreamingAnalyticsDriver(window_ms=1000, vertex_bucket=VB,
                                   edge_bucket=EB)
    for res in drv.run_arrays(src.astype(np.int64),
                              dst.astype(np.int64)):
        assert res.latency is None


def test_wal_bytes_identical_disarmed(monkeypatch, tmp_path):
    # the journal of a disarmed run must carry NO ts column — byte
    # parity with a plane-less build
    from gelly_streaming_tpu.utils import wal as wal_mod

    monkeypatch.setenv("GS_LATENCY", "0")
    latency.reset()
    src, dst = make_edges(EB, seed=7)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    assert co.enable_wal(str(tmp_path))
    co.admit("t")
    co.feed("t", src, dst)
    co._wal.close()
    for _tid, _start, _s, _d, ts in wal_mod.replay(str(tmp_path)):
        assert ts is None


# ----------------------------------------------------------------------
# serve rows / status (the self-throttle satellite)
# ----------------------------------------------------------------------
def test_serve_rows_carry_latency_fields(armed):
    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)

    src, dst = make_edges(2 * EB, seed=8)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    server = StreamServer(co, port=0).start()
    cli = ServeClient(server.port)
    try:
        assert cli.admit("t1")["ok"]
        assert cli.feed("t1", src.tolist(), dst.tolist())["ok"]
        rows = cli.pump()["results"]["t1"]
        assert all("latency_s" in r and "queue_edges" in r
                   for r in rows)
        assert all(r["latency_s"] > 0 for r in rows)
        status = cli.status()["serve"]
        assert status["queues"]["t1"]["edges"] == 0
        assert status["latency"]["enabled"] is True
        assert "t1" in status["latency"]["tenants"]
    finally:
        cli.close()
        server.close()


def test_demoted_tenant_rows_keep_latency_fields(armed):
    # the engine path honors the cohort's delivery deferral, so a
    # demoted tenant's served rows still carry the self-throttle
    # fields (review-hardened)
    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)

    src, dst = make_edges(2 * EB, seed=9)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    server = StreamServer(co, port=0).start()
    cli = ServeClient(server.port)
    try:
        assert cli.admit("t1")["ok"]
        co.demote("t1", reason="test")
        assert cli.feed("t1", src.tolist(), dst.tolist())["ok"]
        rows = cli.pump()["results"]["t1"]
        assert len(rows) == 2
        assert all("latency_s" in r and "queue_edges" in r
                   for r in rows), rows
    finally:
        cli.close()
        server.close()
    # close() restored the direct-pump shape, lane-scoped
    assert co.defer_delivery is False


def test_stale_stamps_cleared_on_reset_and_next_call(armed):
    src, dst = make_edges(2 * EB, seed=10)
    eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
    eng._lat_stamps[0] = {"start": 0.0}  # stranded by a failed call
    eng.reset()
    assert eng._lat_stamps == {}
    eng._lat_stamps[0] = {"start": 0.0}
    eng.process(src, dst)  # clears stranded stamps before joining
    recs = latency.recent()
    # the stranded all-zero boundary never joined: stages stay sane
    assert all(sum(r["stages"].values()) == pytest.approx(
        r["e2e_s"], abs=1e-9) for r in recs)
    assert all(r["stages"].get("queue_wait", 0) < 60
               for r in recs)


# ----------------------------------------------------------------------
# tools: latency_report reconciliation
# ----------------------------------------------------------------------
def _ledger_line(tenant, window, e2e, stages):
    return json.dumps({
        "t": "event", "name": "latency.window", "trace": "x",
        "a": {"tenant": tenant, "window": window, "edges": 10,
              "e2e_s": e2e, "stages": stages}})


def test_latency_report_clean_and_violation(tmp_path):
    from tools import latency_report

    good = tmp_path / "good.jsonl"
    good.write_text("\n".join([
        _ledger_line("t", 0, 1.0, {"admission": 0.2, "dispatch": 0.5,
                                   "finalize": 0.3}),
        _ledger_line("t", 1, 0.5, {"admission": 0.1, "dispatch": 0.3,
                                   "finalize": 0.1}),
    ]) + "\n")
    assert latency_report.main([str(good)]) == 0

    bad = tmp_path / "bad.jsonl"
    bad.write_text(_ledger_line(
        "t", 0, 1.0, {"admission": 0.1, "finalize": 0.1}) + "\n")
    assert latency_report.main([str(bad)]) == 1  # unaccounted time

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert latency_report.main([str(empty)]) == 2


def test_latency_report_rollup_and_tenant_filter(tmp_path, capsys):
    from tools import latency_report

    path = tmp_path / "l.jsonl"
    path.write_text("\n".join(
        [_ledger_line("a", i, 0.1 * (i + 1),
                      {"admission": 0.1 * (i + 1)})
         for i in range(4)]
        + [_ledger_line("b", 0, 9.0, {"admission": 9.0})]) + "\n")
    assert latency_report.main([str(path), "--tenant", "a",
                                "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["windows"] == 4
    assert list(out["rollup"]) == ["a"]
    assert out["rollup"]["a"]["e2e_p99_s"] == pytest.approx(0.4)


# ----------------------------------------------------------------------
# knobs & committed evidence
# ----------------------------------------------------------------------
def test_latency_knobs_registered():
    for name in ("GS_LATENCY", "GS_LAT_MARKS", "GS_LAT_PENDING",
                 "GS_SLO_P99_S", "GS_SLO_BUDGET", "GS_SLO_WINDOW_S",
                 "GS_SLO_BURN"):
        assert name in knobs.REGISTRY, name


def test_committed_latency_section_meets_the_bar():
    """PERF_cpu.json's `latency` section is this plane's acceptance
    bar: parity true, armed overhead ≤ 1.05×, waterfalls reconciled
    on the 524K/32768 row."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PERF_cpu.json")
    with open(path) as f:
        perf = json.load(f)
    meta = perf.get("latency")
    assert meta is not None, "PERF_cpu.json has no latency section"
    assert meta["parity"] is True
    assert meta["overhead_ratio"] <= 1.05
    assert meta["edge_bucket"] == 32768
    assert meta["num_edges"] == 524288
    assert meta["reconciled_windows"] >= 16
    assert meta["max_unaccounted_frac"] <= 0.05
    assert meta["e2e_p99_s"] > 0


def test_healthz_latency_section_registered(monkeypatch):
    monkeypatch.setenv("GS_METRICS", "1")
    monkeypatch.setenv("GS_LATENCY", "1")
    metrics.reset()
    latency.reset()
    latency.on_admit("t", 1)
    latency.on_window("t", edges=1)
    snap = metrics.health_snapshot()
    assert snap["latency"]["enabled"] is True
    assert "t" in snap["latency"]["tenants"]
    metrics.reset()
    latency.reset()

"""utils/tracing direct coverage (previously tested only through the
driver): the `device_trace` graceful-capture contract and the
StepTimer span adapter.

- log-dir creation: the trace dir (nested) is created before the
  profiler starts, so a fresh GS_TRACE_DIR-style path never fails the
  capture it was meant to hold;
- graceful path: a backend whose profiler cannot start degrades to a
  no-op with a `device_trace_failed` telemetry event instead of
  taking down the stream it was asked to observe, and stop is never
  called for a start that failed;
- nesting: jax.profiler allows ONE trace at a time — an inner
  device_trace no-ops under the outermost one (exactly one
  start/stop pair), including across threads;
- the completed-capture stamp: a real CPU capture finishes clean and
  leaves a durable `device_trace_captured` event carrying the log dir
  and the cost observatory's program inventory — the join key that
  makes an on-chip xprof capture attributable;
- StepTimer: `step()` yields the telemetry span (so dispatch-owning
  steps can attach program/sig attrs) while report()/event_log() keep
  their accumulation semantics.
"""

import os
import threading

import pytest

from gelly_streaming_tpu.utils import telemetry, tracing


@pytest.fixture
def armed(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path / "ledger"))
    telemetry.reset()
    yield
    telemetry.reset()


class _FakeProfiler:
    """Deterministic stand-in for jax.profiler: counts start/stop
    pairs, optionally fails on start."""

    def __init__(self, fail_start=False):
        self.starts = 0
        self.stops = 0
        self.fail_start = fail_start

    def start_trace(self, log_dir):
        if self.fail_start:
            raise RuntimeError("profiler unavailable on this backend")
        self.starts += 1

    def stop_trace(self):
        self.stops += 1


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax

    prof = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", prof)
    return prof


# ----------------------------------------------------------------------
# log-dir creation + the one-start-one-stop contract
# ----------------------------------------------------------------------
def test_device_trace_creates_log_dir(tmp_path, fake_profiler):
    log_dir = str(tmp_path / "traces" / "run0")  # nested, absent
    with tracing.device_trace(log_dir):
        assert os.path.isdir(log_dir)
    assert (fake_profiler.starts, fake_profiler.stops) == (1, 1)


def test_device_trace_nested_is_noop(tmp_path, fake_profiler):
    log_dir = str(tmp_path / "t")
    with tracing.device_trace(log_dir):
        with tracing.device_trace(log_dir):
            with tracing.device_trace(log_dir):
                pass
        # inner exits must not stop the outer capture
        assert fake_profiler.stops == 0
    assert (fake_profiler.starts, fake_profiler.stops) == (1, 1)


def test_device_trace_nested_across_threads(tmp_path, fake_profiler):
    """The nesting guard is process-global (jax.profiler is): a
    concurrent capture from another thread no-ops too."""
    log_dir = str(tmp_path / "t")
    entered = threading.Event()
    release = threading.Event()

    def inner():
        with tracing.device_trace(log_dir):
            entered.set()
            release.wait(timeout=10)

    with tracing.device_trace(log_dir):
        t = threading.Thread(target=inner)
        t.start()
        assert entered.wait(timeout=10)
        assert fake_profiler.starts == 1    # inner never started
        release.set()
        t.join()
    assert (fake_profiler.starts, fake_profiler.stops) == (1, 1)


# ----------------------------------------------------------------------
# graceful degradation
# ----------------------------------------------------------------------
def test_device_trace_failed_start_degrades_to_noop(
        tmp_path, armed, monkeypatch):
    import jax

    prof = _FakeProfiler(fail_start=True)
    monkeypatch.setattr(jax, "profiler", prof)
    log_dir = str(tmp_path / "t")
    with tracing.device_trace(log_dir):
        pass                                # body still runs
    assert prof.stops == 0                  # no stop for a failed start
    evs = [r for r in telemetry.records() if r["t"] == "event"]
    fail = next(e for e in evs if e["name"] == "device_trace_failed")
    assert "profiler unavailable" in fail["a"]["error"]
    assert not any(e["name"] == "device_trace_captured" for e in evs)


def test_device_trace_body_exception_still_stops(tmp_path,
                                                 fake_profiler):
    with pytest.raises(ValueError):
        with tracing.device_trace(str(tmp_path / "t")):
            raise ValueError("stream died mid-capture")
    assert (fake_profiler.starts, fake_profiler.stops) == (1, 1)


# ----------------------------------------------------------------------
# the real CPU path + the captured stamp feeding the observatory
# ----------------------------------------------------------------------
def test_device_trace_cpu_capture_stamps_durable_event(
        tmp_path, armed, monkeypatch):
    """End-to-end on the real jax.profiler (CPU): the capture
    completes, and the durable `device_trace_captured` event carries
    the log dir plus the cost observatory's program count — the
    correlation record an on-chip xprof session is joined by."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.utils import costmodel, metrics

    monkeypatch.setenv("GS_COSTMODEL", "1")
    costmodel.reset()
    try:
        log_dir = str(tmp_path / "xla")
        with tracing.device_trace(log_dir):
            fn = metrics.wrap_jit(
                "trace_toy", __import__("jax").jit(lambda x: x + 1))
            fn(jnp.arange(8)).block_until_ready()
        assert os.path.isdir(log_dir)
        evs = [r for r in telemetry.records() if r["t"] == "event"]
        cap = next(e for e in evs
                   if e["name"] == "device_trace_captured")
        assert cap["a"]["log_dir"] == log_dir
        # the program the capture profiled is in the inventory count
        assert cap["a"]["programs"] >= 1
        assert ("trace_toy", "i32[8]") in costmodel.programs()
    finally:
        costmodel.reset()


# ----------------------------------------------------------------------
# StepTimer: span-yield + unchanged accumulation semantics
# ----------------------------------------------------------------------
def test_steptimer_step_yields_span_for_attrs(armed):
    timer = tracing.StepTimer()
    with timer.step("snapshot_scan", num_records=4) as sp:
        sp.attrs.update(program="fused_scan", sig="i32[4]")
    rec = next(r for r in telemetry.records()
               if r.get("name") == "step.snapshot_scan")
    assert rec["a"]["program"] == "fused_scan"
    assert rec["a"]["sig"] == "i32[4]"
    rows = {r["op"]: r for r in timer.report()}
    assert rows["snapshot_scan"]["records"] == 4
    assert rows["snapshot_scan"]["calls"] == 1


def test_steptimer_disarmed_report_unchanged(monkeypatch):
    monkeypatch.setenv("GS_TELEMETRY", "0")
    telemetry.reset()
    try:
        timer = tracing.StepTimer()
        for _ in range(3):
            with timer.step("intern", num_records=10):
                pass
        assert telemetry.records() == []
        rows = {r["op"]: r for r in timer.report()}
        assert rows["intern"]["calls"] == 3
        assert rows["intern"]["records"] == 30
    finally:
        telemetry.reset()

"""Columnar streaming driver: end-to-end ingest→device analytics with
carried state, bucket growth, sharding, and checkpoint/resume."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops import triangles as tri_ops
from gelly_streaming_tpu.ops import unionfind
from gelly_streaming_tpu.parallel.mesh import make_mesh


def _stream(seed=0, n=3000, v=500):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, n)
    dst = rng.integers(0, v, n)
    ts = np.sort(rng.integers(0, 5000, n))
    return src, dst, ts


def _reference_results(src, dst, ts, window_ms):
    """Independent per-window analytics over external ids."""
    starts = ts - ts % window_ms
    out = []
    seen_edges_s, seen_edges_d = [], []
    for w in np.unique(starts):
        m = starts == w
        seen_edges_s.append(src[m])
        seen_edges_d.append(dst[m])
        all_s = np.concatenate(seen_edges_s)
        all_d = np.concatenate(seen_edges_d)
        nv = int(max(all_s.max(), all_d.max())) + 1
        deg = np.bincount(all_s, minlength=nv) + np.bincount(all_d,
                                                            minlength=nv)
        tri = tri_ops.triangle_count_sparse(src[m], dst[m], nv)
        _, _, odd = unionfind.bipartite_labels(all_s, all_d, nv)
        out.append((int(w), deg, tri, odd))
    return out


@pytest.mark.parametrize("sharded", [False, True])
def test_driver_matches_independent_analytics(sharded):
    src, dst, ts = _stream()
    mesh = make_mesh() if sharded else None
    drv = StreamingAnalyticsDriver(window_ms=1000, mesh=mesh,
                                   vertex_bucket=64, edge_bucket=64)
    results = drv.run_arrays(src, dst, ts)  # buckets must grow en route
    refs = _reference_results(src, dst, ts, 1000)
    assert len(results) == len(refs)
    for res, (w, deg, tri, odd) in zip(results, refs):
        assert res.window_start == w
        ids = res.vertex_ids
        # driver state is dense-slot indexed; compare via external ids
        got_deg = np.zeros_like(deg)
        got_deg[ids] = res.degrees[: len(ids)]
        np.testing.assert_array_equal(got_deg[deg > 0], deg[deg > 0])
        assert res.triangles == tri
        got_odd = np.zeros_like(odd)
        got_odd[ids] = res.bipartite_odd[: len(ids)]
        np.testing.assert_array_equal(got_odd[deg > 0], odd[deg > 0])
        # cc labels: same partition as host labels over touched ids
        labels = res.cc_labels[: len(ids)]
        assert labels.min() >= 0


def test_driver_cc_partition_matches_host():
    src = np.array([1, 2, 10, 20, 2])
    dst = np.array([2, 3, 11, 21, 10])
    drv = StreamingAnalyticsDriver(window_ms=100,
                                   analytics=("cc",))
    (res,) = drv.run_arrays(src, dst, np.zeros(5, np.int64))
    ids = res.vertex_ids
    lab = res.cc_labels
    by_label = {}
    for slot, ext in enumerate(ids):
        by_label.setdefault(int(lab[slot]), set()).add(int(ext))
    groups = sorted(sorted(g) for g in by_label.values())
    assert groups == [[1, 2, 3, 10, 11], [20, 21]]


def test_driver_count_windows_without_timestamps():
    src, dst, _ = _stream(n=300)
    drv = StreamingAnalyticsDriver(window_ms=1000, edge_bucket=128,
                                   analytics=("triangles",))
    results = drv.run_arrays(src, dst)
    assert [r.num_edges for r in results] == [128, 128, 44]
    for r, s in zip(results, range(0, 300, 128)):
        assert r.triangles == tri_ops.triangle_count_sparse(
            src[s:s + 128], dst[s:s + 128], 500)


def test_driver_checkpoint_resume():
    src, dst, ts = _stream(seed=3)
    half = len(src) // 2
    a = StreamingAnalyticsDriver(window_ms=500, vertex_bucket=64,
                                 edge_bucket=64)
    a.run_arrays(src[:half], dst[:half], ts[:half])
    state = a.state_dict()

    b = StreamingAnalyticsDriver(window_ms=500, vertex_bucket=64,
                                 edge_bucket=64)
    b.load_state_dict(state)
    out_b = b.run_arrays(src[half:], dst[half:], ts[half:])
    out_a = a.run_arrays(src[half:], dst[half:], ts[half:])
    for ra, rb in zip(out_a, out_b):
        np.testing.assert_array_equal(ra.degrees, rb.degrees)
        np.testing.assert_array_equal(ra.cc_labels, rb.cc_labels)
        np.testing.assert_array_equal(ra.bipartite_odd, rb.bipartite_odd)
        assert ra.triangles == rb.triangles
        np.testing.assert_array_equal(ra.vertex_ids, rb.vertex_ids)


def test_driver_ascending_timestamp_contract():
    drv = StreamingAnalyticsDriver(window_ms=100)
    with pytest.raises(ValueError, match="ascending"):
        drv.run_arrays(np.array([1, 2]), np.array([2, 3]),
                       np.array([500, 100]))


def test_driver_tracing_and_file(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("1 2 100\n2 3 150\n1 3 180\n3 4 300\n")
    drv = StreamingAnalyticsDriver(window_ms=200, tracing=True)
    results = drv.run_file(str(p))
    assert [r.triangles for r in results] == [1, 0]
    report = drv.trace_report()
    assert {row["op"] for row in report} >= {"intern", "triangles"}


def test_driver_cross_mode_checkpoint_converts():
    """A single-chip checkpoint now CONVERTS onto a mesh driver (and
    vice versa — the engine slabs are gathered replicated state): the
    resumed sharded session continues with the checkpointed analytics
    instead of refusing. Full round-trip equality is pinned by
    tests/test_checkpoint_roundtrip.py's cross-mode suite."""
    a = StreamingAnalyticsDriver(window_ms=500)
    a.run_arrays(np.array([1, 2]), np.array([2, 3]),
                 np.array([100, 200]))
    state = a.state_dict()
    b = StreamingAnalyticsDriver(window_ms=500, mesh=make_mesh())
    b.load_state_dict(state)
    assert b.windows_done == a.windows_done
    st = b._engine.state_dict()
    np.testing.assert_array_equal(
        np.asarray(st["degree_state"])[:len(a._degrees)], a._degrees)


def test_driver_auto_checkpoint_failure_recovery(tmp_path):
    """Crash/recover: a driver checkpointing every 2 windows dies; a
    fresh driver resumes from the snapshot cursor and the final state
    matches an uninterrupted run."""
    ckpt = str(tmp_path / "state.ckpt")
    src, dst, _ = _stream(seed=7, n=1024)
    eb = 128  # count-based windows: 8 windows of 128 edges

    a = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb)
    a.enable_auto_checkpoint(ckpt, every_n_windows=2)
    a.run_arrays(src[: 6 * eb], dst[: 6 * eb])  # "crash" after 6 windows

    b = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb)
    assert b.try_resume(ckpt)
    assert b.windows_done == 6  # checkpoint fired at window 6
    out_b = b.run_arrays(src[b.windows_done * eb:],
                         dst[b.windows_done * eb:])

    c = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb)
    out_c = c.run_arrays(src, dst)
    np.testing.assert_array_equal(out_b[-1].degrees, out_c[-1].degrees)
    np.testing.assert_array_equal(out_b[-1].cc_labels, out_c[-1].cc_labels)
    assert out_b[-1].triangles == out_c[-1].triangles
    assert not StreamingAnalyticsDriver(window_ms=0).try_resume(
        str(tmp_path / "missing.ckpt"))


def test_stream_file_matches_run_file(tmp_path):
    """Chunked streaming ingestion (bounded memory) produces the exact
    same windows as whole-file processing, for event-time and
    count-based streams, across tiny chunk sizes."""
    rng = np.random.default_rng(13)
    n = 700
    src = rng.integers(0, 80, n)
    dst = rng.integers(0, 80, n)
    ts = np.sort(rng.integers(0, 2000, n))
    p_ts = tmp_path / "ts.txt"
    p_ts.write_text("".join(f"{s} {d} {t}\n" for s, d, t in
                            zip(src, dst, ts)))
    p_plain = tmp_path / "plain.txt"
    p_plain.write_text("".join(f"{s} {d}\n" for s, d in zip(src, dst)))

    for path in (p_ts, p_plain):
        base = StreamingAnalyticsDriver(window_ms=300, edge_bucket=128)
        want = base.run_file(str(path))
        for chunk_bytes in (64, 1 << 20):
            drv = StreamingAnalyticsDriver(window_ms=300, edge_bucket=128)
            got = list(drv.stream_file(str(path), chunk_bytes=chunk_bytes))
            assert [r.window_start for r in got] == \
                   [r.window_start for r in want]
            assert [r.triangles for r in got] == \
                   [r.triangles for r in want]
            np.testing.assert_array_equal(got[-1].degrees,
                                          want[-1].degrees)
            np.testing.assert_array_equal(got[-1].cc_labels,
                                          want[-1].cc_labels)


def test_stream_file_resume_skips_processed_edges(tmp_path):
    """Crash/resume over an event-time file: resume=True replays
    nothing (carried state equals the uninterrupted run's)."""
    rng = np.random.default_rng(31)
    n = 900
    src = rng.integers(0, 90, n)
    dst = rng.integers(0, 90, n)
    ts = np.sort(rng.integers(0, 3000, n))
    p = tmp_path / "s.txt"
    p.write_text("".join(f"{s} {d} {t}\n" for s, d, t in
                         zip(src, dst, ts)))
    ck = str(tmp_path / "c.ckpt")

    want = StreamingAnalyticsDriver(window_ms=300).run_file(str(p))

    a = StreamingAnalyticsDriver(window_ms=300)
    a.enable_auto_checkpoint(ck, every_n_windows=2)
    seen = []
    for i, res in enumerate(a.stream_file(str(p), chunk_bytes=2048)):
        seen.append(res)
        if i == 4:
            break  # crash; last checkpoint covers windows 1..4

    b = StreamingAnalyticsDriver(window_ms=300)
    assert b.try_resume(ck)
    # the staged-checkpoint contract (driver._stage_ckpt): a FLUSHED
    # checkpoint never covers windows the consumer wasn't handed, and
    # lags the consumer by at most one checkpoint interval — so resume
    # can re-emit delivered windows (at-least-once) but never skip
    # undelivered ones
    done = b.windows_done  # capture: processing advances the cursor
    assert done <= len(seen)
    assert done >= len(seen) - 2
    rest = list(b.stream_file(str(p), chunk_bytes=2048, resume=True))
    # resume continues at exactly the first un-checkpointed window…
    assert [r.window_start for r in rest] == \
           [r.window_start for r in want[done:]]
    assert [r.triangles for r in rest] == \
           [r.triangles for r in want[done:]]
    # …and carried state ends identical to the uninterrupted run
    np.testing.assert_array_equal(rest[-1].degrees, want[-1].degrees)
    np.testing.assert_array_equal(rest[-1].cc_labels, want[-1].cc_labels)
    np.testing.assert_array_equal(rest[-1].bipartite_odd,
                                  want[-1].bipartite_odd)


def test_checkpoint_never_covers_unyielded_windows(tmp_path):
    """At-least-once delivery under ANY crash point: for every prefix
    length K of consumed windows, the checkpoint on disk covers at
    most K windows, and a resumed re-feed emits exactly the
    uninterrupted run's suffix from the checkpoint on — computed
    windows are re-emitted, never dropped (the batched path used to
    checkpoint ahead of emission; found by tools/endurance_run.py)."""
    rng = np.random.default_rng(7)
    n = 1600
    src = rng.integers(0, 60, n)
    dst = rng.integers(0, 60, n)
    ts = np.sort(rng.integers(0, 4000, n))
    p = tmp_path / "s.txt"
    p.write_text("".join(f"{s} {d} {t}\n" for s, d, t in
                         zip(src, dst, ts)))
    want = StreamingAnalyticsDriver(window_ms=250).run_file(str(p))

    for crash_after in (1, 3, 6, len(want) - 1):
        ck = str(tmp_path / f"c{crash_after}.ckpt")
        a = StreamingAnalyticsDriver(window_ms=250)
        a.enable_auto_checkpoint(ck, every_n_windows=2)
        seen = 0
        # big chunk_bytes: the whole file is ONE batch, the shape that
        # used to checkpoint far ahead of what was yielded
        for res in a.stream_file(str(p), chunk_bytes=1 << 20):
            seen += 1
            if seen > crash_after:
                break
        b = StreamingAnalyticsDriver(window_ms=250)
        if not b.try_resume(ck):
            continue  # crashed before the first flush: fresh start
        done = b.windows_done
        assert done <= seen, (crash_after, done, seen)
        rest = list(b.stream_file(str(p), chunk_bytes=1 << 20,
                                  resume=True))
        assert [r.window_start for r in rest] == \
               [r.window_start for r in want[done:]]
        assert [r.triangles for r in rest] == \
               [r.triangles for r in want[done:]]
        np.testing.assert_array_equal(rest[-1].degrees,
                                      want[-1].degrees)


def test_sharded_bucket_growth_carries_engine_state():
    """Vertex-bucket growth AFTER the sharded engine exists must carry
    degree/label/bipartite state into the wider bucket (regression:
    read-only state_dict views + remap correctness)."""
    drv = StreamingAnalyticsDriver(window_ms=0, mesh=make_mesh(),
                                   vertex_bucket=8, edge_bucket=16)
    # window 1: a full 16-edge bucket over vertices 0..7 only, so the
    # engine is built at vb=8 before any growth
    s1 = np.tile(np.arange(4), 4)
    d1 = s1 + 4                                              # nv = 8
    # window 2: new vertices force growth with live engine state
    s2, d2 = np.arange(16), np.arange(16) + 16               # nv = 32
    drv.run_arrays(s1, d1)
    out = drv.run_arrays(s2, d2)
    single = StreamingAnalyticsDriver(window_ms=0, vertex_bucket=8,
                                      edge_bucket=16)
    single.run_arrays(s1, d1)
    want = single.run_arrays(s2, d2)
    np.testing.assert_array_equal(out[-1].degrees[:32],
                                  want[-1].degrees[:32])
    np.testing.assert_array_equal(out[-1].bipartite_odd[:32],
                                  want[-1].bipartite_odd[:32])
    assert out[-1].triangles == want[-1].triangles


def test_driver_count_based_partial_window_guard():
    # ADVICE r1: a chunked count-based feed whose chunk is not an
    # edge_bucket multiple closes a short window and would silently
    # shift every later boundary — the driver must refuse more input
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=8,
                                   analytics=("degrees",))
    src = np.arange(12) % 5
    drv.run_arrays(src, (src + 1) % 5)  # closes an 8 + partial-4 window
    with pytest.raises(ValueError, match="partial window"):
        drv.run_arrays(src[:8], src[:8])
    drv.reset()
    drv.run_arrays(src[:8], (src[:8] + 1) % 5)  # multiples stay fine
    drv.run_arrays(src[:8], (src[:8] + 1) % 5)


def test_partial_window_flag_not_persisted_before_final_window(tmp_path):
    """A mid-call checkpoint taken BEFORE the call's short final window
    must not record closed_partial: a crash between that checkpoint and
    the short window would otherwise leave a state that refuses an
    exact replay of the remaining edges (code-review r2 finding)."""
    ckpt = str(tmp_path / "ck.npz")
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=8,
                                   analytics=("degrees",))
    drv.enable_auto_checkpoint(ckpt, every_n_windows=1)
    src = np.arange(20) % 5  # 2 full windows + partial 4-edge window
    drv.run_arrays(src, (src + 1) % 5)
    assert drv._closed_partial  # live driver did close the short window

    # "crash" after window 2's checkpoint: simulate by resuming a
    # checkpoint cut at windows_done=2 (the every-window cadence means
    # the final checkpoint has 3 windows; rebuild the 2-window one)
    fresh = StreamingAnalyticsDriver(window_ms=0, edge_bucket=8,
                                     analytics=("degrees",))
    fresh.enable_auto_checkpoint(ckpt, every_n_windows=1)
    fresh.run_arrays(src[:16], (src[:16] + 1) % 5)  # exactly 2 windows
    resumed = StreamingAnalyticsDriver(window_ms=0, edge_bucket=8,
                                       analytics=("degrees",))
    assert resumed.try_resume(ckpt)
    assert not resumed._closed_partial
    # replaying the remaining edges must succeed and close the stream
    out = resumed.run_arrays(src[16:], (src[16:] + 1) % 5)
    assert len(out) == 1 and out[-1].num_edges == 4


def test_driver_reset_gives_clean_rerun():
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=8,
                                   analytics=("degrees", "cc"))
    src = np.arange(16) % 7
    dst = (src + 2) % 7
    first = drv.run_arrays(src, dst)
    drv.reset()
    assert drv.windows_done == 0 and drv.edges_done == 0
    again = drv.run_arrays(src, dst)
    np.testing.assert_array_equal(first[-1].degrees, again[-1].degrees)
    np.testing.assert_array_equal(first[-1].cc_labels, again[-1].cc_labels)


def test_driver_checkpoint_carries_vertex_bucket(tmp_path):
    # ADVICE r1: resume must adopt the checkpointed vertex bucket up
    # front instead of dying deep in the engine with a mismatch error
    p = str(tmp_path / "ck.npz")
    a = StreamingAnalyticsDriver(window_ms=0, vertex_bucket=16,
                                 edge_bucket=8, analytics=("degrees",))
    src = np.arange(64) % 40  # grows the vertex bucket past 16
    a.run_arrays(src, (src + 3) % 40)
    import gelly_streaming_tpu.utils.checkpoint as ckpt
    ckpt.save(p, a.state_dict())
    b = StreamingAnalyticsDriver(window_ms=0, vertex_bucket=1 << 12,
                                 edge_bucket=8, analytics=("degrees",))
    assert b.try_resume(p)
    # single-chip keeps the LARGER pre-sized constructor bucket (so a
    # caller who pre-sized to avoid bucket-doubling recompiles doesn't
    # get them back after resume); a smaller constructor adopts the
    # checkpoint's grown bucket (code-review r2 finding)
    assert b.vb == 1 << 12
    c = StreamingAnalyticsDriver(window_ms=0, vertex_bucket=16,
                                 edge_bucket=8, analytics=("degrees",))
    assert c.try_resume(p)
    assert c.vb == a.vb
    ra = a.run_arrays(src[:8], (src[:8] + 3) % 40)
    rb = b.run_arrays(src[:8], (src[:8] + 3) % 40)
    rc = c.run_arrays(src[:8], (src[:8] + 3) % 40)
    np.testing.assert_array_equal(ra[-1].degrees, rb[-1].degrees)
    np.testing.assert_array_equal(ra[-1].degrees, rc[-1].degrees)


@pytest.mark.parametrize("sharded", [False, True])
def test_batched_scan_path_matches_per_window_path(sharded):
    """The batched snapshot-scan fast path (one dispatch per call,
    single-chip jit or shard_map over the mesh) must produce
    bit-identical per-window snapshots to the per-window path
    (one-window calls), including mid-call vertex growth, for both
    count-based and event-time windows."""
    mesh = make_mesh() if sharded else None
    rng = np.random.default_rng(17)
    n, eb = 1024, 128
    # growing vertex domain forces bucket doubling inside the call
    src = np.concatenate([rng.integers(0, 40, n // 2),
                          rng.integers(0, 900, n // 2)])
    dst = np.concatenate([rng.integers(0, 40, n // 2),
                          rng.integers(0, 900, n // 2)])
    ts = (np.arange(n) // eb) * 1000  # event-time: eb edges per window

    for mode in ("count", "event"):
        a = StreamingAnalyticsDriver(window_ms=1000, edge_bucket=eb,
                                     vertex_bucket=16, mesh=mesh)
        b = StreamingAnalyticsDriver(window_ms=1000, edge_bucket=eb,
                                     vertex_bucket=16, mesh=mesh)
        if mode == "count":
            batched = a.run_arrays(src, dst)
            single = []
            for i in range(0, n, eb):
                single += b.run_arrays(src[i:i + eb], dst[i:i + eb])
        else:
            batched = a.run_arrays(src, dst, ts)
            single = []
            for i in range(0, n, eb):
                single += b.run_arrays(src[i:i + eb], dst[i:i + eb],
                                       ts[i:i + eb])
        assert len(batched) == len(single) == n // eb
        for x, y in zip(batched, single):
            assert x.window_start == y.window_start
            assert x.num_edges == y.num_edges
            np.testing.assert_array_equal(x.vertex_ids, y.vertex_ids)
            np.testing.assert_array_equal(x.degrees, y.degrees)
            np.testing.assert_array_equal(x.cc_labels, y.cc_labels)
            np.testing.assert_array_equal(x.bipartite_odd,
                                          y.bipartite_odd)
            assert x.triangles == y.triangles
        # carried mirrors end identical: further feeding agrees too
        extra_s = rng.integers(0, 900, eb)
        extra_d = rng.integers(0, 900, eb)
        ra = a.run_arrays(extra_s, extra_d)[-1]
        rb = b.run_arrays(extra_s, extra_d)[-1]
        np.testing.assert_array_equal(ra.degrees, rb.degrees)
        np.testing.assert_array_equal(ra.cc_labels, rb.cc_labels)
        np.testing.assert_array_equal(ra.bipartite_odd, rb.bipartite_odd)


def test_stream_file_multi_crash_resume_fuzz(tmp_path):
    """Repeated random crashes + resumes over one event-time file must
    end in EXACTLY the uninterrupted run's carried state, regardless of
    chunk sizes, checkpoint cadences, and kill points (the reference
    delegates this whole axis to Flink; SURVEY.md §5.3-5.4)."""
    for seed in (5, 17):
        rng = np.random.default_rng(seed)
        n = 1200
        src = rng.integers(0, 120, n)
        dst = rng.integers(0, 120, n)
        ts = np.sort(rng.integers(0, 4000, n))
        p = tmp_path / f"fuzz{seed}.txt"
        p.write_text("".join(f"{s} {d} {t}\n"
                             for s, d, t in zip(src, dst, ts)))
        ck = str(tmp_path / f"fuzz{seed}.ckpt")

        ref = StreamingAnalyticsDriver(window_ms=400)
        ref.run_file(str(p))
        want = ref.state_dict()

        first = True
        for attempt in range(50):
            d = StreamingAnalyticsDriver(window_ms=400)
            resumed = (not first) and d.try_resume(ck)
            d.enable_auto_checkpoint(
                ck, every_n_windows=int(rng.integers(1, 4)))
            kill_after = int(rng.integers(1, 5))
            finished = True
            for i, _res in enumerate(d.stream_file(
                    str(p), chunk_bytes=int(rng.integers(256, 4096)),
                    resume=resumed)):
                if i + 1 >= kill_after and rng.random() < 0.6:
                    finished = False
                    break
            first = False
            if finished:
                break
        assert finished, "fuzz never completed the stream in 50 attempts"

        got = d.state_dict()
        assert got["windows_done"] == want["windows_done"]
        assert got["edges_done"] == want["edges_done"]
        for key in ("vertex_ids", "degrees", "cc", "bip"):
            np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def test_try_resume_corrupt_checkpoint_starts_fresh(tmp_path):
    """A truncated/corrupt checkpoint file (external damage — save()
    itself is atomic) must behave like a missing one: warn, return
    False, full reprocess stays correct. Semantic mismatches (e.g.
    cross-mode) still raise — covered by
    test_driver_cross_mode_checkpoint_refused."""
    import warnings

    from gelly_streaming_tpu.utils import checkpoint

    d = StreamingAnalyticsDriver(window_ms=100)
    d.run_arrays(np.array([1, 2, 3]), np.array([2, 3, 4]))
    ck = str(tmp_path / "c.ckpt")
    checkpoint.save(ck, d.state_dict())
    raw = open(ck, "rb").read()
    open(ck, "wb").write(raw[:len(raw) // 2])

    e = StreamingAnalyticsDriver(window_ms=100)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert e.try_resume(ck) is False
    assert any("corrupt" in str(w.message) for w in caught)
    assert e.windows_done == 0  # clean fresh state

    # bit-flip INSIDE the compressed payload (valid zip structure,
    # mangled deflate stream -> zlib.error, a different failure shape
    # than truncation's BadZipFile)
    ck2 = str(tmp_path / "c2.ckpt")
    checkpoint.save(ck2, d.state_dict())
    raw2 = bytearray(open(ck2, "rb").read())
    mid = len(raw2) // 2
    raw2[mid] ^= 0xFF
    raw2[mid + 1] ^= 0xFF
    open(ck2, "wb").write(bytes(raw2))
    f = StreamingAnalyticsDriver(window_ms=100)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert f.try_resume(ck2) is False


def test_stream_file_tolerates_malformed_lines(tmp_path):
    """The ingest parser drops malformed lines (native and Python
    fallbacks agree — tests/test_native.py pins that); the driver sees
    only the valid records, and an all-garbage file behaves like an
    empty one."""
    g = tmp_path / "garbage.txt"
    g.write_text("hello world\nfoo bar baz\n# comment\n")
    d = StreamingAnalyticsDriver(window_ms=100)
    assert list(d.stream_file(str(g))) == []
    assert d.windows_done == 0

    m = tmp_path / "mixed.txt"
    m.write_text("x\n1 2 100\nbad line\n3 4 200\n")
    e = StreamingAnalyticsDriver(window_ms=100)
    res = list(e.stream_file(str(m)))
    assert [(r.window_start, int(r.degrees.sum())) for r in res] == \
        [(100, 2), (200, 4)]

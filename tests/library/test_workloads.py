"""Tests for the workloads the reference ships without tests
(SURVEY.md §4 'Gap to note'): weighted matching, iterative CC, and the
two sampling estimators.
"""

import numpy as np
import pytest

from gelly_streaming_tpu import Edge, NULL, StreamEnvironment
from gelly_streaming_tpu.models.iterative_cc import (
    TpuIterativeConnectedComponents, iterative_connected_components)
from gelly_streaming_tpu.models.matching import centralized_weighted_matching
from gelly_streaming_tpu.models.sampling_triangles import (
    broadcast_triangle_count, incidence_sampling_triangle_count)
from gelly_streaming_tpu.utils.events import MatchingEventType


def test_weighted_matching_greedy_semantics(env):
    edges = [
        Edge(1, 2, 30),   # ADD (empty matching)
        Edge(2, 3, 40),   # collides with (1,2): 40 ≤ 2*30 → rejected
        Edge(3, 4, 200),  # no collision → ADD
        Edge(1, 2, 500),  # collides with (1,2,30): 500 > 60 → REMOVE+ADD
    ]
    sink = centralized_weighted_matching(env.from_collection(edges)).collect()
    env.execute()
    events = env.results_of(sink)
    kinds = [(e.type, e.edge.value) for e in events]
    assert kinds == [
        (MatchingEventType.ADD, 30),
        (MatchingEventType.ADD, 200),
        (MatchingEventType.REMOVE, 30),
        (MatchingEventType.ADD, 500),
    ]


def test_iterative_cc_feedback(env):
    edges = [(1, 2), (3, 4), (2, 3), (6, 7)]
    result = iterative_connected_components(env.from_collection(edges))
    sink = result.collect()
    env.execute()
    updates = env.results_of(sink)
    # final label per vertex = last update wins
    final = {}
    for v, c in updates:
        final[v] = c
    assert final == {1: 1, 2: 1, 3: 1, 4: 1, 6: 6, 7: 6}


def test_iterative_cc_tpu_carried_state():
    model = TpuIterativeConnectedComponents()
    first = model.process_batch(np.array([1, 3]), np.array([2, 4]))
    assert dict(first) == {1: 1, 2: 1, 3: 3, 4: 3}
    # bridging edge merges the carried components; vertices already
    # labeled 1 (here: 1 and 2) are unchanged and not re-emitted
    second = model.process_batch(np.array([2]), np.array([3]))
    assert dict(second) == {3: 1, 4: 1}


def _triangle_rich_graph(n=12):
    """Clique on n vertices: C(n,3) triangles, dense signal for samplers."""
    edges = []
    for i in range(n):
        for j in range(i + 1, n):
            edges.append(Edge(i, j, NULL))
    return edges, n


@pytest.mark.parametrize("pipeline", [broadcast_triangle_count,
                                      incidence_sampling_triangle_count])
def test_sampling_estimators_converge(env, pipeline):
    edges, n = _triangle_rich_graph()
    true_triangles = n * (n - 1) * (n - 2) // 6
    sink = pipeline(env.from_collection(edges * 4), 600, n).collect()
    env.execute()
    estimates = env.results_of(sink)
    assert estimates, "estimator emitted nothing"
    final = estimates[-1][1]
    # randomized estimate: just require the right order of magnitude
    assert 0 < final < true_triangles * 50


def test_sampling_estimator_deterministic():
    edges, n = _triangle_rich_graph()

    def run():
        env = StreamEnvironment()
        sink = broadcast_triangle_count(
            env.from_collection(edges * 2), 200, n
        ).collect()
        env.execute()
        return env.results_of(sink)

    assert run() == run()


@pytest.mark.parametrize("seed", range(5))
def test_weighted_matching_invariants_random(env, seed):
    """Random streams: the surviving ADD-set must be a valid matching
    (no shared endpoints) whose weight is >= 1/6 of the brute-force
    optimum — the guarantee of the 2x-threshold preemptive greedy the
    reference implements (keep iff weight > 2 * sum of colliding
    matched weights, CentralizedWeightedMatching.java:68-108; the
    folklore 1/2 bound belongs to a different greedy — e.g. stream
    [(0,1,10), (2,0,19), (1,3,19)] keeps only weight 10 vs optimum
    38)."""
    rng = np.random.default_rng(seed)
    v = 8
    edges = []
    for _ in range(25):
        a, b = rng.choice(v, size=2, replace=False)
        edges.append(Edge(int(a), int(b), int(rng.integers(1, 100))))

    sink = centralized_weighted_matching(env.from_collection(edges)).collect()
    env.execute()
    matched = {}
    for ev in env.results_of(sink):
        key = (ev.edge.source, ev.edge.target)
        if ev.type == MatchingEventType.ADD:
            matched[key] = ev.edge.value
        else:
            # a REMOVE for a never-ADDed edge is a protocol bug
            matched.pop(key)
    # validity: no vertex in two matched edges
    used = [x for (s, t) in matched for x in (s, t)]
    assert len(used) == len(set(used)), matched
    got = sum(matched.values())

    # brute-force optimum over all subsets of distinct edges (dedupe
    # parallel edges keeping max weight; 25 edges over 8 vertices ->
    # <= 28 distinct pairs, optimum found over vertex-disjoint subsets
    # via simple DP on bitmask of used vertices)
    best_w = {}
    for e in edges:
        k = tuple(sorted((e.source, e.target)))
        best_w[k] = max(best_w.get(k, 0), e.value)
    items = [(1 << a | 1 << b, w) for (a, b), w in best_w.items()]
    best = {0: 0}
    for mask, w in items:
        for used_mask, tot in list(best.items()):
            if not (used_mask & mask):
                nm = used_mask | mask
                if best.get(nm, -1) < tot + w:
                    best[nm] = tot + w
    opt = max(best.values())
    assert 6 * got >= opt, (got, opt)


def test_weighted_matching_counterexample_to_half(env):
    """The concrete stream showing the 2x-threshold preemptive greedy
    is NOT a 1/2-approximation: both weight-19 rivals fail the >2x test against the
    kept weight-10 edge, so the final matching is 10 vs optimum 38 —
    below 1/2, above 1/6."""
    edges = [Edge(0, 1, 10), Edge(2, 0, 19), Edge(1, 3, 19)]
    sink = centralized_weighted_matching(env.from_collection(edges)).collect()
    env.execute()
    matched = {}
    for ev in env.results_of(sink):
        key = (ev.edge.source, ev.edge.target)
        if ev.type == MatchingEventType.ADD:
            matched[key] = ev.edge.value
        else:
            matched.pop(key)
    got, opt = sum(matched.values()), 38
    assert matched == {(0, 1): 10}
    assert 2 * got < opt        # refutes the 1/2 claim
    assert 6 * got >= opt       # within the real 1/6 bound

"""Tests for the workloads the reference ships without tests
(SURVEY.md §4 'Gap to note'): weighted matching, iterative CC, and the
two sampling estimators.
"""

import numpy as np
import pytest

from gelly_streaming_tpu import Edge, NULL, StreamEnvironment
from gelly_streaming_tpu.models.iterative_cc import (
    TpuIterativeConnectedComponents, iterative_connected_components)
from gelly_streaming_tpu.models.matching import centralized_weighted_matching
from gelly_streaming_tpu.models.sampling_triangles import (
    broadcast_triangle_count, incidence_sampling_triangle_count)
from gelly_streaming_tpu.utils.events import MatchingEventType


def test_weighted_matching_greedy_semantics(env):
    edges = [
        Edge(1, 2, 30),   # ADD (empty matching)
        Edge(2, 3, 40),   # collides with (1,2): 40 ≤ 2*30 → rejected
        Edge(3, 4, 200),  # no collision → ADD
        Edge(1, 2, 500),  # collides with (1,2,30): 500 > 60 → REMOVE+ADD
    ]
    sink = centralized_weighted_matching(env.from_collection(edges)).collect()
    env.execute()
    events = env.results_of(sink)
    kinds = [(e.type, e.edge.value) for e in events]
    assert kinds == [
        (MatchingEventType.ADD, 30),
        (MatchingEventType.ADD, 200),
        (MatchingEventType.REMOVE, 30),
        (MatchingEventType.ADD, 500),
    ]


def test_iterative_cc_feedback(env):
    edges = [(1, 2), (3, 4), (2, 3), (6, 7)]
    result = iterative_connected_components(env.from_collection(edges))
    sink = result.collect()
    env.execute()
    updates = env.results_of(sink)
    # final label per vertex = last update wins
    final = {}
    for v, c in updates:
        final[v] = c
    assert final == {1: 1, 2: 1, 3: 1, 4: 1, 6: 6, 7: 6}


def test_iterative_cc_tpu_carried_state():
    model = TpuIterativeConnectedComponents()
    first = model.process_batch(np.array([1, 3]), np.array([2, 4]))
    assert dict(first) == {1: 1, 2: 1, 3: 3, 4: 3}
    # bridging edge merges the carried components; vertices already
    # labeled 1 (here: 1 and 2) are unchanged and not re-emitted
    second = model.process_batch(np.array([2]), np.array([3]))
    assert dict(second) == {3: 1, 4: 1}


def _triangle_rich_graph(n=12):
    """Clique on n vertices: C(n,3) triangles, dense signal for samplers."""
    edges = []
    for i in range(n):
        for j in range(i + 1, n):
            edges.append(Edge(i, j, NULL))
    return edges, n


@pytest.mark.parametrize("pipeline", [broadcast_triangle_count,
                                      incidence_sampling_triangle_count])
def test_sampling_estimators_converge(env, pipeline):
    edges, n = _triangle_rich_graph()
    true_triangles = n * (n - 1) * (n - 2) // 6
    sink = pipeline(env.from_collection(edges * 4), 600, n).collect()
    env.execute()
    estimates = env.results_of(sink)
    assert estimates, "estimator emitted nothing"
    final = estimates[-1][1]
    # randomized estimate: just require the right order of magnitude
    assert 0 < final < true_triangles * 50


def test_sampling_estimator_deterministic():
    edges, n = _triangle_rich_graph()

    def run():
        env = StreamEnvironment()
        sink = broadcast_triangle_count(
            env.from_collection(edges * 2), 200, n
        ).collect()
        env.execute()
        return env.results_of(sink)

    assert run() == run()

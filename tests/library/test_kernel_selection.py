"""The measurement-driven kernel selections, tested end-to-end on
synthetic PERF.json files (VERDICT r2 item 5: the selection framework
must itself be under test so a committed chip profile provably flips
the defaults).

Covers the three selectors in ops/triangles.py:
  - resolve_intersect_impl (Pallas fused-tile vs XLA winner)
  - _resolve_dense_choice (Pallas fused contraction vs XLA matmul)
  - _tuned_kb (k-sweep-driven starting K per edge bucket)
and the backend-matching guards of _load_matching_perf (a cpu-labeled
file must never drive a chip selection and vice versa).
"""

import json

import jax
import pytest

from gelly_streaming_tpu.ops import triangles
from gelly_streaming_tpu.ops.pallas_intersect import intersect_local_pallas
from gelly_streaming_tpu.ops.triangles import DENSE_LIMIT


@pytest.fixture
def selection_env(tmp_path, monkeypatch):
    """Redirect the selectors at a writable PERF.json, reset their
    once-per-process caches, and let the test pick the apparent
    backend. Restores everything afterwards."""
    perf_path = tmp_path / "PERF.json"
    monkeypatch.setattr(triangles, "_PERF_PATH", str(perf_path))
    monkeypatch.setattr(triangles, "_INTERSECT_CHOICE", None)
    monkeypatch.setattr(triangles, "_INTERSECT_JIT", None)
    monkeypatch.setattr(triangles, "_DENSE_CHOICE", None)
    monkeypatch.setattr(triangles, "_TUNED_KB", {})
    monkeypatch.setattr(triangles, "_TUNED_CHUNK", {})
    monkeypatch.setattr(triangles, "_STREAM_IMPL", None)
    monkeypatch.setattr(triangles, "_STREAM_IMPL_EB", {})
    monkeypatch.setattr(triangles, "_INGRESS", None)
    monkeypatch.setattr(triangles, "_COMPILE_CAPS", {})

    def configure(file_backend, process_backend, **sections):
        perf_path.write_text(
            json.dumps(dict({"backend": file_backend}, **sections)))
        monkeypatch.setattr(jax, "default_backend",
                            lambda: process_backend)

    return configure


INTERSECT_WIN = {"parity_pallas": True, "pallas_vs_xla_compare": 1.20}
DENSE_WIN = [{"num_vertices": 1024, "pallas_speedup": 1.10},
             {"num_vertices": 2048, "pallas_speedup": 1.07}]


def test_intersect_flips_to_pallas_on_winning_chip_rows(selection_env):
    selection_env("tpu", "tpu", intersect=INTERSECT_WIN)
    assert triangles.resolve_intersect_impl() is intersect_local_pallas


@pytest.mark.parametrize("row", [
    {"parity_pallas": True, "pallas_vs_xla_compare": 1.02},  # < 5% win
    {"parity_pallas": False, "pallas_vs_xla_compare": 9.9},  # no parity
    {},                                                      # no data
])
def test_intersect_keeps_xla_compare_without_a_clean_win(
        selection_env, row):
    selection_env("tpu", "tpu", intersect=row)
    assert triangles.resolve_intersect_impl() is triangles.intersect_local


def test_intersect_ignores_cpu_labeled_file_on_chip(selection_env):
    # the same winning rows, recorded on the wrong backend: no flip
    selection_env("cpu", "tpu", intersect=INTERSECT_WIN)
    assert triangles.resolve_intersect_impl() is triangles.intersect_local


def test_intersect_on_cpu_stays_bsearch_despite_chip_rows(selection_env):
    # chip-only selection: a cpu process keeps its measured XLA winner
    selection_env("tpu", "cpu", intersect=INTERSECT_WIN)
    assert (triangles.resolve_intersect_impl()
            is triangles.intersect_local_bsearch)


INGRESS_WIN = [{"probe": "stream_ab", "parity": True, "speedup": 1.31}]


def test_ingress_flips_to_compact_on_winning_rows(selection_env):
    selection_env("tpu", "tpu", ingress_ab=INGRESS_WIN)
    assert triangles.resolve_ingress(65536) == "compact"


@pytest.mark.parametrize("rows", [
    [{"parity": True, "speedup": 1.02}],   # < 5% win
    [{"parity": False, "speedup": 9.9}],   # no parity
    [],                                    # no data
    [{"parity": True, "speedup": 1.31},
     {"parity": True, "speedup": 0.98}],   # must win at EVERY row
])
def test_ingress_stays_standard_without_a_clean_win(selection_env, rows):
    selection_env("tpu", "tpu", ingress_ab=rows)
    assert triangles.resolve_ingress(65536) == "standard"


def test_ingress_vb_gate_overrides_winning_rows(selection_env):
    # ids wider than uint16: the format is lossy there, never selected
    selection_env("tpu", "tpu", ingress_ab=INGRESS_WIN)
    assert triangles.resolve_ingress(1 << 17) == "standard"
    # the memoized win still applies to buckets that DO fit
    assert triangles.resolve_ingress(32768) == "compact"


def test_ingress_ignores_other_backend_rows(selection_env):
    selection_env("cpu", "tpu", ingress_ab=INGRESS_WIN)
    assert triangles.resolve_ingress(65536) == "standard"


def test_compile_cap_raised_by_clean_probe_row(selection_env):
    selection_env("tpu", "tpu", compile_probe=[
        {"program": "triangle_stream", "slots": 1 << 20, "ok": True,
         "compile_s": 41.0}])
    assert triangles.compile_cap("triangle_stream") == 1 << 20
    # ...and the chunk selector sees it: 2^20 / 32768 = 32 windows
    assert triangles._default_chunk(32768) == 32


FUSED_WEDGE_ROWS = [
    {"program": "fused_scan", "slots": 1 << 19, "ok": False,
     "reason": "timeout"},
    {"program": "fused_scan", "slots": 1 << 17, "ok": True,
     "compile_s": 30.0},
]


def test_compile_cap_lowered_by_probed_failure(selection_env):
    selection_env("tpu", "tpu", compile_probe_scan=FUSED_WEDGE_ROWS)
    assert triangles.compile_cap("fused_scan") == 1 << 17
    # no clean row below the failure: quarter of the failing size
    triangles._reset_compile_caps()
    selection_env("tpu", "tpu", compile_probe_scan=[
        {"program": "snapshot_scan", "slots": 1 << 18, "ok": False,
         "reason": "timeout"}])
    assert triangles.compile_cap("snapshot_scan") == 1 << 16


def test_compile_cap_failure_above_proven_size_keeps_the_default(
        selection_env):
    # a 2^20 triangle wedge must not drag the cap below 2^19 — that
    # size compiled clean in the round-4 chip window (the quarter
    # fallback applies only to programs with NO proven size)
    selection_env("tpu", "tpu", compile_probe=[
        {"program": "triangle_stream", "slots": 1 << 20, "ok": False,
         "reason": "timeout"}])
    assert triangles.compile_cap("triangle_stream") == 1 << 19


def test_compile_cap_ignores_inconclusive_rows(selection_env):
    # ok=None (crash / tunnel flake, not a timed-out compile) moves
    # nothing in either direction
    selection_env("tpu", "tpu", compile_probe_scan=[
        {"program": "fused_scan", "slots": 1 << 17, "ok": None,
         "reason": "backend cpu"}])
    assert triangles.compile_cap("fused_scan") == 1 << 19


def test_compile_cap_ignores_other_backend_and_programs(selection_env):
    selection_env("cpu", "tpu", compile_probe=[
        {"program": "triangle_stream", "slots": 1 << 20, "ok": True}])
    assert triangles.compile_cap("triangle_stream") == 1 << 19
    triangles._reset_compile_caps()
    selection_env("tpu", "tpu", compile_probe=[
        {"program": "triangle_stream", "slots": 1 << 20, "ok": True}])
    # another program's rows never move this program's cap
    assert triangles.compile_cap("fused_scan") == 1 << 19


def test_fused_engine_honors_lowered_cap(selection_env):
    # a probed fused-scan wedge at 2^19 with a clean 2^17 row must
    # shrink the engine's windows-per-dispatch on a chip backend
    # (2^17 / eb=8192 -> 16), while the triangle kernel keeps ITS cap
    selection_env("tpu", "tpu", compile_probe_scan=FUSED_WEDGE_ROWS)
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine

    eng = StreamSummaryEngine(edge_bucket=8192, vertex_bucket=16384)
    assert eng.MAX_WINDOWS == 16
    assert triangles._default_chunk(8192) == 64  # 2^19 / 8192


def test_capped_chunk_unlimited_off_chip(selection_env):
    selection_env("cpu", "cpu", compile_probe_scan=[
        {"program": "fused_scan", "slots": 1 << 17, "ok": False}])
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    assert (triangles.capped_chunk(32768, "fused_scan")
            == TriangleWindowKernel.MAX_STREAM_WINDOWS)


def test_dense_flips_to_pallas_and_doubles_limit(selection_env):
    selection_env("tpu", "tpu", dense=DENSE_WIN)
    assert triangles._resolve_dense_choice() == ("pallas", 2 * DENSE_LIMIT)


def test_dense_requires_a_win_at_every_measured_v(selection_env):
    selection_env("tpu", "tpu", dense=DENSE_WIN + [
        {"num_vertices": 4096, "pallas_speedup": 1.01}])
    assert triangles._resolve_dense_choice() == ("xla", DENSE_LIMIT)


def test_dense_ignores_error_stub_sections(selection_env):
    # a failed profiler section records {"error": ...}; consumers must
    # see no rows, not crash or select on garbage
    selection_env("tpu", "tpu", dense={"error": "timeout"})
    assert triangles._resolve_dense_choice() == ("xla", DENSE_LIMIT)


def test_tuned_kb_picks_fastest_measured_row(selection_env):
    """The fastest measured row wins OUTRIGHT — per_window_ms was
    measured on a run that already paid that K's overflow recounts, so
    an occasionally-overflowing K that wins net is taken (the CPU
    sweep's eb=32768 K=32 case), while a K whose recounts make it slow
    loses on its own measurement."""
    selection_env("cpu", "cpu", window=[{
        "edge_bucket": 8192,
        "k_sweep": [
            {"k_bucket": 32, "per_window_ms": 3.0,
             "overflow_recounts_per_run": 0},
            {"k_bucket": 64, "per_window_ms": 5.0,
             "overflow_recounts_per_run": 0},
            # fastest row WITH its recount cost priced in: wins
            {"k_bucket": 16, "per_window_ms": 1.0,
             "overflow_recounts_per_run": 2},
        ]}])
    assert triangles._tuned_kb(8192) == 16


def test_tuned_kb_recount_heavy_row_loses_on_its_own_measurement(
        selection_env):
    selection_env("cpu", "cpu", window=[{
        "edge_bucket": 8192,
        "k_sweep": [
            # every window recounted: the measurement itself is slow
            {"k_bucket": 16, "per_window_ms": 50.0,
             "overflow_recounts_per_run": 64},
            {"k_bucket": 64, "per_window_ms": 5.0,
             "overflow_recounts_per_run": 0},
        ]}])
    assert triangles._tuned_kb(8192) == 64


def test_tuned_kb_falls_back_to_analytic_on_backend_mismatch(
        selection_env):
    selection_env("tpu", "cpu", window=[{
        "edge_bucket": 8192,
        "k_sweep": [{"k_bucket": 32, "per_window_ms": 3.0,
                     "overflow_recounts_per_run": 0}]}])
    assert triangles._tuned_kb(8192) == min(128, 2 * int(8192 ** 0.5))


def test_tuned_chunk_reads_matching_backend_sweep(selection_env):
    selection_env("cpu", "cpu", window=[{
        "edge_bucket": 8192,
        "chunk_sweep": [
            {"windows_per_dispatch": 32, "per_window_ms": 9.0},
            {"windows_per_dispatch": 128, "per_window_ms": 7.5},
            {"windows_per_dispatch": 64, "per_window_ms": 8.0},
        ]}])
    assert triangles._tuned_chunk(8192) == 128
    # unmeasured bucket: class default
    assert (triangles._tuned_chunk(4096)
            == triangles.TriangleWindowKernel.MAX_STREAM_WINDOWS)


def test_tuned_chunk_merges_chunk_deep_rows(selection_env):
    """chunk_deep rows (the in-window post-probe deep sweep,
    tools/profile_kernels.section_chunk_deep) extend the window
    section's sweep: the fastest row across BOTH sections wins."""
    cap_raise = [{"program": "triangle_stream", "slots": 1 << 20,
                  "ok": True, "compile_s": 40.0}]
    selection_env("tpu", "tpu", window=[{
        "edge_bucket": 32768,
        "chunk_sweep": [
            {"windows_per_dispatch": 8, "per_window_ms": 9.0},
            {"windows_per_dispatch": 16, "per_window_ms": 7.5},
        ]}], chunk_deep=[{
            "edge_bucket": 32768,
            "chunk_sweep": [
                {"windows_per_dispatch": 32, "per_window_ms": 6.1},
            ]}], compile_probe=cap_raise)
    assert triangles._tuned_chunk(32768) == 32
    # a SLOWER deep row must not displace the window section's winner
    triangles._TUNED_CHUNK.clear()
    selection_env("tpu", "tpu", window=[{
        "edge_bucket": 32768,
        "chunk_sweep": [
            {"windows_per_dispatch": 16, "per_window_ms": 7.5}]}],
        chunk_deep=[{
            "edge_bucket": 32768,
            "chunk_sweep": [
                {"windows_per_dispatch": 32, "per_window_ms": 8.8}]}])
    assert triangles._tuned_chunk(32768) == 16


def test_tuned_chunk_clamped_to_current_cap_on_chip(selection_env):
    """A persisted deep-sweep depth measured under a since-lowered cap
    must not drive a dispatch above the CURRENT cap (it would
    recompile the exact oversized program the cap exists to prevent)."""
    selection_env("tpu", "tpu", chunk_deep=[{
        "edge_bucket": 32768,
        "chunk_sweep": [{"windows_per_dispatch": 32,
                         "per_window_ms": 6.0}]}],
        compile_probe=[{"program": "triangle_stream", "slots": 1 << 18,
                        "ok": False, "reason": "timeout"}])
    # cap fell to 2^16 (failure/4, no clean rows): 2^16/32768 = 2
    assert triangles.compile_cap("triangle_stream") == 1 << 16
    assert triangles._tuned_chunk(32768) == 2


def test_compile_cap_contradiction_trusts_clean_row_above_failure(
        selection_env):
    """A clean probe row LARGER than a failure is contradictory
    evidence; the measured success wins (a compile that finished is
    direct proof of the shape, a timeout can be a tunnel flake) —
    ADVICE r4: the cap must not drop below a proven-clean size."""
    selection_env("tpu", "tpu", compile_probe=[
        {"program": "triangle_stream", "slots": 1 << 20, "ok": True,
         "compile_s": 44.0},
        {"program": "triangle_stream", "slots": 1 << 19, "ok": False,
         "reason": "timeout"}])
    assert triangles.compile_cap("triangle_stream") == 1 << 20


def test_rows_clear_bar_rejects_malformed_rows():
    """parity True with a missing/zero rate on either side must FAIL
    the gate, not pass vacuously (ADVICE r4: 0 >= margin*0)."""
    bar = triangles.rows_clear_bar
    assert bar([{"parity": True, "a": 110, "b": 100}], "a", "b")
    assert not bar([{"parity": True}], "a", "b")            # no rates
    assert not bar([{"parity": True, "a": 110}], "a", "b")  # no denom
    assert not bar([{"parity": True, "b": 100}], "a", "b")  # no numer
    assert not bar([{"parity": True, "a": 0, "b": 0}], "a", "b")
    # callable denominators get the same guard
    assert not bar([{"parity": True, "a": 110}], "a", lambda r: 0.0)
    assert bar([{"parity": True, "a": 110}], "a", lambda r: 100.0)


def test_tuned_chunk_backend_mismatch_keeps_default(selection_env):
    selection_env("tpu", "cpu", window=[{
        "edge_bucket": 8192,
        "chunk_sweep": [{"windows_per_dispatch": 128,
                         "per_window_ms": 1.0}]}])
    assert (triangles._tuned_chunk(8192)
            == triangles.TriangleWindowKernel.MAX_STREAM_WINDOWS)


def test_sweep_rows_missing_value_key_are_skipped(selection_env):
    """A malformed/hand-edited PERF.json row with per_window_ms but a
    missing or zero value key must not crash the selector or select a
    degenerate K/chunk (ADVICE r3): such rows are skipped, and the
    surviving fastest row is clamped to a positive int."""
    selection_env("cpu", "cpu", window=[{
        "edge_bucket": 8192,
        "k_sweep": [
            {"per_window_ms": 0.5},                       # no k_bucket
            {"k_bucket": 0, "per_window_ms": 0.7},        # zero
            {"k_bucket": None, "per_window_ms": 0.9},     # null
            {"k_bucket": 64, "per_window_ms": 5.0},
        ],
        "chunk_sweep": [
            {"per_window_ms": 0.1},                       # no value key
            {"windows_per_dispatch": 0, "per_window_ms": 0.2},
        ]}])
    assert triangles._tuned_kb(8192) == 64
    # every chunk row malformed -> the class default stands
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    assert (triangles._tuned_chunk(8192)
            == TriangleWindowKernel.MAX_STREAM_WINDOWS)


HOST_WIN = [{"edge_bucket": 8192, "parity": True,
             "host_edges_per_s": 2_000_000,
             "device_edges_per_s": 800_000},
            {"edge_bucket": 32768, "parity": True,
             "host_edges_per_s": 1_500_000,
             "device_edges_per_s": 900_000}]


def test_stream_impl_chip_routes_per_bucket(selection_env):
    """On a TPU backend the tier is per edge bucket: a bucket whose
    chip-labeled rows show the host tier winning (small windows,
    dispatch-latency-bound — VERDICT r4: 0.44× at 8192) routes to
    host, while a bucket with device-winning rows keeps the chip
    path. Unmeasured buckets default to device."""
    selection_env("tpu", "tpu", host_stream=[
        {"edge_bucket": 8192, "parity": True,
         "host_edges_per_s": 1_200_000, "device_edges_per_s": 500_000},
        {"edge_bucket": 32768, "parity": True,
         "host_edges_per_s": 400_000, "device_edges_per_s": 770_000},
    ])
    assert triangles._resolve_stream_impl(8192) == "host"
    assert triangles._resolve_stream_impl(32768) == "device"
    assert triangles._resolve_stream_impl(65536) == "device"  # no rows
    assert triangles._resolve_stream_impl(None) == "device"


def test_stream_impl_chip_ignores_cpu_rows(selection_env):
    # cpu-labeled wins must not route the chip path anywhere
    selection_env("cpu", "tpu", host_stream=[
        {"edge_bucket": 8192, "parity": True,
         "host_edges_per_s": 1_200_000,
         "device_edges_per_s": 500_000}])
    assert triangles._resolve_stream_impl(8192) == "device"


def test_stream_impl_flips_to_host_on_winning_cpu_rows(selection_env):
    selection_env("cpu", "cpu", host_stream=HOST_WIN)
    assert triangles._resolve_stream_impl() == "host"


def test_stream_impl_stays_device_on_chip(selection_env):
    # the host tier NEVER applies on a TPU backend, whatever the file
    selection_env("tpu", "tpu", host_stream=HOST_WIN)
    assert triangles._resolve_stream_impl() == "device"


@pytest.mark.parametrize("rows", [
    [],                                               # unmeasured
    [dict(HOST_WIN[0], parity=False)],                # parity failure
    [dict(HOST_WIN[0], host_edges_per_s=810_000)],    # < 5% win
    HOST_WIN + [dict(HOST_WIN[1], edge_bucket=65536,  # loses at one eb
                     host_edges_per_s=100_000)],
])
def test_stream_impl_needs_a_clean_win_everywhere(selection_env, rows):
    selection_env("cpu", "cpu", host_stream=rows)
    assert triangles._resolve_stream_impl() == "device"


def test_stream_impl_ignores_tpu_labeled_file_on_cpu(selection_env):
    selection_env("tpu", "cpu", host_stream=HOST_WIN)
    assert triangles._resolve_stream_impl() == "device"


NATIVE_WIN = [dict(r, native_parity=True,
                   native_edges_per_s=3 * r["host_edges_per_s"])
              for r in HOST_WIN]


def test_stream_impl_prefers_native_on_winning_rows(selection_env):
    """Committed rows where the C++ tier beats BOTH the numpy tier and
    the device kernel at every bucket flip the CPU fallback to it
    (requires the built library — present in this repo)."""
    from gelly_streaming_tpu import native

    assert native.triangles_available()
    selection_env("cpu", "cpu", host_stream=NATIVE_WIN)
    assert triangles._resolve_stream_impl() == "native"


@pytest.mark.parametrize("spoil", [
    dict(native_parity=False),               # parity failure
    dict(native_edges_per_s=0),              # missing measurement
    dict(native_edges_per_s=1_550_000),      # < 5% over the numpy tier
])
def test_stream_impl_native_needs_a_clean_win_everywhere(
        selection_env, spoil):
    rows = [NATIVE_WIN[0], dict(NATIVE_WIN[1], **spoil)]
    selection_env("cpu", "cpu", host_stream=rows)
    assert triangles._resolve_stream_impl() == "host"


def test_stream_impl_survives_other_backend_profile(
        selection_env, tmp_path):
    """A chip profile run takes over PERF.json; the CPU fallback's
    selections must keep reading this backend's committed rows from
    the PERF_cpu.json archive (VERDICT r4: the single-file design
    silently deselected the host tier the moment the chip was
    profiled)."""
    import json as _json

    selection_env("tpu", "cpu", window=[])  # PERF.json is chip-labeled
    (tmp_path / "PERF_cpu.json").write_text(_json.dumps(
        {"backend": "cpu", "host_stream": HOST_WIN}))
    assert triangles._resolve_stream_impl() == "host"


def test_winning_ingress_rows_flip_a_fresh_kernel(selection_env):
    """Integration: committed winning ingress_ab rows make a FRESH
    unpinned kernel dispatch compact, with counts identical to the
    standard form (the adoption path bench would take on chip)."""
    import numpy as np

    selection_env("cpu", "cpu", ingress_ab=INGRESS_WIN)
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    auto = TriangleWindowKernel(edge_bucket=128, vertex_bucket=256)
    assert auto.ingress == "compact"
    rng = np.random.default_rng(2)
    src = rng.integers(0, 256, 500).astype(np.int32)
    dst = rng.integers(0, 256, 500).astype(np.int32)
    std = TriangleWindowKernel(edge_bucket=128, vertex_bucket=256,
                               ingress="standard")
    assert (auto._count_stream_device(src, dst)
            == std._count_stream_device(src, dst))

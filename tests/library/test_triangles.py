"""Window triangle count parity tests.

Golden data and result from the reference
(ExamplesTestData.java:20-33: 19-edge timestamped graph, 400ms windows →
"(2,1199) (2,399) (3,799)"; asserted by WindowTrianglesITCase.java:42-44),
checked against BOTH the API-parity candidate pipeline and the fused
device kernel, plus randomized cross-checks of the two device kernels
against a brute-force count.
"""

import itertools

import numpy as np
import pytest

from gelly_streaming_tpu import StreamEnvironment, Time
from gelly_streaming_tpu.core.types import text_line
from gelly_streaming_tpu.models.triangles import WindowTriangleCount
from gelly_streaming_tpu.models.workloads import (timestamped_graph,
                                                  window_triangles_pipeline)
from gelly_streaming_tpu.ops import triangles as tri_ops

TRIANGLES_DATA = "\n".join([
    # reference: ExamplesTestData.java:22-29
    "1 2 100", "1 3 150", "3 2 200", "2 4 250", "3 4 300", "3 5 350",
    "4 5 400", "4 6 450", "6 5 500", "5 7 550", "6 7 600", "8 6 650",
    "7 8 700", "7 9 750", "8 9 800", "10 8 850", "9 10 900", "9 11 950",
    "10 11 1000",
])

EXPECTED = sorted(["(2,1199)", "(2,399)", "(3,799)"])


@pytest.fixture
def data_file(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text(TRIANGLES_DATA + "\n")
    return str(p)


def _run(pipeline_fn, data_file):
    env = StreamEnvironment()
    graph = timestamped_graph(env, data_file)
    sink = pipeline_fn(graph).collect()
    env.execute()
    return sorted(text_line(v) for v in env.results_of(sink))


def test_window_triangles_api_pipeline(data_file):
    assert _run(
        lambda g: window_triangles_pipeline(g, Time.milliseconds_of(400)),
        data_file,
    ) == EXPECTED


def test_window_triangles_fused_device(data_file):
    assert _run(
        lambda g: WindowTriangleCount(Time.milliseconds_of(400)).run(g),
        data_file,
    ) == EXPECTED


def _brute_force(src, dst, n):
    adj = [set() for _ in range(n)]
    for u, v in zip(src, dst):
        if u != v:
            adj[u].add(v)
            adj[v].add(u)
    count = 0
    for a, b, c in itertools.combinations(range(n), 3):
        if b in adj[a] and c in adj[a] and c in adj[b]:
            count += 1
    return count


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("kernel", ["dense", "sparse", "pallas"])
def test_kernels_vs_brute_force(seed, kernel):
    rng = np.random.default_rng(seed)
    n = 30
    e = 120
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    expected = _brute_force(src, dst, n)
    if kernel == "pallas":
        from gelly_streaming_tpu.ops.pallas_triangles import \
            triangle_count_dense_pallas as fn
    else:
        fn = (tri_ops.triangle_count_dense if kernel == "dense"
              else tri_ops.triangle_count_sparse)
    assert fn(src, dst, n) == expected


def test_cpu_backend_selects_binary_search_intersect():
    """On CPU backends the measured winner is the binary search (~5x,
    PERF.md `intersect`); the resolvers must pick it — and it must
    agree with the broadcast compare on the sorted-row contract the
    single-chip builder guarantees (build_window_counter sorts via
    dedupe_and_positions)."""
    import jax
    import jax.numpy as jnp

    assert jax.default_backend() == "cpu"  # conftest pins the backend
    tri_ops._INTERSECT_CHOICE = None       # force re-resolution
    try:
        assert (tri_ops.resolve_intersect_impl()
                is tri_ops.intersect_local_bsearch)
        assert (tri_ops.resolve_xla_intersect()
                is tri_ops.intersect_local_bsearch)
    finally:
        tri_ops._INTERSECT_CHOICE = None
    rng = np.random.default_rng(5)
    vb, k, ep = 128, 64, 512
    # rows exactly as the builder lays them out: unique ascending
    # neighbors packed at the FRONT, sentinel suffix (mid-row sentinels
    # would break the searchsorted contract — and never occur)
    nbr = np.full((vb + 1, k), vb, np.int32)
    for v in range(vb):
        row = np.unique(rng.integers(0, vb, size=k // 2))
        nbr[v, :len(row)] = row.astype(np.int32)
    ea = rng.integers(0, vb, ep).astype(np.int32)
    eb_ = rng.integers(0, vb, ep).astype(np.int32)
    emask = rng.random(ep) < 0.9
    args = tuple(jnp.asarray(x) for x in (nbr, ea, eb_, emask))
    assert int(tri_ops.intersect_local_bsearch(*args)) == int(
        tri_ops.intersect_local(*args))


@pytest.mark.parametrize("seed", range(3))
def test_pallas_intersect_matches_xla_compare(seed):
    """The Pallas rows-intersect prototype (ops/pallas_intersect.py)
    agrees with intersect_local on random sorted dedup'd rows,
    including ragged (non-TILE_E-multiple) edge counts and padding."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops.pallas_intersect import \
        intersect_local_pallas

    rng = np.random.default_rng(seed)
    # shapes chosen to exercise EVERY kernel dimension: ep=600 → ten
    # TILE_E=64 grid tiles (ragged final tile of 24 via padding),
    # k=160 → two CHUNK_K=128 compare chunks (ragged final chunk of 32)
    vb, k, ep = 64, 160, 600
    fill = rng.integers(0, vb, size=(vb + 1, k)).astype(np.int32)
    fill.sort(axis=1)
    # dedupe within rows; duplicates become the sentinel
    dup = np.concatenate(
        [np.zeros((vb + 1, 1), bool), fill[:, 1:] == fill[:, :-1]], axis=1)
    nbr = np.where(dup, vb, fill).astype(np.int32)
    ea = rng.integers(0, vb, ep).astype(np.int32)
    eb_ = rng.integers(0, vb, ep).astype(np.int32)
    emask = rng.random(ep) < 0.9
    args = tuple(jnp.asarray(x) for x in (nbr, ea, eb_, emask))
    assert int(intersect_local_pallas(*args)) == int(
        tri_ops.intersect_local(*args))


def test_pallas_intersect_multi_slab(monkeypatch):
    """Edge buckets beyond MAX_TILES*TILE_E are processed in several
    pallas_calls (the [g] partial vector lives in scarce SMEM, so g is
    capped per call). Shrinking MAX_TILES exercises the slab loop —
    slab-boundary slicing, whole-slab padding, cross-slab accumulation
    — with the same small fixture."""
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import pallas_intersect

    monkeypatch.setattr(pallas_intersect, "MAX_TILES", 2)  # 128-edge slabs
    rng = np.random.default_rng(11)
    vb, k, ep = 64, 128, 300   # pads to 384 = 3 slabs, ragged last slab
    fill = rng.integers(0, vb, size=(vb + 1, k)).astype(np.int32)
    fill.sort(axis=1)
    dup = np.concatenate(
        [np.zeros((vb + 1, 1), bool), fill[:, 1:] == fill[:, :-1]], axis=1)
    nbr = np.where(dup, vb, fill).astype(np.int32)
    ea = rng.integers(0, vb, ep).astype(np.int32)
    eb_ = rng.integers(0, vb, ep).astype(np.int32)
    emask = rng.random(ep) < 0.9
    args = tuple(jnp.asarray(x) for x in (nbr, ea, eb_, emask))
    assert int(pallas_intersect.intersect_local_pallas(*args)) == int(
        tri_ops.intersect_local(*args))


def test_streaming_window_kernel_matches_sparse():
    """Fixed-shape streaming engine (one compile for all windows) agrees
    with the dynamic host path across windows of varying size/shape."""
    k = tri_ops.TriangleWindowKernel(edge_bucket=4096, vertex_bucket=512)
    for seed in range(4):
        rng = np.random.default_rng(seed)
        e = int(rng.integers(10, 4000))
        src = rng.integers(0, 500, e)
        dst = rng.integers(0, 500, e)
        assert k.count(src, dst) == tri_ops.triangle_count_sparse(
            src, dst, 512)
    assert k.count(np.array([], np.int64), np.array([], np.int64)) == 0
    # oversized window is rejected, not silently truncated
    with pytest.raises(ValueError):
        k.count(np.zeros(5000, np.int64), np.ones(5000, np.int64))


def test_streaming_window_kernel_overflow_fallback():
    """A hub whose oriented out-degree exceeds k_bucket must trigger the
    exact fallback, not a wrong count."""
    k = tri_ops.TriangleWindowKernel(edge_bucket=256, vertex_bucket=128,
                                     k_bucket=8)
    # star + clique: vertex 0 connects to everyone; 40-clique on 1..40
    src, dst = [], []
    for v in range(1, 100):
        src.append(0)
        dst.append(v)
    for u in range(1, 41):
        for v in range(u + 1, 41):
            src.append(u)
            dst.append(v)
    src, dst = np.array(src[:256]), np.array(dst[:256])
    assert k.count(src, dst) == _brute_force(src, dst, 128)


def test_count_stream_matches_per_window_counts():
    """Batched lax.map streaming path = per-window counts, including a
    ragged tail window and the empty stream."""
    k = tri_ops.TriangleWindowKernel(edge_bucket=512, vertex_bucket=256)
    rng = np.random.default_rng(11)
    e = 512 * 3 + 137  # three full windows + ragged tail
    src = rng.integers(0, 200, e)
    dst = rng.integers(0, 200, e)
    expected = [k.count(src[s:s + 512], dst[s:s + 512])
                for s in range(0, e, 512)]
    assert k.count_stream(src, dst) == expected
    assert k.count_stream(np.array([], np.int64), np.array([], np.int64)) == []


def test_count_stream_overflow_windows_recounted_exactly():
    """Windows whose hubs outrun K are redone exactly; clean windows in
    the same chunk keep their batched counts."""
    k = tri_ops.TriangleWindowKernel(edge_bucket=256, vertex_bucket=128,
                                     k_bucket=8)
    rng = np.random.default_rng(3)
    # window 0: random sparse (fits K); window 1: 40-clique (overflows)
    s0 = rng.integers(0, 100, 256)
    d0 = rng.integers(0, 100, 256)
    s1, d1 = [], []
    for u in range(1, 41):
        for v in range(u + 1, 41):
            s1.append(u)
            d1.append(v)
    s1, d1 = np.array(s1[:256]), np.array(d1[:256])
    src = np.concatenate([s0, s1])
    dst = np.concatenate([d0, d1])
    assert k.count_stream(src, dst) == [
        _brute_force(s0, d0, 128), _brute_force(s1, d1, 128)]


def test_escalation_ladder_widens_to_kmax():
    k = tri_ops.TriangleWindowKernel(edge_bucket=4096, vertex_bucket=512,
                                     k_bucket=8)
    ladder = k._escalation_ladder()
    assert ladder[0] == 8 and ladder[-1] >= k.kb_max
    assert all(b > a for a, b in zip(ladder, ladder[1:]))


def test_dense_choice_is_measurement_driven(tmp_path, monkeypatch):
    """triangle_count's dense path comes from committed PERF.json
    on-chip measurements: XLA by default (and always off-TPU), Pallas
    only when the measurements were taken on a TPU and every measured
    V shows parity-checked speedup ≥1.05."""
    import json
    import sys

    # import BEFORE jax is monkeypatched: pallas_intersect's own
    # module-level jax imports must resolve against the real jax
    from gelly_streaming_tpu.ops.pallas_intersect import \
        intersect_local_pallas

    # off-TPU (this CI): always XLA at the standard limit
    tri_ops._DENSE_CHOICE = None
    assert tri_ops._resolve_dense_choice() == ("xla", tri_ops.DENSE_LIMIT)

    # fake a TPU backend + measurements in an isolated file
    class _FakeJax:
        @staticmethod
        def default_backend():
            return "tpu"

    perf_path = str(tmp_path / "PERF.json")
    monkeypatch.setattr(tri_ops, "_PERF_PATH", perf_path)
    monkeypatch.setitem(sys.modules, "jax", _FakeJax)
    try:
        with open(perf_path, "w") as f:
            json.dump({"backend": "tpu",
                       "dense": [{"v": 1024, "pallas_speedup": 1.4},
                                 {"v": 2048, "pallas_speedup": 1.2}]}, f)
        tri_ops._DENSE_CHOICE = None
        assert tri_ops._resolve_dense_choice() == (
            "pallas", 2 * tri_ops.DENSE_LIMIT)

        # one losing size vetoes the switch
        with open(perf_path, "w") as f:
            json.dump({"backend": "tpu",
                       "dense": [{"v": 1024, "pallas_speedup": 1.4},
                                 {"v": 2048, "pallas_speedup": 0.9}]}, f)
        tri_ops._DENSE_CHOICE = None
        assert tri_ops._resolve_dense_choice() == (
            "xla", tri_ops.DENSE_LIMIT)

        # measurements recorded on a CPU backend never flip the default
        with open(perf_path, "w") as f:
            json.dump({"backend": "cpu",
                       "dense": [{"v": 1024, "pallas_speedup": 9.9}]}, f)
        tri_ops._DENSE_CHOICE = None
        assert tri_ops._resolve_dense_choice() == (
            "xla", tri_ops.DENSE_LIMIT)

        # intersect selection: same policy (parity + >=1.05 on tpu)
        with open(perf_path, "w") as f:
            json.dump({"backend": "tpu",
                       "intersect": {"parity_pallas": True,
                                     "pallas_vs_xla_compare": 1.3}}, f)
        tri_ops._INTERSECT_CHOICE = None
        assert tri_ops.resolve_intersect_impl() is intersect_local_pallas
        with open(perf_path, "w") as f:
            json.dump({"backend": "tpu",
                       "intersect": {"parity_pallas": True,
                                     "pallas_vs_xla_compare": 0.8}}, f)
        tri_ops._INTERSECT_CHOICE = None
        assert tri_ops.resolve_intersect_impl() is tri_ops.intersect_local
        # tuned K: the fastest MEASURED sweep entry wins outright (its
        # per_window_ms already includes that K's recount cost); rows
        # for other edge buckets are ignored
        with open(perf_path, "w") as f:
            json.dump({"backend": "tpu", "window": [
                {"edge_bucket": 4096, "k_sweep": [
                    {"k_bucket": 32, "per_window_ms": 2.0,
                     "overflow_recounts_per_run": 0},
                    {"k_bucket": 64, "per_window_ms": 5.0,
                     "overflow_recounts_per_run": 0},
                    {"k_bucket": 16, "per_window_ms": 1.0,
                     "overflow_recounts_per_run": 3}]}]}, f)
        tri_ops._TUNED_KB.clear()
        assert tri_ops._tuned_kb(4096) == 16
        assert tri_ops._tuned_kb(8192) == min(
            128, 2 * int(np.sqrt(8192)))  # unmeasured bucket: heuristic

        # K tuning is backend-MATCHED: a cpu-labeled sweep never tunes
        # a (fake-)tpu process
        with open(perf_path, "w") as f:
            json.dump({"backend": "cpu", "window": [
                {"edge_bucket": 4096, "k_sweep": [
                    {"k_bucket": 16, "per_window_ms": 1.0,
                     "overflow_recounts_per_run": 0}]}]}, f)
        tri_ops._TUNED_KB.clear()
        assert tri_ops._tuned_kb(4096) == min(
            128, 2 * int(np.sqrt(4096)))
    finally:
        tri_ops._DENSE_CHOICE = None
        tri_ops._INTERSECT_CHOICE = None
        tri_ops._INTERSECT_JIT = None
        tri_ops._TUNED_KB.clear()


def test_tuned_kb_uses_cpu_sweep_on_cpu_backend(tmp_path, monkeypatch):
    """The real backend here IS cpu: a cpu-labeled committed sweep
    drives K selection (the CPU-fallback speedup path)."""
    import json

    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("needs a real cpu backend (conftest pins one)")
    perf_path = str(tmp_path / "PERF.json")
    monkeypatch.setattr(tri_ops, "_PERF_PATH", perf_path)
    with open(perf_path, "w") as f:
        json.dump({"backend": "cpu", "window": [
            {"edge_bucket": 4096, "k_sweep": [
                {"k_bucket": 16, "per_window_ms": 1.0,
                 "overflow_recounts_per_run": 0}]}]}, f)
    tri_ops._TUNED_KB.clear()
    try:
        assert tri_ops._tuned_kb(4096) == 16
    finally:
        tri_ops._TUNED_KB.clear()


def test_kernels_empty_and_tiny():
    assert tri_ops.triangle_count_sparse(np.array([]), np.array([]), 0) == 0
    assert tri_ops.triangle_count_dense(np.array([0]), np.array([1]), 2) == 0
    tri = tri_ops.triangle_count(np.array([0, 1, 2]), np.array([1, 2, 0]), 3)
    assert tri == 1


def test_numpy_baseline_port_matches_python_port():
    """bench.py's PRIMARY CPU baseline (numpy-vectorized faithful port)
    must compute exactly what the interpreted reference port computes —
    the vectorization may change the cost model, never the counts."""
    import bench

    rng = np.random.default_rng(11)
    for _ in range(8):
        e = int(rng.integers(1, 3000))
        v = int(rng.integers(4, 400))
        src = rng.integers(0, v, e)
        dst = (src + 1 + rng.integers(0, v - 1, e)) % v
        assert (bench.cpu_reference_window_counts_numpy(src, dst, 512)
                == bench.cpu_reference_window_counts(src, dst, 512))


def test_warm_chunks_precompiles_every_stream_bucket():
    """After warm_chunks, count_stream on any ragged stream length must
    trigger ZERO new XLA compiles — the steady-state discipline the
    scale run asserts for the driver (a tuned chunk size must never
    move first-use compiles into the stream tail)."""
    import logging

    import jax

    kern = tri_ops.TriangleWindowKernel(edge_bucket=64, vertex_bucket=64)
    kern.warm_chunks()

    events = []

    class Counter(logging.Handler):
        def emit(self, record):
            if "compiling" in record.getMessage().lower():
                events.append(record.getMessage())

    counter = Counter()
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(counter)
    for name in ("jax._src.interpreters.pxla", "jax._src.dispatch"):
        logging.getLogger(name).setLevel(logging.DEBUG)
    try:
        rng = np.random.default_rng(5)
        for num_w in (1, 3, 7, kern.MAX_STREAM_WINDOWS + 5):
            e = num_w * kern.eb - 3
            kern.count_stream(rng.integers(0, 60, e),
                              rng.integers(0, 60, e))
    finally:
        jax.config.update("jax_log_compiles", False)
        logging.getLogger("jax").removeHandler(counter)
    assert not events, events


# ----------------------------------------------------------------------
# host (numpy) streaming tier: ops/host_triangles.py
# ----------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(5))
def test_host_window_count_vs_brute_force(seed):
    from gelly_streaming_tpu.ops import host_triangles

    rng = np.random.default_rng(100 + seed)
    n, e = 30, 120
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)   # includes self-loops + duplicates
    assert host_triangles.window_count(src, dst) == _brute_force(
        src, dst, n)


def test_host_count_stream_matches_device_kernel():
    """Same window boundaries, same exact counts as
    TriangleWindowKernel._count_stream_device on a skewed stream with
    duplicates — the parity contract `host_stream` selection rows
    assert before the tier can ever win."""
    from gelly_streaming_tpu.ops import host_triangles
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    rng = np.random.default_rng(7)
    eb, vb, num_w = 512, 256, 5
    # zipf-ish skew so hubs stress the orientation + wedge enumeration
    src = (rng.zipf(1.3, num_w * eb) % vb).astype(np.int32)
    dst = (rng.zipf(1.3, num_w * eb) % vb).astype(np.int32)
    kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
    dev = kern._count_stream_device(src, dst)
    host = host_triangles.count_stream(src, dst, eb)
    assert host == dev
    assert sum(host) > 0
    # count_windows form on ragged windows
    wins = [(src[:300], dst[:300]), (src[300:900], dst[300:900])]
    assert (host_triangles.count_windows(wins)
            == [host_triangles.window_count(*w) for w in wins])


def test_host_window_count_wedge_chunking():
    """The wedge-slice cap only bounds memory, never changes counts."""
    from gelly_streaming_tpu.ops import host_triangles

    rng = np.random.default_rng(13)
    n, e = 200, 3000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    want = host_triangles.window_count(src, dst)
    orig = host_triangles._WEDGE_CHUNK
    try:
        host_triangles._WEDGE_CHUNK = 64   # force many slices
        assert host_triangles.window_count(src, dst) == want
    finally:
        host_triangles._WEDGE_CHUNK = orig


def test_host_tier_selected_end_to_end(tmp_path, monkeypatch):
    """With committed winning cpu rows, TriangleWindowKernel routes
    count_stream/count_windows through the numpy tier (and warms
    nothing)."""
    import json

    monkeypatch.setattr(tri_ops, "_PERF_PATH",
                        str(tmp_path / "PERF.json"))
    monkeypatch.setattr(tri_ops, "_STREAM_IMPL", None)
    (tmp_path / "PERF.json").write_text(json.dumps({
        "backend": "cpu",
        "host_stream": [{"edge_bucket": 8192, "parity": True,
                         "host_edges_per_s": 2_000_000,
                         "device_edges_per_s": 800_000}]}))
    try:
        kern = tri_ops.TriangleWindowKernel(edge_bucket=512,
                                            vertex_bucket=256)
        rng = np.random.default_rng(3)
        src = rng.integers(0, 256, 1024).astype(np.int32)
        dst = rng.integers(0, 256, 1024).astype(np.int32)
        got = kern.count_stream(src, dst)
        # the selected tier compiled nothing
        assert not kern._stream_execs
        assert got == kern._count_stream_device(src, dst)
        execs_before = dict(kern._stream_execs)
        kern.warm_chunks()   # must be a no-op, not a compile storm
        assert kern._stream_execs == execs_before
    finally:
        monkeypatch.undo()
        tri_ops._STREAM_IMPL = None


# ----------------------------------------------------------------------
# native (C++) streaming tier: native/ingest.cpp gs_triangle_count_stream
# ----------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    not __import__("gelly_streaming_tpu.native",
                   fromlist=["x"]).triangles_available(),
    reason="libgsnative.so not built in this environment")


@needs_native
@pytest.mark.parametrize("seed", range(5))
def test_native_window_count_vs_brute_force(seed):
    from gelly_streaming_tpu import native

    rng = np.random.default_rng(300 + seed)
    n, e = 30, 120
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)   # includes self-loops + duplicates
    (got,) = native.triangle_count_stream(src, dst, e)
    assert got == _brute_force(src, dst, n)


@needs_native
def test_native_count_stream_matches_both_tiers():
    """Same window boundaries, same exact counts as the numpy tier and
    the device kernel — on a skewed stream (direct-index branch) and on
    a sparse id space (compression branch)."""
    from gelly_streaming_tpu import native
    from gelly_streaming_tpu.ops import host_triangles
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    rng = np.random.default_rng(7)
    eb, vb, num_w = 512, 256, 5
    src = (rng.zipf(1.3, num_w * eb) % vb).astype(np.int32)
    dst = (rng.zipf(1.3, num_w * eb) % vb).astype(np.int32)
    kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
    dev = kern._count_stream_device(src, dst)
    assert list(native.triangle_count_stream(src, dst, eb)) == dev
    # sparse ids (> 16x edge count): the sort-unique compression branch
    big = np.int64(1) << 40
    s2 = src.astype(np.int64) * big // 256
    d2 = dst.astype(np.int64) * big // 256
    assert (list(native.triangle_count_stream(s2, d2, eb))
            == host_triangles.count_stream(src, dst, eb))


@needs_native
def test_native_tier_selected_end_to_end(tmp_path, monkeypatch):
    """Committed rows where the native tier wins everywhere route
    count_stream AND count_windows through C++ (no compiles)."""
    import json

    monkeypatch.setattr(tri_ops, "_PERF_PATH",
                        str(tmp_path / "PERF.json"))
    monkeypatch.setattr(tri_ops, "_STREAM_IMPL", None)
    (tmp_path / "PERF.json").write_text(json.dumps({
        "backend": "cpu",
        "host_stream": [{"edge_bucket": 8192, "parity": True,
                         "host_edges_per_s": 2_000_000,
                         "device_edges_per_s": 800_000,
                         "native_parity": True,
                         "native_edges_per_s": 6_000_000}]}))
    try:
        assert tri_ops._resolve_stream_impl() == "native"
        kern = tri_ops.TriangleWindowKernel(edge_bucket=512,
                                            vertex_bucket=256)
        rng = np.random.default_rng(3)
        src = rng.integers(0, 256, 1024).astype(np.int32)
        dst = rng.integers(0, 256, 1024).astype(np.int32)
        got = kern.count_stream(src, dst)
        assert not kern._stream_execs          # nothing compiled
        assert got == kern._count_stream_device(src, dst)
        wins = [(src[:300], dst[:300]), (src[300:800], dst[300:800])]
        assert (kern.count_windows(wins)
                == [kern.count(*w) for w in wins])
    finally:
        monkeypatch.undo()
        tri_ops._STREAM_IMPL = None


def test_stream_prefetch_parity_and_error_propagation(monkeypatch):
    """The producer-thread prefetch path (default) and the
    single-threaded form (GS_STREAM_PREFETCH=0) return identical
    counts in window order; a prep failure mid-stream surfaces as the
    original exception, not a hang or a truncated result."""
    # ingress pinned standard: the hand-built bad_chunk below fabricates
    # STANDARD-format stacks, and committed winning ingress_ab rows
    # would otherwise resolve the kernel compact (this test pins the
    # pipeline loop's contract, not the wire-format selection)
    kern = tri_ops.TriangleWindowKernel(edge_bucket=256,
                                       vertex_bucket=128,
                                       ingress="standard")
    kern.MAX_STREAM_WINDOWS = 4   # many chunks: 16 windows -> 4 chunks
    rng = np.random.default_rng(11)
    src = rng.integers(0, 128, 16 * 256).astype(np.int32)
    dst = rng.integers(0, 128, 16 * 256).astype(np.int32)
    got = kern._count_stream_device(src, dst)
    monkeypatch.setenv("GS_STREAM_PREFETCH", "0")
    assert kern._count_stream_device(src, dst) == got
    monkeypatch.undo()

    boom = RuntimeError("prep exploded")

    def bad_chunk(at, hi):
        if at >= 8:
            raise boom
        from gelly_streaming_tpu.ops import segment as seg
        num_w, s, d, valid = seg.window_stack(src, dst, kern.eb,
                                              sentinel=kern.vb)
        sc, dc, vc, n = seg.pad_window_chunk(
            s, d, valid, at, hi, kern.MAX_STREAM_WINDOWS, kern.eb,
            kern.vb)
        return (sc, dc, vc), n

    with pytest.raises(RuntimeError, match="prep exploded"):
        kern._run_stack_loop(16, bad_chunk, lambda w: 0)

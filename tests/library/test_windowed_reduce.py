"""Columnar windowed-reduce engine (ops/windowed_reduce.py) — the
stream-rate form of reduceOnEdges/foldNeighbors (BASELINE.json config
#2; reference hot loop GraphWindowStream.java:101-121).

Parity is pinned three ways: against the record-level runtime on the
reference's golden TestSlice graph (same numbers the reference's own
TestSlice.java:81-121 asserts), against a faithful numpy per-window
fold on a 1M-edge fuzz stream, and across the monoid/associative-fn
tiers.
"""

import numpy as np
import pytest

from gelly_streaming_tpu import (EdgeDirection, EdgesReduce,
                                 SimpleEdgeStream, Time)
from gelly_streaming_tpu.ops import segment as seg_ops
from gelly_streaming_tpu.ops.windowed_reduce import (WindowedEdgeReduce,
                                                     numpy_reference)

from ..conftest import long_long_edges, run_and_sort

FOLD_EXPECTED = {  # reference TestSlice.java:81-121
    "out": {1: 25, 2: 23, 3: 69, 4: 45, 5: 51},
    "in": {1: 51, 2: 12, 3: 36, 4: 34, 5: 80},
    "all": {1: 76, 2: 35, 3: 105, 4: 79, 5: 131},
}


@pytest.mark.parametrize("direction", ["out", "in", "all"])
def test_columnar_matches_golden_slice(direction):
    """The columnar engine reproduces the reference's TestSlice sums
    exactly (single window covering the whole 7-edge graph)."""
    edges = long_long_edges()
    src = np.array([e.source for e in edges])
    dst = np.array([e.target for e in edges])
    val = np.array([e.value for e in edges])
    uniq, (s_d, d_d) = seg_ops.intern(src, dst)
    eng = WindowedEdgeReduce(vertex_bucket=len(uniq), edge_bucket=8,
                             name="sum", direction=direction)
    (cells, counts), = eng.process_stream(s_d, d_d, val)
    got = {int(uniq[slot]): int(cells[slot])
           for slot in np.nonzero(counts)[0]}
    assert got == FOLD_EXPECTED[direction]


@pytest.mark.parametrize("direction,enum_dir", [
    ("out", EdgeDirection.OUT), ("in", EdgeDirection.IN),
    ("all", EdgeDirection.ALL)])
def test_columnar_matches_record_level_path(env, direction, enum_dir):
    """Same windows through the record-level runtime
    (slice().reduce_on_edges with a host UDF — exact reference
    semantics) and the columnar engine: identical per-vertex sums."""
    edges = long_long_edges()
    out = SimpleEdgeStream(env.from_collection(edges), env).slice(
        Time.seconds(1), enum_dir).reduce_on_edges(
        EdgesReduce(lambda a, b: a + b))
    record_level = run_and_sort(env, out)

    src = np.array([e.source for e in edges])
    dst = np.array([e.target for e in edges])
    val = np.array([e.value for e in edges])
    uniq, (s_d, d_d) = seg_ops.intern(src, dst)
    eng = WindowedEdgeReduce(vertex_bucket=len(uniq), edge_bucket=8,
                             name="sum", direction=direction)
    (cells, counts), = eng.process_stream(s_d, d_d, val)
    columnar = sorted("%d,%d" % (uniq[slot], cells[slot])
                      for slot in np.nonzero(counts)[0])
    assert columnar == record_level


@pytest.mark.parametrize("direction", ["out", "in", "all"])
@pytest.mark.parametrize("name", ["sum", "min", "max"])
def test_columnar_fuzz_vs_numpy_fold(direction, name):
    """Multi-window fuzz (ragged tail, duplicate edges, skew) against
    the faithful per-window numpy fold."""
    rng = np.random.default_rng(41)
    n, nv, eb = 10_000, 700, 1024
    src = (rng.zipf(1.4, n) % nv).astype(np.int64)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 1000, n).astype(np.int32)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name=name, direction=direction)
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb, direction, name)
    assert len(got) == len(want) == -(-n // eb)
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gn[:nv], wn)
        occ = wn > 0
        np.testing.assert_array_equal(gc[:nv][occ], wc[occ])


@pytest.mark.slow
def test_columnar_million_edge_fuzz():
    """VERDICT r3 item 3's fuzz bar: 1M edges through the engine at the
    bench window size, exact parity with the numpy fold."""
    rng = np.random.default_rng(43)
    n, nv, eb = 1 << 20, 1 << 14, 8192
    src = (rng.zipf(1.3, n) % nv).astype(np.int64)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 100, n).astype(np.int64)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name="sum", direction="out")
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb, "out", "sum")
    assert len(got) == len(want) == n // eb
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gn[:nv], wn)
        np.testing.assert_array_equal(gc[:nv], wc)


def test_associative_fn_tier_matches_monoid():
    """fn=jnp.minimum through the flagged associative scan equals
    name='min' through the segment kernels — and a non-monoid
    associative fn (gcd) equals a direct per-cell fold."""
    import math

    import jax.numpy as jnp

    rng = np.random.default_rng(47)
    n, nv, eb = 600, 40, 128
    src = rng.integers(0, nv, n)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 10_000, n).astype(np.int32)

    m = WindowedEdgeReduce(nv, eb, name="min").process_stream(
        src, dst, val)
    f = WindowedEdgeReduce(nv, eb, fn=jnp.minimum).process_stream(
        src, dst, val)
    for (mc, mn), (fc, fnn) in zip(m, f):
        np.testing.assert_array_equal(mn, fnn)
        occ = mn > 0
        np.testing.assert_array_equal(mc[occ], fc[occ])

    g = WindowedEdgeReduce(nv, eb, fn=jnp.gcd).process_stream(
        src, dst, val)
    for w, (gc, gn) in enumerate(g):
        s, v = src[w * eb:(w + 1) * eb], val[w * eb:(w + 1) * eb]
        for vtx in range(nv):
            mask = s == vtx
            assert gn[vtx] == mask.sum()
            if mask.any():
                acc = None
                for x in v[mask].tolist():
                    acc = x if acc is None else math.gcd(acc, x)
                assert gc[vtx] == acc


def test_window_chunking_boundaries():
    """Streams longer than one dispatch chunk (MAX_STREAM_WINDOWS)
    split without losing or shifting windows."""
    rng = np.random.default_rng(53)
    nv, eb = 64, 32
    n = eb * 70 + 11   # > one 64-window chunk, ragged tail
    src = rng.integers(0, nv, n)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 50, n).astype(np.int32)
    eng = WindowedEdgeReduce(nv, eb, name="sum")
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb)
    assert len(got) == len(want) == 71
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gc[:nv], wc)
        np.testing.assert_array_equal(gn[:nv], wn)

"""Columnar windowed-reduce engine (ops/windowed_reduce.py) — the
stream-rate form of reduceOnEdges/foldNeighbors (BASELINE.json config
#2; reference hot loop GraphWindowStream.java:101-121).

Parity is pinned three ways: against the record-level runtime on the
reference's golden TestSlice graph (same numbers the reference's own
TestSlice.java:81-121 asserts), against a faithful numpy per-window
fold on a 1M-edge fuzz stream, and across the monoid/associative-fn
tiers.
"""

import numpy as np
import pytest

from gelly_streaming_tpu import (EdgeDirection, EdgesReduce,
                                 SimpleEdgeStream, Time)
from gelly_streaming_tpu.ops import segment as seg_ops
from gelly_streaming_tpu.ops.windowed_reduce import (WindowedEdgeReduce,
                                                     numpy_reference)

from ..conftest import long_long_edges, run_and_sort

FOLD_EXPECTED = {  # reference TestSlice.java:81-121
    "out": {1: 25, 2: 23, 3: 69, 4: 45, 5: 51},
    "in": {1: 51, 2: 12, 3: 36, 4: 34, 5: 80},
    "all": {1: 76, 2: 35, 3: 105, 4: 79, 5: 131},
}


@pytest.mark.parametrize("direction", ["out", "in", "all"])
def test_columnar_matches_golden_slice(direction):
    """The columnar engine reproduces the reference's TestSlice sums
    exactly (single window covering the whole 7-edge graph)."""
    edges = long_long_edges()
    src = np.array([e.source for e in edges])
    dst = np.array([e.target for e in edges])
    val = np.array([e.value for e in edges])
    uniq, (s_d, d_d) = seg_ops.intern(src, dst)
    eng = WindowedEdgeReduce(vertex_bucket=len(uniq), edge_bucket=8,
                             name="sum", direction=direction)
    (cells, counts), = eng.process_stream(s_d, d_d, val)
    got = {int(uniq[slot]): int(cells[slot])
           for slot in np.nonzero(counts)[0]}
    assert got == FOLD_EXPECTED[direction]


@pytest.mark.parametrize("direction,enum_dir", [
    ("out", EdgeDirection.OUT), ("in", EdgeDirection.IN),
    ("all", EdgeDirection.ALL)])
def test_columnar_matches_record_level_path(env, direction, enum_dir):
    """Same windows through the record-level runtime
    (slice().reduce_on_edges with a host UDF — exact reference
    semantics) and the columnar engine: identical per-vertex sums."""
    edges = long_long_edges()
    out = SimpleEdgeStream(env.from_collection(edges), env).slice(
        Time.seconds(1), enum_dir).reduce_on_edges(
        EdgesReduce(lambda a, b: a + b))
    record_level = run_and_sort(env, out)

    src = np.array([e.source for e in edges])
    dst = np.array([e.target for e in edges])
    val = np.array([e.value for e in edges])
    uniq, (s_d, d_d) = seg_ops.intern(src, dst)
    eng = WindowedEdgeReduce(vertex_bucket=len(uniq), edge_bucket=8,
                             name="sum", direction=direction)
    (cells, counts), = eng.process_stream(s_d, d_d, val)
    columnar = sorted("%d,%d" % (uniq[slot], cells[slot])
                      for slot in np.nonzero(counts)[0])
    assert columnar == record_level


@pytest.mark.parametrize("direction", ["out", "in", "all"])
@pytest.mark.parametrize("name", ["sum", "min", "max"])
def test_columnar_fuzz_vs_numpy_fold(direction, name):
    """Multi-window fuzz (ragged tail, duplicate edges, skew) against
    the faithful per-window numpy fold."""
    rng = np.random.default_rng(41)
    n, nv, eb = 10_000, 700, 1024
    src = (rng.zipf(1.4, n) % nv).astype(np.int64)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 1000, n).astype(np.int32)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name=name, direction=direction)
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb, direction, name)
    assert len(got) == len(want) == -(-n // eb)
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gn[:nv], wn)
        occ = wn > 0
        np.testing.assert_array_equal(gc[:nv][occ], wc[occ])


@pytest.mark.slow
def test_columnar_million_edge_fuzz():
    """VERDICT r3 item 3's fuzz bar: 1M edges through the engine at the
    bench window size, exact parity with the numpy fold."""
    rng = np.random.default_rng(43)
    n, nv, eb = 1 << 20, 1 << 14, 8192
    src = (rng.zipf(1.3, n) % nv).astype(np.int64)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 100, n).astype(np.int64)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name="sum", direction="out")
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb, "out", "sum")
    assert len(got) == len(want) == n // eb
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gn[:nv], wn)
        np.testing.assert_array_equal(gc[:nv], wc)


needs_native_reduce = pytest.mark.skipif(
    not __import__("gelly_streaming_tpu.native",
                   fromlist=["x"]).windowed_reduce_available(),
    reason="libgsnative.so lacks gs_windowed_reduce")


@needs_native_reduce
@pytest.mark.parametrize("direction", ["out", "in", "all"])
@pytest.mark.parametrize("name", ["sum", "min", "max"])
def test_native_reduce_tier_matches_numpy(direction, name):
    """The C++ fused tier (native/ingest.cpp gs_windowed_reduce):
    identical (cells, counts) to the numpy tier on ragged, skewed,
    duplicate-heavy streams — both the i32 fast path and the i64
    form."""
    from gelly_streaming_tpu.ops import windowed_reduce as wr

    rng = np.random.default_rng(47)
    n, nv, eb = 9_500, 700, 1024
    src = (rng.zipf(1.4, n) % nv).astype(np.int64)
    dst = rng.integers(0, nv, n)
    val = rng.integers(-50, 1000, n).astype(np.int32)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name=name, direction=direction)
    want = eng._host_process_stream(src, dst, val)
    for cast in (np.int32, np.int64):   # i32 fast path + i64 form
        got = eng._native_process_stream(src.astype(cast),
                                         dst.astype(cast), val)
        assert got is not None and len(got) == len(want)
        for (gc, gn), (wc, wn) in zip(got, want):
            np.testing.assert_array_equal(gn, wn)
            occ = wn > 0
            np.testing.assert_array_equal(
                gc[occ] if name != "sum" else gc,
                wc[occ] if name != "sum" else wc)


@needs_native_reduce
def test_native_reduce_selected_end_to_end(tmp_path, monkeypatch):
    """Committed rows where the native tier wins route process_stream
    through C++ for integer values (and keep numpy for floats)."""
    import json

    from gelly_streaming_tpu.ops import triangles as tri_ops
    from gelly_streaming_tpu.ops import windowed_reduce as wr

    monkeypatch.setattr(tri_ops, "_PERF_PATH",
                        str(tmp_path / "PERF.json"))
    monkeypatch.setattr(wr, "_REDUCE_IMPL", {})
    (tmp_path / "PERF.json").write_text(json.dumps({
        "backend": "cpu",
        "host_reduce": [{"name": "sum", "edge_bucket": 8192,
                         "parity": True,
                         "host_edges_per_s": 60_000_000,
                         "device_edges_per_s": 20_000_000,
                         "native_parity": True,
                         "native_edges_per_s": 120_000_000}]}))
    try:
        assert wr._resolve_reduce_impl("sum") == "native"
        rng = np.random.default_rng(3)
        src = rng.integers(0, 100, 3000).astype(np.int32)
        dst = rng.integers(0, 100, 3000).astype(np.int32)
        val = rng.integers(1, 50, 3000).astype(np.int32)
        eng = WindowedEdgeReduce(vertex_bucket=128, edge_bucket=512,
                                 name="sum", direction="all")
        got = eng.process_stream(src, dst, val)
        want = numpy_reference(src, dst, val, 512, "all", "sum")
        for (gc, gn), (wc, wn) in zip(got, want):
            np.testing.assert_array_equal(gc[:100], wc[:100])
            np.testing.assert_array_equal(gn[:100], wn[:100])
        # float values: numpy tier stands in transparently
        fval = val.astype(np.float32)
        gotf = eng.process_stream(src, dst, fval)
        wantf = numpy_reference(src, dst, fval, 512, "all", "sum")
        for (gc, gn), (wc, wn) in zip(gotf, wantf):
            np.testing.assert_allclose(gc[:100], wc[:100])
    finally:
        monkeypatch.undo()
        wr._REDUCE_IMPL.clear()


@needs_native_reduce
def test_native_reduce_rejects_out_of_range_ids():
    """The C++ kernel must fail as loudly as the other tiers on bad
    ids (bincount raises) — never write outside its slabs."""
    from gelly_streaming_tpu import native

    for bad in (np.array([900], np.int32), np.array([-1], np.int32)):
        with pytest.raises(ValueError, match="outside"):
            native.windowed_reduce(bad, np.array([1], bad.dtype),
                                   np.array([7], bad.dtype), 4, 10,
                                   "sum", "out", 0)


@needs_native_reduce
def test_native_i32_output_gate_covers_counts_slab():
    """The int32-output fast form is gated on the COUNTS slab too: a
    cell can receive up to 2·eb contributions regardless of the
    reduce op, so min/max and the all-zero-sum case (where the old
    value-only bound 0 × per_cell passed vacuously) must fall back to
    int64 slabs whenever 2*eb exceeds INT32_MAX. Normal window sizes
    keep the int32 fast path."""
    from gelly_streaming_tpu import native
    from gelly_streaming_tpu.ops.windowed_reduce import _host_identity

    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 0], np.int32)
    ones = np.ones(3, np.int32)
    huge_eb = (1 << 30) + 1           # 2*eb > INT32_MAX, n stays tiny
    for name, val in (("min", ones), ("max", ones),
                      ("sum", np.zeros(3, np.int32))):
        cells, counts = native.windowed_reduce(
            src, dst, val, huge_eb, 8, name, "all",
            int(_host_identity(name, val.dtype)))
        assert counts.dtype == np.int64, (name, counts.dtype)
        assert cells.dtype == np.int64, (name, cells.dtype)
    if native.windowed_reduce_available() and hasattr(
            native._load(), "gs_windowed_reduce_i32o"):
        cells, counts = native.windowed_reduce(
            src, dst, ones, 8, 8, "min", "all", int(2 ** 31 - 1))
        assert counts.dtype == np.int32   # the fast path still fires


def test_host_sum_fast_path_rejects_out_of_range_ids():
    """The per-window bincount fast path must raise (like the
    flattened path's reshape did), not emit a ragged window."""
    eng = WindowedEdgeReduce(vertex_bucket=64, edge_bucket=32,
                             name="sum", direction="out")
    src = np.array([1, 2, 200], np.int64)   # 200 >= vbp=65
    dst = np.array([3, 4, 5], np.int64)
    val = np.ones(3, np.int32)
    with pytest.raises(ValueError, match="outside"):
        eng._host_process_stream(src, dst, val)


def test_associative_fn_tier_matches_monoid():
    """fn=jnp.minimum through the flagged associative scan equals
    name='min' through the segment kernels — and a non-monoid
    associative fn (gcd) equals a direct per-cell fold."""
    import math

    import jax.numpy as jnp

    rng = np.random.default_rng(47)
    n, nv, eb = 600, 40, 128
    src = rng.integers(0, nv, n)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 10_000, n).astype(np.int32)

    m = WindowedEdgeReduce(nv, eb, name="min").process_stream(
        src, dst, val)
    f = WindowedEdgeReduce(nv, eb, fn=jnp.minimum).process_stream(
        src, dst, val)
    for (mc, mn), (fc, fnn) in zip(m, f):
        np.testing.assert_array_equal(mn, fnn)
        occ = mn > 0
        np.testing.assert_array_equal(mc[occ], fc[occ])

    g = WindowedEdgeReduce(nv, eb, fn=jnp.gcd).process_stream(
        src, dst, val)
    for w, (gc, gn) in enumerate(g):
        s, v = src[w * eb:(w + 1) * eb], val[w * eb:(w + 1) * eb]
        for vtx in range(nv):
            mask = s == vtx
            assert gn[vtx] == mask.sum()
            if mask.any():
                acc = None
                for x in v[mask].tolist():
                    acc = x if acc is None else math.gcd(acc, x)
                assert gc[vtx] == acc


def test_window_chunking_boundaries():
    """Streams longer than one dispatch chunk (MAX_STREAM_WINDOWS)
    split without losing or shifting windows."""
    rng = np.random.default_rng(53)
    nv, eb = 64, 32
    n = eb * 70 + 11   # > one 64-window chunk, ragged tail
    src = rng.integers(0, nv, n)
    dst = rng.integers(0, nv, n)
    val = rng.integers(1, 50, n).astype(np.int32)
    eng = WindowedEdgeReduce(nv, eb, name="sum")
    got = eng.process_stream(src, dst, val)
    want = numpy_reference(src, dst, val, eb)
    assert len(got) == len(want) == 71
    for (gc, gn), (wc, wn) in zip(got, want):
        np.testing.assert_array_equal(gc[:nv], wc)
        np.testing.assert_array_equal(gn[:nv], wn)


@needs_native_reduce
def test_reduce_tier_chip_routing_on_chip_labeled_rows(tmp_path,
                                                       monkeypatch):
    """A TPU-backend process consults chip-labeled host_reduce rows
    (the in-window section measures the tunnel host's tiers): winning
    rows route the engine off the device path; cpu-labeled rows never
    do (VERDICT r4 item 4)."""
    import json

    import jax

    from gelly_streaming_tpu.ops import triangles as tri_ops
    from gelly_streaming_tpu.ops import windowed_reduce as wr

    perf = tmp_path / "PERF.json"
    monkeypatch.setattr(tri_ops, "_PERF_PATH", str(perf))
    monkeypatch.setattr(wr, "_REDUCE_IMPL", {})
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    rows = [{"name": "sum", "edge_bucket": 8192, "parity": True,
             "host_edges_per_s": 60_000_000,
             "device_edges_per_s": 200_000,
             "native_parity": True,
             "native_edges_per_s": 120_000_000}]
    try:
        perf.write_text(json.dumps(
            {"backend": "tpu", "host_reduce": rows}))
        assert wr._resolve_reduce_impl("sum") == "native"
        assert wr._resolve_reduce_impl(
            "sum", allow_native=False) == "host"
        # unmeasured monoid keeps the device path
        assert wr._resolve_reduce_impl("min") == "device"
        # the same rows labeled cpu must not drive a chip process
        wr._REDUCE_IMPL.clear()
        perf.write_text(json.dumps(
            {"backend": "cpu", "host_reduce": rows}))
        assert wr._resolve_reduce_impl("sum") == "device"
    finally:
        monkeypatch.undo()
        wr._REDUCE_IMPL.clear()

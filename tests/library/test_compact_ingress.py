"""Compact-ingress parity: the 4-bytes/slot uint16+counts wire format
(ops/compact_ingress.py) must reconstruct EXACTLY the arrays the
standard 9-bytes/slot format ships, and the compact stream program
must produce identical window counts — including ragged tails, empty
windows, hub-overflow recounts, and the id boundary at 65535."""

import numpy as np
import pytest

from gelly_streaming_tpu.ops import compact_ingress
from gelly_streaming_tpu.ops import segment as seg_ops
from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel


def _stream(n, v, seed):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, v, n).astype(np.int32)
    dst = rng.integers(0, v, n).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep]


def _reconstruct(s16, d16, nvalid, eb, vb):
    """Host-side mirror of the device widen/mask rebuild."""
    pos = np.arange(eb)[None, :]
    valid = pos < nvalid[:, None]
    s = np.where(valid, s16.astype(np.int64), vb).astype(np.int32)
    d = np.where(valid, d16.astype(np.int64), vb).astype(np.int32)
    return s, d, valid


@pytest.mark.parametrize("n,eb", [(100, 64), (257, 64), (64, 64),
                                  (1, 64), (4096, 512)])
def test_window_stack_parity(n, eb):
    vb = 256
    src, dst = _stream(n, vb, seed=n)
    num_w_std, s_std, d_std, v_std = seg_ops.window_stack(
        src, dst, eb, sentinel=vb)
    num_w, s16, d16, nvalid = compact_ingress.window_stack(src, dst, eb)
    assert num_w == num_w_std
    s, d, valid = _reconstruct(s16, d16, nvalid, eb, vb)
    np.testing.assert_array_equal(s, s_std)
    np.testing.assert_array_equal(d, d_std)
    np.testing.assert_array_equal(valid, v_std)


def test_stack_window_list_parity():
    vb = 512
    rng = np.random.default_rng(3)
    windows = []
    for k in (0, 1, 17, 64):
        ws = rng.integers(0, vb, k).astype(np.int32)
        wd = rng.integers(0, vb, k).astype(np.int32)
        windows.append((ws, wd))
    s_std, d_std, v_std = seg_ops.stack_window_list(windows, 64,
                                                    sentinel=vb)
    s16, d16, nvalid = compact_ingress.stack_window_list(windows, 64)
    s, d, valid = _reconstruct(s16, d16, nvalid, 64, vb)
    np.testing.assert_array_equal(s, s_std)
    np.testing.assert_array_equal(d, d_std)
    np.testing.assert_array_equal(valid, v_std)


def test_stack_window_list_oversize_raises():
    with pytest.raises(ValueError):
        compact_ingress.stack_window_list(
            [(np.zeros(65, np.int32), np.zeros(65, np.int32))], 64)


def test_pad_chunk_parity():
    vb, eb, n = 128, 32, 517
    src, dst = _stream(n, vb, seed=9)
    _, s_std, d_std, v_std = seg_ops.window_stack(src, dst, eb,
                                                  sentinel=vb)
    num_w, s16, d16, nvalid = compact_ingress.window_stack(src, dst, eb)
    for at, hi, max_w in [(0, 8, 8), (8, num_w, 8), (0, num_w, 32),
                          (0, 3, 8)]:
        hi = min(hi, num_w)
        sc_s, dc_s, vc_s, n_s = seg_ops.pad_window_chunk(
            s_std, d_std, v_std, at, hi, max_w, eb, vb)
        sc, dc, nv, n_c = compact_ingress.pad_chunk(
            s16, d16, nvalid, at, hi, max_w, eb)
        assert n_c == n_s
        s, d, valid = _reconstruct(sc, dc, nv, eb, vb)
        np.testing.assert_array_equal(s, sc_s)
        np.testing.assert_array_equal(d, dc_s)
        np.testing.assert_array_equal(valid, vc_s)


def test_supports_boundary():
    assert compact_ingress.supports(65536)
    assert compact_ingress.supports(4)
    assert not compact_ingress.supports(65537)
    assert not compact_ingress.supports(1 << 20)


def test_compact_pin_rejects_wide_vertex_bucket():
    """An explicit compact pin with ids wider than uint16 must be an
    ERROR, not a silent id-wrapping miscount."""
    with pytest.raises(ValueError):
        TriangleWindowKernel(edge_bucket=256, vertex_bucket=1 << 17,
                             ingress="compact")


def test_compact_stream_counts_match_device_path(monkeypatch):
    """End-to-end: the compact program's counts == the standard device
    path's counts == the escalating per-window kernel, on a stream
    sized to produce ragged tails and nonzero triangles."""
    from gelly_streaming_tpu.ops import triangles as tri_mod

    # pin the device tier: count_windows must exercise the compact
    # DEVICE path even where committed CPU evidence selects a host tier
    monkeypatch.setattr(tri_mod, "_STREAM_IMPL", "device")

    vb, eb, n = 128, 256, 2400  # 10 windows with a 96-edge ragged tail
    src, dst = _stream(n, vb, seed=21)
    # the baseline is PINNED standard: committed winning ingress_ab
    # rows must not silently turn this into compact-vs-compact
    kernel = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                  ingress="standard")
    std = kernel._count_stream_device(src, dst)

    # the kernel's integrated compact path, on both dispatch surfaces
    k_cmp = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                 ingress="compact")
    assert k_cmp._count_stream_device(src, dst) == std
    # multi-chunk form: 10 windows through 3-window chunks exercises
    # the prefetch producer thread + ragged-tail padding on the
    # COMPACT wire format (the single-chunk default skips the thread)
    k_cmp.MAX_STREAM_WINDOWS = 3
    assert k_cmp._count_stream_device(src, dst) == std
    k_cmp.MAX_STREAM_WINDOWS = _tuned = TriangleWindowKernel(
        edge_bucket=eb, vertex_bucket=vb).MAX_STREAM_WINDOWS
    windows = [(src[s:s + eb], dst[s:s + eb])
               for s in range(0, len(src), eb)]
    assert k_cmp.count_windows(windows) == std
    # cross-check against the per-window escalating path
    per_window = [
        kernel.count(src[s:s + kernel.eb], dst[s:s + kernel.eb])
        for s in range(0, len(src), kernel.eb)
    ]
    assert std == per_window


def test_compact_stream_id_65535():
    """The top uint16 id must survive the round trip (padded slots use
    0 + mask, NOT a u16 sentinel, so 65535 stays a real id)."""
    import jax
    import jax.numpy as jnp

    vb = 65536
    eb = 64
    # a triangle among the three highest representable ids
    src = np.array([65535, 65534, 65533], np.int32)
    dst = np.array([65534, 65533, 65535], np.int32)
    kernel = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
    run = jax.jit(compact_ingress.build_stream_fn(
        kernel._fns[kernel.kb], kernel.vb, kernel.eb))
    num_w, s16, d16, nvalid = compact_ingress.window_stack(src, dst, eb)
    c, o = run(jnp.asarray(s16), jnp.asarray(d16), jnp.asarray(nvalid))
    assert int(np.array(o)[0]) == 0
    assert int(np.array(c)[0]) == 1


def test_compact_parity_at_vb_65536_boundary():
    """vb=65536 is the LAST supported bucket (ids ≤ 65535 fit uint16):
    end-to-end counts through the compact kernel must match the
    standard path there, with real ids at the top of the range."""
    vb, eb = 65536, 64
    rng = np.random.default_rng(44)
    # ids clustered at the top of the uint16 range + a known triangle
    src = np.concatenate([
        rng.integers(65000, vb, 200),
        np.array([65535, 65534, 65533])]).astype(np.int32)
    dst = np.concatenate([
        rng.integers(65000, vb, 200),
        np.array([65534, 65533, 65535])]).astype(np.int32)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    std = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                               ingress="standard")
    cmp_ = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                ingress="compact")
    want = std._count_stream_device(src, dst)
    assert cmp_._count_stream_device(src, dst) == want
    assert sum(want) > 0


def test_vb_gate_falls_back_to_standard_everywhere(tmp_path,
                                                   monkeypatch):
    """With committed WINNING ingress_ab rows, every engine adopts
    compact — except when supports(vb) is false (vb > 65536), where
    each resolves standard instead of wrapping ids."""
    import json

    import jax

    from gelly_streaming_tpu.ops import triangles as tri_mod
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    perf = tmp_path / "PERF.json"
    perf.write_text(json.dumps({
        "backend": jax.default_backend(),
        "ingress_ab": [{"probe": "stream_ab", "parity": True,
                        "speedup": 1.5}]}))
    monkeypatch.setattr(tri_mod, "_PERF_PATH", str(perf))
    monkeypatch.setattr(tri_mod, "_INGRESS", None)
    try:
        small = dict(edge_bucket=64, vertex_bucket=256)
        big = dict(edge_bucket=64, vertex_bucket=1 << 17)
        assert TriangleWindowKernel(**small).ingress == "compact"
        assert TriangleWindowKernel(**big).ingress == "standard"
        assert StreamSummaryEngine(**small).ingress == "compact"
        assert StreamSummaryEngine(**big).ingress == "standard"
        assert WindowedEdgeReduce(vertex_bucket=256,
                                  edge_bucket=64).ingress == "compact"
        assert WindowedEdgeReduce(vertex_bucket=1 << 17,
                                  edge_bucket=64).ingress == "standard"
        # an explicit compact pin past the gate is an ERROR everywhere
        with pytest.raises(ValueError):
            StreamSummaryEngine(ingress="compact", **big)
        with pytest.raises(ValueError):
            WindowedEdgeReduce(vertex_bucket=1 << 17, edge_bucket=64,
                               ingress="compact")
    finally:
        monkeypatch.undo()
        tri_mod._INGRESS = None


def test_compact_reduce_rejects_out_of_range_ids():
    """Ids the uint16 cast would wrap must fail as loudly through the
    compact reduce prep as the host tier does."""
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    eng = WindowedEdgeReduce(vertex_bucket=256, edge_bucket=64,
                             name="sum", direction="out",
                             ingress="compact")
    ok = np.array([1], np.int64)
    for bad in (np.array([70000], np.int64),
                np.array([-3], np.int64)):  # both wrap through uint16
        # plain ValueError, same as every other tier (validated on the
        # main thread, never wrapped by the pipeline's PrepError)
        with pytest.raises(ValueError, match="outside \\[0"):
            eng._device_process_stream(bad, ok, np.ones(1, np.int32))


def test_compact_overflow_recount_exact():
    """A hub whose oriented degree overflows the pinned K must be
    recounted exactly through the compact dispatch path (the shared
    _run_stack_loop recount branch)."""
    vb, eb = 256, 128
    # star around vertex 0 + closing edges -> many triangles at the hub
    hub_deg = 60
    src = np.concatenate([np.zeros(hub_deg, np.int64),
                          np.arange(1, hub_deg, dtype=np.int64)])
    dst = np.concatenate([np.arange(1, hub_deg + 1, dtype=np.int64),
                          np.arange(2, hub_deg + 1, dtype=np.int64)])
    src = src.astype(np.int32)[:eb]
    dst = dst.astype(np.int32)[:eb]
    k_std = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                 k_bucket=4, ingress="standard")
    k_cmp = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                 k_bucket=4, ingress="compact")
    want = [k_std.count(src, dst)]  # escalating exact path
    assert k_std._count_stream_device(src, dst) == want
    assert k_cmp._count_stream_device(src, dst) == want
    assert want[0] > 0

"""Algorithm ITs — parity with the reference's example tests
(ConnectedComponentsTest.java, BipartitenessCheckTest.java,
NonBipartitnessCheckTest.java), run through both the host and the
device (TPU kernel) variants of each algorithm.
"""

import re

import pytest

from gelly_streaming_tpu import Edge, NULL, SimpleEdgeStream
from gelly_streaming_tpu.core.types import text_line
from gelly_streaming_tpu.models import (BipartitenessCheck,
                                        ConnectedComponents,
                                        TpuBipartitenessCheck,
                                        TpuConnectedComponents)

CC_EDGES = [
    # reference: ConnectedComponentsTest.java:31-38
    Edge(1, 2, NULL), Edge(1, 3, NULL), Edge(2, 3, NULL),
    Edge(1, 5, NULL), Edge(6, 7, NULL), Edge(8, 9, NULL),
]

BIPARTITE_EDGES = [
    # reference: BipartitenessCheckTest.java:27-34
    Edge(1, 2, NULL), Edge(1, 3, NULL), Edge(1, 4, NULL),
    Edge(4, 5, NULL), Edge(4, 7, NULL), Edge(4, 9, NULL),
]

NON_BIPARTITE_EDGES = [
    # reference: NonBipartitnessCheckTest.java:27-34 (odd cycle 1-2-3)
    Edge(1, 2, NULL), Edge(2, 3, NULL), Edge(3, 1, NULL),
    Edge(4, 5, NULL), Edge(5, 7, NULL), Edge(4, 1, NULL),
]


def _run(env, algorithm, edges):
    graph = SimpleEdgeStream(env.from_collection(edges), env)
    sink = graph.aggregate(algorithm).collect()
    env.execute()
    return [text_line(v) for v in env.results_of(sink)]


@pytest.mark.parametrize("algo_cls", [ConnectedComponents, TpuConnectedComponents])
def test_connected_components(env, algo_cls):
    lines = _run(env, algo_cls(5), CC_EDGES)
    # the final combine result is the last line
    # (reference parser: ConnectedComponentsTest.java:43-57 takes the last
    # line and counts its [component] groups; expected 3 components)
    final = lines[-1]
    groups = re.findall(r"\[([^\]]*)\]", final)
    comps = sorted(sorted(int(x) for x in g.split(",")) for g in groups)
    assert comps == [[1, 2, 3, 5], [6, 7], [8, 9]]


@pytest.mark.parametrize("algo_cls", [BipartitenessCheck, TpuBipartitenessCheck])
def test_bipartiteness_positive(env, algo_cls):
    lines = _run(env, algo_cls(500), BIPARTITE_EDGES)
    # exact golden string (reference: BipartitenessCheckTest.java:18-20)
    assert lines == [
        "(true,{1={1=(1,true), 2=(2,false), 3=(3,false), 4=(4,false), "
        "5=(5,true), 7=(7,true), 9=(9,true)}})"
    ]


@pytest.mark.parametrize("algo_cls", [BipartitenessCheck, TpuBipartitenessCheck])
def test_bipartiteness_negative(env, algo_cls):
    lines = _run(env, algo_cls(500), NON_BIPARTITE_EDGES)
    # exact golden string (reference: NonBipartitnessCheckTest.java:18-19)
    assert lines == ["(false,{})"]


def test_cc_incremental_windows():
    """Multiple merge windows: the merger emits an improving global state
    per window partial (GraphAggregation.java:104-116 eager semantics)."""
    from gelly_streaming_tpu import (AscendingTimestampExtractor,
                                     StreamEnvironment)

    env = StreamEnvironment()
    edges = [Edge(1, 2, 10), Edge(3, 4, 20), Edge(2, 3, 150)]
    graph = SimpleEdgeStream(
        env.from_collection(edges), env,
        timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
    )
    sink = graph.aggregate(ConnectedComponents(100)).collect()
    env.execute()
    states = env.results_of(sink)
    assert len(states) == 2
    comps0 = sorted(sorted(m) for m in states[0].components().values())
    comps1 = sorted(sorted(m) for m in states[1].components().values())
    assert comps0 == [[1, 2], [3, 4]]
    assert comps1 == [[1, 2, 3, 4]]


def test_carried_labels_merge_through_non_root_members():
    """Regression: merging two flat label forests via an edge between
    NON-root members must relabel the losing component's untouched
    members (Shiloach-Vishkin root hook in ops/unionfind.cc_round).
    Without the hook, vertex 1 below keeps label 1 forever."""
    import numpy as np

    from gelly_streaming_tpu.ops import unionfind

    # two converged flat forests: {0,5}->0 and {1,6}->1
    labels = np.array([0, 1, 2, 3, 4, 0, 1, 7], np.int32)
    out = unionfind.connected_components_with_labels(
        np.array([5]), np.array([6]), labels, 8)
    assert list(out[[0, 1, 5, 6]]) == [0, 0, 0, 0]


def test_carried_labels_concurrent_merge_island_split():
    """Regression: an old root merging into TWO trees in one round must
    not strand the larger-label island. Carried forest {3:root,4:child}
    and {1:root,5:child}; batch edges (4,1) and (3,0): without forest
    links in the rounds, {1,4,5} keeps label 1 while 3 joins 0 —
    splitting one true component (ops/unionfind.cc_fixpoint)."""
    import numpy as np

    from gelly_streaming_tpu.ops import unionfind

    labels = np.array([0, 1, 2, 3, 3, 1], np.int32)
    out = unionfind.connected_components_with_labels(
        np.array([4, 3]), np.array([1, 0]), labels, 6)
    assert list(out[[0, 1, 3, 4, 5]]) == [0, 0, 0, 0, 0]


def test_merger_correct_under_partial_disorder():
    """VERDICT r1 item 6: the parallelism-1 Merger funnel must stay
    correct when p>1 partition folds deliver their per-window partials
    interleaved and out of window order (the reference's non-blocking
    Merger makes exactly this guarantee: partials combine in ARRIVAL
    order, GraphAggregation.java:90-117). A naive merger that replaced
    state with the newest partial, or assumed window-ordered arrival,
    fails this test."""
    import copy
    import itertools
    import random

    agg = ConnectedComponents(1000)

    # 3 partitions x 3 windows of edges: a chain that only fully
    # connects once EVERY partial has merged, plus stable islands
    windows = {
        (0, 0): [(1, 2), (3, 4)],
        (1, 0): [(5, 6)],
        (2, 0): [(2, 3)],          # bridges {1,2} and {3,4}
        (0, 1): [(7, 8)],
        (1, 1): [(4, 5)],          # bridges {1..4} and {5,6}
        (2, 1): [(9, 10)],
        (0, 2): [(6, 7)],          # bridges {1..6} and {7,8}
        (1, 2): [(11, 12)],
        (2, 2): [(10, 11)],        # bridges {9,10} and {11,12}
    }

    def fold(edge_list):
        state = copy.deepcopy(agg.initial_value)
        for s, t in edge_list:
            state = agg.update_fun(state, s, t, None)
        return state

    def comps(ds):
        groups = {}
        for v in ds.get_matches():
            groups.setdefault(ds.find(v), set()).add(v)
        return frozenset(frozenset(g) for g in groups.values())

    want_final = frozenset({frozenset(range(1, 9)),
                            frozenset(range(9, 13))})

    orders = [sorted(windows), sorted(windows, reverse=True),
              sorted(windows, key=lambda pw: (-pw[1], pw[0]))]
    rng = random.Random(13)
    for _ in range(4):
        perm = list(windows)
        rng.shuffle(perm)
        orders.append(perm)

    for order in orders:
        merger = agg.make_merger()
        emitted = []
        for key in order:
            # deepcopy: each delivery is an independent partial, as if
            # serialized across the funnel's network boundary
            merger(fold(copy.deepcopy(windows[key])), emitted.append)
        assert len(emitted) == len(windows)
        assert comps(emitted[-1]) == want_final, order
        # improving stream: once two vertices share a component they
        # must share one in every later emission
        for earlier, later in itertools.combinations(emitted, 2):
            for group in comps(earlier):
                for a, b in itertools.combinations(sorted(group), 2):
                    if (a in later.get_matches()
                            and b in later.get_matches()):
                        assert later.find(a) == later.find(b), order


@pytest.mark.parametrize("seed", range(4))
def test_cc_and_bipartiteness_fuzz_host_vs_device(seed):
    """Random graphs through the full aggregate() path: the Tpu*
    variants (array union-find / double cover) must reach the same
    FINAL answer as the host-parity forms (DisjointSet / Candidates) —
    same component partition, same bipartiteness verdict — on graphs
    where the golden fixtures' shapes don't apply."""
    import numpy as np

    from gelly_streaming_tpu import ManualClock, StreamEnvironment

    rng = np.random.default_rng(seed)
    v = int(rng.integers(6, 40))
    e = int(rng.integers(v, 4 * v))
    edges = [Edge(int(a) + 1, int(b) + 1, NULL)
             for a, b in zip(rng.integers(0, v, e),
                             rng.integers(0, v, e)) if a != b]
    if not edges:
        edges = [Edge(1, 2, NULL)]

    def final_components(algo_cls):
        env = StreamEnvironment(clock=ManualClock(0))
        lines = _run(env, algo_cls(5), edges)
        groups = re.findall(r"\[([^\]]*)\]", lines[-1])
        return sorted(sorted(int(x) for x in g.split(","))
                      for g in groups)

    assert final_components(ConnectedComponents) == \
        final_components(TpuConnectedComponents)

    def verdict(algo_cls):
        env = StreamEnvironment(clock=ManualClock(0))
        lines = _run(env, algo_cls(500), edges)
        return lines[-1].startswith("(true")

    host_v = verdict(BipartitenessCheck)
    assert host_v == verdict(TpuBipartitenessCheck)

    # cross-check against an independent BFS 2-coloring oracle
    adj = {}
    for ed in edges:
        adj.setdefault(ed.source, set()).add(ed.target)
        adj.setdefault(ed.target, set()).add(ed.source)
    color, ok = {}, True
    for start in adj:
        if start in color:
            continue
        color[start] = 0
        queue = [start]
        while queue and ok:
            u = queue.pop()
            for w in adj[u]:
                if w not in color:
                    color[w] = color[u] ^ 1
                    queue.append(w)
                elif color[w] == color[u]:
                    ok = False
                    break
    assert host_v == ok


def test_merger_correct_under_true_thread_concurrency():
    """VERDICT r4 item 7: the reference's operation ITs run on a
    multi-threaded mini-cluster (TestSlice.java:39), so the
    parallelism-1 Merger funnel must consume partials produced by
    GENUINELY concurrent subtask threads, not just a shuffled
    single-threaded delivery. Four producer threads fold their
    partition's windows and push partials through a queue with no
    ordering coordination (the funnel's network boundary,
    WindowGraphAggregation.java:54-58); the single consumer merges in
    arrival order. Every run must reach the same final component set
    and keep the emission stream improving, for any interleaving the
    scheduler produces."""
    import copy
    import itertools
    import queue
    import threading

    agg = ConnectedComponents(1000)

    partitions = {
        0: [[(1, 2), (3, 4)], [(7, 8)], [(6, 7)]],
        1: [[(5, 6)], [(4, 5)], [(11, 12)]],
        2: [[(2, 3)], [(9, 10)], [(10, 11)]],
        3: [[(12, 13)], [(8, 9)], [(13, 14)]],
    }
    num_partials = sum(len(w) for w in partitions.values())
    want_final = frozenset({frozenset(range(1, 15))})

    def fold(edge_list):
        state = copy.deepcopy(agg.initial_value)
        for s, t in edge_list:
            state = agg.update_fun(state, s, t, None)
        return state

    def comps(ds):
        groups = {}
        for v in ds.get_matches():
            groups.setdefault(ds.find(v), set()).add(v)
        return frozenset(frozenset(g) for g in groups.values())

    for _ in range(8):   # several runs: let the scheduler vary arrival
        q = queue.Queue()

        def producer(wins):
            for w in wins:
                q.put(fold(copy.deepcopy(w)))

        threads = [threading.Thread(target=producer, args=(w,))
                   for w in partitions.values()]
        for t in threads:
            t.start()
        merger = agg.make_merger()
        emitted = []
        for _ in range(num_partials):     # single consumer, arrival order
            merger(q.get(timeout=30), emitted.append)
        for t in threads:
            t.join(timeout=30)
        assert len(emitted) == num_partials
        assert comps(emitted[-1]) == want_final
        # improving stream under every real interleaving
        for earlier, later in itertools.combinations(emitted, 2):
            for group in comps(earlier):
                for a, b in itertools.combinations(sorted(group), 2):
                    if (a in later.get_matches()
                            and b in later.get_matches()):
                        assert later.find(a) == later.find(b)

"""Fused analytics scan = per-window driver analytics, chunk after
chunk, including triangle hub overflow and carried state."""

import numpy as np

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine


def test_scan_matches_driver_per_window():
    rng = np.random.default_rng(17)
    n, v, eb = 2000, 300, 256
    src = rng.integers(0, v, n)
    dst = rng.integers(0, v, n)

    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=v)
    # two process() calls: carried state must persist across chunks
    got = eng.process(src[:1024], dst[:1024]) + eng.process(src[1024:],
                                                            dst[1024:])

    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                   vertex_bucket=v)
    want = drv.run_arrays(src, dst)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        nv = len(w.vertex_ids)
        assert g["triangles"] == w.triangles
        assert g["max_degree"] == int(w.degrees.max())
        assert g["odd_cycle"] == bool(w.bipartite_odd[:nv].any())
        assert g["num_components"] == len(np.unique(w.cc_labels[:nv]))

    deg, labels, odd = eng.state()
    # driver slots are first-sight order == id order here? not
    # necessarily: compare degree multiset and final component count
    assert sorted(deg[deg > 0]) == sorted(
        want[-1].degrees[want[-1].degrees > 0])


def test_scan_triangle_overflow_recounted():
    eng = StreamSummaryEngine(edge_bucket=1024, vertex_bucket=128,
                              k_bucket=8)
    src, dst = [], []
    for u in range(1, 41):  # 40-clique overflows k=8
        for v in range(u + 1, 41):
            src.append(u)
            dst.append(v)
    out = eng.process(np.array(src), np.array(dst))
    from gelly_streaming_tpu.ops import triangles as tri_ops

    assert out[0]["triangles"] == tri_ops.triangle_count_sparse(
        np.array(src), np.array(dst), 128)
    assert out[0]["odd_cycle"]  # cliques >= 3 have odd cycles


def test_scan_empty_and_reset():
    eng = StreamSummaryEngine(edge_bucket=64, vertex_bucket=16)
    assert eng.process(np.array([]), np.array([])) == []
    out = eng.process(np.array([0, 1]), np.array([1, 2]))
    assert out[0]["num_components"] == 1
    eng.reset()
    deg, labels, odd = eng.state()
    assert deg.sum() == 0 and not odd.any()


def test_scan_partial_window_must_be_final():
    eng = StreamSummaryEngine(edge_bucket=64, vertex_bucket=16)
    eng.process(np.array([0, 1, 2]), np.array([1, 2, 3]))  # partial: closes
    import pytest

    with pytest.raises(ValueError, match="partial window"):
        eng.process(np.array([4]), np.array([5]))
    eng.reset()
    assert eng.process(np.array([4]), np.array([5]))  # fine after reset

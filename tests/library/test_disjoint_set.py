"""DisjointSet unit tests — parity with the reference's only pure unit
test (DisjointSetTest.java:31-77)."""

from gelly_streaming_tpu.utils.disjoint_set import DisjointSet


def _even_odd_set():
    ds = DisjointSet()
    for i in range(8):
        ds.union(i, i + 2)
    return ds


def test_get_matches_size():
    assert len(_even_odd_set().get_matches()) == 10


def test_find_two_parities():
    ds = _even_odd_set()
    root1, root2 = ds.find(0), ds.find(1)
    assert root1 != root2
    for i in range(10):
        assert ds.find(i) == (root1 if i % 2 == 0 else root2)


def test_merge():
    ds = _even_odd_set()
    ds2 = DisjointSet()
    for i in range(8):
        ds2.union(i, i + 100)
    ds2.merge(ds)
    assert len(ds2.get_matches()) == 18
    roots = {ds2.find(e) for e in ds2.get_matches()}
    assert len(roots) == 2


def test_repr_component_format():
    ds = DisjointSet()
    ds.union(1, 2)
    ds.union(8, 9)
    # reference toString prints {root=[members...]} (DisjointSet.java:139-153)
    assert repr(ds) == "{1=[1, 2], 8=[8, 9]}"

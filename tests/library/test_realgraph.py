"""Calibration of the cit-HepPh-shaped citation stream (VERDICT r2
missing-3: real-dataset validation without egress — the generator is
held to the dataset's PUBLISHED summary statistics).

The full-size test generates the complete 421,578-edge stream and
checks the SNAP anchors; it runs in a few seconds (generation ~1s,
exact set-intersection stats ~2s).
"""

import numpy as np
import pytest

from gelly_streaming_tpu.utils.realgraph import (
    CIT_HEPPH_AVG_CLUSTERING, CIT_HEPPH_EDGES, CIT_HEPPH_NODES,
    CIT_HEPPH_TRIANGLES, citation_stream, indegree_powerlaw_alpha,
    undirected_stats)


@pytest.fixture(scope="module")
def full_stream():
    return citation_stream()


def test_exact_node_and_edge_counts(full_stream):
    src, dst, ts = full_stream
    assert len(src) == CIT_HEPPH_EDGES
    assert int(max(src.max(), dst.max())) == CIT_HEPPH_NODES - 1
    # every paper cites or is cited (the SNAP graph's nodes all appear)
    assert len(np.union1d(src, dst)) == CIT_HEPPH_NODES


def test_stream_shape_contract(full_stream):
    """DAG with strictly increasing timestamps and no self-loops — the
    event-time ingestion contract every downstream path assumes."""
    src, dst, ts = full_stream
    assert (src > dst).all()            # citations point backwards
    assert (ts[1:] > ts[:-1]).all()


def test_published_clustering_and_triangles(full_stream):
    """Published anchors: 1,276,868 triangles, average clustering
    0.2848. The calibrated generator lands within 5% on clustering and
    10% on triangles (seed-pinned: the achieved values are ~0.2851 and
    ~1,315,736)."""
    src, dst, _ = full_stream
    tri, avg_cc, deg = undirected_stats(src, dst, CIT_HEPPH_NODES)
    assert abs(avg_cc - CIT_HEPPH_AVG_CLUSTERING) \
        <= 0.05 * CIT_HEPPH_AVG_CLUSTERING
    assert abs(tri - CIT_HEPPH_TRIANGLES) <= 0.10 * CIT_HEPPH_TRIANGLES


def test_degree_tail_powerlaw(full_stream):
    """SNAP publishes no max degree for cit-HepPh, so the degree tail
    is anchored by the in-degree power-law exponent instead: citation
    networks report α ≈ 2-3.5; the seed-pinned generated value is
    ~2.19. Max degree is asserted only as a deterministic sanity band
    (hubby but nowhere near star-graph degeneracy)."""
    src, dst, _ = full_stream
    alpha = indegree_powerlaw_alpha(dst, CIT_HEPPH_NODES)
    assert 1.8 <= alpha <= 3.5
    _, _, deg = undirected_stats(src, dst, CIT_HEPPH_NODES)
    # seed-pinned max degree is 17,985 (~52% of N — the PA urn is
    # hubbier than the real graph, which the α band already bounds)
    assert 1_000 <= int(deg.max()) <= int(0.55 * CIT_HEPPH_NODES)


def test_small_instances_keep_exact_edge_budget():
    """The quota bookkeeping (survey stratum + early-paper deficit
    redistribution) must hit the requested edge count exactly at any
    size, not just the calibrated one."""
    for n, e in ((50, 300), (200, 2000), (1000, 12_000)):
        src, dst, ts = citation_stream(num_papers=n, num_edges=e,
                                       seed=3)
        assert len(src) == e, (n, e, len(src))
        assert (src > dst).all()

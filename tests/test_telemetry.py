"""Flight-recorder suite (utils/telemetry + tools/trace_report.py):

- span nesting and chunk correlation, including across the ingress
  pipeline's worker threads (thread-locals don't cross the pool — the
  chunk ctx handle does);
- ring-buffer bounds (GS_TRACE_RING);
- durable flush on the simulated fatal kill (the utils/faults
  fatal-kill hook), proving the crash-safe ledger contract the chaos
  soak asserts end-to-end;
- Perfetto/Chrome trace export well-formedness;
- `GS_TELEMETRY=0` digest parity on the 524K/32768 CPU row (the
  zero-overhead contract: armed vs disarmed counts are bit-identical);
- nearest-rank percentile math against known samples;
- the StepTimer adapter: report()/event_log() unchanged, spans
  forwarded when armed.
"""

import hashlib
import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from gelly_streaming_tpu.utils import faults, telemetry
from gelly_streaming_tpu.utils.tracing import StepTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_report = _load_trace_report()


@pytest.fixture
def armed(tmp_path, monkeypatch):
    """Recorder armed with a ledger dir; reset before AND after so no
    state (or open ledger handle) leaks across tests."""
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    yield str(tmp_path)
    telemetry.reset()


def _stream(num_edges, num_vertices, seed=7):
    from bench import make_stream

    return make_stream(num_edges, num_vertices, seed)


# ----------------------------------------------------------------------
# span nesting & correlation
# ----------------------------------------------------------------------
def test_span_nesting_same_thread(armed):
    with telemetry.span("outer") as outer:
        with telemetry.span("inner"):
            pass
    recs = {r["name"]: r for r in telemetry.records()}
    assert recs["inner"]["par"] == recs["outer"]["sid"]
    assert "par" not in recs["outer"]  # top-level span: no parent
    assert outer.elapsed > 0
    assert recs["inner"]["trace"] == recs["outer"]["trace"] \
        == telemetry.trace_id()


def test_chunk_ctx_links_across_threads(armed):
    ctx = telemetry.chunk_ctx(7)

    def worker():
        t0 = telemetry.clock()
        telemetry.record_span("ingress.prep", t0, 0.001,
                              parent=ctx["sid"], chunk=ctx["chunk"])

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    telemetry.close_chunk(ctx)
    recs = telemetry.records()
    prep = next(r for r in recs if r["name"] == "ingress.prep")
    chunk = next(r for r in recs if r["name"] == "ingress.chunk")
    assert prep["par"] == chunk["sid"] == ctx["sid"]
    assert prep["a"]["chunk"] == chunk["a"]["chunk"] == 7
    assert prep["tid"] != chunk["tid"]  # recorded from the worker


def test_pipeline_spans_correlate(armed):
    """The real thing: a fused-scan engine fed multiple chunks through
    the worker-pool ingress pipeline produces one chunk span per
    chunk, with the worker-side prep/h2d spans parented to it."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eng = StreamSummaryEngine(edge_bucket=1024, vertex_bucket=2048)
    eng.MAX_WINDOWS = 2  # several chunks → the pool engages
    src, dst = _stream(8 * 1024, 1024, seed=3)
    eng.process(src, dst)
    recs = telemetry.records()
    names = {r["name"] for r in recs}
    assert {"ingress.prep", "ingress.h2d", "ingress.dispatch",
            "ingress.finalize", "ingress.chunk"} <= names
    chunks = {r["sid"] for r in recs if r["name"] == "ingress.chunk"}
    assert len(chunks) >= 4
    preps = [r for r in recs if r["name"] == "ingress.prep"]
    assert preps
    for r in preps:
        assert r.get("par") in chunks
    # dispatch/finalize carry the same chunk correlation ids
    for r in recs:
        if r["name"] in ("ingress.dispatch", "ingress.finalize"):
            assert r.get("par") in chunks


def test_context_binds_correlation_attrs(armed):
    with telemetry.context(window=42):
        telemetry.event("probe")
        with telemetry.span("work", edges=10):
            pass
    ev = next(r for r in telemetry.records() if r["name"] == "probe")
    sp = next(r for r in telemetry.records() if r["name"] == "work")
    assert ev["a"]["window"] == 42
    assert sp["a"] == {"window": 42, "edges": 10}
    telemetry.event("after")
    after = next(r for r in telemetry.records()
                 if r["name"] == "after")
    assert "a" not in after  # the binding ended with the scope


# ----------------------------------------------------------------------
# ring bounds
# ----------------------------------------------------------------------
def test_unwritable_trace_dir_degrades_to_ring(monkeypatch):
    # a broken ledger disk must never take down the stream it traces:
    # recording degrades to ring-only, durable events + flush are no-ops
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", "/proc/no_such_dir/traces")
    telemetry.reset()
    try:
        telemetry.event("selection.fallback", durable=True,
                        component="test", fallback="x")
        with telemetry.span("s"):
            pass
        telemetry.flush()
        assert telemetry.ledger_path() is None
        # the ring still saw everything
        assert [r["name"] for r in telemetry.records()] == [
            "selection.fallback", "s"]
    finally:
        telemetry.reset()


# ----------------------------------------------------------------------
def test_ring_buffer_bounds(monkeypatch):
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.delenv("GS_TRACE_DIR", raising=False)
    monkeypatch.setenv("GS_TRACE_RING", "32")
    telemetry.reset()
    try:
        for i in range(200):
            with telemetry.span("s%d" % (i % 3), i=i):
                pass
        recs = telemetry.records()
        assert len(recs) == 32
        # the ring keeps the NEWEST records
        assert recs[-1]["a"]["i"] == 199
        # ...while the aggregates saw everything
        assert sum(r["count"] for r in telemetry.summary()) == 200
    finally:
        telemetry.reset()


# ----------------------------------------------------------------------
# durability
# ----------------------------------------------------------------------
def test_durable_event_hits_disk_immediately(armed):
    with telemetry.span("buffered"):
        pass
    telemetry.event("tier_demotion", durable=True, window=3)
    recs = trace_report.load(telemetry.ledger_path())
    names = [r.get("name") for r in recs]
    assert "tier_demotion" in names       # durable: on disk, no flush
    assert "buffered" not in names        # ring-only until a flush
    telemetry.flush()
    names = [r.get("name")
             for r in trace_report.load(telemetry.ledger_path())]
    assert "buffered" in names
    # a flush never duplicates the already-written durable event
    assert names.count("tier_demotion") == 1


def test_durable_flush_on_simulated_fatal(armed):
    """The utils/faults fatal-kill hook: a fatal InjectedFault flushes
    the ring before raising, so the post-kill ledger holds the
    pre-kill spans — the flight-recorder contract."""
    for i in range(10):
        with telemetry.span("work", i=i):
            pass
    with pytest.raises(faults.InjectedFault):
        with faults.inject(faults.FaultSpec(site="dispatch",
                                            fatal=True)):
            faults.fire("dispatch")
    recs = trace_report.load(telemetry.ledger_path())
    spans = [r for r in recs if r.get("t") == "span"
             and r["name"] == "work"]
    assert len(spans) == 10               # every pre-kill span on disk
    names = [r.get("name") for r in recs]
    assert "fatal" in names
    assert "fault_injected" in names
    # one trace id across the whole ledger
    trace = telemetry.trace_id()
    assert all(r.get("trace") == trace for r in recs
               if r.get("t") != "meta")
    assert any(r.get("trace") == trace for r in recs
               if r.get("t") == "meta")


def test_ledger_tolerates_torn_tail(armed, tmp_path):
    telemetry.event("resume", durable=True, windows_done=4)
    path = telemetry.ledger_path()
    with open(path, "a") as f:
        f.write('{"t": "span", "name": "torn')  # the crash mid-append
    recs = trace_report.load(path)
    assert any(r.get("name") == "resume" for r in recs)
    assert not any(r.get("name") == "torn" for r in recs)


# ----------------------------------------------------------------------
# Perfetto export
# ----------------------------------------------------------------------
def test_perfetto_export_well_formed(armed):
    with telemetry.span("a", edges=100):
        with telemetry.span("b"):
            pass
    telemetry.event("resume", durable=True, windows_done=3)
    telemetry.counter("edges_seen", 100)
    telemetry.flush()
    recs = trace_report.load(telemetry.ledger_path())
    trace = json.loads(json.dumps(trace_report.to_perfetto(recs)))
    evs = trace["traceEvents"]
    assert evs and all({"name", "ph", "pid", "tid", "ts"} <= set(e)
                       for e in evs)
    complete = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"a", "b"}
    assert all(e["dur"] >= 0 for e in complete)
    assert any(e["ph"] == "i" and e["name"] == "resume" for e in evs)
    assert any(e["ph"] == "C" for e in evs)
    assert trace["otherData"]["trace"] == telemetry.trace_id()


# ----------------------------------------------------------------------
# the zero-overhead contract
# ----------------------------------------------------------------------
def test_disarmed_digest_parity_524k_row(monkeypatch, tmp_path):
    """GS_TELEMETRY=0 vs 1 on the 524K/32768 CPU bench row: counts
    are bit-identical (the recorder observes, never participates)."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    src, dst = _stream(524288, 65536)
    monkeypatch.setenv("GS_TELEMETRY", "0")
    telemetry.reset()
    kern = TriangleWindowKernel(edge_bucket=32768,
                                vertex_bucket=65536)
    base = kern.count_stream(src, dst)
    assert telemetry.records() == []      # disarmed: nothing recorded
    monkeypatch.setenv("GS_TELEMETRY", "1")
    monkeypatch.setenv("GS_TRACE_DIR", str(tmp_path))
    telemetry.reset()
    try:
        armed = kern.count_stream(src, dst)
    finally:
        recorded = bool(telemetry.records())
        telemetry.reset()
    digest = lambda c: hashlib.sha256(  # noqa: E731
        np.asarray(c, np.int64).tobytes()).hexdigest()
    assert digest(base) == digest(armed)
    assert recorded                       # armed: the row was observed


# ----------------------------------------------------------------------
# histogram math
# ----------------------------------------------------------------------
def test_percentile_math_known_samples():
    pct = telemetry.percentiles(list(range(1, 101)))
    assert pct == {50: 50.0, 95: 95.0, 99: 99.0}
    assert telemetry.percentiles([7]) == {50: 7.0, 95: 7.0, 99: 7.0}
    assert telemetry.percentiles([]) == {50: 0.0, 95: 0.0, 99: 0.0}
    # nearest-rank (ceil), order-independent
    assert telemetry.percentiles([4, 2, 3, 1], ps=(50,)) == {50: 2.0}
    assert telemetry.percentiles([1, 2, 3], ps=(50,)) == {50: 2.0}
    assert telemetry.percentiles([10, 20], ps=(99,)) == {99: 20.0}


def test_summary_rows_shape(armed):
    for _ in range(5):
        with telemetry.span("x"):
            pass
    with telemetry.span("y"):
        pass
    rows = {r["span"]: r for r in telemetry.summary()}
    assert rows["x"]["count"] == 5 and rows["y"]["count"] == 1
    for r in rows.values():
        assert {"span", "count", "total_ms", "p50_ms", "p95_ms",
                "p99_ms"} <= set(r)
        assert r["p50_ms"] <= r["p95_ms"] <= r["p99_ms"]


# ----------------------------------------------------------------------
# adapters
# ----------------------------------------------------------------------
def test_steptimer_adapter_armed(armed):
    t = StepTimer()
    with t.step("intern", 10):
        pass
    t.event("tier_demotion", {"window": 1})
    # legacy surface unchanged
    assert t.report()[0]["op"] == "intern"
    assert t.counts["intern"] == 1 and t.records["intern"] == 10
    assert t.event_log() == [{"event": "tier_demotion", "window": 1}]
    # and the recorder saw the step as a span
    spans = [r for r in telemetry.records()
             if r["name"] == "step.intern"]
    assert len(spans) == 1
    assert spans[0]["a"]["records"] == 10


def test_steptimer_disarmed_is_inert(monkeypatch):
    monkeypatch.setenv("GS_TELEMETRY", "0")
    telemetry.reset()
    t = StepTimer()
    with t.step("x", 1):
        pass
    assert t.counts["x"] == 1
    assert telemetry.records() == []


def test_resume_and_checkpoint_events(armed, tmp_path):
    """Driver checkpoint/resume stamps durable ledger events under
    the same trace — the crash-evidence pairing chaos_run asserts at
    soak scale."""
    from gelly_streaming_tpu.core.driver import (
        StreamingAnalyticsDriver)

    src, dst = _stream(4096, 512, seed=5)
    ckpt = str(tmp_path / "job.npz")

    def make():
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=1024, vertex_bucket=1024,
            analytics=("degrees", "cc"))

    drv = make()
    drv.enable_auto_checkpoint(ckpt, every_n_windows=2)
    drv.run_arrays(src, dst)
    drv2 = make()
    assert drv2.try_resume(ckpt)
    # both event classes are durable: readable with NO flush
    recs = trace_report.load(telemetry.ledger_path())
    names = {r.get("name") for r in recs}
    assert "checkpoint_saved" in names
    assert "resume" in names
    resume = next(r for r in recs if r.get("name") == "resume")
    assert resume["a"]["windows_done"] == drv2.windows_done

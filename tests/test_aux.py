"""Auxiliary subsystems: tracing, checkpoint/resume (SURVEY.md §5 build
items — all absent from the reference)."""

import numpy as np

from gelly_streaming_tpu import SimpleEdgeStream
from gelly_streaming_tpu.models.iterative_cc import \
    TpuIterativeConnectedComponents
from gelly_streaming_tpu.utils import checkpoint
from gelly_streaming_tpu.utils.candidates import Candidates, edge_to_candidate
from gelly_streaming_tpu.utils.disjoint_set import DisjointSet

from .conftest import long_long_edges


def test_tracing_reports_per_operator(env):
    env.enable_tracing()
    graph = SimpleEdgeStream(env.from_collection(long_long_edges()), env)
    sink = graph.get_degrees().collect()
    env.execute()
    report = env.trace_report()
    assert report, "tracing produced no rows"
    ops = {row["op"].split("#")[0] for row in report}
    assert "source" in ops and "flat_map" in ops
    total_records = sum(r["records"] for r in report)
    assert total_records > 0


def test_checkpoint_roundtrip_tree(tmp_path):
    tree = {
        "arr": np.arange(10, dtype=np.int32),
        "nested": {"f": 1.5, "s": "hello", "l": [1, 2, 3], "none": None},
        "tup": (np.ones(3), False),
    }
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, tree)
    back = checkpoint.restore(path)
    np.testing.assert_array_equal(back["arr"], tree["arr"])
    assert back["nested"] == tree["nested"]
    np.testing.assert_array_equal(back["tup"][0], tree["tup"][0])
    assert back["tup"][1] is False


def test_checkpoint_colliding_paths(tmp_path):
    """Keys whose flattened path strings coincide ("a.b" vs nested a→b,
    int 1 vs str "1") must survive independently."""
    tree = {
        "a": {"b": np.zeros(3, np.int32)},
        "a.b": np.ones(3, np.int32),
        1: np.full(2, 7, np.int32),
        "1": np.full(2, 9, np.int32),
        "x": [np.array([1])],
        "x[0]": np.array([2]),
    }
    path = str(tmp_path / "collide.npz")
    checkpoint.save(path, tree)
    back = checkpoint.restore(path)
    np.testing.assert_array_equal(back["a"]["b"], np.zeros(3))
    np.testing.assert_array_equal(back["a.b"], np.ones(3))
    np.testing.assert_array_equal(back[1], [7, 7])
    np.testing.assert_array_equal(back["1"], [9, 9])
    np.testing.assert_array_equal(back["x"][0], [1])
    np.testing.assert_array_equal(back["x[0]"], [2])


def test_disjoint_set_checkpoint():
    ds = DisjointSet()
    ds.union(1, 2)
    ds.union(2, 3)
    ds.union(8, 9)
    ds2 = DisjointSet()
    ds2.load_state_dict(ds.state_dict())
    assert repr(ds2) == repr(ds)
    # resumed state keeps merging correctly
    ds2.union(3, 8)
    assert len(ds2.components()) == 1


def test_candidates_checkpoint():
    cand = Candidates(True)
    cand = cand.merge(edge_to_candidate(1, 2))
    cand = cand.merge(edge_to_candidate(1, 3))
    cand2 = Candidates(True)
    cand2.load_state_dict(cand.state_dict())
    assert repr(cand2) == repr(cand)


def test_iterative_cc_checkpoint_resume(tmp_path):
    model = TpuIterativeConnectedComponents()
    model.process_batch(np.array([1, 3]), np.array([2, 4]))
    path = str(tmp_path / "cc.npz")
    checkpoint.save(path, model.state_dict())

    resumed = TpuIterativeConnectedComponents()
    resumed.load_state_dict(checkpoint.restore(path))
    changed = resumed.process_batch(np.array([2]), np.array([3]))
    assert dict(changed) == {3: 1, 4: 1}


def test_sharded_engine_checkpoint():
    from gelly_streaming_tpu.parallel.sharded import ShardedWindowEngine

    eng = ShardedWindowEngine(num_vertices_bucket=32)
    eng.degrees(np.array([1, 2]), np.array([2, 3]))
    state = eng.state_dict()
    eng2 = ShardedWindowEngine(num_vertices_bucket=32)
    eng2.load_state_dict(state)
    out = eng2.degrees(np.array([1]), np.array([2]))
    assert out[1] == 2 and out[2] == 3


def test_time_units_complete():
    """Flink Time surface: every unit form produces the same ms value
    (reference: org.apache.flink.streaming.api.windowing.time.Time)."""
    from gelly_streaming_tpu import Time

    assert Time.of(2, "minutes").milliseconds == 120_000
    assert Time.minutes(2).milliseconds == 120_000
    assert Time.hours(1).milliseconds == Time.of(1, "h").milliseconds \
        == 3_600_000
    assert Time.days(1).milliseconds == Time.of(24, "hours").milliseconds
    assert Time.seconds(3).milliseconds == Time.of(3000).milliseconds


def test_ingress_ab_parity_failure_is_evidence_not_a_crash(monkeypatch):
    """ADVICE r4: a parity failure between wire formats must commit a
    {parity: false} row (which rows_clear_bar rejects, so compact
    ingress is never adopted on it) instead of crashing the tool and
    losing the profiler section's probe rows."""
    import jax
    import jax.numpy as jnp

    from tools import ingress_ab as ab
    from gelly_streaming_tpu.ops import triangles as tri

    class FakeKernel:
        def __init__(self, edge_bucket, vertex_bucket, ingress):
            self.kb = 32
            self.MAX_STREAM_WINDOWS = 4
            self.ingress = ingress

        def warm_chunks(self):
            pass

        def _count_stream_device(self, src, dst):
            # formats disagree: one count differs
            return [1, 2] if self.ingress == "standard" else [1, 3]

    monkeypatch.setattr(tri, "TriangleWindowKernel", FakeKernel)
    results = []
    ab.stream_ab(jax, jnp, 1024, results)
    (row,) = results
    assert row["parity"] is False
    assert "speedup" not in row
    assert not tri.rows_clear_bar([row], "speedup", lambda r: 1.0)

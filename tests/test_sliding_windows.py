"""Pane-composition sliding-window suite (ISSUE 18 tentpole b):

- WindowedEdgeReduce slide=: the pane path (fold each edge into its
  pane ONCE, compose panes_per_window pane summaries per emission) is
  bit-exact against BOTH the naive refold twin (process_stream_naive)
  and the independent numpy oracle (sliding_numpy_reference), across
  monoids x directions x ragged tails;
- slide == size degenerates to tumbling, bit for bit;
- SlidingSummaryEngine (fused scan): slide == size pin, per-emission
  triangle recounts vs the sparse host oracle, cumulative fields ==
  pane-granularity tumbling, kill -> resume mid-pane-ring;
- StreamingAnalyticsDriver slide=: sliding triangle parity vs raw
  slices, tumbling pin, checkpoint mid-ring resume + slide-mismatch
  refusal, event-time / mesh / bad-slide refusals;
- defaults pin: slide unset (GS_SLIDE=0) leaves every surface on the
  legacy tumbling path.

Integer values only where monoid sums are compared: float pane sums
reassociate (pane-tree vs left fold) and are not bit-stable.
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops.scan_analytics import (
    SlidingSummaryEngine, StreamSummaryEngine)
from gelly_streaming_tpu.ops.triangles import triangle_count_sparse
from gelly_streaming_tpu.ops.windowed_reduce import (
    WindowedEdgeReduce, sliding_numpy_reference)
from gelly_streaming_tpu.utils import checkpoint

EB, VB, SLIDE = 64, 64, 16


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("GS_SLIDE", "GS_SANITIZE", "GS_AUTOTUNE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("GS_AUTOTUNE", "0")


def _edges(n, seed=0, ids=40):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, ids, n).astype(np.int64),
            rng.integers(0, ids, n).astype(np.int64))


# ----------------------------------------------------------------------
# WindowedEdgeReduce pane path
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name,direction", [("sum", "out"),
                                            ("min", "in"),
                                            ("max", "all")])
@pytest.mark.parametrize("n", [300, 256, 17, 64])
def test_reduce_sliding_matches_naive_and_oracle(name, direction, n):
    """The pane path == the naive refold twin == the independent
    numpy oracle, per emission, bit for bit — full windows, growing
    head windows, and the ragged tail alike."""
    src, dst = _edges(n, seed=3)
    val = np.random.default_rng(4).integers(-50, 50, n).astype(np.int64)
    eng = WindowedEdgeReduce(VB, EB, name=name, direction=direction,
                             slide=SLIDE)
    assert eng.panes_per_window == EB // SLIDE
    got = eng.process_stream(src, dst, val)
    twin = WindowedEdgeReduce(VB, EB, name=name, direction=direction,
                              slide=SLIDE)
    naive = twin.process_stream_naive(src, dst, val)
    oracle = sliding_numpy_reference(src, dst, val, EB, SLIDE,
                                     direction=direction, name=name)
    assert len(got) == len(naive) == len(oracle) == -(-n // SLIDE)
    for i, ((gc, gn), (nc, nn), (oc, on)) in enumerate(
            zip(got, naive, oracle)):
        assert np.array_equal(gn, nn), f"counts diverge at emission {i}"
        assert np.array_equal(gn[:len(on)], on)
        # touched cells value-identical; count-0 cells compare by count
        mask = gn > 0
        assert np.array_equal(gc[mask], nc[mask]), \
            f"cells diverge at emission {i}"
        assert np.array_equal(gc[:len(oc)][mask[:len(oc)]],
                              oc[mask[:len(oc)]])


def test_reduce_slide_equals_size_is_tumbling():
    """slide == size runs the pane machinery with exactly one pane
    per window — bit-identical to the plain tumbling engine."""
    src, dst = _edges(200, seed=5)
    val = np.arange(200, dtype=np.int64)
    a = WindowedEdgeReduce(VB, EB, name="sum").process_stream(
        src, dst, val)
    b = WindowedEdgeReduce(VB, EB, name="sum",
                           slide=EB).process_stream(src, dst, val)
    assert len(a) == len(b)
    for (ac, an), (bc, bn) in zip(a, b):
        assert np.array_equal(an, bn) and np.array_equal(ac, bc)


def test_reduce_slide_validation():
    with pytest.raises(ValueError, match="power of two dividing"):
        WindowedEdgeReduce(VB, EB, name="sum", slide=24)
    with pytest.raises(ValueError, match="power of two dividing"):
        WindowedEdgeReduce(VB, EB, name="sum", slide=2 * EB)
    with pytest.raises(ValueError, match="monoid"):
        WindowedEdgeReduce(VB, EB, fn=lambda a, b: a + b, slide=SLIDE)


# ----------------------------------------------------------------------
# SlidingSummaryEngine (fused scan)
# ----------------------------------------------------------------------
def test_scan_slide_equals_size_pin():
    """One pane per window: the sliding wrapper's rows equal the plain
    engine's tumbling digests exactly (the wrapper adds nothing but
    the triangle recount, which sees the identical slab)."""
    src, dst = _edges(7 * EB, seed=6, ids=VB)
    plain = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
    slid = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                                slide=EB)
    assert slid.process(src, dst) == plain.process(src, dst)


def test_scan_sliding_triangles_vs_sparse_oracle():
    """Every emission's triangle count == the sparse host count of the
    raw trailing-window slice (growing head + ragged tail included)."""
    n = 25 * SLIDE + 7
    src, dst = _edges(n, seed=7, ids=VB)
    eng = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                               slide=SLIDE)
    rows = eng.process(src, dst)
    assert len(rows) == -(-n // SLIDE)
    for i, row in enumerate(rows):
        lo = max(0, (i + 1) * SLIDE - EB)
        hi = min((i + 1) * SLIDE, n)
        want = int(triangle_count_sparse(
            np.asarray(src[lo:hi], np.int32),
            np.asarray(dst[lo:hi], np.int32), VB))
        assert row["triangles"] == want, f"emission {i}"


def test_scan_sliding_cumulative_fields_are_pane_tumbling():
    """max_degree / num_components / odd_cycle are cumulative: the
    sliding rows carry exactly the pane-granularity tumbling values."""
    src, dst = _edges(6 * SLIDE, seed=8, ids=VB)
    slid = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                                slide=SLIDE).process(src, dst)
    pane = StreamSummaryEngine(edge_bucket=SLIDE,
                               vertex_bucket=VB).process(src, dst)
    assert len(slid) == len(pane)
    for s_row, p_row in zip(slid, pane):
        for k, v in p_row.items():
            if k != "triangles":
                assert s_row[k] == v


def test_scan_sliding_kill_resume_mid_pane_ring(tmp_path):
    """Kill after a pane count that leaves the ring mid-fill, resume
    from the checkpoint: the tail emissions recompose the SAME windows
    the uninterrupted run emits."""
    n = 13 * SLIDE
    src, dst = _edges(n, seed=9, ids=VB)
    ref = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                               slide=SLIDE).process(src, dst)
    cut = 7 * SLIDE  # ring holds wp-1 = 3 panes: mid-stream, full ring
    a = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                             slide=SLIDE)
    head = a.process(src[:cut], dst[:cut])
    path = str(tmp_path / "ck")
    checkpoint.save(path, a.state_dict())
    b = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                             slide=SLIDE)
    state, _used = checkpoint.load_latest(path)
    b.load_state_dict(state)
    assert b.resume_offset() == cut
    tail = b.process(src[cut:], dst[cut:])
    assert head + tail == ref
    # mismatched geometry refuses loudly
    c = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                             slide=SLIDE // 2)
    with pytest.raises(ValueError, match="slide"):
        c.load_state_dict(state)


def test_scan_slide_validation():
    for bad in (24, 0, 2 * EB):
        with pytest.raises(ValueError, match="power of two dividing"):
            SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                                 slide=bad)


# ----------------------------------------------------------------------
# driver slide=
# ----------------------------------------------------------------------
def _driver(slide=None, analytics=StreamingAnalyticsDriver.ANALYTICS):
    return StreamingAnalyticsDriver(
        window_ms=1000, analytics=analytics, vertex_bucket=VB,
        edge_bucket=EB, slide=slide)


def test_driver_sliding_triangles_vs_sparse_oracle():
    n = 300
    src, dst = _edges(n, seed=10)
    out = _driver(slide=SLIDE).run_arrays(src, dst)
    assert len(out) == -(-n // SLIDE)
    for i, res in enumerate(out):
        lo = max(0, (i + 1) * SLIDE - EB)
        hi = min((i + 1) * SLIDE, n)
        s_sl, d_sl = src[lo:hi], dst[lo:hi]
        ids = np.unique(np.concatenate([s_sl, d_sl]))
        remap = {v: k for k, v in enumerate(ids)}
        want = int(triangle_count_sparse(
            np.array([remap[v] for v in s_sl], np.int32),
            np.array([remap[v] for v in d_sl], np.int32), len(ids)))
        assert res.triangles == want, f"emission {i}"
        assert res.num_edges == hi - i * SLIDE


def test_driver_sliding_cumulative_equals_pane_tumbling():
    """degrees/cc/bipartite are running snapshots: pane-sized sliding
    emissions equal a tumbling driver cut at the pane size."""
    src, dst = _edges(240, seed=11)
    names = ("degrees", "cc", "bipartite")
    slid = _driver(slide=SLIDE, analytics=names).run_arrays(src, dst)
    pane = StreamingAnalyticsDriver(
        window_ms=1000, analytics=names, vertex_bucket=VB,
        edge_bucket=SLIDE).run_arrays(src, dst)
    assert len(slid) == len(pane)
    for a, b in zip(slid, pane):
        assert np.array_equal(a.degrees, b.degrees)
        assert np.array_equal(a.cc_labels, b.cc_labels)
        assert np.array_equal(a.bipartite_odd, b.bipartite_odd)


def test_driver_slide_equals_eb_is_tumbling():
    src, dst = _edges(200, seed=12)
    a = _driver().run_arrays(src, dst)
    b = _driver(slide=EB).run_arrays(src, dst)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.triangles == y.triangles
        assert np.array_equal(x.degrees, y.degrees)
        assert np.array_equal(x.cc_labels, y.cc_labels)


def test_driver_sliding_kill_resume_mid_pane_ring(tmp_path):
    n = 240
    src, dst = _edges(n, seed=13)
    ref = _driver(slide=SLIDE).run_arrays(src, dst)
    cut = 7 * SLIDE
    a = _driver(slide=SLIDE)
    head = a.run_arrays(src[:cut], dst[:cut])
    path = str(tmp_path / "ck")
    checkpoint.save(path, a.state_dict())
    b = _driver(slide=SLIDE)
    state, _used = checkpoint.load_latest(path)
    b.load_state_dict(state)
    tail = b.run_arrays(src[cut:], dst[cut:])
    both = head + tail
    assert len(both) == len(ref)
    for x, y in zip(both, ref):
        assert x.triangles == y.triangles
        assert np.array_equal(x.degrees, y.degrees)
        assert np.array_equal(x.cc_labels, y.cc_labels)
    # slide mismatch (either direction) refuses loudly
    for other in (None, SLIDE * 2):
        c = _driver(slide=other)
        with pytest.raises(ValueError, match="slide mismatch"):
            c.load_state_dict(state)


def test_driver_slide_refusals():
    with pytest.raises(ValueError, match="power of two dividing"):
        _driver(slide=24)
    with pytest.raises(ValueError, match="single-chip"):
        StreamingAnalyticsDriver(window_ms=1000, vertex_bucket=VB,
                                 edge_bucket=EB, slide=SLIDE,
                                 mesh=object())
    d = _driver(slide=SLIDE)
    with pytest.raises(ValueError, match="count-based"):
        d.run_arrays(*_edges(10, seed=14), ts=np.arange(10))


def test_driver_gs_slide_knob_arms_and_default_stays_legacy(
        monkeypatch):
    """GS_SLIDE arms the driver exactly like the ctor param; the unset
    default leaves the legacy tumbling cut untouched."""
    src, dst = _edges(128, seed=15)
    default = _driver()
    assert default.slide is None and default._wp == 1
    base = default.run_arrays(src, dst)
    assert len(base) == 2  # eb-sized tumbling windows
    monkeypatch.setenv("GS_SLIDE", str(SLIDE))
    armed = _driver()
    assert armed.slide == SLIDE
    out = armed.run_arrays(src, dst)
    assert len(out) == 128 // SLIDE
    want = _driver(slide=SLIDE).run_arrays(src, dst)
    for x, y in zip(out, want):
        assert x.triangles == y.triangles

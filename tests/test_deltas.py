"""Per-window delta streams (driver emit_deltas=True): the on-device
changed-slot masks must let a consumer reconstruct every snapshot by
cumulatively applying (ids, values) deltas from the analytic's start
state — the per-update improving-stream contract of the reference's
continuous aggregates (SimpleEdgeStream.java:473-481), delivered as
one compact record set per window instead of per input edge
(core/driver.py:12-16 documents that granularity divergence)."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

ANALYTICS = ("degrees", "cc", "bipartite")


def fuzz_stream(num_edges, num_vertices, seed):
    rng = np.random.default_rng(seed)
    # power-ish skew so CC merges + bipartite flips actually happen
    src = rng.zipf(1.7, num_edges) % num_vertices
    dst = (src + 1 + rng.zipf(1.7, num_edges) % (num_vertices - 1)) \
        % num_vertices
    return src.astype(np.int64), dst.astype(np.int64)


class Reconstructor:
    """Applies delta records; never looks at the snapshots."""

    def __init__(self):
        self.deg = np.zeros(0, np.int64)
        self.cc = np.zeros(0, np.int32)
        self.odd = np.zeros(0, bool)

    def _grow(self, n):
        if len(self.deg) < n:
            old = len(self.deg)
            self.deg = np.concatenate(
                [self.deg, np.zeros(n - old, np.int64)])
            self.cc = np.concatenate(
                [self.cc, np.arange(old, n, dtype=np.int32)])
            self.odd = np.concatenate(
                [self.odd, np.zeros(n - old, bool)])

    def apply(self, res):
        n = len(res.vertex_ids)
        self._grow(n)
        for field, arr in (("delta_degrees", self.deg),
                           ("delta_cc", self.cc),
                           ("delta_bipartite", self.odd)):
            ids, vals = getattr(res, field)
            arr[ids] = vals

    def check(self, res):
        n = len(res.vertex_ids)
        np.testing.assert_array_equal(self.deg[:n], res.degrees)
        np.testing.assert_array_equal(self.cc[:n], res.cc_labels)
        np.testing.assert_array_equal(self.odd[:n], res.bipartite_odd)


def roundtrip(driver, src, dst, chunks=1):
    recon = Reconstructor()
    windows = 0
    per = len(src) // chunks
    for c in range(chunks):
        lo, hi = c * per, (c + 1) * per if c < chunks - 1 else len(src)
        for res in driver.run_arrays(src[lo:hi], dst[lo:hi]):
            assert res.delta_degrees is not None
            recon.apply(res)
            recon.check(res)
            windows += 1
    return windows


def test_batched_single_chip_fuzz():
    src, dst = fuzz_stream(6000, 700, seed=11)
    drv = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=256,
        edge_bucket=512, emit_deltas=True)
    assert roundtrip(drv, src, dst) >= 11


def test_deltas_are_sparse():
    """The point of the masks: windows that touch few vertices emit few
    records, not vb-length vectors."""
    src, dst = fuzz_stream(4096, 2000, seed=3)
    drv = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=4096,
        edge_bucket=1024, emit_deltas=True)
    results = drv.run_arrays(src, dst)
    for res in results[1:]:
        ids, _ = res.delta_degrees
        # ≤ 2 endpoints per edge can change degree
        assert len(ids) <= 2 * res.num_edges
        assert len(ids) < len(res.vertex_ids)  # strictly sparse here


def test_per_window_path_matches_batched():
    """Single-window calls route through _window (host-diff deltas);
    feeding the same stream window-by-window must reconstruct
    identically to the batched device-mask path."""
    src, dst = fuzz_stream(2048, 300, seed=5)
    eb = 512
    drv_b = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=256,
        edge_bucket=eb, emit_deltas=True)
    batched = drv_b.run_arrays(src, dst)
    drv_w = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=256,
        edge_bucket=eb, emit_deltas=True)
    recon = Reconstructor()
    for i, lo in enumerate(range(0, len(src), eb)):
        (res,) = drv_w.run_arrays(src[lo:lo + eb], dst[lo:lo + eb])
        recon.apply(res)
        recon.check(res)
        b = batched[i]
        for field in ("delta_degrees", "delta_cc", "delta_bipartite"):
            ids_w, vals_w = getattr(res, field)
            ids_b, vals_b = getattr(b, field)
            np.testing.assert_array_equal(ids_w, ids_b)
            np.testing.assert_array_equal(vals_w, vals_b)


def test_event_time_windows_with_growth():
    """Event-time windows of ragged sizes + vertex-bucket growth mid
    stream (the scan rebuilds at the wider bucket) keep the delta
    contract."""
    rng = np.random.default_rng(17)
    n = 3000
    src = rng.integers(0, 900, n)
    dst = rng.integers(0, 900, n)
    ts = np.sort(rng.integers(0, 4000, n))
    drv = StreamingAnalyticsDriver(
        window_ms=250, analytics=ANALYTICS, vertex_bucket=64,
        edge_bucket=64, emit_deltas=True)
    recon = Reconstructor()
    for res in drv.run_arrays(src, dst, ts):
        recon.apply(res)
        recon.check(res)


def test_sharded_mesh_deltas():
    from gelly_streaming_tpu.parallel.mesh import make_mesh

    src, dst = fuzz_stream(4096, 500, seed=23)
    drv = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=512,
        edge_bucket=512, mesh=make_mesh(), emit_deltas=True)
    assert roundtrip(drv, src, dst, chunks=2) == 8


def test_off_by_default():
    src, dst = fuzz_stream(1024, 200, seed=2)
    drv = StreamingAnalyticsDriver(
        window_ms=0, analytics=ANALYTICS, vertex_bucket=256,
        edge_bucket=512)
    for res in drv.run_arrays(src, dst):
        assert res.delta_degrees is None
        assert res.delta_cc is None
        assert res.delta_bipartite is None

"""Example-CLI smoke tests: every reference workload has a CLI twin
under examples/ (SURVEY.md §2.3); these pin the entry points' argument
surface and end-to-end output on a tiny graph, in hermetic CPU
subprocesses (the CLIs pick their own backend; tests must not touch
the real chip)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EDGES = "1 2 100\n1 3 150\n3 2 200\n2 4 250\n3 4 300\n4 5 400\n"


def _run(args, timeout=240):
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, cwd=REPO, env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


@pytest.fixture(scope="module")
def edge_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("cli") / "edges.txt"
    p.write_text(EDGES)
    return str(p)


def test_window_triangles_cli(edge_file, tmp_path):
    out = str(tmp_path / "tri.txt")
    r = _run(["examples/window_triangles.py", edge_file, out, "200"])
    assert r.returncode == 0, r.stderr[-500:]
    lines = sorted(open(out).read().split())
    # triangle {2,3,4} completes in the 200-399 window
    assert "(1,399)" in lines


def test_connected_components_cli(edge_file, tmp_path):
    out = str(tmp_path / "cc.txt")
    r = _run(["examples/connected_components.py", edge_file, out, "100"])
    assert r.returncode == 0, r.stderr[-500:]
    text = open(out).read()
    assert text.strip(), "no component output"


def test_bipartiteness_cli(edge_file, tmp_path):
    out = str(tmp_path / "bip.txt")
    r = _run(["examples/bipartiteness_check.py", edge_file, out, "100"])
    assert r.returncode == 0, r.stderr[-500:]
    text = open(out).read()
    # the graph has triangles -> odd cycle -> not bipartite at the end
    assert "false" in text.lower()


def test_sliding_degree_sums_cli(edge_file, tmp_path):
    out = str(tmp_path / "slide.txt")
    r = _run(["examples/sliding_degree_sums.py", edge_file, out,
              "200", "100"])
    assert r.returncode == 0, r.stderr[-500:]
    lines = sorted(open(out).read().split())
    # vertex 1's [0,200) window sums edges (1,2,100)+(1,3,150) = 250
    assert "1,250" in lines


def test_measurements_cli_degrees(edge_file):
    r = _run(["examples/measurements.py", "degrees", edge_file, "8"])
    assert r.returncode == 0, r.stderr[-500:]
    import json

    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["workload"] == "degrees" and row["edges"] == 6


@pytest.mark.parametrize("cli", [
    "iterative_connected_components",
    "broadcast_triangle_count",
    "incidence_sampling_triangle_count",
    "centralized_weighted_matching",
    "degree_aggregate",
    "streaming_analytics",
])
def test_remaining_clis_run_with_defaults(cli):
    """Every example CLI must at least run its built-in default data
    end-to-end (argument-surface regressions fail loudly here; the
    deeper output checks live in the per-workload tests above and in
    tests/library/)."""
    r = _run([f"examples/{cli}.py"])
    assert r.returncode == 0, (cli, r.stderr[-500:])


def test_centralized_weighted_matching_on_movielens_file():
    """The matching example end-to-end on a MovieLens-format file
    (user\\titem\\trating\\ttimestamp, timestamp-sorted — the shape of
    the reference's hard-coded movielens_10k_sorted.txt input,
    CentralizedWeightedMatching.java:44): a committed 2,000-line
    fixture with ml-100k's id ranges and a zipf-ish popularity skew."""
    fixture = os.path.join(REPO, "tests", "fixtures",
                           "movielens_2k_sorted.txt")
    r = _run(["examples/centralized_weighted_matching.py", fixture])
    assert r.returncode == 0, r.stderr[-500:]
    out = r.stdout
    # the matcher must have emitted add/replace events and the
    # reference-format runtime line
    assert "ADD" in out, out[:500]
    assert "Runtime:" in out
    # user/item id spaces: items are shifted by 1,000,000 (reference
    # parsing contract) — every matched edge respects it
    import re

    pairs = re.findall(r"ADD (\d+),(\d+),\d+", out)
    assert pairs, "no matched edges printed"
    assert all(int(b) > 1_000_000 > int(a) for a, b in pairs)


def test_measurements_cli_reduce(edge_file):
    """BASELINE config #2's measured leg (columnar reduceOnEdges
    sum-of-weights) runs through the CLI surface."""
    r = _run(["examples/measurements.py", "reduce", edge_file, "8"])
    assert r.returncode == 0, r.stderr[-500:]
    import json

    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["workload"].startswith("reduce_on_edges")
    assert row["edges"] == 6 and row["windows"] >= 1

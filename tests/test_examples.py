"""Example-CLI smoke tests: every reference workload has a CLI twin
under examples/ (SURVEY.md §2.3); these pin the entry points' argument
surface and end-to-end output on a tiny graph, in hermetic CPU
subprocesses (the CLIs pick their own backend; tests must not touch
the real chip)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EDGES = "1 2 100\n1 3 150\n3 2 200\n2 4 250\n3 4 300\n4 5 400\n"


def _run(args, timeout=240):
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, cwd=REPO, env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


@pytest.fixture(scope="module")
def edge_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("cli") / "edges.txt"
    p.write_text(EDGES)
    return str(p)


def test_window_triangles_cli(edge_file, tmp_path):
    out = str(tmp_path / "tri.txt")
    r = _run(["examples/window_triangles.py", edge_file, out, "200"])
    assert r.returncode == 0, r.stderr[-500:]
    lines = sorted(open(out).read().split())
    # triangle {2,3,4} completes in the 200-399 window
    assert "(1,399)" in lines


def test_connected_components_cli(edge_file, tmp_path):
    out = str(tmp_path / "cc.txt")
    r = _run(["examples/connected_components.py", edge_file, out, "100"])
    assert r.returncode == 0, r.stderr[-500:]
    text = open(out).read()
    assert text.strip(), "no component output"


def test_bipartiteness_cli(edge_file, tmp_path):
    out = str(tmp_path / "bip.txt")
    r = _run(["examples/bipartiteness_check.py", edge_file, out, "100"])
    assert r.returncode == 0, r.stderr[-500:]
    text = open(out).read()
    # the graph has triangles -> odd cycle -> not bipartite at the end
    assert "false" in text.lower()


def test_sliding_degree_sums_cli(edge_file, tmp_path):
    out = str(tmp_path / "slide.txt")
    r = _run(["examples/sliding_degree_sums.py", edge_file, out,
              "200", "100"])
    assert r.returncode == 0, r.stderr[-500:]
    lines = sorted(open(out).read().split())
    # vertex 1's [0,200) window sums edges (1,2,100)+(1,3,150) = 250
    assert "1,250" in lines


def test_measurements_cli_degrees(edge_file):
    r = _run(["examples/measurements.py", "degrees", edge_file, "8"])
    assert r.returncode == 0, r.stderr[-500:]
    import json

    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["workload"] == "degrees" and row["edges"] == 6


@pytest.mark.parametrize("cli", [
    "iterative_connected_components",
    "broadcast_triangle_count",
    "incidence_sampling_triangle_count",
    "centralized_weighted_matching",
    "degree_aggregate",
    "streaming_analytics",
])
def test_remaining_clis_run_with_defaults(cli):
    """Every example CLI must at least run its built-in default data
    end-to-end (argument-surface regressions fail loudly here; the
    deeper output checks live in the per-workload tests above and in
    tests/library/)."""
    r = _run([f"examples/{cli}.py"])
    assert r.returncode == 0, (cli, r.stderr[-500:])


def test_centralized_weighted_matching_on_movielens_file():
    """The matching example end-to-end on a MovieLens-format file
    (user\\titem\\trating\\ttimestamp, timestamp-sorted — the shape of
    the reference's hard-coded movielens_10k_sorted.txt input,
    CentralizedWeightedMatching.java:44): a committed 2,000-line
    fixture with ml-100k's id ranges and a zipf-ish popularity skew."""
    fixture = os.path.join(REPO, "tests", "fixtures",
                           "movielens_2k_sorted.txt")
    r = _run(["examples/centralized_weighted_matching.py", fixture])
    assert r.returncode == 0, r.stderr[-500:]
    out = r.stdout
    # the matcher must have emitted add/replace events and the
    # reference-format runtime line
    assert "ADD" in out, out[:500]
    assert "Runtime:" in out
    # user/item id spaces: items are shifted by 1,000,000 (reference
    # parsing contract) — every matched edge respects it
    import re

    pairs = re.findall(r"ADD (\d+),(\d+),\d+", out)
    assert pairs, "no matched edges printed"
    assert all(int(b) > 1_000_000 > int(a) for a, b in pairs)


@pytest.fixture(scope="module")
def citation_file(tmp_path_factory):
    """The full calibrated cit-HepPh-shaped stream (421,578 edges,
    utils/realgraph.py — validated against SNAP's published stats in
    tests/library/test_realgraph.py) as a 'src dst ts' file."""
    import numpy as np

    from gelly_streaming_tpu.utils.realgraph import citation_stream

    src, dst, ts = citation_stream()
    p = tmp_path_factory.mktemp("cit") / "citation.txt"
    with open(p, "w") as f:
        np.savetxt(f, np.stack([src, dst, ts], 1), fmt="%d")
    return str(p)


# Seed-pinned goldens for the calibrated stream, computed by the
# measured host tier and cross-checked against the native C++ tier
# (tests/library/test_triangles.py proves both match the device kernel
# and brute force). ts = arrival index, so window_ms = 32768 gives
# exactly 32768-edge windows.
CITATION_WINDOW_COUNTS = [
    129829, 8285, 4259, 2894, 2335, 1915, 1384, 1259, 1270, 1029,
    945, 714, 525]
CITATION_TOTAL_TRIANGLES = 1_315_736   # == realgraph's calibrated total
CITATION_NODES = 34_546


def test_window_triangles_cli_on_citation_stream(citation_file,
                                                 tmp_path):
    """VERDICT r3 item 6: the headline workload end-to-end through the
    CLI surface on real-shaped data — 13 windows, every per-window
    count exact. A dropped window, a shifted boundary, or a lost chunk
    anywhere in file→parse→window→count→sink changes a line."""
    out = str(tmp_path / "cit_tri.txt")
    r = _run(["examples/window_triangles.py", citation_file, out,
              "32768", "--fused"], timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    lines = open(out).read().split()
    # wmax is the window's nominal end boundary (Flink TimeWindow
    # maxTimestamp), also for the ragged final window
    want = ["(%d,%d)" % (c, (w + 1) * 32768 - 1)
            for w, c in enumerate(CITATION_WINDOW_COUNTS)]
    assert lines == want


def test_window_triangles_cli_citation_whole_graph(citation_file,
                                                   tmp_path):
    """One window covering the whole stream reproduces the graph's
    calibrated triangle total through the CLI."""
    out = str(tmp_path / "cit_tri1.txt")
    r = _run(["examples/window_triangles.py", citation_file, out,
              "1000000", "--fused"], timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    assert open(out).read().split() == [
        "(%d,999999)" % CITATION_TOTAL_TRIANGLES]


def test_connected_components_cli_on_citation_stream(citation_file,
                                                     tmp_path):
    """Streaming CC through the CLI on the full citation stream: the
    final merged DisjointSet must contain every one of the 34,546
    papers in one component (verified against an independent
    union-find oracle over the same file), so any dropped edge batch
    that disconnects the merge shows up."""
    import re

    import numpy as np

    out = str(tmp_path / "cit_cc.txt")
    r = _run(["examples/connected_components.py", citation_file, out,
              "1000"], timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    last = open(out).read().strip().split("\n")[-1]
    n_components = last.count("[")
    members = sorted(int(m) for m in re.findall(
        r"(?<=[\[\s,])\d+(?=[,\]\s])", last[last.index("=") :]))
    # independent oracle: plain union-find over the parsed file
    src, dst = np.loadtxt(citation_file, dtype=np.int64,
                          usecols=(0, 1)).T
    parent = np.arange(CITATION_NODES)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in zip(src, dst):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb
    roots = {find(v) for v in range(CITATION_NODES)}
    assert n_components == len(roots) == 1
    assert members == list(range(CITATION_NODES))


def test_measurements_cli_reduce(edge_file):
    """BASELINE config #2's measured leg (columnar reduceOnEdges
    sum-of-weights) runs through the CLI surface."""
    r = _run(["examples/measurements.py", "reduce", edge_file, "8"])
    assert r.returncode == 0, r.stderr[-500:]
    import json

    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["workload"].startswith("reduce_on_edges")
    assert row["edges"] == 6 and row["windows"] >= 1

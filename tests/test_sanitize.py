"""Admission sanitizer + dead-letter journal + cohort bulkhead
(utils/sanitize.py, core/tenancy.py quarantine, ISSUE 15).

Covers: the vectorized validator vs a pure-Python policy oracle
(including a fuzz loop through native.parse_edge_bytes — random byte
soup must never crash an admission boundary and must split exactly as
the oracle says), the DLQ's framing/rotation/retention/torn-tail
discipline, the knobs-off bit-identity contract, the bulkhead's
bisect→quarantine→probation ladder with its checkpoint round-trip,
and the serving front-end's typed rejection surface."""

import json
import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.tenancy import (
    TenantCohort, TenantQuarantined)
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.utils import faults, sanitize

EB, VB = 64, 128


@pytest.fixture(autouse=True)
def _clean_registry():
    sanitize.reset()
    yield
    sanitize.reset()


def _arm(monkeypatch, tmp_path, mode="on", dlq=True):
    monkeypatch.setenv("GS_SANITIZE", mode)
    if dlq:
        monkeypatch.setenv("GS_DLQ_DIR", str(tmp_path / "dlq"))
    return str(tmp_path / "dlq")


def oracle_split(src, dst, vb, mode):
    """Pure-Python twin of sanitize()'s per-edge policy: returns
    (keep_mask, reason_per_edge). Mirrors the documented severity
    order and the DUP_FLOOD_KEEP constant."""
    n = len(src)
    reasons = [None] * n
    seen = {}
    for i in range(n):
        s, d = src[i], dst[i]

        def intish(x):
            try:
                if isinstance(x, float):
                    return x == int(x)  # finite & integral
                int(x)
                return True
            except (ValueError, OverflowError, TypeError):
                return False

        if not (intish(s) and intish(d)):
            reasons[i] = "non_integer"
            continue
        s, d = int(s), int(d)
        if vb is not None:
            if s < 0 or d < 0:
                reasons[i] = "id_negative"
                continue
            if s >= 2 ** 31 or d >= 2 ** 31:
                reasons[i] = "id_overflow"
                continue
            if s >= vb or d >= vb:
                reasons[i] = "id_out_of_range"
                continue
        if mode == "strict":
            if s == d:
                reasons[i] = "self_loop"
                continue
            k = (s, d)
            seen[k] = seen.get(k, 0) + 1
            if seen[k] > sanitize.DUP_FLOOD_KEEP:
                reasons[i] = "duplicate_flood"
    keep = np.array([r is None for r in reasons], bool)
    return keep, reasons


# ----------------------------------------------------------------------
# the validator vs the oracle
# ----------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["on", "strict"])
def test_adversarial_batch_matches_oracle(monkeypatch, tmp_path,
                                          mode):
    _arm(monkeypatch, tmp_path, mode=mode, dlq=False)
    src = [1, -5, 2 ** 40, 130, 3, 3, float("nan"), float("inf"),
           2.5, 7] + [9] * 12
    dst = [2, 1, 1, 1, 3, 4, 1.0, 2.0, 1.0, 8] + [11] * 12
    keep, reasons = oracle_split(src, dst, VB, mode)
    rep = sanitize.sanitize(np.array(src), np.array(dst), VB)
    assert np.array_equal(rep.keep, keep)
    want = {}
    for r in reasons:
        if r is not None:
            want[r] = want.get(r, 0) + 1
    assert rep.reasons == want
    assert rep.accepted + rep.rejected == len(src)
    # accepted values survive in order
    assert rep.src.tolist() == [int(s) for s, k
                                in zip(src, keep) if k]


def test_fuzz_parse_bytes_never_crashes_and_matches_oracle(
        monkeypatch, tmp_path):
    """Random byte soup through native.parse_edge_bytes → sanitizer:
    no admission boundary may crash, and the accepted split must
    equal the pure-Python oracle exactly (the fuzz contract)."""
    from gelly_streaming_tpu import native

    _arm(monkeypatch, tmp_path, mode="strict", dlq=False)
    rng = np.random.default_rng(1234)
    for it in range(25):
        raw = bytes(rng.integers(0, 256, 512, dtype=np.uint8))
        if it % 2:
            # half the iterations: parseable lines with garbage ids
            raw += b"\n" + b"\n".join(
                b"%d %d" % (rng.integers(-(1 << 40), 1 << 40),
                            rng.integers(-(1 << 40), 1 << 40))
                for _ in range(32))
        src, dst, _ts = native.parse_edge_bytes(raw)
        rep = sanitize.sanitize(src, dst, VB)
        keep, _reasons = oracle_split(src.tolist(), dst.tolist(),
                                      VB, "strict")
        assert np.array_equal(rep.keep, keep), raw[:80]
        assert (rep.src < VB).all() and (rep.src >= 0).all()
        assert (rep.dst < VB).all() and (rep.dst >= 0).all()


def test_driver_domain_vb_none(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, dlq=False)
    rep = sanitize.sanitize(
        np.array([1.0, 2 ** 40, float("nan"), -7, 3.5]),
        np.array([2.0, 5, 1.0, 8, 9]), None)
    # huge and negative EXTERNAL ids are legal (the interner's
    # domain); NaN and fractional ids are not
    assert rep.src.tolist() == [1, 2 ** 40, -7]
    assert rep.reasons == {"non_integer": 2}


def test_off_mode_is_inert(monkeypatch):
    monkeypatch.setenv("GS_SANITIZE", "")
    assert not sanitize.enabled()
    monkeypatch.setenv("GS_SANITIZE", "off")
    assert not sanitize.enabled()
    assert sanitize.resolve_dlq() is None


def test_batch_overflow_typed_and_journaled(monkeypatch, tmp_path):
    dlq_dir = _arm(monkeypatch, tmp_path)
    monkeypatch.setenv("GS_MAX_BATCH_EDGES", "8")
    with pytest.raises(sanitize.BatchRejected) as ei:
        sanitize.sanitize(np.arange(9), np.arange(9), VB,
                          tenant="t", origin="feed",
                          dlq=sanitize.resolve_dlq())
    assert ei.value.reason == "batch_overflow"
    assert ei.value.limit == 8 and ei.value.size == 9
    info = sanitize.scan(dlq_dir)
    assert info["edges"] == 9
    assert info["by_reason"] == {"batch_overflow": 9}


def test_length_mismatch_typed():
    with pytest.raises(sanitize.BatchRejected) as ei:
        sanitize.sanitize(np.arange(3), np.arange(4), VB)
    assert ei.value.reason == "length_mismatch"


# ----------------------------------------------------------------------
# the dead-letter journal
# ----------------------------------------------------------------------
def test_dlq_roundtrip_fields(tmp_path):
    j = sanitize.DeadLetterJournal(str(tmp_path))
    j.append("t1", "feed", "id_out_of_range",
             np.array([5, 9]), np.array([200, 300]),
             np.array([1, 2]))
    j.append("t2", "engine", "id_negative",
             np.array([0]), np.array([-4]), np.array([7]))
    j.close()
    recs = list(sanitize.replay(str(tmp_path)))
    assert [(r["tenant"], r["origin"], r["reason"]) for r in recs] \
        == [("t1", "feed", "id_out_of_range"),
            ("t2", "engine", "id_negative")]
    assert recs[0]["offsets"].tolist() == [5, 9]
    assert recs[0]["src"].tolist() == [200, 300]
    assert recs[1]["src"].tolist() == [-4]
    info = sanitize.scan(str(tmp_path))
    assert info["records"] == 2 and info["edges"] == 3
    assert info["by_tenant"] == {"t1": 2, "t2": 1}


def test_dlq_rotation_and_retention(monkeypatch, tmp_path):
    monkeypatch.setenv("GS_WAL_SEGMENT_BYTES", "4096")
    j = sanitize.DeadLetterJournal(str(tmp_path))
    big = np.arange(400, dtype=np.int64)
    for _ in range(6):
        j.append("t", "feed", "id_out_of_range", big, big, big)
    segs = sorted(p for p in os.listdir(str(tmp_path))
                  if p.endswith(".seg"))
    assert len(segs) > 2  # rotation happened
    # retention: re-rotate with the bound armed → prefix pruned, and
    # replay still yields only intact records (no crash on the gap)
    monkeypatch.setenv("GS_DLQ_RETAIN", "1")
    for _ in range(3):
        j.append("t", "feed", "id_out_of_range", big, big, big)
    j.close()
    segs2 = sorted(p for p in os.listdir(str(tmp_path))
                   if p.endswith(".seg"))
    assert len(segs2) <= 3
    assert list(sanitize.replay(str(tmp_path)))  # readable remainder


def test_dlq_torn_tail_tolerated(tmp_path):
    j = sanitize.DeadLetterJournal(str(tmp_path))
    for i in range(3):
        j.append("t", "feed", "id_negative",
                 np.array([i]), np.array([-i]), np.array([i]))
    j.close()
    seg = sorted(tmp_path.glob("dlq_*.seg"))[-1]
    data = seg.read_bytes()
    seg.write_bytes(data[:-7])  # tear the last record
    recs = list(sanitize.replay(str(tmp_path)))
    assert len(recs) == 2  # the torn one drops, the rest replay


def test_resolve_dlq_registry(monkeypatch, tmp_path):
    dlq_dir = _arm(monkeypatch, tmp_path)
    a = sanitize.resolve_dlq()
    b = sanitize.resolve_dlq()
    assert a is b and a.dir == dlq_dir
    assert sanitize.dlq_status()["records"] == 0
    a.append("t", "feed", "self_loop", np.array([0]),
             np.array([1]), np.array([1]))
    assert sanitize.dlq_status()["records"] == 1


# ----------------------------------------------------------------------
# admission boundaries
# ----------------------------------------------------------------------
def test_feed_armed_rejects_to_dlq_and_accepts_rest(monkeypatch,
                                                    tmp_path):
    dlq_dir = _arm(monkeypatch, tmp_path)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    src = np.array([1, 500, 2, -3, 3], np.int64)
    dst = np.array([2, 1, 3, 4, 4], np.int64)
    take = co.feed("t", src, dst)
    assert take == 3
    rep = co.tenants["t"].last_report
    assert rep.reasons == {"id_negative": 1, "id_out_of_range": 1}
    recs = list(sanitize.replay(dlq_dir))
    assert {r["reason"] for r in recs} \
        == {"id_negative", "id_out_of_range"}
    # absolute source offsets: positions 1 and 3 of the first batch
    offs = sorted(int(o) for r in recs for o in r["offsets"])
    assert offs == [1, 3]
    # second batch continues the offset domain
    co.feed("t", np.array([999]), np.array([0]))
    offs2 = [int(o) for r in sanitize.replay(dlq_dir)
             for o in r["offsets"]]
    assert max(offs2) == 5  # position 0 of batch 2 = offset 5


def test_feed_disarmed_is_legacy(monkeypatch):
    monkeypatch.delenv("GS_SANITIZE", raising=False)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    with pytest.raises(ValueError, match="dense"):
        co.feed("t", np.array([500]), np.array([1]))


def test_armed_clean_stream_digest_parity(monkeypatch, tmp_path):
    """GS_SANITIZE=on with a clean stream is bit-identical to the
    disarmed path (the evidence-gate discipline)."""
    rng = np.random.default_rng(3)
    s = rng.integers(0, VB, 4 * EB).astype(np.int32)
    d = rng.integers(0, VB, 4 * EB).astype(np.int32)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    co.feed("t", s, d)
    want = co.pump()["t"]
    _arm(monkeypatch, tmp_path)
    co2 = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co2.admit("t")
    co2.feed("t", s, d)
    assert co2.pump()["t"] == want
    assert sanitize.scan(str(tmp_path / "dlq"))["records"] == 0


def test_engine_process_armed_matches_filtered_oracle(monkeypatch,
                                                      tmp_path):
    _arm(monkeypatch, tmp_path, dlq=False)
    rng = np.random.default_rng(5)
    s = rng.integers(-8, 80, 4 * 8).astype(np.int64)
    d = rng.integers(0, 64, 4 * 8).astype(np.int64)
    eng = StreamSummaryEngine(edge_bucket=8, vertex_bucket=64)
    eng.reset()
    got = eng.process(s, d)
    keep = (s >= 0) & (s < 64) & (d >= 0) & (d < 64)
    monkeypatch.setenv("GS_SANITIZE", "off")
    eng2 = StreamSummaryEngine(edge_bucket=8, vertex_bucket=64)
    eng2.reset()
    assert got == eng2.process(s[keep], d[keep])


@pytest.mark.faults
def test_admit_fault_site_poisons_upstream_of_sanitizer(monkeypatch,
                                                        tmp_path):
    dlq_dir = _arm(monkeypatch, tmp_path)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")

    def garble(payload):
        tid, src, dst = payload
        src = np.asarray(src).copy()
        src[0] = 10 ** 9  # out of range
        return tid, src, dst

    with faults.inject(faults.FaultSpec(site="admit", action="call",
                                        fn=garble)):
        take = co.feed("t", np.array([1, 2]), np.array([2, 3]))
    assert take == 1
    assert sanitize.scan(dlq_dir)["by_reason"] \
        == {"id_out_of_range": 1}


# ----------------------------------------------------------------------
# the bulkhead: bisect → quarantine → probation
# ----------------------------------------------------------------------
def _streams(n, windows, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "t%d" % i: (rng.integers(0, VB, windows * EB).astype(np.int32),
                    rng.integers(0, VB, windows * EB).astype(np.int32))
        for i in range(n)}


def _oracle(streams):
    out = {}
    for tid, (s, d) in streams.items():
        eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
        eng.reset()
        out[tid] = eng.process(s, d)
    return out


def _poison_plan(hostile):
    def poison(payload):
        if payload and hostile in payload:
            raise faults.InjectedFault("poisoned", "cohort_dispatch")
        return payload

    return faults.FaultSpec(site="cohort_dispatch", action="call",
                            fn=poison, times=10 ** 6)


@pytest.mark.faults
def test_bisect_isolates_exactly_the_poison_tenant():
    streams = _streams(8, 1, seed=11)
    oracle = _oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    with faults.inject(_poison_plan("t5")):
        for tid, (s, d) in streams.items():
            co.feed(tid, s, d)
        out = co.pump()
    assert co.quarantined() == ["t5"]
    for tid in streams:
        if tid != "t5":
            assert out[tid] == oracle[tid], tid


@pytest.mark.faults
def test_poison_output_quarantines_by_row():
    """Implausible finalized analytics (negative counts) quarantine
    exactly the offending slab row — no bisect needed."""
    streams = _streams(3, 1, seed=12)
    oracle = _oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    for tid, (s, d) in streams.items():
        co.feed(tid, s, d)
    real_batch = TenantCohort._dispatch_batch

    def evil(self, vb, kb, slab, out, staged):
        nb, wb, s, d, valid, real, failed, st = slab
        orig = TenantCohort._program.__get__(self)

        def poisoned_program(stacked, sj, dj, vj):
            run = orig(vb, kb, nb, wb)
            carries, outs = run(stacked, sj, dj, vj)
            mdeg, rest = outs[0], outs[1:]
            rows = [r for t, r, _w, _n in real if t.tid == "t1"]
            if rows:  # no-op once t1 is quarantined out of the batch
                mdeg = mdeg.at[rows[0]].set(-1)
            return carries, (mdeg,) + rest

        self._program = lambda *a: poisoned_program
        try:
            return real_batch(self, vb, kb, slab, out, staged)
        finally:
            del self._program

    import unittest.mock as mock

    with mock.patch.object(TenantCohort, "_dispatch_batch", evil):
        out1 = co.pump()
    # t1 quarantined; the re-run of the remaining rows happened under
    # the same (patched) dispatch, so pump again unpatched for the
    # healthy remainder that was deferred
    assert co.quarantined() == ["t1"]
    out2 = co.pump()
    got = {k: out1.get(k, []) + out2.get(k, [])
           for k in streams}
    for tid in ("t0", "t2"):
        assert got[tid] == oracle[tid], tid


@pytest.mark.faults
def test_probation_readmits_after_clean_windows(monkeypatch):
    monkeypatch.setenv("GS_QUARANTINE_WINDOWS", "2")
    streams = _streams(2, 4, seed=13)
    oracle = _oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    with faults.inject(_poison_plan("t1")) as plan:
        for tid, (s, d) in streams.items():
            co.feed(tid, s[:EB], d[:EB])
        out = co.pump()
    assert co.quarantined() == ["t1"]
    # quarantined feeds stay ACCEPTED (probation needs data)
    got = {k: list(v) for k, v in out.items()}
    for w in range(1, 4):
        for tid, (s, d) in streams.items():
            co.feed(tid, s[w * EB:(w + 1) * EB],
                    d[w * EB:(w + 1) * EB])
        for k, v in co.pump().items():
            got.setdefault(k, []).extend(v)
    for _ in range(4):
        for k, v in co.pump().items():
            got.setdefault(k, []).extend(v)
    assert co.tenant_tier("t1") == "cohort"  # re-admitted
    for tid in streams:
        assert got[tid] == oracle[tid], tid


@pytest.mark.faults
def test_systemic_failure_revokes_quarantines_and_raises():
    """A failure that follows EVERY tenant (dead device, wedged
    transfer) is not poison: the bulkhead must revoke its
    evidence-free quarantines and propagate the typed error exactly
    as the pre-bulkhead cohort did."""
    from gelly_streaming_tpu.utils import resilience

    streams = _streams(4, 1, seed=15)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    for tid, (s, d) in streams.items():
        co.feed(tid, s, d)

    def always_fail(payload):
        raise faults.InjectedFault("device is gone",
                                   "cohort_dispatch")

    with faults.inject(faults.FaultSpec(
            site="cohort_dispatch", action="call", fn=always_fail,
            times=10 ** 6)):
        # the ORIGINAL typed error propagates (here the injected
        # fault itself; a guarded-dispatch failure surfaces as the
        # typed StageError) — pre-bulkhead semantics
        with pytest.raises((resilience.StageError,
                            faults.InjectedFault)):
            co.pump()
    assert co.quarantined() == []  # nobody blamed for the hardware
    # the cohort recovers once the fault clears — same round, exact
    oracle = _oracle(streams)
    out = co.pump()
    for tid in streams:
        assert out[tid] == oracle[tid], tid


def test_backpressure_reject_journals_nothing(monkeypatch, tmp_path):
    """A backpressure-refused feed accepts nothing — so it must
    journal nothing: the client's retry would otherwise
    double-journal every reject and skew the source-offset domain."""
    dlq_dir = _arm(monkeypatch, tmp_path)
    monkeypatch.setenv("GS_TENANT_QUEUE_WINDOWS", "1")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    # fill the queue to capacity (1 window)
    co.feed("t", np.zeros(EB, np.int64), np.ones(EB, np.int64))
    off_before = co.tenants["t"].fed_offset
    batch_s = np.array([1, 500, 2], np.int64)
    batch_d = np.array([2, 3, 4], np.int64)
    from gelly_streaming_tpu.core.tenancy import TenantBackpressure

    with pytest.raises(TenantBackpressure):
        co.feed("t", batch_s, batch_d)
    assert sanitize.scan(dlq_dir)["records"] == 0
    assert co.tenants["t"].fed_offset == off_before
    # drain and retry: the reject journals exactly once, offsets
    # contiguous with the pre-refusal domain
    co.pump()
    co.feed("t", batch_s, batch_d)
    info = sanitize.scan(dlq_dir)
    assert info["records"] == 1 and info["edges"] == 1
    rec = next(sanitize.replay(dlq_dir))
    assert rec["offsets"].tolist() == [off_before + 1]


def test_negative_outranks_overflow(monkeypatch, tmp_path):
    """Severity order: a -2^40 id is id_negative (the pre-cast sign),
    never the overflow its magnitude would also trip; huge parseable
    object ints are id_overflow, not non_integer."""
    _arm(monkeypatch, tmp_path, dlq=False)
    rep = sanitize.sanitize(
        np.array([-(2 ** 40), float(-(2 ** 40)), 2 ** 70],
                 dtype=object),
        np.array([1, 1, 1], dtype=object), VB)
    assert rep.reasons == {"id_negative": 2, "id_overflow": 1}


def test_serve_disarmed_never_wraps_huge_ids(monkeypatch):
    """GS_SANITIZE=off keeps the legacy pre-cast: an out-of-int32 id
    in a feed request must error, never silently wrap into a
    plausible small id."""
    from gelly_streaming_tpu.core.serve import StreamServer

    monkeypatch.delenv("GS_SANITIZE", raising=False)
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    server = StreamServer(cohort, port=0)
    try:
        cohort.admit("t")
        with pytest.raises((OverflowError, ValueError)):
            server._op_feed({"tenant": "t", "src": [2 ** 40],
                             "dst": [1]})
        assert cohort.tenants["t"].queued == 0  # nothing admitted
    finally:
        server.close()


def test_permanent_quarantine_refuses_feeds(monkeypatch):
    monkeypatch.setenv("GS_QUARANTINE_WINDOWS", "0")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    co.quarantine("t", "operator says no")
    with pytest.raises(TenantQuarantined) as ei:
        co.feed("t", np.array([1]), np.array([2]))
    assert ei.value.probation_left == -1
    # pump() must terminate with a suspended backlogged tenant
    assert co.pump() == {}


@pytest.mark.faults
def test_quarantine_state_survives_checkpoint(monkeypatch, tmp_path):
    monkeypatch.setenv("GS_QUARANTINE_WINDOWS", "3")
    streams = _streams(2, 2, seed=14)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    with faults.inject(_poison_plan("t1")):
        for tid, (s, d) in streams.items():
            co.feed(tid, s[:EB], d[:EB])
        co.pump()
    assert co.quarantined() == ["t1"]
    t = co.tenants["t1"]
    probation_before = t.probation
    state = co.state_dict()
    co2 = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co2.load_state_dict(state)
    t2 = co2.tenants["t1"]
    assert co2.tenant_tier("t1") == "quarantined"
    assert t2.probation == probation_before
    assert t2.quarantine_reason
    # ... and a PRE-quarantine checkpoint rewinds the bulkhead
    clean = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    clean.admit("t1")
    pre = clean.tenant_state_dict("t1")
    co2.load_tenant_state_dict("t1", pre)
    assert co2.tenant_tier("t1") == "cohort"


# ----------------------------------------------------------------------
# serving surface + dlq_report
# ----------------------------------------------------------------------
def test_serve_feed_surfaces_rejections_and_status_dlq(monkeypatch,
                                                       tmp_path):
    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)

    _arm(monkeypatch, tmp_path)
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    server = StreamServer(cohort, port=0).start()
    cli = ServeClient(server.port)
    try:
        assert cli.admit("t")["ok"]
        r = cli.feed("t", [1, 500, -2], [2, 3, 4])
        assert r["ok"] and r["accepted"] == 1
        assert r["rejected"] == 2
        assert r["reasons"] == {"id_negative": 1,
                                "id_out_of_range": 1}
        st = cli.status()["serve"]
        assert st["dlq"]["records"] == 2
        assert st["sanitize"] == "on"
        # clean feeds keep the legacy reply shape
        r2 = cli.feed("t", [1], [2])
        assert "rejected" not in r2 and "reasons" not in r2
        # batch bound → typed wire error
        monkeypatch.setenv("GS_MAX_BATCH_EDGES", "4")
        r3 = cli.feed("t", [1] * 5, [2] * 5)
        assert r3 == {"ok": False, "error": "BatchRejected",
                      "tenant": "t", "reason": "batch_overflow",
                      "size": 5, "limit": 4,
                      "message": r3["message"]}
    finally:
        cli.close()
        server.close()


def test_serve_surfaces_quarantine(monkeypatch, tmp_path):
    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)

    monkeypatch.setenv("GS_QUARANTINE_WINDOWS", "0")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    server = StreamServer(cohort, port=0).start()
    cli = ServeClient(server.port)
    try:
        assert cli.admit("t")["ok"]
        cohort.quarantine("t", "test")
        r = cli.feed("t", [1], [2])
        assert r["error"] == "TenantQuarantined"
        assert r["probation_left"] == -1
        assert cli.status()["serve"]["quarantined"] == ["t"]
    finally:
        cli.close()
        server.close()


def test_dlq_report_gather_reinject_replay_exact(monkeypatch,
                                                 tmp_path):
    from tools.dlq_report import gather, make_fix, reinject

    dlq_dir = _arm(monkeypatch, tmp_path)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t")
    # interleave two batches whose rejects land in different reason
    # records; gather must restore ORIGINAL source order by offset
    co.feed("t", np.array([500, -1, 501]), np.array([1, 2, 3]))
    co.feed("t", np.array([-2, 502]), np.array([4, 5]))
    offs, src, dst, reasons = gather(dlq_dir)["t"]
    assert offs.tolist() == [0, 1, 2, 3, 4]
    assert src.tolist() == [500, -1, 501, -2, 502]
    fix = make_fix("mod:%d" % VB)
    fixed = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    fixed.admit("t")
    counts = reinject(dlq_dir, fixed.feed, fix=fix)
    assert counts == {"t": 5}
    got = fixed.close("t")
    direct = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    direct.admit("t")
    fs, fd = fix(src, dst)
    direct.feed("t", fs, fd)
    assert got == direct.close("t")


def test_wire_fields_empty_on_clean_batch(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, dlq=False)
    rep = sanitize.sanitize(np.array([1, 2]), np.array([2, 3]), VB)
    assert rep.clean and rep.wire_fields() == {}
    bad = sanitize.sanitize(np.array([500]), np.array([1]), VB)
    assert bad.wire_fields() == {
        "rejected": 1, "reasons": {"id_out_of_range": 1}}


def test_poison_smoke_constants_stay_in_sync():
    """tools/chaos_run.leg_poison imports the smoke's stream shape —
    pin the contract so a smoke refactor can't silently desync the
    chaos leg."""
    from tools import poison_smoke

    assert poison_smoke.EB > 0 and poison_smoke.VB > poison_smoke.EB
    assert callable(poison_smoke.hostile_bytes)
    assert callable(poison_smoke.oracle_filter)

"""Multi-tenant cohort scheduler suite (core/tenancy.py):

- padded-cohort parity: N ragged tenant streams (full + partial
  windows, different lengths) through the vmapped cohort equal N
  sequential StreamSummaryEngine runs window by window;
- the 1-tenant digest pin: a cohort of one IS the single-stream
  engine, bit for bit (the ci_check smoke's in-suite twin);
- admission semantics: GS_TENANT_MAX typed rejection, duplicate and
  unknown ids, closed-tenant feeds;
- backpressure: bounded queue overflow → typed TenantBackpressure
  (`reject`) or counted shedding (`drop`), capacity = queue windows
  x edge bucket;
- per-tenant demotion: one sick tenant falls to its own single-tenant
  engine (tenant-labeled demotion event) while the cohort keeps
  dispatching — results unchanged;
- per-tenant vertex buckets: mixed-bucket cohorts dispatch per bucket
  group with exact parity;
- tenants-per-dispatch: a pinned GS_TENANT_TPD splits rounds into
  several vmapped dispatches (ingest-ring lookahead path) with
  identical results;
- the windowed-reduce cohort leg: WindowedEdgeReduce.cohort_step over
  N tenant windows equals each tenant's own single-window reduce.
"""

import numpy as np
import pytest

from bench import make_stream
from gelly_streaming_tpu.core import tenancy
from gelly_streaming_tpu.core.tenancy import (
    TenantBackpressure, TenantCohort, TenantRejected)
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.utils import resilience

EB, VB = 128, 256


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    from gelly_streaming_tpu.ops import pallas_window
    from gelly_streaming_tpu.ops import resident_engine

    for k in ("GS_TENANT_MAX", "GS_TENANT_QUEUE_WINDOWS",
              "GS_TENANT_ADMISSION", "GS_TENANT_TPD", "GS_AUTOTUNE",
              "GS_COHORT_RESIDENT", "GS_COHORT_PALLAS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("GS_AUTOTUNE", "0")
    resilience.reset_demotions()
    resident_engine._reset_resident_cohort()
    pallas_window._reset_pallas_window()
    yield
    resilience.reset_demotions()
    resident_engine._reset_resident_cohort()
    pallas_window._reset_pallas_window()


def streams_for(n, windows=4, eb=EB, vb=VB, ragged=True):
    out = {}
    for i in range(n):
        edges = windows * eb
        if ragged and i % 2 == 1:
            edges -= eb // 3  # partial final window
        s, d = make_stream(edges, vb, seed=60 + i)
        out["t%d" % i] = (s.astype(np.int32), d.astype(np.int32))
    return out


def oracle(streams, eb=EB, vb=VB):
    return {tid: StreamSummaryEngine(edge_bucket=eb,
                                     vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}


def run_cohort(streams, eb=EB, vb=VB, piece=None, co=None,
               admit_vb=None):
    co = co or TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        if tid not in co.tenants:
            co.admit(tid, vertex_bucket=(admit_vb or {}).get(tid))
    out = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    piece = piece or 2 * eb
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            co.feed(tid, s[c:c + piece], d[c:c + piece])
            cursors[tid] = min(len(s), c + piece)
            live = True
        for tid, res in co.pump().items():
            out[tid].extend(res)
    for tid in streams:
        out[tid].extend(co.close(tid))
    return out, co


# ----------------------------------------------------------------------
# parity
# ----------------------------------------------------------------------
@pytest.mark.parametrize("n_tenants", [1, 3, 8])
def test_cohort_parity_vs_sequential_oracle(n_tenants):
    """Ragged cohorts (different stream lengths, partial tails, pad
    rows on non-power-of-two populations) reproduce N sequential
    single-tenant engines exactly, window by window."""
    streams = streams_for(n_tenants)
    want = oracle(streams)
    got, _co = run_cohort(streams)
    assert got == want


def test_one_tenant_cohort_is_the_single_stream_engine():
    """The digest pin the ci_check smoke enforces: a 1-tenant cohort
    must be indistinguishable from StreamSummaryEngine on the same
    stream, including the partial final window."""
    n = 3 * EB + EB // 4
    s, d = make_stream(n, VB, seed=7)
    s, d = s.astype(np.int32), d.astype(np.int32)
    want = StreamSummaryEngine(edge_bucket=EB,
                               vertex_bucket=VB).process(s, d)
    got, _co = run_cohort({"solo": (s, d)})
    assert got["solo"] == want


def test_ragged_window_counts_within_one_pump():
    """Tenants with unequal queue depths in ONE pump: the slab pads
    the window axis per tenant and drops padded summaries."""
    streams = streams_for(2, ragged=False)
    s0, d0 = streams["t0"]
    s1, d1 = streams["t1"]
    want = oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t0")
    co.admit("t1")
    co.feed("t0", s0, d0)           # 4 windows deep
    co.feed("t1", s1[:EB], d1[:EB])  # 1 window deep
    out = co.pump()
    assert len(out["t0"]) == 4 and len(out["t1"]) == 1
    co.feed("t1", s1[EB:], d1[EB:])
    out2 = co.pump()
    assert out["t0"] + out2.get("t0", []) == want["t0"]
    assert out["t1"] + out2["t1"] == want["t1"]


def test_per_tenant_vertex_buckets_group_dispatch():
    """Tenants declaring different vertex buckets land in separate
    bucket groups (one slab per group) with exact per-tenant parity."""
    small = streams_for(2, vb=VB, ragged=True)
    big_s, big_d = make_stream(3 * EB, 2 * VB, seed=91)
    streams = dict(small, big=(big_s.astype(np.int32),
                               big_d.astype(np.int32)))
    want = oracle(small)
    want["big"] = StreamSummaryEngine(
        edge_bucket=EB, vertex_bucket=2 * VB).process(*streams["big"])
    got, co = run_cohort(streams, admit_vb={"big": 2 * VB})
    assert got == want
    assert co.tenants["big"].vb == 2 * VB


def test_pinned_tenants_per_dispatch_batches(monkeypatch):
    """GS_TENANT_TPD=2 over 5 tenants: every round splits into three
    vmapped dispatches (ingest-ring lookahead prep) — identical
    results, and the ring actually saw work."""
    monkeypatch.setenv("GS_TENANT_TPD", "2")
    streams = streams_for(5)
    want = oracle(streams)
    got, _co = run_cohort(streams)
    assert got == want


# ----------------------------------------------------------------------
# admission / backpressure
# ----------------------------------------------------------------------
def test_admission_cap_typed_rejection(monkeypatch):
    monkeypatch.setenv("GS_TENANT_MAX", "2")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    co.admit("b")
    with pytest.raises(TenantRejected) as ei:
        co.admit("c")
    assert ei.value.tenant == "c"
    assert "GS_TENANT_MAX" in str(ei.value)


def test_duplicate_unknown_and_closed_are_typed():
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    with pytest.raises(TenantRejected):
        co.admit("a")
    with pytest.raises(TenantRejected):
        co.feed("ghost", [0], [1])
    co.close("a")
    with pytest.raises(TenantRejected):
        co.feed("a", [0], [1])


def test_backpressure_reject_is_atomic(monkeypatch):
    """Overflow under the default `reject` policy raises typed
    TenantBackpressure carrying queued/capacity and accepts NOTHING
    (a half-accepted feed could split a window across a retry)."""
    monkeypatch.setenv("GS_TENANT_QUEUE_WINDOWS", "2")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    s, d = make_stream(2 * EB, VB, seed=1)
    assert co.feed("a", s, d) == 2 * EB
    with pytest.raises(TenantBackpressure) as ei:
        co.feed("a", s[:1], d[:1])
    assert ei.value.queued == 2 * EB
    assert ei.value.capacity == 2 * EB
    assert co.queued_edges("a") == 2 * EB  # nothing was accepted
    co.pump()  # draining the queue reopens the tenant
    assert co.feed("a", s[:1], d[:1]) == 1


def test_backpressure_drop_sheds_and_counts(monkeypatch):
    monkeypatch.setenv("GS_TENANT_QUEUE_WINDOWS", "1")
    monkeypatch.setenv("GS_TENANT_ADMISSION", "drop")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    s, d = make_stream(2 * EB, VB, seed=2)
    assert co.feed("a", s, d) == EB  # capacity = 1 window
    assert co.tenants["a"].dropped_edges == EB
    # the accepted prefix still folds exactly
    want = StreamSummaryEngine(
        edge_bucket=EB, vertex_bucket=VB).process(s[:EB], d[:EB])
    assert co.pump()["a"] == want


def test_closed_partial_resume_refuses_more_stream(tmp_path):
    """The engines' partial-window-must-be-final guard holds across a
    checkpoint: a tenant restored AFTER its short final window was
    cut cannot fold more windows on a misaligned carry — feed()
    raises the same ValueError StreamSummaryEngine does."""
    from gelly_streaming_tpu.utils import checkpoint as ck

    s, d = make_stream(EB + EB // 4, VB, seed=3)
    s, d = s.astype(np.int32), d.astype(np.int32)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    co.feed("a", s, d)
    co.close("a")
    path = str(tmp_path / "a.npz")
    ck.save(path, co.tenant_state_dict("a"))

    co2 = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co2.admit("a")
    co2.load_tenant_state_dict("a", ck.restore(path))
    with pytest.raises(ValueError, match="partial window"):
        co2.feed("a", s[:1], d[:1])


def test_close_drains_only_the_closing_tenant():
    """close() must never consume another tenant's queued windows —
    its caller only reads one stream, so a sibling's summaries would
    be silently lost."""
    streams = streams_for(2, ragged=False)
    want = oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t0")
    co.admit("t1")
    co.feed("t0", *streams["t0"])
    co.feed("t1", *streams["t1"])
    got0 = co.close("t0")
    assert got0 == want["t0"]
    # t1's windows are still queued, delivered by the next pump
    assert co.queued_edges("t1") == len(streams["t1"][0])
    got1 = co.pump()["t1"] + co.close("t1")
    assert got1 == want["t1"]


def test_backpressure_durable_stamp_once_per_episode(monkeypatch):
    """A producer retry loop against a full queue must not fsync per
    attempt: the first overflow of an episode stamps durable, repeats
    stamp buffered, and a drain opens a new episode."""
    monkeypatch.setenv("GS_TENANT_QUEUE_WINDOWS", "1")
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    s, d = make_stream(EB, VB, seed=5)
    co.feed("a", s, d)
    for _ in range(3):
        with pytest.raises(TenantBackpressure):
            co.feed("a", s[:1], d[:1])
    assert co.tenants["a"].bp_stamped is True
    co.pump()  # drain resets the episode
    assert co.tenants["a"].bp_stamped is False


def test_unknown_id_introspection_does_not_count_rejections():
    """A typo'd id in read-only introspection raises the typed error
    WITHOUT stamping ledger events or rejection counters (only the
    serving surface — feed — records unknown-tenant refusals)."""
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    with pytest.raises(TenantRejected):
        co.tenant_tier("ghost")


def test_cohort_step_mixed_dtypes_promote():
    """A wider row in the cohort must not be truncated to the first
    row's dtype: the shared value buffer takes the promoted dtype."""
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    eng = WindowedEdgeReduce(vertex_bucket=VB, edge_bucket=EB,
                             name="sum", direction="out")
    s, d = make_stream(EB, VB, seed=8)
    vi = np.ones(EB, np.int64)
    vf = np.full(EB, 0.5, np.float64)
    got = eng.cohort_step([(s, d, vi), (s, d, vf)])
    want_f = eng.process_stream(s, d, vf)[0]
    touched = np.asarray(want_f[1]) > 0
    np.testing.assert_allclose(
        np.asarray(got[1][0])[touched],
        np.asarray(want_f[0])[touched])


def test_feed_validates_ids_against_the_bucket():
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("a")
    with pytest.raises(ValueError, match="dense in"):
        co.feed("a", [VB], [0])
    with pytest.raises(ValueError, match="dense in"):
        co.feed("a", [0], [-1])


# ----------------------------------------------------------------------
# demotion
# ----------------------------------------------------------------------
def test_demoted_tenant_runs_single_while_cohort_dispatches():
    """Mid-stream demotion of one tenant: its remaining windows run on
    its OWN StreamSummaryEngine (seeded from the live carry — exact),
    the others stay on the vmapped cohort, and every tenant's summary
    stream still equals the sequential oracle. The demotion event
    carries the tenant label."""
    streams = streams_for(3)
    want = oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    out = {tid: [] for tid in streams}
    for tid in streams:
        co.admit(tid)
    # first half
    for tid, (s, d) in streams.items():
        co.feed(tid, s[:2 * EB], d[:2 * EB])
    for tid, res in co.pump().items():
        out[tid].extend(res)
    co.demote("t1", reason="test drill")
    assert co.tenant_tier("t1") == "single"
    assert co.tenant_tier("t0") == "cohort"
    evs = [e for e in resilience.demotion_events()
           if e.get("tenant") == "t1"]
    assert evs and evs[0]["from"] == "cohort" \
        and evs[0]["to"] == "single"
    # rest of the streams
    for tid, (s, d) in streams.items():
        co.feed(tid, s[2 * EB:], d[2 * EB:])
    for tid, res in co.pump().items():
        out[tid].extend(res)
    for tid in streams:
        out[tid].extend(co.close(tid))
    assert out == want


def test_poisoned_prep_demotes_only_the_sick_tenant():
    """An injected per-tenant prep fault isolates: the poisoned tenant
    demotes (and its queued windows replay on the single tier), the
    other tenants' summaries are untouched — the chaos tenant leg's
    in-suite twin."""
    from gelly_streaming_tpu.utils import faults

    streams = streams_for(3, ragged=False)
    want = oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid in streams:
        co.admit(tid)
    out = {tid: [] for tid in streams}
    for tid, (s, d) in streams.items():
        co.feed(tid, s, d)
    # round 1 preps tenants in sorted order: call 2 poisons t1
    with faults.inject(faults.FaultSpec(site="tenant_prep",
                                        on_call=2)):
        for tid, res in co.pump().items():
            out[tid].extend(res)
    assert co.tenant_tier("t1") == "single"
    for tid in streams:
        out[tid].extend(co.close(tid))
    assert out == want


# ----------------------------------------------------------------------
# windowed-reduce cohort leg
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name,direction", [("sum", "out"),
                                            ("min", "in"),
                                            ("max", "all")])
def test_windowed_reduce_cohort_step_parity(name, direction):
    """ops/windowed_reduce.cohort_step: N tenants' windows as one
    [N, eb] stack dispatch — counts identical, touched cells value-
    identical to each tenant's own reduce (count-0 cells compare by
    count, the repo-wide convention)."""
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    eng = WindowedEdgeReduce(vertex_bucket=VB, edge_bucket=EB,
                             name=name, direction=direction)
    rows, singles = [], []
    for i in range(5):
        n = EB if i != 3 else EB - 17
        s, d = make_stream(n, VB, seed=70 + i)
        val = (1 + (s + 3 * d) % 13).astype(np.int64)
        rows.append((s, d, val))
        singles.append(eng.process_stream(s, d, val)[0])
    got = eng.cohort_step(rows)
    assert len(got) == 5
    for (gc, gn), (sc, sn) in zip(got, singles):
        gn, sn = np.asarray(gn), np.asarray(sn)
        np.testing.assert_array_equal(gn, sn)
        touched = sn > 0
        np.testing.assert_array_equal(
            np.asarray(gc)[touched].astype(np.int64),
            np.asarray(sc)[touched].astype(np.int64))


def test_cohort_step_rejects_user_fn_and_oversize():
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    eng = WindowedEdgeReduce(vertex_bucket=VB, edge_bucket=EB,
                             fn=lambda a, b: a + b)
    with pytest.raises(ValueError, match="monoid"):
        eng.cohort_step([(np.zeros(1, np.int64),) * 3])
    eng2 = WindowedEdgeReduce(vertex_bucket=VB, edge_bucket=EB)
    big = np.zeros(EB + 1, np.int64)
    with pytest.raises(ValueError, match="exceed"):
        eng2.cohort_step([(big, big, big)])


def test_tenants_per_dispatch_tuner_arm(monkeypatch, tmp_path):
    """With the online tuner live, the cohort's tenant_cohort family
    owns a tenants-per-dispatch arm: rounds record measured edges/s,
    summaries stay identical at every arm (hermetic cache)."""
    monkeypatch.setenv("GS_AUTOTUNE", "1")
    monkeypatch.setenv("GS_TUNE_CACHE", str(tmp_path))
    streams = streams_for(4)
    want = oracle(streams)
    got, co = run_cohort(streams, piece=EB)
    assert got == want
    tuner = co._tuner(VB)
    assert tuner is not None
    summary = tuner.summary()
    assert summary["rounds"] >= 1
    assert "tpd" in summary["chosen"]


# ----------------------------------------------------------------------
# cohort-aware event-time guard
# ----------------------------------------------------------------------
def test_event_time_interleaved_disjoint_ranges_ok():
    """The regression the guard exists to avoid regressing INTO: two
    tenants with disjoint, interleaved time ranges share slabs all
    run long — monotonicity is per tenant, never per slab — and the
    results still match the oracle exactly."""
    streams = streams_for(2, ragged=False)
    want = oracle(streams)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t0")
    co.admit("t1")
    out = {"t0": [], "t1": []}
    piece = EB
    # t0 lives around epoch 1_000_000, t1 around 500 — every feed
    # round interleaves the two clocks in one admission boundary
    for lo in range(0, 4 * EB, piece):
        for tid, base in (("t0", 1_000_000), ("t1", 500)):
            s, d = streams[tid]
            if lo >= len(s):
                continue
            hi = min(lo + piece, len(s))
            co.feed(tid, s[lo:hi], d[lo:hi],
                    ts=np.arange(base + lo, base + hi, dtype=np.int64))
        for tid, res in co.pump().items():
            out[tid].extend(res)
    for tid in streams:
        out[tid].extend(co.close(tid))
    assert out == want


def test_event_time_regression_refuses_atomically():
    """A per-tenant event-time regression — within a batch or against
    the tenant's newest accepted stamp — refuses the WHOLE batch for
    that tenant only, consuming nothing; the other tenant's clock is
    untouched."""
    streams = streams_for(2, ragged=False)
    s0, d0 = streams["t0"]
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    co.admit("t0")
    co.admit("t1")
    # non-monotone WITHIN one batch
    bad = np.arange(EB, dtype=np.int64)
    bad[EB // 2] = 0
    with pytest.raises(ValueError, match="WITHIN the batch"):
        co.feed("t0", s0[:EB], d0[:EB], ts=bad)
    assert co.queued_edges("t0") == 0  # nothing consumed
    # accept a clean batch ending at ts=EB-1 ...
    co.feed("t0", s0[:EB], d0[:EB],
            ts=np.arange(EB, dtype=np.int64))
    # ... then a batch starting BEFORE it: refused, naming the tenant
    with pytest.raises(ValueError, match="t0.*already reached"):
        co.feed("t0", s0[EB:2 * EB], d0[EB:2 * EB],
                ts=np.arange(EB // 2, EB // 2 + EB, dtype=np.int64))
    assert co.queued_edges("t0") == EB
    # t1's clock is independent: far-past stamps are fine
    s1, d1 = streams["t1"]
    assert co.feed("t1", s1[:EB], d1[:EB],
                   ts=np.arange(EB, dtype=np.int64)) == EB


# ----------------------------------------------------------------------
# resident cohort tier (GS_COHORT_RESIDENT)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("n_tenants", [1, 3, 8])
def test_resident_cohort_parity(monkeypatch, n_tenants):
    """Pinned on, the donated stacked-carry super-batch program must
    reproduce the scan-tier cohort (and thus the N sequential
    engines) exactly — and must have actually dispatched through the
    resident path."""
    from gelly_streaming_tpu.ops import resident_engine

    streams = streams_for(n_tenants)
    want = oracle(streams)
    monkeypatch.setenv("GS_COHORT_RESIDENT", "on")
    resident_engine._reset_resident_cohort()
    got, co = run_cohort(streams, piece=EB)
    assert got == want
    assert co.resident_dispatches > 0, \
        "resident tier pinned on but never dispatched"


def test_resident_cohort_defaults_off_digest_identical(monkeypatch):
    """GS_COHORT_RESIDENT unset on a backend with no committed
    cohort_resident rows clearing the bar: the dispatch plan and the
    results are bit-identical to the scan-tier cohort."""
    from gelly_streaming_tpu.ops import resident_engine

    streams = streams_for(3)
    base, co0 = run_cohort(streams)
    assert co0.resident_dispatches == 0
    monkeypatch.setenv("GS_COHORT_RESIDENT", "on")
    resident_engine._reset_resident_cohort()
    got, _co = run_cohort(streams)
    assert got == base


def test_resident_stack_replacement_never_strands_a_carry(monkeypatch):
    """Regression pin: a membership-changed dispatch must evict the
    WHOLE stale resident stack before committing its replacement.
    The bug: staggered stream lengths shrink the batch (t0/t1 drain
    first), then close(t1) dispatches a one-tenant batch whose commit
    replaced the stack while t3 still held a res_row into it — t3's
    final partial window then folded onto a pad row's fresh carry
    instead of its own, silently wrong analytics."""
    from gelly_streaming_tpu.ops import resident_engine

    rng = np.random.default_rng(7)
    streams = {}
    for i in range(4):
        edges = EB * (3 + i) - (EB // 3 if i % 2 else 0)
        streams["t%d" % i] = (
            rng.integers(0, VB, edges).astype(np.int32),
            rng.integers(0, VB, edges).astype(np.int32))
    want = oracle(streams)
    monkeypatch.setenv("GS_COHORT_RESIDENT", "on")
    resident_engine._reset_resident_cohort()
    # piece=2*EB staggers exhaustion so the batch membership churns
    # across rounds before the per-tenant closes cut the tails
    got, co = run_cohort(streams, piece=2 * EB)
    assert co.resident_dispatches > 0
    assert got == want
    resident_engine._reset_resident_cohort()


def test_resolve_resident_cohort_pins_and_gate(monkeypatch):
    from gelly_streaming_tpu.ops import resident_engine
    from gelly_streaming_tpu.ops import triangles as tri_ops

    monkeypatch.setenv("GS_COHORT_RESIDENT", "on")
    assert resident_engine.resolve_resident_cohort() is True
    monkeypatch.setenv("GS_COHORT_RESIDENT", "off")
    assert resident_engine.resolve_resident_cohort() is False
    monkeypatch.delenv("GS_COHORT_RESIDENT")

    def fake_perf(rows):
        return lambda *a, **k: {"tenancy_ab": rows}

    # the committed-evidence bar: EVERY cohort_resident row parity
    # with throughput ≥1.05x its own sequential baseline — the N=1
    # row's honest ~1x keeps auto off
    winning = [{"probe": "cohort_resident", "parity": True,
                "tenants": 8, "tenant_edges_per_s": 2000,
                "sequential_edges_per_s": 1000, "speedup": 2.0}]
    with_n1 = winning + [
        {"probe": "cohort_resident", "parity": True, "tenants": 1,
         "tenant_edges_per_s": 990, "sequential_edges_per_s": 1000,
         "speedup": 0.99}]
    other = [{"probe": "cohort_serving", "parity": True, "tenants": 8,
              "tenant_edges_per_s": 2000,
              "sequential_edges_per_s": 1000, "speedup": 2.0}]
    for rows, want in ((winning, True), (with_n1, False),
                       (other, False), ([], False)):
        monkeypatch.setattr(tri_ops, "_load_matching_perf",
                            fake_perf(rows))
        resident_engine._reset_resident_cohort()
        assert resident_engine.resolve_resident_cohort() is want, rows
    resident_engine._reset_resident_cohort()


def test_tuner_rekeys_on_cohort_size_bucket(monkeypatch, tmp_path):
    """The Nb bugfix pin: the tuner family key includes the cohort
    size bucket, so a grown cohort gets a fresh family (stale
    tenants-per-dispatch EMAs measured at old N can't steer the new
    population) — and the persisted best re-seeds the new key."""
    monkeypatch.setenv("GS_AUTOTUNE", "1")
    monkeypatch.setenv("GS_TUNE_CACHE", str(tmp_path))
    streams = streams_for(2, ragged=False)
    co = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    for tid, (s, d) in streams.items():
        co.admit(tid)
        co.feed(tid, s[:EB], d[:EB])
    co.pump()
    t1 = co._tuner(VB)
    assert t1.key.endswith(":N=8")  # bucket floor
    assert max(t1.space["tpd"]) == 8
    # grow the cohort past the bucket (8 → 16): the SAME cohort
    # object must rekey its family rather than keep tuning the N=8
    # arms on the new program shape
    s, d = streams["t0"]
    for i in range(10, 18):
        co.admit("t%d" % i)
        co.feed("t%d" % i, s[:EB], d[:EB])
    co.pump()
    t2 = co._tuner(VB)
    assert t2 is t1, "rekey must mutate the family, not fork it"
    assert t2.key.endswith(":N=16")  # bucket_size(10 live tenants)
    assert t2 is co._tuner(VB)  # stable until the bucket moves again
    # arms on the new family stay within ITS space
    assert set(t2.space) >= {"tpd"}
    assert max(t2.space["tpd"]) == 16


# ----------------------------------------------------------------------
# knob plumbing
# ----------------------------------------------------------------------
def test_tenancy_knob_readers(monkeypatch):
    assert tenancy.max_tenants() == 64
    assert tenancy.queue_windows() == 8
    assert tenancy.admission_policy() == "reject"
    assert tenancy.pinned_tpd() == 0
    monkeypatch.setenv("GS_TENANT_MAX", "3")
    monkeypatch.setenv("GS_TENANT_ADMISSION", "drop")
    assert tenancy.max_tenants() == 3
    assert tenancy.admission_policy() == "drop"

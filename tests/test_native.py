"""Native host-runtime kernels: parser / window assigner / interner,
cross-checked against the Python fallbacks."""

import numpy as np
import pytest

from gelly_streaming_tpu import native
from gelly_streaming_tpu.utils.interning import IncrementalInterner


def test_native_builds():
    if not native.available():
        pytest.skip("no C++ toolchain — fallbacks in use")


def test_parse_edge_file(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("1 2 100\n3\t4\t200\n\nbad line\n5 6\n-7 8 300\n")
    src, dst, ts = native.parse_edge_file(str(p))
    np.testing.assert_array_equal(src, [1, 3, 5, -7])
    np.testing.assert_array_equal(dst, [2, 4, 6, 8])
    np.testing.assert_array_equal(ts, [100, 200, -1, 300])


def test_parse_large_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    n = 50_000
    src = rng.integers(0, 1 << 40, n)
    dst = rng.integers(0, 1 << 40, n)
    ts = np.arange(n)
    p = tmp_path / "big.txt"
    with open(p, "w") as f:
        for row in zip(src, dst, ts):
            f.write("%d %d %d\n" % row)
    s, d, t = native.parse_edge_file(str(p))
    np.testing.assert_array_equal(s, src)
    np.testing.assert_array_equal(d, dst)
    np.testing.assert_array_equal(t, ts)


def test_parse_crlf_lines_match_python_fallback(tmp_path):
    """CRLF-terminated lines (with and without timestamps) parse the same
    through the native parser and the Python fallback."""
    p = tmp_path / "crlf.txt"
    p.write_bytes(b"1 2\r\n3 4 200\r\n5 6\r")
    for parse in (native.parse_edge_file,
                  lambda f: native._parse_edge_bytes_py(open(f, 'rb').read())):
        src, dst, ts = parse(str(p))
        np.testing.assert_array_equal(src, [1, 3, 5])
        np.testing.assert_array_equal(dst, [2, 4, 6])
        np.testing.assert_array_equal(ts, [-1, 200, -1])


def test_parse_trailing_tokens_match_python_fallback(tmp_path):
    """Lines with extra non-numeric columns keep their first three fields
    identically in the native parser and the Python fallback."""
    p = tmp_path / "annot.txt"
    p.write_text("1 2 100 label\n3 4 200 x y z\n5 6x 300\n7 8\n")
    expected = ([1, 3, 7], [2, 4, 8], [100, 200, -1])
    src, dst, ts = native.parse_edge_file(str(p))
    np.testing.assert_array_equal(src, expected[0])
    np.testing.assert_array_equal(dst, expected[1])
    np.testing.assert_array_equal(ts, expected[2])
    # and the pure-Python path agrees even when the native lib exists
    s, d, t = native._parse_edge_bytes_py(p.read_bytes())
    np.testing.assert_array_equal(s, expected[0])
    np.testing.assert_array_equal(d, expected[1])
    np.testing.assert_array_equal(t, expected[2])


def test_assign_windows():
    ts = np.array([0, 99, 100, 250, 999, 1000])
    np.testing.assert_array_equal(
        native.assign_windows(ts, 100), [0, 0, 100, 200, 900, 1000]
    )


def test_native_interner_matches_python():
    if not native.available():
        pytest.skip("no native lib")
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 500, 5000)
    nat = native.NativeInterner()
    py = IncrementalInterner()
    np.testing.assert_array_equal(nat.intern_array(ids), py.intern_array(ids))
    assert len(nat) == len(py)
    dense = np.arange(len(nat), dtype=np.int32)
    assert list(nat.ids_of(dense)) == py.ids_of(dense)


def test_iter_edge_chunks_prefetch_matches_sync(tmp_path):
    """The producer-thread prefetch path yields byte-identical chunks
    in order, propagates parse errors, and shuts its thread down when
    the consumer abandons mid-stream."""
    import threading

    import numpy as np

    from gelly_streaming_tpu.io.sources import iter_edge_chunks

    p = tmp_path / "edges.txt"
    rng = np.random.default_rng(2)
    rows = ["%d %d %d" % (rng.integers(0, 99), rng.integers(0, 99), t)
            for t in range(5000)]
    p.write_text("\n".join(rows) + "\n")

    sync = list(iter_edge_chunks(str(p), chunk_bytes=4096, prefetch=0))
    pre = list(iter_edge_chunks(str(p), chunk_bytes=4096, prefetch=3))
    assert len(sync) == len(pre) > 1
    for a, b in zip(sync, pre):
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    # abandon mid-stream: the producer thread must exit
    before = threading.active_count()
    it = iter_edge_chunks(str(p), chunk_bytes=512, prefetch=1)
    next(it)
    it.close()
    for _ in range(100):
        if threading.active_count() <= before:
            break
        import time
        time.sleep(0.02)
    assert threading.active_count() <= before

    # a missing file raises in the CONSUMER, not silently in the thread
    import pytest

    with pytest.raises(OSError):
        list(iter_edge_chunks(str(tmp_path / "missing.txt"), prefetch=2))


# ----------------------------------------------------------------------
# native snapshot tier (gs_snapshot_windows): the host form of the
# driver's batched snapshot scan
# ----------------------------------------------------------------------

def _tier_drivers(**kw):
    from gelly_streaming_tpu import native as native_mod
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

    if not native_mod.snapshot_available():
        import pytest

        pytest.skip("libgsnative lacks gs_snapshot_windows")
    return (StreamingAnalyticsDriver(snapshot_tier="scan", **kw),
            StreamingAnalyticsDriver(snapshot_tier="native", **kw))


def _assert_results_equal(ra, rb):
    assert len(ra) == len(rb)
    for x, y in zip(ra, rb):
        np.testing.assert_array_equal(x.degrees, y.degrees)
        np.testing.assert_array_equal(x.cc_labels, y.cc_labels)
        np.testing.assert_array_equal(x.bipartite_odd, y.bipartite_odd)
        assert x.triangles == y.triangles


def test_snapshot_tier_parity_count_windows():
    """Count-based windows incl. vertex-bucket growth mid-stream and a
    partial final window: every per-window snapshot identical across
    tiers."""
    rng = np.random.default_rng(5)
    kw = dict(window_ms=0, edge_bucket=256, vertex_bucket=64)
    a, b = _tier_drivers(**kw)
    for n, hi in ((1024, 50), (1000, 2000)):  # growth on the 2nd batch
        src = rng.integers(0, hi, n)
        dst = rng.integers(0, hi, n)
        _assert_results_equal(a.run_arrays(src, dst),
                              b.run_arrays(src, dst))


def test_snapshot_tier_parity_event_time():
    """Event-time windows (varying lengths) through stream_file."""
    rng = np.random.default_rng(8)
    n = 4000
    src = rng.integers(0, 300, n)
    dst = rng.integers(0, 300, n)
    ts = np.sort(rng.integers(0, 5000, n))
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        f.write("".join(f"{s} {d} {t}\n"
                        for s, d, t in zip(src, dst, ts)))
        path = f.name
    a, b = _tier_drivers(window_ms=400)
    _assert_results_equal(list(a.stream_file(path)),
                          list(b.stream_file(path)))


def test_snapshot_tier_checkpoint_interop(tmp_path):
    """A checkpoint taken under one tier resumes under the OTHER with
    an identical continuation — the carried layouts are shared."""
    rng = np.random.default_rng(13)
    n = 6000
    src = rng.integers(0, 200, n)
    dst = rng.integers(0, 200, n)
    p = tmp_path / "s.txt"
    p.write_text("".join(f"{s} {d}\n" for s, d in zip(src, dst)))
    kw = dict(window_ms=0, edge_bucket=512, vertex_bucket=256)

    a_full, b_full = _tier_drivers(**kw)
    want = a_full.run_file(str(p))
    _assert_results_equal(want, b_full.run_file(str(p)))

    for first, second in (("native", "scan"), ("scan", "native")):
        from gelly_streaming_tpu.core.driver import (
            StreamingAnalyticsDriver)

        ck = str(tmp_path / f"{first}.ckpt")
        a = StreamingAnalyticsDriver(snapshot_tier=first, **kw)
        a.enable_auto_checkpoint(ck, every_n_windows=2)
        seen = 0
        for _res in a.stream_file(str(p), chunk_bytes=4096):
            seen += 1
            if seen == 7:
                break
        b = StreamingAnalyticsDriver(snapshot_tier=second, **kw)
        assert b.try_resume(ck)
        done = b.windows_done
        rest = list(b.stream_file(str(p), chunk_bytes=4096,
                                  resume=True))
        _assert_results_equal(rest, want[done:])


def test_snapshot_tier_resolver_gates(monkeypatch, tmp_path):
    """resolve_snapshot_tier: evidence-gated like the other selections
    — flips only on backend-matched all-parity >=5% wins, never on a
    chip backend."""
    import json

    import jax

    from gelly_streaming_tpu import native as native_mod

    if not native_mod.snapshot_available():
        import pytest

        pytest.skip("libgsnative lacks gs_snapshot_windows")

    from gelly_streaming_tpu.core import driver as drv_mod
    from gelly_streaming_tpu.ops import triangles as tri_mod

    perf = tmp_path / "PERF.json"
    monkeypatch.setattr(tri_mod, "_PERF_PATH", str(perf))

    def configure(file_backend, process_backend, rows):
        perf.write_text(json.dumps(
            {"backend": file_backend, "host_snapshot": rows}))
        monkeypatch.setattr(jax, "default_backend",
                            lambda: process_backend)
        monkeypatch.setattr(drv_mod, "_SNAPSHOT_TIER", None)

    win = [{"parity": True, "scan_edges_per_s": 100,
            "native_edges_per_s": 900}]
    configure("cpu", "cpu", win)
    assert drv_mod.resolve_snapshot_tier() == "native"
    configure("cpu", "tpu", win)   # chip process: scan always stands
    assert drv_mod.resolve_snapshot_tier() == "scan"
    configure("tpu", "cpu", win)   # wrong-backend file
    assert drv_mod.resolve_snapshot_tier() == "scan"
    configure("cpu", "cpu", [{"parity": False,
                              "scan_edges_per_s": 100,
                              "native_edges_per_s": 900}])
    assert drv_mod.resolve_snapshot_tier() == "scan"
    configure("cpu", "cpu", [{"parity": True,
                              "scan_edges_per_s": 100,
                              "native_edges_per_s": 103}])
    assert drv_mod.resolve_snapshot_tier() == "scan"


def test_snapshot_tier_delta_parity():
    """emit_deltas on the native tier: delta streams identical to the
    scan tier's device-computed masks, window by window — INCLUDING
    across chunk boundaries (shrunken _SCAN_CHUNK so the chunk-start
    `prevs` copy is taken from in-place-mutated carried state) and
    across mid-stream vertex-bucket growth."""
    rng = np.random.default_rng(17)
    kw = dict(window_ms=0, edge_bucket=256, vertex_bucket=512,
              analytics=("degrees", "cc", "bipartite"),
              emit_deltas=True)
    a, b = _tier_drivers(**kw)
    a._SCAN_CHUNK = b._SCAN_CHUNK = 2  # many chunks per batch
    for n, hi in ((1024, 500), (768, 500), (1025, 1600)):
        # 3rd batch grows the vertex bucket mid-stream and ends on a
        # partial window (only the FINAL batch may: count-based
        # tumbling semantics)
        src = rng.integers(0, hi, n)
        dst = rng.integers(0, hi, n)
        ra, rb = a.run_arrays(src, dst), b.run_arrays(src, dst)
        assert len(ra) == len(rb)
        for x, y in zip(ra, rb):
            for field in ("delta_degrees", "delta_cc",
                          "delta_bipartite"):
                dx, dy = getattr(x, field), getattr(y, field)
                assert (dx is None) == (dy is None), field
                if dx is not None:
                    np.testing.assert_array_equal(dx[0], dy[0])
                    np.testing.assert_array_equal(dx[1], dy[1])

"""Serving front-end suite (core/serve.py): loopback end-to-end
digest parity with the direct cohort feed, typed wire rejections with
deterministic retry hints, bounded connections, slow-client shedding,
graceful drain (zero queued windows lost + sealed journal), the
file-tail source, and the /healthz `serve` section."""

import json
import os
import threading
import time

import numpy as np
import pytest

from gelly_streaming_tpu.core.serve import ServeClient, StreamServer
from gelly_streaming_tpu.core.tenancy import TenantCohort
from gelly_streaming_tpu.utils import faults
from gelly_streaming_tpu.utils import metrics
from gelly_streaming_tpu.utils import wal

EB, VB = 256, 512


def _stream(num_w, seed=0):
    rng = np.random.default_rng(seed)
    n = num_w * EB
    return (rng.integers(0, VB, n).astype(np.int32),
            rng.integers(0, VB, n).astype(np.int32))


def _oracle(src, dst):
    c = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    c.admit("t")
    out = []
    for i in range(0, len(src), EB):
        c.feed("t", src[i:i + EB], dst[i:i + EB])
        out += c.pump().get("t", [])
    return out + c.close("t")


@pytest.fixture
def server(tmp_path):
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    cohort.enable_wal(str(tmp_path / "wal"))
    cohort.enable_auto_checkpoint(str(tmp_path / "ckpt"),
                                  every_n_windows=2)
    srv = StreamServer(cohort, port=0).start()
    yield srv
    srv.close()


def test_loopback_digest_equals_direct_feed(server):
    src, dst = _stream(4, seed=1)
    want = _oracle(src, dst)
    cli = ServeClient(server.port)
    try:
        assert cli.admit("t")["ok"]
        got = []
        for i in range(0, len(src), EB):
            r = cli.feed("t", src[i:i + EB], dst[i:i + EB])
            assert r == {"ok": True, "accepted": EB}
            got += [row["summary"] for row in
                    cli.pump()["results"].get("t", [])]
        got += [row["summary"] for row in
                cli.close_tenant("t")["results"]]
    finally:
        cli.close()
    assert got == want


def test_backpressure_wire_response_carries_retry_hint(server,
                                                       monkeypatch):
    monkeypatch.setenv("GS_TENANT_QUEUE_WINDOWS", "1")
    src, dst = _stream(3, seed=2)
    cli = ServeClient(server.port)
    try:
        cli.admit("t")
        assert cli.feed("t", src[:EB], dst[:EB])["ok"]
        r1 = cli.feed("t", src[EB:3 * EB], dst[EB:3 * EB])
        assert r1["ok"] is False
        assert r1["error"] == "TenantBackpressure"
        assert r1["queued"] == EB and r1["capacity"] == EB
        assert r1["retry_after_s"] > 0
        # consecutive rejections double the hint (the deterministic
        # GS_STAGE_BACKOFF_S ladder), an accepted feed resets it
        r2 = cli.feed("t", src[EB:3 * EB], dst[EB:3 * EB])
        assert r2["retry_after_s"] == 2 * r1["retry_after_s"]
        cli.pump()  # drain the queue
        assert cli.feed("t", src[EB:2 * EB], dst[EB:2 * EB])["ok"]
        r3 = cli.feed("t", src[EB:3 * EB], dst[EB:3 * EB])
        assert r3["retry_after_s"] == r1["retry_after_s"]
    finally:
        cli.close()


def test_unknown_tenant_and_bad_request_are_typed(server):
    cli = ServeClient(server.port)
    try:
        r = cli.feed("ghost", [1], [2])
        assert r["ok"] is False and r["error"] == "TenantRejected"
        r = cli.request(op="nonsense")
        assert r["ok"] is False and r["error"] == "BadRequest"
    finally:
        cli.close()


def test_connection_cap_answers_typed_busy(tmp_path):
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0, max_connections=1).start()
    try:
        hold = ServeClient(srv.port)
        hold.request(op="status")  # registered as active
        extra = ServeClient(srv.port)
        r = extra.request(op="status")
        assert r["ok"] is False and r["error"] == "ServerBusy"
        assert r["retry_after_s"] > 0
        extra.close()
        hold.close()
    finally:
        srv.close()


@pytest.mark.faults
def test_slow_client_is_shed_not_wedged(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_SERVE_IDLE_S", "0.3")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0).start()
    src, dst = _stream(2, seed=3)
    try:
        slow = ServeClient(srv.port, timeout=30)
        slow.admit("t")
        slow.feed("t", src[:EB], dst[:EB])
        with faults.inject(faults.FaultSpec(
                site="serve_send", on_call=1, action="hang",
                seconds=1.0)):
            with pytest.raises((ConnectionError, OSError)):
                slow.pump()
        # the pump still serves a fresh connection afterwards
        cli = ServeClient(srv.port, timeout=30)
        assert cli.feed("t", src[EB:], dst[EB:])["ok"]
        assert len(cli.pump()["results"]["t"]) >= 1
        cli.close()
        slow.close()
    finally:
        srv.close()


def test_idle_connection_is_closed(tmp_path, monkeypatch):
    monkeypatch.setenv("GS_SERVE_IDLE_S", "0.3")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0).start()
    try:
        cli = ServeClient(srv.port, timeout=30)
        cli.request(op="status")
        time.sleep(0.8)  # idle past the deadline
        with pytest.raises((ConnectionError, OSError)):
            cli.request(op="status")
        cli.close()
    finally:
        srv.close()


def test_drain_finalizes_queued_windows_and_seals(tmp_path):
    """Graceful drain loses nothing: windows still queued at drain
    time come out finalized, the digest equals the keep-running run,
    and the journal is sealed."""
    src, dst = _stream(4, seed=4)
    want = _oracle(src, dst)
    wal_dir = str(tmp_path / "wal")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    cohort.enable_wal(wal_dir)
    cohort.enable_auto_checkpoint(str(tmp_path / "ckpt"),
                                  every_n_windows=2)
    srv = StreamServer(cohort, port=0).start()
    cli = ServeClient(srv.port)
    cli.admit("t")
    for i in range(0, len(src), EB):
        assert cli.feed("t", src[i:i + EB], dst[i:i + EB])["ok"]
    cli.close()
    summary = srv.drain(deadline_s=5)
    assert summary["sealed"] is True
    assert summary["drained_windows"] == 4
    got = [row["summary"] for row in srv.results["t"]]
    assert got == want
    assert wal.scan(wal_dir)["sealed"] is True
    # a checkpoint per tenant was force-flushed at the boundary
    assert os.path.exists(str(tmp_path / "ckpt" / "tenant_t.npz"))
    srv.close()


def test_file_tail_source_end_to_end(tmp_path):
    src, dst = _stream(2, seed=5)
    want = _oracle(src, dst)
    path = str(tmp_path / "feed.txt")
    with open(path, "w") as f:
        pass
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    cohort.enable_wal(str(tmp_path / "wal"))
    srv = StreamServer(cohort, port=0).start()
    try:
        srv.attach_file_tail(path, "t", poll_s=0.02)
        with open(path, "a") as f:
            for s, d in zip(src.tolist(), dst.tolist()):
                f.write("%d %d\n" % (s, d))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srv.pump_once()
            if sum(len(v) for v in srv.results.values()) >= 2:
                break
            time.sleep(0.05)
        got = [row["summary"] for row in srv.results["t"]]
        assert got == want[:len(got)] and len(got) == 2
        # the tailed edges went through the journal too
        assert wal.scan(str(tmp_path / "wal"))["offsets"]["t"] \
            == 2 * EB
    finally:
        srv.drain(deadline_s=5)
        srv.close()


def test_healthz_serve_section(server, monkeypatch):
    monkeypatch.setenv("GS_METRICS", "1")
    metrics.reset()
    try:
        cli = ServeClient(server.port)
        cli.admit("t")
        src, dst = _stream(1, seed=6)
        cli.feed("t", src, dst)
        cli.pump()
        snap = metrics.health_snapshot()
        sec = snap["serve"]
        assert sec["port"] == server.port
        assert sec["windows"] >= 1 and sec["requests"] >= 3
        assert sec["wal"]["edges"] == EB
        assert sec["draining"] is False
        status = cli.status()
        assert status["serve"]["port"] == server.port
        cli.close()
    finally:
        metrics.reset()


def test_results_sink_jsonl(tmp_path):
    src, dst = _stream(2, seed=7)
    results = str(tmp_path / "out.jsonl")
    cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
    srv = StreamServer(cohort, port=0,
                       results_path=results).start()
    try:
        cli = ServeClient(srv.port)
        cli.admit("t")
        cli.feed("t", src, dst)
        cli.pump()
        cli.close()
    finally:
        srv.drain(deadline_s=5)
        srv.close()
    rows = [json.loads(line) for line in open(results)]
    assert [r["window"] for r in rows] == [0, 1]
    assert all(r["tenant"] == "t" for r in rows)
    assert all("max_degree" in r["summary"] for r in rows)


def test_missing_fields_come_back_as_bad_request(server):
    """Review fix: a request missing required fields must produce the
    typed BadRequest the protocol promises, not an uncaught KeyError
    that kills the connection thread with no reply."""
    cli = ServeClient(server.port)
    try:
        r = cli.request(op="feed")  # no tenant/src/dst
        assert r["ok"] is False and r["error"] == "BadRequest"
        assert "KeyError" in r["message"]
        r = cli.request(op="admit")  # no tenant
        assert r["ok"] is False and r["error"] == "BadRequest"
        # the connection survived: a well-formed request still works
        assert cli.request(op="status")["ok"] is True
    finally:
        cli.close()

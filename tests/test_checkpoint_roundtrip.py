"""Checkpoint round-trips of every stateful engine: save → kill →
restore → continue must equal the uninterrupted run, THROUGH the .npz
file format (utils/checkpoint.save/restore — not just in-memory
state_dict hand-off), on every snapshot tier, plus the damaged-file
fallback paths. Tier-interchangeability is asserted explicitly: a
checkpoint taken on one tier resumes on another bit-exactly (the
carried layouts are shared by construction — DESIGN.md §9)."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu import native
from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
from gelly_streaming_tpu.utils import checkpoint as ck
from gelly_streaming_tpu.utils.candidates import (Candidates,
                                                  edge_to_candidate)
from gelly_streaming_tpu.utils.disjoint_set import DisjointSet

pytestmark = pytest.mark.faults

TIERS = ["resident", "scan", "host"] + (
    ["native"] if native.snapshot_available() else [])


def _stream(n=4096, v=384, seed=9):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, v, size=n).astype(np.int64),
            rng.integers(0, v, size=n).astype(np.int64))


def _key(results):
    return [(r.window_start, r.num_edges, r.vertex_ids.tolist(),
             None if r.degrees is None else r.degrees.tolist(),
             None if r.cc_labels is None else r.cc_labels.tolist(),
             None if r.bipartite_odd is None
             else r.bipartite_odd.tolist(),
             r.triangles)
            for r in results]


def _driver(tier, **kw):
    return StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=512, vertex_bucket=1024,
        snapshot_tier=tier, **kw)


@pytest.mark.parametrize("tier", TIERS)
def test_driver_save_kill_restore_continue(tier, tmp_path):
    src, dst = _stream()
    full = _key(_driver(tier).run_arrays(src, dst))

    path = str(tmp_path / "drv.npz")
    a = _driver(tier)
    half = len(src) // 2
    head = _key(a.run_arrays(src[:half], dst[:half]))
    ck.save(path, a.state_dict())
    del a  # the kill

    b = _driver(tier)
    assert b.try_resume(path)
    off = b.edges_done
    tail = _key(b.run_arrays(src[off:], dst[off:]))
    assert head + tail == full


@pytest.mark.parametrize("save_tier,resume_tier",
                         [(a, b) for a in TIERS for b in TIERS
                          if a != b])
def test_driver_checkpoints_are_tier_interchangeable(
        save_tier, resume_tier, tmp_path):
    src, dst = _stream()
    full = _key(_driver(save_tier).run_arrays(src, dst))
    path = str(tmp_path / "x.npz")
    a = _driver(save_tier)
    half = len(src) // 2
    head = _key(a.run_arrays(src[:half], dst[:half]))
    ck.save(path, a.state_dict())
    b = _driver(resume_tier)
    assert b.try_resume(path)
    tail = _key(b.run_arrays(src[b.edges_done:], dst[b.edges_done:]))
    assert head + tail == full


def test_summary_engine_save_kill_restore_continue(tmp_path):
    src, dst = _stream(n=2048, v=200)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)
    eb, vb = 256, 256
    full = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(src32, dst32)

    path = str(tmp_path / "eng.npz")
    a = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    head = a.process(src32[:4 * eb], dst32[:4 * eb])
    ck.save(path, a.state_dict())
    del a

    b = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert b.try_resume(path)
    off = b.resume_offset()
    tail = b.process(src32[off:], dst32[off:])
    assert head + tail == full


def test_summary_engine_auto_checkpoint_resume(tmp_path):
    src, dst = _stream(n=2048, v=200)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)
    eb, vb = 256, 256
    full = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(src32, dst32)
    path = str(tmp_path / "auto.npz")
    a = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    a.enable_auto_checkpoint(path, every_n_windows=2)
    head = a.process(src32[:5 * eb], dst32[:5 * eb])
    assert os.path.exists(path)
    b = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert b.try_resume(path)
    off = b.resume_offset()
    tail = b.process(src32[off:], dst32[off:])
    # positional at-least-once combine: keep the delivered prefix up
    # to the resume cursor, then the resumed suffix
    assert head[:off // eb] + tail == full


def test_resident_engine_cross_tier_resume(tmp_path):
    """A ResidentSummaryEngine checkpoint (device-resident donated
    carry, gathered at the super-batch boundary) resumes bit-exactly
    on (a) a fresh resident engine, (b) the scan-tier
    StreamSummaryEngine, and (c) the numpy HostSummaryEngine — the
    resident → resident / resident → scan / resident → host-twin legs
    of the ISSUE-9 acceptance bar (the carry layout is shared by
    construction, DESIGN.md §15)."""
    from gelly_streaming_tpu.ops.resident_engine import (
        ResidentSummaryEngine)
    from gelly_streaming_tpu.parallel.host_twin import HostSummaryEngine

    src, dst = _stream(n=2048, v=200)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)
    eb, vb = 256, 256
    full = ResidentSummaryEngine(
        edge_bucket=eb, vertex_bucket=vb).process(src32, dst32)
    # the resident engine equals the scan engine window-for-window
    assert full == StreamSummaryEngine(
        edge_bucket=eb, vertex_bucket=vb).process(src32, dst32)

    path = str(tmp_path / "res.npz")
    a = ResidentSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    head = a.process(src32[:4 * eb], dst32[:4 * eb])
    ck.save(path, a.state_dict())
    del a  # the kill

    for make in (lambda: ResidentSummaryEngine(edge_bucket=eb,
                                               vertex_bucket=vb),
                 lambda: StreamSummaryEngine(edge_bucket=eb,
                                             vertex_bucket=vb),
                 lambda: HostSummaryEngine(edge_bucket=eb,
                                           vertex_bucket=vb)):
        b = make()
        assert b.try_resume(path)
        off = b.resume_offset()
        tail = b.process(src32[off:], dst32[off:])
        assert head + tail == full, type(b).__name__

    # and the reverse leg: a SCAN-tier checkpoint resumes on resident
    c = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    c.process(src32[:4 * eb], dst32[:4 * eb])
    ck.save(path, c.state_dict())
    d = ResidentSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert d.try_resume(path)
    off = d.resume_offset()
    assert head + d.process(src32[off:], dst32[off:]) == full


def _cohort_streams(n_tenants=3, windows=6, eb=256, vb=256):
    out = {}
    for i in range(n_tenants):
        n = windows * eb - (eb // 3 if i == 1 else 0)
        s, d = _stream(n=n, v=vb - 10, seed=30 + i)
        out["t%d" % i] = (s.astype(np.int32), d.astype(np.int32))
    return out


def _pump_all(co, streams, cursors, out, piece):
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            co.feed(tid, s[c:c + piece], d[c:c + piece])
            cursors[tid] = min(len(s), c + piece)
            live = True
        for tid, res in co.pump().items():
            out.setdefault(tid, []).extend(res)


def test_tenant_cohort_kill_resume_cohort_to_cohort(tmp_path):
    """Per-tenant auto-checkpoints through the .npz format: kill the
    cohort mid-stream, resume EVERY tenant independently into a fresh
    cohort (resume_all), re-feed from each tenant's own offset — the
    positional at-least-once combine equals the uninterrupted
    sequential runs."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    eb, vb = 256, 256
    streams = _cohort_streams(eb=eb, vb=vb)
    full = {tid: StreamSummaryEngine(edge_bucket=eb,
                                     vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    co.enable_auto_checkpoint(str(tmp_path / "tenants"),
                              every_n_windows=2)
    head, cursors = {}, {tid: 0 for tid in streams}
    # feed/pump only the first 4 windows' worth, then "die"
    for _ in range(4):
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            co.feed(tid, s[c:c + eb], d[c:c + eb])
            cursors[tid] = min(len(s), c + eb)
        for tid, res in co.pump().items():
            head.setdefault(tid, []).extend(res)
    del co

    co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co2.admit(tid)
    co2.enable_auto_checkpoint(str(tmp_path / "tenants"),
                               every_n_windows=2)
    resumed = co2.resume_all()
    assert all(resumed.values())
    final = {}
    for tid, (s, d) in streams.items():
        off = co2.resume_offset(tid)
        assert off > 0 and off <= len(head[tid]) * eb
        final[tid] = head[tid][:off // eb]
    cursors = {tid: co2.resume_offset(tid) for tid in streams}
    _pump_all(co2, streams, cursors, final, 2 * eb)
    for tid in streams:
        final[tid].extend(co2.close(tid))
    assert final == full


def test_tenant_checkpoint_demotes_to_single_engine(tmp_path):
    """The cohort→single demotion ladder THROUGH the file format: a
    per-tenant cohort checkpoint restores into a plain
    StreamSummaryEngine (the state layouts are shared by
    construction) and the single engine finishes the stream
    bit-exactly."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    eb, vb = 256, 256
    streams = _cohort_streams(n_tenants=2, eb=eb, vb=vb)
    full = {tid: StreamSummaryEngine(edge_bucket=eb,
                                     vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    head, cursors = {}, {tid: 0 for tid in streams}
    for _ in range(3):
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            co.feed(tid, s[c:c + eb], d[c:c + eb])
            cursors[tid] = min(len(s), c + eb)
        for tid, res in co.pump().items():
            head.setdefault(tid, []).extend(res)
    path = str(tmp_path / "t0.npz")
    ck.save(path, co.tenant_state_dict("t0"))
    del co

    single = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert single.try_resume(path)
    off = single.resume_offset()
    s, d = streams["t0"]
    tail = single.process(s[off:], d[off:])
    assert head["t0"][:off // eb] + tail == full["t0"]


def test_single_engine_checkpoint_resumes_into_cohort(tmp_path):
    """The reverse ladder: a single-tenant StreamSummaryEngine
    checkpoint loads into a cohort tenant (load_tenant_state_dict)
    and the vmapped cohort finishes the stream bit-exactly — tenants
    can migrate INTO the cohort tier, not just fall out of it."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    eb, vb = 256, 256
    s, d = _stream(n=6 * eb, v=vb - 10, seed=44)
    s, d = s.astype(np.int32), d.astype(np.int32)
    full = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(s, d)

    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    head = eng.process(s[:3 * eb], d[:3 * eb])
    state = eng.state_dict()

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    co.admit("migrated")
    co.load_tenant_state_dict("migrated", state)
    off = co.resume_offset("migrated")
    assert off == 3 * eb
    co.feed("migrated", s[off:], d[off:])
    tail = co.pump().get("migrated", [])
    tail.extend(co.close("migrated"))
    assert head + tail == full


@pytest.mark.parametrize("target", ["resident", "scan", "single"])
def test_resident_cohort_kill_recovers_onto_any_tier(
        tmp_path, target, monkeypatch):
    """Resident-cohort migration contract: kill mid-super-batch (the
    donated [N, ...] stacked-carry state dies with the process; only
    the per-tenant super-batch-boundary checkpoint gathers survive)
    and recover onto (i) a fresh resident cohort, (ii) the scan-tier
    cohort with the tier pinned off, (iii) N plain single engines —
    every target finishes the streams bit-exactly equal to the
    fault-free oracle."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops import resident_engine

    eb, vb = 256, 256
    streams = _cohort_streams(eb=eb, vb=vb)
    full = {tid: StreamSummaryEngine(edge_bucket=eb,
                                     vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}

    monkeypatch.setenv("GS_COHORT_RESIDENT", "on")
    resident_engine._reset_resident_cohort()
    try:
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            co.admit(tid)
        co.enable_auto_checkpoint(str(tmp_path / "tenants"),
                                  every_n_windows=2)
        head, cursors = {}, {tid: 0 for tid in streams}
        for _ in range(4):
            for tid, (s, d) in streams.items():
                c = cursors[tid]
                co.feed(tid, s[c:c + eb], d[c:c + eb])
                cursors[tid] = min(len(s), c + eb)
            for tid, res in co.pump().items():
                head.setdefault(tid, []).extend(res)
        assert co.resident_dispatches > 0
        del co  # the kill: the resident stack is gone with it

        if target == "single":
            # (iii) demote-all: each tenant's checkpoint restores
            # into a plain single-stream engine
            for tid, (s, d) in streams.items():
                eng = StreamSummaryEngine(edge_bucket=eb,
                                          vertex_bucket=vb)
                assert eng.try_resume(str(
                    tmp_path / "tenants" / ("tenant_%s.npz" % tid)))
                off = eng.resume_offset()
                assert 0 < off <= len(head[tid]) * eb
                tail = eng.process(s[off:], d[off:])
                assert head[tid][:off // eb] + tail == full[tid]
            return

        if target == "scan":
            monkeypatch.setenv("GS_COHORT_RESIDENT", "off")
            resident_engine._reset_resident_cohort()
        co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            co2.admit(tid)
        co2.enable_auto_checkpoint(str(tmp_path / "tenants"),
                                   every_n_windows=2)
        resumed = co2.resume_all()
        assert all(resumed.values())
        final, cursors = {}, {}
        for tid in streams:
            off = co2.resume_offset(tid)
            assert 0 < off <= len(head[tid]) * eb
            final[tid] = head[tid][:off // eb]
            cursors[tid] = off
        _pump_all(co2, streams, cursors, final, 2 * eb)
        for tid in streams:
            final[tid].extend(co2.close(tid))
        if target == "resident":
            assert co2.resident_dispatches > 0
        else:
            assert co2.resident_dispatches == 0
        assert final == full
    finally:
        resident_engine._reset_resident_cohort()


def test_sharded_engine_state_roundtrip_through_file(tmp_path):
    """ShardedWindowEngine state through the npz format (skipped when
    this jax build cannot run while_loops under shard_map — the
    pre-existing mesh limitation, not a checkpoint defect)."""
    from gelly_streaming_tpu.parallel.mesh import make_mesh
    from gelly_streaming_tpu.parallel.sharded import ShardedWindowEngine

    src, dst = _stream(n=512, v=100)
    try:
        mesh = make_mesh(8)
        a = ShardedWindowEngine(mesh, num_vertices_bucket=256)
        a.degrees(src[:256].astype(np.int32),
                  dst[:256].astype(np.int32))
    except NotImplementedError as e:
        pytest.skip(f"mesh unsupported in this jax: {e}")
    path = str(tmp_path / "sh.npz")
    ck.save(path, a.state_dict())
    b = ShardedWindowEngine(mesh, num_vertices_bucket=256)
    b.load_state_dict(ck.restore(path))
    ga = a.degrees(src[256:].astype(np.int32),
                   dst[256:].astype(np.int32))
    gb = b.degrees(src[256:].astype(np.int32),
                   dst[256:].astype(np.int32))
    assert np.array_equal(np.asarray(ga), np.asarray(gb))


def test_driver_mesh_checkpoint_resumes_on_one_device_and_host(
        tmp_path):
    """Cross-MESH resume, driver level: a checkpoint taken on a 4-way
    mesh resumes bit-exactly on 1 device (scan tier) AND on the numpy
    host tier — the engine slabs are gathered replicated state, so
    they convert to the single-chip mirrors on load."""
    from gelly_streaming_tpu.parallel.mesh import make_mesh

    src, dst = _stream(n=8 * 512, v=700)

    def mk(**kw):
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=512, vertex_bucket=1024,
            analytics=("degrees", "cc", "bipartite", "triangles"),
            **kw)

    full = _key(mk().run_arrays(src, dst))
    a = mk(mesh=make_mesh(4))
    head = _key(a.run_arrays(src[:4 * 512], dst[:4 * 512]))
    path = str(tmp_path / "mesh.npz")
    ck.save(path, a.state_dict())
    for tier in ("scan", "host"):
        b = mk(snapshot_tier=tier)
        assert b.try_resume(path)
        off = b.edges_done
        tail = _key(b.run_arrays(src[off:], dst[off:]))
        assert head + tail == full, tier
    # and the other direction: a single-chip checkpoint onto a mesh
    c = mk()
    head2 = _key(c.run_arrays(src[:4 * 512], dst[:4 * 512]))
    path2 = str(tmp_path / "single.npz")
    ck.save(path2, c.state_dict())
    d = mk(mesh=make_mesh(4))
    assert d.try_resume(path2)
    tail2 = _key(d.run_arrays(src[d.edges_done:], dst[d.edges_done:]))
    assert head2 + tail2 == full


def test_sharded_summary_checkpoint_cross_mesh_and_twin(tmp_path):
    """Cross-MESH resume, engine level: a 4-shard ShardedSummaryEngine
    checkpoint (through the npz format) continues bit-exactly on the
    single-chip engine, on the numpy host twin, and on a 2-shard mesh
    — the shard-count-independent gathered layout."""
    from gelly_streaming_tpu.parallel.host_twin import HostSummaryEngine
    from gelly_streaming_tpu.parallel.mesh import make_mesh
    from gelly_streaming_tpu.parallel.sharded import ShardedSummaryEngine

    src, dst = _stream(n=2048, v=200)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)
    eb, vb = 256, 256
    full = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(src32, dst32)
    a = ShardedSummaryEngine(make_mesh(4), edge_bucket=eb,
                             vertex_bucket=vb)
    head = a.process(src32[:4 * eb], dst32[:4 * eb])
    assert a.state_dict()["mesh_shape"] == [4]
    path = str(tmp_path / "sh4.npz")
    ck.save(path, a.state_dict())

    resumers = [
        StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb),
        HostSummaryEngine(edge_bucket=eb, vertex_bucket=vb),
        ShardedSummaryEngine(make_mesh(2), edge_bucket=eb,
                             vertex_bucket=vb),
    ]
    for eng in resumers:
        assert eng.try_resume(path), type(eng).__name__
        off = eng.resume_offset()
        assert off == 4 * eb
        tail = eng.process(src32[off:], dst32[off:])
        assert head + tail == full, type(eng).__name__


def test_disjoint_set_roundtrip_through_file(tmp_path):
    edges = [(1, 2), (3, 4), (2, 3), (7, 8), (9, 7), (4, 9)]
    full = DisjointSet()
    for a, b in edges:
        full.union(a, b)

    half = DisjointSet()
    for a, b in edges[:3]:
        half.union(a, b)
    path = str(tmp_path / "ds.npz")
    ck.save(path, half.state_dict())
    resumed = DisjointSet()
    resumed.load_state_dict(ck.restore(path))
    for a, b in edges[3:]:
        resumed.union(a, b)
    assert repr(resumed) == repr(full)


def test_candidates_roundtrip_through_file(tmp_path):
    edges = [(1, 2), (2, 3), (3, 4), (4, 1), (5, 6), (4, 5)]
    full = Candidates(True)
    for a, b in edges:
        full = full.merge(edge_to_candidate(a, b))

    half = Candidates(True)
    for a, b in edges[:3]:
        half = half.merge(edge_to_candidate(a, b))
    path = str(tmp_path / "cand.npz")
    ck.save(path, half.state_dict())
    resumed = Candidates(True)
    resumed.load_state_dict(ck.restore(path))
    for a, b in edges[3:]:
        resumed = resumed.merge(edge_to_candidate(a, b))
    assert repr(resumed) == repr(full)


def test_truncated_file_fallback_and_total_loss(tmp_path):
    path = str(tmp_path / "gen.npz")
    ck.save(path, {"v": np.arange(4), "n": 1})
    ck.save(path, {"v": np.arange(5), "n": 2})
    with open(path, "r+b") as f:
        f.truncate(10)  # external damage to the newest generation
    with pytest.raises(ck.CheckpointCorrupt) as ei:
        ck.restore(path)
    assert ei.value.path == path
    tree, used = ck.load_latest(path)
    assert tree["n"] == 1 and used == ck.prev_path(path)
    with open(used, "r+b") as f:
        f.truncate(10)  # both generations gone
    with pytest.raises(ck.CheckpointCorrupt):
        ck.load_latest(path)
    assert ck.load_latest(str(tmp_path / "missing.npz")) is None


def test_save_is_atomic_and_tmp_is_process_unique(tmp_path):
    path = str(tmp_path / "a.npz")
    ck.save(path, {"x": np.arange(3)})

    class Unsaveable:
        pass

    with pytest.raises(TypeError):
        ck.save(path, {"bad": Unsaveable()})
    # the failed save leaked no tmp and left the good file intact
    assert sorted(os.listdir(tmp_path)) == ["a.npz"]
    assert ck.restore(path)["x"].tolist() == [0, 1, 2]


# ======================================================================
# WAL kill→replay exactness (utils/wal.py; ISSUE 12): with a journal
# armed, a kill at ANY point — including BETWEEN the journal append
# and the queue enqueue — recovers to results bit-identical to the
# fault-free run, on the cohort, single-engine, and driver paths.
# ======================================================================
def _wal_stream(num_w, eb, vb, seed):
    rng = np.random.default_rng(seed)
    n = num_w * eb
    return (rng.integers(0, vb, n).astype(np.int32),
            rng.integers(0, vb, n).astype(np.int32))


def test_engine_wal_kill_and_replay_exact(tmp_path):
    from gelly_streaming_tpu.utils import faults

    eb, vb, num_w = 256, 512, 8
    src, dst = _wal_stream(num_w, eb, vb, seed=21)
    baseline = StreamSummaryEngine(edge_bucket=eb,
                                   vertex_bucket=vb).process(src, dst)

    ckpt = str(tmp_path / "eng.npz")
    a = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert a.enable_wal(str(tmp_path / "wal"))
    a.enable_auto_checkpoint(ckpt, every_n_windows=2)
    out = []
    killed = False
    try:
        with faults.inject(faults.FaultSpec(
                site="dispatch", on_call=3, fatal=True)):
            for w in range(0, num_w, 2):
                out += a.process(src[w * eb:(w + 2) * eb],
                                 dst[w * eb:(w + 2) * eb])
    except faults.InjectedFault:
        killed = True
    assert killed

    b = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert b.enable_wal(str(tmp_path / "wal"))
    replayed = b.resume_and_replay(ckpt)
    # positional at-least-once combine: checkpointed prefix + replay
    final = out[:b.windows_done - len(replayed)] + replayed
    # the caller's view: delivered windows + the recovered tail, then
    # feed the rest of the stream normally
    off = b.resume_offset()
    final += b.process(src[off:], dst[off:])
    assert final == baseline


def test_engine_wal_kill_between_append_and_fold(tmp_path):
    """The narrowest window: the journal append returned but the fold
    never ran (kill at the wal_enqueue site). Replay must recover the
    accepted-but-never-processed edges."""
    from gelly_streaming_tpu.utils import faults

    eb, vb, num_w = 256, 512, 4
    src, dst = _wal_stream(num_w, eb, vb, seed=22)
    baseline = StreamSummaryEngine(edge_bucket=eb,
                                   vertex_bucket=vb).process(src, dst)

    ckpt = str(tmp_path / "eng.npz")
    a = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert a.enable_wal(str(tmp_path / "wal"))
    a.enable_auto_checkpoint(ckpt, every_n_windows=2)
    out = a.process(src[:2 * eb], dst[:2 * eb])
    with pytest.raises(faults.InjectedFault):
        with faults.inject(faults.FaultSpec(
                site="wal_enqueue", on_call=1, fatal=True)):
            a.process(src[2 * eb:], dst[2 * eb:])

    b = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    assert b.enable_wal(str(tmp_path / "wal"))
    replayed = b.resume_and_replay(ckpt)
    assert len(replayed) == 2  # the journaled-but-unfolded windows
    assert out + replayed == baseline


def test_cohort_wal_kill_between_append_and_enqueue(tmp_path):
    """Cohort flavor of the narrowest window: feed() journaled the
    batch, the kill landed before the queue concatenate. recover()
    must replay it; the caller was told nothing (no ack), so the
    at-least-once re-send of the SAME batch must not double-fold
    (replay already covers it — the re-send is what a real producer
    does only for un-acked batches, so here the recovered run feeds
    the NEXT batches only)."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import faults

    eb, vb, num_w = 256, 512, 4
    src, dst = _wal_stream(num_w, eb, vb, seed=23)
    oracle = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    oracle.admit("t")
    oracle.feed("t", src, dst)
    want = oracle.pump()["t"]

    wal_dir = str(tmp_path / "wal")
    a = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert a.enable_wal(wal_dir)
    a.enable_auto_checkpoint(str(tmp_path / "ck"), every_n_windows=2)
    a.admit("t")
    a.feed("t", src[:2 * eb], dst[:2 * eb])
    got = a.pump()["t"]
    with pytest.raises(faults.InjectedFault):
        with faults.inject(faults.FaultSpec(
                site="wal_enqueue", on_call=1, fatal=True)):
            a.feed("t", src[2 * eb:], dst[2 * eb:])

    b = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert b.enable_wal(wal_dir)
    b.enable_auto_checkpoint(str(tmp_path / "ck"), every_n_windows=2)
    info = b.recover()
    assert info["resumed"]["t"] is True
    assert info["replayed_edges"]["t"] == 2 * eb
    got += b.pump()["t"]
    assert got == want


def test_cohort_wal_kill_mid_dispatch_replay_exact(tmp_path):
    """Kill mid-cohort-dispatch (after several checkpointed rounds):
    recover() + continued feeding equals the fault-free run, window
    for window, for every tenant."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import faults

    eb, vb, num_w = 256, 512, 8
    streams = {"a": _wal_stream(num_w, eb, vb, 24),
               "b": _wal_stream(num_w, eb, vb, 25)}
    want = {}
    oracle = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        oracle.admit(tid)
    for w in range(num_w):
        for tid, (s, d) in streams.items():
            oracle.feed(tid, s[w * eb:(w + 1) * eb],
                        d[w * eb:(w + 1) * eb])
        for tid, res in oracle.pump().items():
            want.setdefault(tid, []).extend(res)

    wal_dir = str(tmp_path / "wal")
    a = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert a.enable_wal(wal_dir)
    a.enable_auto_checkpoint(str(tmp_path / "ck"), every_n_windows=2)
    for tid in streams:
        a.admit(tid)
    got = {tid: {} for tid in streams}
    killed_at = None
    try:
        with faults.inject(faults.FaultSpec(
                site="cohort_dispatch", on_call=5, fatal=True)):
            for w in range(num_w):
                for tid, (s, d) in sorted(streams.items()):
                    a.feed(tid, s[w * eb:(w + 1) * eb],
                           d[w * eb:(w + 1) * eb])
                for tid, res in a.pump().items():
                    base = a.windows_done(tid) - len(res)
                    for i, r in enumerate(res):
                        got[tid][base + i] = r
    except faults.InjectedFault:
        killed_at = w
    assert killed_at is not None

    b = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert b.enable_wal(wal_dir)
    b.enable_auto_checkpoint(str(tmp_path / "ck"), every_n_windows=2)
    info = b.recover()
    assert any(info["resumed"].values())
    for tid, res in b.pump().items():  # the replayed suffix
        base = b.windows_done(tid) - len(res)
        for i, r in enumerate(res):
            got[tid][base + i] = r
    for w in range(killed_at + 1, num_w):
        for tid, (s, d) in sorted(streams.items()):
            b.feed(tid, s[w * eb:(w + 1) * eb],
                   d[w * eb:(w + 1) * eb])
        for tid, res in b.pump().items():
            base = b.windows_done(tid) - len(res)
            for i, r in enumerate(res):
                got[tid][base + i] = r
    for tid in streams:
        final = [got[tid][k] for k in sorted(got[tid])]
        assert final == want[tid], tid


def test_driver_wal_kill_and_replay_exact(tmp_path):
    """The driver's LIVE feed path (run_arrays, count-based windows)
    with the journal armed: kill mid-stream, resume_and_replay
    reproduces the un-checkpointed windows bit-exactly."""
    from gelly_streaming_tpu.utils import faults

    src, dst = _stream(n=4096, v=384, seed=26)
    eb = 512
    full = _key(StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=eb,
        vertex_bucket=1024).run_arrays(src, dst))

    ckpt = str(tmp_path / "drv.npz")
    a = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                 vertex_bucket=1024)
    assert a.enable_wal(str(tmp_path / "wal"))
    a.enable_auto_checkpoint(ckpt, every_n_windows=2)
    out = []
    killed = False
    try:
        with faults.inject(faults.FaultSpec(
                site="dispatch", on_call=3, fatal=True)):
            for i in range(0, len(src), 2 * eb):
                out += _key(a.run_arrays(src[i:i + 2 * eb],
                                         dst[i:i + 2 * eb]))
    except faults.InjectedFault:
        killed = True
    assert killed

    b = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                 vertex_bucket=1024)
    assert b.enable_wal(str(tmp_path / "wal"))
    replayed = _key(b.resume_and_replay(ckpt))
    final = out[:b.windows_done - len(replayed)] + replayed
    off = b.edges_done
    final += _key(b.run_arrays(src[off:], dst[off:]))
    assert final == full


def test_driver_wal_checkpoint_offset_contract(tmp_path):
    """The checkpoint carries wal_offset == edges_done, and a
    hand-edited divergence is refused loudly."""
    src, dst = _stream(n=1024, v=128, seed=27)
    a = StreamingAnalyticsDriver(window_ms=0, edge_bucket=512,
                                 vertex_bucket=1024)
    a.run_arrays(src, dst)
    state = a.state_dict()
    assert state["wal_offset"] == state["edges_done"] == len(src)
    state["wal_offset"] = 7
    b = StreamingAnalyticsDriver(window_ms=0, edge_bucket=512,
                                 vertex_bucket=1024)
    with pytest.raises(ValueError, match="wal_offset"):
        b.load_state_dict(state)

"""Incremental vertex-id interning for streaming state.

The host half of SURVEY.md §7's "vertex-id interning at stream rate":
arbitrary hashable vertex ids get stable dense int32 slots, assigned
once on first sight, so device-resident per-vertex state (degree
vectors, CC labels) can live in fixed arrays that grow by bucket
doubling instead of being rebuilt per batch.
"""

from __future__ import annotations

from typing import Dict, Hashable, List

import numpy as np

from . import telemetry


class IncrementalInterner:
    def __init__(self):
        self._to_dense: Dict[Hashable, int] = {}
        self._to_id: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._to_id)

    def intern_array(self, ids: np.ndarray) -> np.ndarray:
        """Map ids to dense slots, assigning new slots on first sight."""
        out = np.empty(len(ids), np.int32)
        to_dense = self._to_dense
        to_id = self._to_id
        for i, v in enumerate(ids.tolist()):
            slot = to_dense.get(v)
            if slot is None:
                slot = len(to_id)
                to_dense[v] = slot
                to_id.append(v)
            out[i] = slot
        return out

    def id_of(self, dense: int) -> Hashable:
        return self._to_id[dense]

    def ids_of(self, dense: np.ndarray) -> List[Hashable]:
        return [self._to_id[i] for i in dense.tolist()]


def parallel_intern_arrays(interner, arrays):
    """Intern several arrays with the heavy per-element work spread
    across the ingress prep pool while producing EXACTLY the slot
    assignment of interning them sequentially in order.

    Scheme (deterministic by construction, not by locking):
      1. parallel: per array, the FIRST-OCCURRENCE-ordered unique ids
         (np.unique + argsort of first indices — pure numpy, GIL-
         dropping) and the inverse map back to positions;
      2. sequential: intern only those unique lists, in array order —
         new ids meet the interner in the same first-occurrence order
         the sequential loop would present, so slots are identical;
      3. parallel: scatter the dense unique slots back through each
         array's inverse map.
    The sequential core shrinks from O(total elements) hash-map work
    to O(total uniques). Falls back to plain sequential interning when
    the pool is disabled — same outputs either way (the worker-pool
    determinism contract).

    Returns (dense_arrays, sizes): sizes[i] = len(interner) after
    array i — the per-window vertex cursor the driver's snapshot
    slicing needs."""
    from ..ops import ingress_pipeline

    arrays = [np.asarray(a) for a in arrays]
    # np.unique needs ORDERABLE elements, and floats are excluded too:
    # np.unique collapses NaNs into one value while the dict-based
    # interner gives every NaN its own slot (NaN != NaN), which would
    # make slots pool-dependent. Non-qualifying streams (object
    # arrays — the Python interner's arbitrary-hashable contract —
    # and float ids) take the sequential loop regardless of the pool,
    # so the parallel scheme never changes accepted inputs or slots.
    orderable = all(a.dtype.kind in "biuSU" for a in arrays)
    if (not orderable or not ingress_pipeline.pipeline_enabled()
            or len(arrays) < 2):
        out = []
        sizes = []
        for a in arrays:
            out.append(interner.intern_array(a))
            sizes.append(len(interner))
        return out, sizes

    def uniques(a):
        if a.size == 0:
            return a, np.zeros(0, np.int64)
        uniq, first, inv = np.unique(a, return_index=True,
                                     return_inverse=True)
        order = np.argsort(first, kind="stable")
        rank = np.empty(len(order), np.int64)
        rank[order] = np.arange(len(order))
        return uniq[order], rank[inv.reshape(-1)]

    pairs = ingress_pipeline.map_ordered(uniques, arrays)
    dense = []
    sizes = []
    for u, _inv in pairs:
        dense.append(interner.intern_array(u))
        sizes.append(len(interner))

    def scatter(i):
        d, (_u, inv) = dense[i], pairs[i]
        return (d[inv].astype(np.int32) if len(d)
                else np.zeros(0, np.int32))

    return ingress_pipeline.map_ordered(scatter,
                                        range(len(arrays))), sizes


def make_interner(ids_sample: np.ndarray = None):
    """Pick the native C++ interner for integer id streams, the Python
    one otherwise (or when the native library can't build)."""
    if ids_sample is None or np.issubdtype(
        np.asarray(ids_sample).dtype, np.integer
    ):
        try:
            from .. import native

            if native.available():
                return native.NativeInterner()
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="interner", fallback="python",
                            error="%s: %s" % (type(e).__name__, e))
    return IncrementalInterner()

"""Incremental vertex-id interning for streaming state.

The host half of SURVEY.md §7's "vertex-id interning at stream rate":
arbitrary hashable vertex ids get stable dense int32 slots, assigned
once on first sight, so device-resident per-vertex state (degree
vectors, CC labels) can live in fixed arrays that grow by bucket
doubling instead of being rebuilt per batch.
"""

from __future__ import annotations

from typing import Dict, Hashable, List

import numpy as np


class IncrementalInterner:
    def __init__(self):
        self._to_dense: Dict[Hashable, int] = {}
        self._to_id: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._to_id)

    def intern_array(self, ids: np.ndarray) -> np.ndarray:
        """Map ids to dense slots, assigning new slots on first sight."""
        out = np.empty(len(ids), np.int32)
        to_dense = self._to_dense
        to_id = self._to_id
        for i, v in enumerate(ids.tolist()):
            slot = to_dense.get(v)
            if slot is None:
                slot = len(to_id)
                to_dense[v] = slot
                to_id.append(v)
            out[i] = slot
        return out

    def id_of(self, dense: int) -> Hashable:
        return self._to_id[dense]

    def ids_of(self, dense: np.ndarray) -> List[Hashable]:
        return [self._to_id[i] for i in dense.tolist()]


def make_interner(ids_sample: np.ndarray = None):
    """Pick the native C++ interner for integer id streams, the Python
    one otherwise (or when the native library can't build)."""
    if ids_sample is None or np.issubdtype(
        np.asarray(ids_sample).dtype, np.integer
    ):
        try:
            from .. import native

            if native.available():
                return native.NativeInterner()
        except Exception:
            pass
    return IncrementalInterner()

"""Checkpoint / resume.

Absent in the reference (SURVEY.md §5.4): its operator state (per-key
hash maps, pane accumulators, merger state) is implicit in the JVM.
Here every stateful engine exposes `state_dict()` / `load_state_dict()`
over plain pytrees (nested dicts of numpy arrays / scalars / lists), so
a streaming job can snapshot between windows and resume after failure —
the recovery story the reference's combine-fn javadoc alludes to
(library/ConnectedComponents.java:117-118) but never implements.

Storage: a single .npz for array leaves + a JSON sidecar-free encoding
of the tree structure (object leaves go through repr-safe lists).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np

_ARRAY_KEY = "__arrays__"


def _key(k):
    """Encode a dict key preserving its type across the JSON spec."""
    if isinstance(k, bool) or not isinstance(k, (int, str)):
        raise TypeError(f"unsupported checkpoint dict key: {k!r}")
    return ["i", k] if isinstance(k, int) else ["s", k]


def _unkey(pair):
    kind, k = pair
    return int(k) if kind == "i" else k


def _flatten(tree: Any, arrays: Dict[str, np.ndarray]):
    if isinstance(tree, dict):
        return {
            "t": "dict",
            "items": [
                [_key(k), _flatten(v, arrays)] for k, v in tree.items()
            ],
        }
    if isinstance(tree, np.ndarray):
        # sequential keys: path-derived strings can collide ("a.b" key vs
        # nested a→b), silently dropping a leaf on restore
        key = f"a{len(arrays)}"
        arrays[key] = tree
        return {"t": "array", "key": key}
    if isinstance(tree, (list, tuple)):
        return {
            "t": "list" if isinstance(tree, list) else "tuple",
            "items": [_flatten(v, arrays) for v in tree],
        }
    if isinstance(tree, (int, float, str, bool)) or tree is None:
        return {"t": "scalar", "v": tree}
    raise TypeError(f"unsupported checkpoint leaf: {type(tree)}")


def _unflatten(node: dict, arrays) -> Any:
    kind = node["t"]
    if kind == "dict":
        return {_unkey(k): _unflatten(v, arrays) for k, v in node["items"]}
    if kind == "array":
        return arrays[node["key"]]
    if kind == "list":
        return [_unflatten(v, arrays) for v in node["items"]]
    if kind == "tuple":
        return tuple(_unflatten(v, arrays) for v in node["items"])
    if kind == "scalar":
        return node["v"]
    raise TypeError(kind)


def save(path: str, tree: Any) -> None:
    arrays: Dict[str, np.ndarray] = {}
    spec = _flatten(tree, arrays)
    arrays[_ARRAY_KEY + "spec"] = np.frombuffer(
        json.dumps(spec).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp"
    np.savez_compressed(tmp, **arrays)
    # np.savez appends .npz to the filename it is given
    os.replace(tmp + ".npz" if not tmp.endswith(".npz") else tmp, path)


def restore(path: str) -> Any:
    with np.load(path, allow_pickle=False) as data:
        spec = json.loads(bytes(data[_ARRAY_KEY + "spec"]).decode())
        arrays = {k: data[k] for k in data.files if k != _ARRAY_KEY + "spec"}
    return _unflatten(spec, arrays)

"""Checkpoint / resume.

Absent in the reference (SURVEY.md §5.4): its operator state (per-key
hash maps, pane accumulators, merger state) is implicit in the JVM.
Here every stateful engine exposes `state_dict()` / `load_state_dict()`
over plain pytrees (nested dicts of numpy arrays / scalars / lists), so
a streaming job can snapshot between windows and resume after failure —
the recovery story the reference's combine-fn javadoc alludes to
(library/ConnectedComponents.java:117-118) but never implements.

Storage: a single .npz for array leaves + a JSON sidecar-free encoding
of the tree structure (object leaves go through repr-safe lists).

Durability contract (the failure-recovery runtime leans on all three):
- `save` is atomic (tmp + rename; the tmp name is process-unique and
  unlinked on ANY failure) and ROTATES: the previous checkpoint
  survives one generation as `path + ".prev"`, so external damage to
  the newest file never strands a resumable job.
- `restore` of a truncated/corrupt file raises a typed
  `CheckpointCorrupt` carrying the path — callers distinguish damage
  (fall back to the previous generation, or start fresh) from
  operational failures (permissions, EIO), which still raise raw.
- `load_latest` is the resume-side pairing: newest generation first,
  rotation fallback on corruption, `None` when nothing usable exists.

`CheckpointPolicy` is the shared cadence object (every N windows
and/or every T seconds, injectable clock for deterministic tests) the
driver and the fused summary engines consult at their window/chunk
boundaries.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from . import faults
from . import telemetry

_ARRAY_KEY = "__arrays__"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file exists but cannot be decoded (truncation,
    bit-flips, torn external writes). `path` names the damaged file."""

    def __init__(self, path: str, cause: BaseException):
        super().__init__(
            f"checkpoint {path!r} is corrupt "
            f"({type(cause).__name__}: {cause})")
        self.path = path


def _key(k):
    """Encode a dict key preserving its type across the JSON spec."""
    if isinstance(k, bool) or not isinstance(k, (int, str)):
        raise TypeError(f"unsupported checkpoint dict key: {k!r}")
    return ["i", k] if isinstance(k, int) else ["s", k]


def _unkey(pair):
    kind, k = pair
    return int(k) if kind == "i" else k


def _flatten(tree: Any, arrays: Dict[str, np.ndarray]):
    if isinstance(tree, dict):
        return {
            "t": "dict",
            "items": [
                [_key(k), _flatten(v, arrays)] for k, v in tree.items()
            ],
        }
    if isinstance(tree, np.ndarray):
        # sequential keys: path-derived strings can collide ("a.b" key vs
        # nested a→b), silently dropping a leaf on restore
        key = f"a{len(arrays)}"
        arrays[key] = tree
        return {"t": "array", "key": key}
    if isinstance(tree, (list, tuple)):
        return {
            "t": "list" if isinstance(tree, list) else "tuple",
            "items": [_flatten(v, arrays) for v in tree],
        }
    if isinstance(tree, (int, float, str, bool)) or tree is None:
        return {"t": "scalar", "v": tree}
    raise TypeError(f"unsupported checkpoint leaf: {type(tree)}")


def _unflatten(node: dict, arrays) -> Any:
    kind = node["t"]
    if kind == "dict":
        return {_unkey(k): _unflatten(v, arrays) for k, v in node["items"]}
    if kind == "array":
        return arrays[node["key"]]
    if kind == "list":
        return [_unflatten(v, arrays) for v in node["items"]]
    if kind == "tuple":
        return tuple(_unflatten(v, arrays) for v in node["items"])
    if kind == "scalar":
        return node["v"]
    raise TypeError(kind)


def prev_path(path: str) -> str:
    """The rotated previous-generation file `save` keeps beside
    `path` (last-2 retention)."""
    return path + ".prev"


def save(path: str, tree: Any) -> None:
    """Atomically write `tree` to `path`, rotating the existing file
    to `prev_path(path)` first. The tmp name carries the pid so two
    writers (e.g. a live job and an operator-driven manual snapshot)
    can never clobber each other's in-progress tmp; the tmp is
    unlinked on any failure instead of leaking beside the
    checkpoint."""
    arrays: Dict[str, np.ndarray] = {}
    spec = _flatten(tree, arrays)
    arrays[_ARRAY_KEY + "spec"] = np.frombuffer(
        json.dumps(spec).encode(), dtype=np.uint8
    )
    # np.savez appends .npz to the filename it is given
    tmp = "%s.tmp.%d" % (path, os.getpid())
    written = tmp + ".npz"
    try:
        np.savez_compressed(tmp, **arrays)
        # fsync the tmp BEFORE the rename: rename-then-crash must
        # never install a checkpoint whose bytes were still in the
        # page cache — the WAL replays only past the offset this file
        # claims, so a torn newest generation would otherwise cost a
        # rotation fallback it didn't need (utils/wal.py leans on
        # this; the same discipline as the journal's own fsyncs)
        fd = os.open(written, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        if os.path.exists(path):
            # one-generation rotation: between this replace and the
            # next, `path` is momentarily absent — restore-side
            # fallback (load_latest) covers a crash in that window
            os.replace(path, prev_path(path))
        os.replace(written, path)
    finally:
        if os.path.exists(written):
            try:
                os.unlink(written)
            except OSError:
                pass
    # durable flight-recorder stamp: the completed save is exactly the
    # recovery point a post-mortem needs to locate
    telemetry.event("checkpoint_saved", durable=True, path=path)
    # external-damage injection point for the fault suite: fires AFTER
    # the atomic replace, modelling damage to a completed checkpoint
    faults.fire("ckpt_save", path)


def restore(path: str) -> Any:
    """Decode one checkpoint file. Damage (truncation, bit-flipped
    deflate streams, mangled payloads) raises CheckpointCorrupt;
    operational failures (missing file, permissions, EIO) raise their
    raw OSError so callers never silently reprocess a fixable
    problem."""
    import zipfile
    import zlib

    faults.fire("ckpt_restore", path)
    try:
        with np.load(path, allow_pickle=False) as data:
            spec = json.loads(bytes(data[_ARRAY_KEY + "spec"]).decode())
            arrays = {k: data[k] for k in data.files
                      if k != _ARRAY_KEY + "spec"}
        tree = _unflatten(spec, arrays)
        telemetry.event("checkpoint_restored", path=path)
        return tree
    except (zipfile.BadZipFile, zlib.error, ValueError, KeyError,
            EOFError, json.JSONDecodeError, TypeError,
            IndexError) as e:
        # the failure shapes np.load / the spec decode produce for
        # damaged archives: truncation -> BadZipFile/EOFError,
        # bit-flipped deflate -> zlib.error, mangled payloads ->
        # ValueError/KeyError/TypeError/IndexError/JSONDecodeError
        telemetry.event("checkpoint_corrupt", durable=True, path=path,
                        cause=type(e).__name__)
        raise CheckpointCorrupt(path, e) from e


def load_latest(path: str):
    """Resume-side restore with rotation fallback: try `path`, then
    `prev_path(path)` when the newest generation is corrupt or absent.
    Returns (tree, used_path), or None when no generation exists;
    raises CheckpointCorrupt only when every existing generation is
    damaged."""
    corrupt = None
    for cand in (path, prev_path(path)):
        if not os.path.exists(cand):
            continue
        try:
            return restore(cand), cand
        except CheckpointCorrupt as e:
            corrupt = e
    if corrupt is not None:
        raise corrupt
    return None


@dataclasses.dataclass
class CheckpointPolicy:
    """When to snapshot: every `every_n_windows` processed windows
    and/or every `every_seconds` of wall time, whichever comes first
    (either 0 disables that trigger). Consumers ask `due(windows_done)`
    at their window/chunk boundaries and `mark(windows_done)` after
    staging a snapshot. `clock` is injectable so the time trigger is
    deterministic under test (and in tools/chaos_run.py)."""

    every_n_windows: int = 0
    every_seconds: float = 0.0
    clock: Callable[[], float] = time.monotonic

    def __post_init__(self):
        if self.every_n_windows < 0 or self.every_seconds < 0:
            raise ValueError("checkpoint cadences must be >= 0")
        self._last_w = 0
        self._last_t: Optional[float] = None

    def enabled(self) -> bool:
        return self.every_n_windows > 0 or self.every_seconds > 0

    def due(self, windows_done: int) -> bool:
        if self.every_n_windows > 0 and (
                windows_done // self.every_n_windows
                > self._last_w // self.every_n_windows):
            return True
        if self.every_seconds > 0:
            now = self.clock()
            if self._last_t is None:
                # the first due() anchors the time base: a job that
                # dies before its first interval elapses simply
                # restarts from the stream head
                self._last_t = now
            elif now - self._last_t >= self.every_seconds:
                return True
        return False

    def mark(self, windows_done: int) -> None:
        self._last_w = windows_done
        if self.every_seconds > 0:
            self._last_t = self.clock()

"""The typed `GS_*` knob registry — the ONE place environment knobs
are declared, parsed, and documented.

Before this module, 33 `GS_*` knobs were read at 23 scattered
`os.environ` sites, each reimplementing the same parse-clamp-default
helper (utils/resilience, utils/telemetry, ops/autotune,
ops/delta_egress, ops/ingress_pipeline all had private copies), and
the README knob table was maintained by hand — so a renamed knob, a
changed default, or a typo'd value degraded silently. Here every knob
is a `Knob` entry with a kind, a default, clamp bounds, and the
one-line meaning the README table renders, and every read goes
through `get()`:

- Reads are LIVE (`os.environ` consulted per call, never cached):
  tests and tools/chaos_run.py flip knobs mid-process, and the old
  helpers were deliberately per-call for exactly that reason.
- A malformed value raises typed `KnobError` naming the knob, the
  offending text, and the expected kind — failing fast at the read
  site instead of silently running with a default the operator did
  not ask for (the old helpers swallowed `ValueError` into the
  default, which is how a mistyped `GS_STAGE_TIMEOUT_S=3O` disarms
  the watchdog unnoticed).
- `tools/gslint` rule R3 enforces adoption: any `os.environ` read
  inside `gelly_streaming_tpu/` outside this module (and the
  non-knob backend setup in core/platform.py) is a lint finding, and
  the README table is diffed row-for-row against `render_table()` so
  the docs cannot drift from the code.

Unset and empty both mean "default": an empty string is what
`VAR= python ...` and CI templating produce for "not configured",
and no knob here distinguishes empty from absent on purpose.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "Knob", "KnobError", "REGISTRY", "register",
    "get_int", "get_float", "get_bool", "get_str", "get_path",
    "render_table",
]

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


class KnobError(ValueError):
    """A `GS_*` environment value could not be parsed as its declared
    kind. Carries `.knob` (the Knob) and `.value` (the offending
    text) so a harness can report exactly what to fix."""

    def __init__(self, knob: "Knob", value: str, problem: str):
        super().__init__(
            "%s=%r: %s (expected %s; default %r)"
            % (knob.name, value, problem, knob.kind, knob.default))
        self.knob = knob
        self.value = value


@dataclass(frozen=True)
class Knob:
    """One declared environment knob. `kind` is one of
    'int' / 'float' / 'bool' / 'str' / 'path'; `lo`/`hi` clamp parsed
    numbers (clamping, not raising: the bounds encode "16 is the
    smallest useful ring", not user error); `choices` restricts str
    knobs; `default_text` overrides how the default renders in the
    README table (e.g. "min(2·eb, vb)" for a computed default);
    `help` is the table's meaning column."""

    name: str
    kind: str
    default: object
    help: str
    lo: Optional[float] = None
    hi: Optional[float] = None
    choices: Optional[Tuple[str, ...]] = None
    default_text: Optional[str] = None


REGISTRY: Dict[str, Knob] = {}


def register(name: str, kind: str, default, help: str, **kw) -> Knob:
    assert name.startswith("GS_"), name
    assert kind in ("int", "float", "bool", "str", "path"), kind
    assert name not in REGISTRY, "duplicate knob %s" % name
    knob = Knob(name, kind, default, help, **kw)
    REGISTRY[name] = knob
    return knob


def _raw(name: str) -> Optional[str]:
    """The live environment text, with unset and empty unified to
    None (= use the default)."""
    val = os.environ.get(name)
    return None if val is None or val == "" else val


def _clamp(knob: Knob, num):
    if knob.lo is not None and num < knob.lo:
        num = type(num)(knob.lo)
    if knob.hi is not None and num > knob.hi:
        num = type(num)(knob.hi)
    return num


def _knob(name: str, kind: str) -> Knob:
    knob = REGISTRY.get(name)
    assert knob is not None, "unregistered knob %s" % name
    assert knob.kind == kind, (name, knob.kind, kind)
    return knob


def get_int(name: str) -> Optional[int]:
    knob = _knob(name, "int")
    raw = _raw(name)
    if raw is None:
        return knob.default if knob.default is None \
            else _clamp(knob, int(knob.default))
    try:
        num = int(raw)
    except ValueError:
        raise KnobError(knob, raw, "not an integer") from None
    return _clamp(knob, num)


def get_float(name: str) -> Optional[float]:
    knob = _knob(name, "float")
    raw = _raw(name)
    if raw is None:
        return knob.default if knob.default is None \
            else _clamp(knob, float(knob.default))
    try:
        num = float(raw)
    except ValueError:
        raise KnobError(knob, raw, "not a number") from None
    return _clamp(knob, num)


def get_bool(name: str) -> bool:
    knob = _knob(name, "bool")
    raw = _raw(name)
    if raw is None:
        return bool(knob.default)
    low = raw.lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    raise KnobError(knob, raw, "not a boolean (%s / %s)"
                    % ("/".join(_TRUE), "/".join(_FALSE)))


def get_str(name: str) -> str:
    knob = _knob(name, "str")
    raw = _raw(name)
    if raw is None:
        return knob.default
    if knob.choices is not None and raw not in knob.choices:
        raise KnobError(knob, raw,
                        "not one of %s" % "/".join(knob.choices))
    return raw


def get_path(name: str) -> Optional[str]:
    """Path knobs: a filesystem location (or the conventional "0" =
    explicitly disabled, which callers test for). None = unset."""
    knob = _knob(name, "path")
    raw = _raw(name)
    return knob.default if raw is None else raw


# ----------------------------------------------------------------------
# the registry — grouped as the README table renders them
# ----------------------------------------------------------------------

# ingress pipeline (ops/ingress_pipeline.py)
register("GS_PIPELINE_WORKERS", "int", None, lo=0,
         help="prep worker-pool width; unset = min(4, cpus-1), `0` "
              "pins the synchronous single-thread form",
         default_text="min(4, cpus-1)")
register("GS_PIPELINE_INFLIGHT", "int", 3, lo=1,
         help="max prepped+transferred chunks kept in flight ahead of "
              "dispatch (the bounded-footprint contract)")
register("GS_STREAM_PREFETCH", "bool", True,
         help="`0` pins the synchronous ingress form everywhere (the "
              "A/B lever `ops/ingress_pipeline.forced_sync` scopes "
              "per-measurement)")

# stage watchdogs & tier demotion (utils/resilience.py)
register("GS_STAGE_TIMEOUT_S", "float", 0.0, lo=0.0,
         help="per-stage watchdog deadline: a hung "
              "prep/h2d/dispatch/finalize surfaces as a typed "
              "`StageTimeout` naming the chunk instead of stalling "
              "forever; 0 = off",
         default_text="0 (off)")
register("GS_STAGE_RETRIES", "int", 0, lo=0,
         help="bounded retry for the pure stages (prep, h2d, the "
              "driver's scan dispatch); exhaustion raises "
              "`StageFailed` with per-attempt timings")
register("GS_STAGE_BACKOFF_S", "float", 0.05, lo=0.0,
         help="deterministic (jitterless) exponential backoff base "
              "between attempts")
register("GS_TIER_RETRY_WINDOWS", "int", 0, lo=0,
         help="probation length before a demoted snapshot tier "
              "re-probes the faster one; 0 = never",
         default_text="0 (never)")
register("GS_TIER_DEMOTE", "bool", True,
         help="`0` pins the resolved tier: persistent device failure "
              "raises instead of degrading sharded→scan→native→host")
register("GS_MESH_DEMOTE", "bool", True,
         help="`0` pins a sharded session to the mesh (the "
              "`sharded→scan` rung specifically): a dead shard then "
              "raises the typed stage error instead of degrading to "
              "one device; subordinate to `GS_TIER_DEMOTE`")
register("GS_MESH_WIRE_CHECK", "bool", False,
         help="`1` arms the per-shard range check of every mesh-bound "
              "h2d stack (`parallel/sharded.guard_wire`): a corrupt "
              "shard wire surfaces as a typed stage failure naming "
              "the shard instead of scattering garbage ids into "
              "carried state",
         default_text="0 (off)")

# dispatch autotuner (ops/autotune.py)
register("GS_AUTOTUNE", "bool", True,
         help="`0` disables the online dispatch scheduler "
              "(`ops/autotune.py`): windows-per-dispatch / K / "
              "ingress then run today's static committed-evidence "
              "gates bit-identically; on, the tuner ε-greedily "
              "(deterministically, with 1.05× hysteresis) finds the "
              "fast configuration on the live stream")
register("GS_AUTOTUNE_ROUND", "int", 4, lo=1,
         help="dispatch chunks per tuner measurement round; a 1-chunk "
              "round would silently measure the synchronous form")
register("GS_AUTOTUNE_EXPLORE", "int", 3, lo=2,
         help="every Nth measurement round explores the next "
              "single-knob move off the incumbent; the rest exploit")
register("GS_TUNE_CACHE", "path", None,
         help="directory of the per-backend tuning cache "
              "(`tuning_<backend>.json`) that seeds the next run with "
              "this run's optimum; `0` disables persistence",
         default_text="`~/.cache/gelly_streaming_tpu`")

# resident-state tier (ops/resident_engine.py)
register("GS_RESIDENT", "str", "", choices=("on", "off", "auto"),
         help="pin the resident-state snapshot tier "
              "(`ops/resident_engine.py`): `on` forces it, `off` "
              "never selects it; unset/`auto` = adopt only on "
              "committed parity+≥5% `resident_ab` rows over the best "
              "committed alternative tier",
         default_text="auto")
register("GS_RESIDENT_SPB", "int", 256, lo=1,
         help="windows per super-batch of the resident megakernel "
              "(one donated dispatch folds this many windows; "
              "compile-size-capped per program on TPU backends)")
register("GS_RESIDENT_SLOTS", "int", 2, lo=1,
         help="ingest-ring depth of the resident tier: super-batches "
              "prepped+transferred ahead of dispatch (2 = the "
              "double-buffered form — slot N+1 fills while N computes)")

# fused window megakernel (ops/pallas_window.py)
register("GS_PALLAS_WINDOW", "str", "", choices=("on", "off", "auto"),
         help="pin the fused Pallas window megakernel "
              "(`ops/pallas_window.py`): `on` forces it (interpret "
              "mode off-TPU), `off` never selects it; unset/`auto` = "
              "adopt only on committed parity+≥1.05× `pallas_ab` "
              "rows — the XLA fused scan stands until a chip row "
              "lands",
         default_text="auto")
register("GS_PALLAS_TILE", "int", 0, lo=0,
         help="pin the megakernel's edge-tile size (edges per grid "
              "step, power of two ≤ edge_bucket); 0 (default) = the "
              "`pallas_window` tuner's persisted optimum, else the "
              "whole slab off-TPU (interpret unrolls the grid at "
              "trace) / 512 on chip",
         default_text="0 (auto)")
register("GS_PALLAS_CK", "int", 0, lo=0,
         help="pin the megakernel's intersection compare-chunk width "
              "(the K-chunk of the seed kernel's inner loop); 0 "
              "(default) = min(128, k_bucket)",
         default_text="0 (auto)")

# egress (ops/delta_egress.py)
register("GS_EGRESS", "str", "", choices=("full", "delta", "auto"),
         help="pin the batched d2h egress: `full` (whole snapshot "
              "vectors) or `delta` (per-window changed-slot wire, "
              "`ops/delta_egress.py`); unset/`auto` = adopt delta "
              "only on committed parity+≥5% `egress_ab` rows",
         default_text="auto")
register("GS_EGRESS_CAP", "int", None, lo=1,
         help="per-window changed-slot capacity of the delta wire; a "
              "window that overflows it refolds its chunk on the "
              "bit-exact host twin, so any cap stays exact",
         default_text="min(2·eb, vb)")

# flight recorder (utils/telemetry.py)
register("GS_TELEMETRY", "bool", False,
         help="arm the flight recorder (`utils/telemetry.py`): "
              "unified spans/counters/gauges with per-run trace IDs "
              "and per-chunk correlation across every layer; off, "
              "every hook is a guarded no-op and the hot path is "
              "bit-identical (bench A/B sections run disarmed by "
              "default)",
         default_text="0 (off)")
register("GS_TRACE_DIR", "path", None,
         help="directory of the crash-safe JSONL run ledger "
              "(`trace_<id>.jsonl`); durable-class events (kills, "
              "demotions, stage timeouts, checkpoints, resumes) are "
              "appended+fsync'd immediately, buffered spans flush at "
              "exit/SIGTERM/fatal-fault",
         default_text="unset")
register("GS_TRACE_RING", "int", 4096, lo=16,
         help="in-memory ring-buffer capacity (records) — the "
              "\"last N spans\" a wedge still leaves on disk")
register("GS_TRACE_DURABLE", "bool", True,
         help="`0` drops the per-durable-event fsync (append still "
              "happens; only the power-loss window widens)")

# live health plane (utils/metrics.py + utils/healthz.py)
register("GS_METRICS", "bool", False,
         help="arm the streaming metrics registry "
              "(`utils/metrics.py`): stage latency histograms, "
              "window/edge throughput, retry/demotion/fault/"
              "checkpoint counters and the compile & memory watch, "
              "fed from the flight-recorder hooks; off (the default) "
              "every hook is a guarded no-op and the hot path is "
              "bit-identical",
         default_text="0 (off)")
register("GS_METRICS_PORT", "int", 0, lo=0, hi=65535,
         help="serve `/metrics` (Prometheus text) and `/healthz` "
              "(JSON) from a stdlib http daemon thread on this "
              "127.0.0.1 port (`utils/healthz.py`); 0 (default) = no "
              "server — the registry still records when GS_METRICS=1",
         default_text="0 (off)")
register("GS_METRICS_SERIES", "int", 64, lo=1,
         help="label-set cardinality bound per metric name: beyond "
              "it new label sets collapse into one `overflow` series "
              "(each DISTINCT collapsed set counts once in "
              "`gs_metrics_dropped_series_total`), so a tenant-shaped "
              "label can never grow the registry unboundedly")
register("GS_METRICS_COMPILE_BASE", "int", 8, lo=1,
         help="base compile allowance per jitted function in the "
              "recompile watch: a function may compile `base + "
              "log2(max/min observed arg size) + 1` times (the "
              "O(log V) bucket-growth envelope) before a durable "
              "`recompile_storm` event fires")
register("GS_HEALTH_STALE_S", "float", 30.0, lo=0.0,
         help="staleness watchdog deadline: with the metrics plane "
              "armed, no window finalizing for this many seconds "
              "flips `/healthz` to `degraded` and writes a durable "
              "`health_degraded` event (the wedged-tunnel detector); "
              "0 disables the watchdog",
         default_text="30")

# multi-tenant cohort scheduler (core/tenancy.py)
register("GS_TENANT_MAX", "int", 64, lo=1,
         help="admission cap of the multi-tenant cohort scheduler "
              "(`core/tenancy.py`): tenants past it are refused with "
              "a typed `TenantRejected` + a durable `tenant_rejected` "
              "event instead of degrading every admitted stream")
register("GS_TENANT_QUEUE_WINDOWS", "int", 8, lo=1,
         help="per-tenant ingest-queue depth in windows (capacity = "
              "depth x edge_bucket edges): the bounded backpressure "
              "buffer between feed() and the cohort dispatch")
register("GS_TENANT_ADMISSION", "str", "reject",
         choices=("reject", "drop"),
         help="queue-overflow policy: `reject` raises a typed "
              "`TenantBackpressure` naming the tenant (accepting "
              "nothing — the caller owns retry), `drop` accepts what "
              "fits and sheds the rest with a durable event + counter")
register("GS_TENANT_TPD", "int", 0, lo=0,
         help="pin tenants-per-dispatch of the cohort slab; 0 "
              "(default) lets the dispatch autotuner's "
              "tenants-per-dispatch arm choose (all ready tenants in "
              "one vmapped dispatch with GS_AUTOTUNE=0)",
         default_text="0 (auto)")
register("GS_COHORT_RESIDENT", "str", "", choices=("on", "off", "auto"),
         help="pin the resident cohort tier (`core/tenancy.py`): a "
              "donated `[N, ...]` stacked-carry super-batch program "
              "per cohort instead of restacking carries every round; "
              "`on` forces it, `off` never selects it; unset/`auto` "
              "= adopt only on committed parity+≥5% "
              "`tenancy_ab`/`cohort_resident` rows over per-tenant "
              "resident dispatch",
         default_text="auto")
register("GS_COHORT_PALLAS", "str", "", choices=("on", "off", "auto"),
         help="pin the tenant-axis Pallas cohort megakernel "
              "(`ops/pallas_window.py`): one `pallas_call` with the "
              "tenant axis as a second grid dimension serves the "
              "whole cohort from VMEM; `on` forces it (interpret "
              "mode off-TPU), `off` never selects it; unset/`auto` = "
              "adopt only on committed non-interpret parity+≥1.05× "
              "`tenancy_ab`/`cohort_pallas` rows — the vmapped XLA "
              "cohort scan stands until a chip row lands",
         default_text="auto")

# durable serving front-end (utils/wal.py + core/serve.py)
register("GS_WAL", "bool", True,
         help="`0` is the write-ahead-journal kill switch: every "
              "`enable_wal()` call site (cohort, engines, driver) "
              "degrades to a no-op and the ingest paths stay "
              "bit-identical to a journal-less run; 1 (default) lets "
              "callers that explicitly enable a journal get one")
register("GS_WAL_FSYNC_S", "float", 0.0, lo=0.0,
         help="fsync batching interval of the edge journal: 0 "
              "(default) fsyncs every append (tightest power-loss "
              "window), >0 batches fsyncs to at most one per interval "
              "(appends in between are flushed but not synced)",
         default_text="0 (every append)")
register("GS_WAL_RETAIN", "bool", False,
         help="`1` arms journal retention: every checkpoint FLUSH "
              "(engine/driver auto-checkpoint, cohort "
              "`checkpoint_all()`) calls `truncate_covered()` with "
              "the OLDER of the two kept checkpoint generations' "
              "offsets, so bounded disk never deletes a record a "
              "rotation-fallback recovery would still replay; 0 "
              "(default) keeps every closed segment",
         default_text="0 (off)")
register("GS_WAL_SEGMENT_BYTES", "int", 1 << 26, lo=4096,
         help="journal segment-rotation size: a segment past this "
              "many bytes closes (fsync'd) and appends continue in a "
              "fresh `wal_<n>.seg`; records never split across "
              "segments",
         default_text="67108864 (64 MiB)")
register("GS_SERVE_PORT", "int", 0, lo=0, hi=65535,
         help="TCP port of the serving front-end "
              "(`core/serve.StreamServer`, 127.0.0.1); 0 in code = "
              "OS-assigned ephemeral port (tests print `.port`)",
         default_text="0 (ephemeral)")
register("GS_SERVE_DRAIN_S", "float", 30.0, lo=0.0,
         help="graceful-drain deadline: on SIGTERM the server stops "
              "accepting, waits up to this long for in-flight "
              "requests, pumps every queue dry, checkpoints, seals "
              "the journal and exits 0; 0 = no deadline (wait "
              "forever for in-flight requests)",
         default_text="30")
register("GS_SERVE_IDLE_S", "float", 60.0, lo=0.1,
         help="per-connection deadline of the serving front-end: a "
              "connection idle (no request) this long is closed, and "
              "a response send stalled this long is SHED (durable "
              "`serve_client_shed` event) so a slow client can never "
              "wedge the pump",
         default_text="60")

# end-to-end latency plane (utils/latency.py)
register("GS_LATENCY", "bool", False,
         help="arm the ingest→deliver latency plane "
              "(`utils/latency.py`): admission stamps on every "
              "accepted edge batch (carried through the WAL ts "
              "column so replayed windows keep their original "
              "admission time), per-window stage waterfalls, "
              "per-tenant latency percentiles, the "
              "oldest-unfinalized-edge age gauge and the SLO burn "
              "module; off (the default) every hook is a guarded "
              "no-op and the hot path is bit-identical",
         default_text="0 (off)")
register("GS_LAT_MARKS", "int", 4096, lo=16,
         help="per-lane admission-mark memory bound (batches "
              "remembered between admission and window finalize); a "
              "window whose mark was evicted reports an approximate, "
              "conservative latency instead of growing memory")
register("GS_LAT_PENDING", "int", 1024, lo=16,
         help="bounded finalized-but-undelivered window records the "
              "serving front-end may hold between pump and sink "
              "write; past it the oldest emits as-finalized")
register("GS_SLO_P99_S", "float", 0.0, lo=0.0,
         help="delivered-window end-to-end latency target "
              "(seconds): each window past it burns the error "
              "budget; 0 (default) disables the SLO module",
         default_text="0 (off)")
register("GS_SLO_BUDGET", "float", 0.01, lo=1e-6, hi=1.0,
         help="error budget: the allowed fraction of delivered "
              "windows over the GS_SLO_P99_S target")
register("GS_SLO_WINDOW_S", "float", 60.0, lo=1.0,
         help="sliding window (seconds) the SLO burn rate is "
              "measured over")
register("GS_SLO_BURN", "float", 2.0, lo=0.1,
         help="burn rate ((bad/total)/budget) at or above which the "
              "`/healthz` `latency` section flips `degraded` with a "
              "durable `slo_burn` event (once per episode; recovery "
              "stamps `slo_recovered`)")

# admission sanitizer, dead-letter journal & tenant bulkheads
# (utils/sanitize.py + core/tenancy.py)
register("GS_SANITIZE", "str", "off", choices=("off", "on", "strict"),
         help="admission sanitizer (`utils/sanitize.py`) run at every "
              "ingest boundary BEFORE the journal: `off` (default) is "
              "bit-identical to a pre-sanitizer build, `on` rejects "
              "structurally invalid records (out-of-range / negative "
              "/ int32-overflowing / non-integer ids) with typed "
              "reason codes, `strict` adds the self-loop and "
              "duplicate-flood policies",
         default_text="off")
register("GS_DLQ_DIR", "path", None,
         help="dead-letter journal directory: rejected admission "
              "records are appended as CRC-framed segment records "
              "(origin tenant + source offset + reason + the edges) "
              "for `tools/dlq_report.py` to render and re-inject; "
              "unset/`0` = rejections are counted and dropped",
         default_text="unset")
register("GS_DLQ_RETAIN", "int", 0, lo=0,
         help="closed dead-letter segments kept after rotation "
              "(rotation size is GS_WAL_SEGMENT_BYTES); 0 (default) "
              "keeps every segment",
         default_text="0 (keep all)")
register("GS_QUARANTINE_WINDOWS", "int", 4, lo=0,
         help="clean solo probation windows a quarantined tenant "
              "must finalize before the cohort re-admits it to the "
              "shared vmapped dispatch (`core/tenancy.py` bulkhead); "
              "0 = quarantine is permanent for the process")
register("GS_MAX_BATCH_EDGES", "int", 0, lo=0,
         help="admission batch-size bound: a single feed()/process() "
              "batch longer than this is refused whole with a typed "
              "`BatchRejected` (and journaled to the DLQ when armed); "
              "0 (default) = unbounded",
         default_text="0 (unbounded)")

# async serving pump, sliding windows & event time
# (core/serve.py + core/tenancy.py + ops/windowed_reduce.py +
#  ops/scan_analytics.py + core/driver.py)
register("GS_PUMP", "str", "sync", choices=("sync", "async"),
         help="serving pump mode (`core/serve.StreamServer`): `sync` "
              "(default) pumps inline under the request lock — "
              "bit-identical to the pre-pump build; `async` runs slab "
              "prep → h2d → dispatch → finalize on a dedicated pump "
              "thread so the accept loop and file tails only "
              "sanitize → journal → enqueue under the queue lock "
              "(ingest overlaps compute; same digests, honest "
              "`queue_wait` attribution)",
         default_text="sync")
register("GS_SLIDE", "int", 0, lo=0,
         help="sliding-window slide in edges for the windowing "
              "engines/driver (`slide=` default): the window advances "
              "by this many edges per emission, each edge folds into "
              "its pane ONCE and `window/slide` pane summaries "
              "compose per emission; must be a power of two dividing "
              "the window size; 0 (default) = tumbling "
              "(slide == window)",
         default_text="0 (tumbling)")
register("GS_OOO_BOUND", "int", 0, lo=0,
         help="bounded out-of-orderness (event-time ns) of the "
              "per-tenant reorder buffer ahead of the monotonic "
              "guard: a `feed(ts=)` edge is held until the tenant's "
              "watermark (newest stamp − bound) passes it, then "
              "released in ts order; 0 (default) = off — ts must "
              "arrive non-decreasing exactly as before",
         default_text="0 (off)")
register("GS_SUB_QUEUE", "int", 256, lo=1,
         help="bounded per-connection queue (WindowResult rows) of "
              "the serve wire protocol's `subscribe` op; a "
              "subscriber whose queue overflows is SHED with the "
              "durable `serve_client_shed` event, never wedging the "
              "pump")

# program cost observatory (utils/costmodel.py)
register("GS_COSTMODEL", "bool", False,
         help="arm the program cost observatory "
              "(`utils/costmodel.py`): every wrapped jit/AOT program "
              "captures its XLA `cost_analysis`/`memory_analysis` "
              "(FLOPs, bytes) per abstract shape signature, and "
              "dispatch spans carry program/signature tags the "
              "attribution tools join on; off (the default) every "
              "hook is a guarded no-op and the hot path is "
              "bit-identical (armed, a jit-path program pays ONE "
              "extra AOT compile per new signature)",
         default_text="0 (off)")
register("GS_COSTMODEL_PEAK_GFLOPS", "float", 197000.0, lo=1.0,
         help="compute roofline peak (GFLOP/s) the boundedness "
              "verdict and achieved fractions are computed against; "
              "default is the public TPU v5e bf16 peak — on a CPU "
              "backend the fractions are structure checks, not chip "
              "numbers",
         default_text="197000 (v5e bf16)")
register("GS_COSTMODEL_PEAK_GBPS", "float", 819.0, lo=0.001,
         help="memory-bandwidth roofline peak (GB/s) for the "
              "bytes-vs-FLOPs boundedness verdict; default is the "
              "public TPU v5e HBM peak",
         default_text="819 (v5e HBM)")

# windowed GNN workload (ops/gnn_window.py)
register("GS_GNN_F", "int", 16, lo=1, hi=256,
         help="feature width F of the windowed GNN workload's "
              "per-vertex slab (`ops/gnn_window.py`); engines built "
              "without an explicit feature_dim read it at "
              "construction. F ≤ 64 keeps the dense update exactly "
              "representable on the storage lattice; larger F snaps "
              "weights to a coarser grid (same deterministic shift "
              "on every tier, so parity holds)")
register("GS_GNN_ACT", "str", "relu", choices=("relu", "abs",
                                               "identity"),
         help="activation of the GNN dense update — restricted to "
              "EXACT elementwise ops (relu/abs/identity) so the "
              "numpy twin stays a bit-exactness oracle; read at "
              "engine construction")
register("GS_GNN_PALLAS", "str", "", choices=("on", "off", "auto"),
         help="pin the fused Pallas GNN window kernel "
              "(`ops/pallas_window.maybe_gnn_body`): `on` forces it "
              "(interpret mode off-TPU), `off` never selects it; "
              "unset/`auto` = adopt only on committed parity+≥1.05× "
              "non-interpret `gnn_ab` rows with probe `gnn_pallas` "
              "— the XLA gather/segment-sum body stands until a "
              "chip row lands",
         default_text="auto")

# tenant observatory (utils/provenance.py, per-tenant attribution)
register("GS_PROVENANCE", "bool", False,
         help="arm the per-window provenance ledger "
              "(`utils/provenance.py`): every finalize owner appends "
              "a CRC-framed record (tenant, window, wal span, tier + "
              "program, knob fingerprint, summary sha256) that "
              "`tools/replay_window.py` re-derives and diffs on any "
              "tier; disarmed (the default) every emit() is a no-op "
              "and digests are bit-identical to a ledger-less build")
register("GS_PROVENANCE_DIR", "path", None,
         help="directory of the provenance ledger's "
              "`prov_<n>.seg` segments; unset disarms emit() even "
              "with GS_PROVENANCE=1 (nowhere durable to write)")
register("GS_PROVENANCE_RETAIN", "int", 0, lo=0,
         help="closed ledger segments kept behind the open one "
              "(rotation uses GS_WAL_SEGMENT_BYTES); 0 = keep "
              "everything — the audit-trail default; bound it only "
              "when an external archiver drains the records")


# ----------------------------------------------------------------------
# docs rendering (README table; gslint R3 diffs it back)
# ----------------------------------------------------------------------
def _default_cell(knob: Knob) -> str:
    if knob.default_text is not None:
        return knob.default_text
    if knob.kind == "bool":
        return "1" if knob.default else "0"
    return str(knob.default)


def render_table() -> str:
    """The README `GS_*` knob table, one row per registered knob in
    registration order. tests/test_knobs.py (and gslint R3's docs
    check) assert the committed README contains exactly this block —
    regenerate with `python -m tools.gslint --knob-table`."""
    lines = ["| knob | default | meaning |", "|---|---|---|"]
    for knob in REGISTRY.values():
        lines.append("| `%s` | %s | %s |"
                     % (knob.name, _default_cell(knob),
                        " ".join(knob.help.split())))
    return "\n".join(lines)

"""Streaming metrics registry — the live half of the observability
plane (the flight recorder, utils/telemetry.py, is the post-mortem
half).

A process-global, thread-safe registry of **counters**, **gauges**,
and **bounded histograms**, each keyed by a label set (engine, tier,
stage, mesh_shape — tenant-ready: labels are an open dict). It is fed
two ways:

- From the EXISTING telemetry hooks: this module registers a sink
  with utils/telemetry (telemetry.register_sink), so every span,
  counter, gauge and event the instrumented layers already emit —
  ingress prep/h2d/dispatch/finalize stage spans, stage retries, tier
  demotions, injected faults, checkpoints, resumes — lands in the
  registry with no new call sites. The sink is consulted even with
  `GS_TELEMETRY=0`: arming the metrics plane never requires arming
  the ledger.
- From a handful of explicit marks on the streaming layers:
  `mark_window()` at every window-finalize OWNER (the driver's chunk
  boundary, SummaryEngineBase._finalize_summaries, the triangle
  kernels' top-level count_stream entries — never the chunk loops
  underneath, which also serve the driver's flush path and would
  double-count) drives window/edge throughput AND the staleness
  clock the health watchdog reads; the ingress pipeline sets the
  in-flight/backlog gauges.

Plus the **compile & memory watch**:

- `wrap_jit(name, fn)` wraps a jitted entry point; each call computes
  the abstract shape signature of its arguments and counts a compile
  whenever a NEW signature appears (jit compiles exactly per abstract
  signature). A function whose compile count exceeds the O(log V)
  bucket-growth envelope — `GS_METRICS_COMPILE_BASE +
  log2(max/min observed argument size) + 1` — stamps a durable
  `recompile_storm` event: doubling buckets stay inside the envelope
  by construction (k doublings ⇒ size ratio 2^(k-1) ⇒ allowance
  ≥ base + k), a shape-churning caller trips it. This is the runtime
  enforcement of the O(log V) recompile claim core/driver.py:27 and
  ops/triangles.py stake their perf semantics on.
- `sample_memory()` snapshots `jax.live_arrays()` (count + bytes) and
  each device's `memory_stats()` into HBM/host gauges where the
  backend supports them (tools/endurance_run.py's leak detector).

Zero-overhead contract (same discipline as the flight recorder): with
`GS_METRICS=0` (the default) every entry point is a guarded no-op, the
telemetry sink reports inactive, and the hot path is bit-identical —
asserted by tests/test_metrics.py digest parity on the 524K/32768 CPU
row. The armed overhead bar (≤1.05×) is committed to PERF_cpu.json's
`metrics` section by tools/profile_kernels.py.

Knobs (utils/knobs.py):
    GS_METRICS               0 (default) = disarmed no-ops; 1 = record
    GS_METRICS_PORT          /metrics + /healthz port (utils/healthz)
    GS_METRICS_SERIES        label-set cardinality bound per metric
    GS_METRICS_COMPILE_BASE  base compile allowance per function
    GS_HEALTH_STALE_S        staleness watchdog deadline (seconds)
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import costmodel
from . import knobs
from . import telemetry

clock = time.monotonic  # health/staleness clock (injectable per call)

_HIST_CAP = 512  # per-series duration reservoir (percentile source)

# telemetry stage spans → the per-stage latency histogram's label
_STAGE_SPANS = {
    "ingress.prep": "prep",
    "ingress.h2d": "h2d",
    "ingress.dispatch": "dispatch",
    "ingress.finalize": "finalize",
}

# durable/notable telemetry events → counters (the bounded event
# vocabulary of the instrumented layers; anything else lands in the
# generic gs_events_total{event=...} under the series bound)
_EVENT_COUNTERS = {
    "stage_retry": "gs_stage_retries_total",
    "stage_timeout": "gs_stage_errors_total",
    "stage_failed": "gs_stage_errors_total",
    "tier_demotion": "gs_tier_demotions_total",
    "fault_injected": "gs_faults_injected_total",
    "checkpoint_saved": "gs_checkpoints_total",
    "resume": "gs_resumes_total",
    "fatal": "gs_fatal_events_total",
}


def enabled() -> bool:
    """GS_METRICS arms the registry; off (the default) every entry
    point — including the telemetry sink — is a guarded no-op."""
    return knobs.get_bool("GS_METRICS")


def max_series() -> int:
    return knobs.get_int("GS_METRICS_SERIES")


def stale_after_s() -> float:
    return knobs.get_float("GS_HEALTH_STALE_S")


# ----------------------------------------------------------------------
# the process-global registry
# ----------------------------------------------------------------------
_OVERFLOW_KEY = (("overflow", "true"),)


class _Registry:
    """All mutable state behind one lock. One instance per process
    (rebuilt by reset())."""

    def __init__(self):
        self.lock = threading.RLock()
        self.counters: Dict[Tuple[str, tuple], float] = {}
        self.gauges: Dict[Tuple[str, tuple], float] = {}
        self.hists: Dict[Tuple[str, tuple], dict] = {}
        self.series: Dict[str, set] = {}   # name → label keys seen
        self.dropped_seen: set = set()     # (name, labels) collapsed
        self.dropped_series = 0
        # compile watch: fn name → {count, sizes, allowed, storm}
        self.compiles: Dict[str, dict] = {}
        # health state (the staleness watchdog's substrate)
        self.health = "ok"
        self.last_finalize: Optional[float] = None
        # (status, t, age_s) — bounded: an episodic stream flips
        # twice per idle gap forever, and only the tail is served
        self.transitions = deque(maxlen=64)
        self.windows_total = 0
        self.edges_total = 0
        self.edges_per_s_ema: Optional[float] = None
        self.engines: Dict[str, dict] = {}   # engine → tier/mesh info
        # per-tenant window/edge counters + staleness clocks (the
        # /healthz `tenants` section), bounded exactly like label
        # sets: past the cardinality bound new tenants collapse into
        # one `overflow` row (tenant_key below)
        self.tenants: Dict[str, dict] = {}

    def series_key(self, name: str, labels: tuple) -> tuple:
        """Admit `labels` under the per-metric cardinality bound;
        past the bound, new label sets collapse into one `overflow`
        series so a tenant-shaped label can never grow the registry
        without bound. `dropped_series` counts DISTINCT collapsed
        label sets (first rejection only — a recurring over-bound
        series marked every window must not inflate it), remembered
        in a set itself bounded at 4x the series bound: past that the
        counter saturates (undercounts) rather than grow memory."""
        seen = self.series.setdefault(name, set())
        if labels in seen:
            return labels
        if len(seen) >= max_series():
            dropped = (name, labels)
            if dropped not in self.dropped_seen \
                    and len(self.dropped_seen) < 4 * max_series():
                self.dropped_seen.add(dropped)
                self.dropped_series += 1
            seen.add(_OVERFLOW_KEY)
            return _OVERFLOW_KEY
        seen.add(labels)
        return labels

    def tenant_key(self, tenant: str) -> str:
        """Admit one tenant id into the bounded per-tenant table —
        the same collapse-don't-grow policy as series_key: past the
        GS_METRICS_SERIES bound, new tenants share one `overflow` row
        (each DISTINCT collapsed tenant counts once in
        `dropped_series`, remembered in the same bounded set)."""
        tenant = str(tenant)
        if tenant in self.tenants:
            return tenant
        if len(self.tenants) >= max_series():
            dropped = ("__tenants__", tenant)
            if dropped not in self.dropped_seen \
                    and len(self.dropped_seen) < 4 * max_series():
                self.dropped_seen.add(dropped)
                self.dropped_series += 1
            return "overflow"
        return tenant


_REG: Optional[_Registry] = None
_REG_LOCK = threading.Lock()

# extra /healthz sections from serving-layer providers (core/serve
# registers "serve"): name -> zero-arg callable returning a JSON-able
# dict, merged into health_snapshot() under the name. Mutated only
# under _REG_LOCK.
_HEALTH_SECTIONS: Dict[str, object] = {}


def register_health_section(name: str, provider) -> None:
    """Attach a named section to the `/healthz` body: `provider()` is
    called per snapshot (its failure is reported in-place, never
    raised into the probe). Idempotent per name — the latest provider
    wins, so a restarted server re-registers cleanly."""
    with _REG_LOCK:
        _HEALTH_SECTIONS[name] = provider


def unregister_health_section(name: str) -> None:
    with _REG_LOCK:
        _HEALTH_SECTIONS.pop(name, None)


def _reg() -> _Registry:
    global _REG
    if _REG is None:
        with _REG_LOCK:
            if _REG is None:
                _REG = _Registry()
    return _REG


def reset() -> None:
    """Test/tool hook: drop all recorded series and health state."""
    global _REG
    with _REG_LOCK:
        _REG = None


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# ----------------------------------------------------------------------
# recording API
# ----------------------------------------------------------------------
def counter_inc(name: str, value: float = 1, **labels) -> None:
    if not enabled():
        return
    reg = _reg()
    with reg.lock:
        key = (name, reg.series_key(name, _labelkey(labels)))
        reg.counters[key] = reg.counters.get(key, 0.0) + value


def gauge_set(name: str, value: float, **labels) -> None:
    if not enabled():
        return
    reg = _reg()
    with reg.lock:
        key = (name, reg.series_key(name, _labelkey(labels)))
        reg.gauges[key] = float(value)


def observe(name: str, value: float, **labels) -> None:
    """One histogram observation (bounded reservoir + count/sum)."""
    if not enabled():
        return
    reg = _reg()
    with reg.lock:
        key = (name, reg.series_key(name, _labelkey(labels)))
        h = reg.hists.get(key)
        if h is None:
            h = reg.hists[key] = {
                "count": 0, "sum": 0.0,
                "samples": deque(maxlen=_HIST_CAP)}
        h["count"] += 1
        h["sum"] += value
        h["samples"].append(value)


# ----------------------------------------------------------------------
# snapshots (tests, /healthz, /metrics)
# ----------------------------------------------------------------------
def counters() -> Dict[Tuple[str, tuple], float]:
    reg = _reg()
    with reg.lock:
        return dict(reg.counters)


def gauges() -> Dict[Tuple[str, tuple], float]:
    reg = _reg()
    with reg.lock:
        return dict(reg.gauges)


def histogram(name: str, **labels) -> Optional[dict]:
    """(count, sum, p50/p95/p99) of one histogram series, or None."""
    reg = _reg()
    with reg.lock:
        h = reg.hists.get((name, _labelkey(labels)))
        if h is None:
            return None
        pct = telemetry.percentiles(h["samples"])
        return {"count": h["count"], "sum": h["sum"],
                "p50": pct[50], "p95": pct[95], "p99": pct[99]}


def _fmt(v: float) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return "%.9g" % v


def _series(name: str, labels: tuple, extra: tuple = ()) -> str:
    pairs = labels + extra
    if not pairs:
        return name
    return "%s{%s}" % (name, ",".join(
        '%s="%s"' % (k, v) for k, v in pairs))


def render_prometheus() -> str:
    """The registry in Prometheus text exposition format (counters,
    gauges, histograms as summaries with nearest-rank quantiles),
    deterministically ordered — the `/metrics` endpoint body and the
    golden-file surface tests/test_metrics.py pins."""
    reg = _reg()
    lines: List[str] = []
    with reg.lock:
        for kind, table in (("counter", reg.counters),
                            ("gauge", reg.gauges)):
            by_name: Dict[str, list] = {}
            for (name, labels), val in table.items():
                by_name.setdefault(name, []).append((labels, val))
            for name in sorted(by_name):
                lines.append("# TYPE %s %s" % (name, kind))
                for labels, val in sorted(by_name[name]):
                    lines.append("%s %s"
                                 % (_series(name, labels), _fmt(val)))
        by_name = {}
        for (name, labels), h in reg.hists.items():
            by_name.setdefault(name, []).append((labels, h))
        for name in sorted(by_name):
            lines.append("# TYPE %s summary" % name)
            for labels, h in sorted(by_name[name],
                                    key=lambda x: x[0]):
                pct = telemetry.percentiles(h["samples"])
                for q, p in (("0.5", 50), ("0.95", 95), ("0.99", 99)):
                    lines.append("%s %s" % (
                        _series(name, labels, (("quantile", q),)),
                        _fmt(pct[p])))
                lines.append("%s %s" % (_series(name + "_sum", labels),
                                        _fmt(h["sum"])))
                lines.append("%s %d" % (
                    _series(name + "_count", labels), h["count"]))
        lines.append("# TYPE gs_metrics_dropped_series_total counter")
        lines.append("gs_metrics_dropped_series_total %d"
                     % reg.dropped_series)
        lines.append("# TYPE gs_health_degraded gauge")
        lines.append("gs_health_degraded %d"
                     % (1 if reg.health == "degraded" else 0))
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# the telemetry sink: the existing span/counter/event hooks feed the
# registry (registered at import time; self-gated on GS_METRICS)
# ----------------------------------------------------------------------
def _sink(rec: dict) -> None:
    kind = rec.get("t")
    name = rec.get("name", "")
    if kind == "span":
        dur = rec.get("dur")
        if dur is None:
            return
        stage = _STAGE_SPANS.get(name)
        if stage is not None:
            observe("gs_stage_seconds", dur, stage=stage)
            return
        attrs = rec.get("a") or {}
        edges = attrs.get("edges")
        if edges:
            observe("gs_round_seconds", dur, span=name)
            counter_inc("gs_round_edges_total", edges, span=name)
    elif kind == "event":
        cname = _EVENT_COUNTERS.get(name)
        attrs = rec.get("a") or {}
        if cname is not None:
            labels = {}
            if cname == "gs_stage_errors_total":
                labels["kind"] = name
            if "stage" in attrs:
                labels["stage"] = attrs["stage"]
            counter_inc(cname, 1, **labels)
        else:
            counter_inc("gs_events_total", 1, event=name)
    elif kind == "counter":
        counter_inc("gs_" + name.replace(".", "_"),
                    rec.get("value", 1))
    elif kind == "gauge":
        gauge_set("gs_" + name.replace(".", "_"),
                  rec.get("value", 0))


telemetry.register_sink(_sink, enabled)


# ----------------------------------------------------------------------
# window-finalize marks + health state (the wedged-tunnel detector)
# ----------------------------------------------------------------------
def on_stream_start(engine: str = "driver",
                    tenant: Optional[str] = None) -> None:
    """Stream entry mark: re-anchors the staleness clock (a stream
    that never finalizes its FIRST window is just as wedged as one
    that stops mid-way — and a stream starting long after the
    previous one finalized must not inherit that stale clock and get
    flagged before its first window is even due), registers `engine`
    on /healthz before its first finalize, and brings up the endpoint
    when GS_METRICS_PORT asks for one."""
    if not enabled():
        return
    reg = _reg()
    now = clock()
    with reg.lock:
        reg.engines.setdefault(engine, {})
        reg.last_finalize = now
        if tenant is not None:
            # anchor the tenant's own staleness clock at admission so
            # a stream admitted long after the cohort's last finalize
            # is not flagged stale before its first window is due
            info = reg.tenants.setdefault(reg.tenant_key(tenant), {})
            info.setdefault("windows", 0)
            info.setdefault("edges", 0)
            info["last_finalize"] = now
    _maybe_serve()


def mark_tenant(tenant: str, windows: int, edges: int,
                tier: Optional[str] = None,
                now: Optional[float] = None) -> None:
    """Per-tenant finalize mark ONLY (the bounded tenants table +
    tenant-labeled counters) — for window-finalize owners that already
    fired the global mark_window themselves (a demoted tenant's
    single-tenant engine marks globally inside process()); the cohort
    dispatch path uses mark_window(tenant=...) which does both."""
    if not enabled() or tenant is None:
        return
    reg = _reg()
    now = clock() if now is None else now
    with reg.lock:
        key = reg.tenant_key(tenant)
        info = reg.tenants.setdefault(key, {})
        info["windows"] = info.get("windows", 0) + windows
        info["edges"] = info.get("edges", 0) + edges
        info["last_finalize"] = now
        if tier is not None:
            info["tier"] = tier
    labels = {"tenant": key}
    if tier is not None:
        labels["tier"] = tier
    counter_inc("gs_tenant_windows_total", windows, **labels)
    counter_inc("gs_tenant_edges_total", edges, **labels)


def mark_window(windows: int, edges: int, engine: str = "driver",
                tier: Optional[str] = None,
                mesh_shape: Optional[list] = None,
                tenant: Optional[str] = None,
                now: Optional[float] = None) -> None:
    """One window-finalize boundary: `windows` windows covering
    `edges` edges were finalized by `engine` on `tier` (for `tenant`
    when the finalize owner serves one — the multi-tenant cohort marks
    once per tenant whose windows the dispatch covered). Drives the
    throughput counters/gauges AND resets the staleness clock; a
    finalize arriving while health is `degraded` is the recovery
    signal (durable `health_recovered` event)."""
    if not enabled():
        return
    reg = _reg()
    now = clock() if now is None else now
    recovered_age = None
    with reg.lock:
        prev = reg.last_finalize
        reg.last_finalize = now
        reg.windows_total += windows
        reg.edges_total += edges
        if prev is not None and now > prev:
            rate = edges / (now - prev)
            ema = reg.edges_per_s_ema
            reg.edges_per_s_ema = (rate if ema is None
                                   else 0.7 * ema + 0.3 * rate)
        info = reg.engines.setdefault(engine, {})
        if tier is not None:
            info["tier"] = tier
        if mesh_shape is not None:
            info["mesh_shape"] = list(mesh_shape)
        info["windows"] = info.get("windows", 0) + windows
        if reg.health == "degraded":
            reg.health = "ok"
            recovered_age = (now - prev) if prev is not None else 0.0
            reg.transitions.append(("ok", now, round(recovered_age, 3)))
    labels = {"engine": engine}
    if tier is not None:
        labels["tier"] = tier
    if tenant is not None:
        mark_tenant(tenant, windows, edges, tier=tier, now=now)
        labels["tenant"] = str(tenant)
    counter_inc("gs_windows_finalized_total", windows, **labels)
    counter_inc("gs_edges_total", edges, **labels)
    if recovered_age is not None:
        telemetry.event("health_recovered", durable=True,
                        engine=engine, gap_s=round(recovered_age, 3))
    _maybe_serve()


def attribute_dispatch(seconds: float, rows,
                       program: Optional[str] = None,
                       sig: Optional[str] = None):
    """Per-tenant cost attribution of ONE cohort dispatch: split the
    span's measured wall `seconds` (and, when the cost observatory is
    armed, the dispatched program's modeled bytes) across `rows` —
    `[(tenant, valid_edges), ...]`, one row per tenant the vmapped
    dispatch carried — proportionally by per-row valid-edge counts.

    The split RECONCILES exactly (DESIGN.md §24): pad/invalid rows
    (edges == 0) attribute zero, and the last nonzero row absorbs the
    floating-point residue, so the attributed shares sum to `seconds`
    bit-for-bit — an aggregator can roll tenant rows back up to the
    device total without drift (pinned by tests/test_provenance.py).

    Feeds `gs_tenant_device_seconds` / `gs_tenant_attributed_bytes`
    counters and the bounded per-tenant table the /healthz hot-tenant
    scoring reads, all under the existing tenant cardinality collapse.
    Returns `[(tenant, seconds_share, bytes_share), ...]` (the armed
    introspection surface; None disarmed)."""
    if not enabled():
        return None
    rows = [(str(t), int(n)) for t, n in rows]
    total = sum(n for _t, n in rows)
    seconds = float(seconds)
    if total <= 0 or seconds < 0:
        return None
    bytes_total = None
    if program is not None and costmodel.enabled():
        progs = costmodel.programs()
        entry = progs.get((program, sig)) if sig is not None else None
        if entry is None:
            # the dispatch tags may be unavailable at this boundary
            # (popped by an inner pipeline) — any captured signature
            # of the same program models the same per-call traffic
            # shape at this cohort's fixed padding
            for (p, _s), e in sorted(progs.items()):
                if p == program:
                    entry = e
                    break
        if entry is not None and entry.get("bytes_accessed"):
            bytes_total = float(entry["bytes_accessed"])  # gslint: disable=host-sync (cost-ledger JSON number, no device value in sight)
    nz = [i for i, (_t, n) in enumerate(rows) if n > 0]
    last = nz[-1]
    out = []
    acc_s = 0.0
    acc_b = 0.0
    for i, (t, n) in enumerate(rows):
        if n == 0:
            out.append((t, 0.0, 0.0))
            continue
        if i == last:
            s = seconds - acc_s
            b = (bytes_total - acc_b) if bytes_total else 0.0
        else:
            s = seconds * (n / total)
            acc_s += s
            b = bytes_total * (n / total) if bytes_total else 0.0
            acc_b += b
        out.append((t, s, b))
    reg = _reg()
    with reg.lock:
        for t, s, b in out:
            if s == 0.0 and b == 0.0:
                continue
            key = reg.tenant_key(t)
            info = reg.tenants.setdefault(key, {})
            info["device_s"] = info.get("device_s", 0.0) + s
            if b:
                info["attr_bytes"] = info.get("attr_bytes", 0.0) + b
            counter_inc("gs_tenant_device_seconds", s, tenant=key)
            if b:
                counter_inc("gs_tenant_attributed_bytes", b,
                            tenant=key)
    return out


def check_staleness(now: Optional[float] = None) -> str:
    """The staleness watchdog body (called by the utils/healthz
    watchdog thread; `now` injectable for tests): no finalize within
    GS_HEALTH_STALE_S of the last one flips health to `degraded` and
    stamps a durable `health_degraded` event — once per episode."""
    if not enabled():
        return "ok"
    stale = stale_after_s()
    reg = _reg()
    flipped_age = None
    with reg.lock:
        if stale > 0 and reg.last_finalize is not None \
                and reg.health == "ok":
            now = clock() if now is None else now
            age = now - reg.last_finalize
            if age > stale:
                reg.health = "degraded"
                flipped_age = age
                reg.transitions.append(
                    ("degraded", now, round(age, 3)))
        status = reg.health
    if flipped_age is not None:
        telemetry.event("health_degraded", durable=True,
                        age_s=round(flipped_age, 3), stale_s=stale)
    return status


def health_snapshot(now: Optional[float] = None) -> dict:
    """The `/healthz` JSON body: current status, per-engine tier and
    mesh shape, last-finalized-window age, backlog, throughput, the
    demotion log tail, and the run-ledger status."""
    from . import resilience

    reg = _reg()
    now = clock() if now is None else now
    with reg.lock:
        age = (None if reg.last_finalize is None
               else round(now - reg.last_finalize, 3))
        backlog = reg.gauges.get(("gs_inflight_chunks", ()), 0.0)
        snap = {
            "status": reg.health,
            "last_finalize_age_s": age,
            "stale_after_s": stale_after_s(),
            "windows_finalized": reg.windows_total,
            "edges_total": reg.edges_total,
            "edges_per_s_ema": (None if reg.edges_per_s_ema is None
                                else round(reg.edges_per_s_ema)),
            "backlog_chunks": backlog,
            "engines": {k: dict(v) for k, v in reg.engines.items()},
            "transitions": [list(t)
                            for t in list(reg.transitions)[-8:]],
            "compiles": {
                name: {"count": c["count"],
                       "allowed": c.get("allowed"),
                       "storm": c["storm"]}
                for name, c in reg.compiles.items()},
            # per-tenant liveness: window/edge counters + the age of
            # each tenant's OWN last finalize (bounded table — see
            # tenant_key; a stale tenant is flagged per-row so one
            # wedged stream is visible while the cohort stays ok)
            "tenants": {
                tid: {
                    "windows": info.get("windows", 0),
                    "edges": info.get("edges", 0),
                    "tier": info.get("tier"),
                    # per-tenant cost attribution (attribute_dispatch)
                    "device_s": round(info.get("device_s", 0.0), 6),
                    "attr_bytes": round(info.get("attr_bytes", 0.0)),
                    "last_finalize_age_s": (
                        None if info.get("last_finalize") is None
                        else round(now - info["last_finalize"], 3)),
                    "stale": bool(
                        stale_after_s() > 0
                        and info.get("last_finalize") is not None
                        and now - info["last_finalize"]
                        > stale_after_s()),
                }
                for tid, info in reg.tenants.items()},
        }
    snap["demotions"] = resilience.demotion_events()[-5:]
    snap["trace"] = telemetry.trace_id()
    snap["ledger"] = telemetry.ledger_path()
    with _REG_LOCK:
        sections = dict(_HEALTH_SECTIONS)
    for name, provider in sections.items():
        try:
            snap[name] = provider()
        except Exception as e:  # gslint: disable=except-hygiene (a broken serving-layer provider must degrade to an error cell in the probe body, never crash the health endpoint itself)
            snap[name] = {"error": "%s: %s" % (type(e).__name__, e)}
    snap["hot_tenants"] = hot_tenants(snap)
    return snap


def hot_tenants(snap: dict, k: int = 8) -> list:
    """Ranked top-K hot-tenant rows off one health snapshot: each
    tenant's device-seconds SHARE (attribute_dispatch's table) joined
    with the latency plane's per-tenant p99 against the SLO target —
    `score = device_share + min(p99 / target, 1)` (the SLO term is 0
    when the plane or the target is disarmed), so a tenant burning
    the device OR burning the error budget surfaces first. This is
    the placement-advisor signal the fleet router consumes
    (tools/tenant_report.py renders it per process)."""
    tens = snap.get("tenants") or {}
    lat = snap.get("latency")
    lanes = (lat.get("tenants") or {}) if isinstance(lat, dict) else {}
    slo = lat.get("slo") if isinstance(lat, dict) else None
    target = (slo or {}).get("target_p99_s") or 0.0
    total_s = sum(row.get("device_s") or 0.0 for row in tens.values())
    rows = []
    for tid, row in tens.items():
        share = ((row.get("device_s") or 0.0) / total_s
                 if total_s > 0 else 0.0)
        lane = lanes.get(tid) or {}
        p99 = lane.get("e2e_p99_s")
        score = share
        if target > 0 and p99:
            score += min(p99 / target, 1.0)
        rows.append({
            "tenant": tid,
            "score": round(score, 6),
            "device_share": round(share, 6),
            "device_s": row.get("device_s", 0.0),
            "attr_bytes": row.get("attr_bytes", 0),
            "tier": row.get("tier"),
            "e2e_p99_s": p99,
            "queue_age_s": lane.get("queue_age_s"),
            "burn_rate": (slo or {}).get("burn_rate"),
            "stale": row.get("stale"),
        })
    rows.sort(key=lambda r: (-r["score"], r["tenant"]))
    return rows[:k]


def _maybe_serve() -> None:
    """Bring up the health endpoint once GS_METRICS_PORT asks for one
    (lazy import: healthz imports this module)."""
    if knobs.get_int("GS_METRICS_PORT") > 0:
        from . import healthz

        healthz.maybe_start()


# ----------------------------------------------------------------------
# compile watch
# ----------------------------------------------------------------------
def _leaf_sig(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(int(s) for s in shape), str(dtype))
    if isinstance(x, (list, tuple)):
        return ("seq",) + tuple(_leaf_sig(e) for e in x)
    if isinstance(x, dict):
        return ("map",) + tuple((k, _leaf_sig(v))
                                for k, v in sorted(x.items()))
    return ("py", type(x).__name__)


def _sig_size(sig) -> int:
    """Total array elements under one signature — the 'V' of the
    O(log V) envelope."""
    if not isinstance(sig, tuple):
        return 0
    if sig and sig[0] == "arr":
        n = 1
        for d in sig[1]:
            n *= max(d, 1)
        return n
    return sum(_sig_size(s) for s in sig)


def abstract_sig(args, kwargs=None) -> tuple:
    """Abstract shape signature of one call: array leaves reduce to
    (shape, dtype) — exactly the identity jit compiles per."""
    sig = tuple(_leaf_sig(a) for a in args)
    if kwargs:
        sig += tuple((k, _leaf_sig(v))
                     for k, v in sorted(kwargs.items()))
    return sig


def note_compile(name: str, sig: tuple) -> None:
    """Count one (re)compile of `name` at `sig` and enforce the
    O(log V) bucket-growth envelope; the first compile past it stamps
    a durable `recompile_storm` event (sticky per function)."""
    if not enabled():
        return
    reg = _reg()
    base = knobs.get_int("GS_METRICS_COMPILE_BASE")
    size = max(1, _sig_size(sig))
    storm = None
    with reg.lock:
        c = reg.compiles.setdefault(
            name, {"count": 0, "lo": size, "hi": size, "storm": False})
        c["count"] += 1
        c["lo"] = min(c["lo"], size)
        c["hi"] = max(c["hi"], size)
        growth = math.log2(c["hi"] / c["lo"])
        c["allowed"] = base + int(growth) + 1
        if c["count"] > c["allowed"] and not c["storm"]:
            c["storm"] = True
            storm = (c["count"], c["allowed"])
    counter_inc("gs_compiles_total", 1, fn=name)
    if storm is not None:
        counter_inc("gs_recompile_storms_total", 1, fn=name)
        telemetry.event("recompile_storm", durable=True, fn=name,
                        compiles=storm[0], allowed=storm[1])


_SIG_CAP = 4096  # per-wrapper distinct-signature memory bound


def wrap_jit(name: str, fn):
    """Wrap a jitted entry point: each call whose abstract shape
    signature was not seen before counts as one compile of `name`
    (jit compiles exactly per signature). Disarmed, the wrapper is a
    set lookup + passthrough; results are identical either way. The
    signature set is bounded at _SIG_CAP: a churner past it (deep in
    storm territory — the sticky event fired thousands of compiles
    earlier) keeps counting but stops being remembered, so the
    watcher itself can't leak in the failure mode it detects (a
    re-presented old signature may then over-count)."""
    seen = set()

    def wrapped(*args, **kwargs):
        # the cost observatory (utils/costmodel) rides the same
        # wrapper: armed, each call tags the pending dispatch-span
        # attributes with (program, signature) and the first call at
        # a new signature captures the program's XLA cost model
        cm = costmodel.enabled()
        if enabled() or cm:
            sig = abstract_sig(args, kwargs)
            if enabled() and sig not in seen:
                if len(seen) < _SIG_CAP:
                    seen.add(sig)
                note_compile(name, sig)
            if cm:
                costmodel.on_call(name, fn, sig, args, kwargs)
        return fn(*args, **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", name)
    wrapped.__wrapped__ = fn
    return wrapped


def compile_report() -> Dict[str, dict]:
    reg = _reg()
    with reg.lock:
        return {name: dict(c) for name, c in reg.compiles.items()}


# ----------------------------------------------------------------------
# memory watch
# ----------------------------------------------------------------------
def sample_memory() -> dict:
    """Snapshot live-buffer and device-memory accounting. Always
    RETURNS the sample (tools/endurance_run.py's leak detector reads
    it directly); gauges are set only when armed. Backends without
    memory_stats() simply contribute no device rows."""
    out = {"live_buffers": None, "live_buffer_bytes": None,
           "devices": []}
    try:
        import jax

        arrs = jax.live_arrays()
        total = 0
        for a in arrs:
            nbytes = getattr(a, "nbytes", 0) or 0
            total += nbytes
        out["live_buffers"] = len(arrs)
        out["live_buffer_bytes"] = total
        for dev in jax.devices():
            try:
                stats = dev.memory_stats()
            except Exception:  # gslint: disable=except-hygiene (capability probe: backends without memory_stats contribute no row)
                stats = None
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            out["devices"].append({
                "device": str(dev), "bytes_in_use": in_use,
                "bytes_limit": limit})
    except Exception as e:
        telemetry.event("memory_sample_failed",
                        error="%s: %s" % (type(e).__name__, e))
        return out
    if enabled():
        if out["live_buffers"] is not None:
            gauge_set("gs_live_buffers", out["live_buffers"])
            gauge_set("gs_live_buffer_bytes", out["live_buffer_bytes"])
        for row in out["devices"]:
            if row["bytes_in_use"] is not None:
                gauge_set("gs_device_bytes_in_use",
                          row["bytes_in_use"], device=row["device"])
    return out

"""Real-shaped graph streams (VERDICT r2 missing-3): a citation-stream
generator calibrated against the published SNAP cit-HepPh summary
statistics, for scale/bench legs and workload runs whose input should
have real-graph degree/timestamp shape rather than the synthetic
power-law of bench.make_stream. (Zero-egress environment: the actual
SNAP file cannot be downloaded, so the generator is validated against
the dataset's published numbers instead — tests/library/test_realgraph.py
asserts the calibration.)

Published anchors (SNAP cit-HepPh summary page; also the dataset named
by /root/repo/BASELINE.json's Continuous Degree Aggregate config):
    nodes 34,546 · edges 421,578 · average clustering coefficient
    0.2848 · triangles 1,276,868
The generated graph hits the node/edge counts exactly and lands within
a few percent of the clustering/triangle figures (seed-pinned values
asserted in the test). SNAP publishes no max-degree figure, so the
degree tail is anchored instead by the in-degree power-law exponent,
asserted inside the α ≈ 2-3.5 band reported for citation networks.

Model: time-ordered preferential attachment with triadic closure and a
bimodal paper population — ordinary papers cite ~11 references, a
survey stratum (1 in 12) cites 60, mostly by copying reference pairs
from already-chosen papers (co-citation bursts). The copying is what
concentrates triangles in hub neighborhoods, which is exactly how the
real dataset combines a high global triangle count with a moderate
average clustering coefficient: hub triangles barely move the local
coefficient of a high-degree vertex. Citations always point backwards
in time, so the stream is a DAG with strictly increasing timestamps
and no self-loops — the shape every ingest path downstream assumes.
"""

from __future__ import annotations

import random

import numpy as np

# SNAP cit-HepPh published summary statistics (the calibration anchors)
CIT_HEPPH_NODES = 34_546
CIT_HEPPH_EDGES = 421_578
CIT_HEPPH_AVG_CLUSTERING = 0.2848
CIT_HEPPH_TRIANGLES = 1_276_868

# Calibrated model parameters (tests assert the resulting statistics;
# re-tune these only against the published anchors above)
_SURVEY_EVERY = 12       # 1-in-12 papers is a survey
_SURVEY_M = 60           # survey reference-list length
_SURVEY_CLOSURE = 0.48   # survey triadic-closure probability
_BASE_CLOSURE = 0.57     # ordinary-paper closure probability
_UNIFORM = 0.46          # uniform (non-preferential) citation share
_BURST = 2               # co-citation copy length for surveys


def citation_stream(num_papers: int = CIT_HEPPH_NODES,
                    num_edges: int = CIT_HEPPH_EDGES,
                    seed: int = 17):
    """Deterministic cit-HepPh-shaped edge stream.

    Returns (src, dst, ts): src strictly newer than dst (a DAG, no
    self-loops), ts = arrival index (strictly increasing, the
    event-time contract of SimpleEdgeStream's extractors). Exactly
    `num_edges` edges over exactly `num_papers` vertices.
    """
    rng = random.Random(seed)
    out_adj: list = [()] * num_papers
    repeated: list = []        # PA urn: one entry per received citation
    src_l: list = []
    dst_l: list = []

    # exact edge quotas: surveys take _SURVEY_M, the remainder spreads
    # over ordinary papers; early papers (t < quota) push their
    # shortfall onto later ones
    n_cite = num_papers - 1
    surveys = sum(1 for t in range(1, num_papers)
                  if t % _SURVEY_EVERY == 0)
    base_total = num_edges - surveys * _SURVEY_M
    base_n = n_cite - surveys
    base_m, rem = divmod(base_total, base_n)
    deficit = 0
    base_seen = 0
    for t in range(1, num_papers):
        if t % _SURVEY_EVERY == 0:
            m = _SURVEY_M
            closure, burst = _SURVEY_CLOSURE, _BURST
        else:
            base_seen += 1
            m = base_m + (1 if base_seen <= rem else 0)
            closure, burst = _BASE_CLOSURE, 1
        m += deficit
        take = min(m, t)
        deficit = m - take
        m = take

        targets: list = []
        tset: set = set()
        guard = 0
        while len(targets) < m and guard < 60 * m:
            guard += 1
            if targets and rng.random() < closure:
                u = targets[rng.randrange(len(targets))]
                refs = out_adj[u]
                if refs:
                    start = rng.randrange(len(refs))
                    for j in range(burst):
                        if len(targets) >= m:
                            break
                        w = refs[(start + j) % len(refs)]
                        if w not in tset:
                            tset.add(w)
                            targets.append(w)
                            repeated.append(w)
                    continue
            if rng.random() < _UNIFORM or not repeated:
                w = rng.randrange(t)
            else:
                w = repeated[rng.randrange(len(repeated))]
            if w not in tset:
                tset.add(w)
                targets.append(w)
                repeated.append(w)
        deficit += m - len(targets)   # guard exhaustion (tiny graphs)
        out_adj[t] = tuple(targets)
        src_l.extend([t] * len(targets))
        dst_l.extend(targets)

    src = np.array(src_l, np.int32)
    dst = np.array(dst_l, np.int32)
    ts = np.arange(len(src), dtype=np.int64)
    return src, dst, ts


def undirected_stats(src: np.ndarray, dst: np.ndarray, n: int):
    """Exact (triangles, average local clustering coefficient, degree
    vector) of the undirected simple graph underlying a COO stream —
    the quantities the SNAP summary pages publish. Set-intersection
    edge iterator: each edge (u,v) contributes |N(u) ∩ N(v)| shared
    neighbors; every triangle is counted once per edge (÷3 globally)
    and twice per incident vertex (÷2 locally)."""
    adj = [set() for _ in range(n)]
    for u, v in zip(src.tolist(), dst.tolist()):
        if u != v:
            adj[u].add(v)
            adj[v].add(u)
    acc = np.zeros(n, np.int64)
    tri3 = 0
    for u in range(n):
        au = adj[u]
        for v in au:
            if v > u:
                c = len(au & adj[v])
                tri3 += c
                acc[u] += c
                acc[v] += c
    deg = np.array([len(a) for a in adj], np.int64)
    tv = acc / 2
    with_deg = deg >= 2
    local = np.zeros(n)
    local[with_deg] = tv[with_deg] / (deg[with_deg]
                                      * (deg[with_deg] - 1) / 2)
    avg_cc = float(local[deg > 0].mean()) if (deg > 0).any() else 0.0
    return tri3 // 3, avg_cc, deg


def indegree_powerlaw_alpha(dst: np.ndarray, n: int,
                            dmin: int = 20) -> float:
    """Discrete-MLE power-law exponent of the in-degree tail (Clauset
    et al.'s continuous approximation, adequate for a band assert):
    α = 1 + k / Σ ln(d_i / (dmin - ½)) over degrees ≥ dmin."""
    ind = np.bincount(dst, minlength=n)
    tail = ind[ind >= dmin].astype(float)
    if len(tail) == 0:
        return float("nan")
    return float(1.0 + len(tail) / np.log(tail / (dmin - 0.5)).sum())

"""Health & metrics endpoint — the serving half of the live
observability plane (utils/metrics.py is the registry it exposes).

An opt-in stdlib `http.server` daemon thread bound to 127.0.0.1
(`GS_METRICS_PORT`; port 0 in code = ephemeral, for tests) serving:

  GET /metrics   the registry in Prometheus text exposition format
  GET /healthz   JSON: status (`ok` / `degraded`), per-engine tier and
                 mesh shape, last-finalized-window age, backlog,
                 throughput, demotion-log tail, compile-watch state,
                 run-ledger path — HTTP 200 while ok, 503 degraded,
                 so a probe needs no JSON parsing

plus the **staleness watchdog**: a daemon thread calling
`metrics.check_staleness()` every quarter of `GS_HEALTH_STALE_S`, so
a wedged tunnel (no window finalizing) flips `/healthz` to `degraded`
and stamps a durable `health_degraded` event within one watchdog
interval — the round-5 dead-queue-hour failure shape becomes a live
signal instead of a post-mortem. Recovery is the next finalize
(metrics.mark_window flips back and stamps `health_recovered`).

The server is brought up lazily by the instrumented layers (driver /
engines / pipeline call metrics.on_stream_start / mark_window, which
consult GS_METRICS_PORT), or explicitly via `start()`. Everything here
is observation-only: no handler touches stream state.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import knobs
from . import metrics
from . import telemetry


class _Handler(BaseHTTPRequestHandler):
    server_version = "gs-healthz/1"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            if self.path.split("?")[0] == "/metrics":
                body = metrics.render_prometheus().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path.split("?")[0] == "/healthz":
                metrics.check_staleness()  # request-time freshness
                snap = metrics.health_snapshot()
                code = 200 if snap["status"] == "ok" else 503
                self._send(code, (json.dumps(snap, default=str)
                                  + "\n").encode(), "application/json")
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as e:
            # a probe must never crash the serving thread; the failure
            # is recorded, the prober sees a 500
            telemetry.event("healthz_request_failed",
                            error="%s: %s" % (type(e).__name__, e))
            try:
                self._send(500, b"internal error\n", "text/plain")
            except OSError:
                pass  # client went away mid-error: nothing to do

    def log_message(self, fmt, *args):
        pass  # probes are high-frequency; stderr is not a log sink


class HealthServer:
    """One HTTP daemon thread + one watchdog daemon thread."""

    def __init__(self, port: int):
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="gs-healthz")
        self._thread.start()
        self._stop = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watch, daemon=True, name="gs-health-watchdog")
        self._watchdog.start()

    def _watch(self) -> None:
        while not self._stop.is_set():
            stale = metrics.stale_after_s()
            tick = min(max(stale / 4.0, 0.05), 1.0) if stale > 0 else 1.0
            if self._stop.wait(tick):
                return
            metrics.check_staleness()

    def close(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


_SERVER: Optional[HealthServer] = None
_SERVER_LOCK = threading.Lock()


def start(port: Optional[int] = None) -> HealthServer:
    """Bring up (or return) the process's health server. `port` None
    reads GS_METRICS_PORT; pass 0 for an OS-assigned ephemeral port
    (tests / the chaos drill) — the bound port is `.port`."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None:
            if port is None:
                port = knobs.get_int("GS_METRICS_PORT")
            _SERVER = HealthServer(port)
        return _SERVER


def maybe_start() -> Optional[HealthServer]:
    """Idempotent lazy start used by the instrumented layers: a
    server comes up only when GS_METRICS_PORT names a port."""
    if _SERVER is not None:
        return _SERVER
    if knobs.get_int("GS_METRICS_PORT") <= 0:
        return None
    return start()


def stop() -> None:
    """Shut the server down (tests / operator teardown)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None

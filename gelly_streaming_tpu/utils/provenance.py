"""Per-window provenance ledger — the portable audit trail of WHAT
was computed, WHERE, and FROM WHICH journal span.

The scale-out fabric (ROADMAP: tenant placement, live migration,
elastic rebalancing) needs a proof stronger than "the digests matched
in this process": a durable record, per finalized window, of the
tenant, the window ordinal, the covered `wal_offset` span, the
computing tier + program, the knob fingerprint the process ran under,
and the sha256 of the summary handed to the caller. With that record
and the WAL, ANY process can re-derive the window on ANY tier and
diff digests — `tools/replay_window.py` is that operator command, and
a migrated tenant's first post-move windows can be audited against
the records its old home wrote.

Format mirrors utils/wal.py (the proven torn-tail discipline):
segment files `prov_<NNNNNNNN>.seg` under one directory, an 8-byte
magic, then records back to back:

    [u32 crc32(payload)] [u32 payload_len] [payload]

    payload: canonical JSON (sorted keys, compact separators) of
             {digest, knobs, program, sig, tenant, tier,
              wal_hi, wal_lo, window}

Records never split across segments; rotation happens between
appends once a segment passes GS_WAL_SEGMENT_BYTES (the journal's
own rotation bound — provenance records are ~200 bytes, so one
segment holds ~300k windows). GS_PROVENANCE_RETAIN > 0 bounds disk:
only that many CLOSED segments are kept behind the open one (0 =
keep everything; the DLQ's retention shape).

Records carry NO wall-clock fields and no process identity on
purpose: a record is a pure function of (tenant, window, tier,
program, knobs, summary), so a kill→checkpoint-resume→WAL-replay run
re-emits byte-identical payloads for the replayed windows
(tools/chaos_run.py provenance leg pins this). Duplicate records for
one (tenant, window) are expected under at-least-once replay —
readers key on the triple and verify digests agree.

The reader tolerates a torn TAIL (partial/CRC-failing bytes at the
end of the LAST segment — the only place an in-flight crash can
tear) by stopping there with a durable `provenance_torn_tail` event;
the same damage anywhere else raises typed `ProvenanceCorrupt`.
Reopening a damaged directory truncates the torn bytes physically,
exactly like the WAL — the record was never acknowledged durable.

`GS_PROVENANCE=0` (the default) is the kill switch: `armed()` is
False, every `emit()` call is a guarded no-op, and the disarmed hot
path stays bit-identical to a ledger-less build (pinned by
tests/test_provenance.py and the profiler's armed-vs-disarmed row).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional

from . import knobs
from . import metrics
from . import telemetry

_MAGIC = b"GSPRVSG1"
_HEAD = struct.Struct("<II")          # crc32, payload_len
_SEG_FMT = "prov_%08d.seg"

# record fields, in canonical (sorted) order — _encode_payload writes
# exactly these keys, so every writer produces byte-identical payloads
# for identical records regardless of call-site dict ordering
FIELDS = ("digest", "knobs", "program", "sig", "tenant", "tier",
          "wal_hi", "wal_lo", "window")


def enabled() -> bool:
    """GS_PROVENANCE=0 (default) is the kill switch: every emit()
    site no-ops and finalize paths stay ledger-less."""
    return knobs.get_bool("GS_PROVENANCE")


def directory() -> Optional[str]:
    """GS_PROVENANCE_DIR: where the ledger segments live; unset
    disarms emit() even with GS_PROVENANCE=1 (nowhere to write)."""
    return knobs.get_path("GS_PROVENANCE_DIR")


def armed() -> bool:
    return enabled() and directory() is not None


class ProvenanceCorrupt(RuntimeError):
    """Ledger damage outside the torn-tail window: a CRC failure or
    truncation NOT at the end of the last segment. `path` names the
    damaged segment."""

    def __init__(self, path: str, problem: str):
        super().__init__("provenance segment %r is corrupt: %s"
                         % (path, problem))
        self.path = path


# ----------------------------------------------------------------------
# digests
# ----------------------------------------------------------------------
def summary_digest(summary) -> str:
    """sha256 hex of one window summary's canonical JSON (sorted
    keys, compact separators) — the cross-tier comparison key. Every
    tier's summary dicts are plain host scalars by the time they are
    handed to the caller, so canonical JSON is total and stable."""
    blob = json.dumps(summary, sort_keys=True,
                      separators=(",", ":"), default=_jsonable)
    return hashlib.sha256(blob.encode()).hexdigest()


def _jsonable(x):
    # numpy scalars reach summaries on some host paths; canonicalize
    # to the python value so host and device tiers hash identically
    if hasattr(x, "item"):
        return x.item()
    raise TypeError("summary field %r is not canonically hashable"
                    % (type(x).__name__,))


def result_digest(res) -> str:
    """sha256 hex of a driver WindowResult's analytic content —
    window_start, num_edges, and the raw bytes of the snapshot arrays
    that are populated at finalize time (absent analytics hash as
    presence markers; a triangles count still pending in the batched
    flush is excluded, which is deterministic per configuration).
    Replaying the same span through the same configuration re-derives
    the same bytes, so this is the driver tier's parity key."""
    import numpy as _np

    h = hashlib.sha256()
    h.update(b"%d|%d" % (int(res.window_start), int(res.num_edges)))
    for name in ("degrees", "cc_labels", "bipartite_odd"):
        a = getattr(res, name, None)
        h.update(b"|" + name.encode() + b":")
        if a is not None:
            h.update(_np.ascontiguousarray(a).tobytes())
    t = getattr(res, "triangles", None)
    h.update(b"|tri:" + (b"-" if t is None else b"%d" % int(t)))
    return h.hexdigest()


_FP_LOCK = threading.Lock()
_FP_CACHE: Dict[tuple, str] = {}


def knob_fingerprint() -> str:
    """sha256 hex prefix over every registered knob's EFFECTIVE raw
    text (unset = its registered default) — the configuration
    identity a record was computed under. Two processes with equal
    fingerprints ran the same knob surface, so digest divergence
    between their records is a real computation difference, never a
    config drift. PATH-kind knobs (trace dirs, cache dirs, this
    ledger's own directory) are deployment-local and never change a
    computed value, so they are excluded — the fingerprint must
    survive a tenant migration to a host with different paths, and a
    crash recovery into a fresh workdir. Cached per
    effective-environment snapshot (reads are live; tests flip knobs
    mid-process)."""
    names = sorted(n for n in knobs.REGISTRY
                   if knobs.REGISTRY[n].kind != "path")
    snap = tuple(knobs._raw(n) for n in names)
    with _FP_LOCK:
        got = _FP_CACHE.get(snap)
        if got is not None:
            return got
        blob = "\n".join(
            "%s=%s" % (n, v if v is not None
                       else repr(knobs.REGISTRY[n].default))
            for n, v in zip(names, snap))
        fp = hashlib.sha256(blob.encode()).hexdigest()[:16]
        if len(_FP_CACHE) > 64:
            _FP_CACHE.clear()
        _FP_CACHE[snap] = fp
        return fp


# ----------------------------------------------------------------------
# framing
# ----------------------------------------------------------------------
def _encode_payload(rec: dict) -> bytes:
    return json.dumps({k: rec.get(k) for k in FIELDS},
                      sort_keys=True,
                      separators=(",", ":")).encode()


def _frame(payload: bytes) -> bytes:
    return _HEAD.pack(zlib.crc32(payload), len(payload)) + payload


def _segments(dirpath: str) -> List[str]:
    try:
        names = sorted(f for f in os.listdir(dirpath)
                       if f.startswith("prov_") and f.endswith(".seg"))
    except FileNotFoundError:
        return []
    return [os.path.join(dirpath, f) for f in names]


def _iter_segment(path: str, is_last: bool) -> Iterator[dict]:
    """Records of one segment. Damage at the TAIL of the last segment
    yields a final {"torn": ...} marker; damage anywhere else raises
    ProvenanceCorrupt (silent mid-ledger loss would hide an audit
    hole)."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < len(_MAGIC) or not data.startswith(_MAGIC):
        if is_last and len(data) < len(_MAGIC) \
                and _MAGIC.startswith(data):
            yield {"torn": "segment header",
                   "dropped_bytes": len(data), "valid_bytes": 0}
            return
        raise ProvenanceCorrupt(path, "bad segment magic")
    off = len(_MAGIC)
    while off < len(data):
        tail = len(data) - off
        torn = None
        if tail < _HEAD.size:
            torn = "partial record header (%d bytes)" % tail
        else:
            crc, length = _HEAD.unpack_from(data, off)
            if tail - _HEAD.size < length:
                torn = ("record body truncated (%d of %d bytes)"
                        % (tail - _HEAD.size, length))
            else:
                payload = data[off + _HEAD.size:
                               off + _HEAD.size + length]
                if zlib.crc32(payload) != crc:
                    torn = "record CRC mismatch"
        if torn is not None:
            if not is_last:
                raise ProvenanceCorrupt(path, torn + " mid-ledger")
            yield {"torn": torn, "dropped_bytes": tail,
                   "valid_bytes": off}
            return
        yield json.loads(payload)
        off += _HEAD.size + length


def scan(dirpath: str) -> dict:
    """Every intact record in append order plus damage status:
    {"records": [...], "segments": n, "torn": None | {...}}. A torn
    tail (last segment only) stamps the durable `provenance_torn_tail`
    event once and stops the scan there."""
    records: List[dict] = []
    torn = None
    segs = _segments(dirpath)
    for i, path in enumerate(segs):
        for rec in _iter_segment(path, is_last=(i == len(segs) - 1)):
            if "torn" in rec:
                telemetry.event("provenance_torn_tail", durable=True,
                                segment=os.path.basename(path),
                                problem=rec["torn"],
                                dropped_bytes=rec["dropped_bytes"])
                metrics.counter_inc("gs_provenance_torn_tail_total")
                torn = {"segment": path, "problem": rec["torn"],
                        "dropped_bytes": rec["dropped_bytes"],
                        "valid_bytes": rec["valid_bytes"]}
                break
            records.append(rec)
        if torn is not None:
            break
    return {"records": records, "segments": len(segs), "torn": torn}


# ----------------------------------------------------------------------
# the appender
# ----------------------------------------------------------------------
class ProvenanceLedger:
    """Appender over one ledger directory. Reopening an existing
    directory quarantines a torn tail physically (truncate/unlink —
    the record was never acknowledged) and continues in a FRESH
    segment, exactly the WAL's reopen contract."""

    def __init__(self, dirpath: str):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._lock = threading.Lock()
        info = scan(dirpath)
        if info["torn"] is not None:
            torn = info["torn"]
            if torn["valid_bytes"] < len(_MAGIC):
                os.unlink(torn["segment"])
            else:
                with open(torn["segment"], "r+b") as f:
                    f.truncate(torn["valid_bytes"])
        segs = _segments(dirpath)
        # next index from the highest EXISTING name, not the count:
        # retention deletes prefix segments, and a count-derived index
        # would re-open a live segment mid-file (the WAL's lesson)
        self._seg_no = (max(int(os.path.basename(p)[5:-4])
                            for p in segs) + 1) if segs else 0
        self._file = None
        self._file_bytes = 0

    def _ensure_segment(self):
        if self._file is not None \
                and self._file_bytes >= knobs.get_int(
                    "GS_WAL_SEGMENT_BYTES"):
            self._file.close()
            self._file = None
            self._file_bytes = 0
            self._retain()
        if self._file is None:
            path = os.path.join(self.dir, _SEG_FMT % self._seg_no)
            self._seg_no += 1
            self._file = open(path, "ab")
            self._file.write(_MAGIC)
            self._file.flush()
            self._file_bytes = len(_MAGIC)
        return self._file

    def _retain(self) -> None:
        """GS_PROVENANCE_RETAIN: keep at most that many CLOSED
        segments (0 = keep all). Runs at rotation, so the open
        segment is never a candidate."""
        keep = knobs.get_int("GS_PROVENANCE_RETAIN")
        if keep <= 0:
            return
        closed = _segments(self.dir)
        for path in closed[:-keep] if len(closed) > keep else []:
            os.unlink(path)

    def append(self, rec: dict) -> None:
        """Durably append one record (fsync per append: a finalize
        already synced its WAL span, and records are ~200 bytes)."""
        frame = _frame(_encode_payload(rec))
        with self._lock:
            f = self._ensure_segment()
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
            self._file_bytes += len(frame)
        metrics.counter_inc("gs_provenance_records_total")

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# ----------------------------------------------------------------------
# the module singleton every finalize owner writes through
# ----------------------------------------------------------------------
_LOCK = threading.Lock()
_LEDGER: Optional[ProvenanceLedger] = None


def _ledger() -> Optional[ProvenanceLedger]:
    global _LEDGER
    d = directory()
    if d is None:
        return None
    with _LOCK:
        if _LEDGER is None or _LEDGER.dir != d:
            if _LEDGER is not None:
                _LEDGER.close()
            _LEDGER = ProvenanceLedger(d)
        return _LEDGER


def emit(*, tenant: str, window: int, wal_lo: int, wal_hi: int,
         tier: str, program: str, summary=None,
         digest: Optional[str] = None,
         sig: Optional[str] = None) -> None:
    """Record one finalized window. A guarded no-op unless armed
    (GS_PROVENANCE=1 AND GS_PROVENANCE_DIR set) — the single call
    every finalize owner makes, cheap enough to sit on the hot path
    disarmed. Pass `summary` (digested here) or a precomputed
    `digest`."""
    if not armed():
        return
    if digest is None:
        digest = summary_digest(summary)
    led = _ledger()
    if led is None:
        return
    led.append({
        "tenant": str(tenant),
        "window": int(window),
        "wal_lo": int(wal_lo),
        "wal_hi": int(wal_hi),
        "tier": str(tier),
        "program": str(program),
        "sig": None if sig is None else str(sig),
        "knobs": knob_fingerprint(),
        "digest": digest,
    })


def reset() -> None:
    """Close and forget the singleton (tests / directory swaps)."""
    global _LEDGER
    with _LOCK:
        if _LEDGER is not None:
            _LEDGER.close()
            _LEDGER = None
    with _FP_LOCK:
        _FP_CACHE.clear()

"""End-to-end latency plane: what a user of the serving front-end
actually FEELS, measured from socket admission to delivered window.

Every observability layer before this one (flight recorder §12,
health plane §14, dispatch observatory §16) measures throughput and
dispatch cost; none measured result *freshness* — the product metric
of a streaming system (PAPER.md L1's event-time/watermark semantics
are exactly a freshness contract). This module closes that gap:

- **Admission marks.** Every accepted edge batch is stamped with a
  monotonic ingest timestamp at its admission boundary
  (`TenantCohort.feed`, `SummaryEngineBase.process`,
  `driver.run_arrays` — the serve front-end's socket requests land in
  the first of these). Marks are per-lane (tenant/engine/driver)
  cumulative-edge-offset cursors, so a finalized window joins back to
  the admission time of the edge that COMPLETED it.
- **Stage waterfall.** The layers stamp boundary times as a window's
  edges move through the pipeline (queue-wait end / slab-prep / h2d /
  dispatch / finalize / delivery). A window's stage latencies are the
  CONSECUTIVE DIFFS of those boundaries, so they sum to the measured
  ingest→deliver end-to-end exactly by construction — the
  conservation discipline tools/latency_report.py re-checks from the
  ledger (same contract explain_perf holds for cost attribution).
  Stages a path cannot attribute are simply absent (the driver's
  coarse decomposition folds prep+h2d into its dispatch boundary);
  the sum identity still holds.
- **Per-tenant percentiles.** Bounded reservoirs (same nearest-rank
  percentile math as utils/telemetry) per lane, under the SAME
  cardinality bound as the metrics registry (past GS_METRICS_SERIES
  lanes collapse into one `overflow` lane). Armed metrics additionally
  get `gs_latency_e2e_seconds{tenant=}` / `gs_latency_stage_seconds{
  stage=}` histograms.
- **Watermark-lag twin.** `queue_age(lane)` = age of the oldest
  ADMITTED-but-unfinalized edge — the ingestion-time twin of event
  -time watermark lag (keyed to event time when that lands), exposed
  as the `gs_latency_oldest_edge_age_s` gauge and per-tenant
  `gs_tenant_queue_age_s`.
- **SLO burn.** With GS_SLO_P99_S set, every delivered window is
  good/bad against the target; the error budget (GS_SLO_BUDGET,
  default 1%) burns at rate `(bad/total)/budget` over a sliding
  GS_SLO_WINDOW_S. Sustained burn ≥ GS_SLO_BURN flips the `/healthz`
  `latency` section to `degraded` with a durable `slo_burn` ledger
  event (once per episode); recovery stamps `slo_recovered`.
- **Replay honesty.** Admission stamps ride the WAL record's ts
  column (int64 nanoseconds of the monotonic clock) on the cohort and
  engine journals, so kill→WAL-replay recovery re-seeds the marks
  with the ORIGINAL admission times — replayed windows report their
  honest, larger latency, never reset-to-zero (chaos latency leg).
  Stamps are CLOCK_MONOTONIC-domain: comparable across processes on
  one boot (the recovery shape), meaningless across reboots — a
  negative replay age clamps to zero rather than lie.

Zero-overhead contract (the flight-recorder discipline): with
GS_LATENCY=0 (the default) every hook is a guarded no-op, summaries
and WAL bytes are bit-identical to a plane-less build
(tests/test_latency.py digest parity; the armed ≤1.05× overhead bar
is committed to PERF_cpu.json's `latency` section).

Knobs (utils/knobs.py):
    GS_LATENCY       0 (default) = disarmed no-ops; 1 = record
    GS_LAT_MARKS     per-lane admission-mark memory bound
    GS_LAT_PENDING   bounded not-yet-delivered window records
    GS_SLO_P99_S     delivered-window latency target; 0 = SLO off
    GS_SLO_BUDGET    allowed bad-window fraction (error budget)
    GS_SLO_WINDOW_S  sliding burn-rate measurement window
    GS_SLO_BURN      burn rate that flips `latency` degraded
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from . import knobs
from . import metrics
from . import telemetry

clock = telemetry.clock  # ONE clock family with the span ledger

# canonical stage taxonomy, in pipeline order; boundary stamps carry
# the name of the stage they CLOSE (see stamp()/on_window)
STAGES = ("admission", "queue_wait", "prep", "h2d", "dispatch",
          "finalize", "deliver")
# boundary-stamp keys a stamps() dict may carry, in order; "start"
# closes queue_wait (it is the first post-queue boundary)
_BOUNDARIES = (("queue_wait", "start"), ("prep", "prep"),
               ("h2d", "h2d"), ("dispatch", "dispatch"))

_RESERVOIR = 512   # per-lane / per-stage sample cap (percentile source)
_RECENT = 2048     # introspection ring of emitted window records
_SLO_MIN_WINDOWS = 8  # burn verdicts need a minimal sample


def enabled() -> bool:
    """GS_LATENCY arms the plane; off (the default) every hook is a
    guarded no-op and the hot path is bit-identical."""
    return knobs.get_bool("GS_LATENCY")


def marks_cap() -> int:
    return knobs.get_int("GS_LAT_MARKS")


def pending_cap() -> int:
    return knobs.get_int("GS_LAT_PENDING")


def slo_target_s() -> float:
    return knobs.get_float("GS_SLO_P99_S")


class _Lane:
    """One stream's latency cursors: cumulative admitted (`fed`) and
    finalized (`done`) edge offsets, the bounded admission-mark deque
    joining windows back to admission times, and the e2e reservoir."""

    __slots__ = ("fed", "done", "marks", "e2e", "windows",
                 "evicted_to", "wm_armed", "wm_lag", "wm_held")

    def __init__(self):
        self.fed = 0
        self.done = 0
        # (end_offset, t_admit_start, t_admit_end, replayed)
        self.marks = collections.deque(maxlen=marks_cap())
        self.e2e = collections.deque(maxlen=_RESERVOIR)
        self.windows = 0
        # highest end_offset pushed out of the bounded mark deque: a
        # window at or below it lost its true admission anchor and
        # reports approximate latency instead of growing memory
        self.evicted_to = 0
        # event-time watermark (note_watermark, GS_OOO_BOUND armed):
        # while armed, the lane's age-gauge contribution is the TRUE
        # watermark lag instead of the ingestion-time queue age
        self.wm_armed = False
        self.wm_lag = 0.0
        self.wm_held = 0

    def push_mark(self, mark) -> None:
        if len(self.marks) == self.marks.maxlen:
            self.evicted_to = max(self.evicted_to, self.marks[0][0])
        self.marks.append(mark)


class _Plane:
    """All mutable state behind one lock (rebuilt by reset())."""

    def __init__(self):
        self.lock = threading.RLock()
        self.lanes: Dict[str, _Lane] = {}
        # (lane, ordinal) → record awaiting its delivery stamp; past
        # pending_cap() the OLDEST is emitted as-finalized instead of
        # growing without bound (a pump whose caller never delivers)
        self.pending = collections.OrderedDict()
        self.recent = collections.deque(maxlen=_RECENT)
        self.stage_samples: Dict[str, collections.deque] = {}
        # SLO burn state: sliding (t, bad) results + episode status.
        # slo_bad is a RUNNING counter maintained on append/expiry —
        # the hot path never rescans the deque under the lock.
        self.slo_results = collections.deque(maxlen=4096)
        self.slo_status = "ok"
        self.slo_burn = 0.0
        self.slo_windows = 0
        self.slo_bad = 0

    def lane(self, name: str) -> _Lane:
        """Admit one lane under the registry's cardinality bound —
        the same collapse-don't-grow policy as metrics.tenant_key:
        past GS_METRICS_SERIES, new lanes share one `overflow` row."""
        name = str(name)
        ln = self.lanes.get(name)
        if ln is not None:
            return ln
        if len(self.lanes) >= knobs.get_int("GS_METRICS_SERIES"):
            return self.lanes.setdefault("overflow", _Lane())
        ln = self.lanes[name] = _Lane()
        return ln


_PLANE: Optional[_Plane] = None
_PLANE_LOCK = threading.Lock()


def _plane() -> _Plane:
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = _Plane()
    return _PLANE


def reset() -> None:
    """Test/tool hook: drop all lanes, marks and SLO state."""
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = None


# ----------------------------------------------------------------------
# admission
# ----------------------------------------------------------------------
def admit_ns(t: Optional[float] = None) -> int:
    """The WAL ts-column form of one admission stamp: int64
    nanoseconds of the monotonic clock (CLOCK_MONOTONIC domain —
    comparable across a kill→restart on one boot)."""
    return int((clock() if t is None else t) * 1e9)


def on_admit(lane, n: int, t0: Optional[float] = None,
             t1: Optional[float] = None) -> None:
    """Mark `n` edges accepted into `lane` at its admission boundary:
    `t0` = admission start (request receive / process() entry), `t1` =
    admission end (journaled + enqueued; defaults to now). The
    admission stage of every window completed by this batch is
    t1 - t0; its end-to-end clock starts at t0."""
    if not enabled() or n <= 0:
        return
    p = _plane()
    now = clock()
    t1 = now if t1 is None else t1
    t0 = t1 if t0 is None else t0
    with p.lock:
        ln = p.lane(lane)
        ln.fed += n
        ln.push_mark((ln.fed, t0, t1, False))
        age = _queue_age_locked(ln, now)
    metrics.gauge_set("gs_tenant_queue_age_s", age or 0.0,
                      tenant=str(lane))
    _age_gauge(p, now)


def on_replay(lane, n: int, ts_ns=None) -> None:
    """Re-seed admission marks for `n` journal-replayed edges with
    their ORIGINAL admission stamps (the WAL ts column, ns of the
    monotonic clock) — replayed windows then report their honest,
    larger ingest→deliver latency instead of reset-to-zero. With no
    journaled stamps (a disarmed-at-feed-time run) the replay moment
    stands in."""
    if not enabled() or n <= 0:
        return
    p = _plane()
    now = clock()
    t = now
    if ts_ns is not None and len(ts_ns):
        t = min(float(ts_ns[0]) / 1e9, now)  # cross-boot clamp
    with p.lock:
        ln = p.lane(lane)
        ln.fed += n
        ln.push_mark((ln.fed, t, t, True))
    _age_gauge(p, now)


# ----------------------------------------------------------------------
# stage boundary stamps
# ----------------------------------------------------------------------
def stamps() -> Optional[dict]:
    """A per-dispatch boundary-stamp dict, or None disarmed (every
    stamp() on None is a no-op — the disarmed hot path carries one
    falsy check per stage)."""
    return {} if enabled() else None


def stamp(st: Optional[dict], name: str,
          t: Optional[float] = None) -> None:
    """Record boundary `name` ("start" closes queue-wait, then
    "prep"/"h2d"/"dispatch") at time `t` (now when omitted)."""
    if st is not None:
        st[name] = clock() if t is None else t


# ----------------------------------------------------------------------
# window finalize / delivery
# ----------------------------------------------------------------------
def on_window(lane, edges: int, st: Optional[dict] = None,
              ordinal: Optional[int] = None,
              defer: bool = False) -> Optional[dict]:
    """One window finalized on `lane` covering the lane's next
    `edges` admitted edges. Joins the window to the admission mark of
    its LAST edge, derives the stage waterfall from the boundary
    stamps, and either emits the record now (deliver = finalize — the
    engine/driver delivery shape) or defers it for `delivered()` (the
    serving front-end stamps the sink write). Returns the record."""
    if not enabled() or edges <= 0:
        return None
    p = _plane()
    now = clock()
    with p.lock:
        ln = p.lane(lane)
        lo = ln.done
        ln.done += edges
        mark = _mark_for_locked(ln, ln.done)
        if ln.done <= ln.evicted_to:
            mark = None  # true anchor evicted: report approximate
        if ordinal is None:
            ordinal = ln.windows
        ln.windows += 1
    if mark is None:
        # marks evicted or the plane was armed mid-stream: anchor at
        # the earliest boundary we do have, flagged approximate
        base = min([v for v in (st or {}).values()] + [now])
        t0 = t1 = base
        replayed, approx = False, True
    else:
        _end, t0, t1, replayed = mark
        approx = False
    stages = {"admission": max(0.0, t1 - t0)}
    prev = t1
    for stage_name, key in _BOUNDARIES:
        bt = (st or {}).get(key)
        if bt is None:
            continue
        stages[stage_name] = max(0.0, bt - prev)
        prev = max(prev, bt)
    stages["finalize"] = max(0.0, now - prev)
    rec = {
        "tenant": str(lane), "window": int(ordinal),
        "edges": int(edges), "lo": int(lo),
        "t_admit": t0, "t_done": now,
        "e2e_s": max(0.0, now - t0),
        "stages": stages,
        "replayed": replayed,
    }
    if approx:
        rec["approx"] = True
    if defer:
        evicted = None
        with p.lock:
            p.pending[(str(lane), int(ordinal))] = rec
            if len(p.pending) > pending_cap():
                _key, evicted = p.pending.popitem(last=False)
        if evicted is not None:
            _emit(p, evicted)
    else:
        _emit(p, rec)
    return rec


def delivered(lane, ordinal, t: Optional[float] = None
              ) -> Optional[dict]:
    """Close one deferred window record at its DELIVERY boundary (the
    results-sink write): stamps the `deliver` stage, finalizes e2e,
    and emits. Returns the record (the serving layer copies
    `e2e_s` into the sink row as `latency_s`); None when nothing is
    pending (plane disarmed, or the record was already evicted)."""
    p = _plane()
    with p.lock:
        rec = p.pending.pop((str(lane), int(ordinal)), None)
    if rec is None:
        return None
    t = clock() if t is None else t
    rec["stages"]["deliver"] = max(0.0, t - rec["t_done"])
    rec["t_done"] = t
    rec["e2e_s"] = max(0.0, t - rec["t_admit"])
    _emit(p, rec)
    return rec


def settle(lane=None) -> int:
    """Emit every still-pending record (deliver = finalize) — the
    teardown path of callers that deferred but will never deliver.
    Returns records settled."""
    p = _plane()
    with p.lock:
        keys = [k for k in p.pending
                if lane is None or k[0] == str(lane)]
        recs = [p.pending.pop(k) for k in keys]
    for rec in recs:
        _emit(p, rec)
    return len(recs)


def _mark_for_locked(ln: _Lane, hi: int):
    """The admission mark covering cumulative edge offset `hi` (the
    window's LAST edge), pruning marks wholly below `done` — called
    under the plane lock."""
    found = None
    for mark in ln.marks:
        if mark[0] >= hi:
            found = mark
            break
    while ln.marks and ln.marks[0][0] <= ln.done:
        ln.marks.popleft()
    return found


def _emit(p: _Plane, rec: dict) -> None:
    """Record one finished window: reservoirs, metrics histograms,
    the `latency.window` ledger event, and SLO accounting. Called
    OUTSIDE the plane lock's critical path where possible (metrics
    and telemetry hold their own locks)."""
    e2e = rec["e2e_s"]
    lane = rec["tenant"]
    with p.lock:
        p.lane(lane).e2e.append(e2e)
        for stage_name, dur in rec["stages"].items():
            p.stage_samples.setdefault(
                stage_name,
                collections.deque(maxlen=_RESERVOIR)).append(dur)
        p.recent.append(rec)
    metrics.observe("gs_latency_e2e_seconds", e2e, tenant=lane)
    for stage_name, dur in rec["stages"].items():
        metrics.observe("gs_latency_stage_seconds", dur,
                        stage=stage_name)
    telemetry.event(
        "latency.window", tenant=lane, window=rec["window"],
        edges=rec["edges"], e2e_s=round(e2e, 9),
        stages={k: round(v, 9) for k, v in rec["stages"].items()},
        replayed=rec["replayed"] or None,
        approx=rec.get("approx"))
    _slo_account(p, e2e)


# ----------------------------------------------------------------------
# watermark-lag twin: oldest-unfinalized-edge age
# ----------------------------------------------------------------------
def _queue_age_locked(ln: _Lane, now: float) -> Optional[float]:
    for mark in ln.marks:
        if mark[0] > ln.done:
            return max(0.0, now - mark[2])
    return None


def note_watermark(lane, lag_s: float, held: int = 0) -> None:
    """Event-time groundwork (core/tenancy GS_OOO_BOUND): record one
    lane's TRUE watermark lag — seconds of event time between the
    newest stamp the stream has seen and the oldest edge still held
    in its reorder buffer (`held` edges). While a lane is armed this
    REPOINTS its contribution to `gs_latency_oldest_edge_age_s`:
    event-time streams report how far the watermark trails the
    stream's frontier, not how long an already-released edge has sat
    in the ingest queue."""
    if not enabled():
        return
    p = _plane()
    now = clock()
    lag = max(0.0, float(lag_s))
    with p.lock:
        ln = p.lane(lane)
        ln.wm_armed = True
        ln.wm_lag = lag
        ln.wm_held = int(held)
    metrics.gauge_set("gs_tenant_watermark_lag_s", round(lag, 6),
                      tenant=str(lane))
    _age_gauge(p, now)


def _lane_age_locked(ln: _Lane, now: float) -> Optional[float]:
    """One lane's age-gauge contribution: the event-time watermark
    lag when armed (note_watermark), else the ingestion-time queue
    age. Caller holds the plane lock."""
    if ln.wm_armed:
        return ln.wm_lag
    return _queue_age_locked(ln, now)


def queue_age(lane, now: Optional[float] = None) -> Optional[float]:
    """Age (seconds) of `lane`'s oldest admitted-but-unfinalized
    edge — the ingestion-time watermark-lag twin. None when the lane
    is fully finalized (or the plane is disarmed)."""
    if not enabled():
        return None
    p = _plane()
    now = clock() if now is None else now
    with p.lock:
        ln = p.lanes.get(str(lane))
        return None if ln is None else _queue_age_locked(ln, now)


def oldest_age(now: Optional[float] = None) -> Optional[float]:
    """The worst per-lane age across every lane (the global
    `gs_latency_oldest_edge_age_s` gauge body): watermark-armed
    lanes contribute their TRUE event-time watermark lag
    (note_watermark), the rest their ingestion-time queue age."""
    if not enabled():
        return None
    p = _plane()
    now = clock() if now is None else now
    ages = []
    with p.lock:
        for ln in p.lanes.values():
            age = _lane_age_locked(ln, now)
            if age is not None:
                ages.append(age)
    return max(ages) if ages else None


def _age_gauge(p: _Plane, now: float) -> None:
    if not metrics.enabled():
        return
    age = oldest_age(now)
    metrics.gauge_set("gs_latency_oldest_edge_age_s",
                      0.0 if age is None else age)


# ----------------------------------------------------------------------
# SLO burn rate
# ----------------------------------------------------------------------
def _slo_account(p: _Plane, e2e: float) -> None:
    target = slo_target_s()
    if target <= 0:
        return
    budget = knobs.get_float("GS_SLO_BUDGET")
    window = knobs.get_float("GS_SLO_WINDOW_S")
    threshold = knobs.get_float("GS_SLO_BURN")
    now = clock()
    bad = e2e > target
    flipped = None
    with p.lock:
        if len(p.slo_results) == p.slo_results.maxlen:
            # maxlen eviction would silently skew the running count
            if p.slo_results.popleft()[1]:
                p.slo_bad -= 1
        p.slo_results.append((now, bad))
        if bad:
            p.slo_bad += 1
        while p.slo_results and p.slo_results[0][0] < now - window:
            if p.slo_results.popleft()[1]:
                p.slo_bad -= 1
        total = len(p.slo_results)
        nbad = p.slo_bad
        burn = (nbad / total) / budget if total else 0.0
        p.slo_burn = burn
        p.slo_windows = total
        if p.slo_status == "ok" and burn >= threshold \
                and total >= _SLO_MIN_WINDOWS:
            p.slo_status = "degraded"
            flipped = ("slo_burn", burn, nbad, total)
        elif p.slo_status == "degraded" and burn < threshold:
            p.slo_status = "ok"
            flipped = ("slo_recovered", burn, nbad, total)
    metrics.counter_inc("gs_slo_windows_total")
    if bad:
        metrics.counter_inc("gs_slo_bad_windows_total")
    metrics.gauge_set("gs_slo_burn_rate", round(burn, 4))
    if flipped is not None:
        name, burn, nbad, total = flipped
        # durable: an SLO episode is exactly the post-mortem evidence
        # class the run ledger exists for
        telemetry.event(name, durable=True, burn_rate=round(burn, 4),
                        bad=nbad, windows=total, target_p99_s=target,
                        budget=budget)
        if name == "slo_burn":
            metrics.counter_inc("gs_slo_burn_episodes_total")


# ----------------------------------------------------------------------
# snapshots (/healthz `latency` section, bench fields, tools)
# ----------------------------------------------------------------------
def health_section(now: Optional[float] = None) -> dict:
    """The `/healthz` `latency` section (registered below with
    metrics.register_health_section): SLO status + burn, the oldest
    unfinalized-edge age, per-lane e2e percentiles and queue age, and
    per-stage percentiles. `{"enabled": False}` disarmed."""
    if not enabled():
        return {"enabled": False}
    p = _plane()
    now = clock() if now is None else now
    target = slo_target_s()
    with p.lock:
        sec = {
            "enabled": True,
            "status": p.slo_status if target > 0 else "ok",
            "oldest_unfinalized_age_s": None,
            "slo": None if target <= 0 else {
                "target_p99_s": target,
                "budget": knobs.get_float("GS_SLO_BUDGET"),
                "window_s": knobs.get_float("GS_SLO_WINDOW_S"),
                "burn_threshold": knobs.get_float("GS_SLO_BURN"),
                "burn_rate": round(p.slo_burn, 4),
                "windows": p.slo_windows,
                "bad": p.slo_bad,
            },
            "tenants": {},
            "stages": {},
        }
        for name, ln in p.lanes.items():
            pct = telemetry.percentiles(ln.e2e)
            row = {
                "windows": ln.windows,
                "unfinalized_edges": ln.fed - ln.done,
                "queue_age_s": _round_opt(
                    _queue_age_locked(ln, now)),
                "e2e_p50_s": round(pct[50], 6),
                "e2e_p95_s": round(pct[95], 6),
                "e2e_p99_s": round(pct[99], 6),
            }
            if ln.wm_armed:
                # event-time lane (note_watermark): expose the true
                # watermark lag + held reorder-buffer depth alongside
                # the ingestion-time queue age
                row["watermark_lag_s"] = _round_opt(ln.wm_lag)
                row["watermark_held"] = ln.wm_held
            sec["tenants"][name] = row
        for stage_name, samples in p.stage_samples.items():
            pct = telemetry.percentiles(samples)
            sec["stages"][stage_name] = {
                "p50_s": round(pct[50], 6),
                "p99_s": round(pct[99], 6),
            }
    age = oldest_age(now)
    sec["oldest_unfinalized_age_s"] = _round_opt(age)
    return sec


def _round_opt(v, nd: int = 6):
    return None if v is None else round(v, nd)


def percentile_fields(prefix: str = "e2e") -> dict:
    """Pooled e2e percentiles as flat `<prefix>_p{50,95,99}_s`
    fields — the shape bench rows emit and tools/bench_compare.py
    compares (lower is better). Empty dict when nothing recorded."""
    p = _plane()
    with p.lock:
        pool: List[float] = []
        for ln in p.lanes.values():
            pool.extend(ln.e2e)
    if not pool:
        return {}
    pct = telemetry.percentiles(pool)
    return {"%s_p%d_s" % (prefix, q): round(pct[q], 6)
            for q in (50, 95, 99)}


def recent() -> List[dict]:
    """Snapshot of the emitted-record ring (tools, tests)."""
    p = _plane()
    with p.lock:
        return [dict(r, stages=dict(r["stages"])) for r in p.recent]


# conservation contract shared by every checker: |sum(stages) − e2e|
# must stay within `tolerance` of the end-to-end, with an absolute
# floor for µs-scale windows. tools/latency_report.py inlines the
# same formula on purpose (it is ledger-only and must not import the
# package/jax) — keep the two in lockstep.
RECONCILE_TOLERANCE = 0.05
RECONCILE_FLOOR_S = 50e-6


def reconcile(rec: dict, tolerance: float = RECONCILE_TOLERANCE):
    """(ok, gap_seconds) of one window record against the
    conservation contract — the ONE formula the chaos leg, the
    profiler's committed section, and the tests all share."""
    e2e = float(rec["e2e_s"])
    gap = abs(sum(float(v) for v in rec["stages"].values()) - e2e)
    return gap <= max(tolerance * e2e, RECONCILE_FLOOR_S), gap


# the /healthz `latency` section rides the existing provider hook —
# registered at import so every armed run serves it with no new wiring
metrics.register_health_section("latency", health_section)

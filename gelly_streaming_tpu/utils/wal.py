"""Write-ahead edge journal — the durable, replayable source the
reference gets for free from Flink's replayable sources (PAPER.md
§L1) and our live serving path never had.

Checkpoints (ISSUE 2/6/11) make carried STATE recoverable, but every
edge fed through `TenantCohort.feed()`, `SummaryEngineBase.process()`
or the driver's live `run_arrays()` since the last window-boundary
checkpoint simply vanished on a crash — "kill→resume" was exact only
for file-backed drains, never for live traffic. This module closes
that gap: edges are appended here BEFORE they enter any queue, each
checkpoint records the journal offset at its finalized-window
boundary (`wal_offset` = edges folded into the carry), and recovery
replays exactly the un-checkpointed suffix — so the recovered window
digests are bit-identical to the fault-free run under a kill at ANY
point (tools/chaos_run.py serve leg; tests/test_checkpoint_roundtrip).

Format — segment files `wal_<NNNNNNNN>.seg` under one directory, each
starting with an 8-byte magic, then records back to back:

    [u32 crc32(payload)] [u32 payload_len] [payload]

    payload: u8  kind        (1 = edges, 2 = seal)
             u16 tenant_len, tenant utf-8 bytes
             u64 seq         (per-tenant record ordinal, 1-based)
             u64 start       (per-tenant cumulative edge offset of
                              the record's first edge)
             u32 n           (edge count)
             u8  itemsize    (4 = int32 ids, 8 = int64 ids)
             u8  has_ts
             n×id src, n×id dst, [n×i64 ts]

Records never split across segments; rotation happens between
appends once a segment passes GS_WAL_SEGMENT_BYTES. Durability is
fsync-batched: GS_WAL_FSYNC_S=0 (the default) fsyncs every append,
>0 batches fsyncs to at most one per interval (the power-loss window
widens to the interval; the OS-crash window stays one flush). Fsync
latency lands in the `gs_wal_fsync_seconds` histogram.

The reader reuses the telemetry-ledger damage discipline: a torn
TAIL — a partial/CRC-failing record at the end of the LAST segment
(the only place an in-flight crash can tear) — is tolerated by
falling back one record, with a durable `wal_torn_tail` event; the
same damage anywhere ELSE (or a per-tenant sequence gap) raises
typed `WalCorrupt`, because silent mid-journal loss would replay a
stream with a hole in it.

`GS_WAL=0` is the kill switch: `enabled()` is False and every
`enable_wal()` call site degrades to a no-op — the disarmed hot path
is bit-identical to a journal-less build.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from . import knobs
from . import metrics
from . import telemetry

_MAGIC = b"GSWALSG1"
_HEAD = struct.Struct("<II")          # crc32, payload_len
_SEG_FMT = "wal_%08d.seg"

KIND_EDGES = 1
KIND_SEAL = 2


def enabled() -> bool:
    """GS_WAL=0 is the kill switch: every enable_wal() site no-ops
    and the ingest paths stay bit-identical to a journal-less run."""
    return knobs.get_bool("GS_WAL")


class RetentionCursor:
    """Checkpoint-flush-driven journal retention (GS_WAL_RETAIN).

    Each flush site (engine/driver auto-checkpoint,
    TenantCohort.checkpoint_all) reports the per-tenant replay offset
    its just-SAVED checkpoint covers; the cursor remembers the last
    TWO reported offsets per tenant and truncates the journal at the
    OLDER one. Two, because utils/checkpoint.save keeps two
    generations (current + `.prev`) and load_latest falls back one on
    corruption — a recovery landing on `.prev` must still find its
    whole replay suffix, so the deletable prefix is only what even
    the previous generation no longer needs. A tenant's FIRST flush
    truncates nothing (floor 0): with only one generation on disk
    there is no `.prev` to fall back to, and a damaged sole
    checkpoint means recovery starts fresh and replays from offset
    0 — which must still be possible. Disarmed (the GS_WAL_RETAIN
    default) every call is a no-op, live per call so tests and
    operators can flip it mid-process."""

    def __init__(self):
        self._hist: Dict[str, List[int]] = {}

    def flushed_many(self, wal: Optional["WriteAheadLog"],
                     offsets: Dict[str, int]) -> int:
        """Record one flush boundary covering `offsets` (per-tenant
        cumulative edges) and truncate; returns segments removed. The
        offsets map must name EVERY tenant the flush covers — the
        cohort passes all tenants at once, because truncate_covered
        treats an unnamed tenant's records as offset 0 (uncovered)."""
        if wal is None or not knobs.get_bool("GS_WAL_RETAIN"):
            return 0
        floors: Dict[str, int] = {}
        for tid, off in offsets.items():
            h = self._hist.setdefault(str(tid), [])
            h.append(int(off))
            del h[:-2]
            # older of the last TWO flushes; a single-entry history
            # floors at 0 — see the class docstring
            floors[str(tid)] = h[0] if len(h) == 2 else 0
        return wal.truncate_covered(floors)

    def flushed(self, wal: Optional["WriteAheadLog"], tenant: str,
                offset: int) -> int:
        """Single-tenant form (engine/driver journals)."""
        return self.flushed_many(wal, {str(tenant): int(offset)})


def fsync_interval_s() -> float:
    """GS_WAL_FSYNC_S: 0 (default) fsyncs every append; >0 batches
    fsyncs to at most one per interval."""
    return knobs.get_float("GS_WAL_FSYNC_S")


def segment_bytes() -> int:
    """GS_WAL_SEGMENT_BYTES: rotate to a fresh segment file once the
    current one passes this size (records never split)."""
    return knobs.get_int("GS_WAL_SEGMENT_BYTES")


class WalCorrupt(RuntimeError):
    """Journal damage outside the torn-tail window: a CRC failure or
    truncation NOT at the end of the last segment, or a per-tenant
    sequence gap. `path` names the damaged segment."""

    def __init__(self, path: str, problem: str):
        super().__init__("WAL segment %r is corrupt: %s"
                         % (path, problem))
        self.path = path


def _encode(kind: int, tenant: str, seq: int, start: int,
            src: np.ndarray, dst: np.ndarray,
            ts: Optional[np.ndarray]) -> bytes:
    tb = tenant.encode()
    itemsize = src.dtype.itemsize if len(src) else 4
    head = struct.pack(
        "<BH%dsQQIBB" % len(tb), kind, len(tb), tb, seq, start,
        len(src), itemsize, 0 if ts is None else 1)
    parts = [head, src.tobytes(), dst.tobytes()]
    if ts is not None:
        parts.append(np.asarray(ts, np.int64).tobytes())
    payload = b"".join(parts)
    return _HEAD.pack(zlib.crc32(payload), len(payload)) + payload


def _decode(payload: bytes) -> dict:
    kind, tlen = struct.unpack_from("<BH", payload, 0)
    off = 3
    tenant = payload[off:off + tlen].decode()
    off += tlen
    seq, start, n, itemsize, has_ts = struct.unpack_from(
        "<QQIBB", payload, off)
    off += 22
    dt = np.int32 if itemsize == 4 else np.int64
    src = np.frombuffer(payload, dt, n, off)
    off += n * itemsize
    dst = np.frombuffer(payload, dt, n, off)
    off += n * itemsize
    ts = None
    if has_ts:
        ts = np.frombuffer(payload, np.int64, n, off)
    return {"kind": kind, "tenant": tenant, "seq": seq,
            "start": start, "src": src, "dst": dst, "ts": ts}


def _segments(directory: str) -> List[str]:
    try:
        names = sorted(f for f in os.listdir(directory)
                       if f.startswith("wal_") and f.endswith(".seg"))
    except FileNotFoundError:
        return []
    return [os.path.join(directory, f) for f in names]


def _iter_segment(path: str, is_last: bool) -> Iterator[dict]:
    """Records of one segment. Damage at the TAIL of the last segment
    yields a final {"torn": ...} marker instead of records; damage
    anywhere else raises WalCorrupt."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < len(_MAGIC) or not data.startswith(_MAGIC):
        if is_last and len(data) < len(_MAGIC) \
                and _MAGIC.startswith(data):
            # segment created, header write torn by the crash
            yield {"torn": "segment header",
                   "dropped_bytes": len(data), "valid_bytes": 0}
            return
        raise WalCorrupt(path, "bad segment magic")
    off = len(_MAGIC)
    while off < len(data):
        tail = len(data) - off
        torn = None
        if tail < _HEAD.size:
            torn = "partial record header (%d bytes)" % tail
        else:
            crc, length = _HEAD.unpack_from(data, off)
            if tail - _HEAD.size < length:
                torn = ("record body truncated (%d of %d bytes)"
                        % (tail - _HEAD.size, length))
            else:
                payload = data[off + _HEAD.size:
                               off + _HEAD.size + length]
                if zlib.crc32(payload) != crc:
                    torn = "record CRC mismatch"
        if torn is not None:
            if not is_last:
                raise WalCorrupt(path, torn + " mid-journal")
            yield {"torn": torn, "dropped_bytes": tail,
                   "valid_bytes": off}
            return
        yield _decode(payload)
        off += _HEAD.size + length


def _scan_records(directory: str) -> Iterator[dict]:
    """Every record of the journal in append order, with seq-gap
    checking per tenant; a torn tail (last segment only) stamps the
    durable `wal_torn_tail` event once and stops."""
    segs = _segments(directory)
    seqs: Dict[str, int] = {}
    for i, path in enumerate(segs):
        for rec in _iter_segment(path, is_last=(i == len(segs) - 1)):
            if "torn" in rec:
                telemetry.event("wal_torn_tail", durable=True,
                                segment=os.path.basename(path),
                                problem=rec["torn"],
                                dropped_bytes=rec["dropped_bytes"])
                metrics.counter_inc("gs_wal_torn_tail_total")
                rec["segment"] = path
                yield rec
                return
            if rec["kind"] == KIND_EDGES:
                prev = seqs.get(rec["tenant"])
                if prev is not None and rec["seq"] != prev + 1:
                    raise WalCorrupt(
                        path, "tenant %r sequence gap (%d after %d)"
                        % (rec["tenant"], rec["seq"], prev))
                seqs[rec["tenant"]] = rec["seq"]
            yield rec


def scan(directory: str) -> dict:
    """Journal summary without materializing edge data: per-tenant
    end offsets (cumulative edges) and record seqs, record/segment
    counts, and whether a seal record closes the journal."""
    offsets: Dict[str, int] = {}
    seqs: Dict[str, int] = {}
    records = 0
    sealed = False
    torn = None
    for rec in _scan_records(directory):
        if "torn" in rec:
            torn = {"segment": rec["segment"],
                    "problem": rec["torn"],
                    "dropped_bytes": rec["dropped_bytes"],
                    "valid_bytes": rec["valid_bytes"]}
            break
        if rec["kind"] == KIND_SEAL:
            sealed = True
            continue
        sealed = False  # edges after a seal re-open the stream
        records += 1
        offsets[rec["tenant"]] = rec["start"] + len(rec["src"])
        seqs[rec["tenant"]] = rec["seq"]
    return {"offsets": offsets, "seqs": seqs, "records": records,
            "segments": len(_segments(directory)), "sealed": sealed,
            "torn": torn}


def replay(directory: str,
           offsets: Optional[Dict[str, int]] = None
           ) -> Iterator[Tuple[str, int, np.ndarray, np.ndarray,
                               Optional[np.ndarray]]]:
    """Yield `(tenant, start, src, dst, ts)` for every journaled edge
    past each tenant's `offsets` entry (cumulative edges; missing
    tenant = 0 = everything). A record straddling its tenant's offset
    is trimmed, so the replayed suffix begins EXACTLY at the
    checkpointed boundary."""
    offsets = offsets or {}
    for rec in _scan_records(directory):
        if "torn" in rec or rec["kind"] != KIND_EDGES:
            continue
        off = int(offsets.get(rec["tenant"], 0))
        start, n = rec["start"], len(rec["src"])
        if start + n <= off:
            continue
        cut = max(0, off - start)
        yield (rec["tenant"], start + cut, rec["src"][cut:],
               rec["dst"][cut:],
               None if rec["ts"] is None else rec["ts"][cut:])


class WriteAheadLog:
    """Appender over one journal directory. Reopening an existing
    directory recovers the per-tenant offsets/seqs from a tolerant
    scan and continues in a FRESH segment — a torn tail is never
    appended after (the damaged bytes stay quarantined in their own
    segment, and replay drops exactly that one record)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        info = scan(directory)
        if info["torn"] is not None:
            # quarantine the torn bytes PHYSICALLY: once a fresh
            # segment follows this one, a leftover damaged tail would
            # read as mid-journal corruption (WalCorrupt) instead of
            # the tolerated one-record fallback. The record was never
            # acknowledged durable, so cutting it is exact.
            torn = info["torn"]
            if torn["valid_bytes"] < len(_MAGIC):
                os.unlink(torn["segment"])
            else:
                with open(torn["segment"], "r+b") as f:
                    f.truncate(torn["valid_bytes"])
        self._offsets: Dict[str, int] = dict(info["offsets"])
        self._seqs: Dict[str, int] = dict(info["seqs"])
        segs = _segments(directory)
        # next segment index must come from the highest EXISTING
        # name, not the count: truncate_covered() deletes prefix
        # segments, and a count-derived index would re-open a live
        # segment and write a second magic header mid-file
        self._seg_no = (max(int(os.path.basename(p)[4:-4])
                            for p in segs) + 1) if segs else 0
        self._file = None
        self._file_bytes = 0
        self._last_fsync = 0.0
        self._pending_sync = False
        self.sealed = False

    # -- segment management -------------------------------------------
    def _ensure_segment(self):
        if self._file is not None \
                and self._file_bytes >= segment_bytes():
            self._rotate()
        if self._file is None:
            path = os.path.join(self.dir, _SEG_FMT % self._seg_no)
            self._seg_no += 1
            self._file = open(path, "ab")
            self._file.write(_MAGIC)
            self._file.flush()
            self._file_bytes = len(_MAGIC)
            metrics.gauge_set("gs_wal_segments",
                              len(_segments(self.dir)))
        return self._file

    def _rotate(self) -> None:
        self._fsync(force=True)
        self._file.close()
        self._file = None
        self._file_bytes = 0

    def _fsync(self, force: bool = False) -> None:
        if self._file is None or not self._pending_sync:
            return
        now = time.monotonic()
        interval = fsync_interval_s()
        if not force and interval > 0 \
                and now - self._last_fsync < interval:
            return
        t0 = time.perf_counter()
        os.fsync(self._file.fileno())
        metrics.observe("gs_wal_fsync_seconds",
                        time.perf_counter() - t0)
        self._last_fsync = now
        self._pending_sync = False

    # -- the append path ----------------------------------------------
    def append(self, tenant: str, src, dst,
               ts=None) -> Tuple[int, int]:
        """Journal one batch of edges for `tenant` BEFORE they enter
        any queue. Returns `(start, end)` — the batch's cumulative
        per-tenant edge offsets; `end` is the offset a checkpoint
        taken after these edges fold would record."""
        src = np.ascontiguousarray(src)
        dst = np.ascontiguousarray(dst)
        if len(src) != len(dst):
            raise ValueError("src/dst length mismatch")
        if src.dtype != dst.dtype or src.dtype.kind != "i" \
                or src.dtype.itemsize not in (4, 8):
            # one itemsize is framed for BOTH id arrays: mismatched
            # or exotic dtypes would serialize fine and replay
            # garbage (a CRC-valid record with wrong data defeats
            # the journal) — canonicalize to int64 instead
            src = src.astype(np.int64)
            dst = dst.astype(np.int64)
        with self._lock:
            if self.sealed:
                raise ValueError(
                    "journal %r is sealed (drained); open a fresh "
                    "WriteAheadLog to accept a new stream" % self.dir)
            f = self._ensure_segment()
            tenant = str(tenant)
            start = self._offsets.get(tenant, 0)
            seq = self._seqs.get(tenant, 0) + 1
            rec = _encode(KIND_EDGES, tenant, seq, start, src, dst,
                          None if ts is None
                          else np.asarray(ts, np.int64))
            f.write(rec)
            f.flush()
            self._pending_sync = True
            self._fsync()
            self._file_bytes += len(rec)
            self._offsets[tenant] = start + len(src)
            self._seqs[tenant] = seq
            metrics.counter_inc("gs_wal_records_total")
            metrics.counter_inc("gs_wal_bytes_total", len(rec))
            return start, start + len(src)

    def sync(self) -> None:
        """Force the batched fsync now (the drain path; also what a
        caller with its own durability boundary uses)."""
        with self._lock:
            self._fsync(force=True)

    def offsets(self) -> Dict[str, int]:
        """Per-tenant cumulative edges journaled so far."""
        with self._lock:
            return dict(self._offsets)

    def seal(self) -> None:
        """Close the journal durably: append the seal record, fsync,
        close — the graceful-drain marker (`wal_sealed` durable
        event). A sealed journal refuses further appends."""
        with self._lock:
            if self.sealed:
                return
            f = self._ensure_segment()
            f.write(_encode(KIND_SEAL, "", 0, 0,
                            np.zeros(0, np.int32),
                            np.zeros(0, np.int32), None))
            f.flush()
            self._pending_sync = True
            self._fsync(force=True)
            self._file.close()
            self._file = None
            self.sealed = True
        telemetry.event("wal_sealed", durable=True, dir=self.dir,
                        tenants=len(self._offsets),
                        edges=sum(self._offsets.values()))

    def close(self) -> None:
        """Close without sealing (the journal stays open for a
        successor process — a crash looks exactly like this plus a
        possibly-torn tail)."""
        with self._lock:
            if self._file is not None:
                self._fsync(force=True)
                self._file.close()
                self._file = None

    # -- retention -----------------------------------------------------
    def truncate_covered(self, offsets: Dict[str, int]) -> int:
        """Delete CLOSED segments every record of which is covered by
        `offsets` (per-tenant cumulative edges a flushed checkpoint
        recorded) — bounded-disk retention that can never delete an
        un-checkpointed edge. Returns segments removed."""
        removed = 0
        with self._lock:
            open_path = (self._file.name
                         if self._file is not None else None)
            for path in _segments(self.dir):
                if path == open_path:
                    continue
                covered = True
                try:
                    for rec in _iter_segment(path, is_last=False):
                        if rec["kind"] != KIND_EDGES:
                            continue
                        end = rec["start"] + len(rec["src"])
                        if end > int(offsets.get(rec["tenant"], 0)):
                            covered = False
                            break
                except WalCorrupt:
                    covered = False  # keep damage for the post-mortem
                if not covered:
                    # segments are append-ordered: the first
                    # uncovered one bounds the deletable prefix
                    break
                os.unlink(path)
                removed += 1
        if removed:
            metrics.gauge_set("gs_wal_segments",
                              len(_segments(self.dir)))
        return removed

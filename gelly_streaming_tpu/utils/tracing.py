"""Tracing / profiling.

The reference has none (SURVEY.md §5.1 — only wall-clock via
getNetRuntime, CentralizedWeightedMatching.java:62-64). Here:

- `StepTimer` — per-operator / per-window wall-time and record counts,
  collected by the runtime when `env.enable_tracing()` is on.
- `device_trace` — context manager around `jax.profiler.trace` for a
  TensorBoard-readable XLA trace of the device kernels.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List


class StepTimer:
    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.records: Dict[str, int] = defaultdict(int)
        self.events: List[dict] = []  # discrete happenings (demotions)

    def event(self, name: str, info: dict = None) -> None:
        """Record a discrete runtime event (e.g. a tier demotion) into
        the trace: not a timing, a happening — surfaced by
        `event_log()` beside `report()` so a degraded run's trace says
        so explicitly."""
        self.events.append({"event": name, **(info or {})})

    def event_log(self) -> List[dict]:
        return list(self.events)

    def add(self, name: str, seconds: float, num_records: int = 0) -> None:
        """Record one already-measured step (used by the runtime's
        exclusive-time accounting)."""
        self.totals[name] += seconds
        self.counts[name] += 1
        self.records[name] += num_records

    @contextlib.contextmanager
    def step(self, name: str, num_records: int = 0):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, num_records)

    def report(self) -> List[dict]:
        out = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            total = self.totals[name]
            recs = self.records[name]
            out.append({
                "op": name,
                "total_s": round(total, 6),
                "calls": self.counts[name],
                "records": recs,
                "records_per_s": round(recs / total) if total and recs else 0,
            })
        return out

    def __str__(self) -> str:
        lines = ["op                            total_s    calls  records  rec/s"]
        for row in self.report():
            lines.append(
                f"{row['op']:<28} {row['total_s']:>9.4f} {row['calls']:>7}"
                f" {row['records']:>8} {row['records_per_s']:>7}"
            )
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """XLA device trace (view in TensorBoard / xprof)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

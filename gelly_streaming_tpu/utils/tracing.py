"""Tracing / profiling.

The reference has none (SURVEY.md §5.1 — only wall-clock via
getNetRuntime, CentralizedWeightedMatching.java:62-64). Here:

- `StepTimer` — per-operator / per-window wall-time and record counts,
  collected by the runtime when `env.enable_tracing()` is on. Since
  the flight recorder landed (utils/telemetry) StepTimer is a thin
  adapter over it: `step()` measures through a telemetry span (so an
  armed recorder sees every step as a `step.<name>` span with the
  run's trace ID), while `report()`/`event_log()` and their
  accumulation semantics are unchanged for existing call sites.
- `device_trace` — context manager around `jax.profiler.trace` for a
  TensorBoard-readable XLA trace of the device kernels. Graceful by
  contract: the log directory is created, a backend that cannot trace
  (or a nested trace — jax allows one at a time) degrades to a no-op
  with a telemetry event instead of taking down the stream it was
  asked to observe, and a completed capture stamps a durable
  `device_trace_captured` event carrying the log dir plus the cost
  observatory's program inventory (utils/costmodel), so an on-chip
  xprof capture is joinable with the cost registry it profiled.
"""

from __future__ import annotations

import contextlib
import os
import threading
from collections import defaultdict
from typing import Dict, List

from . import telemetry


class StepTimer:
    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.records: Dict[str, int] = defaultdict(int)
        self.events: List[dict] = []  # discrete happenings (demotions)

    def event(self, name: str, info: dict = None) -> None:
        """Record a discrete runtime event (e.g. a tier demotion) into
        the trace: not a timing, a happening — surfaced by
        `event_log()` beside `report()` so a degraded run's trace says
        so explicitly."""
        self.events.append({"event": name, **(info or {})})

    def event_log(self) -> List[dict]:
        return list(self.events)

    def add(self, name: str, seconds: float, num_records: int = 0) -> None:
        """Record one already-measured step (used by the runtime's
        exclusive-time accounting)."""
        self.totals[name] += seconds
        self.counts[name] += 1
        self.records[name] += num_records

    @contextlib.contextmanager
    def step(self, name: str, num_records: int = 0):
        # the telemetry span IS the stopwatch (identical perf_counter
        # measurement armed or not); the local accumulation keeps
        # report() byte-compatible for existing consumers. Yields the
        # span so dispatch-owning steps can attach attributes before
        # it records (the driver stamps program/sig cost tags).
        sp = telemetry.span("step." + name, records=num_records)
        try:
            with sp:
                yield sp
        finally:
            self.add(name, sp.elapsed, num_records)

    def report(self) -> List[dict]:
        out = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            total = self.totals[name]
            recs = self.records[name]
            out.append({
                "op": name,
                "total_s": round(total, 6),
                "calls": self.counts[name],
                "records": recs,
                "records_per_s": round(recs / total) if total and recs else 0,
            })
        return out

    def __str__(self) -> str:
        lines = ["op                            total_s    calls  records  rec/s"]
        for row in self.report():
            lines.append(
                f"{row['op']:<28} {row['total_s']:>9.4f} {row['calls']:>7}"
                f" {row['records']:>8} {row['records_per_s']:>7}"
            )
        return "\n".join(lines)


# device_trace nesting guard: jax.profiler allows ONE trace at a time;
# a nested device_trace degrades to a no-op instead of raising inside
# the stream it observes. Depth is written under the lock only.
_TRACE_LOCK = threading.Lock()
_TRACE_DEPTH = 0


@contextlib.contextmanager
def device_trace(log_dir: str):
    """XLA device trace (view in TensorBoard / xprof). Graceful: the
    log dir is created, an untraceable backend (or a failed profiler
    start) yields a no-op with a `device_trace_failed` telemetry
    event, nested captures no-op under the outermost one, and a
    completed capture stamps a durable `device_trace_captured` event
    with the log dir + the cost observatory's captured-program count
    — the on-chip feed that makes an xprof capture joinable with the
    cost registry (utils/costmodel) it profiled."""
    global _TRACE_DEPTH
    import jax

    os.makedirs(log_dir, exist_ok=True)
    started = False
    with _TRACE_LOCK:
        if _TRACE_DEPTH == 0:
            try:
                jax.profiler.start_trace(log_dir)
                started = True
            except Exception as e:
                telemetry.event(
                    "device_trace_failed", log_dir=str(log_dir),
                    error="%s: %s" % (type(e).__name__, e))
        _TRACE_DEPTH += 1
    try:
        yield
    finally:
        with _TRACE_LOCK:
            _TRACE_DEPTH -= 1
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    telemetry.event(
                        "device_trace_failed", log_dir=str(log_dir),
                        error="stop: %s: %s" % (type(e).__name__, e))
                else:
                    from . import costmodel

                    telemetry.event(
                        "device_trace_captured", durable=True,
                        log_dir=str(log_dir),
                        programs=len(costmodel.programs()))

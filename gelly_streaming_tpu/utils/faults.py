"""Deterministic fault injection.

The resilient runtime (ops/ingress_pipeline stage guards, the driver's
tier demotion, utils/checkpoint rotation) is only trustworthy if its
failure paths are EXERCISED deterministically — the reference leans on
Flink's restart strategies and never tests them in-repo; the round-5
queue log ("tunnel never answered") shows the real failure mode is a
hang, which no exception-based mock reproduces. This module is a
process-global, context-manager-scoped fault plan that the runtime's
hook points consult:

    with faults.inject(
            faults.FaultSpec(site="prep", on_call=3),          # raise
            faults.FaultSpec(site="h2d", on_call=2,
                             action="hang", seconds=5.0),      # stall
            faults.FaultSpec(site="ckpt_save",
                             action="truncate_file")):         # damage
        engine.process(src, dst)

Sites are plain strings fired by the runtime (`fire(site)`); the
active plan counts calls per site and triggers each spec on its
1-based `on_call`-th firing, `times` times. No randomness anywhere —
the same plan against the same stream injects the same faults, which
is what lets tools/chaos_run.py assert fault-run counts equal the
fault-free run bit-for-bit.

Hooked sites (all no-ops when no plan is active — the hooks are one
dict lookup on the hot path):

    prep          ops/ingress_pipeline._timed_prep (worker side)
    h2d           ops/ingress_pipeline._prep_then_h2d (worker side)
    dispatch      ops/ingress_pipeline.run_pipeline + the driver's
                  snapshot-scan dispatch
    finalize      ops/ingress_pipeline.run_pipeline + the driver's
                  snapshot materialize
    ckpt_save     utils/checkpoint.save (fires AFTER the atomic
                  replace, payload=final path — truncate_file here
                  models external damage to a completed checkpoint)
    ckpt_restore  utils/checkpoint.restore (before the load)
    parse         io/sources edge-chunk parse (payload=bytes;
                  corrupt_bytes garbles one line)
    admit         every admission boundary — TenantCohort.feed,
                  SummaryEngineBase.process, driver.run_arrays —
                  BEFORE the sanitizer (utils/sanitize) and the
                  journal see the batch; payload=(tenant, src, dst),
                  so a `call` spec can poison the parsed arrays the
                  way corrupt_bytes tears file bytes (chaos targets
                  the sanitizer through exactly this hook)
    wal_enqueue   between the journal append and the queue/fold (the
                  kill window the WAL contract pins)

Mesh-scoped sites (fired only by the sharded engines and the driver's
mesh path — parallel/sharded.py; a single-chip run never fires them,
which is what lets a demoted stream keep running through a plan that
keeps killing the mesh):

    shard_dispatch  every sharded shard_map dispatch (the SPMD program
                    covering ALL shards — a dead chip fails the whole
                    dispatch, so `raise` here with FaultSpec.shard=k
                    models shard k dying: the InjectedFault carries
                    the shard id for the demotion record; `hang`
                    models an ICI stall the GS_STAGE_TIMEOUT_S
                    watchdog must cut)
    shard_gather    the d2h gather of replicated sharded outputs /
                    engine state slabs
    shard_wire      the mesh h2d wire; payload=(arrays, n_shards).
                    corrupt_shard garbles FaultSpec.shard's slice of
                    each array's edge axis — GS_MESH_WIRE_CHECK=1
                    (utils/resilience.mesh_wire_check_enabled) is the
                    guard that must catch it before dispatch.

Actions:
    raise          raise InjectedFault (or `exc` if given). fatal=True
                   marks the fault non-retryable: the stage guards
                   re-raise it immediately instead of burning retries
                   — the deterministic "kill" for crash/resume drills.
    hang           time.sleep(seconds) inside the stage — the watchdog
                   deadline (GS_STAGE_TIMEOUT_S) is what must cut it.
    truncate_file  payload is a path: cut the file to half its bytes.
    corrupt_bytes  payload is bytes: garble the first line-break-free
                   span (models a torn/overwritten edge line).
    corrupt_shard  payload is (arrays, n_shards): poison shard
                   `spec.shard`'s contiguous slice of each array's
                   trailing (edge) axis with out-of-range vertex ids —
                   a torn/garbled ICI wire that MUST be caught by the
                   wire check, never silently folded.
    call           invoke `fn(payload)` and return its result — the
                   escape hatch for bespoke corruption.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Callable, List, Optional

from . import telemetry


class InjectedFault(RuntimeError):
    """A fault raised by the active plan. `site` names the hook that
    fired; `fatal` marks it exempt from stage-guard retries (the
    simulated hard kill); `shard` (mesh-scoped sites) names the shard
    the fault implicates — the driver's demotion record carries it
    into the `degradations` evidence as `shard_id`."""

    def __init__(self, message: str, site: str, fatal: bool = False,
                 shard: Optional[int] = None):
        super().__init__(message)
        self.site = site
        self.fatal = fatal
        self.shard = shard


@dataclasses.dataclass
class FaultSpec:
    """One planned fault: fire at the `on_call`-th firing of `site`
    (1-based, counted per plan), `times` consecutive firings."""

    site: str
    on_call: int = 1
    times: int = 1
    action: str = "raise"
    seconds: float = 0.0          # hang duration
    exc: Optional[type] = None    # raise: exception class to use
    fatal: bool = False           # raise: exempt from guard retries
    fn: Optional[Callable] = None  # call: bespoke payload transform
    shard: Optional[int] = None   # mesh sites: implicated shard id

    def _matches(self, call_no: int) -> bool:
        return self.on_call <= call_no < self.on_call + self.times


class FaultPlan:
    """An ordered set of FaultSpecs plus per-site call counters.
    Thread-safe: stages fire from pool workers and watchdog threads."""

    def __init__(self, specs):
        self.specs: List[FaultSpec] = list(specs)
        self.calls = {}   # site -> firings so far
        self.fired = []   # (site, call_no, action) log, for assertions
        self._lock = threading.Lock()

    def fire(self, site: str, payload=None):
        with self._lock:
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            hits = [s for s in self.specs
                    if s.site == site and s._matches(n)]
            for s in hits:
                self.fired.append((site, n, s.action))
        # injected faults are part of the run's timeline: the flight
        # recorder (utils/telemetry) stamps each firing so a chaos
        # run's ledger interleaves faults with the spans they poisoned
        for s in hits:
            telemetry.event("fault_injected", durable=s.fatal,
                            site=site, call=n, action=s.action,
                            fatal=s.fatal, shard=s.shard)
        # act OUTSIDE the lock: a hang must not serialize other sites
        for s in hits:
            payload = _act(s, site, n, payload)
        return payload


def _act(spec: FaultSpec, site: str, call_no: int, payload):
    if spec.action == "raise":
        if spec.fatal:
            # the simulated hard kill: flush the telemetry ring FIRST,
            # so the post-kill ledger still holds the pre-kill spans —
            # the flight-recorder durability contract
            # tools/chaos_run.py and tests/test_telemetry.py assert
            telemetry.on_fatal(site)
        exc = spec.exc
        where = ("site %r (call %d)" % (site, call_no)
                 if spec.shard is None else
                 "site %r (call %d, shard %d)"
                 % (site, call_no, spec.shard))
        if exc is None:
            raise InjectedFault("injected fault at " + where, site,
                                fatal=spec.fatal, shard=spec.shard)
        raise exc("injected fault at " + where)
    if spec.action == "hang":
        time.sleep(spec.seconds)
        return payload
    if spec.action == "truncate_file":
        path = payload
        with open(path, "r+b") as f:
            f.seek(0, 2)
            f.truncate(f.tell() // 2)
        return payload
    if spec.action == "corrupt_shard":
        import numpy as np

        arrays, n = payload
        k = spec.shard or 0
        poisoned = []
        for a in arrays:
            a = np.array(a)  # fresh copy: never poison caller state
            width = a.shape[-1] // n
            if width and np.issubdtype(a.dtype, np.integer):
                # out-of-range vertex ids (far above any bucket's
                # sentinel): the wire check must trip, the scatter
                # kernels must never silently fold them
                a[..., k * width:(k + 1) * width] = np.iinfo(
                    a.dtype).max
            poisoned.append(a)
        return tuple(poisoned), n
    if spec.action == "corrupt_bytes":
        data = bytearray(payload)
        # garble the first line: digits -> 'x' makes the parser drop
        # it (a torn write), never silently misread it
        end = data.find(b"\n")
        end = len(data) if end < 0 else end
        for i in range(end):
            data[i] = ord("x")
        return bytes(data)
    if spec.action == "call":
        return spec.fn(payload)
    raise ValueError("unknown fault action %r" % spec.action)


_ACTIVE: List[FaultPlan] = []  # stack; innermost plan wins
_ACTIVE_LOCK = threading.Lock()


@contextlib.contextmanager
def inject(*specs):
    """Activate a fault plan for the dynamic extent of the context.
    Nestable (innermost plan fires); process-global, so concurrently
    running measurement harnesses must not overlap an injection."""
    plan = FaultPlan(specs)
    with _ACTIVE_LOCK:
        _ACTIVE.append(plan)
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE.remove(plan)


def active() -> Optional[FaultPlan]:
    return _ACTIVE[-1] if _ACTIVE else None


def fire(site: str, payload=None):
    """Runtime hook: consult the active plan (no-op without one). May
    raise, sleep, or transform `payload`; returns the (possibly
    transformed) payload."""
    plan = active()
    if plan is None:
        return payload
    return plan.fire(site, payload)

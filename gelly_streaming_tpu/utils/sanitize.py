"""Admission sanitizer + dead-letter journal — the data-fault
isolation layer of the ingest plane.

The runtime survives hangs (stage watchdogs, utils/resilience), crashes
(WAL replay-exact recovery, utils/wal) and mesh loss (the demotion
ladder) — but until this module it TRUSTED every byte it admitted: once
`native.parse_edge_bytes` yields COO arrays, nothing between the wire
and the scatter kernels checked them. An out-of-range vertex id
silently wraps (or clips) a scatter into another slot's carried state;
a negative id indexes from the end; a 2^40 id cast to int32 wraps into
a perfectly plausible small id — the worst kind of corruption, the
kind that keeps producing digests. Production multi-tenant GNN serving
(PAPERS.md: "A Survey on Graph Neural Network Acceleration") assumes
per-tenant fault isolation; this module is the admission half (the
cohort bulkhead in core/tenancy.py is the dispatch half).

`sanitize()` is a vectorized validator run at every admission boundary
(serve sources → `TenantCohort.feed`, `SummaryEngineBase.process`, the
driver's `run_arrays`) BEFORE the write-ahead journal sees the batch —
so the journal only ever holds edges the sanitizer vouched for and
kill→replay recovery replays a clean stream. Each rejected edge gets
ONE typed reason code (first match in severity order):

    length_mismatch   src/dst lengths differ (whole batch refused)
    non_integer       non-numeric dtype, NaN/inf, or fractional ids
    id_negative       id < 0 (would index from the slab end)
    id_overflow       id >= 2^31 (would wrap the int32 device cast)
    id_out_of_range   id >= the tenant's vertex bucket (would scatter
                      into the sentinel slot / another id's state)
    self_loop         src == dst (strict mode only)
    duplicate_flood   the same (src, dst) pair repeated more than
                      DUP_FLOOD_KEEP times in one batch (strict only —
                      a classic amplification probe)
    batch_overflow    the batch exceeds GS_MAX_BATCH_EDGES (whole
                      batch refused with typed `BatchRejected`)

Rejected records are appended to a WAL-style **dead-letter journal**
(`dlq_<n>.seg` segments under GS_DLQ_DIR: 8-byte magic, then CRC-framed
records carrying origin tenant + source offsets + reason + the edge
data itself), so nothing is silently dropped: `tools/dlq_report.py`
renders the journal per tenant × reason and re-injects records after an
operator fix. Segments rotate at GS_WAL_SEGMENT_BYTES and GS_DLQ_RETAIN
bounds how many closed segments are kept (0 = keep all).

`GS_SANITIZE=off` (the default) is the inert switch: every boundary
skips straight to its legacy path and behavior is bit-identical to a
pre-sanitizer build — the evidence-gate discipline every armed plane in
this repo follows. `on` rejects structurally invalid records; `strict`
adds the self-loop and duplicate-flood policies.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional

import numpy as np

from . import knobs
from . import metrics
from . import telemetry

# typed reason codes, in per-edge assignment severity order (an edge
# that is both negative AND a self-loop reports id_negative)
REASONS = ("length_mismatch", "non_integer", "id_negative",
           "id_overflow", "id_out_of_range", "self_loop",
           "duplicate_flood", "batch_overflow")

# strict mode keeps the first this-many copies of an identical
# (src, dst) pair per batch; the excess is a duplicate flood. A fixed
# constant, not a knob: determinism matters more than tunability here
# (the same batch must always split the same way).
DUP_FLOOD_KEEP = 8

_INT32_CEIL = 1 << 31


# ----------------------------------------------------------------------
# knobs (utils/knobs.py registry; live per-call reads)
# ----------------------------------------------------------------------
def mode() -> str:
    """GS_SANITIZE: `off` (default — every boundary runs its legacy
    path bit-identically), `on` (structural validation), `strict`
    (adds the self-loop + duplicate-flood policies)."""
    return knobs.get_str("GS_SANITIZE")


def enabled() -> bool:
    return mode() != "off"


def dlq_dir() -> Optional[str]:
    """GS_DLQ_DIR: directory of the dead-letter journal; unset (or the
    conventional `0`) = rejected records are counted and dropped."""
    d = knobs.get_path("GS_DLQ_DIR")
    return None if d in (None, "0") else d


def dlq_retain() -> int:
    """GS_DLQ_RETAIN: closed DLQ segments kept after rotation
    (0 = keep all)."""
    return knobs.get_int("GS_DLQ_RETAIN")


def max_batch_edges() -> int:
    """GS_MAX_BATCH_EDGES: admission batch-size bound (whole batches
    past it are refused with typed `BatchRejected` and journaled);
    0 = unbounded."""
    return knobs.get_int("GS_MAX_BATCH_EDGES")


class BatchRejected(ValueError):
    """A whole admission batch was refused (oversized or structurally
    unusable). Carries `tenant`, `reason` (a REASONS code) and `size`
    so the serving front-end can surface a typed wire error.
    Construction stamps the flight-recorder event + counter — every
    raise site is covered by construction (the TenantError pattern)."""

    def __init__(self, message: str, tenant: str, reason: str,
                 size: int, limit: int = 0):
        super().__init__(message)
        self.tenant = str(tenant)
        self.reason = reason
        self.size = int(size)
        self.limit = int(limit)
        telemetry.event("sanitize_reject", tenant=self.tenant,
                        reason=reason, rejected=self.size,
                        whole_batch=True)
        metrics.counter_inc("gs_sanitize_rejected_edges_total",
                            self.size, reason=reason)


class SanitizeReport:
    """One batch's admission verdict: the accepted arrays (int64,
    every id proven in [0, vb)) plus per-reason rejection counts."""

    __slots__ = ("src", "dst", "ts", "keep", "accepted", "rejected",
                 "reasons", "rejects")

    def __init__(self, src, dst, ts, keep, rejected: int,
                 reasons: Dict[str, int], rejects):
        self.src = src
        self.dst = dst
        self.ts = ts
        self.keep = keep      # bool mask over the ORIGINAL batch, so
        self.accepted = len(src)  # callers can filter aligned arrays
        self.rejected = int(rejected)
        self.reasons = reasons
        # the rejected records themselves, per reason — what
        # commit_report() journals once the caller ACCEPTS the batch
        # (a backpressure-refused feed must journal nothing, or the
        # client's retry double-journals every reject)
        self.rejects = rejects  # [(reason, offsets, src, dst), ...]

    @property
    def clean(self) -> bool:
        return self.rejected == 0

    def wire_fields(self) -> dict:
        """The typed-rejection fields the serving front-end adds to a
        feed response ({} for a clean batch — disarmed/clean replies
        stay byte-identical)."""
        if self.clean:
            return {}
        return {"rejected": self.rejected, "reasons": dict(self.reasons)}


def _to_int64(a, ceiling: int) -> "tuple":
    """(values int64, ok_mask, overflow_mask, negative_mask):
    canonicalize one id array. Non-integer floats / NaN / inf fail
    `ok`; magnitudes at or past `ceiling` (including huge floats,
    past-int64 Python ints and uint64 values an astype would wrap)
    land in `overflow`; `negative` carries the PRE-cast sign (a
    -2^40 id must report id_negative, not the overflow its masked
    cast value would suggest). All masks are computed BEFORE any
    cast, so a 2^40 id can never wrap into a plausible small one.
    `ceiling` is 2^31 for the dense-id planes (the device int32
    cast) and 2^63 for the driver's external-id plane (the int64
    representability bound)."""
    a = np.asarray(a)
    if a.dtype.kind not in "iufb":
        # object/str arrays (hostile JSON): try an elementwise parse;
        # unparseable entries are non_integer, parseable-but-huge
        # ones are overflow
        vals = np.zeros(len(a), np.int64)
        ok = np.zeros(len(a), bool)
        over = np.zeros(len(a), bool)
        neg = np.zeros(len(a), bool)
        for i, x in enumerate(a.tolist()):
            try:
                v = int(x)
            except (TypeError, ValueError, OverflowError):
                continue
            ok[i] = True
            neg[i] = v < 0
            if -ceiling <= v < ceiling and -(1 << 63) <= v < (1 << 63):
                vals[i] = v
            else:
                over[i] = True
        return vals, ok, over, neg
    if a.dtype.kind == "f":
        ok = np.isfinite(a)
        intish = np.zeros(len(a), bool)
        intish[ok] = np.equal(a[ok], np.floor(a[ok]))
        ok &= intish
        over = ok & (np.abs(a) >= float(ceiling))
        neg = ok & (a < 0)
        safe = np.where(ok & ~over, a, 0.0)
        return safe.astype(np.int64), ok, over, neg
    if a.dtype.kind == "u" and a.dtype.itemsize == 8:
        over = a >= np.uint64(min(ceiling, (1 << 63) - 1))
        safe = np.where(over, np.uint64(0), a)
        return (safe.astype(np.int64), np.ones(len(a), bool), over,
                np.zeros(len(a), bool))
    vals = a.astype(np.int64)
    ones = np.ones(len(a), bool)
    if ceiling >= (1 << 63):
        return vals, ones, np.zeros(len(a), bool), vals < 0
    return vals, ones, np.abs(vals) >= ceiling, vals < 0


def sanitize(src, dst, vb: Optional[int], *, tenant: str = "",
             origin: str = "", offset: int = 0, ts=None,
             dlq: Optional["DeadLetterJournal"] = None,
             commit: bool = True) -> SanitizeReport:
    """Validate one admission batch against the vertex bucket `vb`.
    Returns the accepted sub-batch (order preserved) and — with
    `commit=True`, the default — journals every rejected record to
    `dlq` (when armed) with its origin tenant, absolute source
    offsets (`offset` + position) and reason code, stamping the
    rejection counters/event. `commit=False` defers that side effect
    to an explicit `commit_report()` call: a caller with its own
    acceptance gate after validation (the cohort's queue-capacity
    check) must journal only batches it actually accepted, or a
    backpressure retry double-journals every reject. Raises typed
    `BatchRejected` for whole-batch refusals (length mismatch,
    GS_MAX_BATCH_EDGES overflow) — the refused batch is journaled
    first (refusals are terminal, never retried-as-is), so even a
    refusal is recoverable.

    `vb=None` is the driver's EXTERNAL-id plane: ids are arbitrary
    int64 keys the interner densifies, so the range/negative/int32
    checks don't apply — only representability (non-integer, NaN/inf,
    past-int64 magnitudes), the batch bound and the strict-mode
    policies run."""
    ceiling = _INT32_CEIL if vb is not None else (1 << 63)
    sv, s_ok, s_over, s_neg = _to_int64(src, ceiling)
    dv, d_ok, d_over, d_neg = _to_int64(dst, ceiling)
    if len(sv) != len(dv):
        raise BatchRejected(
            "src/dst length mismatch (%d vs %d)" % (len(sv), len(dv)),
            tenant, "length_mismatch", max(len(sv), len(dv)))
    n = len(sv)
    tv = None if ts is None else np.asarray(ts)
    bound = max_batch_edges()
    if bound and n > bound:
        if dlq is not None:
            dlq.append(tenant, origin, "batch_overflow",
                       offset + np.arange(n, dtype=np.int64), sv, dv)
        raise BatchRejected(
            "batch of %d edges exceeds GS_MAX_BATCH_EDGES=%d for "
            "tenant %r" % (n, bound, tenant),
            tenant, "batch_overflow", n, limit=bound)
    # one reason per edge, assigned in severity order (REASONS index)
    reason = np.full(n, -1, np.int8)

    def mark(mask, code: str):
        m = mask & (reason < 0)
        if m.any():
            reason[m] = REASONS.index(code)

    mark(~(s_ok & d_ok), "non_integer")
    if vb is not None:
        # the documented severity order: a -2^40 id is id_negative
        # (the pre-cast sign masks), not the overflow its magnitude
        # would also trip
        mark(s_neg | d_neg | (sv < 0) | (dv < 0), "id_negative")
        mark(s_over | d_over
             | (sv >= _INT32_CEIL) | (dv >= _INT32_CEIL),
             "id_overflow")
        mark((sv >= vb) | (dv >= vb), "id_out_of_range")
    else:
        mark(s_over | d_over, "id_overflow")
    if mode() == "strict" and n:
        mark(sv == dv, "self_loop")
        live = reason < 0
        if live.any():
            # occurrence index per identical (src, dst) pair among the
            # still-accepted edges: stable lexsort the pairs, the rank
            # within each equal-pair run is position - run_start
            idx = np.flatnonzero(live)
            order = np.lexsort((dv[idx], sv[idx]))
            ss, dd = sv[idx][order], dv[idx][order]
            run_start = np.zeros(len(ss), np.int64)
            new_run = np.flatnonzero((np.diff(ss) != 0)
                                     | (np.diff(dd) != 0)) + 1
            run_start[new_run] = new_run
            np.maximum.accumulate(run_start, out=run_start)
            occ = np.arange(len(ss), dtype=np.int64) - run_start
            flood = np.zeros(n, bool)
            flood[idx[order[occ >= DUP_FLOOD_KEEP]]] = True
            mark(flood, "duplicate_flood")
    bad = reason >= 0
    n_rej = int(bad.sum())
    reasons: Dict[str, int] = {}
    rejects = []
    if n_rej:
        offs = offset + np.arange(n, dtype=np.int64)
        for code_i in np.unique(reason[bad]):
            code = REASONS[int(code_i)]
            m = reason == code_i
            reasons[code] = int(m.sum())
            rejects.append((code, offs[m], sv[m], dv[m]))
    keep = ~bad
    report = SanitizeReport(
        sv[keep], dv[keep],
        None if tv is None else tv[keep],
        keep, n_rej, reasons, rejects)
    if commit:
        commit_report(report, tenant=tenant, origin=origin, dlq=dlq)
    return report


def commit_report(report: SanitizeReport, *, tenant: str = "",
                  origin: str = "",
                  dlq: Optional["DeadLetterJournal"] = None) -> None:
    """Journal a report's rejected records and stamp the rejection
    counters/event — the acceptance-time half of a
    `sanitize(commit=False)` call. Idempotence is the CALLER's
    contract: commit exactly once per accepted batch."""
    if not report.rejected:
        return
    for code, offs, rs, rd in report.rejects:
        metrics.counter_inc("gs_sanitize_rejected_edges_total",
                            len(rs), reason=code)
        if dlq is not None:
            dlq.append(tenant, origin, code, offs, rs, rd)
    telemetry.event("sanitize_reject", tenant=str(tenant),
                    origin=origin, rejected=report.rejected,
                    reasons=report.reasons)


# ----------------------------------------------------------------------
# the dead-letter journal (WAL-style segments; utils/wal discipline)
# ----------------------------------------------------------------------
_MAGIC = b"GSDLQSG1"
_HEAD = struct.Struct("<II")           # crc32, payload_len
_SEG_FMT = "dlq_%08d.seg"


def _encode(tenant: str, origin: str, reason: str,
            offs: np.ndarray, src: np.ndarray,
            dst: np.ndarray) -> bytes:
    tb, ob, rb = tenant.encode(), origin.encode(), reason.encode()
    head = struct.pack(
        "<BH%dsH%dsH%dsI" % (len(tb), len(ob), len(rb)),
        1, len(tb), tb, len(ob), ob, len(rb), rb, len(src))
    payload = b"".join([
        head,
        np.ascontiguousarray(offs, np.int64).tobytes(),
        np.ascontiguousarray(src, np.int64).tobytes(),
        np.ascontiguousarray(dst, np.int64).tobytes()])
    return _HEAD.pack(zlib.crc32(payload), len(payload)) + payload


def _decode(payload: bytes) -> dict:
    off = 1
    out = {}
    for field in ("tenant", "origin", "reason"):
        (ln,) = struct.unpack_from("<H", payload, off)
        off += 2
        out[field] = payload[off:off + ln].decode()
        off += ln
    (n,) = struct.unpack_from("<I", payload, off)
    off += 4
    for field in ("offsets", "src", "dst"):
        out[field] = np.frombuffer(payload, np.int64, n, off)
        off += 8 * n
    return out


def _segments(directory: str) -> List[str]:
    try:
        names = sorted(f for f in os.listdir(directory)
                       if f.startswith("dlq_") and f.endswith(".seg"))
    except FileNotFoundError:
        return []
    return [os.path.join(directory, f) for f in names]


def replay(directory: str) -> Iterator[dict]:
    """Every intact DLQ record in append order. Damage (a torn tail
    from a crash mid-append, or an externally truncated segment) stops
    the iteration of THAT segment with a telemetry event — a rejected
    record was never acknowledged anywhere, so dropping a torn one is
    exact; later segments still replay."""
    for path in _segments(directory):
        with open(path, "rb") as f:
            data = f.read()
        if not data.startswith(_MAGIC):
            telemetry.event("dlq_torn", segment=os.path.basename(path),
                            problem="bad segment magic")
            continue
        off = len(_MAGIC)
        while off < len(data):
            tail = len(data) - off
            if tail < _HEAD.size:
                telemetry.event("dlq_torn",
                                segment=os.path.basename(path),
                                problem="partial record header")
                break
            crc, length = _HEAD.unpack_from(data, off)
            payload = data[off + _HEAD.size:off + _HEAD.size + length]
            if len(payload) < length or zlib.crc32(payload) != crc:
                telemetry.event("dlq_torn",
                                segment=os.path.basename(path),
                                problem="truncated or CRC-failing "
                                        "record")
                break
            yield _decode(payload)
            off += _HEAD.size + length


def scan(directory: str) -> dict:
    """DLQ summary: record/edge totals, per-reason and per-tenant edge
    counts, segment count."""
    records = edges = 0
    by_reason: Dict[str, int] = {}
    by_tenant: Dict[str, int] = {}
    for rec in replay(directory):
        records += 1
        n = len(rec["src"])
        edges += n
        by_reason[rec["reason"]] = by_reason.get(rec["reason"], 0) + n
        by_tenant[rec["tenant"]] = by_tenant.get(rec["tenant"], 0) + n
    return {"records": records, "edges": edges,
            "by_reason": by_reason, "by_tenant": by_tenant,
            "segments": len(_segments(directory))}


class DeadLetterJournal:
    """Appender over one DLQ directory. Thread-safe (serve connection
    threads and the pump both reject); every append is fsync'd — the
    "every rejected record is recoverable" contract is only worth
    stating if a crash right after the rejection can't lose it, and
    rejection is off the hot path by definition."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        segs = _segments(directory)
        self._seg_no = (max(int(os.path.basename(p)[4:-4])
                            for p in segs) + 1) if segs else 0
        self._file = None
        self._file_bytes = 0
        info = scan(directory)
        self.records = info["records"]
        self.edges = info["edges"]
        self.by_reason: Dict[str, int] = dict(info["by_reason"])

    def _ensure_segment(self):
        if self._file is not None \
                and self._file_bytes >= knobs.get_int(
                    "GS_WAL_SEGMENT_BYTES"):
            self._file.close()
            self._file = None
            self._retain()
        if self._file is None:
            path = os.path.join(self.dir, _SEG_FMT % self._seg_no)
            self._seg_no += 1
            self._file = open(path, "ab")
            self._file.write(_MAGIC)
            self._file.flush()
            self._file_bytes = len(_MAGIC)
        return self._file

    def _retain(self) -> None:
        """GS_DLQ_RETAIN: drop the oldest CLOSED segments past the
        bound (the open segment never counts). 0 keeps everything."""
        keep = dlq_retain()
        if keep <= 0:
            return
        closed = _segments(self.dir)
        if self._file is not None and closed \
                and closed[-1] == self._file.name:
            closed = closed[:-1]
        for path in closed[:-keep] if len(closed) > keep else []:
            os.unlink(path)

    def append(self, tenant: str, origin: str, reason: str,
               offsets, src, dst) -> None:
        """Journal one rejected record (origin tenant + absolute
        source offsets + reason + the edges themselves)."""
        rec = _encode(str(tenant), str(origin), str(reason),
                      np.asarray(offsets, np.int64),
                      np.asarray(src, np.int64),
                      np.asarray(dst, np.int64))
        with self._lock:
            f = self._ensure_segment()
            f.write(rec)
            f.flush()
            os.fsync(f.fileno())
            self._file_bytes += len(rec)
            self.records += 1
            self.edges += len(np.atleast_1d(src))
            self.by_reason[reason] = (self.by_reason.get(reason, 0)
                                      + len(np.atleast_1d(src)))
        metrics.counter_inc("gs_dlq_records_total")
        metrics.counter_inc("gs_dlq_edges_total",
                            len(np.atleast_1d(src)))
        metrics.gauge_set("gs_dlq_depth_records", self.records)

    def status(self) -> dict:
        """Live depth for /healthz and the serve `status` op."""
        with self._lock:
            return {"dir": self.dir, "records": self.records,
                    "edges": self.edges,
                    "by_reason": dict(self.by_reason)}

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# process-global journal registry keyed by directory: every admission
# boundary resolving the same GS_DLQ_DIR shares one appender (and its
# depth counters), the way the telemetry/metrics registries behave
_DLQS: Dict[str, DeadLetterJournal] = {}
_DLQ_LOCK = threading.Lock()


def resolve_dlq() -> Optional[DeadLetterJournal]:
    """The shared journal for the current GS_SANITIZE/GS_DLQ_DIR
    configuration; None when the sanitizer or the journal is
    disarmed (rejections are then counted and dropped)."""
    if not enabled():
        return None
    d = dlq_dir()
    if d is None:
        return None
    with _DLQ_LOCK:
        j = _DLQS.get(d)
        if j is None:
            j = _DLQS[d] = DeadLetterJournal(d)
        return j


def dlq_status() -> Optional[dict]:
    """The live journal's depth (None when disarmed/never touched) —
    the serving front-end's /healthz `dlq` cell."""
    d = dlq_dir()
    if d is None:
        return None
    with _DLQ_LOCK:
        j = _DLQS.get(d)
    return j.status() if j is not None else None


def reset() -> None:
    """Test hook: close and forget every registered journal."""
    with _DLQ_LOCK:
        for j in _DLQS.values():
            j.close()
        _DLQS.clear()

"""Bipartiteness-check summary state: signed two-coloring candidates.

Result-parity re-implementation of the reference's `Candidates` /
`SignedVertex` (example/util/Candidates.java:26-196,
example/util/SignedVertex.java:23-41): a success flag plus an ordered
map component-id → {vertex-id → (vertex-id, sign)}. `merge` compares
each incoming component against existing ones, merges along shared
vertices with sign reversal, and collapses to `(false,{})` on any odd
cycle (Candidates.java:76-138). The reference notes its own O(C²·V)
merge needs cleanup (Candidates.java:75); the vectorizable device
equivalent is the parity union-find in ops/unionfind.py — this class
exists for exact golden-string parity
(BipartitenessCheckTest.java:18-20).

`__repr__` matches Java's `Tuple2(Boolean, TreeMap).toString`:
``(true,{1={1=(1,true), 2=(2,false)}})``.
"""

from __future__ import annotations

from typing import Dict


class SignedVertex:
    """(vertex id, sign) pair (reference: SignedVertex.java:23-41)."""

    __slots__ = ("vertex", "sign")

    def __init__(self, vertex: int, sign: bool):
        self.vertex = vertex
        self.sign = sign

    def reverse(self) -> "SignedVertex":
        return SignedVertex(self.vertex, not self.sign)

    def __repr__(self) -> str:
        return f"({self.vertex},{'true' if self.sign else 'false'})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, SignedVertex)
                and self.vertex == other.vertex and self.sign == other.sign)


Component = Dict[int, SignedVertex]  # vertex id -> signed vertex


class Candidates:
    def __init__(self, success: bool = True):
        self.success = success
        # component id -> {vertex id -> SignedVertex}; kept key-sorted on
        # iteration (the reference uses TreeMaps).
        self.map: Dict[int, Component] = {}

    # ------------------------------------------------------------------
    def add(self, component: int, vertex: SignedVertex) -> bool:
        """Add a signed vertex; False on sign conflict within the component
        (reference: Candidates.java:60-73)."""
        comp = self.map.setdefault(component, {})
        stored = comp.get(vertex.vertex)
        if stored is not None and stored.sign != vertex.sign:
            return False
        comp[vertex.vertex] = vertex
        return True

    def _add_component(self, component: int, vertices: Component) -> bool:
        for v in vertices.values():
            if not self.add(component, v):
                return False
        return True

    # ------------------------------------------------------------------
    def merge(self, other: "Candidates") -> "Candidates":
        """Merge another candidate set into this one
        (reference: Candidates.java:76-138). Mutates and returns self,
        or a fresh failed instance on an odd cycle."""
        if not other.success or not self.success:
            return Candidates(False)

        for in_key in sorted(other.map):
            in_comp = other.map[in_key]
            # Components of self sharing a vertex (identical-set ones skipped)
            merge_with = []
            for self_key in sorted(self.map):
                self_comp = self.map[self_key]
                if set(in_comp) == set(self_comp):
                    continue
                if any(v in self_comp for v in in_comp):
                    merge_with.append(self_key)

            if not merge_with:
                # Disjoint from everything: adopt the component as-is
                # (the reference ignores add's return here too,
                # Candidates.java:110).
                self._add_component(in_key, in_comp)
                continue

            first_key = merge_with[0]
            if not self._merge_components(other, in_key, first_key):
                return Candidates(False)
            first_key = min(in_key, first_key)
            for self_key in merge_with[1:]:
                if not self._merge_components(self, self_key, first_key):
                    # Deliberate divergence: the reference ignores this
                    # failure (Candidates.java:127-130 calls fail() and
                    # drops the result, staying success=true) — an odd
                    # cycle detected while collapsing bridged components
                    # is a genuine non-bipartiteness witness, so we fail.
                    return Candidates(False)
                self.map.pop(self_key, None)

        return self

    def _merge_components(self, source: "Candidates", source_key: int,
                          self_key: int) -> bool:
        """Merge source's component into self's, under key
        min(source_key, self_key), reversing signs if the first shared
        vertex disagrees; False if shared vertices are inconsistent
        (reference: Candidates.java:141-191)."""
        src_comp = source.map[source_key]
        self_comp = self.map[self_key]
        shared = [v for v in src_comp if v in self_comp]
        reversed_ = src_comp[shared[0]].sign != self_comp[shared[0]].sign
        for v in shared:
            agree = src_comp[v].sign == self_comp[v].sign
            if agree == reversed_:
                return False
        common_key = min(source_key, self_key)
        for sv in list(src_comp.values()):
            if not self.add(common_key, sv.reverse() if reversed_ else sv):
                return False
        return True

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "success": self.success,
            "components": {
                str(k): {str(v): sv.sign for v, sv in comp.items()}
                for k, comp in self.map.items()
            },
        }

    def load_state_dict(self, state: dict) -> None:
        self.success = state["success"]
        self.map = {
            int(k): {
                int(v): SignedVertex(int(v), bool(sign))
                for v, sign in comp.items()
            }
            for k, comp in state["components"].items()
        }

    # ------------------------------------------------------------------
    def __repr__(self) -> str:
        inner = ", ".join(
            "{}={{{}}}".format(
                k, ", ".join(f"{v}={self.map[k][v]}" for v in sorted(self.map[k]))
            )
            for k in sorted(self.map)
        )
        return f"({'true' if self.success else 'false'},{{{inner}}})"


def edge_to_candidate(v1: int, v2: int) -> Candidates:
    """An edge as a two-vertex signed component keyed by the smaller
    endpoint (reference: BipartitenessCheck.java:57-64)."""
    src, trg = min(v1, v2), max(v1, v2)
    cand = Candidates(True)
    cand.add(src, SignedVertex(src, True))
    cand.add(src, SignedVertex(trg, False))
    return cand

"""Event/record types used by the workload library.

Counterparts of the reference's example/util records:
MatchingEvent (MatchingEvent.java:26-41), SampledEdge
(SampledEdge.java:26-55), TriangleEstimate (TriangleEstimate.java:24-43).
"""

from __future__ import annotations

import enum
from typing import NamedTuple

from ..core.types import Edge


class MatchingEventType(enum.Enum):
    ADD = "ADD"
    REMOVE = "REMOVE"


class MatchingEvent:
    """Output event of streaming weighted matching: an edge entering or
    leaving the matching. A plain class (not a tuple) so sinks print it
    via its own formatting."""

    __slots__ = ("type", "edge")

    def __init__(self, type: MatchingEventType, edge: Edge):
        self.type = type
        self.edge = edge

    def __repr__(self) -> str:
        return (f"{self.type.value} "
                f"{self.edge.source},{self.edge.target},{self.edge.value}")

    def __eq__(self, other) -> bool:
        return (isinstance(other, MatchingEvent)
                and self.type == other.type and self.edge == other.edge)


class SampledEdge(NamedTuple):
    """Routing record for incidence sampling: which subtask/instance an
    (edge, edge_count) observation belongs to, and whether the instance
    resampled on this edge."""

    subtask: int
    instance: int
    edge: Edge
    edge_count: int
    resample: bool


class TriangleEstimate(NamedTuple):
    """Partial estimate from one sampler subtask."""

    source_subtask: int
    edge_count: int
    beta: int

"""Union-find summary state for streaming connected components.

Host-side counterpart of the reference's `DisjointSet`
(example/util/DisjointSet.java:30-154): parent map with path
compression, union by rank, and a merge that unions in the entries of
another instance ("naive symmetric hash join", DisjointSet.java:126-136).
The device-side equivalent is ops/unionfind.py (array label propagation);
this class is the exact-parity state used by the merge-tree and tests.

`__repr__` prints components as `{root=[members...]}` matching the
reference's toString (DisjointSet.java:139-153), which its tests parse
(ConnectedComponentsTest.java:45-57); members are emitted in sorted
order for determinism.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, TypeVar

R = TypeVar("R")


class DisjointSet(Generic[R]):
    def __init__(self, elements: Iterable[R] = ()):
        self._parent: Dict[R, R] = {}
        self._rank: Dict[R, int] = {}
        for e in elements:
            self.make_set(e)

    def get_matches(self) -> Dict[R, R]:
        return self._parent

    def make_set(self, e: R) -> None:
        self._parent[e] = e
        self._rank[e] = 0

    def find(self, e: R):
        """Root of e's set, with full path compression
        (reference: DisjointSet.java:71-85)."""
        if e not in self._parent:
            return None
        root = e
        while self._parent[root] != root:
            root = self._parent[root]
        while self._parent[e] != root:
            self._parent[e], e = root, self._parent[e]
        return root

    def union(self, e1: R, e2: R) -> None:
        """Union by rank; absent elements are created
        (reference: DisjointSet.java:97-123)."""
        if e1 not in self._parent:
            self.make_set(e1)
        if e2 not in self._parent:
            self.make_set(e2)
        r1, r2 = self.find(e1), self.find(e2)
        if r1 == r2:
            return
        if self._rank[r1] > self._rank[r2]:
            self._parent[r2] = r1
        elif self._rank[r1] < self._rank[r2]:
            self._parent[r1] = r2
        else:
            self._parent[r2] = r1
            self._rank[r1] += 1

    def merge(self, other: "DisjointSet[R]") -> None:
        """Union in every (element, parent) entry of `other`
        (reference: DisjointSet.java:132-136)."""
        for e, p in other.get_matches().items():
            self.union(e, p)

    def size(self) -> int:
        return len(self._parent)

    def components(self) -> Dict[R, list]:
        comps: Dict[R, list] = {}
        for v in self._parent:
            comps.setdefault(self.find(v), []).append(v)
        return comps

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "elements": list(self._parent.keys()),
            "parents": list(self._parent.values()),
            "ranks": [self._rank[e] for e in self._parent],
        }

    def load_state_dict(self, state: dict) -> None:
        self._parent = dict(zip(state["elements"], state["parents"]))
        self._rank = dict(zip(state["elements"], state["ranks"]))

    def __repr__(self) -> str:
        comps = self.components()
        try:
            keys = sorted(comps)
        except TypeError:
            keys = list(comps)
        return "{" + ", ".join(
            f"{k}=[{', '.join(str(m) for m in sorted(comps[k]))}]" for k in keys
        ) + "}"

"""Stage watchdogs, bounded retry, and the tier-demotion registry.

The round-5 queue log's failure mode ("tunnel never answered") is a
HANG, not an exception: an h2d or dispatch through a tunneled chip that
never returns stalls the whole stream forever, because nothing in the
ingress pipeline owned a deadline. This module is the shared guard
machinery:

- Typed stage errors. `StageTimeout` / `StageFailed` carry which chunk,
  which stage, and the per-attempt timings, so an operator (or
  tools/chaos_run.py) can tell a wedged transfer from a poisoned prep
  without parsing tracebacks.
- `call_guarded` — run one stage under a configurable deadline
  (`GS_STAGE_TIMEOUT_S`) with bounded retry and DETERMINISTIC
  (jitterless) exponential backoff (`GS_STAGE_RETRIES`,
  `GS_STAGE_BACKOFF_S`). With both knobs at their defaults (0) the
  guard is inert and callers run their legacy inline path — zero
  threads, zero overhead, bit-identical behavior.
- The demotion registry — a process-global log of tier demotions
  (device→native→host) the driver records and
  tools/profile_kernels.py commits to PERF.json as a `degradations`
  section, so a degraded run is visibly labeled and can never
  masquerade as a device-tier measurement.

Deadline mechanics: the guarded callable runs on a helper thread and
the caller waits `timeout` seconds. On expiry the helper is ABANDONED
(daemon; Python cannot safely interrupt a thread blocked in a ctypes
or network call — exactly the hung-tunnel shape) and the attempt is
retried or surfaced as `StageTimeout`. A guarded stage must therefore
be safe to re-run: prep is pure and h2d is an idempotent transfer;
side-effecting stages (finalize, carry-mutating dispatch) are guarded
with `retries=0` — deadline only — by their callers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from . import faults
from . import knobs
from . import telemetry


class StageError(RuntimeError):
    """Base of the typed stage failures. `stage` is the pipeline stage
    name ('prep' / 'h2d' / 'dispatch' / 'finalize'), `chunk` the chunk
    descriptor the caller passed, `attempts` one dict per attempt:
    {"outcome": "timeout" | exception class name, "elapsed_s": float}.

    Construction stamps a durable flight-recorder event
    (utils/telemetry): a typed stage failure is exactly the
    post-mortem evidence the run ledger exists for, and putting the
    stamp here covers BOTH guard implementations (call_guarded and
    ingress_pipeline._guarded_prep_h2d) by construction."""

    def __init__(self, message: str, stage: str, chunk,
                 attempts: Optional[List[dict]] = None):
        super().__init__(message)
        self.stage = stage
        self.chunk = chunk
        self.attempts = attempts or []
        telemetry.event(
            {"StageTimeout": "stage_timeout",
             "StageFailed": "stage_failed"}.get(type(self).__name__,
                                                "stage_error"),
            durable=True, stage=stage,
            chunk=telemetry.chunk_key(chunk),
            attempts=len(self.attempts))


class StageTimeout(StageError):
    """A stage exceeded its GS_STAGE_TIMEOUT_S deadline on every
    allowed attempt (the hung-tunnel shape)."""


class StageFailed(StageError):
    """A stage raised on every allowed attempt; the last exception
    rides as __cause__."""


# ----------------------------------------------------------------------
# env knobs (read per call through the utils/knobs registry: tests and
# tools/chaos_run.py flip them mid-process)
# ----------------------------------------------------------------------
def stage_timeout_s() -> float:
    """Per-stage watchdog deadline in seconds (GS_STAGE_TIMEOUT_S);
    0 (default) disables the watchdog entirely."""
    return knobs.get_float("GS_STAGE_TIMEOUT_S")


def stage_retries() -> int:
    """Extra attempts after the first failure/timeout
    (GS_STAGE_RETRIES, default 0 = fail on first error)."""
    return knobs.get_int("GS_STAGE_RETRIES")


def stage_backoff_s() -> float:
    """Base of the deterministic exponential backoff between retry
    attempts: sleep base·2^attempt, NO jitter (GS_STAGE_BACKOFF_S,
    default 0.05). Jitter exists to de-correlate fleets; a single
    streaming process gains nothing from it and loses reproducibility.
    """
    return knobs.get_float("GS_STAGE_BACKOFF_S")


def backoff_s(attempt: int) -> float:
    """The deterministic (jitterless) backoff ladder: base·2^attempt
    seconds with the GS_STAGE_BACKOFF_S base. The stage guard sleeps
    it between retries, and the serving front-end (core/serve.py)
    returns it as the `retry_after_s` hint on a typed backpressure
    response — one discipline, so a polite client and the in-process
    retry pace identically."""
    return stage_backoff_s() * (2 ** max(0, attempt))


def guard_active() -> bool:
    """True when either knob arms the guard; callers keep their legacy
    inline path (and exact legacy exception types) otherwise."""
    return stage_timeout_s() > 0 or stage_retries() > 0


_TIMEOUT = object()  # sentinel: deadline expired


def _run_with_deadline(fn: Callable, timeout: float):
    """Run fn() on a daemon helper thread, waiting at most `timeout`
    seconds. Returns fn's value, re-raises its exception, or returns
    the _TIMEOUT sentinel (the helper is abandoned — see module
    docstring)."""
    box = {}
    done = threading.Event()

    def runner():
        try:
            box["value"] = fn()
        except BaseException as e:  # gslint: disable=except-hygiene (captured: _run_with_deadline re-raises on the caller)
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name="gs-stage-watchdog")
    t.start()
    if not done.wait(timeout):
        return _TIMEOUT
    if "error" in box:
        raise box["error"]
    return box["value"]


def call_guarded(stage: str, chunk, fn: Callable, *,
                 retries: Optional[int] = None,
                 timeout: Optional[float] = None):
    """Run `fn()` (one stage of one chunk) under the watchdog/retry
    policy. retries/timeout default to the env knobs; pass retries=0
    for side-effecting stages that must not re-run.

    Raises StageTimeout/StageFailed with per-attempt timings once the
    attempt budget is exhausted. KeyboardInterrupt/SystemExit and
    FATAL injected faults (faults.InjectedFault(fatal=True) — the
    chaos harness's simulated kill) pass through unwrapped and
    unretried."""
    if retries is None:
        retries = stage_retries()
    if timeout is None:
        timeout = stage_timeout_s()
    attempts: List[dict] = []
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        try:
            if timeout > 0:
                out = _run_with_deadline(fn, timeout)
            else:
                out = fn()
        except faults.InjectedFault as e:
            if e.fatal:
                raise  # the simulated hard kill: never retried
            attempts.append({"outcome": type(e).__name__,
                             "elapsed_s": time.perf_counter() - t0})
            if attempt >= retries:
                raise StageFailed(
                    "%s stage failed for chunk %r after %d attempt(s): %s"
                    % (stage, chunk, len(attempts), e),
                    stage, chunk, attempts) from e
        except Exception as e:
            attempts.append({"outcome": type(e).__name__,
                             "elapsed_s": time.perf_counter() - t0})
            if attempt >= retries:
                raise StageFailed(
                    "%s stage failed for chunk %r after %d attempt(s): %s"
                    % (stage, chunk, len(attempts), e),
                    stage, chunk, attempts) from e
        else:
            if out is not _TIMEOUT:
                return out
            attempts.append({"outcome": "timeout",
                             "elapsed_s": time.perf_counter() - t0})
            if attempt >= retries:
                raise StageTimeout(
                    "%s stage of chunk %r exceeded its %.3gs deadline "
                    "on %d attempt(s) (GS_STAGE_TIMEOUT_S; per-attempt "
                    "timings on .attempts)"
                    % (stage, chunk, timeout, len(attempts)),
                    stage, chunk, attempts)
        telemetry.event("stage_retry", stage=stage,
                        chunk=telemetry.chunk_key(chunk),
                        attempt=attempt + 1,
                        outcome=attempts[-1]["outcome"])
        time.sleep(backoff_s(attempt))


# ----------------------------------------------------------------------
# tier-demotion registry
# ----------------------------------------------------------------------
_DEMOTIONS: List[dict] = []
_DEMOTIONS_LOCK = threading.Lock()


def record_demotion(component: str, from_tier: str, to_tier: str,
                    window: int, reason: str,
                    mesh_shape: Optional[list] = None,
                    shard_id: Optional[int] = None,
                    tenant: Optional[str] = None) -> dict:
    """Log one tier demotion (or a failed re-promotion probe). The
    process-global log is what tools/profile_kernels.py snapshots into
    PERF.json's `degradations` section, so a run that silently fell
    off the device tier is labeled in the committed evidence.

    `mesh_shape` (device counts per mesh axis; None = single-chip) and
    `shard_id` (the implicated shard of a mesh failure, when known —
    e.g. faults.InjectedFault.shard) are ALWAYS present in the event:
    a demoted mesh run must carry its mesh provenance into the
    degradations evidence, so it can never masquerade as a healthy
    sharded-tier row (tools/perf_schema.py enforces the key)."""
    event = {
        "component": component,
        "from": from_tier,
        "to": to_tier,
        "window": int(window),
        "reason": reason[:500],
        "mesh_shape": (None if mesh_shape is None
                       else [int(x) for x in mesh_shape]),
        "shard_id": None if shard_id is None else int(shard_id),
        # multi-tenant provenance (core/tenancy.py): a demoted tenant's
        # event names WHICH stream fell off the cohort tier, so the
        # degradations evidence (and /healthz's demotion tail) can
        # never blame the whole cohort for one sick stream
        "tenant": None if tenant is None else str(tenant),
    }
    with _DEMOTIONS_LOCK:
        _DEMOTIONS.append(event)
    # durable flight-recorder stamp: a demotion must survive whatever
    # killed the tier (the whole point of the run ledger)
    telemetry.event("tier_demotion", durable=True, **event)
    return event


def demotion_events() -> List[dict]:
    with _DEMOTIONS_LOCK:
        return list(_DEMOTIONS)


def reset_demotions() -> None:
    """Test/tool hook: clear the process-global demotion log."""
    with _DEMOTIONS_LOCK:
        _DEMOTIONS.clear()


def tier_retry_windows() -> int:
    """Probation length for re-promotion after a tier demotion
    (GS_TIER_RETRY_WINDOWS): after this many windows finalized on the
    demoted tier without failure, the driver retries the higher tier
    once; a repeat failure demotes again (and restarts probation).
    0 (default) = a demotion is permanent for the process."""
    return knobs.get_int("GS_TIER_RETRY_WINDOWS")


def tier_demotion_enabled() -> bool:
    """GS_TIER_DEMOTE=0 pins the resolved tier: failures raise instead
    of degrading — what a measurement harness wants (a silently
    demoted bench row is worse than a failed one; the profiler also
    labels any demotion that does happen)."""
    return knobs.get_bool("GS_TIER_DEMOTE")


def mesh_demotion_enabled() -> bool:
    """GS_MESH_DEMOTE=0 pins a sharded session to the mesh: a
    persistent mesh failure raises instead of demoting
    sharded → single-chip scan → native → host (subordinate to
    GS_TIER_DEMOTE, which pins EVERY rung). Default 1: a dead shard
    degrades the stream to one device instead of wedging it — the
    multi-chip leg of the core/driver demotion ladder."""
    return knobs.get_bool("GS_MESH_DEMOTE")


def mesh_wire_check_enabled() -> bool:
    """GS_MESH_WIRE_CHECK=1 arms the sharded h2d wire validation
    (parallel/sharded.guard_wire): every mesh-bound window stack is
    range-checked per shard slice before dispatch, so a corrupt shard
    wire (torn transfer, faults.py's corrupt_shard drill) surfaces as
    a typed stage failure naming the shard instead of scattering
    out-of-range ids into carried state. Default 0: the hot path
    stays byte-identical to the unguarded form."""
    return knobs.get_bool("GS_MESH_WIRE_CHECK")
